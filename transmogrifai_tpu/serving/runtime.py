"""Resilient serving runtime: continuous batching with backpressure,
deadlines, and per-model circuit breaking (docs/serving.md).

``micro_batch_score_function`` (local/scoring.py) is the throughput path —
one compiled XLA program per device-fusable segment, reused across batch
sizes via the bucketed plan cache — but nothing drives it under concurrent
load. This runtime does, and it treats serving as a robustness problem
first (ROADMAP item 1; the Spark executor fault model the reference got
for free, rebuilt for the serving tier):

* **bounded queue + admission control** — ``submit`` enqueues up to
  ``max_queue`` requests; beyond that the request is *shed* with a typed
  :class:`OverloadError` instead of growing memory without bound. Shedding
  at the door is what keeps p99 bounded under a 2× overload.
* **continuous batching** — a single batcher thread coalesces queued
  requests into micro-batches and flushes on size-or-deadline: a full
  ``max_batch`` (sized to the padding bucket grid of ``plan.py``, so one
  compiled program serves every flush) or the oldest request aging past
  ``max_wait_ms``. While a batch is on the device the queue keeps
  accepting — the next batch is already forming.
* **per-request deadlines** — an expired request is shed *before*
  dispatch (:class:`DeadlineExceededError` on its future), so a slow
  batch never spends device time on work nobody is waiting for.
* **per-model circuit breaker** — dispatch/plan failures feed a
  :class:`~.breaker.CircuitBreaker`; while open, batches degrade to the
  eager per-row ``score_function`` path (bit-equal results) instead of
  failing requests, recorded via FaultLog (``breaker_degraded``) and the
  ``tg_breaker_state`` gauge. A half-open probe re-tries the device path
  and closes on success.
* **adaptive degradation under memory pressure** — a flush whose compiled
  dispatch exhausts device/host memory (XLA ``RESOURCE_EXHAUSTED``, host
  ``MemoryError`` — robustness/resources.py) splits in half and retries,
  recursively down to singleton requests: latency degrades, requests
  never fail, and each split is an ``oom_downshift`` FaultLog report +
  ``tg_oom_total{site="oom.serve"}``. Resource faults NEVER feed the
  breaker — exhaustion says the *batch* was too big, not that the device
  path is broken, and opening the breaker would needlessly route healthy
  traffic to the slow eager path. Only if even singletons exhaust does
  the batch degrade to the eager per-row scorer (still zero failures).
* **hang watchdog** — the batcher thread beats a
  :mod:`~..robustness.watchdog` heart every loop iteration
  (``TG_WATCHDOG_S``); a wedged dispatch stops the beats, which records
  ``thread_stalled`` + ``tg_watchdog_stalls_total`` and trips the
  breaker so the *next* batches degrade instead of queueing behind the
  wedge. ``close()`` likewise refuses to silently discard a batcher that
  outlives its join timeout — the leak is recorded the same way.
* **pipelined dataplane** — with ``TG_SERVE_PIPELINE`` > 1 (default 2)
  the per-model loop splits into three overlapped stages: the batcher
  *gathers* (take-batch, deadline shed, one pooled columnar gather per
  flush — local/scoring.ServeStages) and *dispatches* (launches the
  compiled program via JAX async dispatch, no blocking), then hands the
  in-flight device result to a ``tg-serve-completer[<name>]`` thread
  that *completes* flushes strictly in flush order: block on device
  results, vectorized record flattening, ``_finish`` accounting + future
  resolution, drift fold — all off the batcher's critical path. Depth 1
  is byte-for-byte today's serial loop (selectable for A/B); records
  are bit-equal across depths because per-row results are independent
  of batching. Failures surface at completion but count against the
  dispatching flush; breaker-open and ``oom.serve`` downshift ladders
  drain the pipeline and run serially. Per-stage
  ``tg_serve_stage_seconds{stage}`` histograms attribute which stage
  bounds throughput (docs/serving.md "Pipelined dataplane").

Failure injection: the ``serve.enqueue`` / ``serve.flush`` /
``serve.dispatch`` / ``serve.complete`` / ``oom.serve`` chaos sites
(robustness/faults.py) make every one of those paths deterministically
testable.

Metrics: every instrument is kept in a **serve-local**
``MetricsRegistry`` (always on — health/SLO snapshots must work with
observability disabled) and mirrored into the process-global registry
through the gated helpers when ``TG_METRICS``/``TG_TRACE`` is enabled, so
``summary()["observability"]["serving"]`` and ``metrics.prom`` pick the
series up. Per-model p50/p95/p99 comes straight from the streaming
histogram in ``observability/metrics.py``.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Sequence

from ..local.scoring import (
    SCORE_ERROR_KEY, ScoreSchemaError, ServeStages,
    micro_batch_score_function, score_function,
)
from ..observability import blackbox as _blackbox
from ..observability import ledger as _obs_ledger
from ..observability import metrics as _obs_metrics
from ..observability import postmortem as _postmortem
from ..observability import slo as _slo
from ..observability import timeseries as _timeseries
from ..observability.trace import add_event as _obs_event
from ..observability.trace import span as _obs_span
from ..robustness import faults, resources
from ..robustness import watchdog as _watchdog
from ..robustness.policy import FaultLog, FaultReport
from ..robustness.watchdog import WatchdogStallError
from .breaker import BREAKER_GAUGE, CLOSED, CircuitBreaker, OPEN


class ServingError(RuntimeError):
    """Base of the typed serving-runtime failures."""


class OverloadError(ServingError):
    """The bounded request queue is full — the request was shed at
    admission (backpressure). Retry with backoff or route elsewhere."""


class DeadlineExceededError(ServingError):
    """The request's deadline expired while it was queued; it was shed
    before any device work was spent on it."""


class RuntimeStoppedError(ServingError):
    """The runtime is not accepting requests (stopped or never started)."""


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: Optional[float]) -> Optional[float]:
    raw = os.environ.get(name)
    if raw in (None, ""):
        return default
    try:
        return float(raw)
    except ValueError:
        return default


@dataclass
class ServeConfig:
    """Runtime knobs; every field has a ``TG_SERVE_*`` environment default
    (documented in docs/serving.md "Env knobs").

    ``max_batch`` defaults to the plan compiler's minimum padding bucket
    (utils/padding.py: 256): every flush of up to ``max_batch`` rows pads
    to the same bucket, so ONE compiled program serves all of them.

    ``pipeline_depth`` bounds how many flushes may be in flight at once
    (``TG_SERVE_PIPELINE``): 1 runs today's serial loop; >= 2 enables the
    gather/dispatch/complete pipeline with a completer thread."""
    max_batch: int = 256
    max_queue: int = 1024
    max_wait_ms: float = 2.0
    default_deadline_ms: Optional[float] = None
    breaker_failures: int = 3
    breaker_reset_ms: float = 500.0
    drain_on_close: bool = True
    pipeline_depth: int = 2

    @classmethod
    def from_env(cls) -> "ServeConfig":
        return cls(
            max_batch=_env_int("TG_SERVE_MAX_BATCH", 256),
            max_queue=_env_int("TG_SERVE_QUEUE_MAX", 1024),
            max_wait_ms=_env_float("TG_SERVE_MAX_WAIT_MS", 2.0) or 2.0,
            default_deadline_ms=_env_float("TG_SERVE_DEADLINE_MS", None),
            breaker_failures=_env_int("TG_SERVE_BREAKER_FAILURES", 3),
            breaker_reset_ms=_env_float(
                "TG_SERVE_BREAKER_RESET_MS", 500.0) or 500.0,
            pipeline_depth=max(1, _env_int("TG_SERVE_PIPELINE", 2)),
        )


@dataclass
class _Request:
    row: Dict[str, Any]
    future: Future
    enqueued: float
    deadline: Optional[float]  # absolute monotonic, None = no deadline
    #: flight-recorder correlation id, minted at enqueue and carried
    #: through flush → dispatch → resolve (None when TG_BLACKBOX=0);
    #: also exposed on the Future as ``tg_corr`` so callers (loadgen,
    #: the exemplar reports) can name their requests
    corr: Optional[str] = None
    #: optional tenant label: per-tenant twin series (tg_serve_tenant_*)
    #: feed per-tenant SLO budgets (observability/slo.py); flows through
    #: the TG_METRICS_MAX_LABELS cardinality bound like any label
    tenant: Optional[str] = None


@dataclass
class _Flush:
    """One in-flight flush handed from the batcher to the completer.

    ``kind`` names which completion path applies:

    * ``device`` — the compiled program was launched; ``scored`` holds
      the (possibly still computing) device-result table to block on.
    * ``eager`` — the flush already degraded in the batcher
      (``serve.flush`` fault); the completer scores it per-row.
    * ``quarantine`` — gather/dispatch raised the micro-batch quarantine
      family (ScoreSchemaError/TypeError/ValueError); the completer
      re-scores through the monolithic scorer so quarantined records are
      bit-equal to the serial path's.
    * ``oom`` — the launch exhausted memory; the completer runs the
      adaptive downshift ladder (splits re-fire ``oom.serve`` exactly
      like the serial recursion).
    * ``error`` — a non-resource dispatch failure; the completer counts
      it against the breaker (the dispatching flush) and degrades.
    """
    reqs: List[_Request]
    kind: str
    scored: Any = None
    rows: Optional[List[Dict[str, Any]]] = None
    err: Optional[BaseException] = None
    site: str = "serve.dispatch"


#: live (started, not yet closed) runtimes — the conftest no-leak fixture
#: asserts this is empty around every test
_LIVE_LOCK = threading.Lock()
_LIVE: List["ServingRuntime"] = []


def live_runtimes() -> List["ServingRuntime"]:
    with _LIVE_LOCK:
        return list(_LIVE)


class ServingRuntime:
    """One model's serving loop. Use as a context manager::

        with ServingRuntime(model, name="churn") as rt:
            fut = rt.submit({"x1": 0.2, "x2": -1.0}, deadline_ms=50)
            record = fut.result(timeout=5)

    or synchronously: ``rt.score(row, timeout=5)``. ``close()`` drains the
    queue (by default) and joins the batcher thread.
    """

    def __init__(self, model, name: str = "model",
                 config: Optional[ServeConfig] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 fault_log: Optional[FaultLog] = None,
                 metrics_registry: Optional[_obs_metrics.MetricsRegistry] = None,
                 drift_monitor=None,
                 auto_start: bool = True):
        self.model = model
        self.name = name
        self.config = config or ServeConfig.from_env()
        #: serve-local instruments — always on (see module docstring)
        self.metrics = metrics_registry or _obs_metrics.MetricsRegistry()
        #: memoized (serve-local, global-mirror) instrument handles — the
        #: hot-path counters/histograms skip the registry's per-call
        #: lock + dict resolution (keyed (kind, name, labels); entries
        #: revalidate against the live global registry so metrics.reset()
        #: or set_registry() can never leave a stale mirror bound)
        self._metric_cache: Dict[Any, Any] = {}
        #: serve-scoped fault accounting (ring-bounded; TG_FAULTS_MAX)
        self.fault_log = fault_log or FaultLog()
        #: online distribution monitor (serving/drift.py); every scored
        #: micro-batch folds into it on the batcher thread, behind a
        #: crash-isolation fence — a drift failure can never fail a request
        self.drift_monitor = drift_monitor
        if drift_monitor is not None:
            drift_monitor.bind(name, self.metrics, self.fault_log)
        self.warm_info: Optional[Dict[str, Any]] = None
        self._scorer = micro_batch_score_function(model)
        self._eager_row = score_function(model)
        self._result_names = [f.name for f in model.result_features]
        self._cond = threading.Condition()
        self._queue: Deque[_Request] = deque()
        self._running = False    # batcher thread live
        self._accepting = True   # submit() admits (True before start too,
        #                          so tests can stage a queue deterministically)
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._heart = None  # watchdog heartbeat (set in start())
        #: pipelined dataplane state (module docstring "pipelined
        #: dataplane"); depth 1 = serial, no completer thread
        self.pipeline_depth = max(1, int(self.config.pipeline_depth))
        self._stages = ServeStages(model)
        self._pipe: Deque[_Flush] = deque()
        self._pipe_cond = threading.Condition()
        self._pipe_busy = 0          # flushes popped but still completing
        self._producer_done = False  # batcher exited; completer may drain
        self._completer: Optional[threading.Thread] = None
        self._completer_heart = None
        #: memory-pressure backoff: after any resource exhaustion the next
        #: flush drains the pipeline and runs serially (one clean serial
        #: flush clears it — the pipelined analog of a half-open probe)
        self._oom_serial = False
        #: windowed time-series source over the serve-local registry
        #: (None when TG_SAMPLER=0; set in start(), detached in close())
        self.sampler: Optional[_timeseries.MetricsSampler] = None
        #: one SLO tracker per registered spec for this model (default
        #: env-driven spec when none registered; observability/slo.py)
        self.slo_trackers: List[_slo.SLOTracker] = []
        self.breaker = breaker or CircuitBreaker(
            name=name,
            failure_threshold=self.config.breaker_failures,
            reset_after=self.config.breaker_reset_ms / 1000.0)
        self.breaker.on_transition = self._on_breaker_transition
        self._set_gauge("tg_breaker_state", BREAKER_GAUGE[CLOSED],
                        help="per-model circuit breaker state "
                        "(0=closed, 1=half_open, 2=open; docs/serving.md)")
        if auto_start:
            self.start()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ServingRuntime":
        with self._cond:
            if self._closed:
                raise RuntimeStoppedError(
                    f"runtime '{self.name}' is closed")
            if self._running:
                return self
            self._running = True
        # hang watchdog: the batcher beats this heart every loop
        # iteration; a wedged dispatch stops the beats → thread_stalled
        # is recorded and the breaker trips (docs/robustness.md)
        self._heart = _watchdog.register(
            f"tg-serve[{self.name}]", kind="serve.batcher",
            on_stall=self._on_watchdog_stall, fault_log=self.fault_log)
        # windowed telemetry + SLO budgets: attach the serve-local
        # registry to the shared tg-sampler thread and evaluate every
        # registered SLO spec on its tick cadence (TG_SAMPLER=0 opts the
        # whole plane out — no thread, no trackers, zero writes)
        if self.sampler is None:
            self.sampler = _timeseries.attach(self.metrics, name=self.name)
        if self.sampler is not None and not self.slo_trackers:
            self.slo_trackers = [
                _slo.SLOTracker(spec, self.sampler, self.metrics,
                                runtime=self)
                for spec in _slo.specs_for(self.name)]
            self.sampler.on_sample.append(self._evaluate_slo)
        if self.pipeline_depth > 1 and self._completer is None:
            # the completer gets its own heart: a wedged device wait
            # (stage complete blocks on results) must surface exactly
            # like a wedged batcher dispatch
            self._completer_heart = _watchdog.register(
                f"tg-serve-completer[{self.name}]", kind="serve.completer",
                on_stall=self._on_watchdog_stall, fault_log=self.fault_log)
            self._completer = threading.Thread(
                target=self._completer_loop,
                name=f"tg-serve-completer[{self.name}]", daemon=True)
            self._completer.start()
        self._thread = threading.Thread(
            target=self._loop, name=f"tg-serve[{self.name}]", daemon=True)
        self._thread.start()
        with _LIVE_LOCK:
            _LIVE.append(self)
        return self

    def close(self, drain: Optional[bool] = None) -> None:
        """Stop accepting requests. ``drain=True`` (the config default)
        scores everything already queued before returning; ``drain=False``
        fails queued requests with :class:`RuntimeStoppedError`."""
        drain = self.config.drain_on_close if drain is None else drain
        with self._cond:
            if self._closed:
                return
            self._running = False
            self._accepting = False
            if not drain:
                while self._queue:
                    r = self._queue.popleft()
                    self._fail_future(r.future, RuntimeStoppedError(
                        f"runtime '{self.name}' closed before dispatch"))
                self._set_gauge("tg_serve_queue_depth", 0.0)
            self._cond.notify_all()
        for t in (self._thread, self._completer):
            if t is None:
                continue
            # the batcher joins first: its exit marks the pipe done, which
            # is what lets the completer drain every in-flight flush
            # (zero lost futures) and retire
            t.join(timeout=30)
            if t.is_alive():
                # never discard a still-alive worker silently: record the
                # stall (serve-local counter + FaultLog + global series)
                self.metrics.counter(
                    "tg_watchdog_stalls_total",
                    "thread stalls (docs/robustness.md)",
                    model=self.name, site="serve.close").inc()
                _watchdog.report_thread_stalled(
                    site="serve.close", thread_name=t.name,
                    waited_s=30.0, fault_log=self.fault_log,
                    model=self.name)
        if self._heart is not None:
            self._heart.close()
        if self._completer_heart is not None:
            self._completer_heart.close()
        _timeseries.detach(self.sampler)
        self.sampler = None
        with self._cond:
            self._closed = True
        with _LIVE_LOCK:
            if self in _LIVE:
                _LIVE.remove(self)

    def __enter__(self) -> "ServingRuntime":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def running(self) -> bool:
        with self._cond:
            return self._running

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    # -- request API ---------------------------------------------------------
    def submit(self, row: Dict[str, Any],
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the result
        record (``{feature name: value}``; quarantined rows carry
        ``__score_error__``). Raises :class:`OverloadError` when the queue
        is full and :class:`RuntimeStoppedError` when not running.

        ``tenant`` labels the request for per-tenant SLO budgets: its
        outcome is additionally counted on the ``tg_serve_tenant_*``
        twin series (rows / shed / quarantined / latency), bounded by
        the registry's TG_METRICS_MAX_LABELS cardinality guard."""
        # deterministic chaos entry: an injected fault here models an
        # admission-layer failure (e.g. the listener thread dying)
        faults.inject("serve.enqueue", key=self.name)
        dl_ms = (deadline_ms if deadline_ms is not None
                 else self.config.default_deadline_ms)
        now = time.monotonic()
        deadline = now + dl_ms / 1000.0 if dl_ms else None
        fut: Future = Future()
        # flight-recorder correlation: one id per request, minted here,
        # resolved in _finish — the black box can replay any request's
        # enqueue→resolve timeline (observability/blackbox.py)
        boxed = _blackbox.blackbox_enabled()
        corr = _blackbox.new_correlation_id() if boxed else None
        fut.tg_corr = corr
        with self._cond:
            if not self._accepting:
                raise RuntimeStoppedError(
                    f"runtime '{self.name}' is not accepting requests")
            if len(self._queue) >= self.config.max_queue:
                self._count("tg_serve_shed_total", reason="overload",
                            help="requests shed (docs/serving.md)")
                if tenant is not None:
                    self._count_tenant("tg_serve_tenant_shed_total", tenant)
                if boxed:
                    _blackbox.record("serve.shed", corr=corr,
                                     model=self.name, reason="overload",
                                     queueDepth=len(self._queue))
                raise OverloadError(
                    f"serve queue for model '{self.name}' is full "
                    f"({self.config.max_queue} pending); request shed")
            self._queue.append(_Request(row, fut, now, deadline, corr,
                                        tenant))
            depth = len(self._queue)
            self._set_gauge("tg_serve_queue_depth", float(depth),
                            help="requests waiting for a flush")
            self._cond.notify()
        if boxed:
            _blackbox.record("serve.enqueue", corr=corr, model=self.name,
                             queueDepth=depth)
        return fut

    def score(self, row: Dict[str, Any], timeout: Optional[float] = None,
              deadline_ms: Optional[float] = None) -> Dict[str, Any]:
        """Synchronous convenience: submit + wait."""
        return self.submit(row, deadline_ms=deadline_ms).result(timeout)

    def warm(self, rows: int = 8) -> List[Dict[str, Any]]:
        """Drive the compiled serve path once with synthetic all-missing
        rows — compiles the plan + jitted programs for the padding bucket
        the first real flush will land in (serving/warmup.py). Builds are
        ledger-attributed to subsystem ``serve`` (cause ``cold``)."""
        with _obs_ledger.subsystem_scope("serve"):
            return self._scorer([{} for _ in range(max(1, rows))])

    # -- batcher -------------------------------------------------------------
    def _beat(self) -> None:
        h = self._heart
        if h is not None:
            h.beat()

    def _on_watchdog_stall(self, heart, waited: float) -> None:
        """Watchdog stall response (scanner thread): the batcher stopped
        beating — most likely a wedged dispatch. Trip the breaker so
        batches after the wedge clears (and probes) prefer the degraded
        path, and count the stall on the serve-local registry (the
        FaultLog report + global counter come from the watchdog)."""
        self.breaker.trip(error=WatchdogStallError(
            f"serve batcher for model '{self.name}' stalled "
            f"{waited:.1f}s (> TG_WATCHDOG_S)"))
        self.metrics.counter(
            "tg_watchdog_stalls_total",
            "thread stalls (docs/robustness.md)",
            model=self.name, site="serve.batcher").inc()

    def _loop(self) -> None:
        try:
            while True:
                self._beat()
                batch = self._take_batch()
                if batch is None:
                    return
                if not batch:
                    continue
                try:
                    if (self.pipeline_depth > 1 and not self._oom_serial
                            and self.breaker.state == CLOSED):
                        self._flush_pipelined(batch)
                    else:
                        # breaker not closed (open / half-open probe) or
                        # memory-pressure backoff: drain the in-flight
                        # pipeline, then run this flush serially — the
                        # degraded ladders keep their exact serial shape
                        was_backoff = self._oom_serial
                        self._drain_pipe()
                        self._flush(batch)
                        if was_backoff:
                            self._oom_serial = False
                except Exception as e:  # belt-and-braces: never kill the loop
                    for r in batch:
                        self._fail_future(r.future, e)
        finally:
            # unblock the completer: it drains whatever is still in the
            # pipe (in flush order) and retires — no future is ever
            # dropped by shutdown
            with self._pipe_cond:
                self._producer_done = True
                self._pipe_cond.notify_all()

    def _take_batch(self) -> Optional[List[_Request]]:
        """Block until a batch is ready: a full ``max_batch``, the oldest
        request aging past ``max_wait_ms``, or shutdown (drain). Returns
        None when stopped and drained."""
        cfg = self.config
        with self._cond:
            while not self._queue and self._running:
                self._beat()
                self._cond.wait(0.05)
            if not self._queue:
                return None  # stopped and drained
            flush_at = self._queue[0].enqueued + cfg.max_wait_ms / 1000.0
            while (len(self._queue) < cfg.max_batch and self._running):
                self._beat()
                remaining = flush_at - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.05))
            k = min(len(self._queue), cfg.max_batch)
            batch = [self._queue.popleft() for _ in range(k)]
            self._set_gauge("tg_serve_queue_depth", float(len(self._queue)))
            return batch

    def _flush(self, batch: List[_Request]) -> None:
        # stage attribution twin of the pipelined histograms: one serial
        # flush is gather+dispatch+complete fused, recorded as
        # stage="serial" so the bench A/B can compare like with like
        t0 = time.perf_counter()
        try:
            with _obs_span("serve.flush", cat="serve", model=self.name,
                           rows=len(batch)):
                _blackbox.record("serve.flush", model=self.name,
                                 rows=len(batch),
                                 queueDepth=self.queue_depth())
                alive = self._shed_expired(batch)
                if not alive:
                    return
                try:
                    # chaos: a fault assembling the batch (the batching
                    # layer itself failing) — requests degrade, they do
                    # not fail
                    faults.inject("serve.flush", key=self.name)
                except Exception as e:
                    self._record_degraded("serve.flush", len(alive),
                                          error=e)
                    self._finish(alive, self._eager_records(alive),
                                 degraded=True)
                    return
                self._dispatch(alive)
        finally:
            self._observe_stage("serial", time.perf_counter() - t0)

    # -- pipelined dataplane --------------------------------------------------
    def _observe_stage(self, stage: str, seconds: float) -> None:
        self._observe("tg_serve_stage_seconds", seconds,
                      help="per-pipeline-stage wall time (gather / "
                      "dispatch / complete; stage=serial is one whole "
                      "serial flush — docs/observability.md)", stage=stage)

    def _flush_pipelined(self, batch: List[_Request]) -> None:
        """Stages gather + dispatch on the batcher thread and hands the
        in-flight flush to the completer. Mirrors ``_flush``/``_dispatch``
        step for step — spans, blackbox records, chaos sites, exception
        classification — except nothing here blocks on device results:
        the compiled launch is asynchronous, so the batcher turns around
        and forms the next flush while the device computes this one."""
        # bound the in-flight depth: slots count queued + still-completing
        with self._pipe_cond:
            while len(self._pipe) + self._pipe_busy >= self.pipeline_depth:
                self._beat()
                self._pipe_cond.wait(0.05)
        with _obs_span("serve.flush", cat="serve", model=self.name,
                       rows=len(batch)):
            _blackbox.record("serve.flush", model=self.name,
                             rows=len(batch),
                             queueDepth=self.queue_depth())
            alive = self._shed_expired(batch)
            if not alive:
                return
            try:
                faults.inject("serve.flush", key=self.name)
            except Exception as e:
                # same meaning as serial: the batching layer failed, the
                # requests degrade (counted here, against this flush) —
                # the completer only scores them eagerly, in flush order
                self._record_degraded("serve.flush", len(alive), error=e)
                self._pipe_push(_Flush(alive, "eager", err=e,
                                       site="serve.flush"))
                return
            rows = [r.row for r in alive]
            with _obs_span("serve.dispatch", cat="serve",
                           model=self.name, rows=len(rows)), \
                    _obs_ledger.subsystem_scope("serve"), \
                    _blackbox.correlated(alive[0].corr):
                _blackbox.record("serve.dispatch", model=self.name,
                                 rows=len(rows))
                try:
                    # chaos order matches the serial path exactly:
                    # serve.dispatch, then oom.serve (which the serial
                    # _score_adaptive fires before its scorer call; the
                    # downshift halves re-fire it in the completer's
                    # ladder, so injection call counts are identical)
                    faults.inject("serve.dispatch", key=self.name)
                    faults.inject("oom.serve", key=self.name)
                except Exception as e:
                    if resources.classify_exhaustion(e) is not None:
                        # memory pressure: flushes after this one run
                        # serially until a clean serial flush clears the
                        # backoff (the pipelined half-open analog)
                        self._oom_serial = True
                        self._pipe_push(_Flush(alive, "oom", rows=rows,
                                               err=e))
                    else:
                        self._pipe_push(_Flush(alive, "error", rows=rows,
                                               err=e,
                                               site="serve.dispatch"))
                    return
                try:
                    t0 = time.perf_counter()
                    table = self._stages.gather(rows)
                    t1 = time.perf_counter()
                    scored = self._stages.dispatch(table)
                    t2 = time.perf_counter()
                except (ScoreSchemaError, TypeError, ValueError) as e:
                    # the monolithic scorer's quarantine family: the
                    # completer re-scores through it so quarantined
                    # records stay bit-equal to serial
                    self._pipe_push(_Flush(alive, "quarantine",
                                           rows=rows, err=e))
                    return
                except Exception as e:
                    if resources.classify_exhaustion(e) is not None:
                        self._oom_serial = True
                        self._pipe_push(_Flush(alive, "oom", rows=rows,
                                               err=e))
                    else:
                        self._pipe_push(_Flush(alive, "error", rows=rows,
                                               err=e,
                                               site="serve.dispatch"))
                    return
            self._observe_stage("gather", t1 - t0)
            self._observe_stage("dispatch", t2 - t1)
            self._pipe_push(_Flush(alive, "device", scored=scored,
                                   rows=rows))

    def _pipe_push(self, fl: _Flush) -> None:
        with self._pipe_cond:
            self._pipe.append(fl)
            self._pipe_cond.notify_all()

    def _pipe_pop(self) -> Optional[_Flush]:
        """Completer side: next flush in flush order, or None when the
        batcher has retired and the pipe is fully drained."""
        with self._pipe_cond:
            while not self._pipe and not self._producer_done:
                h = self._completer_heart
                if h is not None:
                    h.beat()
                self._pipe_cond.wait(0.05)
            if not self._pipe:
                return None
            fl = self._pipe.popleft()
            self._pipe_busy += 1
            self._pipe_cond.notify_all()
            return fl

    def _drain_pipe(self) -> None:
        """Batcher side: block until every in-flight flush has fully
        completed. The serial fallbacks (breaker open / half-open probe,
        memory backoff, belt-and-braces) must observe a quiet pipe so
        flush-order resolution and the breaker's single-probe discipline
        hold; with depth 1 the pipe is always empty and this is a no-op."""
        with self._pipe_cond:
            while self._pipe or self._pipe_busy:
                self._beat()
                self._pipe_cond.wait(0.05)

    def _completer_loop(self) -> None:
        while True:
            h = self._completer_heart
            if h is not None:
                h.beat()
            fl = self._pipe_pop()
            if fl is None:
                return
            try:
                self._complete(fl)
            except Exception as e:  # belt-and-braces: never drop futures
                for r in fl.reqs:
                    self._fail_future(r.future, e)
            finally:
                with self._pipe_cond:
                    self._pipe_busy -= 1
                    self._pipe_cond.notify_all()

    def _complete(self, fl: _Flush) -> None:
        """Stage complete (completer thread): resolve one flush exactly
        as the serial path would — breaker accounting charged to the
        dispatching flush, ``_finish`` counting before resolving, drift
        fold — all off the batcher's critical path."""
        reqs = fl.reqs
        rows = fl.rows if fl.rows is not None else [r.row for r in reqs]
        if fl.kind == "eager":
            # _record_degraded already ran in the batcher (serve.flush)
            self._finish(reqs, self._eager_records(reqs), degraded=True)
            return
        if fl.kind == "error":
            # a non-resource dispatch failure surfaces here but counts
            # against the dispatching flush — same breaker arithmetic,
            # same degraded accounting, as the serial _dispatch handler
            self.breaker.record_failure(error=fl.err)
            self._record_degraded(fl.site, len(reqs), error=fl.err)
            self._finish(reqs, self._eager_records(reqs), degraded=True)
            return
        if fl.kind == "oom":
            self._complete_oom(reqs, rows, fl.err)
            return
        if fl.kind == "quarantine":
            self._complete_quarantine(reqs, rows)
            return
        # kind == "device": block on the async result and flatten
        t0 = time.perf_counter()
        try:
            # chaos: a fault here models completion-side failure (a
            # poisoned device result, a transfer error while blocking)
            faults.inject("serve.complete", key=self.name)
        except Exception as e:
            if resources.classify_exhaustion(e) is not None:
                self._oom_serial = True
                self._complete_oom(reqs, rows, e)
                return
            self.breaker.record_failure(error=e)
            self._record_degraded("serve.complete", len(reqs), error=e)
            self._finish(reqs, self._eager_records(reqs), degraded=True)
            return
        try:
            with _obs_ledger.subsystem_scope("serve"), \
                    _blackbox.correlated(reqs[0].corr):
                recs = self._stages.flatten(fl.scored, len(reqs))
        except (ScoreSchemaError, TypeError, ValueError):
            self._complete_quarantine(reqs, rows)
            return
        except Exception as e:
            if resources.classify_exhaustion(e) is not None:
                self._oom_serial = True
                self._complete_oom(reqs, rows, e)
                return
            self.breaker.record_failure(error=e)
            self._record_degraded("serve.complete", len(reqs), error=e)
            self._finish(reqs, self._eager_records(reqs), degraded=True)
            return
        self._observe_stage("complete", time.perf_counter() - t0)
        self.breaker.record_success()
        self._finish(reqs, recs, degraded=False)

    def _complete_quarantine(self, reqs: List[_Request],
                             rows: List[Dict[str, Any]]) -> None:
        """A pipelined flush hit the quarantine family
        (ScoreSchemaError/TypeError/ValueError): re-score through the
        monolithic micro-batch scorer, whose per-row isolation produces
        exactly the records the serial path would have — valid rows score,
        offenders come back quarantined under ``__score_error__``."""
        try:
            with _obs_ledger.subsystem_scope("serve"), \
                    _blackbox.correlated(reqs[0].corr):
                recs = self._scorer(rows)
        except Exception as e:
            # terminal fallback, mirroring _dispatch's handlers
            if resources.classify_exhaustion(e) is not None:
                self._record_degraded("oom.serve", len(rows), error=e)
            else:
                self.breaker.record_failure(error=e)
                self._record_degraded("serve.dispatch", len(rows),
                                      error=e)
            self._finish(reqs, self._eager_records(reqs), degraded=True)
            return
        self.breaker.record_success()
        self._finish(reqs, recs, degraded=False)

    def _complete_oom(self, reqs: List[_Request],
                      rows: List[Dict[str, Any]],
                      err: Optional[BaseException]) -> None:
        """The adaptive downshift ladder for a pipelined flush whose
        launch (or completion) exhausted memory: identical reports,
        counters, and split shape to the serial ``_score_adaptive``
        recursion — the halves go back through ``_score_adaptive``
        itself, so they re-fire ``oom.serve`` exactly like serial
        retries, and resource faults still never feed the breaker."""
        n = len(rows)
        try:
            with _obs_ledger.subsystem_scope("serve"), \
                    _blackbox.correlated(reqs[0].corr):
                if n <= 1:
                    raise err  # a singleton still exhausts → eager
                mid = n // 2
                self.fault_log.add(FaultReport(
                    site="oom.serve", kind="oom_downshift",
                    detail={"model": self.name, "rows": n,
                            "splitRows": [mid, n - mid],
                            "error": f"{type(err).__name__}: {err}"[:200]}))
                self._count("tg_oom_total", site="oom.serve",
                            help="resource-exhaustion events by site "
                            "(docs/robustness.md)")
                self._count("tg_oom_downshift_total",
                            help="adaptive downshifts after resource "
                            "exhaustion (docs/robustness.md)")
                _postmortem.trigger(
                    "oom_downshift", fault_log=self.fault_log,
                    metrics=self.metrics,
                    detail={"site": "oom.serve", "model": self.name,
                            "rows": n,
                            "error": f"{type(err).__name__}: {err}"[:200]})
                recs = (self._score_adaptive(rows[:mid])
                        + self._score_adaptive(rows[mid:]))
        except Exception as e:
            if resources.classify_exhaustion(e) is not None:
                self._record_degraded("oom.serve", n, error=e)
                self._finish(reqs, self._eager_records(reqs),
                             degraded=True)
                return
            self.breaker.record_failure(error=e)
            self._record_degraded("serve.dispatch", n, error=e)
            self._finish(reqs, self._eager_records(reqs), degraded=True)
            return
        self.breaker.record_success()
        self._finish(reqs, recs, degraded=False)

    def _shed_expired(self, batch: List[_Request]) -> List[_Request]:
        """Deadline enforcement happens HERE, after dequeue and before any
        device work — dead requests never reach the compiled program."""
        now = time.monotonic()
        alive: List[_Request] = []
        for r in batch:
            if r.deadline is not None and now >= r.deadline:
                self._count("tg_serve_shed_total", reason="deadline",
                            help="requests shed (docs/serving.md)")
                if r.tenant is not None:
                    self._count_tenant("tg_serve_tenant_shed_total",
                                       r.tenant)
                _blackbox.record("serve.shed", corr=r.corr,
                                 model=self.name, reason="deadline")
                self._fail_future(r.future, DeadlineExceededError(
                    f"deadline expired after "
                    f"{(now - r.enqueued) * 1000:.1f}ms in queue "
                    f"(model '{self.name}'); shed before dispatch"))
            elif r.future.cancelled():
                # a caller cancelled after enqueue: without a typed
                # bucket the request would silently vanish from
                # submitted = completed + typed sheds
                self._count("tg_serve_shed_total", reason="cancelled",
                            help="requests shed (docs/serving.md)")
                if r.tenant is not None:
                    self._count_tenant("tg_serve_tenant_shed_total",
                                       r.tenant)
                _blackbox.record("serve.shed", corr=r.corr,
                                 model=self.name, reason="cancelled")
                continue
            else:
                alive.append(r)
        return alive

    def _score_adaptive(self, rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        """Compiled micro-batch scoring with adaptive degradation: a flush
        whose dispatch exhausts memory splits in half and retries, down to
        singletons — per-row results are independent of the batching, so
        the concatenated halves are bit-equal to the unsplit flush. Each
        split is an ``oom_downshift`` report + ``tg_oom_total``; anything
        non-resource (or a singleton that still exhausts) re-raises to
        ``_dispatch``'s breaker/eager handling."""
        try:
            # chaos: a RESOURCE_EXHAUSTED here models the padded flush not
            # fitting on the device (call-counted, so halves can succeed)
            faults.inject("oom.serve", key=self.name)
            return self._scorer(rows)
        except Exception as e:
            if resources.classify_exhaustion(e) is None or len(rows) <= 1:
                raise
            mid = len(rows) // 2
            self.fault_log.add(FaultReport(
                site="oom.serve", kind="oom_downshift",
                detail={"model": self.name, "rows": len(rows),
                        "splitRows": [mid, len(rows) - mid],
                        "error": f"{type(e).__name__}: {e}"[:200]}))
            self._count("tg_oom_total", site="oom.serve",
                        help="resource-exhaustion events by site "
                        "(docs/robustness.md)")
            self._count("tg_oom_downshift_total",
                        help="adaptive downshifts after resource "
                        "exhaustion (docs/robustness.md)")
            # trigger event: freeze the flight-recorder context for the
            # exhaustion (rate-limited; observability/postmortem.py)
            _postmortem.trigger(
                "oom_downshift", fault_log=self.fault_log,
                metrics=self.metrics,
                detail={"site": "oom.serve", "model": self.name,
                        "rows": len(rows),
                        "error": f"{type(e).__name__}: {e}"[:200]})
            return (self._score_adaptive(rows[:mid])
                    + self._score_adaptive(rows[mid:]))

    def _dispatch(self, alive: List[_Request]) -> None:
        rows = [r.row for r in alive]
        if self.breaker.allow_device():
            try:
                # ledger attribution: any program build this flush pays
                # (a retrace after a schema-shifted request, a new
                # padding bucket) lands as subsystem "serve", correlated
                # to the flush's oldest request — so `cli doctor`
                # timelines show which request paid the retrace
                with _obs_span("serve.dispatch", cat="serve",
                               model=self.name, rows=len(rows)), \
                        _obs_ledger.subsystem_scope("serve"), \
                        _blackbox.correlated(alive[0].corr):
                    _blackbox.record("serve.dispatch", model=self.name,
                                     rows=len(rows))
                    # chaos: a fault here models the compiled micro-batch
                    # path failing (wedged XLA dispatch, poisoned plan)
                    faults.inject("serve.dispatch", key=self.name)
                    recs = self._score_adaptive(rows)
            except Exception as e:
                if resources.classify_exhaustion(e) is not None:
                    # even singleton dispatches exhaust: final fallback is
                    # the eager per-row path — requests still never fail.
                    # The breaker counts only NON-resource faults: the
                    # device path is healthy, the allocations were not.
                    self._record_degraded("oom.serve", len(rows), error=e)
                    self._finish(alive, self._eager_records(alive),
                                 degraded=True)
                    return
                self.breaker.record_failure(error=e)
                self._record_degraded("serve.dispatch", len(rows), error=e)
                self._finish(alive, self._eager_records(alive),
                             degraded=True)
                return
            self.breaker.record_success()
            self._finish(alive, recs, degraded=False)
        else:
            # breaker open: the device path is failing — serve the batch
            # through the eager per-row scorer (bit-equal) instead of
            # failing requests
            self._record_degraded("serve.dispatch", len(rows))
            self._finish(alive, self._eager_records(alive), degraded=True)

    def _eager_records(self, reqs: Sequence[_Request]) -> List[Dict[str, Any]]:
        """The degraded path: eager per-row ``score_function``. Rows the
        eager path cannot score are quarantined under ``__score_error__``
        exactly like the micro-batch path does."""
        out: List[Dict[str, Any]] = []
        for r in reqs:
            try:
                out.append(self._eager_row(r.row))
            except Exception as e:
                rec: Dict[str, Any] = {nm: None for nm in self._result_names}
                rec[SCORE_ERROR_KEY] = f"{type(e).__name__}: {e}"
                out.append(rec)
        return out

    def _finish(self, reqs: Sequence[_Request],
                recs: Sequence[Dict[str, Any]], degraded: bool) -> None:
        # account the flush BEFORE resolving futures: a caller that takes
        # its result and immediately reads summary() must see this flush
        # already counted — resolving first let the woken waiter race
        # ahead of the batcher's counter writes (latencies use one `now`,
        # so the ordering changes no measured value)
        now = time.monotonic()
        boxed = _blackbox.blackbox_enabled()
        quarantined = 0
        for r, rec in zip(reqs, recs):
            if SCORE_ERROR_KEY in rec:
                quarantined += 1
                if r.tenant is not None:
                    self._count_tenant("tg_serve_tenant_quarantined_total",
                                       r.tenant)
            if r.future.cancelled():
                continue
            seconds = now - r.enqueued
            if r.tenant is not None:
                # per-tenant twin series: the tenant-budget SLO trackers'
                # SLI inputs (observability/slo.py)
                self._count_tenant("tg_serve_tenant_rows_total", r.tenant)
                self.metrics.histogram(
                    "tg_serve_tenant_request_seconds",
                    "per-tenant enqueue-to-result latency",
                    model=self.name, tenant=r.tenant).observe(seconds)
            # the request's latency histogram keeps the correlation ids
            # of its slowest observations as exemplars — a p99 outlier
            # links straight to its recorder timeline
            self._observe("tg_serve_request_seconds", seconds,
                          help="enqueue-to-result latency per request "
                          "(p50/p95/p99; docs/serving.md)",
                          exemplar=r.corr)
            if boxed:
                _blackbox.record("serve.resolve", corr=r.corr,
                                 model=self.name,
                                 seconds=round(seconds, 6),
                                 degraded=degraded)
        n = len(reqs)
        self._count("tg_serve_rows_total", float(n),
                    help="requests scored by the serving runtime")
        self._observe("tg_serve_batch_rows", float(n),
                      help="coalesced flush sizes (continuous batching)")
        if degraded:
            self._count("tg_serve_degraded_total", float(n),
                        help="requests served via the eager per-row "
                        "fallback (breaker open or dispatch failure)")
        if quarantined:
            self._count("tg_serve_quarantined_total", float(quarantined),
                        help="requests quarantined under __score_error__")
        for r, rec in zip(reqs, recs):
            try:
                r.future.set_result(rec)
            except InvalidStateError:
                continue  # cancelled while in flight
        # drift fold AFTER every future resolved: still off the request
        # hot path (the batcher thread when serial, the completer when
        # pipelined), post-quarantine, and fenced — nothing past this
        # line can affect a response
        self._drift_observe(reqs, recs)

    def _drift_observe(self, reqs: Sequence[_Request],
                       recs: Sequence[Dict[str, Any]]) -> None:
        """The drift crash-isolation fence: fold the batch's clean rows
        into the monitor; ANY exception (a ``drift.fold`` chaos raise, a
        poisoned fold, a monitor bug) is typed ``drift_fold_failed`` in
        the FaultLog + ``tg_drift_errors_total`` and swallowed."""
        mon = self.drift_monitor
        if mon is None:
            return
        rows = [r.row for r, rec in zip(reqs, recs)
                if SCORE_ERROR_KEY not in rec]
        if not rows:
            return
        try:
            mon.observe(rows)
        except Exception as e:
            mon.fold_errors += 1
            self._count("tg_drift_errors_total", reason="fold",
                        help="drift-monitor failures contained by the "
                        "crash-isolation fence (docs/serving.md)")
            self.fault_log.add(FaultReport(
                site="drift.fold", kind="drift_fold_failed",
                detail={"model": self.name, "rows": len(rows),
                        "error": f"{type(e).__name__}: {e}"[:300]}))

    # -- accounting ----------------------------------------------------------
    def _record_degraded(self, site: str, rows: int,
                         error: Optional[BaseException] = None) -> None:
        detail: Dict[str, Any] = {"model": self.name, "rows": rows,
                                  "breakerState": self.breaker.state}
        if error is not None:
            detail["error"] = f"{type(error).__name__}: {error}"[:300]
        self.fault_log.add(FaultReport(site=site, kind="breaker_degraded",
                                       detail=detail))

    def _on_breaker_transition(self, state: str) -> None:
        self._set_gauge("tg_breaker_state", BREAKER_GAUGE[state],
                        help="per-model circuit breaker state "
                        "(0=closed, 1=half_open, 2=open; docs/serving.md)")
        _obs_event("serve.breaker", model=self.name, state=state)
        if state == OPEN:
            # trigger event: the breaker opening is the canonical serving
            # incident — dump the post-mortem while the recorder still
            # holds the dispatches that opened it. NOTE: this runs under
            # the breaker's lock (on_transition contract), so the detail
            # must not call back into breaker.snapshot().
            _postmortem.trigger(
                "breaker_open", fault_log=self.fault_log,
                metrics=self.metrics,
                detail={"model": self.name, "state": state,
                        "queueDepth": self.queue_depth()})

    def _instruments(self, kind: str, name: str, help: str,
                     labels: Dict[str, str]):
        """Memoized ``(serve-local, global-mirror)`` instrument pair for
        the hot-path helpers below: the registry's per-call lock + label
        resolution runs once per (kind, name, labels) instead of once per
        request. Entries revalidate against the *live* global registry
        (and the enabled switch) by identity, so ``metrics.reset()`` /
        ``set_registry()`` / ``enable_metrics()`` can never leave a stale
        mirror bound — disabled metrics still mean zero global writes."""
        key = (kind, name, tuple(sorted(labels.items())))
        greg = (_obs_metrics.registry()
                if _obs_metrics.metrics_enabled() else None)
        ent = self._metric_cache.get(key)
        if ent is not None and ent[1] is greg:
            return ent[0], ent[2]
        if len(self._metric_cache) > 4096:
            # the registries already bound label cardinality
            # (TG_METRICS_MAX_LABELS → __other__); this is only a backstop
            # against unbounded memoization across registry swaps
            self._metric_cache.clear()
        local = getattr(self.metrics, kind)(
            name, help, model=self.name, **labels)
        mirror = (None if greg is None else
                  getattr(greg, kind)(name, help, model=self.name,
                                      **labels))
        self._metric_cache[key] = (local, greg, mirror)
        return local, mirror

    def _count(self, name: str, n: float = 1.0, help: str = "",
               **labels: str) -> None:
        local, mirror = self._instruments("counter", name, help, labels)
        local.inc(n)
        if mirror is not None:
            mirror.inc(n)

    def _count_tenant(self, name: str, tenant: str, n: float = 1.0) -> None:
        """Per-tenant twin counter (serve-local + gated global mirror);
        the label flows through TG_METRICS_MAX_LABELS like any other."""
        self._count(name, n, help="per-tenant serve accounting "
                    "(docs/serving.md)", tenant=tenant)

    def _evaluate_slo(self, _sampler, now: float) -> None:
        """Sampler tick hook: run every tracker's evaluation pass. Fenced
        per tracker — a broken SLO evaluation must never stop the others
        (the hook runner in timeseries.py fences the whole call too)."""
        for t in self.slo_trackers:
            try:
                t.evaluate(now)
            except Exception:  # pragma: no cover - defensive
                pass

    def slo_snapshot(self) -> Optional[Dict[str, Any]]:
        """Per-spec SLO snapshots keyed by spec key (``model`` or
        ``model/tenant``); None when the sampler is disabled (no windowed
        telemetry → no budgets)."""
        if not self.slo_trackers:
            return None
        return {t.key: t.snapshot() for t in self.slo_trackers}

    def _tenant_breakdown(self, snap: Dict[str, Dict[str, Any]]
                          ) -> Optional[Dict[str, Dict[str, Any]]]:
        """Per-tenant accounting from the twin series; None when no
        request ever carried a tenant label."""
        tenants: Dict[str, Dict[str, Any]] = {}
        for name, field in (("tg_serve_tenant_rows_total", "rows"),
                            ("tg_serve_tenant_shed_total", "shed"),
                            ("tg_serve_tenant_quarantined_total",
                             "quarantined")):
            for key, v in snap.get(name, {}).items():
                kv = dict(p.split("=", 1) for p in key.split(",")
                          if "=" in p)
                if kv.get("model") != self.name or "tenant" not in kv:
                    continue
                tenants.setdefault(kv["tenant"], {})[field] = v
        for key, v in snap.get("tg_serve_tenant_request_seconds",
                               {}).items():
            kv = dict(p.split("=", 1) for p in key.split(",") if "=" in p)
            if kv.get("model") == self.name and "tenant" in kv:
                tenants.setdefault(kv["tenant"], {})["latency"] = v
        return tenants or None

    def _observe(self, name: str, v: float, help: str = "",
                 exemplar: Any = None, **labels: str) -> None:
        local, mirror = self._instruments("histogram", name, help, labels)
        # exemplars live on the serve-local series only (as before)
        local.observe(v, exemplar=exemplar)
        if mirror is not None:
            mirror.observe(v)

    def _set_gauge(self, name: str, v: float, help: str = "",
                   **labels: str) -> None:
        local, mirror = self._instruments("gauge", name, help, labels)
        local.set(v)
        if mirror is not None:
            mirror.set(v)

    @staticmethod
    def _fail_future(fut: Future, exc: BaseException) -> None:
        try:
            fut.set_exception(exc)
        except InvalidStateError:
            pass

    # -- introspection -------------------------------------------------------
    def _series(self, snap: Dict[str, Dict[str, Any]], name: str,
                **match: str) -> float:
        total = 0.0
        for key, v in snap.get(name, {}).items():
            kv = dict(p.split("=", 1) for p in key.split(",") if "=" in p)
            if all(kv.get(k) == val for k, val in match.items()):
                total += float(v)
        return total

    def summary(self) -> Dict[str, Any]:
        """The serve-side ``summary()`` section: SLO quantiles, shed /
        degraded / quarantine counts, breaker + queue state, fault-log
        tail size (docs/serving.md "SLO metrics")."""
        snap = self.metrics.snapshot()
        latency = snap.get("tg_serve_request_seconds", {}).get(
            f"model={self.name}", {})
        return {
            "model": self.name,
            "state": self.health_state(),
            "breaker": self.breaker.snapshot(),
            "queueDepth": self.queue_depth(),
            "latency": latency,
            "batchRows": snap.get("tg_serve_batch_rows", {}).get(
                f"model={self.name}", {}),
            "rowsScored": self._series(snap, "tg_serve_rows_total"),
            "degradedRows": self._series(snap, "tg_serve_degraded_total"),
            "quarantinedRows": self._series(
                snap, "tg_serve_quarantined_total"),
            "shed": {
                "overload": self._series(snap, "tg_serve_shed_total",
                                         reason="overload"),
                "deadline": self._series(snap, "tg_serve_shed_total",
                                         reason="deadline"),
                "cancelled": self._series(snap, "tg_serve_shed_total",
                                          reason="cancelled"),
            },
            # pipelined dataplane state: configured depth and the flushes
            # currently between dispatch and completion (0 when serial)
            "pipeline": {"depth": self.pipeline_depth,
                         "inFlight": len(self._pipe) + self._pipe_busy},
            "faults": {"reports": len(self.fault_log.reports),
                       "dropped": self.fault_log.dropped,
                       # adaptive flush splits under memory pressure and
                       # watchdog/join-leak stall detections
                       # (docs/robustness.md)
                       "oomDownshifts": len(
                           self.fault_log.of_kind("oom_downshift")),
                       "threadStalls": len(
                           self.fault_log.of_kind("thread_stalled"))},
            "warm": self.warm_info,
            # per-model drift verdict + per-feature JS/fill deltas
            # (serving/drift.py); None when no monitor is attached
            "drift": (self.drift_monitor.snapshot()
                      if self.drift_monitor is not None else None),
            # per-spec SLO verdicts/budgets (None when TG_SAMPLER=0) and
            # the derived autoscaling signal — the readiness artifact
            # ROADMAP item 2 consumes (observability/slo.py)
            "slo": self.slo_snapshot(),
            "scaleHint": _slo.scale_hint(self, self.slo_snapshot()),
            # per-tenant accounting breakdown (None without tenants)
            "tenants": self._tenant_breakdown(snap),
        }

    def health_state(self) -> str:
        """``ready`` (running, device path live), ``degraded`` (running but
        the breaker is open — eager fallback serving), or ``stopped``."""
        if not self.running:
            return "stopped"
        return "degraded" if self.breaker.state == OPEN else "ready"
