"""Subprocess replica worker: one ModelRegistry behind a JSON-lines
stdio protocol (docs/serving.md "Replica fleet & front door").

Spawned by :class:`~.fleet.SubprocessReplica` as::

    python -m transmogrifai_tpu.serving.replica_worker \
        --model churn=/path/to/saved_model [--model other=...]

Protocol (one JSON object per line, both directions):

parent → child
    ``{"op": "submit", "id": n, "model": m, "row": {...},
    "deadlineMs": x|null, "tenant": t|null}``,
    ``{"op": "health", "id": n}``,
    ``{"op": "swap", "id": n, "model": m, "path": dir}``,
    ``{"op": "close"}``

child → parent
    ``{"ready": true, "models": [...]}`` once, after every model is
    loaded + warm; then per request ``{"id": n, "record": {...}}`` or
    ``{"id": n, "error": {"type": <typed class name>, "msg": ...}}``
    (typed serving errors survive the process boundary by name —
    fleet.py maps them back), ``{"id": n, "health": {...}}``.

Results are written from Future done-callbacks (the replica's batcher
thread) under one write lock — the protocol needs no ordering beyond
line atomicity. stdout is reserved for the protocol; anything the model
stack prints goes to stderr.
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
from typing import Any, Dict, Optional


def _json_default(o: Any):
    """Records carry numpy scalars off the serve path; JSON them as
    their Python values so bit-equality survives the pipe (binary64
    round-trips exactly through repr)."""
    item = getattr(o, "item", None)
    if callable(item):
        return item()
    return str(o)


class _Writer:
    def __init__(self, stream):
        self._stream = stream
        self._lock = threading.Lock()

    def send(self, msg: Dict[str, Any]) -> None:
        line = json.dumps(msg, separators=(",", ":"),
                          default=_json_default)
        with self._lock:
            self._stream.write(line + "\n")
            self._stream.flush()


def _typed_name(e: BaseException) -> str:
    from .runtime import ServingError
    return (type(e).__name__ if isinstance(e, ServingError)
            else "ReplicaError")


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(prog="replica_worker")
    p.add_argument("--model", action="append", required=True,
                   help="name=saved_model_dir (repeatable)")
    p.add_argument("--max-batch", type=int, default=256)
    p.add_argument("--queue-max", type=int, default=1024)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    a = p.parse_args(argv)

    # stdout is the protocol channel: route any stray prints (jax
    # warnings, model-stack chatter) to stderr before importing them
    proto = sys.stdout
    sys.stdout = sys.stderr

    from .registry import ModelRegistry
    from .runtime import ServeConfig

    cfg = ServeConfig.from_env()
    cfg.max_batch = a.max_batch
    cfg.max_queue = a.queue_max
    cfg.max_wait_ms = a.max_wait_ms
    writer = _Writer(proto)
    reg = ModelRegistry(cfg)
    try:
        names = []
        for spec in a.model:
            name, _, path = spec.partition("=")
            reg.load(name, path)
            names.append(name)
        writer.send({"ready": True, "models": names})
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            op = msg.get("op")
            if op == "close":
                break
            rid = msg.get("id")
            if op == "health":
                try:
                    writer.send({"id": rid, "health": reg.health()})
                except Exception as e:  # noqa: BLE001 - protocol fence
                    writer.send({"id": rid, "error": {
                        "type": _typed_name(e),
                        "msg": f"{type(e).__name__}: {e}"[:300]}})
            elif op == "swap":
                try:
                    reg.swap(msg["model"], msg["path"])
                    writer.send({"id": rid, "record": {"swapped": True}})
                except Exception as e:  # noqa: BLE001 - protocol fence
                    writer.send({"id": rid, "error": {
                        "type": _typed_name(e),
                        "msg": f"{type(e).__name__}: {e}"[:300]}})
            elif op == "submit":
                try:
                    fut = reg.submit(msg["model"], msg.get("row") or {},
                                     deadline_ms=msg.get("deadlineMs"),
                                     tenant=msg.get("tenant"))
                except Exception as e:  # typed shed (overload/stopped)
                    writer.send({"id": rid, "error": {
                        "type": _typed_name(e),
                        "msg": f"{type(e).__name__}: {e}"[:300]}})
                    continue

                def _emit(f, _rid=rid):
                    e = f.exception()
                    if e is not None:
                        writer.send({"id": _rid, "error": {
                            "type": _typed_name(e),
                            "msg": f"{type(e).__name__}: {e}"[:300]}})
                    else:
                        writer.send({"id": _rid, "record": f.result()})
                fut.add_done_callback(_emit)
    finally:
        reg.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
