"""Multi-model registry: named runtimes, warm plan caches, health/SLO.

The serving analog of the reference's one-model-per-MLeap-bundle local
scorer, grown to the multi-model process ROADMAP item 1 asks for: each
registered model gets its own :class:`~.runtime.ServingRuntime` (own
bounded queue, batcher thread, circuit breaker, serve-local metrics), so
one failing model degrades *itself* while its neighbors keep their SLOs.

``load()`` goes through ``persistence.load_model`` (manifest-verified)
and, by default, warm-starts the plan cache from the ``serving`` section
``save_model`` recorded in ``MANIFEST.json`` (serving/warmup.py) — a
fresh process serves its first request without retracing.

``health()`` is the readiness endpoint payload: per-model state
(ready / degraded / stopped), breaker snapshot, queue depth, p50/p95/p99
latency, shed + degraded + quarantine counts, and the warm report.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from .breaker import CircuitBreaker
from .runtime import ServeConfig, ServingRuntime
from . import warmup as _warmup


class ModelRegistry:
    """Name → :class:`ServingRuntime` map with lifecycle management."""

    def __init__(self, config: Optional[ServeConfig] = None):
        self._default_config = config
        self._lock = threading.Lock()
        self._runtimes: Dict[str, ServingRuntime] = {}

    # -- registration --------------------------------------------------------
    def register(self, name: str, model,
                 config: Optional[ServeConfig] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 warm: bool = False,
                 warm_entry: Optional[Dict[str, Any]] = None
                 ) -> ServingRuntime:
        """Start a runtime for ``model`` under ``name``. ``warm=True``
        pre-traces the serve plans before the runtime takes traffic."""
        with self._lock:
            if name in self._runtimes:
                raise ValueError(
                    f"model '{name}' is already registered; "
                    f"unregister() it first")
            rt = ServingRuntime(
                model, name=name,
                config=config or self._default_config,
                breaker=breaker, auto_start=False)
            self._runtimes[name] = rt
        if warm:
            _warmup.warm_runtime(rt, warm_entry)
        rt.start()
        return rt

    def load(self, name: str, path: str, workflow=None,
             config: Optional[ServeConfig] = None,
             warm: bool = True) -> ServingRuntime:
        """Load a saved model (manifest-verified) and register it; by
        default pre-traces the plans recorded in its ``MANIFEST.json``
        ``serving`` section so the first request is served warm."""
        from ..manifest import CheckpointManifest
        from ..persistence import FORMAT_VERSION, load_model
        model = load_model(path, workflow=workflow)
        manifest, err = CheckpointManifest.load(path, FORMAT_VERSION)
        entry = dict(manifest.serving) if err is None else {}
        return self.register(name, model, config=config, warm=warm,
                             warm_entry=entry or None)

    def unregister(self, name: str, drain: bool = True) -> None:
        with self._lock:
            rt = self._runtimes.pop(name, None)
        if rt is not None:
            rt.close(drain=drain)

    # -- access --------------------------------------------------------------
    def runtime(self, name: str) -> ServingRuntime:
        with self._lock:
            try:
                return self._runtimes[name]
            except KeyError:
                raise KeyError(
                    f"no model '{name}' registered "
                    f"(have: {sorted(self._runtimes)})") from None

    __getitem__ = runtime

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._runtimes)

    def submit(self, name: str, row: Dict[str, Any], **kw):
        return self.runtime(name).submit(row, **kw)

    def score(self, name: str, row: Dict[str, Any], **kw) -> Dict[str, Any]:
        return self.runtime(name).score(row, **kw)

    # -- health --------------------------------------------------------------
    def health(self) -> Dict[str, Any]:
        """Readiness snapshot: ``ready`` is True only when every registered
        model is serving with its device path live (breaker not open)."""
        with self._lock:
            rts = dict(self._runtimes)
        models = {name: rt.summary() for name, rt in sorted(rts.items())}
        return {
            "ready": bool(models) and all(
                m["state"] == "ready" for m in models.values()),
            "models": models,
        }

    # -- lifecycle -----------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        with self._lock:
            rts = list(self._runtimes.values())
            self._runtimes.clear()
        for rt in rts:
            rt.close(drain=drain)

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
