"""Multi-model registry: named runtimes, warm plan caches, health/SLO,
drift-aware self-healing.

The serving analog of the reference's one-model-per-MLeap-bundle local
scorer, grown to the multi-model process ROADMAP item 1 asks for: each
registered model gets its own :class:`~.runtime.ServingRuntime` (own
bounded queue, batcher thread, circuit breaker, serve-local metrics), so
one failing model degrades *itself* while its neighbors keep their SLOs.

``load()`` goes through ``persistence.load_model`` (manifest-verified)
and, by default, warm-starts the plan cache from the ``serving`` section
``save_model`` recorded in ``MANIFEST.json`` (serving/warmup.py) — a
fresh process serves its first request without retracing. When the
manifest also carries a ``drift`` baseline (and ``TG_DRIFT`` is not
``0``), a :class:`~.drift.DriftMonitor` is attached so the model's
scoring distribution is compared online against its training
distribution (docs/serving.md "Drift monitoring & self-healing").

Self-healing: a configured ``refit_hook`` — ``(name, runtime, drift
report) -> saved-model path or OpWorkflowModel`` — fires in a background
thread the first time a model's drift verdict degrades. The refreshed
model then hot-swaps through :meth:`ModelRegistry.swap`: built + warmed
*before* the entry flips, old runtime drained *after*, so requests keep
flowing (on the old model) throughout and not one is shed by the swap. A
failed refit is typed ``drift_refit_failed`` in the runtime's FaultLog
and the old model keeps serving — the breaker is untouched (the device
path is healthy; the *data* is what drifted).

``health()`` is the readiness endpoint payload: per-model state
(ready / degraded / stopped), breaker snapshot, queue depth, p50/p95/p99
latency, shed + degraded + quarantine counts, the warm report, and the
drift verdict.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..observability import blackbox as _blackbox
from ..observability.trace import add_event as _obs_event
from ..robustness import faults
from ..robustness import watchdog as _watchdog
from ..robustness.policy import FaultReport
from . import drift as _drift
from . import warmup as _warmup
from .breaker import CircuitBreaker
from .runtime import ServeConfig, ServingRuntime

#: refit hook signature: (model name, live runtime, drift report) → a
#: saved-model directory path (manifest-verified load) or a fitted
#: OpWorkflowModel. ``OpWorkflow.drift_refit_hook`` builds one.
RefitHook = Callable[[str, ServingRuntime, Dict[str, Any]], Any]


class ModelRegistry:
    """Name → :class:`ServingRuntime` map with lifecycle management."""

    def __init__(self, config: Optional[ServeConfig] = None,
                 refit_hook: Optional[RefitHook] = None):
        self._default_config = config
        self._lock = threading.Lock()
        self._runtimes: Dict[str, ServingRuntime] = {}
        self._refit_hook = refit_hook
        self._refit_lock = threading.Lock()
        self._refits_inflight: set = set()
        #: completed refit attempts, oldest first (success and failure)
        self.refit_history: List[Dict[str, Any]] = []

    # -- registration --------------------------------------------------------
    def register(self, name: str, model,
                 config: Optional[ServeConfig] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 warm: bool = False,
                 warm_entry: Optional[Dict[str, Any]] = None,
                 drift_monitor: Optional["_drift.DriftMonitor"] = None,
                 store_path: Optional[str] = None,
                 ) -> ServingRuntime:
        """Start a runtime for ``model`` under ``name``. ``warm=True``
        pre-warms the serve programs before the runtime takes traffic
        (deserialized from the AOT store when a session is open, traced
        otherwise — and captured back into ``store_path`` when given);
        ``drift_monitor`` attaches online distribution monitoring."""
        with self._lock:
            if name in self._runtimes:
                raise ValueError(
                    f"model '{name}' is already registered; "
                    f"unregister() it first")
            rt = ServingRuntime(
                model, name=name,
                config=config or self._default_config,
                breaker=breaker, drift_monitor=drift_monitor,
                auto_start=False)
            self._runtimes[name] = rt
        self._wire_drift(name, rt)
        if warm:
            _warmup.warm_runtime(rt, warm_entry, store_path=store_path)
        rt.start()
        return rt

    def load(self, name: str, path: str, workflow=None,
             config: Optional[ServeConfig] = None,
             warm: bool = True) -> ServingRuntime:
        """Load a saved model (manifest-verified) and register it; by
        default pre-traces the plans recorded in its ``MANIFEST.json``
        ``serving`` section so the first request is served warm, and
        attaches a DriftMonitor when the manifest carries a ``drift``
        baseline (``TG_DRIFT=0`` opts out)."""
        model, entry, monitor = self._load_parts(path, workflow)
        rt = self.register(name, model, config=config, warm=warm,
                           warm_entry=entry or None,
                           drift_monitor=monitor,
                           store_path=path if warm else None)
        if warm:
            # warmup-time cost persistence: the warm pre-trace just
            # measured this process's (segment fingerprint × bucket)
            # bytes/compile/execute costs — merge them into the model's
            # MANIFEST `costs` section so admission control (ROADMAP
            # item 2) and the AOT store (item 1) can read them next load.
            # Best-effort by contract: a read-only model dir must not
            # fail the load.
            from ..observability import devicemem as _devicemem
            _devicemem.persist_costs(path)
        return rt

    @staticmethod
    def _load_parts(path: str, workflow=None):
        from ..manifest import CheckpointManifest
        from ..persistence import FORMAT_VERSION, load_model
        from ..programstore import store as _pstore
        # AOT program store: open the session over the manifest
        # `programs` section BEFORE anything can trace, so the warm
        # pre-pass (and every later new-bucket dispatch) deserializes
        # stored programs instead of compiling (docs/serving.md "AOT
        # cold start & the program store"; None when absent/disabled)
        _pstore.open_model_session(path)
        model = load_model(path, workflow=workflow)
        manifest, err = CheckpointManifest.load(path, FORMAT_VERSION)
        entry = dict(manifest.serving) if err is None else {}
        monitor = None
        if err is None and manifest.drift and _drift.drift_enabled():
            monitor = _drift.DriftMonitor(
                _drift.DriftBaseline.from_json(manifest.drift))
        return model, entry, monitor

    def unregister(self, name: str, drain: bool = True) -> None:
        with self._lock:
            rt = self._runtimes.pop(name, None)
        if rt is not None:
            rt.close(drain=drain)

    # -- access --------------------------------------------------------------
    def runtime(self, name: str) -> ServingRuntime:
        with self._lock:
            try:
                return self._runtimes[name]
            except KeyError:
                raise KeyError(
                    f"no model '{name}' registered "
                    f"(have: {sorted(self._runtimes)})") from None

    __getitem__ = runtime

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._runtimes)

    def submit(self, name: str, row: Dict[str, Any], **kw):
        return self.runtime(name).submit(row, **kw)

    def score(self, name: str, row: Dict[str, Any], **kw) -> Dict[str, Any]:
        return self.runtime(name).score(row, **kw)

    # -- drift-triggered refit + hot swap ------------------------------------
    def set_refit_hook(self, hook: Optional[RefitHook]) -> "ModelRegistry":
        self._refit_hook = hook
        return self

    def _wire_drift(self, name: str, rt: ServingRuntime) -> None:
        mon = rt.drift_monitor
        if mon is not None:
            mon.on_degraded = (
                lambda report, _n=name: self._on_degraded(_n, report))

    def _on_degraded(self, name: str, report: Dict[str, Any]) -> None:
        """Fired (once per transition into ``degraded``) from the model's
        batcher thread — must never block it: the refit runs in its own
        daemon thread, at most one per model."""
        _obs_event("drift.degraded", model=name)
        _blackbox.record("drift.degraded", model=name,
                         refitHook=self._refit_hook is not None)
        if self._refit_hook is None:
            return
        with self._refit_lock:
            if name in self._refits_inflight:
                return
            self._refits_inflight.add(name)
        t = threading.Thread(target=self._run_refit, args=(name, report),
                             name=f"tg-drift-refit[{name}]", daemon=True)
        _drift.track_refit(t)
        t.start()

    def _run_refit(self, name: str, report: Dict[str, Any]) -> None:
        entry: Dict[str, Any] = {"model": name, "ok": False}
        try:
            rt = self.runtime(name)
        except KeyError:
            with self._refit_lock:
                self._refits_inflight.discard(name)
            _drift.untrack_refit(threading.current_thread())
            return
        # hang watchdog: a refit is one long hook call with no heartbeat
        # cadence, so a heart that never beats past TG_WATCHDOG_S records
        # the wedge (thread_stalled + tg_watchdog_stalls_total) — the
        # model keeps serving either way, but the hang is never silent
        heart = _watchdog.register(
            f"tg-drift-refit[{name}]", kind="drift.refit",
            fault_log=rt.fault_log)
        try:
            # deterministic chaos entry: a fault anywhere in the refit
            # path (hook crash, corrupt save, load failure) — the old
            # model keeps serving, the breaker is untouched
            faults.inject("drift.refit", key=name)
            result = self._refit_hook(name, rt, report)
            new_rt = self.swap(name, result)
            entry.update(ok=True, swapped=True,
                         path=result if isinstance(result, str) else None)
            new_rt.fault_log.add(FaultReport(
                site="drift.refit", kind="drift_refit",
                detail={"model": name,
                        "path": entry.get("path"),
                        "triggerVerdict": report.get("verdict")}))
            _obs_event("drift.refit", model=name, ok=True)
        except Exception as e:
            entry["error"] = f"{type(e).__name__}: {e}"[:300]
            rt.fault_log.add(FaultReport(
                site="drift.refit", kind="drift_refit_failed",
                detail={"model": name, "error": entry["error"]}))
            _obs_event("drift.refit", model=name, ok=False)
        finally:
            heart.close()
            self.refit_history.append(entry)
            with self._refit_lock:
                self._refits_inflight.discard(name)
            _drift.untrack_refit(threading.current_thread())

    def swap(self, name: str, model_or_path, warm: bool = True,
             workflow=None) -> ServingRuntime:
        """Hot-swap ``name`` to a new model with zero request loss: the
        replacement runtime is built, (optionally) warm pre-traced, and
        *started* before the registry entry flips; the old runtime then
        closes with ``drain=True``, scoring everything already queued on
        the old model. ``model_or_path``: a saved-model directory
        (manifest-verified load + warm fingerprint + drift baseline) or a
        fitted ``OpWorkflowModel`` (baseline rebuilt from its train
        table when possible)."""
        old = self.runtime(name)
        entry: Optional[Dict[str, Any]] = None
        if isinstance(model_or_path, str):
            model, entry, monitor = self._load_parts(model_or_path, workflow)
        else:
            model = model_or_path
            monitor = None
            if _drift.drift_enabled():
                try:
                    monitor = _drift.DriftMonitor(
                        _drift.DriftBaseline.from_model(model))
                except Exception:
                    monitor = None  # no baseline → serve unmonitored
        new_rt = ServingRuntime(model, name=name,
                                config=old.config,
                                drift_monitor=monitor, auto_start=False)
        self._wire_drift(name, new_rt)
        if warm:
            _warmup.warm_runtime(new_rt, entry or None,
                                 store_path=(model_or_path
                                             if isinstance(model_or_path,
                                                           str) else None))
        new_rt.start()
        with self._lock:
            if self._runtimes.get(name) is not old:
                current = self._runtimes.get(name)
                raise RuntimeError(
                    f"model '{name}' changed during swap "
                    f"({'unregistered' if current is None else 'replaced'})")
            self._runtimes[name] = new_rt
        old.close(drain=True)
        _obs_event("serve.swap", model=name)
        _blackbox.record("serve.swap", model=name)
        return new_rt

    # -- health --------------------------------------------------------------
    def health(self) -> Dict[str, Any]:
        """Readiness snapshot: ``ready`` is True only when every registered
        model is serving with its device path live (breaker not open).
        Each model's entry carries its drift verdict (``summary()["drift"]``)
        — a drift-degraded model still serves, so it does not flip
        ``ready``; it flags that its *data* needs attention (or a refit is
        already healing it)."""
        with self._lock:
            rts = dict(self._runtimes)
        models = {name: rt.summary() for name, rt in sorted(rts.items())}
        with self._refit_lock:
            inflight = sorted(self._refits_inflight)
        return {
            "ready": bool(models) and all(
                m["state"] == "ready" for m in models.values()),
            "models": models,
            # the fleet's autoscaling view in one map: each model's
            # up/hold/down hint (derived from queue depth, shed rate,
            # breaker state, SLO burn and drift verdict — each entry's
            # full reasons live in models[name]["scaleHint"]); the
            # artifact ROADMAP item 2's replica controller consumes
            "scaleHints": {name: m["scaleHint"]["hint"]
                           for name, m in models.items()},
            "refitsInFlight": inflight,
            "refits": list(self.refit_history),
        }

    # -- lifecycle -----------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        # runtime.close drains the pipelined dataplane too: the batcher
        # retires first (failing still-queued futures typed when
        # drain=False), then the completer resolves every in-flight
        # flush before its join — zero lost futures at any depth
        with self._lock:
            rts = list(self._runtimes.values())
            self._runtimes.clear()
        for rt in rts:
            rt.close(drain=drain)
        # a refit racing close() targets an unregistered name and exits;
        # wait briefly so no tg-drift-refit thread outlives the registry —
        # and never discard one that does silently: the leak is recorded
        # as thread_stalled + tg_watchdog_stalls_total (docs/robustness.md)
        for t in _drift.live_refits():
            t.join(timeout=30)
            if t.is_alive():
                _watchdog.report_thread_stalled(
                    site="registry.close", thread_name=t.name,
                    waited_s=30.0)

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
