"""Resilient serving runtime (docs/serving.md; ROADMAP item 1).

Continuous batching over the compiled micro-batch scorer with
backpressure (bounded queue + typed :class:`OverloadError` shedding),
per-request deadlines (shed before dispatch), a per-model circuit breaker
that degrades to the bit-equal eager path instead of failing requests, a
multi-model registry with warm plan caches, per-model p50/p95/p99 SLO
reporting from ``observability/metrics.py``, and drift-aware self-healing
(``drift.py``): online train-vs-score distribution monitoring with
automatic background refit + hot swap.

The horizontal layer (ROADMAP item 2, docs/serving.md "Replica fleet &
front door"): a shared-nothing replica fleet (``fleet.py`` — in-process
replicas tier-1, subprocess replicas behind ``TG_FLEET_SUBPROCESS``)
behind a :class:`~.frontdoor.FrontDoor` that routes load-aware, ejects
sick replicas, fails requests over on replica loss with zero lost
futures, refuses-or-splits flushes against ``TG_DEVICE_BUDGET`` before
dispatch, rolls deploys replica-by-replica, and autoscales on
``scale_hint``.

Fleet density (ROADMAP item 4, docs/serving.md "Multi-model placement &
paging"): ``placement.py`` bin-packs many models onto few replicas
against predicted MANIFEST ``costs`` bytes / a warm-count cap, pages
cold models in on demand (single-flight, a deserialize via the AOT
program store), LRU-evicts idle ones (SLO-burn protected), and keeps
the zero-lost-futures identity through warm-copy loss.

The process boundary (docs/serving.md "Network edge"): a chaos-hardened
asyncio front end (``netedge.py`` + ``netproto.py``) terminating
HTTP/JSON and a length-prefixed binary columnar framing on a real
socket, with per-tenant auth/quota at the edge, ``Retry-After`` derived
from the windowed shed rate, and typed sheds for every wire failure
mode — the zero-lost-futures identity extends across the network.
"""
from .breaker import BREAKER_GAUGE, CircuitBreaker  # noqa: F401
from .drift import (  # noqa: F401
    DEGRADED, DRIFTING, OK, DriftBaseline, DriftConfig, DriftMonitor,
    drift_enabled, live_refits, manifest_drift_entry,
)
from .fleet import (  # noqa: F401
    AdmissionRefusedError, FleetConfig, Replica, ReplicaLostError,
    SubprocessReplica,
)
from .frontdoor import FrontDoor, live_fleets  # noqa: F401
from .loadgen import (  # noqa: F401
    run_open_loop, run_wire_open_loop, synthetic_rows,
)
from .netedge import (  # noqa: F401
    SHED_STATUS, NetEdge, NetEdgeConfig, derive_retry_after, live_edges,
)
from .netproto import (  # noqa: F401
    FrameError, WireClient, WireDisconnect, WireResult,
)
from .placement import (  # noqa: F401
    PlaceConfig, Placer, PlacementRefusedError, UnknownModelError,
    live_placers, model_cost_bytes,
)
from .registry import ModelRegistry  # noqa: F401
from .runtime import (  # noqa: F401
    DeadlineExceededError, OverloadError, RuntimeStoppedError, ServeConfig,
    ServingError, ServingRuntime, live_runtimes,
)
from .warmup import (  # noqa: F401
    manifest_serving_entry, serve_plan_fingerprint, warm_runtime,
)
