"""Multi-model fleet density: cost-driven placement, LRU cold-model
paging, and warm-copy failover bookkeeping.

The paper's AutoML setting produces one pipeline per customer, so a
production fleet holds *thousands of models*, not one hot one. The
:class:`Placer` is the fleet-level generalization of the per-request
admission control (docs/serving.md "Admission control"): it bin-packs
models onto replicas against a per-replica budget using predicted
resident bytes from each saved model's MANIFEST ``costs`` table
(observability/devicemem.py), keeps a deterministic LRU over warm
copies, and pages cold models in on demand — a *deserialize*, not a
compile, thanks to the AOT program store (PR 15) — under a single-flight
guard so N concurrent requests for a cold model trigger one page-in,
not N.

Capacity is two-dimensional and both axes are optional:

* ``max_warm`` — per-replica warm-model **count** cap (deterministic,
  works for in-memory models with no manifest);
* ``device_budget`` — per-replica predicted-**bytes** cap
  (``TG_PLACE_BUDGET``, falling back to ``TG_DEVICE_BUDGET``). A model
  whose predicted bytes exceed the budget even alone on an empty
  replica fits *nowhere* and every submit for it raises the typed
  :class:`PlacementRefusedError` (an :class:`~.runtime.OverloadError`,
  so it buckets as a shed — never a lost future).

A model whose MANIFEST ``costs`` section is absent or corrupt is
**blind-admitted**: placement degrades to counting it as zero bytes and
records a typed ``placement_blind_admit`` FaultLog warning (plus
``tg_place_blind_admits_total``) instead of refusing or crashing —
admission is a consumer of telemetry, not a guess.

Chaos sites (deterministic, counter-driven — robustness/faults.py):

* ``place.assign`` — per model, as the bin-pack assigns it to a
  replica; a raise leaves the model cold (typed ``place_assign_failed``)
  and it pages in on first demand — zero request impact.
* ``place.evict`` — before an LRU victim's runtime unloads; a raise
  skips the eviction (capacity prediction is advisory) with a typed
  ``place_evict_failed`` and the page-in proceeds anyway.
* ``place.pagein`` — in the single-flight leader, before the cold
  model's runtime loads; a raise fails the page-in typed
  (``place_pagein_failed``) and the front door retries within its
  bounded failover budget — typed shed when exhausted, never lost.

Eviction protection: a ``protect`` hook (the front door wires it to
per-model SLO page-alert state) exempts models with active SLO burn
from victim selection, so one noisy neighbor cannot page out a model
that is already missing its objectives.

Gated series (zero-write when TG_METRICS is off): ``tg_place_resident``
(gauge, per replica), ``tg_place_pageins_total``,
``tg_place_evictions_total``, ``tg_place_blind_admits_total``,
``tg_place_refused_total``, ``tg_place_pagein_seconds`` (histogram).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..observability import blackbox as _blackbox
from ..observability import metrics as _obs_metrics
from ..robustness import faults
from ..robustness.policy import FaultLog, FaultReport
from .runtime import OverloadError, ServingError

_ENV_PREFIX = "TG_PLACE_"

#: live placers, for the leak oracle (robustness/oracles.py) and the
#: post-mortem ``placement`` section (observability/postmortem.py)
_LIVE: List["Placer"] = []
_LIVE_LOCK = threading.Lock()


def live_placers() -> List["Placer"]:
    with _LIVE_LOCK:
        return list(_LIVE)


class PlacementRefusedError(OverloadError):
    """Per-model admission refused: the model's predicted resident bytes
    exceed the per-replica budget even alone on an empty replica, so no
    amount of eviction can page it in. An :class:`OverloadError` so the
    front door sheds it typed (``placement`` reason) — the caller sees a
    clean refusal, never a lost future. The fix is capacity (raise
    ``TG_PLACE_BUDGET`` / add device memory), not a retry."""


class UnknownModelError(ServingError):
    """The request names a model this fleet does not serve. Typed so the
    network edge maps it to a 404 shed (serving/netedge.py) — a wrong
    model id is a *client* error and must never look like capacity."""


def model_cost_bytes(src: Any) -> Optional[int]:
    """Predicted resident device bytes for a model source, from its
    MANIFEST ``costs`` table: the sum over segment fingerprints of each
    segment's largest-bucket measured bytes. ``None`` (→ blind admit)
    for in-memory models, absent manifests, or corrupt cost sections —
    a garbled cost table must never block placement."""
    if not isinstance(src, str):
        return None
    try:
        from ..manifest import CheckpointManifest
        from ..persistence import FORMAT_VERSION
        manifest, err = CheckpointManifest.load(src, FORMAT_VERSION)
        if err is not None:
            return None
        table = manifest.costs.get("table")
        if not isinstance(table, dict) or not table:
            return None
        by_fp: Dict[str, int] = {}
        for row in table.values():
            if not isinstance(row, dict):
                continue
            fp = str(row.get("fingerprint", ""))
            b = int(row.get("bytes", 0))
            if fp and b > 0:
                by_fp[fp] = max(by_fp.get(fp, 0), b)
        if not by_fp:
            return None
        return sum(by_fp.values())
    except Exception:
        return None


class PlaceConfig:
    """Placement knobs (``TG_PLACE_*`` env — docs/serving.md
    "Multi-model placement & paging").

    ``max_warm``: per-replica warm-model count cap (0 = unlimited).
    ``device_budget``: per-replica predicted-bytes cap (0 = off;
    ``TG_PLACE_BUDGET`` falls back to ``TG_DEVICE_BUDGET`` so one knob
    governs both per-request and per-model admission).
    ``pagein_timeout_s``: how long a waiter blocks on another thread's
    in-flight page-in before giving up typed.
    ``protect_slo``: exempt models with active SLO page alerts from
    LRU victim selection."""

    def __init__(self, max_warm: int = 0, device_budget: int = 0,
                 pagein_timeout_s: float = 30.0,
                 protect_slo: bool = True):
        self.max_warm = int(max_warm)
        self.device_budget = int(device_budget)
        self.pagein_timeout_s = float(pagein_timeout_s)
        self.protect_slo = bool(protect_slo)

    @classmethod
    def from_env(cls) -> "PlaceConfig":
        import os

        def _i(name: str, default: int) -> int:
            try:
                return int(os.environ.get(name, default))
            except (TypeError, ValueError):
                return default

        budget = _i(_ENV_PREFIX + "BUDGET", 0) or _i("TG_DEVICE_BUDGET", 0)
        try:
            timeout = float(os.environ.get(
                _ENV_PREFIX + "PAGEIN_TIMEOUT_S", 30.0))
        except (TypeError, ValueError):
            timeout = 30.0
        return cls(max_warm=_i(_ENV_PREFIX + "MAX_WARM", 0),
                   device_budget=budget,
                   pagein_timeout_s=timeout,
                   protect_slo=os.environ.get(
                       _ENV_PREFIX + "PROTECT_SLO", "1") != "0")


class Placer:
    """Fleet-level model→replica placement: bin-packing, deterministic
    LRU paging, single-flight page-in, and warm-copy bookkeeping.

    The placer owns *policy and accounting* only — the front door owns
    the replicas and passes ``load``/``unload`` callables into
    :meth:`page_in`, so the placer is directly testable with fakes.

    LRU is a logical sequence counter (no clocks): every routed request
    bumps its model's ``last_used`` sequence; the victim on a replica is
    the resident model with the smallest ``(last_used, name)`` — the
    name tie-break makes eviction order deterministic for models that
    have never been touched."""

    def __init__(self, models: Dict[str, Any],
                 config: Optional[PlaceConfig] = None,
                 name: str = "fleet",
                 fault_log: Optional[FaultLog] = None,
                 metrics: Optional[_obs_metrics.MetricsRegistry] = None,
                 protect: Optional[Callable[[str], bool]] = None):
        self.models = dict(models)
        self.config = config or PlaceConfig()
        self.name = name
        self.fault_log = fault_log if fault_log is not None else FaultLog()
        self.metrics = metrics if metrics is not None \
            else _obs_metrics.MetricsRegistry()
        #: hook: model → True when eviction must be refused (the front
        #: door wires active SLO page-alert state in here)
        self.protect = protect
        self._lock = threading.Lock()
        #: rid → set of models resident (warm) on that replica
        self._resident: Dict[str, Set[str]] = {}
        #: model → logical last-used sequence (insertion order seeds it
        #: so never-touched models evict deterministically, oldest name
        #: first among ties via the (seq, name) sort key)
        self._last_used: Dict[str, int] = {}
        self._seq = 0
        #: (rid, model) → Event: in-flight single-flight page-ins
        self._inflight: Dict[Tuple[str, str], threading.Event] = {}
        self._pagein_ms: List[float] = []
        self._evictions = 0
        self._pageins = 0
        self._closed = False
        #: predicted resident bytes per model (None = blind admit)
        self.bytes: Dict[str, Optional[int]] = {}
        #: models refused outright: predicted bytes exceed the budget
        #: even alone on an empty replica
        self.refused: Set[str] = set()
        self.blind: Set[str] = set()
        budget = self.config.device_budget
        for m in sorted(self.models):
            self._last_used[m] = self._next_seq()
            b = model_cost_bytes(self.models[m])
            self.bytes[m] = b
            if budget and b is None:
                # degraded, not refused: admit blind with a typed warning
                self.blind.add(m)
                self.fault_log.add(FaultReport(
                    site="place.assign", kind="placement_blind_admit",
                    detail={"fleet": self.name, "model": m,
                            "reason": "no usable MANIFEST costs table"}))
                self._count("tg_place_blind_admits_total", model=m)
            elif budget and b is not None and b > budget:
                self.refused.add(m)
                self.fault_log.add(FaultReport(
                    site="place.assign", kind="placement_refused",
                    detail={"fleet": self.name, "model": m,
                            "predictedBytes": b, "budgetBytes": budget}))
                self._count("tg_place_refused_total", model=m)
        with _LIVE_LOCK:
            _LIVE.append(self)

    # -- helpers -------------------------------------------------------------
    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _count(self, name: str, n: float = 1.0, **labels: str) -> None:
        labels.setdefault("fleet", self.name)
        self.metrics.counter(
            name, "multi-model placement accounting "
            "(docs/serving.md 'Multi-model placement & paging')",
            **labels).inc(n)
        _obs_metrics.inc_counter(name, n, **labels)

    def check_admitted(self, model: str) -> None:
        """Raise the typed refusal for a model that fits nowhere."""
        if model in self.refused:
            raise PlacementRefusedError(
                f"placement refused for model '{model}': predicted "
                f"resident bytes {self.bytes[model]} exceed the "
                f"per-replica budget {self.config.device_budget} even on "
                f"an empty replica — raise TG_PLACE_BUDGET or shrink the "
                f"model")

    def _fits(self, resident: Set[str], model: str) -> bool:
        cfg = self.config
        if cfg.max_warm and len(resident) >= cfg.max_warm:
            return False
        if cfg.device_budget:
            used = sum(self.bytes.get(m) or 0 for m in resident)
            need = self.bytes.get(model) or 0
            if used + need > cfg.device_budget:
                return False
        return True

    # -- bin-packing ---------------------------------------------------------
    def plan(self, rids: List[str]) -> Dict[str, List[str]]:
        """First-fit-decreasing bin-pack of every admitted model onto
        ``rids``: models sorted by predicted bytes (descending, name
        tie-break), each placed on the least-loaded replica it fits on.
        Models that fit nowhere *because warm capacity is exhausted*
        stay cold and page in on demand; only a model too big for an
        empty replica lands in :attr:`refused`. Chaos: ``place.assign``
        fires per assignment — a raise leaves that model cold, typed
        ``place_assign_failed``."""
        with self._lock:
            for rid in rids:
                self._resident.setdefault(rid, set())
        order = sorted(
            (m for m in self.models
             if m not in self.refused),
            key=lambda m: (-(self.bytes.get(m) or 0), m))
        for m in order:
            with self._lock:
                # least-loaded replica (resident count, then rid) the
                # model fits on
                cands = sorted(
                    ((len(self._resident[r]), r) for r in rids
                     if self._fits(self._resident[r], m)),
                )
            if not cands:
                continue  # cold: pages in on demand
            rid = cands[0][1]
            try:
                faults.inject("place.assign", key=m)
            except Exception as e:
                self.fault_log.add(FaultReport(
                    site="place.assign", kind="place_assign_failed",
                    detail={"fleet": self.name, "model": m,
                            "replica": rid,
                            "error": f"{type(e).__name__}: {e}"[:200]}))
                continue  # left cold — demand paging recovers
            with self._lock:
                self._resident[rid].add(m)
            _blackbox.record("place.assign", fleet=self.name,
                             model=m, replica=rid)
        self._set_gauges()
        with self._lock:
            return {r: sorted(self._resident.get(r, ()))
                    for r in rids}

    def assign_new(self, rid: str) -> List[str]:
        """Assign cold models to a freshly spawned replica (autoscale /
        respawn path) up to its capacity — same ``place.assign``
        semantics as :meth:`plan`."""
        with self._lock:
            self._resident.setdefault(rid, set())
            warm = set().union(*self._resident.values()) \
                if self._resident else set()
        cold = sorted(m for m in self.models
                      if m not in warm and m not in self.refused)
        out: List[str] = []
        for m in cold:
            with self._lock:
                if not self._fits(self._resident[rid], m):
                    continue
            try:
                faults.inject("place.assign", key=m)
            except Exception as e:
                self.fault_log.add(FaultReport(
                    site="place.assign", kind="place_assign_failed",
                    detail={"fleet": self.name, "model": m,
                            "replica": rid,
                            "error": f"{type(e).__name__}: {e}"[:200]}))
                continue
            with self._lock:
                self._resident[rid].add(m)
            out.append(m)
            _blackbox.record("place.assign", fleet=self.name,
                             model=m, replica=rid)
        self._set_gauges()
        return out

    # -- residency / LRU -----------------------------------------------------
    def residents(self, rid: str) -> List[str]:
        with self._lock:
            return sorted(self._resident.get(rid, ()))

    def holders(self, model: str) -> List[str]:
        with self._lock:
            return sorted(r for r, ms in self._resident.items()
                          if model in ms)

    def is_resident(self, rid: str, model: str) -> bool:
        with self._lock:
            return model in self._resident.get(rid, ())

    def note_resident(self, rid: str, model: str) -> None:
        """Record a warm copy placed outside the planner (e.g. the
        front door seeding a fresh replica with the default model)."""
        with self._lock:
            self._resident.setdefault(rid, set()).add(model)
        self._set_gauges()

    def touch(self, model: str) -> None:
        """Bump the model's logical LRU sequence (one per routed
        request)."""
        with self._lock:
            if model in self._last_used:
                self._last_used[model] = self._next_seq()

    def paging(self, rid: str, model: Optional[str] = None) -> bool:
        """True when ``rid`` has an in-flight page-in (for ``model``,
        or any model when None) — the router steers traffic around a
        replica that is busy deserializing."""
        with self._lock:
            if model is not None:
                return (rid, model) in self._inflight
            return any(r == rid for r, _ in self._inflight)

    def _protected(self, model: str) -> bool:
        if not self.config.protect_slo or self.protect is None:
            return False
        try:
            return bool(self.protect(model))
        except Exception:  # pragma: no cover - defensive
            return False

    def victim(self, rid: str,
               exclude: Optional[Set[str]] = None) -> Optional[str]:
        """Deterministic LRU victim on ``rid``: smallest ``(last_used,
        name)`` among residents, skipping ``exclude``, models mid-page-in
        (their runtime is still materializing — evicting would orphan
        the load), and SLO-protected models."""
        with self._lock:
            cands = [m for m in self._resident.get(rid, ())
                     if m not in (exclude or ())
                     and (rid, m) not in self._inflight]
        cands = [m for m in cands if not self._protected(m)]
        if not cands:
            return None
        with self._lock:
            return min(cands, key=lambda m: (self._last_used.get(m, 0), m))

    # -- eviction ------------------------------------------------------------
    def evict(self, rid: str, model: str,
              unload: Callable[[str], None]) -> None:
        """Evict ``model``'s runtime from ``rid`` (store entry kept — a
        later page-in deserializes, it does not compile). Refused typed
        when the model is itself mid-page-in on that replica. Chaos:
        ``place.evict`` — a raise skips the eviction (capacity is
        advisory), typed ``place_evict_failed``."""
        with self._lock:
            if (rid, model) in self._inflight:
                raise PlacementRefusedError(
                    f"eviction refused: model '{model}' is mid-page-in "
                    f"on replica {rid}")
        faults.inject("place.evict", key=model)
        unload(model)
        with self._lock:
            self._resident.get(rid, set()).discard(model)
            self._evictions += 1
        self._count("tg_place_evictions_total", model=model)
        self.fault_log.add(FaultReport(
            site="place.evict", kind="placement_evicted",
            detail={"fleet": self.name, "model": model, "replica": rid}))
        _blackbox.record("place.evict", fleet=self.name, model=model,
                         replica=rid)
        self._set_gauges()

    def _make_room(self, rid: str, model: str,
                   unload: Callable[[str], None]) -> None:
        """Evict LRU victims until ``model`` fits on ``rid``. A faulted
        or refused eviction is typed and *skipped* — the predicted
        budget is advisory, so the page-in proceeds over-budget rather
        than failing the request."""
        tried: Set[str] = {model}
        for _ in range(len(self.models) + 1):
            with self._lock:
                resident = set(self._resident.get(rid, ()))
            if self._fits(resident, model):
                return
            v = self.victim(rid, exclude=tried)
            if v is None:
                return  # everything protected/inflight: proceed blind
            tried.add(v)
            try:
                self.evict(rid, v, unload)
            except Exception as e:
                self.fault_log.add(FaultReport(
                    site="place.evict", kind="place_evict_failed",
                    detail={"fleet": self.name, "model": v,
                            "replica": rid,
                            "error": f"{type(e).__name__}: {e}"[:200]}))

    # -- demand paging -------------------------------------------------------
    def page_in(self, rid: str, model: str,
                load: Callable[[str], None],
                unload: Callable[[str], None]) -> bool:
        """Make ``model`` warm on ``rid``; returns True when it is.
        Single-flight: the first caller for a cold ``(rid, model)``
        becomes the leader and loads inline; concurrent callers block on
        the leader's Event (bounded by ``pagein_timeout_s``) — N
        concurrent requests for a cold model trigger ONE deserialize.
        Chaos: ``place.pagein`` fires in the leader before the load — a
        raise fails every waiter typed (``place_pagein_failed``) and the
        front door retries within its failover budget."""
        self.check_admitted(model)
        with self._lock:
            if model in self._resident.get(rid, ()):
                return True
            ev = self._inflight.get((rid, model))
            if ev is None:
                ev = threading.Event()
                self._inflight[(rid, model)] = ev
                leader = True
            else:
                leader = False
        if not leader:
            ev.wait(self.config.pagein_timeout_s)
            return self.is_resident(rid, model)
        t0 = time.monotonic()
        try:
            self._make_room(rid, model, unload)
            faults.inject("place.pagein", key=model)
            load(model)
            # residency must be recorded BEFORE the finally releases
            # waiters — a waiter wakes on the Event and immediately
            # checks is_resident
            ms = (time.monotonic() - t0) * 1000.0
            with self._lock:
                self._resident.setdefault(rid, set()).add(model)
                self._pagein_ms.append(ms)
                self._pageins += 1
                self._last_used[model] = self._next_seq()
        except Exception as e:
            self.fault_log.add(FaultReport(
                site="place.pagein", kind="place_pagein_failed",
                detail={"fleet": self.name, "model": model,
                        "replica": rid,
                        "error": f"{type(e).__name__}: {e}"[:200]}))
            return False
        finally:
            with self._lock:
                self._inflight.pop((rid, model), None)
            ev.set()
        self._count("tg_place_pageins_total", model=model)
        self.metrics.histogram(
            "tg_place_pagein_seconds",
            "cold-model demand page-in latency (deserialize via the AOT "
            "program store, not a compile)", fleet=self.name,
            model=model).observe(ms / 1000.0)
        _obs_metrics.observe("tg_place_pagein_seconds", ms / 1000.0,
                             fleet=self.name, model=model)
        self.fault_log.add(FaultReport(
            site="place.pagein", kind="placement_paged_in",
            detail={"fleet": self.name, "model": model, "replica": rid,
                    "ms": round(ms, 3)}))
        _blackbox.record("place.pagein", fleet=self.name, model=model,
                         replica=rid, ms=round(ms, 3))
        self._set_gauges()
        return True

    # -- replica lifecycle ---------------------------------------------------
    def drop_replica(self, rid: str) -> List[str]:
        """A replica died/retired: forget its residents and any page-in
        in flight there (waiters are released — they re-route). Returns
        the models whose ONLY warm copy was on it (now cold fleet-wide;
        they page in on a survivor on next demand)."""
        with self._lock:
            gone = self._resident.pop(rid, set())
            for key in [k for k in self._inflight if k[0] == rid]:
                self._inflight.pop(key).set()
            still_warm = set().union(*self._resident.values()) \
                if self._resident else set()
        orphaned = sorted(gone - still_warm)
        if orphaned:
            _blackbox.record("place.orphaned", fleet=self.name,
                             replica=rid, models=orphaned)
        self._set_gauges()
        return orphaned

    # -- introspection -------------------------------------------------------
    def pagein_p99_ms(self) -> Optional[float]:
        with self._lock:
            if not self._pagein_ms:
                return None
            xs = sorted(self._pagein_ms)
        return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

    def inflight(self) -> List[Tuple[str, str]]:
        with self._lock:
            return sorted(self._inflight)

    def _set_gauges(self) -> None:
        with self._lock:
            counts = {r: len(ms) for r, ms in self._resident.items()}
        for rid, n in counts.items():
            self.metrics.gauge(
                "tg_place_resident",
                "warm models resident per replica", fleet=self.name,
                replica=rid).set(float(n))
            _obs_metrics.set_gauge("tg_place_resident", float(n),
                                   fleet=self.name, replica=rid)

    def snapshot(self) -> Dict[str, Any]:
        """Post-mortem / doctor payload: per-replica resident sets,
        cold set, refusals, blind admits, eviction/page-in counters and
        page-in p99 (the bundle's ``placement`` section, schema v5)."""
        with self._lock:
            resident = {r: sorted(ms)
                        for r, ms in sorted(self._resident.items())}
            warm = set().union(*self._resident.values()) \
                if self._resident else set()
            inflight = sorted(f"{r}:{m}" for r, m in self._inflight)
            evictions, pageins = self._evictions, self._pageins
        return {
            "fleet": self.name,
            "models": sorted(self.models),
            "resident": resident,
            "cold": sorted(m for m in self.models
                           if m not in warm and m not in self.refused),
            "refused": sorted(self.refused),
            "blindAdmits": sorted(self.blind),
            "inflightPageIns": inflight,
            "evictions": evictions,
            "pageIns": pageins,
            "pageInP99Ms": self.pagein_p99_ms(),
            "predictedBytes": {m: self.bytes.get(m)
                               for m in sorted(self.models)},
            "config": {"maxWarm": self.config.max_warm,
                       "deviceBudget": self.config.device_budget or None},
        }

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for ev in self._inflight.values():
                ev.set()
            self._inflight.clear()
        with _LIVE_LOCK:
            try:
                _LIVE.remove(self)
            except ValueError:
                pass

    def __enter__(self) -> "Placer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
