"""Drift-aware self-healing serving (docs/serving.md "Drift monitoring").

The RawFeatureFilter already knows how to compare a training distribution
against a scoring distribution — fill rates + JS divergence over streaming
histogram sketches (filters/distribution.py, reference RawFeatureFilter).
But that knowledge is train-time-only: a model served under the runtime
can silently rot as traffic drifts. This module moves the same math
online (ROADMAP item 5; Breck et al., "Data Validation for Machine
Learning"; TFX-style continuous training loops):

* **save time** — :func:`manifest_drift_entry` persists a per-feature
  training baseline under a ``drift`` section in the model's
  ``MANIFEST.json``: one streaming-histogram sketch state + fill rate per
  numeric raw feature (the streaming ``HistogramFold`` monoid state —
  the same fold the out-of-core trainer runs), hash-bin counts per
  text-ish feature.
* **serve time** — a :class:`DriftMonitor`, owned by each registry entry,
  folds every scored micro-batch into the same fold on the batcher
  thread (off the request hot path, post-quarantine), and on a row
  cadence compares against the baseline through the ONE shared
  implementation (``filters.distribution.compare_distributions``):
  ``tg_drift_js_divergence{feature}`` / ``tg_drift_fill_delta{feature}``
  gauges, span events past ``TG_DRIFT_WARN``, and a per-model verdict
  ``ok → drifting → degraded`` surfaced in ``registry.health()``.
* **self-healing** — when the verdict crosses ``TG_DRIFT_REFIT`` the
  registry (when a refit hook is configured) launches a background refit
  (``OpWorkflow.drift_refit_hook`` wraps ``train(resume=...)`` + save),
  then hot-swaps through the existing manifest-verified load + warm
  pre-trace path. Requests keep flowing on the old model throughout; a
  failed refit degrades gracefully (FaultLog kind ``drift_refit_failed``,
  breaker untouched).

Crash isolation: a drift-path exception can NEVER fail a scoring request
— the runtime fences every monitor call (FaultLog kinds
``drift_fold_failed`` / ``drift_verdict_failed``), and the deterministic
chaos sites ``drift.fold`` / ``drift.verdict`` / ``drift.refit``
(robustness/faults.py) make each failure path testable.

Env knobs (docs/serving.md "Drift monitoring & self-healing"):

==========================  =================================================
``TG_DRIFT``                ``0`` disables monitor auto-attach at
                            ``registry.load`` (default on when the manifest
                            carries a baseline)
``TG_DRIFT_BINS``           histogram bins per numeric feature (64)
``TG_DRIFT_TEXT_BINS``      hash bins per text feature (64)
``TG_DRIFT_WARN``           per-feature JS/fill-delta warn threshold (0.10)
                            — past it the feature counts as *drifting*
``TG_DRIFT_REFIT``          degradation threshold (0.25) — past it the model
                            verdict is *degraded* and the refit hook fires
``TG_DRIFT_EVERY_ROWS``     verdict cadence in folded rows (512)
``TG_DRIFT_MIN_ROWS``       rows folded before the first verdict (256 —
                            below ~256 rows a 64-bin sketch's sampling
                            noise alone reads JS ≈ 0.1, the warn line)
``TG_DRIFT_HISTORY``        verdict history ring size (64)
==========================  =================================================
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..filters.distribution import (
    FeatureDistribution, Summary, _hash_bin, column_distributions,
    compare_distributions, fold_distribution,
)
from ..observability import blackbox as _blackbox
from ..observability import metrics as _obs_metrics
from ..observability import postmortem as _postmortem
from ..observability.trace import add_event as _obs_event
from ..robustness import faults
from ..robustness.policy import FaultLog, FaultReport
from ..streaming.folds import HistogramFold
from ..utils.streaming_histogram import StreamingHistogram

#: per-model drift verdicts, in degradation order
OK, DRIFTING, DEGRADED = "ok", "drifting", "degraded"
#: verdict → ``tg_drift_verdict`` gauge value (0 is healthy, dashboards
#: alert on non-zero — same convention as ``tg_breaker_state``)
VERDICT_GAUGE = {OK: 0.0, DRIFTING: 1.0, DEGRADED: 2.0}
_ORDER = {OK: 0, DRIFTING: 1, DEGRADED: 2}

_FALSY = ("", "0", "false", "False", "no")


def drift_enabled() -> bool:
    """The ``registry.load`` auto-attach gate (``TG_DRIFT``; default on)."""
    return os.environ.get("TG_DRIFT", "1") not in _FALSY


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass
class DriftConfig:
    """Monitor knobs; every field has a ``TG_DRIFT_*`` environment
    default (module docstring table)."""
    bins: int = 64
    text_bins: int = 64
    warn: float = 0.10
    refit: float = 0.25
    every_rows: int = 512
    min_rows: int = 256
    history: int = 64

    @classmethod
    def from_env(cls) -> "DriftConfig":
        return cls(
            bins=_env_int("TG_DRIFT_BINS", 64),
            text_bins=_env_int("TG_DRIFT_TEXT_BINS", 64),
            warn=_env_float("TG_DRIFT_WARN", 0.10),
            refit=_env_float("TG_DRIFT_REFIT", 0.25),
            every_rows=_env_int("TG_DRIFT_EVERY_ROWS", 512),
            min_rows=_env_int("TG_DRIFT_MIN_ROWS", 256),
            history=_env_int("TG_DRIFT_HISTORY", 64),
        )


# ---------------------------------------------------------------------------
# Training baseline (save-time)
# ---------------------------------------------------------------------------

class DriftBaseline:
    """Per-feature training distribution snapshot.

    ``features`` maps the feature's full name to a JSON-able entry::

        numeric: {"kind": "numeric", "key": None, "count", "nulls",
                  "sketch": {"maxBins", "centers", "masses",
                             "total", "min", "max"}}
        text:    {"kind": "text", "key": None, "count", "nulls",
                  "counts": [hash-bin counts]}

    Map sub-features round-trip (``key`` set) but are not folded online —
    the monitor compares scalar features only (documented host boundary).
    """

    def __init__(self, features: Dict[str, Dict[str, Any]], rows: int,
                 bins: int, text_bins: int):
        self.features = features
        self.rows = int(rows)
        self.bins = int(bins)
        self.text_bins = int(text_bins)

    # -- construction --------------------------------------------------------
    @classmethod
    def from_model(cls, model, bins: Optional[int] = None,
                   text_bins: Optional[int] = None) -> "DriftBaseline":
        """Sketch the model's training table (the streamed-train probe for
        out-of-core models) over its non-response raw features."""
        table = getattr(model, "train_table", None)
        if table is None:
            raise ValueError(
                "model has no train_table to build a drift baseline from "
                "(models loaded from disk carry their baseline in "
                "MANIFEST.json instead)")
        cfg = DriftConfig.from_env()
        bins = bins or cfg.bins
        text_bins = text_bins or cfg.text_bins
        features: Dict[str, Dict[str, Any]] = {}
        for f in model.raw_features:
            if f.is_response or f.name not in table.column_names:
                continue
            for d in column_distributions(f.name, table[f.name],
                                          bins, text_bins):
                features[d.full_name] = _dist_entry(d)
        return cls(features, table.num_rows, bins, text_bins)

    # -- (de)serialization (the MANIFEST.json ``drift`` section) -------------
    def to_json(self) -> Dict[str, Any]:
        return {"rows": self.rows, "bins": self.bins,
                "textBins": self.text_bins, "features": self.features}

    @classmethod
    def from_json(cls, doc: Dict[str, Any]) -> "DriftBaseline":
        return cls(dict(doc.get("features", {})), doc.get("rows", 0),
                   doc.get("bins", 64), doc.get("textBins", 64))

    # -- distribution views --------------------------------------------------
    def distribution(self, name: str) -> Optional[FeatureDistribution]:
        """The baseline side of a train-vs-score comparison, rebuilt as a
        :class:`FeatureDistribution` (sketch-backed for numeric features),
        so the shared ``compare_distributions`` math applies unchanged."""
        e = self.features.get(name)
        if e is None:
            return None
        if e["kind"] == "numeric":
            sk = e["sketch"]
            sketch = StreamingHistogram.from_state({
                "max_bins": int(sk["maxBins"]),
                "centers": np.asarray(sk["centers"], np.float64),
                "masses": np.asarray(sk["masses"], np.float64),
                "total": float(sk["total"]),
                "min": float(sk["min"]), "max": float(sk["max"])})
            filled = float(e["count"]) - float(e["nulls"])
            return FeatureDistribution(
                name=name, key=e.get("key"), count=float(e["count"]),
                nulls=float(e["nulls"]),
                summary=Summary(sketch.min if filled else np.inf,
                                sketch.max if filled else -np.inf,
                                0.0, filled),
                is_numeric=True, sketch=sketch)
        return FeatureDistribution(
            name=name, key=e.get("key"), count=float(e["count"]),
            nulls=float(e["nulls"]),
            distribution=np.asarray(e["counts"], np.float64),
            is_numeric=False)

    def monitored(self) -> Dict[str, str]:
        """{feature name: kind} for the scalar (non-map-key) features the
        online monitor folds."""
        return {n: e["kind"] for n, e in sorted(self.features.items())
                if e.get("key") is None}


def _dist_entry(d: FeatureDistribution) -> Dict[str, Any]:
    if d.is_numeric and d.sketch is not None:
        st = d.sketch.to_state()
        return {"kind": "numeric", "key": d.key, "count": d.count,
                "nulls": d.nulls,
                "sketch": {"maxBins": int(st["max_bins"]),
                           "centers": np.asarray(st["centers"]).tolist(),
                           "masses": np.asarray(st["masses"]).tolist(),
                           "total": float(st["total"]),
                           "min": float(st["min"]),
                           "max": float(st["max"])}}
    return {"kind": "text", "key": d.key, "count": d.count,
            "nulls": d.nulls,
            "counts": np.asarray(d.distribution).tolist()}


def manifest_drift_entry(model) -> Dict[str, Any]:
    """The ``drift`` section written into the model's ``MANIFEST.json`` at
    save time (persistence.save_model; never fails a save — the caller
    try/excepts exactly like the ``serving`` warm-start entry)."""
    return DriftBaseline.from_model(model).to_json()


# ---------------------------------------------------------------------------
# Online monitor (serve-time)
# ---------------------------------------------------------------------------

class DriftMonitor:
    """Folds scored request rows into per-feature streaming sketches and
    periodically compares them against the training baseline.

    Called exclusively from the runtime's batcher thread (``observe``);
    ``snapshot``/``report`` may run from any thread (one lock). The
    runtime fences every ``observe`` call — an exception here is recorded
    (``drift_fold_failed``) and the batch's requests are entirely
    unaffected; see ``ServingRuntime._drift_observe``.
    """

    def __init__(self, baseline: DriftBaseline,
                 config: Optional[DriftConfig] = None,
                 model_name: str = "model",
                 on_degraded: Optional[Callable[[Dict[str, Any]], None]]
                 = None):
        self.baseline = baseline
        self.config = config or DriftConfig.from_env()
        self.model_name = model_name
        #: fired once per ok/drifting → degraded transition (the registry
        #: wires its refit launcher here)
        self.on_degraded = on_degraded
        self._lock = threading.Lock()
        kinds = baseline.monitored()
        self._numeric = [n for n, k in kinds.items() if k == "numeric"]
        self._text = [n for n, k in kinds.items() if k == "text"]
        self._fold = HistogramFold(len(self._numeric),
                                   max_bins=self.config.bins)
        self._state = self._fold.zero()
        #: raw (values, mask) blocks awaiting a sketch fold — the hot
        #: path only gathers request values into numpy blocks (cheap);
        #: the per-column sketch update + compaction amortizes over
        #: ``every_rows``-sized batches instead of running per flush
        #: (the ≤5% serve-overhead budget, docs/benchmarks.md)
        self._pending: List[Any] = []
        self._pending_rows = 0
        self._text_counts = {
            n: np.zeros(len(baseline.features[n]["counts"]), np.float64)
            for n in self._text}
        self._text_nulls = {n: 0 for n in self._text}
        self._text_rows = 0
        self._rows = 0
        self._rows_at_verdict = 0
        self._verdict = OK
        self._features: Dict[str, Dict[str, float]] = {}
        self._history: deque = deque(maxlen=self.config.history)
        self._verdict_errors = 0
        self.fold_errors = 0      # incremented by the runtime's fence
        #: bound by the owning runtime (serve-local instruments + log)
        self._metrics: Optional[_obs_metrics.MetricsRegistry] = None
        self._fault_log: Optional[FaultLog] = None

    # -- runtime wiring ------------------------------------------------------
    def bind(self, model_name: str, metrics: _obs_metrics.MetricsRegistry,
             fault_log: FaultLog) -> None:
        self.model_name = model_name
        self._metrics = metrics
        self._fault_log = fault_log

    # -- folding (batcher thread) --------------------------------------------
    def observe(self, rows: Sequence[Dict[str, Any]]) -> None:
        """Fold one scored micro-batch (post-quarantine rows only — the
        runtime filters). Raises propagate to the runtime's fence, which
        types them ``drift_fold_failed``; a verdict-pass failure is
        contained here and typed ``drift_verdict_failed`` (the fold state
        stays intact either way)."""
        if not rows:
            return
        # deterministic chaos entry: a fault folding the batch
        faults.inject("drift.fold", key=self.model_name)
        with self._lock:
            self._fold_rows(rows)
            due = (self._rows - self._rows_at_verdict
                   >= self.config.every_rows
                   and self._rows >= self.config.min_rows)
        if due:
            try:
                self.run_verdict()
            except Exception as e:
                self._verdict_errors += 1
                self._record_fault("drift.verdict", "drift_verdict_failed", e)

    def _fold_rows(self, rows: Sequence[Dict[str, Any]]) -> None:
        n = len(rows)
        self._rows += n
        if self._numeric:
            d = len(self._numeric)
            V = np.zeros((n, d), np.float64)
            M = np.zeros((n, d), bool)
            for j, name in enumerate(self._numeric):
                vals = [r.get(name) if isinstance(r, dict) else None
                        for r in rows]
                try:
                    # homogeneous numeric fast path (one numpy sweep)
                    col = np.asarray(vals, np.float64)
                    V[:, j] = np.nan_to_num(col)
                    M[:, j] = np.isfinite(col)
                except (TypeError, ValueError):
                    for i, v in enumerate(vals):
                        if v is None or isinstance(v, str):
                            continue
                        try:
                            fv = float(v)
                        except (TypeError, ValueError):
                            continue
                        if np.isfinite(fv):
                            V[i, j] = fv
                            M[i, j] = True
            self._pending.append((V, M))
            self._pending_rows += n
            if self._pending_rows >= self.config.every_rows:
                self._flush_pending()
        for name in self._text:
            counts = self._text_counts[name]
            bins = counts.size
            for r in rows:
                v = r.get(name) if isinstance(r, dict) else None
                if v is None:
                    self._text_nulls[name] += 1
                elif isinstance(v, (list, tuple, set)):
                    for t in v:
                        counts[_hash_bin(str(t), bins)] += 1.0
                else:
                    counts[_hash_bin(str(v), bins)] += 1.0
        self._text_rows += n

    def _flush_pending(self) -> None:
        # lock held by caller
        if not self._pending:
            return
        blocks = self._pending
        self._pending = []
        self._pending_rows = 0
        V = blocks[0][0] if len(blocks) == 1 else np.vstack(
            [b[0] for b in blocks])
        M = blocks[0][1] if len(blocks) == 1 else np.vstack(
            [b[1] for b in blocks])
        self._state = self._fold.accumulate(self._state, V, M)

    # -- verdicts ------------------------------------------------------------
    def run_verdict(self) -> str:
        """Compare the folded scoring distributions against the baseline
        and update the per-model verdict (normally cadence-driven from
        ``observe``; public so tests and the CLI can force a pass)."""
        faults.inject("drift.verdict", key=self.model_name)
        cfg = self.config
        with self._lock:
            self._flush_pending()
            self._rows_at_verdict = self._rows
            per_feature: Dict[str, Dict[str, float]] = {}
            worst = OK
            worst_feature = None
            for j, name in enumerate(self._numeric):
                if not self._rows:
                    continue
                score = fold_distribution(self._fold, self._state, j, name)
                per_feature[name] = self._compare(name, score)
            for name in self._text:
                if not self._text_rows:
                    continue
                score = FeatureDistribution(
                    name=name, count=float(self._text_rows),
                    nulls=float(self._text_nulls[name]),
                    distribution=self._text_counts[name].copy(),
                    is_numeric=False)
                per_feature[name] = self._compare(name, score)
            for name, m in per_feature.items():
                level = max(m["jsDivergence"], m["fillDelta"])
                fv = (DEGRADED if level > cfg.refit
                      else DRIFTING if level > cfg.warn else OK)
                m["verdict"] = fv
                if _ORDER[fv] > _ORDER[worst]:
                    worst, worst_feature = fv, name
                elif worst_feature is None:
                    worst_feature = name
            prev = self._verdict
            self._verdict = worst
            self._features = per_feature
            self._history.append({
                "rows": self._rows, "at": time.time(), "verdict": worst,
                "worstFeature": worst_feature,
                "worst": (max(per_feature[worst_feature]["jsDivergence"],
                              per_feature[worst_feature]["fillDelta"])
                          if worst_feature else 0.0)})
        # instruments outside the lock (snapshot() takes it)
        for name, m in per_feature.items():
            self._gauge("tg_drift_js_divergence", m["jsDivergence"], name,
                        help="per-feature JS divergence of the live "
                        "scoring distribution vs the training baseline "
                        "(docs/serving.md)")
            self._gauge("tg_drift_fill_delta", m["fillDelta"], name,
                        help="per-feature |train fill − score fill| "
                        "(docs/serving.md)")
            if m["verdict"] != OK:
                _obs_event("drift.warn", model=self.model_name,
                           feature=name, js=m["jsDivergence"],
                           fillDelta=m["fillDelta"], verdict=m["verdict"])
        self._gauge("tg_drift_verdict", VERDICT_GAUGE[worst], None,
                    help="per-model drift verdict (0=ok, 1=drifting, "
                    "2=degraded; docs/serving.md)")
        if worst != prev:
            _obs_event("drift.verdict", model=self.model_name,
                       verdict=worst, previous=prev)
            # verdict transitions are flight-recorder events (always on):
            # the drift story must be readable next to the serve events
            # it interleaves with (observability/blackbox.py)
            _blackbox.record("drift.verdict", model=self.model_name,
                             verdict=worst, previous=prev,
                             worstFeature=worst_feature,
                             rows=self._rows)
        if worst == DEGRADED and prev != DEGRADED:
            # trigger event: the model's data went bad — freeze the
            # recorder context + the per-feature comparison while the
            # offending traffic is still in the ring (rate-limited;
            # observability/postmortem.py)
            _postmortem.trigger(
                "drift_degraded", fault_log=self._fault_log,
                metrics=self._metrics,
                detail={"model": self.model_name,
                        "worstFeature": worst_feature, "rows": self._rows},
                state={"drift": {"verdict": worst, "previous": prev,
                                 "features": {n: dict(m) for n, m
                                              in per_feature.items()}}})
            if self.on_degraded is not None:
                try:
                    self.on_degraded(self.report())
                except Exception as e:
                    self._record_fault("drift.refit",
                                       "drift_refit_failed", e)
        return worst

    def _compare(self, name: str, score: FeatureDistribution
                 ) -> Dict[str, float]:
        train = self.baseline.distribution(name)
        if train is None:
            return {"jsDivergence": 0.0, "fillDelta": 0.0,
                    "trainFill": 0.0, "scoreFill": score.fill_fraction()}
        cmp = compare_distributions(train, score, self.baseline.bins)
        return {"jsDivergence": cmp["jsDivergence"],
                "fillDelta": cmp["fillDelta"],
                "trainFill": cmp["trainFill"],
                "scoreFill": cmp["scoreFill"]}

    # -- accounting ----------------------------------------------------------
    def _gauge(self, name: str, v: float, feature: Optional[str],
               help: str = "") -> None:
        labels = {"model": self.model_name}
        if feature is not None:
            labels["feature"] = feature
        if self._metrics is not None:
            self._metrics.gauge(name, help, **labels).set(v)
        _obs_metrics.set_gauge(name, v, help, **labels)

    def _record_fault(self, site: str, kind: str, e: BaseException) -> None:
        report = FaultReport(site=site, kind=kind, detail={
            "model": self.model_name,
            "error": f"{type(e).__name__}: {e}"[:300]})
        if self._fault_log is not None:
            self._fault_log.add(report)
        else:
            FaultLog.record(report)

    # -- introspection -------------------------------------------------------
    def verdict(self) -> str:
        with self._lock:
            return self._verdict

    def snapshot(self) -> Dict[str, Any]:
        """The ``drift`` section of ``runtime.summary()`` /
        ``registry.health()``."""
        with self._lock:
            return {
                "verdict": self._verdict,
                "rows": self._rows,
                "rowsAtVerdict": self._rows_at_verdict,
                "features": {n: dict(m) for n, m in self._features.items()},
                "foldErrors": self.fold_errors,
                "verdictErrors": self._verdict_errors,
            }

    def report(self) -> Dict[str, Any]:
        """Snapshot + verdict history + baseline shape — the refit hook's
        input and the ``op serve`` bundle's drift report."""
        out = self.snapshot()
        with self._lock:
            out["history"] = list(self._history)
        out["baseline"] = {"rows": self.baseline.rows,
                           "bins": self.baseline.bins,
                           "features": sorted(self.baseline.features)}
        out["model"] = self.model_name
        return out


# ---------------------------------------------------------------------------
# Background refit bookkeeping (conftest _no_drift_leak asserts on this)
# ---------------------------------------------------------------------------

_REFIT_LOCK = threading.Lock()
_LIVE_REFITS: List[threading.Thread] = []


def track_refit(thread: threading.Thread) -> None:
    with _REFIT_LOCK:
        _LIVE_REFITS.append(thread)


def untrack_refit(thread: threading.Thread) -> None:
    with _REFIT_LOCK:
        if thread in _LIVE_REFITS:
            _LIVE_REFITS.remove(thread)


def live_refits() -> List[threading.Thread]:
    """Refit threads still running — the conftest no-leak fixture asserts
    this is empty around every test."""
    with _REFIT_LOCK:
        _LIVE_REFITS[:] = [t for t in _LIVE_REFITS if t.is_alive()]
        return list(_LIVE_REFITS)
