"""Per-model circuit breaker for the serving runtime.

The reference rides Spark's blacklisting + task retry to keep a failing
executor from taking the job down; the TPU serving tier has one failure
domain that matters instead — the compiled micro-batch dispatch (a wedged
XLA program, a poisoned plan, a device that stopped answering). The
breaker isolates it with the classic three-state machine:

* **closed** — dispatches flow to the device path; consecutive failures
  are counted (any success resets the count).
* **open** — after ``failure_threshold`` consecutive dispatch failures the
  breaker opens: the runtime stops offering batches to the device path and
  serves them through the eager per-row scorer instead (bit-equal results,
  no device time wasted on a failing program). Requests never fail because
  the breaker is open — they degrade.
* **half-open** — after ``reset_after`` seconds the next batch is let
  through as a *probe*. Success closes the breaker; failure re-opens it
  and restarts the clock.

Transitions call ``on_transition(state)`` (the runtime wires a
``tg_breaker_state`` gauge + span event there) and are all O(1) under one
lock. The clock is injectable so the open→half-open edge is
deterministically testable without sleeping.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ..observability import blackbox as _blackbox

#: state → ``tg_breaker_state`` gauge value (0 is the healthy steady state
#: so dashboards can alert on anything non-zero)
CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
BREAKER_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class CircuitBreaker:
    """Consecutive-failure breaker with a timed half-open probe."""

    def __init__(self, name: str = "model", failure_threshold: int = 3,
                 reset_after: float = 0.5,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable[[str], None]] = None):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.reset_after = float(reset_after)
        self.clock = clock
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._opens = 0
        self._probes = 0
        self._last_error: Optional[str] = None

    # -- state ---------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, state: str) -> None:
        # lock held by caller
        if state == self._state:
            return
        prev, self._state = self._state, state
        # every breaker transition lands in the always-on flight recorder
        # (observability/blackbox.py) — the open→half_open→close dance is
        # the heart of any serving post-mortem. NOTE: the breaker lock is
        # held; on_transition callbacks must not call back into snapshot().
        _blackbox.record("breaker", name=self.name, state=state,
                         previous=prev,
                         consecutiveFailures=self._consecutive_failures,
                         error=self._last_error)
        cb = self.on_transition
        if cb is not None:
            cb(state)

    # -- runtime protocol ----------------------------------------------------
    def allow_device(self) -> bool:
        """May the next batch go to the compiled device path?  ``closed`` —
        yes; ``open`` — no until ``reset_after`` has elapsed, at which point
        this call itself moves to ``half_open`` and grants ONE probe;
        ``half_open`` — no (a probe is already in flight; extra batches stay
        on the degraded path until it resolves)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if (self.clock() - (self._opened_at or 0.0)
                        >= self.reset_after):
                    self._probes += 1
                    self._transition(HALF_OPEN)
                    return True
                return False
            return False  # half-open: probe outstanding

    def record_success(self) -> None:
        """A device dispatch completed: close (from any state) and reset the
        failure count."""
        with self._lock:
            self._consecutive_failures = 0
            self._last_error = None
            self._transition(CLOSED)

    def record_failure(self, error: Optional[BaseException] = None) -> None:
        """A device dispatch raised. A failed half-open probe re-opens
        immediately; in closed state the breaker opens once
        ``failure_threshold`` consecutive failures accumulate."""
        with self._lock:
            self._consecutive_failures += 1
            if error is not None:
                self._last_error = f"{type(error).__name__}: {error}"[:300]
            if self._state == HALF_OPEN or (
                    self._state == CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._opened_at = self.clock()
                self._opens += 1
                self._transition(OPEN)

    def trip(self, error: Optional[BaseException] = None) -> None:
        """Force the breaker open immediately, bypassing the consecutive-
        failure count — the watchdog's stall response: a batcher that
        stopped beating is wedged *now*, and new batches must route to the
        degraded path instead of queueing behind it. Heals normally
        (timed half-open probe → close on success)."""
        with self._lock:
            if error is not None:
                self._last_error = f"{type(error).__name__}: {error}"[:300]
            if self._state != OPEN:
                self._opened_at = self.clock()
                self._opens += 1
                self._transition(OPEN)

    # -- introspection -------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Health/summary view (docs/serving.md "Breaker semantics")."""
        with self._lock:
            return {
                "state": self._state,
                "consecutiveFailures": self._consecutive_failures,
                "opens": self._opens,
                "probes": self._probes,
                "lastError": self._last_error,
            }
