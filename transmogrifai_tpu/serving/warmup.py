"""Warm-start: zero-retrace first requests (first slice of ROADMAP item 1).

A fresh process pays the transform-plan build + XLA trace on its first
micro-batch — tens of ms to seconds of p99 on request one. The cure has
two halves:

* **save time** — :func:`manifest_serving_entry` records the micro-batch
  plan *schema fingerprint* (what ``plan.py`` keys its cache on: per
  external column name / dtype / trailing shape / mask presence) in the
  model's ``MANIFEST.json``. The fingerprint is computed from a synthetic
  all-missing request batch, which is schema-identical to any real batch:
  ``Column.of_values`` derives dtype and mask presence from the *feature
  type*, never the data.
* **load time** — :func:`warm_runtime` drives the runtime's compiled
  scorer once over the same synthetic batch, building the plan and
  compiling the jitted segment programs for the padding bucket every
  flush of up to ``max_batch`` rows lands in — so the first real request
  is served from warm caches. The recorded fingerprint is verified
  against the loaded model's (a mismatch means the plan cache would miss
  — reported in the health snapshot, never fatal). One warm pass covers
  BOTH serve paths: the serial monolithic scorer and the pipelined
  gather/dispatch stages (``local/scoring.ServeStages``) build
  byte-identical tables, so they key the same fingerprinted plan-cache
  entry — the pipelined first flush is warm too, no second trace.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from ..local.scoring import serve_table_builder

#: synthetic rows used for the warm trace; any value <= max_batch compiles
#: the same (256-minimum) padding bucket, so small is fine
WARM_ROWS_ENV = "TG_SERVE_WARM_ROWS"
DEFAULT_WARM_ROWS = 8


def _warm_rows(rows: Optional[int] = None) -> int:
    if rows is not None:
        return max(1, int(rows))
    try:
        return max(1, int(os.environ.get(WARM_ROWS_ENV, "")
                          or DEFAULT_WARM_ROWS))
    except ValueError:
        return DEFAULT_WARM_ROWS


def serve_plan_fingerprint(model, rows: int = 1) -> List[List[Any]]:
    """The JSON-ready plan schema fingerprint of the model's serve path:
    what ``plan.get_plan`` will key on for any request batch (row count is
    not part of it — padding buckets absorb that)."""
    from .. import plan as _plan
    table = serve_table_builder(model)([{} for _ in range(max(1, rows))])
    return _plan.schema_fingerprint(model.stages, table)


def manifest_serving_entry(model) -> Dict[str, Any]:
    """The ``serving`` section written into the model's ``MANIFEST.json``
    at save time (persistence.save_model)."""
    return {
        "planFingerprint": serve_plan_fingerprint(model),
        "warmRows": _warm_rows(),
        "resultFeatures": [f.name for f in model.result_features],
    }


def warm_runtime(runtime, entry: Optional[Dict[str, Any]] = None,
                 rows: Optional[int] = None,
                 store_path: Optional[str] = None) -> Dict[str, Any]:
    """Pre-warm the runtime's serve programs; returns the warm report
    that lands in ``runtime.warm_info`` / the registry health snapshot:
    ``{"rows", "plansWarmed", "ok", "fingerprintMatch", "error",
    "compiles", "compileCauses", "aotHits", "aotMisses", "aotExports"}``.

    With an AOT program-store session open over the model dir
    (``registry.load`` opens it before calling here), the warm pass
    *deserializes* the stored programs instead of tracing — zero
    compile-ledger builds, ``aotHits`` > 0. When ``store_path`` is given
    and the store missed (first replica, pre-AOT model dir), the traced
    warm dispatches are captured back into ``<store_path>/programs/`` +
    the manifest ``programs`` section so the NEXT load deserializes —
    a fleet's N replicas compile once total (docs/serving.md "AOT cold
    start & the program store").

    Never raises — a model whose raw extracts cannot handle an
    all-missing probe row simply serves its first request cold
    (reported)."""
    import contextlib

    from .. import plan as _plan
    from ..observability import ledger as _ledger
    from ..programstore import store as _pstore
    n = _warm_rows(rows if rows is not None
                   else (entry or {}).get("warmRows"))
    before = _plan.cache_stats()["entries"]
    led = _ledger.ledger()
    mark = led.mark()
    aot_before = _pstore.stats()
    info: Dict[str, Any] = {"rows": n, "plansWarmed": 0, "ok": True,
                            "fingerprintMatch": None, "error": None}
    cap = (_pstore.capture(store_path) if store_path is not None
           else contextlib.nullcontext())
    try:
        # the warm pass runs under the runtime's fault log so a store
        # fallback (typed `aot_fallback`) lands where health/campaign
        # oracles read it, and under the capture scope so traced
        # programs populate the store
        with runtime.fault_log.activate(), cap:
            runtime.warm(n)
            if store_path is not None:
                mid = _pstore.stats()
                if mid["hitsTotal"] - aot_before["hitsTotal"] == 0:
                    # the store did not serve this model (first replica,
                    # pre-AOT dir): populate it so the NEXT load
                    # deserializes. Dispatch-time offers cover freshly
                    # traced segments; a plan the process had already
                    # traced needs this explicit probe-aval export.
                    p = _pstore.serve_plan_for(runtime.model, n)
                    if p is not None:
                        _plan.export_plan_programs(p)
    except Exception as e:
        info["ok"] = False
        info["error"] = f"{type(e).__name__}: {e}"[:300]
    info["plansWarmed"] = max(0, _plan.cache_stats()["entries"] - before)
    aot_after = _pstore.stats()
    info["aotHits"] = aot_after["hitsTotal"] - aot_before["hitsTotal"]
    info["aotMisses"] = (aot_after["missesTotal"]
                         - aot_before["missesTotal"])
    info["aotExports"] = aot_after["exports"] - aot_before["exports"]
    # compile-ledger accounting: the builds warmup pre-paid (subsystem
    # "serve") — what the warm-path zero-retrace gate subtracts before
    # asserting the first real request compiles NOTHING
    warm_builds = led.since(mark)
    causes: Dict[str, int] = {}
    for rec in warm_builds:
        causes[rec.cause] = causes.get(rec.cause, 0) + 1
    info["compiles"] = led.mark() - mark
    info["compileCauses"] = causes
    recorded = (entry or {}).get("planFingerprint")
    if recorded is not None:
        try:
            actual = serve_plan_fingerprint(runtime.model)
            info["fingerprintMatch"] = (
                _normalize(actual) == _normalize(recorded))
        except Exception as e:
            info["fingerprintMatch"] = False
            info["error"] = info["error"] or f"{type(e).__name__}: {e}"[:300]
    runtime.warm_info = info
    return info


def _normalize(fp: Any) -> List[List[Any]]:
    # JSON round-trips tuples to lists; compare shape-insensitively
    return [[c[0], c[1], list(c[2]), bool(c[3])] for c in fp]
