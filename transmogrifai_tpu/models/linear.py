"""Linear model families: logistic regression, linear/ridge regression,
linear SVC, naive Bayes.

TPU-native replacements for the reference's SparkML wrappers
(reference: core/.../impl/classification/OpLogisticRegression.scala,
OpLinearSVC.scala, OpNaiveBayes.scala, impl/regression/OpLinearRegression.scala).
Each family fits its whole hyperparameter × fold batch in ONE jitted, vmapped
XLA program: the inner loop is prox-Newton / closed-form solves built from
(n,d)ᵀ(n,d) MXU matmuls, and per-configuration 0/1 row-weight vectors express
CV folds without reshaping data.

Conventions (matching Spark ML so reference grids transfer):
* objective = mean loss + regParam * (α·‖w‖₁ + (1-α)/2·‖w‖₂²), bias unpenalized
* features are standardized internally (Spark standardization=true default);
  coefficients are reported in the original scale.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .api import FittedParams, ModelFamily, register_family

_PREC = jax.lax.Precision.HIGHEST


def _standardize(X: jnp.ndarray, w: jnp.ndarray):
    """Weighted feature standardization; returns (Xs, mean, scale).

    Columns constant within the weighted rows get a huge scale (Xs ≈ 0,
    coefficient pinned at 0) instead of 1/sqrt(noise) — same dead-column
    guard as _BatchStd, or the unscale step amplifies rounding noise 1e6x."""
    cnt = jnp.maximum(w.sum(), 1.0)
    mean = (X * w[:, None]).sum(0) / cnt
    var = ((X - mean) ** 2 * w[:, None]).sum(0) / cnt
    # dead = EXACTLY constant within the weighted rows (weighted range 0) —
    # matches Spark zeroing only zero-variance columns. An informative column
    # whose natural scale is tiny (std 1e-4 → var 1e-8) or whose offset is
    # huge (epoch-millis: var/ex2 ~ 1e-10) must NOT be pinned to 0, so no
    # variance threshold can be used here; the range test is exact
    active = w[:, None] > 0
    hi = jnp.where(active, X, -jnp.inf).max(0)
    lo = jnp.where(active, X, jnp.inf).min(0)
    dead = hi <= lo
    scale = jnp.where(dead, 1e30, jnp.sqrt(jnp.maximum(var, 1e-30)))
    return (X - mean) / scale, mean, scale


def _unscale(coef_s: jnp.ndarray, bias_s: jnp.ndarray, mean: jnp.ndarray,
             scale: jnp.ndarray):
    coef = coef_s / scale
    bias = bias_s - (coef * mean).sum()
    return coef, bias


# ---------------------------------------------------------------------------
# Binary logistic regression — batched prox-Newton-CG
#
# The whole |grid| × |folds| batch is ONE program in which every heavy op is a
# shared (n,d)@(d,B) matmul over the raw feature matrix: per-configuration
# standardization is folded into coefficient algebra (Xs·v computed as
# X·(v/scale) − mean·(v/scale)), so X is read once per matmul instead of being
# re-materialized per configuration, and the Newton direction comes from a
# fixed-length conjugate-gradient solve whose Hessian-vector products are two
# such matmuls (the LIBLINEAR trust-region-Newton structure, batched). This is
# the MXU-shaped replacement for the reference's per-config SparkML fits
# (OpValidator.scala:270-322).
# ---------------------------------------------------------------------------

class _BatchStd:
    """Per-config standardization algebra over shared matmuls.

    Globally standardizes X once (keeps the shared matmuls well-conditioned
    at fast default matmul precision whatever the raw column scales), then
    expresses each config's weighted standardization algebraically:
    Xs·v = Xg·(v/scale) − mean·(v/scale). The per-config standardized space —
    and hence Spark's regularization semantics (standardization=true) — is
    invariant to the global affine map. X is never copied per config."""

    def __init__(self, X, W):
        g_mean = X.mean(axis=0)
        g_scale = jnp.sqrt(jnp.maximum(X.var(axis=0), 1e-12))
        self.g_mean, self.g_scale = g_mean, g_scale
        self.Xg = (X - g_mean) / g_scale
        self.Wt = W.T                                        # (n, B)
        self.cnt = jnp.maximum(W.sum(axis=1), 1.0)           # (B,)
        mean = (self.Wt.T @ self.Xg) / self.cnt[:, None]     # (B, d)
        ex2 = (self.Wt.T @ (self.Xg * self.Xg)) / self.cnt[:, None]
        var_raw = ex2 - mean ** 2
        self.var = jnp.maximum(var_raw, 1e-12)
        # a column that is CONSTANT within a config's weighted rows (e.g. a
        # rare one-hot slot whose nonzero rows all fell in the val fold) has
        # var ≈ rounding noise; 1/sqrt(var) then blows the solve up to NaN.
        # Give dead columns a huge scale instead: Xs ≈ 0, gradient 0, coef
        # stays 0 — Spark's zero-variance standardization semantics. The
        # test is RELATIVE to ex2 (one-pass cancellation noise is eps·ex2,
        # eps≈6e-8 f32) so a genuinely tiny-but-varying column stays alive;
        # the absolute floor catches columns constant at ≈0 within the config
        dead = var_raw < jnp.maximum(1e-6 * ex2, 1e-10)
        self.mean = mean
        self.scale = jnp.where(dead, 1e30, jnp.sqrt(self.var))  # (B, d)

    def xs_dot(self, A):
        """Xs Aᵀ for A (B, d) → (n, B)."""
        At = A / self.scale
        return self.Xg @ At.T - (self.mean * At).sum(axis=1)[None, :]

    def xs_t_dot(self, V):
        """Xsᵀ V for V (n, B) → (B, d)."""
        return ((V.T @ self.Xg)
                - V.sum(axis=0)[:, None] * self.mean) / self.scale

    def unscale(self, A, b):
        """Per-config standardized coefficients → original scale."""
        coef_g = A / self.scale
        bias_g = b - (coef_g * self.mean).sum(axis=1)
        coef = coef_g / self.g_scale
        bias = bias_g - (coef * self.g_mean).sum(axis=1)
        return coef, bias

    def typed_ops(self, cdt, Xg_c):
        """(xs_dot_c, xs_t_dot_c) computing the standardized matmuls with
        (n, B) intermediates in ``cdt`` (bf16 for CV sweeps) while every
        REDUCTION accumulates f32. ``Xg_c`` is the pre-cast globally
        standardized matrix so callers share one cast."""
        def xs_dot_c(A):
            """Xs Aᵀ → (n, B) cdt."""
            At = (A / self.scale).astype(cdt)
            off = (self.mean * (A / self.scale)).sum(axis=1).astype(cdt)
            return (jnp.dot(Xg_c, At.T, preferred_element_type=cdt)
                    - off[None, :])

        def xs_t_dot_c(V):
            """Xsᵀ V for V (n, B) cdt → (B, d) f32 (f32 accumulate)."""
            vt = jnp.dot(V.T, Xg_c, preferred_element_type=jnp.float32)
            return (vt - jnp.sum(V, axis=0, dtype=jnp.float32)[:, None]
                    * self.mean) / self.scale

        return xs_dot_c, xs_t_dot_c


@partial(jax.jit, static_argnames=("newton_iters", "cg_iters", "sweep"))
def _fit_logreg_batch(X, y, W, reg, elastic_net, newton_iters=10, cg_iters=8,
                      sweep=False):
    """Fit B logistic regressions at once. W: (B, n) per-config row weights;
    reg/elastic_net: (B,). Returns (coef (B, d), bias (B,)) in original scale.

    ``sweep``: keep the (n, B) elementwise temps (Z/P/R/S and the CG
    Hessian-vector products) in bfloat16 — the fit is HBM-bound on those
    temps at 1M rows, and CV candidates only need metric-ranking accuracy;
    all gradient/Hessian REDUCTIONS still accumulate f32, and the winner's
    refit runs with sweep=False (exact f32 temps).
    """
    nB = W.shape[0]
    d = X.shape[1]
    std = _BatchStd(X, W)
    Xg, Wt, cnt = std.Xg, std.Wt, std.cnt
    mean, var, scale = std.mean, std.var, std.scale
    l2 = reg * (1.0 - elastic_net)
    l1 = reg * elastic_net
    cdt = jnp.bfloat16 if sweep else X.dtype
    Xg_c = Xg.astype(cdt)
    Wt_c = Wt.astype(cdt)
    yv_c = y[:, None].astype(cdt)
    xs_dot_c, xs_t_dot_c = std.typed_ops(cdt, Xg_c)

    def newton_step(carry, _):
        A, b = carry                                    # (B, d), (B,)
        Z = xs_dot_c(A) + b[None, :].astype(cdt)        # (n, B) cdt
        P = jax.nn.sigmoid(Z)
        R = Wt_c * (P - yv_c)                           # (n, B) cdt
        S = Wt_c * jnp.maximum(P * (1 - P),
                               jnp.asarray(1e-6, cdt))  # (n, B) cdt
        g_A = xs_t_dot_c(R) / cnt[:, None] + l2[:, None] * A
        g_b = jnp.sum(R, axis=0, dtype=jnp.float32) / cnt
        ssum = jnp.sum(S, axis=0, dtype=jnp.float32)

        def hv(VA, vb):                                 # H·[v; v_b], all B
            U = xs_dot_c(VA) + vb[None, :].astype(cdt)
            T = S * U
            hA = xs_t_dot_c(T) / cnt[:, None] + (l2 + 1e-8)[:, None] * VA
            hb = jnp.sum(T, axis=0, dtype=jnp.float32) / cnt + 1e-8 * vb
            return hA, hb

        def cg_step(c, _):
            dA, db, rA, rb, pA, pb, rs = c
            hA, hb = hv(pA, pb)
            pHp = (pA * hA).sum(axis=1) + pb * hb
            alpha = rs / jnp.maximum(pHp, 1e-20)
            dA = dA + alpha[:, None] * pA
            db = db + alpha * pb
            rA = rA - alpha[:, None] * hA
            rb = rb - alpha * hb
            rs_new = (rA * rA).sum(axis=1) + rb * rb
            beta = rs_new / jnp.maximum(rs, 1e-20)
            pA = rA + beta[:, None] * pA
            pb = rb + beta * pb
            return (dA, db, rA, rb, pA, pb, rs_new), None

        z0 = jnp.zeros_like(A)
        zb = jnp.zeros_like(b)
        rs0 = (g_A * g_A).sum(axis=1) + g_b * g_b
        (dA, db, *_), _ = jax.lax.scan(
            cg_step, (z0, zb, g_A, g_b, g_A, g_b, rs0), None, length=cg_iters)

        A = A - dA
        b = b - db
        # prox for L1 in the diagonal-Hessian metric:
        # diag(Hs) = (Sᵀ Xg² − 2 mean·(Sᵀ Xg) + Σ S·mean²) / var / cnt
        StX = jnp.dot(S.T, Xg_c, preferred_element_type=jnp.float32)
        StX2 = jnp.dot(S.T, Xg_c * Xg_c,
                       preferred_element_type=jnp.float32)
        diag = (StX2 - 2 * mean * StX
                + ssum[:, None] * mean ** 2) / var / cnt[:, None]
        thresh = l1[:, None] / jnp.maximum(diag, 1e-8)
        A = jnp.where(l1[:, None] > 0,
                      jnp.sign(A) * jnp.maximum(jnp.abs(A) - thresh, 0.0), A)
        return (A, b), None

    A0 = jnp.zeros((nB, d), X.dtype)
    b0 = jnp.zeros((nB,), X.dtype)
    (A, b), _ = jax.lax.scan(newton_step, (A0, b0), None, length=newton_iters)
    return std.unscale(A, b)


def _fit_logreg(X, y, w, reg, elastic_net):
    """Single-config fit: the B=1 slice of the batched solver."""
    coef, bias = _fit_logreg_batch(
        X, y, w[None, :], jnp.asarray([reg], X.dtype),
        jnp.asarray([elastic_net], X.dtype))
    return coef[0], bias[0]


class LogisticRegressionFamily(ModelFamily):
    """reference OpLogisticRegression (defaults: regParam [0.01,0.1,0.2],
    elasticNetParam [0,0.5] — DefaultSelectorParams.scala)."""

    name = "OpLogisticRegression"
    #: grid values are consumed purely as (B,) arrays — safe to
    #: trace as a packed, donated device block under the mesh
    traced_grid_ok = True
    supports = frozenset({"binary", "multiclass"})

    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        return [{"regParam": r, "elasticNetParam": e}
                for r in (0.01, 0.1, 0.2) for e in (0.0, 0.5)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        if num_classes <= 2:
            coef, bias = _fit_logreg_batch(
                X, y, weights, grid["regParam"], grid["elasticNetParam"])
            return {"coef": coef, "bias": bias}
        W, b = _fit_softmax_batch(X, y.astype(jnp.int32), weights,
                                  grid["regParam"], num_classes)
        return {"W": W, "b": b}

    def sweep_fit_batch(self, X, y, weights, grid, num_classes):
        # CV candidates: bf16 (n, B) temps and a shorter Newton-CG schedule
        # — metric-ranking accuracy only; the winner refits through
        # fit_batch (exact f32 temps, full 10x8 schedule)
        if num_classes <= 2:
            coef, bias = _fit_logreg_batch(
                X, y, weights, grid["regParam"], grid["elasticNetParam"],
                newton_iters=8, cg_iters=6, sweep=True)
            return {"coef": coef, "bias": bias}
        return self.fit_batch(X, y, weights, grid, num_classes)

    def predict_batch(self, params, X, num_classes):
        if num_classes <= 2:
            return jax.nn.sigmoid(
                jnp.einsum("bd,nd->bn", params["coef"], X, precision=_PREC)
                + params["bias"][:, None])
        logits = jnp.einsum("bdc,nd->bnc", params["W"], X, precision=_PREC) \
            + params["b"][:, None, :]
        return jax.nn.softmax(logits, axis=-1)

    def predict_parts(self, fitted: FittedParams, X):
        if fitted.num_classes <= 2:
            margin = X @ jnp.asarray(fitted.params["coef"]) \
                + fitted.params["bias"]
            p1 = jax.nn.sigmoid(margin)
            prob = jnp.stack([1 - p1, p1], axis=1)
            raw = jnp.stack([-margin, margin], axis=1)
        else:
            raw = X @ jnp.asarray(fitted.params["W"]) \
                + jnp.asarray(fitted.params["b"])
            prob = jax.nn.softmax(raw, axis=-1)
        pred = prob.argmax(axis=1).astype(jnp.float32)
        return {"prediction": pred, "probability": prob, "rawPrediction": raw}

    def predict_one(self, fitted: FittedParams, X) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v)
                for k, v in self.predict_parts(fitted, X).items()}


@partial(jax.jit, static_argnames=("num_classes", "iters"))
def _fit_softmax_batch(X, y_idx, W_rows, reg, num_classes, iters=200):
    """Multinomial logistic regression, all B configs in one program of
    shared matmuls: full-batch Adam whose forward/backward are single
    (n,d)@(d,B·C) / (d,n)@(n,B·C) contractions via the same standardization
    algebra as the binary solver. W_rows: (B, n) row weights; reg: (B,).
    Returns (W (B, d, C), b (B, C)) in original scale."""
    C = num_classes
    nB = W_rows.shape[0]
    d = X.shape[1]
    std = _BatchStd(X, W_rows)
    Xg, cnt = std.Xg, std.cnt
    mean, scale = std.mean, std.scale                   # (B, d)
    Wt = W_rows.T                                       # (n, B)
    Y = jax.nn.one_hot(y_idx, C, dtype=X.dtype)         # (n, C)

    def grads(Wc, b):
        """Wc: (B, d, C) per-config standardized coefs; b: (B, C)."""
        At = Wc / scale[:, :, None]                     # (B, d, C)
        off = (mean[:, :, None] * At).sum(axis=1)       # (B, C)
        Z = jnp.einsum("nd,bdc->nbc", Xg, At) + (b - off)[None]
        P = jax.nn.softmax(Z, axis=-1)
        R = Wt[:, :, None] * (P - Y[:, None, :])        # (n, B, C)
        GX = jnp.einsum("nd,nbc->bdc", Xg, R)           # Xgᵀ R
        Rsum = R.sum(axis=0)                            # (B, C)
        g_W = ((GX - mean[:, :, None] * Rsum[:, None, :]) / scale[:, :, None]
               / cnt[:, None, None]) + reg[:, None, None] * Wc
        g_b = Rsum / cnt[:, None]
        return g_W, g_b

    # hand-rolled Adam (optax pulls jax.experimental.checkify, which clashes
    # with the axon platform-registry rewrite in this environment)
    lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
    params = (jnp.zeros((nB, d, C), X.dtype), jnp.zeros((nB, C), X.dtype))
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)

    def step(carry, i):
        params, m, v = carry
        g = grads(*params)
        m = jax.tree_util.tree_map(lambda a, b_: b1 * a + (1 - b1) * b_, m, g)
        v = jax.tree_util.tree_map(
            lambda a, b_: b2 * a + (1 - b2) * b_ * b_, v, g)
        t = i + 1.0
        params = jax.tree_util.tree_map(
            lambda p, mm, vv: p - lr * (mm / (1 - b1 ** t)) /
            (jnp.sqrt(vv / (1 - b2 ** t)) + eps), params, m, v)
        return (params, m, v), None

    (params, _, _), _ = jax.lax.scan(
        step, (params, zeros, zeros), jnp.arange(iters, dtype=X.dtype))
    Wc, b = params
    # per-config standardized → Xg space → original space (per class)
    W_g = Wc / scale[:, :, None]
    b_g = b - (W_g * mean[:, :, None]).sum(axis=1)
    Wx = W_g / std.g_scale[None, :, None]
    bx = b_g - (Wx * std.g_mean[None, :, None]).sum(axis=1)
    return Wx, bx


def _fit_softmax(X, y_idx, w, reg, num_classes, iters=200):
    """Single-config fit: the B=1 slice of the batched solver."""
    W, b = _fit_softmax_batch(X, y_idx, w[None, :],
                              jnp.asarray([reg], X.dtype), num_classes,
                              iters=iters)
    return W[0], b[0]


# ---------------------------------------------------------------------------
# Linear / ridge regression — closed form + ISTA refinement for L1
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("l1_iters",))
def _fit_linreg(X, y, w, reg, elastic_net, l1_iters=60):
    n, d = X.shape
    Xs, mean, scale = _standardize(X, w)
    cnt = jnp.maximum(w.sum(), 1.0)
    l2 = reg * (1.0 - elastic_net)
    l1 = reg * elastic_net
    Xa = jnp.concatenate([Xs, jnp.ones((n, 1), X.dtype)], axis=1)
    A = jnp.einsum("ni,nj->ij", Xa * w[:, None], Xa, precision=_PREC) / cnt
    A = A + jnp.diag(jnp.concatenate([jnp.full((d,), l2), jnp.zeros((1,))])) \
        + 1e-8 * jnp.eye(d + 1, dtype=X.dtype)
    rhs = (Xa * (w * y)[:, None]).sum(0) / cnt
    theta = jnp.linalg.solve(A, rhs)

    # ISTA refinement handles the L1 part (no-op when l1 == 0)
    lips = jnp.trace(A)  # cheap Lipschitz upper bound for the quadratic part
    step_sz = 1.0 / jnp.maximum(lips, 1e-6)

    def ista(theta, _):
        grad = A @ theta - rhs
        t = theta - step_sz * grad
        coef = jnp.sign(t[:d]) * jnp.maximum(jnp.abs(t[:d]) - step_sz * l1, 0.0)
        return jnp.concatenate([coef, t[d:]]), None

    theta = jax.lax.cond(
        l1 > 0,
        lambda th: jax.lax.scan(ista, th, None, length=l1_iters)[0],
        lambda th: th, theta)
    coef, bias = _unscale(theta[:d], theta[d], mean, scale)
    return coef, bias


_fit_linreg_batch = jax.jit(jax.vmap(_fit_linreg, in_axes=(None, None, 0, 0, 0)))


class LinearRegressionFamily(ModelFamily):
    """reference OpLinearRegression (defaults: regParam [0.001,0.01,0.1],
    elasticNetParam [0,0.5])."""

    name = "OpLinearRegression"
    #: grid values are consumed purely as (B,) arrays — safe to
    #: trace as a packed, donated device block under the mesh
    traced_grid_ok = True
    supports = frozenset({"regression"})

    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        return [{"regParam": r, "elasticNetParam": e}
                for r in (0.001, 0.01, 0.1) for e in (0.0, 0.5)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        coef, bias = _fit_linreg_batch(
            X, y, weights, grid["regParam"], grid["elasticNetParam"])
        return {"coef": coef, "bias": bias}

    def predict_batch(self, params, X, num_classes):
        return jnp.einsum("bd,nd->bn", params["coef"], X, precision=_PREC) \
            + params["bias"][:, None]

    def predict_parts(self, fitted: FittedParams, X):
        pred = X @ jnp.asarray(fitted.params["coef"]) + fitted.params["bias"]
        return {"prediction": pred}

    def predict_one(self, fitted: FittedParams, X) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v)
                for k, v in self.predict_parts(fitted, X).items()}


# ---------------------------------------------------------------------------
# Linear SVC — squared hinge + L2, Nesterov accelerated GD, batched over
# configs via the same shared-matmul standardization algebra as logistic.
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("iters", "sweep"))
def _fit_svc_batch(X, y, W, reg, iters=100, sweep=False):
    """Fit B linear SVCs at once. W: (B, n) row weights; reg: (B,).
    Each GD step is two shared (n,d)@(d,B) matmuls. ``sweep``: bf16 (n, B)
    margin/gradient temps (f32 reduction accumulates) — see
    _fit_logreg_batch."""
    nB = W.shape[0]
    d = X.shape[1]
    std = _BatchStd(X, W)
    Wt, cnt = std.Wt, std.cnt
    cdt = jnp.bfloat16 if sweep else X.dtype
    Wt_c = Wt.astype(cdt)
    ypm_c = (2.0 * y - 1.0)[:, None].astype(cdt)        # (n, 1), {-1,+1}
    xs_dot_c, xs_t_dot_c = std.typed_ops(cdt, std.Xg.astype(cdt))

    def loss_grad(A, b):
        Z = xs_dot_c(A) + b[None, :].astype(cdt)
        M = ypm_c * Z                                   # (n, B) margins
        act = jnp.maximum(jnp.asarray(1.0, cdt) - M, jnp.asarray(0.0, cdt))
        G_m = jnp.asarray(-2.0, cdt) * act * ypm_c * Wt_c   # (n, B)
        g_A = xs_t_dot_c(G_m) / cnt[:, None] + reg[:, None] * A
        g_b = jnp.sum(G_m, axis=0, dtype=jnp.float32) / cnt
        return g_A, g_b

    # Lipschitz ≈ 2·mean row-norm² (+ reg); standardized rows → ‖x‖² ≈ d
    lr = 1.0 / (2.0 * d / 4.0 + reg + 1.0)              # (B,)

    def step(carry, _):
        A, b, Ap, bp, t = carry
        mom = (t - 1.0) / (t + 2.0)
        mA = A + mom * (A - Ap)
        mb = b + mom * (b - bp)
        g_A, g_b = loss_grad(mA, mb)
        return (mA - lr[:, None] * g_A, mb - lr * g_b, A, b, t + 1.0), None

    zA = jnp.zeros((nB, d), X.dtype)
    zb = jnp.zeros((nB,), X.dtype)
    (A, b, _, _, _), _ = jax.lax.scan(
        step, (zA, zb, zA, zb, jnp.asarray(1.0, X.dtype)), None, length=iters)
    return std.unscale(A, b)


def _fit_svc(X, y, w, reg, iters=100):
    """Single-config fit: the B=1 slice of the batched solver."""
    coef, bias = _fit_svc_batch(X, y, w[None, :], jnp.asarray([reg], X.dtype),
                                iters=iters)
    return coef[0], bias[0]


class LinearSVCFamily(ModelFamily):
    """reference OpLinearSVC (defaults: regParam [0.01,0.1,0.2])."""

    name = "OpLinearSVC"
    #: grid values are consumed purely as (B,) arrays — safe to
    #: trace as a packed, donated device block under the mesh
    traced_grid_ok = True
    supports = frozenset({"binary"})

    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        return [{"regParam": r} for r in (0.01, 0.1, 0.2)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        coef, bias = _fit_svc_batch(X, y, weights, grid["regParam"])
        return {"coef": coef, "bias": bias}

    def sweep_fit_batch(self, X, y, weights, grid, num_classes):
        coef, bias = _fit_svc_batch(X, y, weights, grid["regParam"],
                                    sweep=True)
        return {"coef": coef, "bias": bias}

    def predict_batch(self, params, X, num_classes):
        # squash margins so threshold-style validation metrics (which cut at
        # 0.5) and LogLoss see [0,1] scores; rank metrics are unaffected by
        # the monotone map, and sigmoid(m) > 0.5 ⇔ margin > 0
        margins = jnp.einsum("bd,nd->bn", params["coef"], X, precision=_PREC) \
            + params["bias"][:, None]
        return jax.nn.sigmoid(margins)

    def predict_parts(self, fitted: FittedParams, X):
        margin = X @ jnp.asarray(fitted.params["coef"]) + fitted.params["bias"]
        pred = (margin > 0).astype(jnp.float32)
        raw = jnp.stack([-margin, margin], axis=1)
        return {"prediction": pred, "rawPrediction": raw}

    def predict_one(self, fitted: FittedParams, X) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v)
                for k, v in self.predict_parts(fitted, X).items()}


# ---------------------------------------------------------------------------
# Naive Bayes — multinomial with Laplace smoothing (closed-form counting)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("num_classes",))
def _fit_nb(X, y_idx, w, smoothing, num_classes):
    Xp = jnp.maximum(X, 0.0)  # multinomial NB needs nonnegative counts
    Y = jax.nn.one_hot(y_idx, num_classes, dtype=X.dtype) * w[:, None]
    class_cnt = Y.sum(0)
    feat_cnt = jnp.einsum("nc,nd->cd", Y, Xp, precision=_PREC)
    d = X.shape[1]
    log_prob = jnp.log(feat_cnt + smoothing) - \
        jnp.log(feat_cnt.sum(1, keepdims=True) + smoothing * d)
    log_prior = jnp.log(jnp.maximum(class_cnt, 1e-12) /
                        jnp.maximum(class_cnt.sum(), 1e-12))
    return log_prob, log_prior


_fit_nb_batch = jax.jit(jax.vmap(_fit_nb, in_axes=(None, None, 0, 0, None)),
                        static_argnames=("num_classes",))


class NaiveBayesFamily(ModelFamily):
    """reference OpNaiveBayes (default smoothing 1.0)."""

    name = "OpNaiveBayes"
    #: grid values are consumed purely as (B,) arrays — safe to
    #: trace as a packed, donated device block under the mesh
    traced_grid_ok = True
    supports = frozenset({"binary", "multiclass"})

    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        return [{"smoothing": s} for s in (0.5, 1.0, 2.0)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        lp, prior = _fit_nb_batch(X, y.astype(jnp.int32), weights,
                                  grid["smoothing"], max(num_classes, 2))
        return {"log_prob": lp, "log_prior": prior}

    def predict_batch(self, params, X, num_classes):
        Xp = jnp.maximum(X, 0.0)
        logits = jnp.einsum("bcd,nd->bnc", params["log_prob"], Xp,
                            precision=_PREC) + params["log_prior"][:, None, :]
        if num_classes <= 2:
            return jax.nn.softmax(logits, axis=-1)[:, :, 1]
        return jax.nn.softmax(logits, axis=-1)

    def predict_parts(self, fitted: FittedParams, X):
        Xp = jnp.maximum(X, 0.0)
        raw = Xp @ jnp.asarray(fitted.params["log_prob"]).T \
            + jnp.asarray(fitted.params["log_prior"])
        prob = jax.nn.softmax(raw, axis=-1)
        pred = prob.argmax(axis=1).astype(jnp.float32)
        return {"prediction": pred, "probability": prob, "rawPrediction": raw}

    def predict_one(self, fitted: FittedParams, X) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v)
                for k, v in self.predict_parts(fitted, X).items()}


register_family(LogisticRegressionFamily())
register_family(LinearRegressionFamily())
register_family(LinearSVCFamily())
register_family(NaiveBayesFamily())
