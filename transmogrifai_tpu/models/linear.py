"""Linear model families: logistic regression, linear/ridge regression,
linear SVC, naive Bayes.

TPU-native replacements for the reference's SparkML wrappers
(reference: core/.../impl/classification/OpLogisticRegression.scala,
OpLinearSVC.scala, OpNaiveBayes.scala, impl/regression/OpLinearRegression.scala).
Each family fits its whole hyperparameter × fold batch in ONE jitted, vmapped
XLA program: the inner loop is prox-Newton / closed-form solves built from
(n,d)ᵀ(n,d) MXU matmuls, and per-configuration 0/1 row-weight vectors express
CV folds without reshaping data.

Conventions (matching Spark ML so reference grids transfer):
* objective = mean loss + regParam * (α·‖w‖₁ + (1-α)/2·‖w‖₂²), bias unpenalized
* features are standardized internally (Spark standardization=true default);
  coefficients are reported in the original scale.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .api import FittedParams, ModelFamily, register_family

_PREC = jax.lax.Precision.HIGHEST


def _standardize(X: jnp.ndarray, w: jnp.ndarray):
    """Weighted feature standardization; returns (Xs, mean, scale)."""
    cnt = jnp.maximum(w.sum(), 1.0)
    mean = (X * w[:, None]).sum(0) / cnt
    var = ((X - mean) ** 2 * w[:, None]).sum(0) / cnt
    scale = jnp.sqrt(jnp.maximum(var, 1e-12))
    return (X - mean) / scale, mean, scale


def _unscale(coef_s: jnp.ndarray, bias_s: jnp.ndarray, mean: jnp.ndarray,
             scale: jnp.ndarray):
    coef = coef_s / scale
    bias = bias_s - (coef * mean).sum()
    return coef, bias


# ---------------------------------------------------------------------------
# Binary logistic regression — prox-Newton (IRLS + coordinate-wise soft
# thresholding for the L1 part)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("iters",))
def _fit_logreg(X, y, w, reg, elastic_net, iters=25):
    n, d = X.shape
    Xs, mean, scale = _standardize(X, w)
    cnt = jnp.maximum(w.sum(), 1.0)
    l2 = reg * (1.0 - elastic_net)
    l1 = reg * elastic_net

    def step(carry, _):
        coef, bias = carry
        z = Xs @ coef + bias
        p = jax.nn.sigmoid(z)
        s = jnp.maximum(p * (1 - p), 1e-6) * w
        g_coef = (Xs * (w * (p - y))[:, None]).sum(0) / cnt + l2 * coef
        g_bias = (w * (p - y)).sum() / cnt
        H = jnp.einsum("ni,nj->ij", Xs * s[:, None], Xs, precision=_PREC) / cnt
        H = H + (l2 + 1e-8) * jnp.eye(d, dtype=X.dtype)
        h_bias = s.sum() / cnt + 1e-8
        Hx_b = (Xs * s[:, None]).sum(0) / cnt
        # full (d+1) system with bias row/col
        Ha = jnp.zeros((d + 1, d + 1), X.dtype)
        Ha = Ha.at[:d, :d].set(H).at[d, d].set(h_bias)
        Ha = Ha.at[:d, d].set(Hx_b).at[d, :d].set(Hx_b)
        g = jnp.concatenate([g_coef, jnp.array([g_bias], X.dtype)])
        delta = jnp.linalg.solve(Ha, g)
        coef = coef - delta[:d]
        bias = bias - delta[d]
        # prox step for L1 in the diagonal-Hessian metric
        thresh = l1 / jnp.maximum(jnp.diag(H), 1e-8)
        coef = jnp.where(l1 > 0,
                         jnp.sign(coef) * jnp.maximum(jnp.abs(coef) - thresh, 0.0),
                         coef)
        return (coef, bias), None

    init = (jnp.zeros((d,), X.dtype), jnp.asarray(0.0, X.dtype))
    (coef_s, bias_s), _ = jax.lax.scan(step, init, None, length=iters)
    coef, bias = _unscale(coef_s, bias_s, mean, scale)
    return coef, bias


_fit_logreg_batch = jax.jit(
    jax.vmap(_fit_logreg, in_axes=(None, None, 0, 0, 0)),
    static_argnames=())


class LogisticRegressionFamily(ModelFamily):
    """reference OpLogisticRegression (defaults: regParam [0.01,0.1,0.2],
    elasticNetParam [0,0.5] — DefaultSelectorParams.scala)."""

    name = "OpLogisticRegression"
    supports = frozenset({"binary", "multiclass"})

    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        return [{"regParam": r, "elasticNetParam": e}
                for r in (0.01, 0.1, 0.2) for e in (0.0, 0.5)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        if num_classes <= 2:
            coef, bias = _fit_logreg_batch(
                X, y, weights, grid["regParam"], grid["elasticNetParam"])
            return {"coef": coef, "bias": bias}
        W, b = _fit_softmax_batch(X, y.astype(jnp.int32), weights,
                                  grid["regParam"], num_classes)
        return {"W": W, "b": b}

    def predict_batch(self, params, X, num_classes):
        if num_classes <= 2:
            return jax.nn.sigmoid(
                jnp.einsum("bd,nd->bn", params["coef"], X, precision=_PREC)
                + params["bias"][:, None])
        logits = jnp.einsum("bdc,nd->bnc", params["W"], X, precision=_PREC) \
            + params["b"][:, None, :]
        return jax.nn.softmax(logits, axis=-1)

    def predict_one(self, fitted: FittedParams, X) -> Dict[str, np.ndarray]:
        if fitted.num_classes <= 2:
            margin = X @ fitted.params["coef"] + fitted.params["bias"]
            p1 = jax.nn.sigmoid(margin)
            prob = jnp.stack([1 - p1, p1], axis=1)
            raw = jnp.stack([-margin, margin], axis=1)
        else:
            raw = X @ fitted.params["W"] + fitted.params["b"]
            prob = jax.nn.softmax(raw, axis=-1)
        pred = prob.argmax(axis=1).astype(jnp.float32)
        return {"prediction": np.asarray(pred),
                "probability": np.asarray(prob),
                "rawPrediction": np.asarray(raw)}


@partial(jax.jit, static_argnames=("num_classes", "iters"))
def _fit_softmax(X, y_idx, w, reg, num_classes, iters=200):
    """Multinomial logistic regression via full-batch Adam (fixed-length scan)."""
    n, d = X.shape
    Xs, mean, scale = _standardize(X, w)
    cnt = jnp.maximum(w.sum(), 1.0)
    Y = jax.nn.one_hot(y_idx, num_classes, dtype=X.dtype)

    def loss_fn(params):
        W, b = params
        logits = Xs @ W + b
        lp = jax.nn.log_softmax(logits, axis=-1)
        nll = -(Y * lp).sum(axis=1) * w
        return nll.sum() / cnt + 0.5 * reg * (W ** 2).sum()

    # hand-rolled Adam (optax pulls jax.experimental.checkify, which clashes
    # with the axon platform-registry rewrite in this environment)
    lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
    params = (jnp.zeros((d, num_classes), X.dtype),
              jnp.zeros((num_classes,), X.dtype))
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)

    def step(carry, i):
        params, m, v = carry
        g = jax.grad(loss_fn)(params)
        m = jax.tree_util.tree_map(lambda a, b: b1 * a + (1 - b1) * b, m, g)
        v = jax.tree_util.tree_map(lambda a, b: b2 * a + (1 - b2) * b * b, v, g)
        t = i + 1.0
        params = jax.tree_util.tree_map(
            lambda p, mm, vv: p - lr * (mm / (1 - b1 ** t)) /
            (jnp.sqrt(vv / (1 - b2 ** t)) + eps), params, m, v)
        return (params, m, v), None

    (params, _, _), _ = jax.lax.scan(
        step, (params, zeros, zeros), jnp.arange(iters, dtype=X.dtype))
    W_s, b_s = params
    W = W_s / scale[:, None]
    b = b_s - (W * mean[:, None]).sum(0)
    return W, b


_fit_softmax_batch = jax.jit(
    jax.vmap(_fit_softmax, in_axes=(None, None, 0, 0, None)),
    static_argnames=("num_classes", "iters"))


# ---------------------------------------------------------------------------
# Linear / ridge regression — closed form + ISTA refinement for L1
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("l1_iters",))
def _fit_linreg(X, y, w, reg, elastic_net, l1_iters=60):
    n, d = X.shape
    Xs, mean, scale = _standardize(X, w)
    cnt = jnp.maximum(w.sum(), 1.0)
    l2 = reg * (1.0 - elastic_net)
    l1 = reg * elastic_net
    Xa = jnp.concatenate([Xs, jnp.ones((n, 1), X.dtype)], axis=1)
    A = jnp.einsum("ni,nj->ij", Xa * w[:, None], Xa, precision=_PREC) / cnt
    A = A + jnp.diag(jnp.concatenate([jnp.full((d,), l2), jnp.zeros((1,))])) \
        + 1e-8 * jnp.eye(d + 1, dtype=X.dtype)
    rhs = (Xa * (w * y)[:, None]).sum(0) / cnt
    theta = jnp.linalg.solve(A, rhs)

    # ISTA refinement handles the L1 part (no-op when l1 == 0)
    lips = jnp.trace(A)  # cheap Lipschitz upper bound for the quadratic part
    step_sz = 1.0 / jnp.maximum(lips, 1e-6)

    def ista(theta, _):
        grad = A @ theta - rhs
        t = theta - step_sz * grad
        coef = jnp.sign(t[:d]) * jnp.maximum(jnp.abs(t[:d]) - step_sz * l1, 0.0)
        return jnp.concatenate([coef, t[d:]]), None

    theta = jax.lax.cond(
        l1 > 0,
        lambda th: jax.lax.scan(ista, th, None, length=l1_iters)[0],
        lambda th: th, theta)
    coef, bias = _unscale(theta[:d], theta[d], mean, scale)
    return coef, bias


_fit_linreg_batch = jax.jit(jax.vmap(_fit_linreg, in_axes=(None, None, 0, 0, 0)))


class LinearRegressionFamily(ModelFamily):
    """reference OpLinearRegression (defaults: regParam [0.001,0.01,0.1],
    elasticNetParam [0,0.5])."""

    name = "OpLinearRegression"
    supports = frozenset({"regression"})

    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        return [{"regParam": r, "elasticNetParam": e}
                for r in (0.001, 0.01, 0.1) for e in (0.0, 0.5)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        coef, bias = _fit_linreg_batch(
            X, y, weights, grid["regParam"], grid["elasticNetParam"])
        return {"coef": coef, "bias": bias}

    def predict_batch(self, params, X, num_classes):
        return jnp.einsum("bd,nd->bn", params["coef"], X, precision=_PREC) \
            + params["bias"][:, None]

    def predict_one(self, fitted: FittedParams, X) -> Dict[str, np.ndarray]:
        pred = X @ fitted.params["coef"] + fitted.params["bias"]
        return {"prediction": np.asarray(pred)}


# ---------------------------------------------------------------------------
# Linear SVC — squared hinge + L2, Nesterov accelerated GD
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("iters",))
def _fit_svc(X, y, w, reg, iters=150):
    n, d = X.shape
    Xs, mean, scale = _standardize(X, w)
    cnt = jnp.maximum(w.sum(), 1.0)
    ypm = 2.0 * y - 1.0  # {0,1} → {-1,+1}

    def loss_grad(theta):
        coef, bias = theta[:d], theta[d]
        m = ypm * (Xs @ coef + bias)
        act = jnp.maximum(1.0 - m, 0.0)
        g_m = -2.0 * act * ypm * w
        g_coef = (Xs * g_m[:, None]).sum(0) / cnt + reg * coef
        g_bias = g_m.sum() / cnt
        return jnp.concatenate([g_coef, jnp.array([g_bias], X.dtype)])

    # Lipschitz ≈ 2·mean row-norm² (+ reg); standardized rows → ‖x‖² ≈ d
    lr = 1.0 / (2.0 * d / 4.0 + reg + 1.0)

    def step(carry, _):
        theta, theta_prev, t = carry
        mom = theta + (t - 1.0) / (t + 2.0) * (theta - theta_prev)
        nxt = mom - lr * loss_grad(mom)
        return (nxt, theta, t + 1.0), None

    z = jnp.zeros((d + 1,), X.dtype)
    (theta, _, _), _ = jax.lax.scan(step, (z, z, jnp.asarray(1.0, X.dtype)),
                                    None, length=iters)
    coef, bias = _unscale(theta[:d], theta[d], mean, scale)
    return coef, bias


_fit_svc_batch = jax.jit(jax.vmap(_fit_svc, in_axes=(None, None, 0, 0)))


class LinearSVCFamily(ModelFamily):
    """reference OpLinearSVC (defaults: regParam [0.01,0.1,0.2])."""

    name = "OpLinearSVC"
    supports = frozenset({"binary"})

    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        return [{"regParam": r} for r in (0.01, 0.1, 0.2)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        coef, bias = _fit_svc_batch(X, y, weights, grid["regParam"])
        return {"coef": coef, "bias": bias}

    def predict_batch(self, params, X, num_classes):
        # squash margins so threshold-style validation metrics (which cut at
        # 0.5) and LogLoss see [0,1] scores; rank metrics are unaffected by
        # the monotone map, and sigmoid(m) > 0.5 ⇔ margin > 0
        margins = jnp.einsum("bd,nd->bn", params["coef"], X, precision=_PREC) \
            + params["bias"][:, None]
        return jax.nn.sigmoid(margins)

    def predict_one(self, fitted: FittedParams, X) -> Dict[str, np.ndarray]:
        margin = X @ fitted.params["coef"] + fitted.params["bias"]
        pred = (margin > 0).astype(jnp.float32)
        raw = jnp.stack([-margin, margin], axis=1)
        return {"prediction": np.asarray(pred), "rawPrediction": np.asarray(raw)}


# ---------------------------------------------------------------------------
# Naive Bayes — multinomial with Laplace smoothing (closed-form counting)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("num_classes",))
def _fit_nb(X, y_idx, w, smoothing, num_classes):
    Xp = jnp.maximum(X, 0.0)  # multinomial NB needs nonnegative counts
    Y = jax.nn.one_hot(y_idx, num_classes, dtype=X.dtype) * w[:, None]
    class_cnt = Y.sum(0)
    feat_cnt = jnp.einsum("nc,nd->cd", Y, Xp, precision=_PREC)
    d = X.shape[1]
    log_prob = jnp.log(feat_cnt + smoothing) - \
        jnp.log(feat_cnt.sum(1, keepdims=True) + smoothing * d)
    log_prior = jnp.log(jnp.maximum(class_cnt, 1e-12) /
                        jnp.maximum(class_cnt.sum(), 1e-12))
    return log_prob, log_prior


_fit_nb_batch = jax.jit(jax.vmap(_fit_nb, in_axes=(None, None, 0, 0, None)),
                        static_argnames=("num_classes",))


class NaiveBayesFamily(ModelFamily):
    """reference OpNaiveBayes (default smoothing 1.0)."""

    name = "OpNaiveBayes"
    supports = frozenset({"binary", "multiclass"})

    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        return [{"smoothing": s} for s in (0.5, 1.0, 2.0)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        lp, prior = _fit_nb_batch(X, y.astype(jnp.int32), weights,
                                  grid["smoothing"], max(num_classes, 2))
        return {"log_prob": lp, "log_prior": prior}

    def predict_batch(self, params, X, num_classes):
        Xp = jnp.maximum(X, 0.0)
        logits = jnp.einsum("bcd,nd->bnc", params["log_prob"], Xp,
                            precision=_PREC) + params["log_prior"][:, None, :]
        if num_classes <= 2:
            return jax.nn.softmax(logits, axis=-1)[:, :, 1]
        return jax.nn.softmax(logits, axis=-1)

    def predict_one(self, fitted: FittedParams, X) -> Dict[str, np.ndarray]:
        Xp = jnp.maximum(X, 0.0)
        raw = Xp @ fitted.params["log_prob"].T + fitted.params["log_prior"]
        prob = jax.nn.softmax(raw, axis=-1)
        pred = prob.argmax(axis=1).astype(jnp.float32)
        return {"prediction": np.asarray(pred), "probability": np.asarray(prob),
                "rawPrediction": np.asarray(raw)}


register_family(LogisticRegressionFamily())
register_family(LinearRegressionFamily())
register_family(LinearSVCFamily())
register_family(NaiveBayesFamily())
