"""Generalized linear model family.

TPU-native replacement for the reference's Spark GLR wrapper (reference:
core/.../impl/regression/OpGeneralizedLinearRegression.scala; default grid
DistFamily {gaussian, poisson} × Regularization per DefaultSelectorParams).

One IRLS (iteratively reweighted least squares) loop of fixed length fits
every distribution family: the working response and weights are selected by
a traced family code, so a mixed gaussian/poisson grid still compiles to one
XLA program under ``lax.map``-free vmap (the per-config arithmetic differs
only in elementwise `where`s).

Links: gaussian → identity; poisson / gamma / tweedie → log (Spark's gamma
default link is inverse; log is used here for numerical robustness on
standardized features — documented deviation).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .api import FittedParams, ModelFamily, register_family

_PREC = jax.lax.Precision.HIGHEST

#: distribution family codes (carried as float32 through grid arrays)
FAMILY_CODES = {"gaussian": 0.0, "poisson": 1.0, "gamma": 2.0, "tweedie": 3.0}


@partial(jax.jit, static_argnames=("iters",))
def _fit_glm(X, y, w, reg, fam, var_power, iters=25):
    """IRLS for one configuration. fam: family code; var_power: tweedie
    variance power (Var(μ) = μ^p); ignored for other families."""
    n, d = X.shape
    Xa = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
    cnt = jnp.maximum(w.sum(), 1.0)
    is_gauss = fam == FAMILY_CODES["gaussian"]
    # variance power: gaussian 0 (unused), poisson 1, gamma 2, tweedie p
    p = jnp.where(fam == FAMILY_CODES["poisson"], 1.0,
                  jnp.where(fam == FAMILY_CODES["gamma"], 2.0, var_power))

    def deviance(theta):
        """Weighted mean deviance-like loss per family (log link):
        gaussian: squared error; others: -y·η + μ (poisson-shaped surrogate,
        monotone in fit quality for the log-link families)."""
        eta = jnp.clip(Xa @ theta, -30.0, 30.0)
        mu = jnp.exp(eta)
        loss_log = (mu - y * eta)
        loss_gauss = 0.5 * (y - eta) ** 2
        return (jnp.where(is_gauss, loss_gauss, loss_log) * w).sum() / cnt

    def step(carry, _):
        theta, best_theta, best_loss = carry
        eta = Xa @ theta
        mu = jnp.exp(jnp.clip(eta, -30.0, 30.0))
        # log link: W = μ^(2-p), z = η + (y-μ)/μ ; identity: W = 1, z = y
        W_log = jnp.power(jnp.maximum(mu, 1e-12), 2.0 - p)
        z_log = jnp.clip(eta + (y - mu) / jnp.maximum(mu, 1e-12), -1e6, 1e6)
        W = jnp.where(is_gauss, 1.0, W_log) * w
        z = jnp.where(is_gauss, y, z_log)
        A = jnp.einsum("ni,nj->ij", Xa * W[:, None], Xa,
                       precision=_PREC) / cnt
        A = A + jnp.diag(jnp.concatenate(
            [jnp.full((d,), reg), jnp.zeros((1,))])) \
            + 1e-8 * jnp.eye(d + 1, dtype=X.dtype)
        rhs = (Xa * (W * z)[:, None]).sum(0) / cnt
        prop = jnp.linalg.solve(A, rhs)
        prop = jnp.where(jnp.all(jnp.isfinite(prop)), prop, theta)
        # divergence guard: track the best iterate (mismatched family/link
        # configs — e.g. log link on negative targets — oscillate or blow
        # up; keep the best-deviance parameters instead of the last)
        loss = deviance(prop)
        better = loss < best_loss
        best_theta = jnp.where(better, prop, best_theta)
        best_loss = jnp.where(better, loss, best_loss)
        return (prop, best_theta, best_loss), None

    theta0 = jnp.zeros((d + 1,), X.dtype)
    init = (theta0, theta0, deviance(theta0))
    (_, theta, _), _ = jax.lax.scan(step, init, None, length=iters)
    return theta[:d], theta[d]


_fit_glm_batch = jax.jit(
    jax.vmap(_fit_glm, in_axes=(None, None, 0, 0, 0, 0)))


def _glm_mean(margin, fam):
    mu_log = jnp.exp(jnp.clip(margin, -30.0, 30.0))
    return jnp.where(fam == FAMILY_CODES["gaussian"], margin, mu_log)


class GeneralizedLinearRegressionFamily(ModelFamily):
    """reference OpGeneralizedLinearRegression (defaults: family
    {gaussian, poisson}, regParam per DefaultSelectorParams.Regularization)."""

    name = "OpGeneralizedLinearRegression"
    supports = frozenset({"regression"})

    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        return [{"family": f, "regParam": r}
                for f in ("gaussian", "poisson")
                for r in (0.001, 0.01, 0.1, 0.2)]

    def grid_to_arrays(self, grid: Sequence[Dict[str, Any]]) -> Dict[str, jnp.ndarray]:
        coded = []
        for g in grid:
            g = dict(g)
            famval = g.get("family", "gaussian")
            if isinstance(famval, str):
                g["family"] = FAMILY_CODES[famval]
            g.setdefault("variancePower", 1.5)
            coded.append(g)
        return super().grid_to_arrays(coded)

    def fit_batch(self, X, y, weights, grid, num_classes):
        fam = grid.get("family")
        if fam is None:
            fam = jnp.zeros_like(grid["regParam"])
        vp = grid.get("variancePower")
        if vp is None:
            vp = jnp.full_like(fam, 1.5)
        coef, bias = _fit_glm_batch(X, y, weights, grid["regParam"], fam, vp)
        return {"coef": coef, "bias": bias, "family": fam}

    def predict_batch(self, params, X, num_classes):
        margin = jnp.einsum("bd,nd->bn", params["coef"], X, precision=_PREC) \
            + params["bias"][:, None]
        return _glm_mean(margin, params["family"][:, None])

    def predict_parts(self, fitted: FittedParams, X):
        margin = X @ jnp.asarray(fitted.params["coef"]) + fitted.params["bias"]
        pred = _glm_mean(margin, jnp.asarray(fitted.params["family"]))
        return {"prediction": pred}

    def predict_one(self, fitted: FittedParams, X) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v)
                for k, v in self.predict_parts(fitted, X).items()}


register_family(GeneralizedLinearRegressionFamily())
