from .api import ModelFamily, FittedParams, MODEL_REGISTRY, register_family
from . import linear  # noqa: F401  (registers linear families)
from . import mlp  # noqa: F401  (registers the MLP family)
