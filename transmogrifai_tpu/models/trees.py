"""Tree model families: decision tree, random forest, gradient-boosted trees.

TPU-native replacement for the reference's SparkML tree wrappers and for its
XGBoost JNI dependency (reference: core/.../impl/classification/
OpDecisionTreeClassifier.scala, OpRandomForestClassifier.scala,
OpGBTClassifier.scala, OpXGBoostClassifier.scala and the impl/regression
variants; XGBoost native core per SURVEY §2.9).

Design — TPU-first, not a port of either Spark's RDD tree builder or
XGBoost's C++:

* **Histogram growth** (the XGBoost-hist / LightGBM algorithm): features are
  quantile-binned once into int32 bins (n_bins=32 — Spark's maxBins default);
  each tree level's split search is a (nodes, features, bins, stats)
  histogram, a cumsum over bins, and an argmax — all static shapes, all on
  device, no per-node host control flow.
* **MXU histograms, no scatters**: split search runs on a deterministic
  strided row sample (≤ _HIST_SAMPLE rows, weights rescaled by n/S — the
  XGBoost 'approx'/GOSS design point: split thresholds are order-statistic
  estimates and converge long before 65k rows), and each level's histogram
  is ONE matmul — (nodes⊗stats)ᵀ expanded against the int32 bin codes by
  the fused pallas kernel (ops/tree_hist.py): the bin one-hot is built
  tile-by-tile in VMEM and never reaches HBM. Routing between levels is a
  *feature-select matmul*: the split feature's bin code is gathered by a
  (d, nodes) one-hot matmul and compared against the bin threshold —
  1/n_bins-th the FLOPs of a comparison-bit contraction.
* **Leaf statistics**: during the CV sweep, leaf values come from the
  split-search sample the grower already routed (free — a segment-sum of
  the sample's final node ids via the histogram kernel); the sweep only
  needs them to *score validation rows*, and the winner is refit with
  ``sweep=False`` where the FULL dataset is routed down the grown trees by
  the fused descent kernel (ops/forest.py) for EXACT served leaf values.
  Scatter-free end to end, so the whole builder tiles onto the MXU and
  scales to millions of rows.
* **Complete-heap trees of static depth**: arrays feat/thresh/leaf. A node
  that stops early keeps threshold +inf so every row routes left — training
  and serving follow identical routing with zero dynamic shapes. Empty
  descendant leaves are unreachable by construction.
* **The sweep**: hyperparameter × fold configurations run in
  ``_CFG_CHUNK_ELEMS``-bounded tree-batched chunks (one wide histogram
  matmul per tree level for the whole chunk) under an outer ``lax.map``;
  CV folds are 0/1 row weights exactly like the linear families.
* Binned routing and raw-value routing agree exactly: bin(x) = #{edges < x},
  so (bin > b) ⇔ (x > edges[b]) even with tied edges.
"""
from __future__ import annotations

import logging

from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.fidelity import ROUND4_SWEEP_HIST_SAMPLE, round4_defaults

logger = logging.getLogger(__name__)

from ..ops.forest import (
    forest_leaf_sums, forest_leaf_sums_chain, forest_predict,
    forest_predict_chain,
)
from ..histeng import build_hist, build_node_hist, pinned_row_sum
from .api import FittedParams, ModelFamily, register_family

N_BINS = 32  # Spark maxBins default (reference DefaultSelectorParams.MaxBin)

#: split-search sample cap: histograms are built from at most this many
#: evenly-strided rows (weights rescaled by n/S so count-based stopping
#: criteria keep full-data semantics); served leaf values use ALL rows
#: (exact refit pass), sweep-time leaf values use the sample.
_HIST_SAMPLE = 65536

#: sweep-time sample cap: CV candidates grow from a fraction of the refit
#: sample — split thresholds are order statistics and the CV ranking is
#: robust to the extra estimator noise (measured: docs/benchmarks.md "Sweep
#: fidelity", re-run for this value); the refit winner regrows at
#: _HIST_SAMPLE. Round 3 used 32768, round 4 16384; each halving halves
#: every growth histogram's rows for the depth-12 default grids
_SWEEP_HIST_SAMPLE = 8192

#: sweep-time ensemble caps: CV candidates RANK with this many RF trees /
#: GBT boosting rounds — the metric is an ensemble-size-consistent estimate
#: (every config gets the same cap), the winner refits at its full
#: numTrees/maxIter through fit_batch(sweep=False). Same contract as the
#: split-search sample above; fidelity-gated by docs/experiments/
#: fidelity_1m64.py ("Sweep fidelity" in docs/benchmarks.md)
_SWEEP_RF_TREES = 16
_SWEEP_GBT_ROUNDS = 12


def _sweep_hist_sample() -> int:
    """Sweep-time split-search sample rows; TG_SWEEP_FIDELITY=round4
    restores the round-4 value (utils/fidelity.py)."""
    return ROUND4_SWEEP_HIST_SAMPLE if round4_defaults() else _SWEEP_HIST_SAMPLE


def _sweep_ensemble_cap(vals: np.ndarray, cap: int,
                        param: str) -> Optional[np.ndarray]:
    """Rank-consistent sweep-time ensemble capping.

    All configs equal (the default grids): clamp uniformly to ``cap`` — the
    CV estimate stays ensemble-size-consistent because every candidate gets
    the same budget. Distinct values (a custom grid sweeping ensemble size):
    a uniform clamp would fit every above-cap config byte-identically and
    selection among them would silently degenerate to grid order, so the
    sizes scale PROPORTIONALLY (max → cap, floor 1) instead, preserving the
    grid's relative budgets; the warning flags that ranking across ensemble
    sizes is then an approximation. Returns the capped per-config values, or
    None when no cap applies (all values ≤ cap, or round-4 fidelity
    defaults disable sweep caps)."""
    if round4_defaults():
        return None
    vals = np.asarray(vals, dtype=np.float64)
    vmax = float(vals.max())
    if vmax <= cap:
        return None
    if np.unique(vals).size == 1:
        return np.minimum(vals, float(cap))
    scaled = np.maximum(1.0, np.round(vals * (cap / vmax)))
    logger.warning(
        "custom grid sweeps %s over distinct values %s above the sweep "
        "ranking cap %d; candidates rank with proportionally scaled "
        "ensembles %s (a uniform cap would make them byte-identical and "
        "unrankable) and the winner refits at its full %s — an "
        "approximation when ranking across ensemble sizes. Set "
        "TG_SWEEP_FIDELITY=round4 to disable sweep ensemble caps.",
        param, sorted(set(vals.tolist())), cap,
        sorted(set(scaled.tolist())), param)
    return scaled

#: config-chunk sizing: batch configurations together until the deepest
#: level's (sample rows x configs x trees x nodes) transient reaches this
#: element budget (~2 GB bf16), then lax.map over chunks — halving the
#: sweep sample therefore doubles the configs per chunk
_CFG_CHUNK_ELEMS = 1 << 30

#: trees per fused-descent call (ops/forest.py pallas cap)
_PREDICT_TREE_CHUNK = 128

#: chain-grower sibling subtraction pays off only for wide tree batches
#: (see the measurement note in _grow_forest_capped); below this width the
#: per-level reconstruction overhead exceeds the saved contraction
_CHAIN_SIBLING_MIN_TB = 128

#: per-level histogram element budget (f32): bounds the (Tb·nodes, d,
#: n_bins, k) split-search pipeline — XLA keeps ~3-6 of these alive
#: through the cumsum/gain chain, so ~1 GB per tensor keeps peak HBM well
#: inside a 16 GB chip even with that multiplier
_LEVEL_HIST_ELEMS = 1 << 28


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------

def _quantile_edges(X: jnp.ndarray, n_bins: int) -> jnp.ndarray:
    """Per-feature quantile bin edges, shape (d, n_bins-1)."""
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return jnp.quantile(X, qs, axis=0).T.astype(X.dtype)


def _bin_features(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """bin(x) = #{edges < x} ∈ [0, n_bins-1], shape (n, d) int32.

    Computed as a sum of broadcast comparisons — one fused elementwise pass
    (TPU sorts/searchsorted are far slower than n_bins comparisons)."""
    return (X[:, :, None] > edges[None, :, :]).sum(axis=2, dtype=jnp.int32)


def _sample_rows(n: int, cap: int = _HIST_SAMPLE) -> np.ndarray:
    """Deterministic strided sample indices for split search (static)."""
    if n <= cap:
        return np.arange(n)
    return np.linspace(0, n - 1, cap).astype(np.int64)


def _exact_leaf_stats(codes: jnp.ndarray, feat_heaps: jnp.ndarray,
                      bin_heaps: jnp.ndarray, stats: jnp.ndarray,
                      w: jnp.ndarray, depth: int, n_bins: int):
    """EXACT full-data leaf statistics via the fused descent kernel
    (ops/forest.py): route every row down T trees and accumulate stat sums
    per (tree, leaf) without any (n, T·m) HBM intermediate. Returns
    (T, L, k) stat sums and (T, L) weight sums. f32 end to end — leaf
    values are served predictions and must not inherit bf16 rounding."""
    T = feat_heaps.shape[0]
    aug = jnp.concatenate([stats * w[:, None], w[:, None]], axis=1)
    parts = []
    for lo in range(0, T, _PREDICT_TREE_CHUNK):
        hi = min(lo + _PREDICT_TREE_CHUNK, T)
        parts.append(forest_leaf_sums(
            codes, feat_heaps[lo:hi], bin_heaps[lo:hi], aug,
            depth=depth, n_bins=n_bins))
    out = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
    return out[..., :-1], out[..., -1]


def _split_gain(SL, SR, total, cfg, mode: str):
    """Gain + validity for every candidate split.

    SL/SR: (m, d, n_bins-1, k) left/right stats; total: (m, k); cfg values
    are scalars (per-config growth under vmap) or (m,) arrays (the
    tree-batched grower, one entry per heap node).
    mode 'gh': stats = [grad, hess, count] — XGBoost-style Newton gain,
    normalized by parent count so min_info_gain is scale-free (matches the
    variance-impurity gain Spark compares against minInfoGain).
    mode 'counts': stats = per-class weighted counts — Gini gain.
    """
    def bc(v):  # broadcast a scalar or (m,) cfg entry over (m, d, nb-1)
        v = jnp.asarray(v)
        return v[:, None, None] if v.ndim == 1 else v

    if mode == "gh":
        lam_v = jnp.asarray(cfg["lam"])          # scalar or (m,)
        lam = bc(lam_v)
        GL, HL, CL = SL[..., 0], SL[..., 1], SL[..., 2]
        GR, HR, CR = SR[..., 0], SR[..., 1], SR[..., 2]
        GP, HP, CP = total[:, 0], total[:, 1], total[:, 2]

        def score(G, H, l):
            return G * G / (H + l + 1e-12)

        raw = (score(GL, HL, lam) + score(GR, HR, lam)
               - score(GP, HP, lam_v)[:, None, None])
        gain = raw / jnp.maximum(CP, 1.0)[:, None, None]
        mcw = bc(cfg["min_child_weight"])
        mi = jnp.maximum(bc(cfg["min_instances"]), 1e-6)
        valid = (CL >= mi) & (CR >= mi) & (HL >= mcw) & (HR >= mcw)
        return gain, valid
    # Gini (classification trees)
    wL = SL.sum(-1)
    wR = SR.sum(-1)
    wP = total.sum(-1)

    def gini(S, W):
        p = S / jnp.maximum(W, 1e-12)[..., None]
        return 1.0 - (p * p).sum(-1)

    impP = gini(total, wP)[:, None, None]
    wPn = jnp.maximum(wP, 1e-12)[:, None, None]
    gain = impP - (wL / wPn) * gini(SL, wL) - (wR / wPn) * gini(SR, wR)
    mi = jnp.maximum(bc(cfg["min_instances"]), 1e-6)
    valid = (wL >= mi) & (wR >= mi)
    return gain, valid


def _grow_tree(codes_s, edges, stats_s, w_s, feat_mask, cfg, *,
               depth: int, n_bins: int, mode: str):
    """Grow one complete-heap tree on the split-search sample.

    codes_s: (S, d) int32 bin codes (shared across trees/configs);
    stats_s: (S, k) per-row stat vector; w_s: (S,) row weights (folds ×
    bootstrap, pre-scaled by n/S); feat_mask: (d,) bool; cfg: traced scalars
    {max_depth, min_instances, min_info_gain, lam, min_child_weight}.

    Each level's histogram is ONE fused one-hot matmul — (node-one-hot ⊗
    weighted stats)ᵀ expanded against the bin codes (hist_matmul,
    ops/tree_hist.py; the bin one-hot never reaches HBM on the pallas path)
    — and sample routing is a plain-XLA feature-select matmul: a (d, m)
    one-hot of the chosen split features gathers each node's bin code for
    an elementwise threshold compare. Batches under vmap over trees/configs
    (GBT's per-round trees); the heavily-batched DT/RF sweeps use the
    tree-batched `_grow_forest` instead, whose flattened lane layout avoids
    the tiny-minor-dim arrays vmap produces here. Returns (feat_heap
    (2^D−1,), thresh_heap (2^D−1,), bin_heap (2^D−1,) int32 with sentinel
    n_bins for non-splits, node_s (S,) final sample leaf assignment).
    """
    S = codes_s.shape[0]
    d = feat_mask.shape[0]
    k = stats_s.shape[1]
    sw = (stats_s * w_s[:, None]).astype(jnp.bfloat16)      # (S, k)
    codes_f = codes_s.astype(jnp.bfloat16)  # bin codes < 256: exact in bf16
    feat_heap = jnp.zeros((2 ** depth - 1,), jnp.int32)
    thr_heap = jnp.full((2 ** depth - 1,), jnp.inf, dtype=jnp.float32)
    bin_heap = jnp.full((2 ** depth - 1,), n_bins, dtype=jnp.int32)
    node = jnp.zeros((S,), jnp.int32)
    # each level runs at its NATURAL node width m = 2^level (half the
    # padded-to-deepest FLOPs summed over levels); under vmap the batch axis
    # widens the histogram's stat columns, one kernel call per level for the
    # whole chunk
    for level in range(depth):
        m = 2 ** level
        n_oh = (node[:, None]
                == jnp.arange(m, dtype=jnp.int32)).astype(jnp.bfloat16)
        A = (n_oh[:, :, None] * sw[:, None, :]).reshape(S, m * k)
        hist = build_hist(codes_s, A, n_bins)
        hist = hist.reshape(m, k, d, n_bins).transpose(0, 2, 3, 1)
        cum = jnp.cumsum(hist, axis=2)
        total = cum[:, 0, -1, :]                      # (m, k) node totals
        SL = cum[:, :, :-1, :]                        # split "bin <= b"
        SR = total[:, None, None, :] - SL
        gain, valid = _split_gain(SL, SR, total, cfg, mode)
        valid = valid & feat_mask[None, :, None]
        gain = jnp.where(valid, gain, -jnp.inf)
        gflat = gain.reshape(m, d * (n_bins - 1))
        best = jnp.argmax(gflat, axis=1)
        bf = (best // (n_bins - 1)).astype(jnp.int32)
        bb = (best % (n_bins - 1)).astype(jnp.int32)
        bgain = jnp.take_along_axis(gflat, best[:, None], axis=1)[:, 0]
        active = jnp.asarray(level, jnp.float32) < cfg["max_depth"]
        do_split = active & jnp.isfinite(bgain) & (bgain > cfg["min_info_gain"])
        thr = jnp.where(do_split, edges[bf, bb], jnp.inf).astype(jnp.float32)
        feat_heap = feat_heap.at[m - 1: 2 * m - 1].set(
            jnp.where(do_split, bf, 0))
        thr_heap = thr_heap.at[m - 1: 2 * m - 1].set(thr)
        bb_eff = jnp.where(do_split, bb, n_bins)
        bin_heap = bin_heap.at[m - 1: 2 * m - 1].set(bb_eff)
        # feature-select routing: gather each node's split-feature code by a
        # (d, m) one-hot matmul, compare against the bin threshold (sentinel
        # n_bins ⇒ never greater ⇒ route left), pick the row's node via the
        # n_oh mask already built for the histogram
        f_sel = (jnp.where(do_split, bf, 0)[None, :]
                 == jnp.arange(d, dtype=jnp.int32)[:, None]
                 ).astype(jnp.bfloat16)                          # (d, m)
        code_sel = codes_f @ f_sel                               # (S, m)
        go_m = (code_sel > bb_eff.astype(jnp.bfloat16)
                ).astype(jnp.bfloat16)
        go = jnp.sum(go_m * n_oh, axis=1) > 0.5
        node = 2 * node + go.astype(jnp.int32)
    return feat_heap, thr_heap, bin_heap, node


def _grow_forest(codes_s, edges, sw_list, fmasks, cfg, *, depth: int,
                 n_bins: int, mode: str, return_leaf_stats: bool = False):
    """Grow Tb complete-heap trees AT ONCE on the split-search sample.

    The tree batch (configs × trees) lives flattened in the lane axis from
    end to end — every intermediate is (S, m·Tb)-shaped (j-major: lane =
    j·Tb + t) with a large minor dimension, because TPU arrays pad the
    minor-most dim to 128 lanes and a (S, Tb, k≈2) layout wastes 64× HBM
    (measured OOM under the vmapped per-tree grower). J-major keeps every
    per-tree group reduction a free (S, m, Tb) reshape + axis-1 sum.

    codes_s: (S, d) shared int32 bin codes; sw_list: k arrays (S, Tb) — the
    per-tree stat·rowweight products, one array per stat so no tiny-minor
    array ever exists; fmasks: (Tb, d) feature subsets; cfg: dict of (Tb,)
    per-tree scalars. Returns (feat (Tb,H), thresh (Tb,H), bins (Tb,H),
    node_s (S, Tb)); with ``return_leaf_stats`` also a (Tb, 2^depth, k)
    per-leaf stat-sum tensor read off the FINAL level's histogram — the
    chosen split's left cumsum is the left child's total and the right
    child is the node total minus it, so sweep-time leaf values cost no
    extra histogram pass (stopped nodes route everything left)."""
    S, d = codes_s.shape
    Tb = sw_list[0].shape[1]
    k = len(sw_list)
    codes_f = codes_s.astype(jnp.bfloat16)
    H = 2 ** depth - 1
    feat_heap = jnp.zeros((Tb, H), jnp.int32)
    thr_heap = jnp.full((Tb, H), jnp.inf, jnp.float32)
    bin_heap = jnp.full((Tb, H), n_bins, jnp.int32)
    node = jnp.zeros((S, Tb), jnp.int32)
    hist_prev = None
    # depth 0: one root leaf per tree, stats are the plain column sums
    leaf_stats = jnp.stack(
        [pinned_row_sum(s.astype(jnp.float32), axis=0) for s in sw_list],
        axis=-1)[:, None, :]                                # (Tb, 1, k)
    for level in range(depth):
        m = 2 ** level
        M = Tb * m
        # lane layout J-MAJOR: lane = j*Tb + t, i.e. a (S, M) array is a
        # no-copy reshape of (S, m, Tb) — the per-tree group sums in the
        # routing step become an axis-1 reduction over sublane groups
        # instead of a dense (S, M) @ (M, Tb) block-diagonal matmul.
        # Sibling subtraction (the LightGBM/XGBoost-hist trick): per-tree
        # row weights are constant across levels and a node's children
        # partition its rows exactly, so only the LEFT child of every node
        # needs a histogram — the right child is parent − left. Halves the
        # histogram matmul FLOPs and the A_cat HBM traffic at every level.
        if level == 0:
            # root: node == 0 everywhere, the one-hot is all-ones
            hist = build_node_hist(codes_s, node, sw_list, n_bins, n_nodes=1)
            hist = hist[:, 0].transpose(1, 2, 3, 0)
        else:
            h = m // 2
            # left children only (heap slot 2j), fused in VMEM
            # (node_hist_matmul stride=2); right = parent − left below
            hist_l = build_node_hist(codes_s, node, sw_list, n_bins,
                                     n_nodes=h, stride=2)
            hist_l = hist_l.reshape(k, h * Tb, d, n_bins
                                    ).transpose(1, 2, 3, 0)          # (h·Tb,…)
            hist_r = hist_prev - hist_l
            # interleave children j-major: row (2j'+parity)·Tb + t
            hist = jnp.stack(
                [hist_l.reshape(h, Tb, d, n_bins, k),
                 hist_r.reshape(h, Tb, d, n_bins, k)],
                axis=1).reshape(M, d, n_bins, k)
        hist_prev = hist
        cum = jnp.cumsum(hist, axis=2)
        total = cum[:, 0, -1, :]                       # (M, k) node totals
        SL = cum[:, :, :-1, :]
        SR = total[:, None, None, :] - SL
        cfg_m = {key: jnp.tile(v, m) for key, v in cfg.items()}
        gain, valid = _split_gain(SL, SR, total, cfg_m, mode)
        valid = valid & jnp.tile(fmasks, (m, 1))[:, :, None]
        gain = jnp.where(valid, gain, -jnp.inf)
        gflat = gain.reshape(M, d * (n_bins - 1))
        best = jnp.argmax(gflat, axis=1)
        bf = (best // (n_bins - 1)).astype(jnp.int32)
        bb = (best % (n_bins - 1)).astype(jnp.int32)
        bgain = jnp.take_along_axis(gflat, best[:, None], axis=1)[:, 0]
        active = jnp.asarray(level, jnp.float32) < jnp.tile(
            cfg["max_depth"], m)
        do_split = active & jnp.isfinite(bgain) & (bgain > cfg_m["min_info_gain"])
        bf_eff = jnp.where(do_split, bf, 0)
        bb_eff = jnp.where(do_split, bb, n_bins)
        thr = jnp.where(do_split, edges[bf, bb], jnp.inf).astype(jnp.float32)
        # j-major (M,) -> heap order (Tb, m)
        feat_heap = feat_heap.at[:, m - 1: 2 * m - 1].set(
            bf_eff.reshape(m, Tb).T)
        thr_heap = thr_heap.at[:, m - 1: 2 * m - 1].set(
            thr.reshape(m, Tb).T)
        bin_heap = bin_heap.at[:, m - 1: 2 * m - 1].set(
            bb_eff.reshape(m, Tb).T)
        # feature-select routing: gather each node's split-feature code by a
        # (d, M) one-hot matmul, compare against the bin threshold (sentinel
        # n_bins ⇒ route left), select the row's node via the j-major node
        # one-hot and reduce the j axis as an (S, m, Tb) sublane sum
        sel = (bf_eff[None, :] == jnp.arange(d, dtype=jnp.int32)[:, None]
               ).astype(jnp.bfloat16)                             # (d, M)
        code_sel = codes_f @ sel                                  # (S, M)
        go_lane = (code_sel > bb_eff.astype(jnp.bfloat16)
                   ).astype(jnp.bfloat16)
        j_all = jnp.arange(m, dtype=jnp.int32)[None, :, None]
        n_oh = (node[:, None, :] == j_all).astype(jnp.bfloat16)   # (S, m, Tb)
        go = (go_lane.reshape(S, m, Tb) * n_oh).sum(axis=1)       # (S, Tb)
        node = 2 * node + (go > jnp.bfloat16(0.5)).astype(jnp.int32)
        if return_leaf_stats and level == depth - 1:
            # leaf stats off this level's histogram: left child = chosen
            # split's left cumsum (node total when stopped), right = rest
            k_st = hist.shape[-1]
            SL_flat = SL.reshape(M, d * (n_bins - 1), k_st)
            left = jnp.take_along_axis(
                SL_flat, best[:, None, None], axis=1)[:, 0]       # (M, k)
            left = jnp.where(do_split[:, None], left, total)
            right = total - left
            # j-major rows (j·Tb + t) → (Tb, L=2m, k), leaf id = 2j + parity
            leaf_stats = jnp.stack(
                [left.reshape(m, Tb, k_st), right.reshape(m, Tb, k_st)],
                axis=1).transpose(2, 0, 1, 3).reshape(Tb, 2 * m, k_st)
    if return_leaf_stats:
        return feat_heap, thr_heap, bin_heap, node, leaf_stats
    return feat_heap, thr_heap, bin_heap, node


def _grow_forest_capped(codes_s, edges, sw_list, fmasks, cfg, *, depth: int,
                        n_bins: int, mode: str, n_slots: int):
    """Grow Tb slot-chain ("leaf budget") trees at once — arbitrary depth at
    a bounded per-level width.

    The complete-heap grower's per-level histogram is (2^level·Tb, d, nb, k),
    which caps practical depth at ~8; the reference's default grids sweep
    maxDepth 12 (DefaultSelectorParams.scala:37). Here each level holds at
    most ``n_slots`` live nodes: every valid candidate split is ranked by
    gain per tree and the top (budget) splits are performed — each split
    adds exactly one net slot, so ``n_slots`` is precisely a leaf budget
    (the XGBoost 'lossguide' / LightGBM num_leaves design point). Unsplit
    nodes carry forward as leaves (they keep competing at later levels, and
    re-lose deterministically once stopped — same rows ⇒ same gain). Slots
    are compact by construction: level l holds slots [0, n_live_t) with
    n_live ≤ min(2^l, n_slots).

    Emits per-level tables (Tb, depth, W): split feature, bin threshold
    (sentinel ``n_bins`` ⇒ route left), raw threshold, and the child base
    pointer — routing is ``slot' = base[slot] + go`` (ops/forest.py chain
    kernels). Returns (feat_lv, thr_lv, bin_lv, base_lv, node_s) with
    node_s (S, Tb) the final sample leaf slot in [0, min(2^depth, W))."""
    from ..ops.forest import _chain_widths, _check_slots
    _check_slots(n_slots)
    S, d = codes_s.shape
    Tb = sw_list[0].shape[1]
    k = len(sw_list)
    W = n_slots
    codes_f = codes_s.astype(jnp.bfloat16)
    feat_lv = jnp.zeros((Tb, depth, W), jnp.int32)
    thr_lv = jnp.full((Tb, depth, W), jnp.inf, jnp.float32)
    bin_lv = jnp.full((Tb, depth, W), n_bins, jnp.int32)
    base_lv = jnp.zeros((Tb, depth, W), jnp.int32)
    node = jnp.zeros((S, Tb), jnp.int32)          # slot at current level
    n_live = jnp.ones((Tb,), jnp.int32)
    widths = _chain_widths(depth, W)
    # sibling subtraction, chain edition (the heap grower's LightGBM trick
    # adapted to slot-chain trees): per-tree row weights are constant
    # across levels, so a freshly-computed histogram is only needed for
    # EVEN slots (node_hist_matmul stride=2 — halves the dominant
    # contraction). Odd slots reconstruct from the previous level: a right
    # child (its parent was kept, child base even) is parent − left
    # sibling; a carried slot landing on an odd position keeps its old
    # histogram verbatim. The (j_src, is_rchild) odd-slot inverse mapping
    # is built from the level's kept/carried/base tables; dead slots
    # (≥ n_live) may carry garbage but are masked out of every split
    # decision (`live`) and are never sourced by kept/carried.
    # MEASURED (v5e, S=16384, d=64, nb=32, W=64): wins only when the tree
    # batch is wide enough for the halved contraction to stay MXU-bound —
    # RF sweep chunks (Tb=500) −8%, GBT's Tb=54 boosting scan +17% (its
    # narrow per-step ops are latency-bound; the reconstruction's extra
    # gathers/stacks cost more than the saved FLOPs), hence the width gate.
    sibling = Tb >= _CHAIN_SIBLING_MIN_TB
    hist5_prev = None                 # (Wl_prev, Tb, d, nb, k) f32
    odd_map_prev = None               # (j_src (Wh_o, Tb), is_rchild)
    for level in range(depth):
        Wl = widths[level]
        Wn = widths[level + 1] if level + 1 < depth else min(2 ** depth, W)
        M = Wl * Tb
        # node-histogram contraction (histeng.build_node_hist):
        # XLA's pipelined A_cat contraction — a pallas kernel that expanded
        # the operand in VMEM measured slower at every production shape and
        # is retired to docs/experiments/node_hist_pallas.py
        if level == 0 or Wl % 2 or not sibling:
            hist5 = build_node_hist(codes_s, node, sw_list, n_bins,
                                    n_nodes=Wl).transpose(1, 2, 3, 4, 0)
        else:
            Wh = Wl // 2
            he5 = build_node_hist(codes_s, node, sw_list, n_bins,
                                  n_nodes=Wh, stride=2
                                  ).transpose(1, 2, 3, 4, 0)   # slot 2j'
            j_src, is_rch = odd_map_prev
            prev_flat = hist5_prev.reshape(
                hist5_prev.shape[0], Tb, d * n_bins * k)
            src = jnp.take_along_axis(
                prev_flat.transpose(1, 0, 2),             # (Tb, Wl_prev, ·)
                j_src.T[:, :, None].astype(jnp.int32), axis=1
            ).transpose(1, 0, 2).reshape(Wh, Tb, d, n_bins, k)
            odd5 = src - jnp.where(
                is_rch[:, :, None, None, None], he5,
                jnp.zeros_like(he5))
            hist5 = jnp.stack([he5, odd5], axis=1).reshape(
                Wl, Tb, d, n_bins, k)
        hist5_prev = hist5
        hist = hist5.reshape(M, d, n_bins, k)
        cum = jnp.cumsum(hist, axis=2)
        total = cum[:, 0, -1, :]                       # (M, k) node totals
        SL = cum[:, :, :-1, :]
        SR = total[:, None, None, :] - SL
        cfg_m = {key: jnp.tile(v, Wl) for key, v in cfg.items()}
        gain, valid = _split_gain(SL, SR, total, cfg_m, mode)
        valid = valid & jnp.tile(fmasks, (Wl, 1))[:, :, None]
        gain = jnp.where(valid, gain, -jnp.inf)
        gflat = gain.reshape(M, d * (n_bins - 1))
        best = jnp.argmax(gflat, axis=1)
        bf = (best // (n_bins - 1)).astype(jnp.int32)
        bb = (best % (n_bins - 1)).astype(jnp.int32)
        bgain = jnp.take_along_axis(gflat, best[:, None], axis=1)[:, 0]
        active = jnp.asarray(level, jnp.float32) < jnp.tile(
            cfg["max_depth"], Wl)
        cand = active & jnp.isfinite(bgain) & (bgain > cfg_m["min_info_gain"])
        # live slots are [0, n_live) per tree; dead lanes must not split
        j_2d = jnp.arange(Wl, dtype=jnp.int32)[:, None]          # (Wl, 1)
        live = j_2d < n_live[None, :]                            # (Wl, Tb)
        cand_2d = cand.reshape(Wl, Tb) & live
        # leaf-budget cap: each split adds one net slot, so at most
        # q = W_next − n_live splits may run this level; keep the q best
        # by gain. Rank by COUNTING dominating slots — a (Wl, Wl, Tb)
        # comparison reduction — instead of a double argsort: XLA's sort
        # costs ~ms per call at these shapes while the count is one
        # elementwise pass; ties break by slot index ascending, identical
        # to a stable descending argsort
        key = jnp.where(cand_2d, bgain.reshape(Wl, Tb), -jnp.inf)
        k_i = key[:, None, :]                                    # (Wl,1,Tb)
        k_j = key[None, :, :]                                    # (1,Wl,Tb)
        j_lt_i = (jnp.arange(Wl)[None, :, None]
                  < jnp.arange(Wl)[:, None, None])
        dominates = (k_j > k_i) | ((k_j == k_i) & j_lt_i)
        rank = dominates.sum(axis=1).astype(jnp.int32)           # (Wl, Tb)
        q = jnp.maximum(Wn - n_live, 0)[None, :]
        kept = cand_2d & (rank < q)
        n_split = kept.sum(axis=0).astype(jnp.int32)             # (Tb,)
        # child base for kept splits: 2·gain-rank (kept ⊆ top-q candidates,
        # so their candidate rank IS their split rank); carried live slots
        # land after the children in slot order
        carried = live & ~kept
        c_rank = jnp.cumsum(carried.astype(jnp.int32), axis=0) - 1
        base_2d = jnp.where(
            kept, 2 * rank,
            jnp.where(carried, 2 * n_split[None, :] + c_rank, 0))
        if sibling and level + 1 < depth and widths[level + 1] % 2 == 0:
            # odd-slot inverse map for the next level's sibling
            # subtraction: odd slot i sources prev slot j where either j
            # was kept and its right child landed at i (base+1 == i), or
            # j carried onto i (base == i). Targets are unique, so the
            # one-hot · j sum IS the inverse permutation.
            wh_n = widths[level + 1] // 2
            i_odd = (1 + 2 * jnp.arange(wh_n, dtype=jnp.int32)
                     )[None, :, None]                       # (1, wh_n, 1)
            oh_r = (jnp.where(kept, base_2d + 1, -1)[:, None, :]
                    == i_odd)                               # (Wl, wh_n, Tb)
            oh_c = (jnp.where(carried, base_2d, -1)[:, None, :]
                    == i_odd)
            j_idx = jnp.arange(Wl, dtype=jnp.int32)[:, None, None]
            odd_map_prev = (((oh_r | oh_c) * j_idx).sum(axis=0),
                            oh_r.any(axis=0))               # (wh_n, Tb) ×2
        kept_f = kept.reshape(M)
        bf_eff = jnp.where(kept_f, bf, 0)
        bb_eff = jnp.where(kept_f, bb, n_bins)
        thr = jnp.where(kept_f, edges[bf, bb], jnp.inf).astype(jnp.float32)
        # j-major (M,) → (Tb, Wl) table rows
        feat_lv = feat_lv.at[:, level, :Wl].set(bf_eff.reshape(Wl, Tb).T)
        thr_lv = thr_lv.at[:, level, :Wl].set(thr.reshape(Wl, Tb).T)
        bin_lv = bin_lv.at[:, level, :Wl].set(bb_eff.reshape(Wl, Tb).T)
        base_lv = base_lv.at[:, level, :Wl].set(base_2d.T)
        # route: slot' = base[slot] + go (sentinel bin ⇒ go 0); base ≤ W−1
        # and W ≤ 256, so the bf16 lane accumulation is exact
        sel = (bf_eff[None, :] == jnp.arange(d, dtype=jnp.int32)[:, None]
               ).astype(jnp.bfloat16)                             # (d, M)
        code_sel = codes_f @ sel                                  # (S, M)
        go_lane = (code_sel > bb_eff.astype(jnp.bfloat16)
                   ).astype(jnp.bfloat16)
        val_lane = go_lane + base_2d.reshape(M).astype(jnp.bfloat16)[None, :]
        j_all = jnp.arange(Wl, dtype=jnp.int32)[None, :, None]
        n_oh = (node[:, None, :] == j_all).astype(jnp.bfloat16)   # (S, Wl, Tb)
        nxt = (val_lane.reshape(S, Wl, Tb) * n_oh).sum(axis=1)    # (S, Tb)
        node = jnp.round(nxt.astype(jnp.float32)).astype(jnp.int32)
        n_live = n_live + n_split
    return feat_lv, thr_lv, bin_lv, base_lv, node


_DIAG_BLOCK = 64


def _diag_leaf_hist(node_s: jnp.ndarray, A_cols: jnp.ndarray,
                    L: int) -> jnp.ndarray:
    """out[j, t, l] = Σ_s A_cols[s, j, t]·1[node_s[s, t] == l] — per-tree
    segment-sums through the histogram kernel (trees as 'features', leaves
    as 'bins'), diagonal extracted. ``A_cols``: (S, Tb) for one stat — or
    (S, J, Tb) to reduce J stats against the same trees in ONE kernel call
    (GBT's G and H sums). Blocked in groups of _DIAG_BLOCK trees so the
    cross-tree waste stays a constant factor (full-width would be quadratic
    in the tree count)."""
    squeeze = A_cols.ndim == 2
    if squeeze:
        A_cols = A_cols[:, None, :]
    S, J, Tb = A_cols.shape
    g = _DIAG_BLOCK
    Tp = -(-Tb // g) * g
    if Tp != Tb:  # sentinel code L matches no leaf; zero stat columns
        node_s = jnp.pad(node_s, ((0, 0), (0, Tp - Tb)), constant_values=L)
        A_cols = jnp.pad(A_cols, ((0, 0), (0, 0), (0, Tp - Tb)))
    outs = []
    for lo in range(0, Tp, g):
        blk = A_cols[:, :, lo:lo + g].reshape(S, J * g)     # stat-major rows
        full = build_hist(node_s[:, lo:lo + g], blk, L,
                          exact=True)                      # (J*g, g*L)
        full = full.reshape(J, g, g, L)
        outs.append(full[:, jnp.arange(g), jnp.arange(g)])  # (J, g, L)
    out = jnp.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]
    out = out[:, :Tb]
    return out[0] if squeeze else out


# ---------------------------------------------------------------------------
# Batched fit drivers (chunked vmap over configurations)
# ---------------------------------------------------------------------------


def _class_leaf(leaf_stats, leaf_w):
    """Per-leaf class probabilities from weighted counts."""
    tot = jnp.maximum(leaf_stats.sum(-1, keepdims=True), 1e-12)
    return leaf_stats / tot


def _mean_leaf(leaf_stats, leaf_w):
    """gh-mode with g=-y, h=1: Newton leaf -G/H = weighted mean of y."""
    return -leaf_stats[:, 0] / jnp.maximum(leaf_stats[:, 1], 1e-12)


def _make_stats(y, num_classes: int, task: str):
    if task == "classification":
        return jax.nn.one_hot(y.astype(jnp.int32), num_classes,
                              dtype=jnp.float32), "counts"
    ones = jnp.ones_like(y)
    return jnp.stack([-y, ones, ones], axis=1), "gh"


def _prep_tree_inputs(X, y, n_bins, num_classes, task, full_bin=True,
                      sweep=False):
    """Shared per-fit prep: sampled edges, full + sampled int32 bin codes
    (the operands of the fused histogram/routing kernels), per-row stats,
    and the n/S weight rescale. ``full_bin`` skips binning the full dataset
    for fits that never touch it (GBT trains entirely on the sample).
    ``sweep`` halves the split-search sample (_SWEEP_HIST_SAMPLE)."""
    n = X.shape[0]
    samp = jnp.asarray(_sample_rows(
        n, _sweep_hist_sample() if sweep else _HIST_SAMPLE))
    Xs = X[samp]
    edges = _quantile_edges(Xs, n_bins)
    if full_bin:
        binned = _bin_features(X, edges)
        binned_s = binned[samp]
    else:
        binned = None
        binned_s = _bin_features(Xs, edges)
    stats, mode = _make_stats(y, num_classes, task)
    w_scale = jnp.asarray(n / samp.shape[0], X.dtype)
    return samp, edges, binned, binned_s, stats, mode, w_scale


def _exact_leaf_stats_chain(codes, feat_lv, bin_lv, base_lv, stats,
                            w: jnp.ndarray, n_bins: int):
    """Chain-format analog of :func:`_exact_leaf_stats` (full-data leaf
    sums via the fused chain descent kernel, f32 end to end)."""
    aug = jnp.concatenate([stats * w[:, None], w[:, None]], axis=1)
    out = forest_leaf_sums_chain(codes, feat_lv, bin_lv, base_lv, aug,
                                 n_bins=n_bins)
    return out[..., :-1], out[..., -1]


@partial(jax.jit, static_argnames=("depth", "n_bins", "num_classes", "task",
                                   "sweep", "n_slots"))
def _fit_dt_batch(X, y, weights, max_depth, min_inst, min_gain, *,
                  depth, n_bins, num_classes, task, sweep=False, n_slots=0):
    d = X.shape[1]
    B = weights.shape[0]
    samp, edges, binned, binned_s, stats, mode, w_scale = \
        _prep_tree_inputs(X, y, n_bins, num_classes, task,
                          full_bin=not sweep, sweep=sweep)
    stats_s = stats[samp]                                   # (S, k)
    k = stats.shape[1]
    deep = n_slots > 0
    L = min(2 ** depth, n_slots) if deep else 2 ** depth
    lane_w = (min(2 ** (depth - 1), n_slots) * k if deep
              else 2 ** (depth - 1))
    cb = max(1, min(B, _CFG_CHUNK_ELEMS // (binned_s.shape[0] * lane_w)))

    def one_chunk(w_c, md, mi, mg):
        """Grow cb single-tree configs in one tree-batched forest call."""
        w_bs = w_c[:, samp].T * w_scale                     # (S, cb)
        sw_list = [stats_s[:, k_i][:, None] * w_bs
                   for k_i in range(stats_s.shape[1])]
        cfg = {"max_depth": md, "min_instances": mi, "min_info_gain": mg,
               "lam": jnp.full((cb,), 1e-6, jnp.float32),
               "min_child_weight": jnp.zeros((cb,), jnp.float32)}
        if deep:
            fs, ths, bhs, abs_, node_s = _grow_forest_capped(
                binned_s, edges, sw_list, jnp.ones((cb, d), bool), cfg,
                depth=depth, n_bins=n_bins, mode=mode, n_slots=n_slots)
        else:
            fs, ths, bhs, node_s = _grow_forest(
                binned_s, edges, sw_list, jnp.ones((cb, d), bool), cfg,
                depth=depth, n_bins=n_bins, mode=mode)
            abs_ = jnp.zeros((cb, 0), jnp.int32)
        if sweep:  # sample leaf stats (validation scoring only)
            aug_cols = sw_list + [w_bs]
            sums = jnp.stack(
                [_diag_leaf_hist(node_s, c.astype(jnp.float32), L)
                 for c in aug_cols], axis=-1)               # (cb, L, k+1)
            ls, lw = sums[..., :-1], sums[..., -1]
            leaf_c = (jax.vmap(_class_leaf)(ls, lw)
                      if task == "classification"
                      else jax.vmap(_mean_leaf)(ls, lw)[:, :, None])
        else:
            leaf_c = jnp.zeros(
                (cb, L, stats.shape[1] if task == "classification" else 1),
                jnp.float32)
        return fs, ths, bhs, abs_, leaf_c

    n_chunks = -(-B // cb)
    B_pad = n_chunks * cb
    args = (weights, max_depth, min_inst, min_gain)
    if B_pad != B:
        idx = jnp.arange(B_pad) % B
        args = jax.tree_util.tree_map(lambda a: a[idx], args)
    args = jax.tree_util.tree_map(
        lambda a: a.reshape((n_chunks, cb) + a.shape[1:]), args)
    feat, thr, bheap, bases, leaf = jax.lax.map(
        lambda ch: one_chunk(*ch), args)
    feat, thr, bheap, bases, leaf = jax.tree_util.tree_map(
        lambda a: a.reshape((B_pad,) + a.shape[2:])[:B],
        (feat, thr, bheap, bases, leaf))

    if not sweep:  # EXACT full-data leaf stats via the fused descent kernel
        def leaf_one(args):
            if deep:
                f, bh, ab, w = args
                ls, lw = _exact_leaf_stats_chain(
                    binned, f[None], bh[None], ab[None], stats, w, n_bins)
            else:
                f, bh, w = args
                ls, lw = _exact_leaf_stats(binned, f[None], bh[None], stats,
                                           w, depth, n_bins)
            return (_class_leaf(ls[0], lw[0]) if task == "classification"
                    else _mean_leaf(ls[0], lw[0])[:, None])

        leaf = jax.lax.map(
            leaf_one, ((feat, bheap, bases, weights) if deep
                       else (feat, bheap, weights)))
    if deep:
        return {"feat_lv": feat, "thresh_lv": thr, "bins_lv": bheap,
                "base_lv": bases, "leaf": leaf, "edges": edges}
    return {"feat": feat, "thresh": thr, "bins": bheap, "leaf": leaf,
            "edges": edges}


@partial(jax.jit, static_argnames=("depth", "n_bins", "num_classes", "task",
                                   "n_trees", "sweep", "n_slots"))
def _fit_rf_batch(X, y, weights, max_depth, min_inst, min_gain, num_trees,
                  subsample, seeds, *, depth, n_bins, num_classes, task,
                  n_trees, sweep=False, n_slots=0):
    n, d = X.shape
    samp, edges, binned, binned_s, stats, mode, w_scale = \
        _prep_tree_inputs(X, y, n_bins, num_classes, task,
                          full_bin=not sweep, sweep=sweep)
    # per-tree feature subset (Spark featureSubsetStrategy auto:
    # sqrt for classification, 1/3 for regression)
    p_feat = float(np.ceil(np.sqrt(d)) / d) if task == "classification" \
        else max(1.0 / 3.0, 1.0 / d)
    S = binned_s.shape[0]
    k = stats.shape[1]
    stats_s = stats[samp]
    deep = n_slots > 0
    L = min(2 ** depth, n_slots) if deep else 2 ** depth
    B = weights.shape[0]
    # chunk budget covers BOTH the grower's bf16 (S, Tb·nodes) transients
    # and the sweep leaf-stat path's f32 (S, k+1, Tb) A_cols tensor (f32
    # counts double in the bf16-element budget); the capped grower's level
    # width is n_slots·k (no sibling subtraction, k stat planes per slot)
    lane_w = (min(2 ** (depth - 1), n_slots) * k if deep
              else 2 ** (depth - 1))
    cb = max(1, min(B, _CFG_CHUNK_ELEMS
                    // (S * n_trees * max(lane_w, 2 * (k + 1)))))
    # ...AND the per-level histogram/gain pipeline, whose (Tb·nodes, d,
    # n_bins, k) f32 tensors scale with the FEATURE count, not the sample:
    # at small S the first bound lets whole wide grids through, and a
    # 600-column text-hashed vector at depth 12 then asks for >25 GB of
    # HBM (seen on the Titanic pipeline; XLA holds several of these
    # alive across the cumsum/gain chain)
    nodes_w = min(2 ** depth, n_slots) if deep else 2 ** (depth - 1)
    cb = max(1, min(cb, _LEVEL_HIST_ELEMS
                    // (n_trees * nodes_w * d * n_bins * k)))

    def one_chunk(w_c, md, mi, mg, ss, seed):
        """Grow a chunk of cb configs — cb·n_trees trees — in one
        tree-batched forest call. Leading axes here are (cb,)."""
        Tb = cb * n_trees
        w_s = w_c[:, samp] * w_scale                        # (cb, S)

        def boots_one(seed_c, ss_c):
            base = jax.random.PRNGKey(seed_c.astype(jnp.uint32))
            # Poisson(ss) bootstrap weights by inverse-CDF over uniforms,
            # truncated at 7 (P[X>7 | lam<=1] < 1e-6) — 3x cheaper than
            # jax.random.poisson's rejection sampling at these volumes
            ks = jnp.arange(8, dtype=jnp.float32)
            lam = jnp.maximum(ss_c.astype(jnp.float32), 1e-12)
            log_pmf = (-lam + ks * jnp.log(lam)
                       - jax.scipy.special.gammaln(ks + 1.0))
            cdf = jnp.cumsum(jnp.exp(log_pmf))

            def per_tree(t):
                k1, k2 = jax.random.split(jax.random.fold_in(base, t))
                u = jax.random.uniform(k1, (S,))
                boot = (u[:, None] > cdf[None, :]).sum(-1).astype(X.dtype)
                fmask = jax.random.bernoulli(k2, p_feat, (d,))
                return boot, fmask

            return jax.vmap(per_tree)(jnp.arange(n_trees))

        boots, fmasks = jax.vmap(boots_one)(seed, ss)   # (cb,T,S) (cb,T,d)
        # per-tree row weight = config fold weight x bootstrap; flatten the
        # (config, tree) axes into the lane dim: t-major lane = c*T + t
        w_ts = (w_s[:, None, :] * boots).reshape(Tb, S).T   # (S, Tb)
        sw_list = [stats_s[:, k_i][:, None] * w_ts for k_i in range(k)]
        cfg = {"max_depth": jnp.repeat(md, n_trees),
               "min_instances": jnp.repeat(mi, n_trees),
               "min_info_gain": jnp.repeat(mg, n_trees),
               "lam": jnp.full((Tb,), 1e-6, jnp.float32),
               "min_child_weight": jnp.zeros((Tb,), jnp.float32)}
        if deep:
            fs, ths, bhs, abs_, node_s = _grow_forest_capped(
                binned_s, edges, sw_list, fmasks.reshape(Tb, d), cfg,
                depth=depth, n_bins=n_bins, mode=mode, n_slots=n_slots)
        else:
            fs, ths, bhs, node_s = _grow_forest(
                binned_s, edges, sw_list, fmasks.reshape(Tb, d), cfg,
                depth=depth, n_bins=n_bins, mode=mode)
            abs_ = jnp.zeros((Tb, 0), jnp.int32)

        if sweep:
            # sample leaf stats for the WHOLE chunk in one blocked
            # segment-sum: per-tree stat columns A[s, j, t] = stat_j(s) ·
            # w_{config(t)}(s), reduced by _diag_leaf_hist's 64-tree blocks
            # — replaces cb separate per-config histogram dispatches
            # (~100ms/chunk of launch overhead at cb=20)
            w_ts = jnp.repeat(w_s, n_trees, axis=0).T        # (S, Tb)
            stats_aug = jnp.concatenate(
                [stats_s, jnp.ones((S, 1), stats_s.dtype)], axis=1)
            A_cols = stats_aug[:, :, None] * w_ts[:, None, :]  # (S, k+1, Tb)
            sums = _diag_leaf_hist(node_s, A_cols.astype(jnp.float32), L)
            sums = sums.transpose(1, 2, 0)                   # (Tb, L, k+1)
            ls, lw = sums[..., :-1], sums[..., -1]
            leaf_flat = (jax.vmap(_class_leaf)(ls, lw)
                         if task == "classification"
                         else jax.vmap(_mean_leaf)(ls, lw)[:, :, None])
            leaf_c = leaf_flat.reshape((cb, n_trees) + leaf_flat.shape[1:])
        else:
            leaf_c = jnp.zeros(
                (cb, n_trees, L, k if task == "classification" else 1),
                jnp.float32)
        tail = fs.shape[1:]
        return (fs.reshape((cb, n_trees) + tail),
                ths.reshape((cb, n_trees) + tail),
                bhs.reshape((cb, n_trees) + tail),
                abs_.reshape((cb, n_trees) + abs_.shape[1:]), leaf_c)

    n_chunks = -(-B // cb)
    B_pad = n_chunks * cb
    args = (weights, max_depth, min_inst, min_gain, subsample, seeds)
    if B_pad != B:
        idx = jnp.arange(B_pad) % B
        args = jax.tree_util.tree_map(lambda a: a[idx], args)
    args = jax.tree_util.tree_map(
        lambda a: a.reshape((n_chunks, cb) + a.shape[1:]), args)
    feat, thr, bheap, bases, leaf = jax.lax.map(
        lambda ch: one_chunk(*ch), args)
    feat, thr, bheap, bases, leaf = jax.tree_util.tree_map(
        lambda a: a.reshape((B_pad,) + a.shape[2:])[:B],
        (feat, thr, bheap, bases, leaf))

    if not sweep:
        # EXACT full-data leaf stats per config (fused descent kernel is a
        # pallas call — sequential per config, outside the batched grower)
        def leaf_one(args):
            if deep:
                f, bh, ab, w = args
                ls, lw = _exact_leaf_stats_chain(binned, f, bh, ab, stats,
                                                 w, n_bins)
            else:
                f, bh, w = args
                ls, lw = _exact_leaf_stats(binned, f, bh, stats, w, depth,
                                           n_bins)
            return (jax.vmap(_class_leaf)(ls, lw)
                    if task == "classification"
                    else jax.vmap(_mean_leaf)(ls, lw)[:, :, None])

        leaf = jax.lax.map(
            leaf_one, ((feat, bheap, bases, weights) if deep
                       else (feat, bheap, weights)))
    tree_mask = (jnp.arange(n_trees)[None, :] <
                 num_trees[:, None]).astype(jnp.float32)
    if deep:
        return {"feat_lv": feat, "thresh_lv": thr, "bins_lv": bheap,
                "base_lv": bases, "leaf": leaf, "tree_mask": tree_mask,
                "edges": edges}
    return {"feat": feat, "thresh": thr, "bins": bheap, "leaf": leaf,
            "tree_mask": tree_mask,
            "edges": edges}


@partial(jax.jit, static_argnames=("depth", "n_bins", "num_classes", "task",
                                   "n_rounds", "sweep", "n_slots"))
def _fit_gbt_batch(X, y, weights, max_depth, min_inst, min_gain, max_iter,
                   step_size, lam, min_child_weight, *, depth, n_bins,
                   num_classes, task, n_rounds, sweep=False, n_slots=0):
    """Gradient boosting: binary logistic / regression squared / multiclass
    softmax. Each round grows ONE tree-batched forest over all configs ×
    classes (`_grow_forest`) — the per-round hist/route ops are Tb-wide
    instead of |configs| narrow vmapped copies."""
    n, d = X.shape
    samp, edges, _, binned_s, _, _, w_scale = \
        _prep_tree_inputs(X, y, n_bins, num_classes, "regression",
                          full_bin=False, sweep=sweep)
    C = num_classes if task == "multiclass" else 1
    B = weights.shape[0]
    S = binned_s.shape[0]
    deep = n_slots > 0
    L = min(2 ** depth, n_slots) if deep else 2 ** depth
    Tb = B * C                                             # trees per round
    y_s = y[samp]
    Y1_s = (jax.nn.one_hot(y_s.astype(jnp.int32), max(C, 2), dtype=X.dtype)
            if task == "multiclass" else None)
    W_s = weights[:, samp] * w_scale                       # (B, S)
    # per-tree (config, class) row weights / cfg: lane order t = b*C + c
    w_tb = jnp.repeat(W_s, C, axis=0).T                    # (S, Tb)
    rep = lambda v: jnp.repeat(v, C)                       # (B,) -> (Tb,)
    cfg = {"max_depth": rep(max_depth), "min_instances": rep(min_inst),
           "min_info_gain": rep(min_gain), "lam": rep(lam),
           "min_child_weight": rep(min_child_weight)}
    lam_t = rep(lam)
    fmasks = jnp.ones((Tb, d), bool)
    # boosting state lives on the split-search sample: gradients, F and leaf
    # values all come from it (the XGBoost subsample design point); at 65k
    # rows and ≥2^depth≥8 leaves every leaf still averages 1000+ rows
    if task == "regression":
        # pinned row sums: f0 must stay bit-identical when rows shard over
        # the mesh 'data' axis (docs/trees.md, "Determinism")
        f0 = (pinned_row_sum(weights * y[None, :], axis=1)
              / jnp.maximum(pinned_row_sum(weights, axis=1), 1.0))[:, None]
    else:
        f0 = jnp.zeros((B, C), X.dtype)
    F_init = jnp.broadcast_to(f0[:, :, None], (B, C, S))

    def round_step(F, t):                                   # F: (B, C, S)
        if task == "binary":
            p = jax.nn.sigmoid(F[:, 0, :])                  # (B, S)
            g = (p - y_s[None, :])[:, None, :]
            h = jnp.maximum(p * (1 - p), 1e-6)[:, None, :]
        elif task == "regression":
            g = F - y_s[None, None, :]
            h = jnp.ones_like(g)
        else:
            P = jax.nn.softmax(F, axis=1)                   # (B, C, S)
            g = P - Y1_s.T[None, :C, :]
            h = jnp.maximum(P * (1 - P), 1e-6)
        g_tb = g.reshape(Tb, S).T                           # (S, Tb)
        h_tb = h.reshape(Tb, S).T
        sw_list = [(g_tb * w_tb), (h_tb * w_tb), w_tb]
        if deep:
            # slot-chain trees (maxDepth > heap practical limit): leaves
            # via the f32-exact per-tree segment sum — the last-level
            # histogram trick below does not apply (leaves settle at many
            # levels), and the f32 path needs no bf16 noise clamp
            fs, ths, bhs, abs_, node_s = _grow_forest_capped(
                binned_s, edges, sw_list, fmasks, cfg,
                depth=depth, n_bins=n_bins, mode="gh", n_slots=n_slots)
            gh = _diag_leaf_hist(
                node_s, jnp.stack([g_tb * w_tb, h_tb * w_tb], axis=1
                                  ).astype(jnp.float32), L)  # (2, Tb, L)
            leaf = -gh[0] / (gh[1] + lam_t[:, None] + 1e-12)  # (Tb, L)
        elif sweep:
            # CV candidates take Newton leaves straight off the final
            # level's histogram (bf16-summed, free); the refit winner
            # (sweep=False) keeps the exact f32 segment-sum below since
            # its leaves are SERVED predictions
            fs, ths, bhs, node_s, lst = _grow_forest(
                binned_s, edges, sw_list, fmasks, cfg,
                depth=depth, n_bins=n_bins, mode="gh",
                return_leaf_stats=True)
            abs_ = jnp.zeros((Tb, 0), jnp.int32)
            # bf16 sibling-subtracted histograms leave cancellation noise in
            # near-empty leaves' H; with small lam -G/H can then be huge and
            # wrong-signed, polluting later boosting rounds. The subtraction
            # error is ~eps_bf16·(parent H), so zero a leaf only when its H
            # is below that PARENT-relative floor (parent = leaf + heap
            # sibling) — a legitimately small leaf under a small parent
            # (min_child_weight territory) stays alive, unlike a
            # root-relative cutoff which would override the grid's
            # minChildWeight for deep trees
            h_leaf = lst[..., 1]                              # (Tb, L)
            L_ = h_leaf.shape[-1]
            if L_ >= 2:
                h_sib = h_leaf.reshape(-1, L_ // 2, 2)[..., ::-1].reshape(
                    h_leaf.shape)
                h_parent = h_leaf + h_sib
            else:
                h_parent = h_leaf
            raw = -lst[..., 0] / (h_leaf + lam_t[:, None] + 1e-12)
            leaf = jnp.where(h_leaf < 2 ** -8 * h_parent,
                             jnp.zeros_like(raw), raw)        # (Tb, L)
        else:
            fs, ths, bhs, node_s = _grow_forest(
                binned_s, edges, sw_list, fmasks, cfg,
                depth=depth, n_bins=n_bins, mode="gh")
            abs_ = jnp.zeros((Tb, 0), jnp.int32)
            # Newton leaves from per-tree G/H segment sums (f32 exact),
            # both stats reduced in one histogram call
            gh = _diag_leaf_hist(
                node_s, jnp.stack([g_tb * w_tb, h_tb * w_tb], axis=1
                                  ).astype(jnp.float32), L)  # (2, Tb, L)
            leaf = -gh[0] / (gh[1] + lam_t[:, None] + 1e-12)  # (Tb, L)
        # per-row leaf values via one-hot einsum — a (Tb, S) take_along_axis
        # gather measures ~3x slower on TPU; HIGHEST keeps the Newton values
        # exact in the boosting state. Chunk the tree axis so the (S, tb, L)
        # one-hot operand stays bounded (large multiclass sweeps would OOM
        # materializing all Tb*L columns at once)
        tb_chunk = max(1, 16384 // L)
        preds = []
        for lo in range(0, Tb, tb_chunk):
            hi2 = min(lo + tb_chunk, Tb)
            l_oh = (node_s[:, lo:hi2, None]
                    == jnp.arange(L, dtype=jnp.int32)).astype(jnp.float32)
            preds.append(jnp.einsum(
                "stl,tl->ts", l_oh, leaf[lo:hi2],
                precision=jax.lax.Precision.HIGHEST))
        pred = jnp.concatenate(preds, axis=0) if len(preds) > 1 \
            else preds[0]                                       # (Tb, S)
        active = rep((t.astype(jnp.float32) < max_iter).astype(X.dtype))
        eta_t = rep(step_size)
        scale = (eta_t * active).reshape(B, C)[:, :, None]
        F_new = F + scale * pred.reshape(B, C, S)
        return F_new, (fs, ths, bhs, abs_, leaf)

    _, (feat, thr, bheap, bases, leaf) = jax.lax.scan(
        round_step, F_init, jnp.arange(n_rounds))

    # (rounds, Tb=B*C, ...) → (B, rounds, C, ...)
    def to_bc(a):
        return jnp.swapaxes(
            a.reshape((n_rounds, B, C) + a.shape[2:]), 0, 1)

    feat, thr, bheap, bases, leaf = map(
        to_bc, (feat, thr, bheap, bases, leaf))
    tree_mask = (jnp.arange(n_rounds)[None, :] <
                 max_iter[:, None]).astype(jnp.float32)
    if deep:
        return {"feat_lv": feat, "thresh_lv": thr, "bins_lv": bheap,
                "base_lv": bases, "leaf": leaf, "f0": f0, "eta": step_size,
                "tree_mask": tree_mask, "edges": edges}
    return {"feat": feat, "thresh": thr, "bins": bheap, "leaf": leaf,
            "f0": f0, "eta": step_size, "tree_mask": tree_mask,
            "edges": edges}


# ---------------------------------------------------------------------------
# Batched predict drivers
# ---------------------------------------------------------------------------

def _forest_values(codes, feat_heaps, bin_heaps, leaf, *, depth, n_bins):
    """Σ_t leaf[t, node(row, t), :] via the fused descent kernel, chunking
    the tree axis at the kernel's cap. leaf: (T, L, k) with any per-tree
    weighting baked in."""
    T = feat_heaps.shape[0]
    out = None
    for lo in range(0, T, _PREDICT_TREE_CHUNK):
        hi = min(lo + _PREDICT_TREE_CHUNK, T)
        part = forest_predict(codes, feat_heaps[lo:hi], bin_heaps[lo:hi],
                              leaf[lo:hi], depth=depth, n_bins=n_bins)
        out = part if out is None else out + part
    return out


def _forest_values_grouped(codes, feat, bins, leaf, *, depth, n_bins):
    """Per-config leaf-value sums for a BATCH of configs in shared descent
    calls: a group's trees are concatenated and each config's leaf values
    occupy their own block of output columns, so one kernel pass scores the
    whole group (36 per-config launches → a handful; the summation over a
    config's trees stays inside the kernel's final matmul because other
    configs' columns are zero). feat/bins: (B, T, H); leaf: (B, T, L, k)
    with per-tree weighting baked in. Returns (B, n, k)."""
    B, T, H = feat.shape
    L, k = leaf.shape[2], leaf.shape[3]
    n = codes.shape[0]
    g = max(1, min(B, 128 // max(k, 1)))   # ≤128 output columns per call
    outs = []
    for lo in range(0, B, g):
        hi = min(lo + g, B)
        gb = hi - lo
        f_all = feat[lo:hi].reshape(gb * T, H)
        b_all = bins[lo:hi].reshape(gb * T, H)
        blocks = [jnp.pad(leaf[lo + c],
                          ((0, 0), (0, 0), (c * k, (gb - 1 - c) * k)))
                  for c in range(gb)]
        lf = jnp.concatenate(blocks, axis=0)            # (gb*T, L, gb*k)
        vals = _forest_values(codes, f_all, b_all, lf,
                              depth=depth, n_bins=n_bins)  # (n, gb*k)
        outs.append(vals.reshape(n, gb, k))
    out = jnp.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]
    return out.transpose(1, 0, 2)                       # (B, n, k)


@partial(jax.jit, static_argnames=("depth", "n_bins"))
def _predict_dt_batch(feat, bins, leaf, edges, X, *, depth, n_bins):
    codes = _bin_features(X, edges)
    return _forest_values_grouped(codes, feat[:, None], bins[:, None],
                                  leaf[:, None], depth=depth,
                                  n_bins=n_bins)           # (B, n, k)


@partial(jax.jit, static_argnames=("depth", "n_bins"))
def _predict_rf_batch(feat, bins, leaf, tree_mask, edges, X, *, depth,
                      n_bins):
    codes = _bin_features(X, edges)
    lw = leaf * tree_mask[:, :, None, None]                # (B, T, L, k)
    out = _forest_values_grouped(codes, feat, bins, lw,
                                 depth=depth, n_bins=n_bins)
    return out / jnp.maximum(tree_mask.sum(1), 1.0)[:, None, None]


@partial(jax.jit, static_argnames=("depth", "n_bins"))
def _predict_gbt_batch(feat, bins, leaf, f0, eta, tree_mask, edges, X, *,
                       depth, n_bins):
    codes = _bin_features(X, edges)
    B, T, C, H = feat.shape
    L = leaf.shape[-1]
    # class-routing leaf table: value·one-hot(class) per (tree·class, leaf)
    # so one descent over T·C trees yields per-class margins
    lv = leaf * tree_mask[:, :, None, None]                # (B, T, C, L)
    cls_oh = (jnp.arange(C)[:, None]
              == jnp.arange(C)[None, :]).astype(lv.dtype)  # (C, C)
    M = lv[:, :, :, :, None] * cls_oh[None, None, :, None, :]
    contrib = _forest_values_grouped(
        codes, feat.reshape(B, T * C, H), bins.reshape(B, T * C, H),
        M.reshape(B, T * C, L, C), depth=depth, n_bins=n_bins)  # (B, n, C)
    return (f0[:, None, :] + eta[:, None, None] * contrib
            ).transpose(0, 2, 1)                           # (B, C, n)


# -- slot-chain predict drivers ---------------------------------------------

def _forest_values_grouped_chain(codes, feat, bins, bases, leaf, *, n_bins):
    """Chain-format analog of `_forest_values_grouped`: per-config leaf-value
    sums for a batch of slot-chain configs in shared descent calls.
    feat/bins/bases: (B, T, depth, W); leaf: (B, T, W_out, k)."""
    B, T, depth, W = feat.shape
    W_out, k = leaf.shape[2], leaf.shape[3]
    n = codes.shape[0]
    g = max(1, min(B, 128 // max(k, 1)))
    outs = []
    for lo in range(0, B, g):
        hi = min(lo + g, B)
        gb = hi - lo
        f_all = feat[lo:hi].reshape(gb * T, depth, W)
        b_all = bins[lo:hi].reshape(gb * T, depth, W)
        a_all = bases[lo:hi].reshape(gb * T, depth, W)
        blocks = [jnp.pad(leaf[lo + c],
                          ((0, 0), (0, 0), (c * k, (gb - 1 - c) * k)))
                  for c in range(gb)]
        lf = jnp.concatenate(blocks, axis=0)            # (gb*T, W_out, gb*k)
        vals = forest_predict_chain(codes, f_all, b_all, a_all, lf,
                                    n_bins=n_bins)      # (n, gb*k)
        outs.append(vals.reshape(n, gb, k))
    out = jnp.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]
    return out.transpose(1, 0, 2)                       # (B, n, k)


@partial(jax.jit, static_argnames=("n_bins",))
def _predict_dt_chain_batch(feat, bins, bases, leaf, edges, X, *, n_bins):
    codes = _bin_features(X, edges)
    return _forest_values_grouped_chain(
        codes, feat[:, None], bins[:, None], bases[:, None], leaf[:, None],
        n_bins=n_bins)                                      # (B, n, k)


@partial(jax.jit, static_argnames=("n_bins",))
def _predict_rf_chain_batch(feat, bins, bases, leaf, tree_mask, edges, X, *,
                            n_bins):
    codes = _bin_features(X, edges)
    lw = leaf * tree_mask[:, :, None, None]                # (B, T, W_out, k)
    out = _forest_values_grouped_chain(codes, feat, bins, bases, lw,
                                       n_bins=n_bins)
    return out / jnp.maximum(tree_mask.sum(1), 1.0)[:, None, None]


@partial(jax.jit, static_argnames=("n_bins",))
def _predict_gbt_chain_batch(feat, bins, bases, leaf, f0, eta, tree_mask,
                             edges, X, *, n_bins):
    codes = _bin_features(X, edges)
    B, T, C, depth, W = feat.shape
    W_out = leaf.shape[-1]
    lv = leaf * tree_mask[:, :, None, None]                # (B, T, C, W_out)
    cls_oh = (jnp.arange(C)[:, None]
              == jnp.arange(C)[None, :]).astype(lv.dtype)  # (C, C)
    M = lv[:, :, :, :, None] * cls_oh[None, None, :, None, :]
    contrib = _forest_values_grouped_chain(
        codes, feat.reshape(B, T * C, depth, W),
        bins.reshape(B, T * C, depth, W),
        bases.reshape(B, T * C, depth, W),
        M.reshape(B, T * C, W_out, C), n_bins=n_bins)      # (B, n, C)
    return (f0[:, None, :] + eta[:, None, None] * contrib
            ).transpose(0, 2, 1)                           # (B, C, n)


# ---------------------------------------------------------------------------
# Model families
# ---------------------------------------------------------------------------

def _g(grid, key, default):
    return grid[key] if key in grid else jnp.full_like(
        next(iter(grid.values())), default)


class _TreeFamilyBase(ModelFamily):
    #: +inf thresholds are the "stopped node routes left" sentinel in both
    #: the heap (thresh) and slot-chain (thresh_lv) layouts — legitimate
    #: fitted state, exempted from the refit finite-params guard
    inf_ok_params = ("thresh", "thresh_lv")
    #: config sweep runs under chunked lax.map (sequential per chip), so the
    #: batch axis cannot shard over the 'model' mesh axis; rows still shard.
    shardable = False
    #: histogram builds route through the engine's pinned contraction —
    #: the fused sweep dispatcher arms the ``hist.build`` chaos gate and
    #: the engine mesh context for these families
    uses_hist_engine = True

    def sweep_fit_batch(self, X, y, weights, grid, num_classes):
        """CV-sweep fits: leaf values come from the split-search sample —
        the sweep only scores validation rows with them, and the winner is
        refit via plain ``fit_batch`` with EXACT full-data leaves (reference
        ModelSelector.fit refits best on full prepared train :158-159)."""
        return self.fit_batch(X, y, weights, grid, num_classes, sweep=True)

    task_of = staticmethod(lambda problem: "classification"
                           if problem in ("binary", "multiclass")
                           else "regression")

    def _task(self, num_classes):
        if "regression" in self.supports and len(self.supports) == 1:
            return "regression"
        return "classification"

    def select_params(self, batched, idx):
        """Per-config slice, except the bin-edge table, which is shared by
        every configuration of a fit and stored once."""
        import jax
        return {k: (np.asarray(v) if k == "edges" else np.asarray(v[idx]))
                for k, v in batched.items()}

    def slice_params(self, batched, lo, hi):
        # quantile bin edges are shared across the whole sweep
        return {k: (v if k == "edges" else v[lo:hi])
                for k, v in batched.items()}

    @staticmethod
    def _edges_of(params):
        """Shared (d, n_bins−1) edge table whether params came from a batched
        fit (2-D) or went through predict_one's uniform [None] stacking."""
        e = jnp.asarray(params["edges"])
        return e[0] if e.ndim == 3 else e


#: reference DefaultSelectorParams.MaxDepth {3, 6, 12}
#: (DefaultSelectorParams.scala:37). Depths ≤ _MAX_HEAP_DEPTH grow/serve as
#: complete heaps; deeper ones as slot-chain ("leaf budget") trees.
_DEPTHS = (3, 6, 12)

#: beyond this depth a complete heap's 2^depth layout outgrows HBM/VMEM and
#: the slot-chain representation takes over
_MAX_HEAP_DEPTH = 8

#: slot-chain leaf budgets: CV-sweep candidates rank configs (LightGBM-scale
#: num_leaves suffices — the winner is regrown exactly), served refits get
#: the full budget
_SWEEP_SLOTS = 64
_REFIT_SLOTS = 256


def _heap_to_chain(params, d_heap: int, depth: int, W: int, n_bins: int,
                   leaf_axis: int):
    """EXACT re-expression of complete-heap trees in the slot-chain layout.

    A heap node j at level l maps to chain slot j with child base 2j (the
    positional child rule); levels past the heap's depth are identity
    carries (sentinel bin ⇒ go 0, base = slot), so a row reaching heap leaf
    j stays at slot j through the remaining levels. Requires 2^d_heap ≤ W.
    Non-tree entries (edges, tree_mask, f0, eta) pass through."""
    if 2 ** d_heap > W:
        raise ValueError(f"heap depth {d_heap} needs {2 ** d_heap} slots, "
                         f"chain budget is {W}")
    feat, bins = params["feat"], params["bins"]
    thr, leaf = params["thresh"], params["leaf"]
    lead = feat.shape[:-1]
    W_out = min(2 ** depth, W)
    f_lv = jnp.zeros(lead + (depth, W), jnp.int32)
    b_lv = jnp.full(lead + (depth, W), n_bins, jnp.int32)
    t_lv = jnp.full(lead + (depth, W), jnp.inf, jnp.float32)
    a_lv = jnp.zeros(lead + (depth, W), jnp.int32)
    for level in range(depth):
        Wl = min(2 ** level, W)
        if level < d_heap:
            base_i, m = 2 ** level - 1, 2 ** level
            f_lv = f_lv.at[..., level, :m].set(feat[..., base_i:base_i + m])
            b_lv = b_lv.at[..., level, :m].set(bins[..., base_i:base_i + m])
            t_lv = t_lv.at[..., level, :m].set(thr[..., base_i:base_i + m])
            a_lv = a_lv.at[..., level, :m].set(
                2 * jnp.arange(m, dtype=jnp.int32))
        else:
            a_lv = a_lv.at[..., level, :Wl].set(
                jnp.arange(Wl, dtype=jnp.int32))
    ax = leaf_axis % leaf.ndim
    pad = [(0, 0)] * leaf.ndim
    pad[ax] = (0, W_out - leaf.shape[ax])
    out = {k: v for k, v in params.items()
           if k not in ("feat", "bins", "thresh", "leaf")}
    out.update({"feat_lv": f_lv, "bins_lv": b_lv, "thresh_lv": t_lv,
                "base_lv": a_lv, "leaf": jnp.pad(leaf, pad)})
    return out


def _pad_chain_depth(params, d_small: int, depth: int, n_bins: int,
                     leaf_axis: int):
    """Extend chain tables from d_small to depth levels with identity
    carries, and pad the leaf axis to the deeper W_out. Exact."""
    if d_small == depth:
        return params
    f_lv = params["feat_lv"]
    lead, W = f_lv.shape[:-2], f_lv.shape[-1]
    W_out = min(2 ** depth, W)
    ext = depth - d_small
    out = dict(params)

    def pad_levels(a, fill):
        pad = [(0, 0)] * (a.ndim - 2) + [(0, ext), (0, 0)]
        return jnp.pad(a, pad, constant_values=fill)

    out["feat_lv"] = pad_levels(f_lv, 0)
    out["bins_lv"] = pad_levels(params["bins_lv"], n_bins)
    out["thresh_lv"] = pad_levels(params["thresh_lv"], jnp.inf)
    a_lv = pad_levels(params["base_lv"], 0)
    for level in range(d_small, depth):
        Wl = min(2 ** level, W)
        a_lv = a_lv.at[..., level, :Wl].set(jnp.arange(Wl, dtype=jnp.int32))
    out["base_lv"] = a_lv
    leaf = params["leaf"]
    ax = leaf_axis % leaf.ndim
    pad = [(0, 0)] * leaf.ndim
    pad[ax] = (0, W_out - leaf.shape[ax])
    out["leaf"] = jnp.pad(leaf, pad)
    return out


def _embed_depth(params, d_small: int, d_max: int, n_bins: int,
                 leaf_axis: int):
    """Re-express a depth-``d_small`` fit in the depth-``d_max`` layout.

    Complete heaps are level-ordered, so the small heap is a PREFIX of the
    big one (remaining nodes: sentinel ⇒ route left), and a row at small
    leaf l descends all-left to big leaf l·(L_max/L_small) — the embedding
    is exact, letting mixed-maxDepth grids share one predict program while
    each depth bucket pays only its own growth cost."""
    if d_small == d_max:
        return params
    H_s, H_m = 2 ** d_small - 1, 2 ** d_max - 1
    r = 2 ** (d_max - d_small)
    out = dict(params)

    def pad_last(a, value):
        pad = [(0, 0)] * (a.ndim - 1) + [(0, H_m - H_s)]
        return jnp.pad(a, pad, constant_values=value)

    out["feat"] = pad_last(params["feat"], 0)
    out["thresh"] = pad_last(params["thresh"], jnp.inf)
    out["bins"] = pad_last(params["bins"], n_bins)
    leaf = params["leaf"]
    ax = leaf_axis % leaf.ndim
    shape = list(leaf.shape)
    shape[ax] = shape[ax] * r
    idx = [slice(None)] * leaf.ndim
    idx[ax] = slice(None, None, r)
    out["leaf"] = jnp.zeros(shape, leaf.dtype).at[tuple(idx)].set(leaf)
    return out


def _stitch_parts(B: int, parts):
    """Scatter per-bucket param dicts (config-subset axis 0) back into a
    (B, ...) batch; 'edges' is shared across buckets and passes through."""
    stitched = None
    for idx, p in parts:
        if stitched is None:
            stitched = {k: (v if k == "edges"
                            else jnp.zeros((B,) + v.shape[1:], v.dtype))
                        for k, v in p.items()}
        for k, v in p.items():
            if k != "edges":
                stitched[k] = stitched[k].at[jnp.asarray(idx)].set(v)
    return stitched


def _fit_depth_grouped(grid, weights, fit_group, n_bins: int,
                       leaf_axis: int, fit_group_deep=None, n_slots: int = 0):
    """Partition the config batch by maxDepth and fit each bucket with its
    own (cheap) depth program, embedding results into the deepest layout.
    ``fit_group(sub_grid, sub_weights, depth) -> params``. maxDepth values
    are host-side constants (grid arrays), so grouping is static.

    Depths past ``_MAX_HEAP_DEPTH`` fit via ``fit_group_deep`` (slot-chain
    grower, ``n_slots`` leaf budget); when any bucket is deep, every bucket
    is re-expressed in the chain layout (exact for heaps) so the whole batch
    shares one predict program."""
    md = np.asarray(grid["maxDepth"], dtype=np.float64).reshape(-1)
    uniq = sorted({int(v) for v in md})
    d_max = uniq[-1]
    any_deep = d_max > _MAX_HEAP_DEPTH
    if len(uniq) == 1:
        return (fit_group_deep(grid, weights, d_max, n_slots) if any_deep
                else fit_group(grid, weights, d_max))
    # the shared chain width must hold the DEEPEST heap bucket's full leaf
    # layer (a depth-8 heap has 256 leaves — more than the sweep budget)
    if any_deep:
        d_heap_max = max([u for u in uniq if u <= _MAX_HEAP_DEPTH],
                         default=0)
        n_slots = max(n_slots, 2 ** d_heap_max)
    B = md.shape[0]
    parts = []
    for u in uniq:
        idx = np.nonzero(md == u)[0]
        sub = {k: v[idx] for k, v in grid.items()}
        if u > _MAX_HEAP_DEPTH:
            p = _pad_chain_depth(fit_group_deep(sub, weights[idx], u,
                                                n_slots), u,
                                 d_max, n_bins, leaf_axis)
        elif any_deep:
            p = _heap_to_chain(fit_group(sub, weights[idx], u), u, d_max,
                               n_slots, n_bins, leaf_axis)
        else:
            p = _embed_depth(fit_group(sub, weights[idx], u), u, d_max,
                             n_bins, leaf_axis)
        parts.append((idx, p))
    return _stitch_parts(B, parts)


class DecisionTreeFamilyBase(_TreeFamilyBase):
    """reference OpDecisionTreeClassifier/Regressor (grids per
    DefaultSelectorParams: maxDepth × minInstancesPerNode {10,100}
    × minInfoGain {0.001,0.01,0.1})."""

    def default_grid(self, problem):
        return [{"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg}
                for d in _DEPTHS for mi in (10, 100)
                for mg in (0.001, 0.01, 0.1)]

    def fit_batch(self, X, y, weights, grid, num_classes, sweep=False):
        task = self._task(num_classes)
        n_slots = _SWEEP_SLOTS if sweep else _REFIT_SLOTS

        def fit_group(g, w, depth, slots=0):
            return _fit_dt_batch(
                X, y, w, g["maxDepth"], _g(g, "minInstancesPerNode", 1.0),
                _g(g, "minInfoGain", 0.0),
                depth=depth, n_bins=N_BINS,
                num_classes=max(num_classes, 2), task=task, sweep=sweep,
                n_slots=slots)

        return _fit_depth_grouped(
            grid, weights, fit_group, N_BINS, leaf_axis=-2,
            fit_group_deep=fit_group, n_slots=n_slots)

    def predict_batch(self, params, X, num_classes):
        edges = self._edges_of(params)
        task = self._task(num_classes)
        leaf = params["leaf"]
        if task == "classification" and num_classes <= 2:
            # binary: p0 = 1 − p1, so only the class-1 column needs routing
            # (halves the descent's output columns → 2x configs per call)
            leaf = leaf[..., 1:]
        if "base_lv" in params:
            out = _predict_dt_chain_batch(
                params["feat_lv"], params["bins_lv"], params["base_lv"],
                leaf, edges, X, n_bins=edges.shape[-1] + 1)
        else:
            depth = _depth_of(params["leaf"].shape[-2])
            out = _predict_dt_batch(params["feat"], params["bins"],
                                    leaf, edges, X, depth=depth,
                                    n_bins=edges.shape[-1] + 1)
        if task == "classification" and num_classes <= 2:
            return out[..., 0]
        return _shape_scores(out, num_classes, task)

    def predict_parts(self, fitted: FittedParams, X):
        params = {k: jnp.asarray(v)[None] for k, v in fitted.params.items()}
        out = self.predict_batch(params, X, fitted.num_classes)[0]
        return _parts_j(out, fitted.num_classes, self._task(fitted.num_classes))

    def predict_one(self, fitted: FittedParams, X):
        return {k: np.asarray(v)
                for k, v in self.predict_parts(fitted, jnp.asarray(X)).items()}


class RandomForestFamilyBase(_TreeFamilyBase):
    """reference OpRandomForestClassifier/Regressor (numTrees 50,
    subsample 1.0 per DefaultSelectorParams; bootstrap via Poisson row
    weights, per-tree feature subsets)."""

    def default_grid(self, problem):
        return [{"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg,
                 "numTrees": 50, "subsamplingRate": 1.0}
                for d in _DEPTHS for mi in (10, 100)
                for mg in (0.001, 0.01, 0.1)]

    def fit_batch(self, X, y, weights, grid, num_classes, sweep=False):
        task = self._task(num_classes)
        tree_vals = np.asarray(_g(grid, "numTrees", 20.0))
        n_trees = int(tree_vals.max())
        B = weights.shape[0]
        seeds = jnp.arange(B, dtype=jnp.float32) + 7.0
        grid = dict(grid, _seeds=seeds)
        if sweep:
            # rank with a capped forest; the winner refits at full numTrees
            # (proportional per-config scaling when the grid sweeps
            # numTrees itself — see _sweep_ensemble_cap)
            capped = _sweep_ensemble_cap(tree_vals, _SWEEP_RF_TREES,
                                         "numTrees")
            if capped is not None:
                n_trees = int(capped.max())
                grid = dict(grid, numTrees=jnp.asarray(capped, jnp.float32))
        n_slots = _SWEEP_SLOTS if sweep else _REFIT_SLOTS

        def fit_group(g, w, depth, slots=0):
            return _fit_rf_batch(
                X, y, w, g["maxDepth"],
                _g(g, "minInstancesPerNode", 1.0), _g(g, "minInfoGain", 0.0),
                _g(g, "numTrees", 20.0), _g(g, "subsamplingRate", 1.0),
                g["_seeds"], depth=depth, n_bins=N_BINS,
                num_classes=max(num_classes, 2), task=task, n_trees=n_trees,
                sweep=sweep, n_slots=slots)

        return _fit_depth_grouped(
            grid, weights, fit_group, N_BINS, leaf_axis=-2,
            fit_group_deep=fit_group, n_slots=n_slots)

    def predict_batch(self, params, X, num_classes):
        edges = self._edges_of(params)
        task = self._task(num_classes)
        leaf = params["leaf"]
        if task == "classification" and num_classes <= 2:
            # binary: route only the class-1 probability column (see DT)
            leaf = leaf[..., 1:]
        if "base_lv" in params:
            out = _predict_rf_chain_batch(
                params["feat_lv"], params["bins_lv"], params["base_lv"],
                leaf, params["tree_mask"], edges, X,
                n_bins=edges.shape[-1] + 1)
        else:
            depth = _depth_of(params["leaf"].shape[-2])
            out = _predict_rf_batch(params["feat"], params["bins"],
                                    leaf, params["tree_mask"],
                                    edges, X, depth=depth,
                                    n_bins=edges.shape[-1] + 1)
        if task == "classification" and num_classes <= 2:
            return out[..., 0]
        return _shape_scores(out, num_classes, task)

    def predict_parts(self, fitted: FittedParams, X):
        params = {k: jnp.asarray(v)[None] for k, v in fitted.params.items()}
        out = self.predict_batch(params, X, fitted.num_classes)[0]
        return _parts_j(out, fitted.num_classes, self._task(fitted.num_classes))

    def predict_one(self, fitted: FittedParams, X):
        return {k: np.asarray(v)
                for k, v in self.predict_parts(fitted, jnp.asarray(X)).items()}


class GBTFamilyBase(_TreeFamilyBase):
    """reference OpGBTClassifier/Regressor (maxIter 20, stepSize 0.1 per
    DefaultSelectorParams). Spark's GBTClassifier is binary-only; so is this
    one — multiclass boosting lives in the XGBoost families."""

    lam_default = 0.0
    mcw_default = 0.0

    def default_grid(self, problem):
        return [{"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg,
                 "maxIter": 20, "stepSize": 0.1}
                for d in _DEPTHS for mi in (10, 100)
                for mg in (0.001, 0.01, 0.1)]

    def _gbt_task(self, num_classes):
        if "regression" in self.supports and len(self.supports) == 1:
            return "regression"
        return "multiclass" if num_classes > 2 else "binary"

    def fit_batch(self, X, y, weights, grid, num_classes, sweep=False):
        # GBT trains entirely on the split-search sample: sweep and refit
        # are the same program
        task = self._gbt_task(num_classes)
        iter_vals = np.asarray(_g(grid, "maxIter", 20.0))
        n_rounds = int(iter_vals.max())
        if sweep:
            # rank with truncated boosting; the winner refits at full
            # maxIter (boosting rounds are the sweep's serial-step floor;
            # proportional per-config scaling when the grid sweeps maxIter
            # itself — see _sweep_ensemble_cap)
            capped = _sweep_ensemble_cap(iter_vals, _SWEEP_GBT_ROUNDS,
                                         "maxIter")
            if capped is not None:
                n_rounds = int(capped.max())
                grid = dict(grid, maxIter=jnp.asarray(capped, jnp.float32))
        n_slots = _SWEEP_SLOTS if sweep else _REFIT_SLOTS

        def one_raw(g, w, depth, slots=0):
            return _fit_gbt_batch(
                X, y, w, g["maxDepth"],
                _g(g, "minInstancesPerNode", 0.0), _g(g, "minInfoGain", 0.0),
                _g(g, "maxIter", 20.0), _g(g, "stepSize", 0.1),
                _g(g, "lambda", self.lam_default),
                _g(g, "minChildWeight", self.mcw_default),
                depth=depth, n_bins=N_BINS, num_classes=max(num_classes, 2),
                task=task, n_rounds=n_rounds, sweep=sweep, n_slots=slots)

        def one_call(g, w, depth, slots=0):
            # config chunking under the SAME per-level histogram budget as
            # RF (_LEVEL_HIST_ELEMS): the (Tb·nodes, d, n_bins, k) split
            # pipeline scales with the feature count, and GBT's boosting
            # scan otherwise runs every config at once — a 600-column
            # text-hashed vector at depth 12 would ask XLA for tens of GB
            B_g = w.shape[0]
            C_g = max(num_classes, 2) if task == "multiclass" else 1
            nodes_w = (min(2 ** depth, slots) if slots
                       else 2 ** max(depth - 1, 0))
            per_cfg = C_g * nodes_w * X.shape[1] * N_BINS * 3
            cb = int(max(1, min(B_g, _LEVEL_HIST_ELEMS // max(per_cfg, 1))))
            # ...AND bound the (S, k·Wl·T_pad) masked-stat operand of the
            # level histogram itself: at the refit sample (65536 rows) a
            # 200+-config exact grid otherwise asks XLA for a >10 GB
            # concatenate per level, and the scheduler keeps ~3 pipeline
            # stages of it alive (observed 24.5 GB on the fidelity
            # experiment's exact arm)
            S_est = min(X.shape[0],
                        _sweep_hist_sample() if sweep else _HIST_SAMPLE)
            lanes_max = max((1 << 29) // max(S_est, 1), 192)
            cb = int(max(1, min(cb, lanes_max // (3 * nodes_w * C_g))))
            if cb >= B_g:
                return one_raw(g, w, depth, slots)
            n_ch = -(-B_g // cb)
            parts = []
            for c in range(n_ch):
                # wrap the tail chunk so every chunk shares one compile
                # plain-numpy index: grid values may be host constants
                # (the fused sweep program passes them that way), and
                # numpy cannot be indexed by a traced jnp constant
                idx = np.arange(c * cb, (c + 1) * cb) % B_g
                sub = {k2: v[idx] for k2, v in g.items()}
                p = one_raw(sub, w[idx], depth, slots)
                count = min((c + 1) * cb, B_g) - c * cb
                parts.append((idx[:count],
                              {k2: (v if k2 == "edges" else v[:count])
                               for k2, v in p.items()}))
            return _stitch_parts(B_g, parts)

        md = np.asarray(grid["maxDepth"], dtype=np.float64).reshape(-1)
        d_max = int(md.max())
        if d_max <= _MAX_HEAP_DEPTH:
            # no depth grouping: boosting rounds are a sequential scan, and
            # a second scan chain for shallow configs costs more than the
            # wasted deep levels (their active-mask already stops splitting)
            return one_call(grid, weights, d_max)
        # deep grid: ONE slot-chain scan for ALL configs at the deepest
        # depth. Boosting is step-count-bound (each of rounds x levels
        # sequential steps carries ~ms of small-op overhead at GBT's narrow
        # lane widths), so a merged 240-step scan beats a 120-step heap
        # scan PLUS a 240-step chain scan even though shallow configs ride
        # along through the deep levels (their max_depth mask stops
        # splitting; the budget keeps those levels narrow). Shallow
        # configs' trees still fit within the budget exactly when
        # 2^depth <= n_slots (chain == heap, test_capped_grower_matches_
        # heap_when_uncapped).
        shallow = md[md <= _MAX_HEAP_DEPTH]
        if shallow.size:  # budget must hold a shallow config's full tree
            n_slots = max(n_slots, 2 ** int(shallow.max()))
        return one_call(grid, weights, d_max, n_slots)

    def predict_batch(self, params, X, num_classes):
        edges = self._edges_of(params)
        if "base_lv" in params:
            margins = _predict_gbt_chain_batch(
                params["feat_lv"], params["bins_lv"], params["base_lv"],
                params["leaf"], params["f0"], params["eta"],
                params["tree_mask"], edges, X,
                n_bins=edges.shape[-1] + 1)                      # (B, C, n)
        else:
            depth = _depth_of(params["leaf"].shape[-1])
            margins = _predict_gbt_batch(
                params["feat"], params["bins"], params["leaf"], params["f0"],
                params["eta"], params["tree_mask"], edges, X, depth=depth,
                n_bins=edges.shape[-1] + 1)                      # (B, C, n)
        task = self._gbt_task(num_classes)
        if task == "regression":
            return margins[:, 0, :]
        if task == "binary":
            return jax.nn.sigmoid(margins[:, 0, :])
        return jax.nn.softmax(jnp.swapaxes(margins, 1, 2), axis=-1)

    def predict_parts(self, fitted: FittedParams, X):
        params = {k: jnp.asarray(v)[None] for k, v in fitted.params.items()}
        task = self._gbt_task(fitted.num_classes)
        out = self.predict_batch(params, X, fitted.num_classes)[0]
        if task == "regression":
            return {"prediction": out}
        if task == "binary":
            prob = jnp.stack([1 - out, out], axis=1)
            pred = (out > 0.5).astype(jnp.float32)
            return {"prediction": pred, "probability": prob,
                    "rawPrediction": jnp.log(jnp.maximum(prob, 1e-12))}
        pred = out.argmax(axis=1).astype(jnp.float32)
        return {"prediction": pred, "probability": out,
                "rawPrediction": jnp.log(jnp.maximum(out, 1e-12))}

    def predict_one(self, fitted: FittedParams, X):
        return {k: np.asarray(v)
                for k, v in self.predict_parts(fitted, jnp.asarray(X)).items()}


# -- shared output shaping ---------------------------------------------------

def _depth_of(n_leaves: int) -> int:
    return int(np.log2(n_leaves))


def _shape_scores(out, num_classes, task):
    """(B, n, k) leaf outputs → family score convention: binary (B, n) p1;
    multiclass (B, n, C); regression (B, n)."""
    if task == "regression":
        return out[..., 0]
    if num_classes <= 2:
        return out[..., 1]
    return out[..., :num_classes]


def _parts_j(out, num_classes, task):
    """Prediction parts from family-convention scores, jit-traceable."""
    if task == "regression":
        return {"prediction": out}
    prob = jnp.stack([1 - out, out], axis=1) if out.ndim == 1 else out
    pred = prob.argmax(axis=1).astype(jnp.float32)
    return {"prediction": pred, "probability": prob,
            "rawPrediction": jnp.log(jnp.maximum(prob, 1e-12))}


# -- concrete registered families --------------------------------------------

class DecisionTreeClassifierFamily(DecisionTreeFamilyBase):
    name = "OpDecisionTreeClassifier"
    supports = frozenset({"binary", "multiclass"})


class DecisionTreeRegressorFamily(DecisionTreeFamilyBase):
    name = "OpDecisionTreeRegressor"
    supports = frozenset({"regression"})


class RandomForestClassifierFamily(RandomForestFamilyBase):
    name = "OpRandomForestClassifier"
    supports = frozenset({"binary", "multiclass"})


class RandomForestRegressorFamily(RandomForestFamilyBase):
    name = "OpRandomForestRegressor"
    supports = frozenset({"regression"})


class GBTClassifierFamily(GBTFamilyBase):
    name = "OpGBTClassifier"
    supports = frozenset({"binary"})


class GBTRegressorFamily(GBTFamilyBase):
    name = "OpGBTRegressor"
    supports = frozenset({"regression"})


class XGBoostClassifierFamily(GBTFamilyBase):
    """reference OpXGBoostClassifier (grid per DefaultSelectorParams:
    numRound {100} → maxIter, eta {0.1, 0.3} → stepSize, minChildWeight
    {1, 5, 10}); second-order splits with L2 ``lambda`` = 1 like XGBoost."""
    name = "OpXGBoostClassifier"
    supports = frozenset({"binary", "multiclass"})
    lam_default = 1.0
    mcw_default = 1.0

    def default_grid(self, problem):
        return [{"maxDepth": 6, "maxIter": 100, "stepSize": e,
                 "minChildWeight": m, "lambda": 1.0, "minInfoGain": 0.0,
                 "minInstancesPerNode": 0.0}
                for e in (0.1, 0.3) for m in (1.0, 5.0, 10.0)]


class XGBoostRegressorFamily(XGBoostClassifierFamily):
    name = "OpXGBoostRegressor"
    supports = frozenset({"regression"})


register_family(DecisionTreeClassifierFamily())
register_family(DecisionTreeRegressorFamily())
register_family(RandomForestClassifierFamily())
register_family(RandomForestRegressorFamily())
register_family(GBTClassifierFamily())
register_family(GBTRegressorFamily())
register_family(XGBoostClassifierFamily())
register_family(XGBoostRegressorFamily())
