"""Tree model families: decision tree, random forest, gradient-boosted trees.

TPU-native replacement for the reference's SparkML tree wrappers and for its
XGBoost JNI dependency (reference: core/.../impl/classification/
OpDecisionTreeClassifier.scala, OpRandomForestClassifier.scala,
OpGBTClassifier.scala, OpXGBoostClassifier.scala and the impl/regression
variants; XGBoost native core per SURVEY §2.9).

Design — TPU-first, not a port of either Spark's RDD tree builder or
XGBoost's C++:

* **Histogram growth** (the XGBoost-hist / LightGBM algorithm): features are
  quantile-binned once into int32 bins (n_bins=32 — Spark's maxBins default);
  each tree level's split search is ONE segment-sum scatter into a
  (nodes, features, bins, stats) histogram, a cumsum over bins, and an argmax
  — all static shapes, all on device, no per-node host control flow.
* **Complete-heap trees of static depth**: arrays feat/thresh/leaf. A node
  that stops early keeps threshold +inf so every row routes left — training
  and serving follow identical routing with zero dynamic shapes. Empty
  descendant leaves are unreachable by construction.
* **The sweep**: hyperparameter × fold configurations run under ``lax.map``
  (sequential per chip — histogram building already saturates the chip) and
  shard over the 'model' mesh axis across chips via ``sharded_fit_batch``;
  CV folds are 0/1 row weights exactly like the linear families.
* Binned routing and raw-value routing agree exactly: bin(x) = #{edges < x},
  so (bin > b) ⇔ (x > edges[b]) even with tied edges.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .api import FittedParams, ModelFamily, register_family

N_BINS = 32  # Spark maxBins default (reference DefaultSelectorParams.MaxBin)


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------

def _quantile_edges(X: jnp.ndarray, n_bins: int) -> jnp.ndarray:
    """Per-feature quantile bin edges, shape (d, n_bins-1)."""
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return jnp.quantile(X, qs, axis=0).T.astype(X.dtype)


def _bin_features(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """bin(x) = #{edges < x} ∈ [0, n_bins-1], shape (n, d) int32."""
    return jax.vmap(
        lambda e, col: jnp.searchsorted(e, col, side="left"),
        in_axes=(0, 1), out_axes=1)(edges, X).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Single-tree growth
# ---------------------------------------------------------------------------

def _split_gain(SL, SR, total, cfg, mode: str):
    """Gain + validity for every candidate split.

    SL/SR: (m, d, n_bins-1, k) left/right stats; total: (m, k).
    mode 'gh': stats = [grad, hess, count] — XGBoost-style Newton gain,
    normalized by parent count so min_info_gain is scale-free (matches the
    variance-impurity gain Spark compares against minInfoGain).
    mode 'counts': stats = per-class weighted counts — Gini gain.
    """
    if mode == "gh":
        lam = cfg["lam"]
        GL, HL, CL = SL[..., 0], SL[..., 1], SL[..., 2]
        GR, HR, CR = SR[..., 0], SR[..., 1], SR[..., 2]
        GP, HP, CP = total[:, 0], total[:, 1], total[:, 2]

        def score(G, H):
            return G * G / (H + lam + 1e-12)

        raw = score(GL, HL) + score(GR, HR) - score(GP, HP)[:, None, None]
        gain = raw / jnp.maximum(CP, 1.0)[:, None, None]
        mcw = cfg["min_child_weight"]
        mi = jnp.maximum(cfg["min_instances"], 1e-6)
        valid = (CL >= mi) & (CR >= mi) & (HL >= mcw) & (HR >= mcw)
        return gain, valid
    # Gini (classification trees)
    wL = SL.sum(-1)
    wR = SR.sum(-1)
    wP = total.sum(-1)

    def gini(S, W):
        p = S / jnp.maximum(W, 1e-12)[..., None]
        return 1.0 - (p * p).sum(-1)

    impP = gini(total, wP)[:, None, None]
    wPn = jnp.maximum(wP, 1e-12)[:, None, None]
    gain = impP - (wL / wPn) * gini(SL, wL) - (wR / wPn) * gini(SR, wR)
    mi = jnp.maximum(cfg["min_instances"], 1e-6)
    valid = (wL >= mi) & (wR >= mi)
    return gain, valid


def _grow_tree(binned, edges, stats, w, feat_mask, cfg, *,
               depth: int, n_bins: int, mode: str):
    """Grow one complete-heap tree.

    binned: (n, d) int32; stats: (n, k) per-row stat vector; w: (n,) row
    weights (folds × bootstrap); feat_mask: (d,) bool; cfg: traced scalars
    {max_depth, min_instances, min_info_gain, lam, min_child_weight}.

    Returns (feat_heap (2^D-1,), thresh_heap (2^D-1,), leaf_stats (2^D, k),
    leaf_w (2^D,), node (n,) final leaf assignment).
    """
    n, d = binned.shape
    k = stats.shape[1]
    sw = stats * w[:, None]
    feat_heap = jnp.zeros((2 ** depth - 1,), jnp.int32)
    thr_heap = jnp.full((2 ** depth - 1,), jnp.inf, dtype=jnp.float32)
    node = jnp.zeros((n,), jnp.int32)
    jd = jnp.arange(d, dtype=jnp.int32)
    for level in range(depth):
        m = 2 ** level
        flat = (node[:, None] * d + jd[None, :]) * n_bins + binned
        vals = jnp.broadcast_to(sw[:, None, :], (n, d, k)).reshape(n * d, k)
        hist = jax.ops.segment_sum(vals, flat.reshape(-1),
                                   num_segments=m * d * n_bins)
        hist = hist.reshape(m, d, n_bins, k)
        cum = jnp.cumsum(hist, axis=2)
        total = cum[:, 0, -1, :]                      # (m, k) node totals
        SL = cum[:, :, :-1, :]                        # split "bin <= b"
        SR = total[:, None, None, :] - SL
        gain, valid = _split_gain(SL, SR, total, cfg, mode)
        valid = valid & feat_mask[None, :, None]
        gain = jnp.where(valid, gain, -jnp.inf)
        gflat = gain.reshape(m, d * (n_bins - 1))
        best = jnp.argmax(gflat, axis=1)
        bf = (best // (n_bins - 1)).astype(jnp.int32)
        bb = (best % (n_bins - 1)).astype(jnp.int32)
        bgain = jnp.take_along_axis(gflat, best[:, None], axis=1)[:, 0]
        active = jnp.asarray(level, jnp.float32) < cfg["max_depth"]
        do_split = active & jnp.isfinite(bgain) & (bgain > cfg["min_info_gain"])
        thr = jnp.where(do_split, edges[bf, bb], jnp.inf).astype(jnp.float32)
        feat_heap = feat_heap.at[m - 1: 2 * m - 1].set(
            jnp.where(do_split, bf, 0))
        thr_heap = thr_heap.at[m - 1: 2 * m - 1].set(thr)
        row_bin = jnp.take_along_axis(binned, bf[node][:, None], axis=1)[:, 0]
        go_right = do_split[node] & (row_bin > bb[node])
        node = 2 * node + go_right.astype(jnp.int32)
    leaf_stats = jax.ops.segment_sum(sw, node, num_segments=2 ** depth)
    leaf_w = jax.ops.segment_sum(w, node, num_segments=2 ** depth)
    return feat_heap, thr_heap, leaf_stats, leaf_w, node


def _predict_tree(feat, thr, leaf, X, depth: int):
    """Route raw rows down one heap tree; returns leaf rows (n, k)."""
    n = X.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    for _ in range(depth):
        f = feat[node]
        t = thr[node]
        xv = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        node = 2 * node + 1 + (xv > t).astype(jnp.int32)
    return leaf[node - (2 ** depth - 1)]


# ---------------------------------------------------------------------------
# Batched fit drivers (lax.map over configurations)
# ---------------------------------------------------------------------------

def _class_leaf(leaf_stats, leaf_w):
    """Per-leaf class probabilities from weighted counts."""
    tot = jnp.maximum(leaf_stats.sum(-1, keepdims=True), 1e-12)
    return leaf_stats / tot


def _mean_leaf(leaf_stats, leaf_w):
    """gh-mode with g=-y, h=1: Newton leaf -G/H = weighted mean of y."""
    return -leaf_stats[:, 0] / jnp.maximum(leaf_stats[:, 1], 1e-12)


def _make_stats(y, num_classes: int, task: str):
    if task == "classification":
        return jax.nn.one_hot(y.astype(jnp.int32), num_classes,
                              dtype=jnp.float32), "counts"
    ones = jnp.ones_like(y)
    return jnp.stack([-y, ones, ones], axis=1), "gh"


@partial(jax.jit, static_argnames=("depth", "n_bins", "num_classes", "task"))
def _fit_dt_batch(X, y, weights, max_depth, min_inst, min_gain, *,
                  depth, n_bins, num_classes, task):
    edges = _quantile_edges(X, n_bins)
    binned = _bin_features(X, edges)
    stats, mode = _make_stats(y, num_classes, task)
    fmask = jnp.ones((X.shape[1],), bool)

    def one(args):
        w, md, mi, mg = args
        cfg = {"max_depth": md, "min_instances": mi, "min_info_gain": mg,
               "lam": 1e-6, "min_child_weight": 0.0}
        f, t, ls, lw, _ = _grow_tree(binned, edges, stats, w, fmask, cfg,
                                     depth=depth, n_bins=n_bins, mode=mode)
        leaf = _class_leaf(ls, lw) if task == "classification" \
            else _mean_leaf(ls, lw)[:, None]
        return f, t, leaf

    feat, thr, leaf = jax.lax.map(one, (weights, max_depth, min_inst, min_gain))
    return {"feat": feat, "thresh": thr, "leaf": leaf}


@partial(jax.jit, static_argnames=("depth", "n_bins", "num_classes", "task",
                                   "n_trees"))
def _fit_rf_batch(X, y, weights, max_depth, min_inst, min_gain, num_trees,
                  subsample, seeds, *, depth, n_bins, num_classes, task,
                  n_trees):
    n, d = X.shape
    edges = _quantile_edges(X, n_bins)
    binned = _bin_features(X, edges)
    stats, mode = _make_stats(y, num_classes, task)
    # per-tree feature subset (Spark featureSubsetStrategy auto:
    # sqrt for classification, 1/3 for regression)
    p_feat = float(np.ceil(np.sqrt(d)) / d) if task == "classification" \
        else max(1.0 / 3.0, 1.0 / d)

    def one(args):
        w, md, mi, mg, ss, seed = args
        cfg = {"max_depth": md, "min_instances": mi, "min_info_gain": mg,
               "lam": 1e-6, "min_child_weight": 0.0}
        base = jax.random.PRNGKey(seed.astype(jnp.uint32))

        def tree_step(_, t):
            k1, k2 = jax.random.split(jax.random.fold_in(base, t))
            boot = jax.random.poisson(k1, ss, (n,)).astype(X.dtype)
            fmask = jax.random.bernoulli(k2, p_feat, (d,))
            f, th, ls, lw, _ = _grow_tree(
                binned, edges, stats, w * boot, fmask, cfg,
                depth=depth, n_bins=n_bins, mode=mode)
            leaf = _class_leaf(ls, lw) if task == "classification" \
                else _mean_leaf(ls, lw)[:, None]
            return None, (f, th, leaf)

        _, (fs, ths, leaves) = jax.lax.scan(tree_step, None,
                                            jnp.arange(n_trees))
        return fs, ths, leaves

    feat, thr, leaf = jax.lax.map(
        one, (weights, max_depth, min_inst, min_gain, subsample, seeds))
    tree_mask = (jnp.arange(n_trees)[None, :] <
                 num_trees[:, None]).astype(jnp.float32)
    return {"feat": feat, "thresh": thr, "leaf": leaf, "tree_mask": tree_mask}


@partial(jax.jit, static_argnames=("depth", "n_bins", "num_classes", "task",
                                   "n_rounds"))
def _fit_gbt_batch(X, y, weights, max_depth, min_inst, min_gain, max_iter,
                   step_size, lam, min_child_weight, *, depth, n_bins,
                   num_classes, task, n_rounds):
    """Gradient boosting: binary logistic / regression squared / multiclass
    softmax (one tree per class per round, vmapped over the class axis)."""
    n, d = X.shape
    edges = _quantile_edges(X, n_bins)
    binned = _bin_features(X, edges)
    fmask = jnp.ones((d,), bool)
    C = num_classes if task == "multiclass" else 1
    y_i = y.astype(jnp.int32)
    Y1 = jax.nn.one_hot(y_i, max(C, 2), dtype=X.dtype) if task == "multiclass" \
        else None

    def one(args):
        w, md, mi, mg, it, eta, lm, mcw = args
        cfg = {"max_depth": md, "min_instances": mi, "min_info_gain": mg,
               "lam": lm, "min_child_weight": mcw}
        if task == "regression":
            f0 = jnp.full((1,), (w * y).sum() / jnp.maximum(w.sum(), 1.0))
        else:
            f0 = jnp.zeros((C,), X.dtype)
        F_init = jnp.broadcast_to(f0[None, :], (n, C))

        def grow_class(g, h):
            ones = jnp.ones_like(g)
            st = jnp.stack([g, h, ones], axis=1)
            f, th, ls, lw, node = _grow_tree(
                binned, edges, st, w, fmask, cfg,
                depth=depth, n_bins=n_bins, mode="gh")
            leaf = -ls[:, 0] / (ls[:, 1] + lm + 1e-12)
            return f, th, leaf, leaf[node]

        def round_step(F, t):
            if task == "binary":
                p = jax.nn.sigmoid(F[:, 0])
                g = (p - y)[None, :]
                h = jnp.maximum(p * (1 - p), 1e-6)[None, :]
            elif task == "regression":
                g = (F[:, 0] - y)[None, :]
                h = jnp.ones((1, n), X.dtype)
            else:
                P = jax.nn.softmax(F, axis=1)
                g = (P - Y1[:, :C]).T
                h = jnp.maximum(P * (1 - P), 1e-6).T
            f, th, leaf, preds = jax.vmap(grow_class)(g, h)   # (C, ...)
            active = (t.astype(jnp.float32) < it).astype(X.dtype)
            F_new = F + eta * active * preds.T
            return F_new, (f, th, leaf)

        _, (fs, ths, leaves) = jax.lax.scan(round_step, F_init,
                                            jnp.arange(n_rounds))
        return fs, ths, leaves, f0

    feat, thr, leaf, f0 = jax.lax.map(
        one, (weights, max_depth, min_inst, min_gain, max_iter, step_size,
              lam, min_child_weight))
    tree_mask = (jnp.arange(n_rounds)[None, :] <
                 max_iter[:, None]).astype(jnp.float32)
    return {"feat": feat, "thresh": thr, "leaf": leaf, "f0": f0,
            "eta": step_size, "tree_mask": tree_mask}


# ---------------------------------------------------------------------------
# Batched predict drivers
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("depth",))
def _predict_dt_batch(feat, thr, leaf, X, *, depth):
    return jax.vmap(lambda f, t, l: _predict_tree(f, t, l, X, depth))(
        feat, thr, leaf)                                  # (B, n, k)


@partial(jax.jit, static_argnames=("depth",))
def _predict_rf_batch(feat, thr, leaf, tree_mask, X, *, depth):
    def one(f, t, l, m):
        per_tree = jax.vmap(
            lambda ft, tt, lt: _predict_tree(ft, tt, lt, X, depth))(f, t, l)
        wsum = (per_tree * m[:, None, None]).sum(0)
        return wsum / jnp.maximum(m.sum(), 1.0)
    return jax.vmap(one)(feat, thr, leaf, tree_mask)      # (B, n, k)


@partial(jax.jit, static_argnames=("depth",))
def _predict_gbt_batch(feat, thr, leaf, f0, eta, tree_mask, X, *, depth):
    def one(f, t, l, f0b, etab, m):
        # f: (T, C, M) — flatten tree×class, route, re-split
        T, C, M = f.shape
        per = jax.vmap(lambda ft, tt, lt: _predict_tree(
            ft, tt, lt[:, None], X, depth))(
            f.reshape(T * C, M), t.reshape(T * C, M),
            l.reshape(T * C, -1))                          # (T*C, n, 1)
        per = per[..., 0].reshape(T, C, -1)
        contrib = (per * m[:, None, None]).sum(0)          # (C, n)
        return f0b[:, None] + etab * contrib
    return jax.vmap(one)(feat, thr, leaf, f0, eta, tree_mask)  # (B, C, n)


# ---------------------------------------------------------------------------
# Model families
# ---------------------------------------------------------------------------

def _g(grid, key, default):
    return grid[key] if key in grid else jnp.full_like(
        next(iter(grid.values())), default)


class _TreeFamilyBase(ModelFamily):
    task_of = staticmethod(lambda problem: "classification"
                           if problem in ("binary", "multiclass")
                           else "regression")

    def _task(self, num_classes):
        if "regression" in self.supports and len(self.supports) == 1:
            return "regression"
        return "classification"


#: reference DefaultSelectorParams.MaxDepth is {3, 6, 12}; the default grid
#: here stops at 6 because a complete-heap tree allocates 2^depth leaves —
#: depth 12 is fully supported, pass it explicitly when wanted.
_DEPTHS = (3, 6)


class DecisionTreeFamilyBase(_TreeFamilyBase):
    """reference OpDecisionTreeClassifier/Regressor (grids per
    DefaultSelectorParams: maxDepth × minInstancesPerNode {10,100}
    × minInfoGain {0.001,0.01,0.1})."""

    def default_grid(self, problem):
        return [{"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg}
                for d in _DEPTHS for mi in (10, 100)
                for mg in (0.001, 0.01, 0.1)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        task = self._task(num_classes)
        depth = int(np.max(np.asarray(grid["maxDepth"])))
        return _fit_dt_batch(
            X, y, weights, grid["maxDepth"], _g(grid, "minInstancesPerNode", 1.0),
            _g(grid, "minInfoGain", 0.0),
            depth=depth, n_bins=N_BINS,
            num_classes=max(num_classes, 2), task=task)

    def predict_batch(self, params, X, num_classes):
        depth = _depth_of(params["leaf"].shape[-2])
        out = _predict_dt_batch(params["feat"], params["thresh"],
                                params["leaf"], X, depth=depth)
        return _shape_scores(out, num_classes, self._task(num_classes))

    def predict_one(self, fitted: FittedParams, X):
        params = {k: jnp.asarray(v)[None] for k, v in fitted.params.items()}
        out = np.asarray(self.predict_batch(
            params, jnp.asarray(X), fitted.num_classes))[0]
        return _parts(out, fitted.num_classes, self._task(fitted.num_classes))


class RandomForestFamilyBase(_TreeFamilyBase):
    """reference OpRandomForestClassifier/Regressor (numTrees 50,
    subsample 1.0 per DefaultSelectorParams; bootstrap via Poisson row
    weights, per-tree feature subsets)."""

    def default_grid(self, problem):
        return [{"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg,
                 "numTrees": 50, "subsamplingRate": 1.0}
                for d in _DEPTHS for mi in (10, 100)
                for mg in (0.001, 0.01, 0.1)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        task = self._task(num_classes)
        depth = int(np.max(np.asarray(grid["maxDepth"])))
        n_trees = int(np.max(np.asarray(_g(grid, "numTrees", 20.0))))
        B = weights.shape[0]
        seeds = jnp.arange(B, dtype=jnp.float32) + 7.0
        return _fit_rf_batch(
            X, y, weights, grid["maxDepth"],
            _g(grid, "minInstancesPerNode", 1.0), _g(grid, "minInfoGain", 0.0),
            _g(grid, "numTrees", 20.0), _g(grid, "subsamplingRate", 1.0),
            seeds, depth=depth, n_bins=N_BINS,
            num_classes=max(num_classes, 2), task=task, n_trees=n_trees)

    def predict_batch(self, params, X, num_classes):
        depth = _depth_of(params["leaf"].shape[-2])
        out = _predict_rf_batch(params["feat"], params["thresh"],
                                params["leaf"], params["tree_mask"], X,
                                depth=depth)
        return _shape_scores(out, num_classes, self._task(num_classes))

    def predict_one(self, fitted: FittedParams, X):
        params = {k: jnp.asarray(v)[None] for k, v in fitted.params.items()}
        out = np.asarray(self.predict_batch(
            params, jnp.asarray(X), fitted.num_classes))[0]
        return _parts(out, fitted.num_classes, self._task(fitted.num_classes))


class GBTFamilyBase(_TreeFamilyBase):
    """reference OpGBTClassifier/Regressor (maxIter 20, stepSize 0.1 per
    DefaultSelectorParams). Spark's GBTClassifier is binary-only; so is this
    one — multiclass boosting lives in the XGBoost families."""

    lam_default = 0.0
    mcw_default = 0.0

    def default_grid(self, problem):
        return [{"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg,
                 "maxIter": 20, "stepSize": 0.1}
                for d in _DEPTHS for mi in (10, 100)
                for mg in (0.001, 0.01, 0.1)]

    def _gbt_task(self, num_classes):
        if "regression" in self.supports and len(self.supports) == 1:
            return "regression"
        return "multiclass" if num_classes > 2 else "binary"

    def fit_batch(self, X, y, weights, grid, num_classes):
        task = self._gbt_task(num_classes)
        depth = int(np.max(np.asarray(grid["maxDepth"])))
        n_rounds = int(np.max(np.asarray(_g(grid, "maxIter", 20.0))))
        return _fit_gbt_batch(
            X, y, weights, grid["maxDepth"],
            _g(grid, "minInstancesPerNode", 0.0), _g(grid, "minInfoGain", 0.0),
            _g(grid, "maxIter", 20.0), _g(grid, "stepSize", 0.1),
            _g(grid, "lambda", self.lam_default),
            _g(grid, "minChildWeight", self.mcw_default),
            depth=depth, n_bins=N_BINS, num_classes=max(num_classes, 2),
            task=task, n_rounds=n_rounds)

    def predict_batch(self, params, X, num_classes):
        depth = _depth_of(params["leaf"].shape[-1])
        margins = _predict_gbt_batch(
            params["feat"], params["thresh"], params["leaf"], params["f0"],
            params["eta"], params["tree_mask"], X, depth=depth)  # (B, C, n)
        task = self._gbt_task(num_classes)
        if task == "regression":
            return margins[:, 0, :]
        if task == "binary":
            return jax.nn.sigmoid(margins[:, 0, :])
        return jax.nn.softmax(jnp.swapaxes(margins, 1, 2), axis=-1)

    def predict_one(self, fitted: FittedParams, X):
        params = {k: jnp.asarray(v)[None] for k, v in fitted.params.items()}
        task = self._gbt_task(fitted.num_classes)
        out = np.asarray(self.predict_batch(
            params, jnp.asarray(X), fitted.num_classes))[0]
        if task == "regression":
            return {"prediction": out}
        if task == "binary":
            prob = np.stack([1 - out, out], axis=1)
            pred = (out > 0.5).astype(np.float32)
            return {"prediction": pred, "probability": prob,
                    "rawPrediction": np.log(np.clip(prob, 1e-12, None))}
        pred = out.argmax(axis=1).astype(np.float32)
        return {"prediction": pred, "probability": out,
                "rawPrediction": np.log(np.clip(out, 1e-12, None))}


# -- shared output shaping ---------------------------------------------------

def _depth_of(n_leaves: int) -> int:
    return int(np.log2(n_leaves))


def _shape_scores(out, num_classes, task):
    """(B, n, k) leaf outputs → family score convention: binary (B, n) p1;
    multiclass (B, n, C); regression (B, n)."""
    if task == "regression":
        return out[..., 0]
    if num_classes <= 2:
        return out[..., 1]
    return out[..., :num_classes]


def _parts(out, num_classes, task):
    if task == "regression":
        return {"prediction": out}
    prob = np.stack([1 - out, out], axis=1) if out.ndim == 1 else out
    pred = prob.argmax(axis=1).astype(np.float32)
    return {"prediction": pred, "probability": prob,
            "rawPrediction": np.log(np.clip(prob, 1e-12, None))}


# -- concrete registered families --------------------------------------------

class DecisionTreeClassifierFamily(DecisionTreeFamilyBase):
    name = "OpDecisionTreeClassifier"
    supports = frozenset({"binary", "multiclass"})


class DecisionTreeRegressorFamily(DecisionTreeFamilyBase):
    name = "OpDecisionTreeRegressor"
    supports = frozenset({"regression"})


class RandomForestClassifierFamily(RandomForestFamilyBase):
    name = "OpRandomForestClassifier"
    supports = frozenset({"binary", "multiclass"})


class RandomForestRegressorFamily(RandomForestFamilyBase):
    name = "OpRandomForestRegressor"
    supports = frozenset({"regression"})


class GBTClassifierFamily(GBTFamilyBase):
    name = "OpGBTClassifier"
    supports = frozenset({"binary"})


class GBTRegressorFamily(GBTFamilyBase):
    name = "OpGBTRegressor"
    supports = frozenset({"regression"})


class XGBoostClassifierFamily(GBTFamilyBase):
    """reference OpXGBoostClassifier (grid per DefaultSelectorParams:
    numRound {100} → maxIter, eta {0.1, 0.3} → stepSize, minChildWeight
    {1, 5, 10}); second-order splits with L2 ``lambda`` = 1 like XGBoost."""
    name = "OpXGBoostClassifier"
    supports = frozenset({"binary", "multiclass"})
    lam_default = 1.0
    mcw_default = 1.0

    def default_grid(self, problem):
        return [{"maxDepth": 6, "maxIter": 100, "stepSize": e,
                 "minChildWeight": m, "lambda": 1.0, "minInfoGain": 0.0,
                 "minInstancesPerNode": 0.0}
                for e in (0.1, 0.3) for m in (1.0, 5.0, 10.0)]


class XGBoostRegressorFamily(XGBoostClassifierFamily):
    name = "OpXGBoostRegressor"
    supports = frozenset({"regression"})


register_family(DecisionTreeClassifierFamily())
register_family(DecisionTreeRegressorFamily())
register_family(RandomForestClassifierFamily())
register_family(RandomForestRegressorFamily())
register_family(GBTClassifierFamily())
register_family(GBTRegressorFamily())
register_family(XGBoostClassifierFamily())
register_family(XGBoostRegressorFamily())
