"""Tree model families: decision tree, random forest, gradient-boosted trees.

TPU-native replacement for the reference's SparkML tree wrappers and for its
XGBoost JNI dependency (reference: core/.../impl/classification/
OpDecisionTreeClassifier.scala, OpRandomForestClassifier.scala,
OpGBTClassifier.scala, OpXGBoostClassifier.scala and the impl/regression
variants; XGBoost native core per SURVEY §2.9).

Design — TPU-first, not a port of either Spark's RDD tree builder or
XGBoost's C++:

* **Histogram growth** (the XGBoost-hist / LightGBM algorithm): features are
  quantile-binned once into int32 bins (n_bins=32 — Spark's maxBins default);
  each tree level's split search is a (nodes, features, bins, stats)
  histogram, a cumsum over bins, and an argmax — all static shapes, all on
  device, no per-node host control flow.
* **MXU histograms, no scatters**: split search runs on a deterministic
  strided row sample (≤ _HIST_SAMPLE rows, weights rescaled by n/S — the
  XGBoost 'approx'/GOSS design point: split thresholds are order-statistic
  estimates and converge long before 65k rows), and each level's histogram
  is ONE matmul — (nodes⊗stats)ᵀ expanded against the int32 bin codes by
  the fused pallas kernel (ops/tree_hist.py): the bin one-hot is built
  tile-by-tile in VMEM and never reaches HBM. Leaf statistics stay EXACT: the full dataset is
  routed down the grown tree (bin-space comparisons identical to growth) and
  reduced with a leaf-one-hot matmul. Scatter-free end to end, so the whole
  builder tiles onto the MXU and scales to millions of rows.
* **Complete-heap trees of static depth**: arrays feat/thresh/leaf. A node
  that stops early keeps threshold +inf so every row routes left — training
  and serving follow identical routing with zero dynamic shapes. Empty
  descendant leaves are unreachable by construction.
* **The sweep**: hyperparameter × fold configurations run under ``lax.map``
  (sequential per chip — histogram building already saturates the chip) and
  shard over the 'model' mesh axis across chips via ``sharded_fit_batch``;
  CV folds are 0/1 row weights exactly like the linear families.
* Binned routing and raw-value routing agree exactly: bin(x) = #{edges < x},
  so (bin > b) ⇔ (x > edges[b]) even with tied edges.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.tree_hist import hist_matmul, route_matmul
from .api import FittedParams, ModelFamily, register_family

N_BINS = 32  # Spark maxBins default (reference DefaultSelectorParams.MaxBin)

#: split-search sample cap: histograms are built from at most this many
#: evenly-strided rows (weights rescaled by n/S so count-based stopping
#: criteria keep full-data semantics); leaf values use ALL rows.
_HIST_SAMPLE = 65536

#: trees per chunk in the exact-leaf full-data pass (bounds the (rows,
#: trees·leaves) one-hot transient)
_LEAF_CHUNK = 8


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------

def _quantile_edges(X: jnp.ndarray, n_bins: int) -> jnp.ndarray:
    """Per-feature quantile bin edges, shape (d, n_bins-1)."""
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return jnp.quantile(X, qs, axis=0).T.astype(X.dtype)


def _bin_features(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """bin(x) = #{edges < x} ∈ [0, n_bins-1], shape (n, d) int32.

    Computed as a sum of broadcast comparisons — one fused elementwise pass
    (TPU sorts/searchsorted are far slower than n_bins comparisons)."""
    return (X[:, :, None] > edges[None, :, :]).sum(axis=2, dtype=jnp.int32)


def _sample_rows(n: int) -> np.ndarray:
    """Deterministic strided sample indices for split search (static)."""
    if n <= _HIST_SAMPLE:
        return np.arange(n)
    return np.linspace(0, n - 1, _HIST_SAMPLE).astype(np.int64)


def _route_codes(codes: jnp.ndarray, feat_heaps: jnp.ndarray,
                 bin_heaps: jnp.ndarray, depth: int, n_bins: int,
                 d: int) -> jnp.ndarray:
    """Route every row down T trees at once: per level the fused pallas
    kernel (ops/tree_hist.py route_matmul) expands the bin codes' comparison
    bits in VMEM and matmuls them against the level's (feature, bin)
    selector — the (n, d·n_bins) cmp matrix (4 GB at 1M rows × 64 features)
    never exists. Go-right bits are picked per row by a fused node-one-hot
    reduction. feat/bin heaps: (T, 2^depth−1). Returns (n, T) leaf
    assignments in [0, 2^depth). Every level pads its node axis to the
    deepest level's width: on the pallas path that makes the whole loop one
    kernel program, and on the XLA path the 128-wide contraction measures
    FASTER than exact tiny widths (RF leaf pass 4.0s vs 5.8s at 1M rows) —
    see the dispatch note in ops/tree_hist.py for why the cmp build also
    stays inside each call."""
    n = codes.shape[0]
    T = feat_heaps.shape[0]
    m_max = 2 ** (depth - 1)
    node = jnp.zeros((n, T), jnp.int32)
    for level in range(depth):
        base = 2 ** level - 1
        m = 2 ** level
        f_lvl = jnp.pad(feat_heaps[:, base:base + m],
                        ((0, 0), (0, m_max - m)))
        b_lvl = jnp.pad(bin_heaps[:, base:base + m],
                        ((0, 0), (0, m_max - m)), constant_values=n_bins)
        D = route_matmul(codes, f_lvl.reshape(-1), b_lvl.reshape(-1),
                         n_bins)
        D = D.reshape(n, T, -1)[:, :, :m]
        n_oh = (node[:, :, None]
                == jnp.arange(m, dtype=jnp.int32)).astype(jnp.bfloat16)
        go = (D * n_oh).sum(-1)                            # (n, T)
        node = 2 * node + (go > 0.5).astype(jnp.int32)
    return node


def _leaf_reduce_forest(node: jnp.ndarray, stats: jnp.ndarray,
                        w: jnp.ndarray, depth: int):
    """Exact leaf statistics for T trees at once: a (T·L)-wide leaf-one-hot
    matmul. node: (n, T). Returns (T, L, k) stat sums and (T, L) weights."""
    n, T = node.shape
    L = 2 ** depth
    comb = node + (jnp.arange(T, dtype=jnp.int32) * L)[None, :]  # (n, T)
    # f32 one-hot and stats: leaf values are served predictions, so they
    # must not inherit bf16 rounding (histogram matmuls may; these may not)
    l_oh = (comb[:, :, None].reshape(n, T, 1)
            == jnp.arange(T * L, dtype=jnp.int32).reshape(1, T, L)
            ).astype(jnp.float32).reshape(n, T * L)
    aug = jnp.concatenate([stats * w[:, None], w[:, None]], axis=1)
    out = jnp.einsum("na,nk->ak", l_oh, aug.astype(jnp.float32),
                     preferred_element_type=jnp.float32)     # (T·L, k+1)
    out = out.reshape(T, L, -1)
    return out[..., :-1], out[..., -1]


# ---------------------------------------------------------------------------
# Single-tree growth
# ---------------------------------------------------------------------------

def _split_gain(SL, SR, total, cfg, mode: str):
    """Gain + validity for every candidate split.

    SL/SR: (m, d, n_bins-1, k) left/right stats; total: (m, k).
    mode 'gh': stats = [grad, hess, count] — XGBoost-style Newton gain,
    normalized by parent count so min_info_gain is scale-free (matches the
    variance-impurity gain Spark compares against minInfoGain).
    mode 'counts': stats = per-class weighted counts — Gini gain.
    """
    if mode == "gh":
        lam = cfg["lam"]
        GL, HL, CL = SL[..., 0], SL[..., 1], SL[..., 2]
        GR, HR, CR = SR[..., 0], SR[..., 1], SR[..., 2]
        GP, HP, CP = total[:, 0], total[:, 1], total[:, 2]

        def score(G, H):
            return G * G / (H + lam + 1e-12)

        raw = score(GL, HL) + score(GR, HR) - score(GP, HP)[:, None, None]
        gain = raw / jnp.maximum(CP, 1.0)[:, None, None]
        mcw = cfg["min_child_weight"]
        mi = jnp.maximum(cfg["min_instances"], 1e-6)
        valid = (CL >= mi) & (CR >= mi) & (HL >= mcw) & (HR >= mcw)
        return gain, valid
    # Gini (classification trees)
    wL = SL.sum(-1)
    wR = SR.sum(-1)
    wP = total.sum(-1)

    def gini(S, W):
        p = S / jnp.maximum(W, 1e-12)[..., None]
        return 1.0 - (p * p).sum(-1)

    impP = gini(total, wP)[:, None, None]
    wPn = jnp.maximum(wP, 1e-12)[:, None, None]
    gain = impP - (wL / wPn) * gini(SL, wL) - (wR / wPn) * gini(SR, wR)
    mi = jnp.maximum(cfg["min_instances"], 1e-6)
    valid = (wL >= mi) & (wR >= mi)
    return gain, valid


def _grow_tree(codes_s, edges, stats_s, w_s, feat_mask, cfg, *,
               depth: int, n_bins: int, mode: str):
    """Grow one complete-heap tree on the split-search sample.

    codes_s: (S, d) int32 bin codes (shared across trees/configs);
    stats_s: (S, k) per-row stat vector; w_s: (S,) row weights (folds ×
    bootstrap, pre-scaled by n/S); feat_mask: (d,) bool; cfg: traced scalars
    {max_depth, min_instances, min_info_gain, lam, min_child_weight}.

    Each level's histogram is ONE fused one-hot matmul — (node-one-hot ⊗
    weighted stats)ᵀ expanded against the bin codes → (m·k, d·n_bins) — and
    sample routing is the fused route_matmul, both pallas kernels from
    ops/tree_hist.py (neither the bin one-hot nor the cmp matrix ever
    reaches HBM; non-TPU backends fall back to the XLA einsums). Both batch
    cleanly under vmap over trees/configs (shared codes are never copied —
    vmap widens the stat/node columns of the single kernel call). Returns (feat_heap (2^D−1,),
    thresh_heap (2^D−1,), bin_heap (2^D−1,) int32 with sentinel n_bins for
    non-splits, node_s (S,) final sample leaf assignment).
    """
    S = codes_s.shape[0]
    d = feat_mask.shape[0]
    k = stats_s.shape[1]
    sw = (stats_s * w_s[:, None]).astype(jnp.bfloat16)      # (S, k)
    feat_heap = jnp.zeros((2 ** depth - 1,), jnp.int32)
    thr_heap = jnp.full((2 ** depth - 1,), jnp.inf, dtype=jnp.float32)
    bin_heap = jnp.full((2 ** depth - 1,), n_bins, dtype=jnp.int32)
    node = jnp.zeros((S,), jnp.int32)
    # every level calls the histogram kernel at the deepest level's width so
    # the whole loop shares ONE pallas program (early levels pad with zero
    # columns — the kernel is far from the bottleneck, compiles are not)
    mk_max = 2 ** (depth - 1) * k
    for level in range(depth):
        m = 2 ** level
        n_oh = (node[:, None]
                == jnp.arange(m, dtype=jnp.int32)).astype(jnp.bfloat16)
        A = (n_oh[:, :, None] * sw[:, None, :]).reshape(S, m * k)
        A = jnp.pad(A, ((0, 0), (0, mk_max - m * k)))
        hist = hist_matmul(codes_s, A, n_bins)[:m * k]
        hist = hist.reshape(m, k, d, n_bins).transpose(0, 2, 3, 1)
        cum = jnp.cumsum(hist, axis=2)
        total = cum[:, 0, -1, :]                      # (m, k) node totals
        SL = cum[:, :, :-1, :]                        # split "bin <= b"
        SR = total[:, None, None, :] - SL
        gain, valid = _split_gain(SL, SR, total, cfg, mode)
        valid = valid & feat_mask[None, :, None]
        gain = jnp.where(valid, gain, -jnp.inf)
        gflat = gain.reshape(m, d * (n_bins - 1))
        best = jnp.argmax(gflat, axis=1)
        bf = (best // (n_bins - 1)).astype(jnp.int32)
        bb = (best % (n_bins - 1)).astype(jnp.int32)
        bgain = jnp.take_along_axis(gflat, best[:, None], axis=1)[:, 0]
        active = jnp.asarray(level, jnp.float32) < cfg["max_depth"]
        do_split = active & jnp.isfinite(bgain) & (bgain > cfg["min_info_gain"])
        thr = jnp.where(do_split, edges[bf, bb], jnp.inf).astype(jnp.float32)
        feat_heap = feat_heap.at[m - 1: 2 * m - 1].set(
            jnp.where(do_split, bf, 0))
        thr_heap = thr_heap.at[m - 1: 2 * m - 1].set(thr)
        bb_eff = jnp.where(do_split, bb, n_bins)
        bin_heap = bin_heap.at[m - 1: 2 * m - 1].set(bb_eff)
        f_pad = jnp.pad(jnp.where(do_split, bf, 0), (0, 2 ** (depth - 1) - m))
        b_pad = jnp.pad(bb_eff, (0, 2 ** (depth - 1) - m),
                        constant_values=n_bins)
        D = route_matmul(codes_s, f_pad, b_pad, n_bins)[:, :m]   # (S, m)
        go = (D * n_oh).sum(-1) > 0.5
        node = 2 * node + go.astype(jnp.int32)
    return feat_heap, thr_heap, bin_heap, node


# ---------------------------------------------------------------------------
# Batched fit drivers (lax.map over configurations)
# ---------------------------------------------------------------------------

def _class_leaf(leaf_stats, leaf_w):
    """Per-leaf class probabilities from weighted counts."""
    tot = jnp.maximum(leaf_stats.sum(-1, keepdims=True), 1e-12)
    return leaf_stats / tot


def _mean_leaf(leaf_stats, leaf_w):
    """gh-mode with g=-y, h=1: Newton leaf -G/H = weighted mean of y."""
    return -leaf_stats[:, 0] / jnp.maximum(leaf_stats[:, 1], 1e-12)


def _make_stats(y, num_classes: int, task: str):
    if task == "classification":
        return jax.nn.one_hot(y.astype(jnp.int32), num_classes,
                              dtype=jnp.float32), "counts"
    ones = jnp.ones_like(y)
    return jnp.stack([-y, ones, ones], axis=1), "gh"


def _prep_tree_inputs(X, y, n_bins, num_classes, task, full_bin=True):
    """Shared per-fit prep: sampled edges, full + sampled int32 bin codes
    (the operands of the fused histogram/routing kernels), per-row stats,
    and the n/S weight rescale. ``full_bin`` skips binning the full dataset
    for fits that never touch it (GBT trains entirely on the sample)."""
    n = X.shape[0]
    samp = jnp.asarray(_sample_rows(n))
    Xs = X[samp]
    edges = _quantile_edges(Xs, n_bins)
    if full_bin:
        binned = _bin_features(X, edges)
        binned_s = binned[samp]
    else:
        binned = None
        binned_s = _bin_features(Xs, edges)
    stats, mode = _make_stats(y, num_classes, task)
    w_scale = jnp.asarray(n / samp.shape[0], X.dtype)
    return samp, edges, binned, binned_s, stats, mode, w_scale


@partial(jax.jit, static_argnames=("depth", "n_bins", "num_classes", "task"))
def _fit_dt_batch(X, y, weights, max_depth, min_inst, min_gain, *,
                  depth, n_bins, num_classes, task):
    d = X.shape[1]
    samp, edges, binned, binned_s, stats, mode, w_scale = \
        _prep_tree_inputs(X, y, n_bins, num_classes, task)
    fmask = jnp.ones((d,), bool)
    stats_s = stats[samp]

    def grow_one(w, md, mi, mg):
        cfg = {"max_depth": md, "min_instances": mi, "min_info_gain": mg,
               "lam": 1e-6, "min_child_weight": 0.0}
        return _grow_tree(binned_s, edges, stats_s, w[samp] * w_scale,
                          fmask, cfg, depth=depth, n_bins=n_bins, mode=mode)

    feat, thr, bheap, _ = jax.vmap(grow_one)(
        weights, max_depth, min_inst, min_gain)            # (B, H)

    # exact full-data leaf stats, one config at a time (bounds memory)
    def leaf_one(args):
        f, bh, w = args
        node = _route_codes(binned, f[None], bh[None], depth, n_bins, d)
        ls, lw = _leaf_reduce_forest(node, stats, w, depth)
        return (_class_leaf(ls[0], lw[0]) if task == "classification"
                else _mean_leaf(ls[0], lw[0])[:, None])

    leaf = jax.lax.map(leaf_one, (feat, bheap, weights))
    return {"feat": feat, "thresh": thr, "bins": bheap, "leaf": leaf,
            "edges": edges}


@partial(jax.jit, static_argnames=("depth", "n_bins", "num_classes", "task",
                                   "n_trees"))
def _fit_rf_batch(X, y, weights, max_depth, min_inst, min_gain, num_trees,
                  subsample, seeds, *, depth, n_bins, num_classes, task,
                  n_trees):
    n, d = X.shape
    samp, edges, binned, binned_s, stats, mode, w_scale = \
        _prep_tree_inputs(X, y, n_bins, num_classes, task)
    # per-tree feature subset (Spark featureSubsetStrategy auto:
    # sqrt for classification, 1/3 for regression)
    p_feat = float(np.ceil(np.sqrt(d)) / d) if task == "classification" \
        else max(1.0 / 3.0, 1.0 / d)
    S = binned_s.shape[0]
    stats_s = stats[samp]

    def one(args):
        w, md, mi, mg, ss, seed = args
        cfg = {"max_depth": md, "min_instances": mi, "min_info_gain": mg,
               "lam": 1e-6, "min_child_weight": 0.0}
        base = jax.random.PRNGKey(seed.astype(jnp.uint32))
        w_s = w[samp] * w_scale

        def grow_t(t):
            # bootstrap the split-search sample (the forest's randomness
            # lives in split selection; leaf stats are exact full-data
            # class/mean statistics per grown tree)
            k1, k2 = jax.random.split(jax.random.fold_in(base, t))
            boot_s = jax.random.poisson(k1, ss, (S,)).astype(X.dtype)
            fmask = jax.random.bernoulli(k2, p_feat, (d,))
            f, th, bh, _ = _grow_tree(
                binned_s, edges, stats_s, w_s * boot_s, fmask,
                cfg, depth=depth, n_bins=n_bins, mode=mode)
            return f, th, bh

        fs, ths, bhs = jax.vmap(grow_t)(jnp.arange(n_trees))   # (T, H)

        # exact full-data leaf stats in chunks of _LEAF_CHUNK trees: the
        # all-trees-at-once (n, T·L) leaf-one-hot peaks several GB at
        # millions of rows; per-chunk it is (n, C·L) while the matmuls stay
        # batched. Padded chunk slots carry sentinel heaps (all rows → leaf
        # 0) and are dropped after.
        C = _LEAF_CHUNK
        T_pad = -(-n_trees // C) * C
        fs_p = jnp.pad(fs, ((0, T_pad - n_trees), (0, 0)))
        bhs_p = jnp.pad(bhs, ((0, T_pad - n_trees), (0, 0)),
                        constant_values=n_bins)

        def leaf_chunk(args):
            f_c, bh_c = args                                   # (C, H)
            node = _route_codes(binned, f_c, bh_c, depth, n_bins, d)
            ls, lw = _leaf_reduce_forest(node, stats, w, depth)
            return (jax.vmap(_class_leaf)(ls, lw)
                    if task == "classification"
                    else jax.vmap(_mean_leaf)(ls, lw)[:, :, None])

        lv = jax.lax.map(leaf_chunk, (fs_p.reshape(T_pad // C, C, -1),
                                      bhs_p.reshape(T_pad // C, C, -1)))
        leaves = lv.reshape(T_pad, *lv.shape[2:])[:n_trees]    # (T, L, k)
        return fs, ths, bhs, leaves

    feat, thr, bheap, leaf = jax.lax.map(
        one, (weights, max_depth, min_inst, min_gain, subsample, seeds))
    tree_mask = (jnp.arange(n_trees)[None, :] <
                 num_trees[:, None]).astype(jnp.float32)
    return {"feat": feat, "thresh": thr, "bins": bheap, "leaf": leaf,
            "tree_mask": tree_mask,
            "edges": edges}


@partial(jax.jit, static_argnames=("depth", "n_bins", "num_classes", "task",
                                   "n_rounds"))
def _fit_gbt_batch(X, y, weights, max_depth, min_inst, min_gain, max_iter,
                   step_size, lam, min_child_weight, *, depth, n_bins,
                   num_classes, task, n_rounds):
    """Gradient boosting: binary logistic / regression squared / multiclass
    softmax (one tree per class per round, vmapped over the class axis)."""
    n, d = X.shape
    samp, edges, _, binned_s, _, _, w_scale = \
        _prep_tree_inputs(X, y, n_bins, num_classes, "regression",
                          full_bin=False)
    fmask = jnp.ones((d,), bool)
    C = num_classes if task == "multiclass" else 1
    B = weights.shape[0]
    S = binned_s.shape[0]
    L = 2 ** depth
    y_s = y[samp]
    Y1_s = (jax.nn.one_hot(y_s.astype(jnp.int32), max(C, 2), dtype=X.dtype)
            if task == "multiclass" else None)
    W_s = weights[:, samp] * w_scale                       # (B, S)
    # boosting state lives on the split-search sample: gradients, F and leaf
    # values all come from it (the XGBoost subsample design point); at 65k
    # rows and ≥2^depth≥8 leaves every leaf still averages 1000+ rows
    if task == "regression":
        f0 = ((weights * y[None, :]).sum(1)
              / jnp.maximum(weights.sum(1), 1.0))[:, None]  # (B, 1)
    else:
        f0 = jnp.zeros((B, C), X.dtype)
    F_init = jnp.broadcast_to(f0[:, None, :], (B, S, C))

    def grow_bc(g, h, w_b, cfg, lm):
        """One (config, class) tree on the sample; returns heaps, leaf
        values, and per-sample-row predictions."""
        st = jnp.stack([g, h, jnp.ones_like(g)], axis=1)   # (S, 3)
        f, th, bh, node_s = _grow_tree(
            binned_s, edges, st, w_b, fmask, cfg,
            depth=depth, n_bins=n_bins, mode="gh")
        l_oh = (node_s[:, None]
                == jnp.arange(L, dtype=jnp.int32)).astype(jnp.float32)
        sums = jnp.einsum("sl,sk->lk", l_oh, st * w_b[:, None],
                          preferred_element_type=jnp.float32)
        leaf = -sums[:, 0] / (sums[:, 1] + lm + 1e-12)
        pred_s = leaf[node_s]
        return f, th, bh, leaf, pred_s

    def one_config_round(F_b, args):
        """(S, C) state for one config → grown trees for each class."""
        w_b, cfg, lm, eta_b, it_b, t = args
        if task == "binary":
            p = jax.nn.sigmoid(F_b[:, 0])
            g = (p - y_s)[None, :]
            h = jnp.maximum(p * (1 - p), 1e-6)[None, :]
        elif task == "regression":
            g = (F_b[:, 0] - y_s)[None, :]
            h = jnp.ones((1, S), X.dtype)
        else:
            P = jax.nn.softmax(F_b, axis=1)
            g = (P - Y1_s[:, :C]).T
            h = jnp.maximum(P * (1 - P), 1e-6).T
        f, th, bh, leaf, preds = jax.vmap(
            grow_bc, in_axes=(0, 0, None, None, None))(g, h, w_b, cfg, lm)
        active = (t.astype(jnp.float32) < it_b).astype(X.dtype)
        return F_b + eta_b * active * preds.T, (f, th, bh, leaf)

    def round_step(F, t):                                   # F: (B, S, C)
        cfgs = {"max_depth": max_depth, "min_instances": min_inst,
                "min_info_gain": min_gain, "lam": lam,
                "min_child_weight": min_child_weight}
        F_new, out = jax.vmap(one_config_round)(
            F, (W_s, cfgs, lam, step_size, max_iter,
                jnp.broadcast_to(t, (B,))))
        return F_new, out

    _, (feat, thr, bheap, leaf) = jax.lax.scan(
        round_step, F_init, jnp.arange(n_rounds))
    # (T, B, C, ...) → (B, T, C, ...)
    feat = jnp.swapaxes(feat, 0, 1)
    thr = jnp.swapaxes(thr, 0, 1)
    bheap = jnp.swapaxes(bheap, 0, 1)
    leaf = jnp.swapaxes(leaf, 0, 1)
    tree_mask = (jnp.arange(n_rounds)[None, :] <
                 max_iter[:, None]).astype(jnp.float32)
    return {"feat": feat, "thresh": thr, "bins": bheap, "leaf": leaf,
            "f0": f0, "eta": step_size, "tree_mask": tree_mask,
            "edges": edges}


# ---------------------------------------------------------------------------
# Batched predict drivers
# ---------------------------------------------------------------------------

def _leaf_select(node, leaf_flat):
    """(n, A) one-hot of node-with-offset → values; fused one-hot matmul.
    node: (n, T) leaf ids; leaf_flat: (T·L, k) values. Returns (n, k) sums
    over trees (leaf_flat rows carry any per-tree weighting)."""
    n, T = node.shape
    A, k = leaf_flat.shape
    L = A // T
    comb = node + (jnp.arange(T, dtype=jnp.int32) * L)[None, :]
    # f32 end to end: served predictions must match the exact leaf values
    l_oh = (comb[:, :, None]
            == jnp.arange(A, dtype=jnp.int32).reshape(1, T, L)
            ).astype(jnp.float32).reshape(n, A)
    return jnp.einsum("na,ak->nk", l_oh, leaf_flat.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


@partial(jax.jit, static_argnames=("depth", "n_bins"))
def _predict_dt_batch(feat, bins, leaf, edges, X, *, depth, n_bins):
    d = X.shape[1]
    codes = _bin_features(X, edges)

    def one(args):
        f, bh, l = args
        node = _route_codes(codes, f[None], bh[None], depth, n_bins, d)
        return _leaf_select(node, l)                       # (n, k)

    return jax.lax.map(one, (feat, bins, leaf))            # (B, n, k)


@partial(jax.jit, static_argnames=("depth", "n_bins"))
def _predict_rf_batch(feat, bins, leaf, tree_mask, edges, X, *, depth,
                      n_bins):
    d = X.shape[1]
    codes = _bin_features(X, edges)

    def one(args):
        f, bh, l, m = args                                 # (T,H) (T,L,k) (T,)
        T, L, k = l.shape
        node = _route_codes(codes, f, bh, depth, n_bins, d)
        lw = (l * m[:, None, None]).reshape(T * L, k)
        s = _leaf_select(node, lw)
        return s / jnp.maximum(m.sum(), 1.0)

    return jax.lax.map(one, (feat, bins, leaf, tree_mask))  # (B, n, k)


@partial(jax.jit, static_argnames=("depth", "n_bins"))
def _predict_gbt_batch(feat, bins, leaf, f0, eta, tree_mask, edges, X, *,
                       depth, n_bins):
    d = X.shape[1]
    codes = _bin_features(X, edges)

    def one(args):
        f, bh, l, f0b, etab, m = args     # (T,C,H), leaf (T,C,L), m (T,)
        T, C, H = f.shape
        L = l.shape[-1]
        node = _route_codes(codes, f.reshape(T * C, H), bh.reshape(T * C, H),
                            depth, n_bins, d)              # (n, T·C)
        # class-routing matrix: value·one-hot(class) per (tree, class, leaf)
        lv = (l * m[:, None, None]).reshape(T * C * L)
        cls = jnp.tile(jnp.repeat(jnp.arange(C), L), T)
        M = lv[:, None] * (cls[:, None]
                           == jnp.arange(C)).astype(lv.dtype)  # (T·C·L, C)
        contrib = _leaf_select(node, M)                    # (n, C)
        return (f0b[None, :] + etab * contrib).T           # (C, n)

    return jax.lax.map(one, (feat, bins, leaf, f0, eta, tree_mask))


# ---------------------------------------------------------------------------
# Model families
# ---------------------------------------------------------------------------

def _g(grid, key, default):
    return grid[key] if key in grid else jnp.full_like(
        next(iter(grid.values())), default)


class _TreeFamilyBase(ModelFamily):
    #: config sweep runs under lax.map (sequential per chip), so the batch
    #: axis cannot shard over the 'model' mesh axis; rows still shard.
    shardable = False

    task_of = staticmethod(lambda problem: "classification"
                           if problem in ("binary", "multiclass")
                           else "regression")

    def _task(self, num_classes):
        if "regression" in self.supports and len(self.supports) == 1:
            return "regression"
        return "classification"

    def select_params(self, batched, idx):
        """Per-config slice, except the bin-edge table, which is shared by
        every configuration of a fit and stored once."""
        import jax
        return {k: (np.asarray(v) if k == "edges" else np.asarray(v[idx]))
                for k, v in batched.items()}

    def slice_params(self, batched, lo, hi):
        # quantile bin edges are shared across the whole sweep
        return {k: (v if k == "edges" else v[lo:hi])
                for k, v in batched.items()}

    @staticmethod
    def _edges_of(params):
        """Shared (d, n_bins−1) edge table whether params came from a batched
        fit (2-D) or went through predict_one's uniform [None] stacking."""
        e = jnp.asarray(params["edges"])
        return e[0] if e.ndim == 3 else e


#: reference DefaultSelectorParams.MaxDepth is {3, 6, 12}; the default grid
#: here stops at 6 because a complete-heap tree allocates 2^depth leaves —
#: depth 12 is fully supported, pass it explicitly when wanted.
_DEPTHS = (3, 6)


class DecisionTreeFamilyBase(_TreeFamilyBase):
    """reference OpDecisionTreeClassifier/Regressor (grids per
    DefaultSelectorParams: maxDepth × minInstancesPerNode {10,100}
    × minInfoGain {0.001,0.01,0.1})."""

    def default_grid(self, problem):
        return [{"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg}
                for d in _DEPTHS for mi in (10, 100)
                for mg in (0.001, 0.01, 0.1)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        task = self._task(num_classes)
        depth = int(np.max(np.asarray(grid["maxDepth"])))
        return _fit_dt_batch(
            X, y, weights, grid["maxDepth"], _g(grid, "minInstancesPerNode", 1.0),
            _g(grid, "minInfoGain", 0.0),
            depth=depth, n_bins=N_BINS,
            num_classes=max(num_classes, 2), task=task)

    def predict_batch(self, params, X, num_classes):
        depth = _depth_of(params["leaf"].shape[-2])
        edges = self._edges_of(params)
        out = _predict_dt_batch(params["feat"], params["bins"],
                                params["leaf"], edges, X, depth=depth,
                                n_bins=edges.shape[-1] + 1)
        return _shape_scores(out, num_classes, self._task(num_classes))

    def predict_one(self, fitted: FittedParams, X):
        params = {k: jnp.asarray(v)[None] for k, v in fitted.params.items()}
        out = np.asarray(self.predict_batch(
            params, jnp.asarray(X), fitted.num_classes))[0]
        return _parts(out, fitted.num_classes, self._task(fitted.num_classes))


class RandomForestFamilyBase(_TreeFamilyBase):
    """reference OpRandomForestClassifier/Regressor (numTrees 50,
    subsample 1.0 per DefaultSelectorParams; bootstrap via Poisson row
    weights, per-tree feature subsets)."""

    def default_grid(self, problem):
        return [{"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg,
                 "numTrees": 50, "subsamplingRate": 1.0}
                for d in _DEPTHS for mi in (10, 100)
                for mg in (0.001, 0.01, 0.1)]

    def fit_batch(self, X, y, weights, grid, num_classes):
        task = self._task(num_classes)
        depth = int(np.max(np.asarray(grid["maxDepth"])))
        n_trees = int(np.max(np.asarray(_g(grid, "numTrees", 20.0))))
        B = weights.shape[0]
        seeds = jnp.arange(B, dtype=jnp.float32) + 7.0
        return _fit_rf_batch(
            X, y, weights, grid["maxDepth"],
            _g(grid, "minInstancesPerNode", 1.0), _g(grid, "minInfoGain", 0.0),
            _g(grid, "numTrees", 20.0), _g(grid, "subsamplingRate", 1.0),
            seeds, depth=depth, n_bins=N_BINS,
            num_classes=max(num_classes, 2), task=task, n_trees=n_trees)

    def predict_batch(self, params, X, num_classes):
        depth = _depth_of(params["leaf"].shape[-2])
        edges = self._edges_of(params)
        out = _predict_rf_batch(params["feat"], params["bins"],
                                params["leaf"], params["tree_mask"],
                                edges, X, depth=depth,
                                n_bins=edges.shape[-1] + 1)
        return _shape_scores(out, num_classes, self._task(num_classes))

    def predict_one(self, fitted: FittedParams, X):
        params = {k: jnp.asarray(v)[None] for k, v in fitted.params.items()}
        out = np.asarray(self.predict_batch(
            params, jnp.asarray(X), fitted.num_classes))[0]
        return _parts(out, fitted.num_classes, self._task(fitted.num_classes))


class GBTFamilyBase(_TreeFamilyBase):
    """reference OpGBTClassifier/Regressor (maxIter 20, stepSize 0.1 per
    DefaultSelectorParams). Spark's GBTClassifier is binary-only; so is this
    one — multiclass boosting lives in the XGBoost families."""

    lam_default = 0.0
    mcw_default = 0.0

    def default_grid(self, problem):
        return [{"maxDepth": d, "minInstancesPerNode": mi, "minInfoGain": mg,
                 "maxIter": 20, "stepSize": 0.1}
                for d in _DEPTHS for mi in (10, 100)
                for mg in (0.001, 0.01, 0.1)]

    def _gbt_task(self, num_classes):
        if "regression" in self.supports and len(self.supports) == 1:
            return "regression"
        return "multiclass" if num_classes > 2 else "binary"

    def fit_batch(self, X, y, weights, grid, num_classes):
        task = self._gbt_task(num_classes)
        depth = int(np.max(np.asarray(grid["maxDepth"])))
        n_rounds = int(np.max(np.asarray(_g(grid, "maxIter", 20.0))))
        return _fit_gbt_batch(
            X, y, weights, grid["maxDepth"],
            _g(grid, "minInstancesPerNode", 0.0), _g(grid, "minInfoGain", 0.0),
            _g(grid, "maxIter", 20.0), _g(grid, "stepSize", 0.1),
            _g(grid, "lambda", self.lam_default),
            _g(grid, "minChildWeight", self.mcw_default),
            depth=depth, n_bins=N_BINS, num_classes=max(num_classes, 2),
            task=task, n_rounds=n_rounds)

    def predict_batch(self, params, X, num_classes):
        depth = _depth_of(params["leaf"].shape[-1])
        edges = self._edges_of(params)
        margins = _predict_gbt_batch(
            params["feat"], params["bins"], params["leaf"], params["f0"],
            params["eta"], params["tree_mask"], edges, X, depth=depth,
            n_bins=edges.shape[-1] + 1)                          # (B, C, n)
        task = self._gbt_task(num_classes)
        if task == "regression":
            return margins[:, 0, :]
        if task == "binary":
            return jax.nn.sigmoid(margins[:, 0, :])
        return jax.nn.softmax(jnp.swapaxes(margins, 1, 2), axis=-1)

    def predict_one(self, fitted: FittedParams, X):
        params = {k: jnp.asarray(v)[None] for k, v in fitted.params.items()}
        task = self._gbt_task(fitted.num_classes)
        out = np.asarray(self.predict_batch(
            params, jnp.asarray(X), fitted.num_classes))[0]
        if task == "regression":
            return {"prediction": out}
        if task == "binary":
            prob = np.stack([1 - out, out], axis=1)
            pred = (out > 0.5).astype(np.float32)
            return {"prediction": pred, "probability": prob,
                    "rawPrediction": np.log(np.clip(prob, 1e-12, None))}
        pred = out.argmax(axis=1).astype(np.float32)
        return {"prediction": pred, "probability": out,
                "rawPrediction": np.log(np.clip(out, 1e-12, None))}


# -- shared output shaping ---------------------------------------------------

def _depth_of(n_leaves: int) -> int:
    return int(np.log2(n_leaves))


def _shape_scores(out, num_classes, task):
    """(B, n, k) leaf outputs → family score convention: binary (B, n) p1;
    multiclass (B, n, C); regression (B, n)."""
    if task == "regression":
        return out[..., 0]
    if num_classes <= 2:
        return out[..., 1]
    return out[..., :num_classes]


def _parts(out, num_classes, task):
    if task == "regression":
        return {"prediction": out}
    prob = np.stack([1 - out, out], axis=1) if out.ndim == 1 else out
    pred = prob.argmax(axis=1).astype(np.float32)
    return {"prediction": pred, "probability": prob,
            "rawPrediction": np.log(np.clip(prob, 1e-12, None))}


# -- concrete registered families --------------------------------------------

class DecisionTreeClassifierFamily(DecisionTreeFamilyBase):
    name = "OpDecisionTreeClassifier"
    supports = frozenset({"binary", "multiclass"})


class DecisionTreeRegressorFamily(DecisionTreeFamilyBase):
    name = "OpDecisionTreeRegressor"
    supports = frozenset({"regression"})


class RandomForestClassifierFamily(RandomForestFamilyBase):
    name = "OpRandomForestClassifier"
    supports = frozenset({"binary", "multiclass"})


class RandomForestRegressorFamily(RandomForestFamilyBase):
    name = "OpRandomForestRegressor"
    supports = frozenset({"regression"})


class GBTClassifierFamily(GBTFamilyBase):
    name = "OpGBTClassifier"
    supports = frozenset({"binary"})


class GBTRegressorFamily(GBTFamilyBase):
    name = "OpGBTRegressor"
    supports = frozenset({"regression"})


class XGBoostClassifierFamily(GBTFamilyBase):
    """reference OpXGBoostClassifier (grid per DefaultSelectorParams:
    numRound {100} → maxIter, eta {0.1, 0.3} → stepSize, minChildWeight
    {1, 5, 10}); second-order splits with L2 ``lambda`` = 1 like XGBoost."""
    name = "OpXGBoostClassifier"
    supports = frozenset({"binary", "multiclass"})
    lam_default = 1.0
    mcw_default = 1.0

    def default_grid(self, problem):
        return [{"maxDepth": 6, "maxIter": 100, "stepSize": e,
                 "minChildWeight": m, "lambda": 1.0, "minInfoGain": 0.0,
                 "minInstancesPerNode": 0.0}
                for e in (0.1, 0.3) for m in (1.0, 5.0, 10.0)]


class XGBoostRegressorFamily(XGBoostClassifierFamily):
    name = "OpXGBoostRegressor"
    supports = frozenset({"regression"})


register_family(DecisionTreeClassifierFamily())
register_family(DecisionTreeRegressorFamily())
register_family(RandomForestClassifierFamily())
register_family(RandomForestRegressorFamily())
register_family(GBTClassifierFamily())
register_family(GBTRegressorFamily())
register_family(XGBoostClassifierFamily())
register_family(XGBoostRegressorFamily())
