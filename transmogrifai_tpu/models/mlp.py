"""Multilayer perceptron family — gradient-trained on TPU.

TPU-native replacement for the reference's Spark MLP wrapper (reference:
core/.../impl/classification/OpMultilayerPerceptronClassifier.scala:48). The
reference fits one JVM L-BFGS job per (layers, paramMap, fold); here the whole
hyperparameter × fold batch trains as ONE jitted, vmapped Adam program.

Variable hidden-layer widths would break vmap (different weight shapes per
configuration), so the family uses a fixed two-hidden-layer template sized to
the *maximum* width in the grid and applies per-configuration neuron masks
(``iota < width``) — every configuration shares one XLA program of MXU matmuls
and narrower networks simply carry masked-off columns. This is the standard
"pad-and-mask" trick for heterogeneous sweeps on SPMD hardware.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .api import FittedParams, ModelFamily, register_family

_PREC = jax.lax.Precision.HIGHEST


def _forward(params, X, masks):
    """Two masked hidden layers (sigmoid, matching Spark MLP) + linear head."""
    W1, b1, W2, b2, W3, b3 = params
    m1, m2 = masks
    h1 = jax.nn.sigmoid(X @ W1 + b1) * m1
    h2 = jax.nn.sigmoid(h1 @ W2 + b2) * m2
    return h2 @ W3 + b3


def _init(key, d, h_max, num_classes, dtype):
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = jnp.sqrt(2.0 / (d + h_max)).astype(dtype)
    s2 = jnp.sqrt(2.0 / (2 * h_max)).astype(dtype)
    s3 = jnp.sqrt(2.0 / (h_max + num_classes)).astype(dtype)
    return (jax.random.normal(k1, (d, h_max), dtype) * s1,
            jnp.zeros((h_max,), dtype),
            jax.random.normal(k2, (h_max, h_max), dtype) * s2,
            jnp.zeros((h_max,), dtype),
            jax.random.normal(k3, (h_max, num_classes), dtype) * s3,
            jnp.zeros((num_classes,), dtype))


@partial(jax.jit, static_argnames=("h_max", "num_classes", "iters"))
def _fit_mlp(X, y_idx, w, h1, h2, step_size, seed, h_max, num_classes, iters):
    n, d = X.shape
    dtype = X.dtype
    cnt = jnp.maximum(w.sum(), 1.0)
    Y = jax.nn.one_hot(y_idx, num_classes, dtype=dtype)
    m1 = (jnp.arange(h_max, dtype=jnp.float32) < h1).astype(dtype)
    m2 = (jnp.arange(h_max, dtype=jnp.float32) < h2).astype(dtype)
    params = _init(jax.random.PRNGKey(seed.astype(jnp.int32)), d, h_max,
                   num_classes, dtype)

    def loss_fn(params):
        logits = _forward(params, X, (m1, m2))
        lp = jax.nn.log_softmax(logits, axis=-1)
        return (-(Y * lp).sum(axis=1) * w).sum() / cnt

    b1_, b2_, eps = 0.9, 0.999, 1e-8
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)

    def step(carry, i):
        params, m, v = carry
        g = jax.grad(loss_fn)(params)
        m = jax.tree_util.tree_map(lambda a, b: b1_ * a + (1 - b1_) * b, m, g)
        v = jax.tree_util.tree_map(lambda a, b: b2_ * a + (1 - b2_) * b * b, v, g)
        t = i + 1.0
        params = jax.tree_util.tree_map(
            lambda p, mm, vv: p - step_size * (mm / (1 - b1_ ** t)) /
            (jnp.sqrt(vv / (1 - b2_ ** t)) + eps), params, m, v)
        return (params, m, v), None

    (params, _, _), _ = jax.lax.scan(
        step, (params, zeros, zeros), jnp.arange(iters, dtype=dtype))
    return params, m1, m2


_fit_mlp_batch = jax.jit(
    jax.vmap(_fit_mlp, in_axes=(None, None, 0, 0, 0, 0, 0, None, None, None)),
    static_argnames=("h_max", "num_classes", "iters"))


@jax.jit
def _predict_mlp_batch(params, masks, X):
    return jax.nn.softmax(
        jax.vmap(_forward, in_axes=(0, None, 0))(params, X, masks), axis=-1)


class MultilayerPerceptronFamily(ModelFamily):
    """reference OpMultilayerPerceptronClassifier (Spark MLP: sigmoid hidden
    layers, softmax output; grid over hidden-layer sizes and stepSize)."""

    name = "OpMultilayerPerceptronClassifier"
    supports = frozenset({"binary", "multiclass"})

    def __init__(self, max_iter: int = 100, seed: int = 42):
        self.max_iter = max_iter
        self.seed = seed

    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        return [{"hiddenLayer1": h, "hiddenLayer2": h, "stepSize": 0.05}
                for h in (10, 50, 100)]

    def _h_max(self, grid: Dict[str, jnp.ndarray]) -> int:
        return int(max(np.max(np.asarray(grid["hiddenLayer1"])),
                       np.max(np.asarray(grid["hiddenLayer2"]))))

    def fit_batch(self, X, y, weights, grid, num_classes):
        B = weights.shape[0]
        h_max = self._h_max(grid)
        nc = max(num_classes, 2)
        seeds = jnp.arange(B, dtype=jnp.float32) + float(self.seed)
        params, m1, m2 = _fit_mlp_batch(
            X, y.astype(jnp.int32), weights,
            grid["hiddenLayer1"].astype(jnp.float32),
            grid["hiddenLayer2"].astype(jnp.float32),
            grid["stepSize"], seeds, h_max, nc, self.max_iter)
        return {"params": params, "masks": (m1, m2), "num_classes": nc}

    def slice_params(self, batched, lo, hi):
        import jax
        return {
            "params": jax.tree_util.tree_map(lambda a: a[lo:hi],
                                             batched["params"]),
            "masks": jax.tree_util.tree_map(lambda a: a[lo:hi],
                                            batched["masks"]),
            "num_classes": batched["num_classes"],
        }

    def predict_batch(self, params, X, num_classes):
        probs = _predict_mlp_batch(params["params"], params["masks"], X)
        if num_classes <= 2:
            return probs[:, :, 1]
        return probs

    def select_params(self, batched, idx: int):
        return jax.tree_util.tree_map(
            lambda a: np.asarray(a[idx]) if hasattr(a, "__getitem__") else a,
            {"params": batched["params"], "masks": batched["masks"],
             "num_classes": batched["num_classes"]},
            is_leaf=lambda x: isinstance(x, (jnp.ndarray, np.ndarray, int)))

    def predict_parts(self, fitted: FittedParams, X):
        p = fitted.params
        logits = _forward(p["params"], X, p["masks"])
        prob = jax.nn.softmax(logits, axis=-1)
        pred = prob.argmax(axis=1).astype(jnp.float32)
        return {"prediction": pred, "probability": prob,
                "rawPrediction": logits}

    def predict_one(self, fitted: FittedParams, X) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v)
                for k, v in self.predict_parts(fitted, jnp.asarray(X)).items()}


register_family(MultilayerPerceptronFamily())
