"""Model-family API — the TPU re-design of the reference's Spark model wrappers.

The reference wraps SparkML ``Predictor``s (reference:
core/.../sparkwrappers/specific/OpPredictorWrapper.scala:67-122) and fits one
JVM job per (model, paramMap, fold). Here a *family* exposes batched, jitted
fits: ``fit_batch`` consumes stacked hyperparameters plus per-configuration
row weights and returns stacked parameters — so ModelSelector's whole
``|grid| × |folds|`` sweep compiles to ONE XLA program of MXU matmuls instead
of thousands of Spark jobs (the SURVEY §2.10 P2 axis, the north-star metric).
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..utils.jax_cache import ensure_compilation_cache

ensure_compilation_cache()
import numpy as np


@dataclass
class FittedParams:
    """One fitted configuration's parameters (a pytree of arrays) plus the
    hyperparameters that produced it."""
    family: str
    params: Any
    hyper: Dict[str, Any]
    num_classes: int = 2


class ModelFamily(abc.ABC):
    """A homogeneous model family whose hyperparameter grid can be vmapped.

    Mesh sharding contract (docs/parallel.md): when the ModelSelector sweep
    runs over a ('data', 'model') mesh, ``fit_batch`` / ``sweep_fit_batch``
    are traced into one GSPMD program whose operands carry these shardings —
    X rows over 'data' (features replicated), y over 'data', weights
    ('model', 'data'), grid arrays over 'model' — and the returned stacked
    params must keep their leading config axis partitionable over 'model'
    (no cross-config reductions; per-config math only, which every vmapped
    fit satisfies by construction). ``shardable=False`` opts a family's
    config axis out (sequential-scan fits whose chunk loop is not a single
    vmapped program); rows still shard over 'data'.
    """

    #: family name, e.g. "OpLogisticRegression"
    name: str = ""
    #: problem kinds: subset of {"binary", "multiclass", "regression"}
    supports: frozenset = frozenset()
    #: config (B) axis may shard over the mesh 'model' axis; False keeps
    #: configs whole per device (see the sharding contract above)
    shardable: bool = True
    #: grid arrays may be passed as ONE packed traced f32 device block
    #: (uploaded sharded over 'model', donated for buffer reuse) instead of
    #: host constants baked into the trace. Only safe for families whose fit
    #: reads grid values as arrays; families deriving STATIC trace structure
    #: from the grid (tree depth bucketing) must keep host constants
    traced_grid_ok: bool = False
    #: fitted-param keys where ±inf is a STRUCTURAL sentinel, not divergence
    #: (tree thresholds use +inf for "stopped node routes every row left");
    #: the refit non-finite guard (robustness/guards.params_finite) checks
    #: these keys for NaN only
    inf_ok_params: tuple = ()

    @abc.abstractmethod
    def default_grid(self, problem: str) -> List[Dict[str, Any]]:
        """Default hyperparameter grid (reference DefaultSelectorParams)."""

    @abc.abstractmethod
    def fit_batch(self, X: jnp.ndarray, y: jnp.ndarray,
                  weights: jnp.ndarray, grid: Dict[str, jnp.ndarray],
                  num_classes: int) -> Any:
        """Fit B configurations at once.

        X: (n, d); y: (n,); weights: (B, n) row weights (0 = excluded);
        grid: dict of (B,) hyperparameter arrays. Returns stacked params with
        leading axis B.
        """

    def sweep_fit_batch(self, X: jnp.ndarray, y: jnp.ndarray,
                        weights: jnp.ndarray, grid: Dict[str, jnp.ndarray],
                        num_classes: int) -> Any:
        """``fit_batch`` for CV-sweep candidates. Families may trade exact
        fitted state for sweep throughput here (tree families use
        sample-based leaf values — validation scoring only); the selector
        refits the winner through plain ``fit_batch``. Default: identical
        to ``fit_batch``."""
        return self.fit_batch(X, y, weights, grid, num_classes)

    @abc.abstractmethod
    def predict_batch(self, params: Any, X: jnp.ndarray,
                      num_classes: int) -> jnp.ndarray:
        """Scores for stacked params: (B, n) margins / (B, n, C) probabilities."""

    @abc.abstractmethod
    def predict_one(self, fitted: FittedParams, X: jnp.ndarray) -> Dict[str, np.ndarray]:
        """Single-model prediction parts: {'prediction', 'probability'?, 'rawPrediction'?}."""

    def predict_parts(self, fitted: FittedParams,
                      X: jnp.ndarray) -> Optional[Dict[str, jnp.ndarray]]:
        """jit-traceable dual of ``predict_one``: identical parts as jnp
        arrays (the fitted params close over the trace as constants), so the
        winning model's Prediction emission can compile INTO the one fused
        serve program (local/scoring.compiled_score_function — reference
        analog FitStagesUtil.scala:96-119 folds every stage in one pass).
        None = this family's predict is host-only and the serve-path fusion
        must leave the model stage outside the compiled program."""
        return None

    def feature_importances(self, fitted: "FittedParams") -> Optional[np.ndarray]:
        """Per-input-dimension contribution scores for ModelInsights
        (|coefficients| for linear families, split frequencies for trees);
        None when the family has no natural attribution."""
        p = fitted.params
        if isinstance(p, dict):
            if "coef" in p:
                return np.abs(np.asarray(p["coef"])).reshape(-1)
            if "W" in p:
                return np.abs(np.asarray(p["W"])).mean(axis=-1).reshape(-1)
            if "feat" in p or "feat_lv" in p:
                # tree ensembles (heap or slot-chain layout): how often each
                # feature splits; sentinel-binned entries are stopped/padded
                # nodes, not real splits, and must not count toward slot 0
                fk, bk = (("feat", "bins") if "feat" in p
                          else ("feat_lv", "bins_lv"))
                feats = np.asarray(p[fk]).reshape(-1).astype(np.int64)
                if bk in p and "edges" in p:
                    nb = np.asarray(p["edges"]).shape[-1] + 1
                    feats = feats[np.asarray(p[bk]).reshape(-1) < nb]
                feats = feats[feats >= 0]
                d = int(np.asarray(p.get("num_features", feats.max() + 1 if
                                         feats.size else 1)))
                counts = np.bincount(feats, minlength=d).astype(np.float64)
                return counts / max(counts.sum(), 1.0)
        return None

    def select_params(self, batched: Any, idx: int) -> Any:
        """Extract configuration ``idx`` from stacked params."""
        import jax
        return jax.tree_util.tree_map(lambda a: np.asarray(a[idx]), batched)

    #: score CV candidates on their own fold's gathered rows (capped at
    #: OpValidator.max_eval_rows) instead of full-row masked scoring; with
    #: the cap this wins even for single-matmul predicts, and the fold
    #: gather is shared across families. See OpValidator.validate.
    fold_sliced_predict: bool = True

    def slice_params(self, batched: Any, lo: int, hi: int) -> Any:
        """Slice a config-range [lo, hi) of stacked params, on device.
        Families whose params carry unbatched leaves (shared bin edges,
        static ints) override this to leave those leaves whole."""
        import jax
        return jax.tree_util.tree_map(lambda a: a[lo:hi], batched)

    def grid_to_arrays(self, grid: Sequence[Dict[str, Any]]) -> Dict[str, jnp.ndarray]:
        keys = sorted({k for g in grid for k in g})
        return {k: jnp.asarray([g[k] for g in grid], dtype=jnp.float32) for k in keys}


MODEL_REGISTRY: Dict[str, ModelFamily] = {}


def register_family(family: ModelFamily) -> ModelFamily:
    MODEL_REGISTRY[family.name] = family
    return family
