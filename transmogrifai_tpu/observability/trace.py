"""Hierarchical tracing: spans with monotonic timestamps and a bounded buffer.

The reference observes its runs through a Spark listener (reference:
utils/.../spark/OpSparkListener.scala:55-110 — per-stage task metrics pushed
by the cluster scheduler); a JAX process has no cluster scheduler to listen
to, so the spans are emitted by the framework itself at every interesting
boundary: ``workflow.train`` → ``stage.fit``/``stage.transform`` (per layer),
``sweep.family`` (per ModelSelector candidate family), ``score.micro_batch``
(per serving batch). Fault recoveries (robustness/) land as span *events* on
whatever span is open, so a trace shows retries and quarantines in line with
the work they interrupted.

Cost model: a disabled tracer is one env/flag check per ``span()`` call —
no Span objects, no buffer writes — so the always-compiled call sites add
nothing measurable to the hot paths (the same discipline as
``robustness/faults.py`` sites). Enabled, finished spans go into a bounded
ring (``TG_TRACE_MAX_SPANS``, default 65536) so a long-lived scorer cannot
grow without bound; drops are counted, never silent.

Switches: ``TG_TRACE=1`` enables tracing process-wide;
:func:`enable_tracing` overrides programmatically (``None`` returns control
to the env). State is process-global by design — like the reference's one
listener per SparkContext — and :func:`reset` gives tests a clean slate.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

#: env switch: truthy value enables tracing process-wide
TRACE_ENV = "TG_TRACE"

_FALSY = ("", "0", "false", "False", "no")

_enabled_override: Optional[bool] = None


def tracing_enabled() -> bool:
    """True when spans are being recorded (TG_TRACE, unless overridden)."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(TRACE_ENV, "") not in _FALSY


def enable_tracing(on: Optional[bool]) -> None:
    """Force tracing on/off from code (the CLI and tests); ``None`` hands
    control back to the ``TG_TRACE`` environment switch."""
    global _enabled_override
    _enabled_override = None if on is None else bool(on)


class Span:
    """One timed operation. ``ts_ns``/``dur_ns`` are monotonic-clock
    nanoseconds relative to the owning tracer's epoch; ``dur_ns`` is None
    while open (and stays None for instant events). ``events`` are
    point-in-time annotations: ``(name, ts_ns, attrs)``."""

    __slots__ = ("name", "cat", "span_id", "parent_id", "ts_ns", "dur_ns",
                 "attrs", "events", "tid")

    def __init__(self, name: str, cat: str, span_id: int,
                 parent_id: Optional[int], ts_ns: int, tid: int,
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.cat = cat
        self.span_id = span_id
        self.parent_id = parent_id
        self.ts_ns = ts_ns
        self.dur_ns: Optional[int] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.events: List[Tuple[str, int, Dict[str, Any]]] = []
        self.tid = tid

    def set_attr(self, **kv: Any) -> "Span":
        self.attrs.update(kv)
        return self

    def add_event(self, name: str, **attrs: Any) -> "Span":
        self.events.append((name, _now_rel_ns(), attrs))
        return self

    @property
    def seconds(self) -> float:
        return (self.dur_ns or 0) / 1e9

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name, "cat": self.cat, "id": self.span_id,
            "parent": self.parent_id, "tsNs": self.ts_ns,
            "durNs": self.dur_ns, "tid": self.tid, "attrs": dict(self.attrs),
            "events": [{"name": n, "tsNs": t, "attrs": dict(a)}
                       for n, t, a in self.events],
        }


class _NullSpan:
    """Yielded by :func:`span` when tracing is off: every method is a no-op
    so call sites never need an enabled check around attribute writes."""

    __slots__ = ()

    def set_attr(self, **kv: Any) -> "_NullSpan":
        return self

    def add_event(self, name: str, **attrs: Any) -> "_NullSpan":
        return self

    seconds = 0.0


NULL_SPAN = _NullSpan()


class Tracer:
    """Span collector: per-thread open-span stacks (spans nest within a
    thread), one shared bounded ring of finished spans."""

    def __init__(self, max_spans: Optional[int] = None):
        if max_spans is None:
            max_spans = int(os.environ.get("TG_TRACE_MAX_SPANS", "65536"))
        self.max_spans = max(1, int(max_spans))
        self.spans: deque = deque(maxlen=self.max_spans)
        self.dropped = 0
        #: wall-clock anchor for the monotonic epoch (export metadata)
        self.epoch_unix = time.time()
        self.epoch_ns = time.perf_counter_ns()
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._lock = threading.Lock()

    # -- span lifecycle ------------------------------------------------------
    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def start(self, name: str, cat: str = "",
              attrs: Optional[Dict[str, Any]] = None) -> Span:
        st = self._stack()
        s = Span(name, cat, next(self._ids),
                 st[-1].span_id if st else None,
                 time.perf_counter_ns() - self.epoch_ns,
                 threading.get_ident(), attrs)
        st.append(s)
        return s

    def end(self, s: Span) -> None:
        s.dur_ns = (time.perf_counter_ns() - self.epoch_ns) - s.ts_ns
        st = self._stack()
        if s in st:          # tolerate out-of-order ends (generator exits)
            st.remove(s)
        self._append(s)

    def instant(self, name: str, attrs: Optional[Dict[str, Any]] = None
                ) -> Span:
        """A free-standing point event (no open span to attach to)."""
        s = Span(name, "event", next(self._ids), None,
                 time.perf_counter_ns() - self.epoch_ns,
                 threading.get_ident(), attrs)
        self._append(s)
        return s

    def _append(self, s: Span) -> None:
        with self._lock:
            if len(self.spans) == self.spans.maxlen:
                self.dropped += 1
            self.spans.append(s)
        # span close summary into the always-on flight recorder: when a
        # run is traced, the black box sees the traced world too — a
        # post-mortem bundle then carries the span names/durations of the
        # seconds before the trigger (observability/blackbox.py)
        from . import blackbox as _blackbox
        if _blackbox.blackbox_enabled():
            _blackbox.record("span", name=s.name, cat=s.cat,
                             durNs=s.dur_ns)

    # -- queries -------------------------------------------------------------
    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def finished(self) -> List[Span]:
        with self._lock:
            return list(self.spans)

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()
            self.dropped = 0


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


def set_tracer(t: Tracer) -> Tracer:
    global _TRACER
    _TRACER = t
    return t


def reset() -> None:
    """Fresh tracer + env-driven enablement (test isolation; see
    tests/conftest.py)."""
    global _TRACER, _enabled_override
    _TRACER = Tracer()
    _enabled_override = None


def _now_rel_ns() -> int:
    return time.perf_counter_ns() - _TRACER.epoch_ns


@contextmanager
def span(name: str, cat: str = "", **attrs: Any):
    """``with span("stage.fit", uid=...) as s:`` — records one Span when
    tracing is enabled; otherwise yields the inert :data:`NULL_SPAN`."""
    if not tracing_enabled():
        yield NULL_SPAN
        return
    t = _TRACER
    s = t.start(name, cat, attrs)
    try:
        yield s
    finally:
        t.end(s)


def add_event(name: str, **attrs: Any) -> None:
    """Annotate the current thread's open span (or record a free-standing
    instant event when none is open). No-op when tracing is disabled —
    the robustness choke points call this unconditionally."""
    if not tracing_enabled():
        return
    s = _TRACER.current()
    if s is not None:
        s.add_event(name, **attrs)
    else:
        _TRACER.instant(name, attrs)
