"""Exporters: Chrome trace-event JSON, Prometheus text, JSONL event log.

All pure-stdlib and written atomically through the checkpoint machinery's
tmp+fsync+rename helper (``manifest.atomic_write_bytes``): a preempted
export leaves either the previous file or the new one, never a torn JSON —
the same discipline as every other artifact this framework writes.

* :func:`chrome_trace` / :func:`write_chrome_trace` — the trace-event
  format (``{"traceEvents": [{"name","ph","ts","pid","tid",...}]}``)
  loadable in ``chrome://tracing`` or https://ui.perfetto.dev. Spans export
  as complete events (``ph: "X"``, microsecond ``ts``/``dur``); span events
  and free-standing instants as ``ph: "i"``.
* :func:`write_prometheus` — the registry's text exposition format
  (``metrics.prom``), scrape-able or pushable as-is.
* :func:`write_jsonl` — one JSON object per finished span, for ad-hoc
  ``jq``/pandas analysis of long runs.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ..manifest import atomic_write_bytes
from . import metrics as _metrics
from . import trace as _trace


def chrome_trace(tracer: Optional[_trace.Tracer] = None) -> Dict[str, Any]:
    """Render the tracer's finished spans as a Chrome trace-event document."""
    t = tracer or _trace.tracer()
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    for s in t.finished():
        base = {"name": s.name, "cat": s.cat or "span", "pid": pid,
                "tid": s.tid, "ts": s.ts_ns / 1e3}
        if s.dur_ns is None:       # instant event
            events.append({**base, "ph": "i", "s": "t",
                           "args": dict(s.attrs)})
        else:
            events.append({**base, "ph": "X", "dur": s.dur_ns / 1e3,
                           "args": dict(s.attrs)})
        for name, ts_ns, attrs in s.events:
            events.append({"name": name, "cat": "event", "ph": "i",
                           "s": "t", "pid": pid, "tid": s.tid,
                           "ts": ts_ns / 1e3, "args": dict(attrs)})
    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "epochUnix": t.epoch_unix,
            "droppedSpans": t.dropped,
            "maxSpans": t.max_spans,
        },
    }


def write_chrome_trace(path: str,
                       tracer: Optional[_trace.Tracer] = None) -> str:
    doc = chrome_trace(tracer)
    atomic_write_bytes(path, json.dumps(doc).encode("utf-8"))
    return path


def write_prometheus(path: str,
                     registry: Optional[_metrics.MetricsRegistry] = None
                     ) -> str:
    reg = registry or _metrics.registry()
    atomic_write_bytes(path, reg.to_prometheus().encode("utf-8"))
    return path


def write_jsonl(path: str, tracer: Optional[_trace.Tracer] = None) -> str:
    t = tracer or _trace.tracer()
    lines = [json.dumps(s.to_json()) for s in t.finished()]
    atomic_write_bytes(path, ("\n".join(lines) + ("\n" if lines else ""))
                       .encode("utf-8"))
    return path
