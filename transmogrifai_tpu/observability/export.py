"""Exporters: Chrome trace-event JSON, Prometheus text, JSONL event log.

All pure-stdlib and written atomically through the checkpoint machinery's
tmp+fsync+rename helper (``manifest.atomic_write_bytes``): a preempted
export leaves either the previous file or the new one, never a torn JSON —
the same discipline as every other artifact this framework writes.

* :func:`chrome_trace` / :func:`write_chrome_trace` — the trace-event
  format (``{"traceEvents": [{"name","ph","ts","pid","tid",...}]}``)
  loadable in ``chrome://tracing`` or https://ui.perfetto.dev. Spans export
  as complete events (``ph: "X"``, microsecond ``ts``/``dur``); span events
  and free-standing instants as ``ph: "i"``.
* :func:`prometheus_text` / :func:`write_prometheus` — the registry's
  text exposition format (``metrics.prom``), scrape-able or pushable
  as-is. Histograms export as REAL cumulative ``_bucket``/``_sum``/
  ``_count`` series (the bucket boundaries are the streaming sketch's
  bin centroids, the cumulative counts its ``Sum`` estimates — monotone
  by construction, ``+Inf`` exact), so Prometheus can aggregate and
  ``histogram_quantile`` across instances — the one thing the old
  quantile-summary exposition could never do. ``TG_PROM_SUMMARY_COMPAT=1``
  (or ``compat=True``) restores the pre-round-11 summary lines for
  scrapers built against them.
* :func:`write_jsonl` — one JSON object per finished span, for ad-hoc
  ``jq``/pandas analysis of long runs.

When the rendered registry is attached to the windowed time-series
sampler (``observability/timeseries.py``), the exposition additionally
carries the **windowed series as gauges with a ``window`` label**
(``TG_SAMPLE_WINDOWS``, default ``60,300`` seconds): every counter gets
a ``<name>_rate{...,window="60"}`` per-second rate and every histogram
gets ``<name>_p50/_p95/_p99{...,window="60"}`` windowed quantiles (SPDT
sketch subtraction) — the scrape-side view of the same numbers the SLO
engine (``observability/slo.py``) burns budgets on.
"""
from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional

from ..manifest import atomic_write_bytes
from . import metrics as _metrics
from . import trace as _trace


def chrome_trace(tracer: Optional[_trace.Tracer] = None) -> Dict[str, Any]:
    """Render the tracer's finished spans as a Chrome trace-event document."""
    t = tracer or _trace.tracer()
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    for s in t.finished():
        base = {"name": s.name, "cat": s.cat or "span", "pid": pid,
                "tid": s.tid, "ts": s.ts_ns / 1e3}
        if s.dur_ns is None:       # instant event
            events.append({**base, "ph": "i", "s": "t",
                           "args": dict(s.attrs)})
        else:
            events.append({**base, "ph": "X", "dur": s.dur_ns / 1e3,
                           "args": dict(s.attrs)})
        for name, ts_ns, attrs in s.events:
            events.append({"name": name, "cat": "event", "ph": "i",
                           "s": "t", "pid": pid, "tid": s.tid,
                           "ts": ts_ns / 1e3, "args": dict(attrs)})
    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "epochUnix": t.epoch_unix,
            "droppedSpans": t.dropped,
            "maxSpans": t.max_spans,
        },
    }


def write_chrome_trace(path: str,
                       tracer: Optional[_trace.Tracer] = None) -> str:
    doc = chrome_trace(tracer)
    atomic_write_bytes(path, json.dumps(doc).encode("utf-8"))
    return path


#: compat switch: truthy restores the quantile-summary exposition
PROM_COMPAT_ENV = "TG_PROM_SUMMARY_COMPAT"
_FALSY = ("", "0", "false", "False", "no")

#: comma-separated window lengths (seconds) for the windowed exposition
SAMPLE_WINDOWS_ENV = "TG_SAMPLE_WINDOWS"
DEFAULT_SAMPLE_WINDOWS = (60.0, 300.0)


def _prom_compat() -> bool:
    return os.environ.get(PROM_COMPAT_ENV, "") not in _FALSY


def export_windows() -> List[float]:
    raw = os.environ.get(SAMPLE_WINDOWS_ENV, "")
    if not raw:
        return list(DEFAULT_SAMPLE_WINDOWS)
    out: List[float] = []
    for part in raw.split(","):
        try:
            v = float(part.strip())
            if v > 0:
                out.append(v)
        except ValueError:
            continue
    return out or list(DEFAULT_SAMPLE_WINDOWS)


def windowed_prometheus_lines(sampler, windows: Optional[List[float]] = None
                              ) -> List[str]:
    """The windowed exposition block (module docstring): counter rates
    and histogram quantiles over each window as gauges carrying a
    ``window`` label. Empty when the sampler holds fewer than two
    samples (no window to subtract yet)."""
    if sampler is None or sampler.snapshot()["samples"] < 2:
        return []
    labels_of = _metrics._labels
    num = _metrics._num
    windows = windows if windows is not None else export_windows()
    lines: List[str] = []
    for name in sampler.counter_names():
        series_name = f"{name}_rate"
        emitted_type = False
        for lbls in sampler.series_labels(name):
            for w in windows:
                v = sampler.rate(name, w, **lbls)
                if not emitted_type:
                    lines.append(f"# TYPE {series_name} gauge")
                    emitted_type = True
                lines.append(
                    f"{series_name}"
                    f"{labels_of({**lbls, 'window': f'{w:g}'})} {num(v)}")
    for name in sampler.histogram_names():
        for q in _metrics.QUANTILES:
            series_name = f"{name}_p{int(q * 100)}"
            emitted_type = False
            for lbls in sampler.series_labels(name):
                for w in windows:
                    v = sampler.quantile(name, q, w, **lbls)
                    if not math.isfinite(v):
                        continue
                    if not emitted_type:
                        lines.append(f"# TYPE {series_name} gauge")
                        emitted_type = True
                    lines.append(
                        f"{series_name}"
                        f"{labels_of({**lbls, 'window': f'{w:g}'})} "
                        f"{num(v)}")
    return lines


def prometheus_text(registry: Optional[_metrics.MetricsRegistry] = None,
                    compat: Optional[bool] = None,
                    sampler: Optional[Any] = None) -> str:
    """Render a registry in the Prometheus text exposition format
    (validated against the format grammar in tests/test_blackbox.py).

    Histograms (default): ``# TYPE <name> histogram`` with cumulative
    ``<name>_bucket{le="..."}`` series from
    :meth:`~.metrics.Histogram.cumulative_buckets` plus the exact
    ``le="+Inf"``/``_sum``/``_count`` triple. ``compat=True`` (or the
    ``TG_PROM_SUMMARY_COMPAT`` env): the pre-round-11 summary exposition
    — ``# TYPE <name> summary`` with p50/p95/p99 ``quantile`` series."""
    reg = registry or _metrics.registry()
    if compat is None:
        compat = _prom_compat()
    labels_of = _metrics._labels
    num = _metrics._num
    lines: List[str] = []
    for name, kind, help, ms in reg.collect():
        if help:
            lines.append(f"# HELP {name} {_metrics._escape_help(help)}")
        is_hist = kind in ("histogram", "summary")
        lines.append(f"# TYPE {name} "
                     f"{('summary' if compat else 'histogram') if is_hist else kind}")
        for m in ms:
            if isinstance(m, _metrics.Histogram):
                if compat:
                    if m.count:
                        for q in _metrics.QUANTILES:
                            v = m.quantile(q)
                            if math.isfinite(v):
                                lines.append(
                                    f"{name}{labels_of(m.labels, quantile=q)}"
                                    f" {num(v)}")
                else:
                    for le, cum in m.cumulative_buckets():
                        lines.append(
                            f"{name}_bucket{labels_of(m.labels, le=num(le))}"
                            f" {num(cum)}")
                    lines.append(
                        f"{name}_bucket{labels_of(m.labels, le='+Inf')} "
                        f"{m.count}")
                lines.append(f"{name}_sum{labels_of(m.labels)} "
                             f"{num(m.sum)}")
                lines.append(f"{name}_count{labels_of(m.labels)} "
                             f"{m.count}")
            else:
                lines.append(f"{name}{labels_of(m.labels)} {num(m.value)}")
    # windowed exposition: when the registry is sampled, append its
    # counter rates + histogram quantiles as window-labelled gauges
    if sampler is None:
        from . import timeseries as _timeseries
        sampler = _timeseries.sampler_for(reg)
    lines.extend(windowed_prometheus_lines(sampler))
    return "\n".join(lines) + ("\n" if lines else "")


def write_prometheus(path: str,
                     registry: Optional[_metrics.MetricsRegistry] = None,
                     compat: Optional[bool] = None) -> str:
    atomic_write_bytes(
        path, prometheus_text(registry, compat=compat).encode("utf-8"))
    return path


def write_jsonl(path: str, tracer: Optional[_trace.Tracer] = None) -> str:
    t = tracer or _trace.tracer()
    lines = [json.dumps(s.to_json()) for s in t.finished()]
    atomic_write_bytes(path, ("\n".join(lines) + ("\n" if lines else ""))
                       .encode("utf-8"))
    return path
