"""Device-memory observatory: predicted vs measured bytes per dispatch.

Device memory is the other scarce serving-path resource (with compiles —
``observability/ledger.py``): a flush that allocates past the device
limit dies as a ``RESOURCE_EXHAUSTED`` mid-dispatch, and until this
module nothing could answer *how many bytes will this flush allocate
before it OOMs?*. The observatory keeps both sides of that question:

* **predicted** — every dispatch site computes the bytes its padded
  program will stage (plan segment shapes × padding bucket —
  ``utils/padding.py`` :func:`~..utils.padding.padded_bytes`; the
  sweep's packed argument blocks; a streaming chunk's packed upload)
  and reports them via :func:`record_dispatch`. Prediction is pure
  shape arithmetic — it works on every backend, CPU included.
* **measured** — where the backend supports ``device.memory_stats()``
  (TPU/GPU; CPU returns nothing), :func:`sample_measured` folds the
  live ``bytes_in_use`` / ``peak_bytes_in_use`` into per-subsystem
  peaks. Graceful no-op when unsupported: predicted stands alone and
  ``measuredSupported`` says so.

The **cost table** is the artifact ROADMAP items 1 (AOT compile store)
and 2 (pre-flight admission control) consume: measured
``(segment fingerprint × padding bucket) → {bytes, compileSeconds,
executeSeconds}``, accumulated by the plan executor per dispatch and
persisted into a ``costs`` section of the model's ``MANIFEST.json`` at
save and warmup time (``persistence.save_model``,
``serving/registry.load`` → :func:`persist_costs`). ``bytes`` is the
measured allocation delta where memory_stats exists, the shape-predicted
bytes otherwise — either way a number admission control can subtract
from the device budget *before* dispatch instead of catch-and-bisect.

Gated series: ``tg_device_mem_predicted_bytes{subsystem}`` (gauge, last
dispatch), ``tg_device_mem_predicted_peak_bytes{subsystem}`` and
``tg_device_mem_measured_peak_bytes{subsystem}`` (gauges). All zero-write
when observability is off. State is process-global (:func:`observatory`);
:func:`reset` gives tests a clean slate.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from . import metrics as _obs_metrics

#: manifest ``costs`` section format (bumped on incompatible change;
#: loaders tolerate unknown versions by ignoring the section)
COSTS_VERSION = 1

_stats_supported: Optional[bool] = None


def memory_stats() -> Optional[Dict[str, int]]:
    """The first local device's ``memory_stats()`` (bytes_in_use /
    peak_bytes_in_use / bytes_limit / num_allocs), or None where the
    backend does not report (CPU) — the graceful-no-op contract every
    caller leans on. The support probe is cached: once a backend says
    no, later dispatches pay one flag check."""
    global _stats_supported
    if _stats_supported is False:
        return None
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        stats = None
    if not stats:
        _stats_supported = False
        return None
    _stats_supported = True
    return {k: int(v) for k, v in stats.items()
            if k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                     "num_allocs")}


class DeviceMemObservatory:
    """Per-subsystem predicted/measured peaks + the measured cost table."""

    def __init__(self):
        self._lock = threading.Lock()
        #: subsystem → {"dispatches", "predictedBytes" (last),
        #: "predictedPeakBytes", "measuredPeakBytes" | None}
        self._subsystems: Dict[str, Dict[str, Any]] = {}
        #: "<segment fingerprint>@<bucket>" → cost row
        self._costs: Dict[str, Dict[str, Any]] = {}

    # -- predicted ------------------------------------------------------------
    def record_dispatch(self, subsystem: str, predicted_bytes: int,
                        bucket: Optional[int] = None,
                        rows: Optional[int] = None) -> None:
        predicted_bytes = int(predicted_bytes)
        with self._lock:
            s = self._subsystems.setdefault(
                subsystem, {"dispatches": 0, "predictedBytes": 0,
                            "predictedPeakBytes": 0,
                            "measuredPeakBytes": None})
            s["dispatches"] += 1
            s["predictedBytes"] = predicted_bytes
            s["predictedPeakBytes"] = max(s["predictedPeakBytes"],
                                          predicted_bytes)
        _obs_metrics.set_gauge(
            "tg_device_mem_predicted_bytes", float(predicted_bytes),
            help="shape-predicted device bytes of the last dispatch "
            "(docs/observability.md)", subsystem=subsystem)
        _obs_metrics.set_gauge(
            "tg_device_mem_predicted_peak_bytes",
            float(self._subsystems[subsystem]["predictedPeakBytes"]),
            help="peak shape-predicted device bytes per dispatch",
            subsystem=subsystem)

    # -- measured -------------------------------------------------------------
    def sample_measured(self, subsystem: str) -> Optional[Dict[str, int]]:
        """Fold the backend's live-buffer stats into the subsystem's
        measured peak; None (and no state change) where unsupported."""
        stats = memory_stats()
        if stats is None:
            return None
        peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
        with self._lock:
            s = self._subsystems.setdefault(
                subsystem, {"dispatches": 0, "predictedBytes": 0,
                            "predictedPeakBytes": 0,
                            "measuredPeakBytes": None})
            prev = s["measuredPeakBytes"] or 0
            s["measuredPeakBytes"] = max(prev, int(peak))
        _obs_metrics.set_gauge(
            "tg_device_mem_measured_peak_bytes",
            float(self._subsystems[subsystem]["measuredPeakBytes"]),
            help="peak measured live device bytes (device.memory_stats; "
            "absent on CPU)", subsystem=subsystem)
        return stats

    # -- cost table -----------------------------------------------------------
    @staticmethod
    def cost_key(fingerprint: str, bucket: int) -> str:
        return f"{fingerprint}@{int(bucket)}"

    def record_cost(self, fingerprint: str, bucket: int, bytes_: int,
                    compile_s: Optional[float] = None,
                    execute_s: Optional[float] = None) -> Dict[str, Any]:
        """Accumulate one dispatch into the (fingerprint × bucket) row:
        bytes last-write-wins (shapes are deterministic per bucket),
        compileSeconds records the first (compile-bearing) dispatch,
        executeSeconds keeps the minimum warm wall (the steady-state
        number admission control should budget with)."""
        key = self.cost_key(fingerprint, bucket)
        with self._lock:
            row = self._costs.setdefault(
                key, {"fingerprint": fingerprint, "bucket": int(bucket),
                      "bytes": 0, "compileSeconds": None,
                      "executeSeconds": None, "dispatches": 0})
            row["dispatches"] += 1
            row["bytes"] = int(bytes_)
            if compile_s is not None and row["compileSeconds"] is None:
                row["compileSeconds"] = round(float(compile_s), 6)
            if execute_s is not None:
                prev = row["executeSeconds"]
                row["executeSeconds"] = (
                    round(float(execute_s), 6) if prev is None
                    else min(prev, round(float(execute_s), 6)))
            return dict(row)

    def cost_table(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._costs.items()}

    def load_costs(self, doc: Any) -> int:
        """Merge a manifest ``costs`` section back in (warm start for the
        table). Tolerant by contract: a corrupt/foreign section loads
        zero rows, never raises — an unreadable cost table must not fail
        a model load."""
        try:
            if not isinstance(doc, dict):
                return 0
            table = doc.get("table")
            if not isinstance(table, dict):
                return 0
            loaded = 0
            with self._lock:
                for key, row in table.items():
                    if not isinstance(row, dict) or "bytes" not in row:
                        continue
                    self._costs.setdefault(str(key), dict(row))
                    loaded += 1
            return loaded
        except Exception:
            return 0

    # -- introspection --------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "measuredSupported": bool(_stats_supported),
                "subsystems": {k: dict(v)
                               for k, v in sorted(self._subsystems.items())},
                "costRows": len(self._costs),
            }

    def peaks(self) -> Dict[str, Any]:
        """``{"predicted": max over subsystems, "measured": ... | None}``
        — the two numbers every bench line reports."""
        with self._lock:
            pred = max((s["predictedPeakBytes"]
                        for s in self._subsystems.values()), default=0)
            meas = [s["measuredPeakBytes"] for s in self._subsystems.values()
                    if s["measuredPeakBytes"] is not None]
            return {"predicted": int(pred),
                    "measured": max(meas) if meas else None}

    def clear(self) -> None:
        with self._lock:
            self._subsystems.clear()
            self._costs.clear()


_OBSERVATORY = DeviceMemObservatory()


def observatory() -> DeviceMemObservatory:
    return _OBSERVATORY


def reset() -> None:
    global _OBSERVATORY
    _OBSERVATORY = DeviceMemObservatory()


# -- hot-path helpers --------------------------------------------------------

def record_dispatch(subsystem: str, predicted_bytes: int,
                    bucket: Optional[int] = None,
                    rows: Optional[int] = None) -> None:
    _OBSERVATORY.record_dispatch(subsystem, predicted_bytes,
                                 bucket=bucket, rows=rows)


def sample_measured(subsystem: str) -> Optional[Dict[str, int]]:
    return _OBSERVATORY.sample_measured(subsystem)


def record_cost(fingerprint: str, bucket: int, bytes_: int,
                compile_s: Optional[float] = None,
                execute_s: Optional[float] = None) -> None:
    _OBSERVATORY.record_cost(fingerprint, bucket, bytes_,
                             compile_s=compile_s, execute_s=execute_s)


# -- manifest persistence ----------------------------------------------------

def costs_manifest_entry() -> Dict[str, Any]:
    """The ``costs`` section written into ``MANIFEST.json``: the process's
    measured cost table (empty table → empty section, the caller skips
    it)."""
    return {"version": COSTS_VERSION, "table": _OBSERVATORY.cost_table()}


def persist_costs(dirpath: str) -> int:
    """Merge the live cost table into ``dirpath``'s manifest ``costs``
    section (warmup-time persistence: ``serving/registry.load`` calls
    this after the warm pre-trace so the warm process's measured costs
    land next to the model). Returns rows persisted; never raises."""
    try:
        from ..manifest import CheckpointManifest
        from ..persistence import FORMAT_VERSION
        table = _OBSERVATORY.cost_table()
        if not table:
            return 0
        manifest, err = CheckpointManifest.load(dirpath, FORMAT_VERSION)
        if err is not None:
            return 0
        merged = dict(manifest.costs.get("table", {})
                      if isinstance(manifest.costs.get("table"), dict)
                      else {})
        merged.update(table)
        manifest.costs = {"version": COSTS_VERSION, "table": merged}
        manifest.save()
        return len(table)
    except Exception:
        return 0
