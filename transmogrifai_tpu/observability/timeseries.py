"""Windowed time series over the metrics registry: the forward-looking
telemetry plane (docs/observability.md "SLOs, budgets & burn rates").

Every instrument in ``observability/metrics.py`` is a *lifetime*
aggregate: counters only grow, histogram quantiles cover every
observation since process start. That is the right shape for "how much
work has this process done" and useless for "is this model unhealthy
*right now*" — the question SLO budgets, burn-rate alerts
(``observability/slo.py``) and autoscaling signals (ROADMAP item 2) all
ask. This module adds the missing dimension:

* :class:`MetricsSampler` — a bounded ring of periodic registry
  snapshots on an **injectable clock**. Each sample stores only the
  series that *changed* since the previous tick (compact deltas), so an
  idle registry costs near nothing and a busy one costs O(active
  series) per tick.
* **windowed queries** — :meth:`~MetricsSampler.rate` /
  :meth:`~MetricsSampler.increase` turn cumulative counters into
  per-window rates, :meth:`~MetricsSampler.gauge_window` turns gauges
  into last/min/max-over-window, and :meth:`~MetricsSampler.quantile`
  turns lifetime histograms into **windowed quantiles** via SPDT sketch
  subtraction (:func:`sketch_delta`).
* **one shared ``tg-sampler`` daemon thread** (the watchdog-scanner
  pattern — robustness/watchdog.py): sources attach/detach
  (:func:`attach` / :func:`detach`), the thread lives exactly while
  sources exist, and ``TG_SAMPLER=0`` opts the whole subsystem out
  (attach returns None, zero threads, zero writes).

Sketch subtraction: SPDT sketches merge (utils/streaming_histogram.py)
but are **not** exactly subtractable — compaction merges bins, so
``now - start`` has no unique bin-level answer. :func:`sketch_delta`
instead subtracts the two sketches' cumulative distribution estimates
(``Sum``) on the union of their bin centroids, clamps the difference
monotone non-negative and caps it at the count delta, then rebuilds a
sketch from the interval masses. Mass is conserved exactly (the delta
sketch's total equals ``now.total - start.total``); quantile accuracy
is approximate with the same error character as the underlying sketch
(validated against exact numpy quantiles within documented tolerance in
tests/test_slo.py).

Window semantics: a series' value before its first sample is taken as 0
(a counter is born at zero), so a window opening before the first sample
counts everything ever recorded; ``rate``'s elapsed-time denominator is
clipped to the sampled history so such a window doesn't divide by time
nobody observed. Both are the honest choices for rates on a bounded ring
and are documented here rather than silently approximated.

Env knobs: ``TG_SAMPLER`` (default on; ``0`` opts out),
``TG_SAMPLE_EVERY_S`` (cadence, default 5), ``TG_SAMPLE_MAX`` (ring
bound in samples, default 720 — one hour at the default cadence).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.streaming_histogram import StreamingHistogram, _compress_bins
from . import metrics as _metrics

#: env switch: "0"/falsy disables the sampler subsystem entirely
SAMPLER_ENV = "TG_SAMPLER"
#: sampling cadence (seconds) for the shared tg-sampler thread
SAMPLE_EVERY_ENV = "TG_SAMPLE_EVERY_S"
DEFAULT_EVERY_S = 5.0
#: ring bound, in samples
SAMPLE_MAX_ENV = "TG_SAMPLE_MAX"
DEFAULT_MAX_SAMPLES = 720

_FALSY = ("0", "false", "False", "no", "off")

_enabled_override: Optional[bool] = None


def sampler_enabled() -> bool:
    """True when sampling is on (default; ``TG_SAMPLER=0`` opts out,
    :func:`enable_sampler` overrides)."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(SAMPLER_ENV, "1") not in _FALSY


def enable_sampler(on: Optional[bool]) -> None:
    """Force sampling on/off from code (benches, tests); ``None`` hands
    control back to the ``TG_SAMPLER`` environment switch."""
    global _enabled_override
    _enabled_override = None if on is None else bool(on)


def sample_every_s() -> float:
    try:
        v = float(os.environ.get(SAMPLE_EVERY_ENV, "") or DEFAULT_EVERY_S)
        return v if v > 0 else DEFAULT_EVERY_S
    except ValueError:
        return DEFAULT_EVERY_S


def max_samples() -> int:
    try:
        return max(2, int(os.environ.get(SAMPLE_MAX_ENV, "")
                          or DEFAULT_MAX_SAMPLES))
    except ValueError:
        return DEFAULT_MAX_SAMPLES


# -- sketch subtraction ------------------------------------------------------

def sketch_delta(now: StreamingHistogram,
                 start: Optional[StreamingHistogram]) -> StreamingHistogram:
    """The window's sub-sketch: observations in ``now`` but not in
    ``start`` (a snapshot of the same stream at the window's open).

    Subtracts the cumulative ``Sum`` estimates on the union of both
    sketches' centroids, clamped monotone non-negative and capped at the
    count delta, and rebuilds a sketch from the interval masses. The
    result conserves mass exactly (``total == now.total - start.total``);
    its quantiles are approximations (see module docstring)."""
    out = StreamingHistogram(now.max_bins)
    if start is None or start.total <= 0:
        bins = now.bins()
        if bins:
            out._load_state(bins, now.total, now.min, now.max)
        return out
    dtotal = now.total - start.total
    if dtotal <= 0:
        return out
    bs = sorted({c for c, _ in now.bins()} | {c for c, _ in start.bins()})
    cum: List[float] = []
    prev = 0.0
    for b in bs:
        d = now.sum(b) - start.sum(b)
        d = min(max(d, prev), dtotal)
        cum.append(d)
        prev = d
    masses = [cum[0]] + list(np.diff(np.asarray(cum, dtype=np.float64)))
    bins = [(b, m) for b, m in zip(bs, masses) if m > 0.0]
    tail = dtotal - cum[-1]
    if tail > 0.0:
        hi = max(float(now.max), bs[-1])
        if bins and bins[-1][0] == hi:
            bins[-1] = (hi, bins[-1][1] + tail)
        else:
            bins.append((hi, tail))
    if not bins:  # numerically everything clamped away: one lump bin
        bins = [(float(now.max), dtotal)]
    lo = bins[0][0]
    hi = bins[-1][0]
    out._load_state(_compress_bins(bins, now.max_bins), dtotal, lo, hi)
    return out


# -- the sampler -------------------------------------------------------------

#: one recorded histogram point: cumulative count/sum + the sketch state
#: (plain arrays — utils/streaming_histogram.to_state, impl-independent)
_HistPoint = Dict[str, Any]

SeriesKey = Tuple[str, str]  # (metric name, sorted "k=v,..." label string)


def _label_str(labels: Dict[str, str]) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


class MetricsSampler:
    """Bounded ring of periodic snapshots of ONE
    :class:`~.metrics.MetricsRegistry`, with windowed queries.

    Thread-safe: the shared ``tg-sampler`` thread ticks it while query
    callers (SLO trackers, ``health()``, exporters) read. Tests build
    their own instance with an injectable ``clock`` and drive
    :meth:`tick` manually."""

    def __init__(self, registry: _metrics.MetricsRegistry,
                 name: str = "metrics",
                 clock: Callable[[], float] = time.monotonic,
                 every_s: Optional[float] = None,
                 max_samples_: Optional[int] = None):
        self.registry = registry
        self.name = name
        self.clock = clock
        self.every_s = float(every_s) if every_s else sample_every_s()
        self.max_samples = (int(max_samples_) if max_samples_
                            else max_samples())
        self._lock = threading.Lock()
        #: ring of (ts, {key: value}) — only series that changed that tick
        self._samples: "deque[Tuple[float, Dict[SeriesKey, Any]]]" = deque(
            maxlen=self.max_samples)
        #: latest cumulative value per series (query fast path)
        self._last: Dict[SeriesKey, Any] = {}
        self._kinds: Dict[SeriesKey, str] = {}
        self._labels: Dict[SeriesKey, Dict[str, str]] = {}
        self._last_tick: Optional[float] = None
        self.ticks = 0
        #: called after every tick as ``hook(sampler, ts)`` — the SLO
        #: trackers' evaluation cadence; exceptions are contained (a bad
        #: hook must never kill the shared sampler thread)
        self.on_sample: List[Callable[["MetricsSampler", float], None]] = []

    # -- sampling ------------------------------------------------------------
    def due(self, now: Optional[float] = None) -> bool:
        now = self.clock() if now is None else now
        return (self._last_tick is None
                or now - self._last_tick >= self.every_s * 0.95)

    def tick(self, now: Optional[float] = None) -> int:
        """Snapshot the registry; returns how many series changed."""
        now = self.clock() if now is None else now
        changed: Dict[SeriesKey, Any] = {}
        for name, kind, _help, ms in self.registry.collect():
            for m in ms:
                key = (name, _label_str(m.labels))
                if kind == "histogram":
                    prev = self._last.get(key)
                    if prev is not None and prev["count"] == m.count:
                        continue
                    entry: Any = {"count": m.count, "sum": m.sum,
                                  "state": m.sketch_state()}
                else:
                    entry = float(m.value)
                    if self._last.get(key) == entry:
                        continue
                changed[key] = entry
                with self._lock:
                    self._last[key] = entry
                    self._kinds[key] = kind
                    self._labels[key] = dict(m.labels)
        with self._lock:
            self._samples.append((now, changed))
            self._last_tick = now
            self.ticks += 1
        for hook in list(self.on_sample):
            try:
                hook(self, now)
            except Exception:  # a hook must never kill the sampler
                pass
        return len(changed)

    # -- series reconstruction -----------------------------------------------
    def _matching(self, name: str, labels: Dict[str, str]
                  ) -> List[SeriesKey]:
        """Every sampled series of ``name`` whose labels are a superset
        of ``labels`` (Prometheus-style aggregation across the rest)."""
        with self._lock:
            out = []
            for key, lbls in self._labels.items():
                if key[0] != name:
                    continue
                if all(lbls.get(k) == str(v) for k, v in labels.items()):
                    out.append(key)
            return out

    def _value_at(self, key: SeriesKey, t: float) -> Optional[Any]:
        """The series' cumulative value at time ``t`` (value of the last
        sample at or before ``t``, carried or inherited); None when the
        series first appears after ``t`` (→ born-at-zero convention)."""
        val: Optional[Any] = None
        with self._lock:
            for ts, changed in self._samples:
                if ts > t:
                    break
                if key in changed:
                    val = changed[key]
            return val

    def _history_start(self, now: float, window_s: float) -> float:
        """Window start clipped to the retained sample history — ONLY
        for elapsed-time denominators (:meth:`rate`). Baseline lookups
        use the raw window start: a start before the first sample means
        "no baseline" (:meth:`_value_at` returns None → born-at-zero),
        so the first sample's recorded values count INSIDE the window
        rather than becoming its baseline."""
        start = now - window_s
        with self._lock:
            if self._samples:
                start = max(start, self._samples[0][0])
        return start

    # -- windowed queries ----------------------------------------------------
    def increase(self, name: str, window_s: float,
                 now: Optional[float] = None, **labels: str) -> float:
        """Counter delta over the window, summed across matching series
        (``increase("tg_serve_shed_total", 60, model="m")`` aggregates
        every ``reason``)."""
        now = self.clock() if now is None else now
        start = now - window_s
        total = 0.0
        for key in self._matching(name, labels):
            with self._lock:
                v_now = self._last.get(key)
            if v_now is None:
                continue
            if isinstance(v_now, dict):  # histogram: count delta
                v0 = self._value_at(key, start)
                total += v_now["count"] - (v0["count"] if v0 else 0)
            else:
                v0 = self._value_at(key, start)
                total += v_now - (float(v0) if v0 is not None else 0.0)
        return total

    def rate(self, name: str, window_s: float,
             now: Optional[float] = None, **labels: str) -> float:
        """Per-second rate over the window (counter increase / elapsed,
        elapsed clipped to the sampled history)."""
        now = self.clock() if now is None else now
        start = self._history_start(now, window_s)
        elapsed = now - start
        if elapsed <= 0:
            return 0.0
        return self.increase(name, window_s, now=now, **labels) / elapsed

    def window_count(self, name: str, window_s: float,
                     now: Optional[float] = None, **labels: str) -> float:
        """Histogram observation count over the window."""
        return self.increase(name, window_s, now=now, **labels)

    def _delta_sketches(self, name: str, window_s: float, now: float,
                        labels: Dict[str, str]
                        ) -> List[Tuple[StreamingHistogram, float]]:
        start = now - window_s
        out: List[Tuple[StreamingHistogram, float]] = []
        for key in self._matching(name, labels):
            with self._lock:
                v_now = self._last.get(key)
            if not isinstance(v_now, dict):
                continue
            v0 = self._value_at(key, start)
            now_sk = StreamingHistogram.from_state(v_now["state"])
            start_sk = (StreamingHistogram.from_state(v0["state"])
                        if isinstance(v0, dict) else None)
            delta = sketch_delta(now_sk, start_sk)
            out.append((delta, v_now["count"] - (v0["count"] if v0 else 0)))
        return out

    def quantile(self, name: str, q: float, window_s: float,
                 now: Optional[float] = None, **labels: str) -> float:
        """Windowed quantile via SPDT sketch subtraction, merged across
        matching series; NaN when the window holds no observations."""
        now = self.clock() if now is None else now
        deltas = [d for d, _n in
                  self._delta_sketches(name, window_s, now, labels)]
        deltas = [d for d in deltas if d.total > 0]
        if not deltas:
            return float("nan")
        merged = (deltas[0] if len(deltas) == 1
                  else StreamingHistogram.merged(deltas))
        return float(merged.quantile(q))

    def cdf_increase(self, name: str, threshold: float, window_s: float,
                     now: Optional[float] = None, **labels: str) -> float:
        """Estimated number of window observations ≤ ``threshold``
        (cumulative-``Sum`` subtraction, clamped into [0, count delta]) —
        the latency-SLO primitive: observations *over* a target are
        ``window_count - cdf_increase``."""
        now = self.clock() if now is None else now
        start = now - window_s
        total = 0.0
        for key in self._matching(name, labels):
            with self._lock:
                v_now = self._last.get(key)
            if not isinstance(v_now, dict):
                continue
            v0 = self._value_at(key, start)
            now_sk = StreamingHistogram.from_state(v_now["state"])
            below = now_sk.sum(threshold)
            if isinstance(v0, dict):
                below -= StreamingHistogram.from_state(
                    v0["state"]).sum(threshold)
            dcount = v_now["count"] - (v0["count"] if v0 else 0)
            total += min(max(below, 0.0), float(dcount))
        return total

    def gauge_window(self, name: str, window_s: float,
                     now: Optional[float] = None, **labels: str
                     ) -> Dict[str, float]:
        """Gauge over the window: ``{"last", "min", "max"}`` across the
        carried sample points plus the inherited value at window start;
        empty dict when the gauge was never sampled."""
        now = self.clock() if now is None else now
        start = now - window_s
        vals: List[float] = []
        last: Optional[float] = None
        for key in self._matching(name, labels):
            v0 = self._value_at(key, start)
            if v0 is not None and not isinstance(v0, dict):
                vals.append(float(v0))
            with self._lock:
                for ts, changed in self._samples:
                    if start < ts <= now and key in changed:
                        v = changed[key]
                        if not isinstance(v, dict):
                            vals.append(float(v))
                v_last = self._last.get(key)
            if v_last is not None and not isinstance(v_last, dict):
                last = float(v_last)
        if not vals and last is None:
            return {}
        if not vals:
            vals = [last]
        return {"last": last if last is not None else vals[-1],
                "min": min(vals), "max": max(vals)}

    # -- introspection -------------------------------------------------------
    def counter_names(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k, kind in self._kinds.items()
                           if kind == "counter"})

    def histogram_names(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k, kind in self._kinds.items()
                           if kind == "histogram"})

    def series_labels(self, name: str) -> List[Dict[str, str]]:
        with self._lock:
            return [dict(lbls) for key, lbls in sorted(self._labels.items())
                    if key[0] == name]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"name": self.name, "samples": len(self._samples),
                    "maxSamples": self.max_samples, "ticks": self.ticks,
                    "everyS": self.every_s, "series": len(self._last),
                    "lastTick": self._last_tick}

    def recent(self, n: int = 16) -> List[Dict[str, Any]]:
        """The last ``n`` samples with their scalar (counter/gauge)
        changes — the compact form post-mortem bundles carry (sketch
        states stay out of bundles; the SLO snapshot already summarizes
        them)."""
        with self._lock:
            tail = list(self._samples)[-n:]
        out = []
        for ts, changed in tail:
            scalars = {f"{k[0]}{{{k[1]}}}": v for k, v in changed.items()
                       if not isinstance(v, dict)}
            hists = {f"{k[0]}{{{k[1]}}}": {"count": v["count"],
                                           "sum": round(v["sum"], 6)}
                     for k, v in changed.items() if isinstance(v, dict)}
            out.append({"ts": ts, "scalars": scalars, "histograms": hists})
        return out


# -- the shared tg-sampler thread (watchdog-scanner lifecycle) ---------------

_LOCK = threading.Lock()
_SOURCES: List[MetricsSampler] = []
_THREAD: Optional[threading.Thread] = None
_WAKE = threading.Event()


def attach(registry: _metrics.MetricsRegistry, name: str = "metrics",
           every_s: Optional[float] = None,
           max_samples_: Optional[int] = None) -> Optional[MetricsSampler]:
    """Register ``registry`` with the shared sampler thread; returns the
    source's :class:`MetricsSampler` (None when ``TG_SAMPLER=0`` — the
    caller must treat a None sampler as "no windowed telemetry"). A
    baseline tick runs immediately so the first window has an anchor."""
    global _THREAD
    if not sampler_enabled():
        return None
    s = MetricsSampler(registry, name=name, every_s=every_s,
                       max_samples_=max_samples_)
    s.tick()
    with _LOCK:
        _SOURCES.append(s)
        if _THREAD is None or not _THREAD.is_alive():
            _THREAD = threading.Thread(target=_run, name="tg-sampler",
                                       daemon=True)
            _THREAD.start()
    return s


def detach(sampler: Optional[MetricsSampler]) -> None:
    """Unregister a source (idempotent); the thread retires when no
    sources remain."""
    if sampler is None:
        return
    with _LOCK:
        if sampler in _SOURCES:
            _SOURCES.remove(sampler)
        _WAKE.set()


def attached() -> List[MetricsSampler]:
    with _LOCK:
        return list(_SOURCES)


def sampler_for(registry: _metrics.MetricsRegistry
                ) -> Optional[MetricsSampler]:
    """The attached sampler snapshotting ``registry`` (exporters use
    this to find windowed series for the registry they render)."""
    with _LOCK:
        for s in _SOURCES:
            if s.registry is registry:
                return s
    return None


def _run() -> None:
    global _THREAD
    while True:
        with _LOCK:
            if not _SOURCES:
                _THREAD = None
                return
            interval = min(s.every_s for s in _SOURCES)
        _WAKE.wait(min(max(interval, 0.02), 5.0))
        _WAKE.clear()
        for s in attached():
            try:
                if s.due():
                    s.tick()
            except Exception:  # pragma: no cover - defensive
                pass


def idle_join(timeout: float = 5.0) -> None:
    """Join the sampler thread once no sources remain (test teardown)."""
    with _LOCK:
        t = _THREAD
        if _SOURCES or t is None:
            return
    _WAKE.set()
    t.join(timeout)


def reset() -> None:
    """Detach every source, retire the thread, and hand enablement back
    to the env (test isolation)."""
    global _enabled_override
    with _LOCK:
        _SOURCES.clear()
        _WAKE.set()
    idle_join()
    _enabled_override = None
