"""Always-on flight recorder: request-correlated black-box telemetry.

TG_TRACE/TG_METRICS are *opt-in* — off in production by default — so when
a real incident fires (a breaker opens, the watchdog catches a wedge, an
OOM downshift cascades) there is no record of what the process was doing
in the seconds before. This module is the aviation-style black box the
resilience layer (PRs 6–10) was missing: a process-wide, **always-on**
(``TG_BLACKBOX=0`` opts out), fixed-size, lock-cheap ring of compact
events that is cheap enough to leave running under full serving load
(≤2% on the BENCH_MODE=serve clean line — asserted) and that
``observability/postmortem.py`` snapshots into a self-contained bundle
the moment a trigger event fires.

Event sources (each stamped with a monotonic ``ts_ns`` and, when one is
active, a **correlation id**):

* span open/close summaries (``trace.Tracer`` forwards finished spans
  here when tracing is on — the black box sees the traced world too);
* every FaultLog record (``robustness/policy.py`` choke point: retries,
  quarantines, breaker degradations, OOM downshifts, thread stalls,
  unclean exits, drift events — one hook covers them all);
* circuit-breaker state transitions (``serving/breaker.py``);
* serve request lifecycle: enqueue / shed / flush / dispatch / resolve
  (``serving/runtime.py``), each enqueue+resolve carrying the request's
  correlation id;
* drift verdict transitions (``serving/drift.py``);
* chaos injections actually applied (``robustness/faults.py``);
* stream passes and sweep family dispatches (``streaming/trainer.py``,
  ``impl/tuning/validators.py``), stamped with the owning run's id.

Correlation ids (Dapper-style, but in-process): minted per serving
request at enqueue (``ServingRuntime.submit`` → ``Future.tg_corr``) and
per run for train/stream/sweep (``OpWorkflow.train`` sets the ambient id
via :func:`correlated`), so :meth:`FlightRecorder.slice_for` reconstructs
one request's or one run's full timeline out of the shared ring. The
serve-local latency histograms keep the ids of their slowest requests as
**exemplars** (``observability/metrics.py``), so a p99 outlier links
straight back to its recorder slice.

Cost model: disabled (``TG_BLACKBOX=0``) every touch point is one flag
check — no objects, no lock. Enabled, :func:`record` is one lock-guarded
deque append of a small ``__slots__`` object; the ring is bounded by
``TG_BLACKBOX_MAX`` (default 4096) and drops are counted, never silent.

State is process-global by design (one black box per aircraft);
:func:`reset` gives tests a clean slate (tests/conftest.py
``_no_blackbox_leak``).
"""
from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: env switch: "0"/falsy DISABLES the recorder (on by default — the whole
#: point of a black box is that it is recording when the incident happens)
BLACKBOX_ENV = "TG_BLACKBOX"
#: ring bound (events); drops are counted in FlightRecorder.dropped
BLACKBOX_MAX_ENV = "TG_BLACKBOX_MAX"
DEFAULT_MAX_EVENTS = 4096

_FALSY = ("0", "false", "False", "no", "off")

_enabled_override: Optional[bool] = None


def blackbox_enabled() -> bool:
    """True when the flight recorder is recording (default on; TG_BLACKBOX=0
    disables, :func:`enable_blackbox` overrides)."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(BLACKBOX_ENV, "1") not in _FALSY


def enable_blackbox(on: Optional[bool]) -> None:
    """Force the recorder on/off from code (benches, tests); ``None`` hands
    control back to the ``TG_BLACKBOX`` environment switch."""
    global _enabled_override
    _enabled_override = None if on is None else bool(on)


# -- correlation ids ---------------------------------------------------------

#: process-wide monotone id sequence: ids are bit-stable within a process
#: (same submission order → same ids) and globally unique across processes
#: via the pid component
_IDS = itertools.count(1)

_CORR: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "tg_blackbox_corr", default=None)


def new_correlation_id(prefix: str = "req") -> str:
    """Mint a correlation id: ``<prefix>-<pid hex>-<seq hex>``. The serve
    path mints one per request at enqueue; ``OpWorkflow.train`` mints one
    per run (``prefix="run"``)."""
    return f"{prefix}-{os.getpid():x}-{next(_IDS):06x}"


def current_correlation() -> Optional[str]:
    """The ambient correlation id (a train/stream/sweep run id set by
    :func:`correlated`), or None outside any correlated scope."""
    return _CORR.get()


@contextlib.contextmanager
def correlated(corr: Optional[str]):
    """Make ``corr`` the ambient correlation id for the block: every
    :func:`record` without an explicit ``corr`` inside it (same thread /
    context) is stamped with it. No-op context when ``corr`` is None."""
    if corr is None:
        yield None
        return
    token = _CORR.set(corr)
    try:
        yield corr
    finally:
        _CORR.reset(token)


# -- events + recorder -------------------------------------------------------

class BlackboxEvent:
    """One compact recorder entry. ``ts_ns`` is monotonic nanoseconds
    relative to the owning recorder's epoch (``epoch_unix`` anchors it to
    wall clock for reports); ``corr`` is the correlation id or None."""

    __slots__ = ("kind", "ts_ns", "corr", "attrs")

    def __init__(self, kind: str, ts_ns: int, corr: Optional[str],
                 attrs: Dict[str, Any]):
        self.kind = kind
        self.ts_ns = ts_ns
        self.corr = corr
        self.attrs = attrs

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.kind, "tsNs": self.ts_ns, "corr": self.corr,
                "attrs": dict(self.attrs)}


class FlightRecorder:
    """The bounded event ring. One module-level singleton records the
    process (:func:`recorder`); tests build their own instances."""

    def __init__(self, max_events: Optional[int] = None):
        if max_events is None:
            try:
                max_events = int(os.environ.get(BLACKBOX_MAX_ENV, "")
                                 or DEFAULT_MAX_EVENTS)
            except ValueError:
                max_events = DEFAULT_MAX_EVENTS
        self.max_events = max(1, int(max_events))
        self._events: deque = deque(maxlen=self.max_events)
        self.dropped = 0
        #: wall-clock anchor for the monotonic epoch (bundle metadata)
        self.epoch_unix = time.time()
        self.epoch_ns = time.perf_counter_ns()
        self._lock = threading.Lock()

    # -- recording (the hot path) -------------------------------------------
    def record(self, kind: str, corr: Optional[str] = None,
               **attrs: Any) -> None:
        """Append one event. ``corr=None`` picks up the ambient correlation
        id (a train run inside :func:`correlated`); pass an explicit id on
        the serve path where each request carries its own."""
        if corr is None:
            corr = _CORR.get()
        ev = BlackboxEvent(kind, time.perf_counter_ns() - self.epoch_ns,
                           corr, attrs)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    # -- queries -------------------------------------------------------------
    def events(self) -> List[BlackboxEvent]:
        with self._lock:
            return list(self._events)

    def tail(self, n: int) -> List[BlackboxEvent]:
        """The most recent ``n`` events (oldest first) — the post-mortem
        bundle's "recent ring slice"."""
        with self._lock:
            if n >= len(self._events):
                return list(self._events)
            return list(self._events)[-n:]

    def slice_for(self, corr: str) -> List[BlackboxEvent]:
        """Every ring event stamped with ``corr`` — one request's (or one
        run's) timeline, oldest first."""
        with self._lock:
            return [e for e in self._events if e.corr == corr]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def snapshot(self) -> Dict[str, Any]:
        """Ring accounting (no events): size / bound / drops."""
        with self._lock:
            return {"events": len(self._events),
                    "maxEvents": self.max_events,
                    "dropped": self.dropped,
                    "epochUnix": self.epoch_unix}


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def set_recorder(r: FlightRecorder) -> FlightRecorder:
    global _RECORDER
    _RECORDER = r
    return r


def reset() -> None:
    """Fresh recorder + env-driven enablement (test isolation; the
    correlation-id sequence is NOT reset — ids stay unique per process)."""
    global _RECORDER, _enabled_override
    _RECORDER = FlightRecorder()
    _enabled_override = None


# -- the instrumentation entry point (one enabled check, zero writes off) ----

def record(kind: str, corr: Optional[str] = None, **attrs: Any) -> None:
    """Record one event on the process flight recorder; inert (one flag
    check) when ``TG_BLACKBOX=0``. This is the call compiled into every
    instrumented site — the black-box analog of ``faults.inject``."""
    if not blackbox_enabled():
        return
    _RECORDER.record(kind, corr, **attrs)
