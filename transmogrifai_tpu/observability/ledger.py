"""Compile ledger: every program build, with a *classified cause*.

The substrate swap makes compiles the scarcest serving-path resource —
a retrace on the hot path is tens of ms to seconds of p99 — yet until
this module the repo could count compiles (the ``/jax/compilation_cache``
listener, which reads 0 on CPU) but never answer *why did this request
retrace?*. The ledger closes that gap: the four dispatch subsystems —
transform-plan segments (``plan.py``), fused sweep programs
(``impl/tuning/validators.py``, single-device and mesh), serve warmup +
serving flushes (``serving/warmup.py`` / ``serving/runtime.py``), and
streaming fold passes (``streaming/trainer.py``) — report every program
build here with its cache key, schema fingerprint, stage/segment
identity, wall time, and the ledger classifies the *cause*:

``cold``
    first build for this identity (nothing to compare against);
``schema-change``
    the identity was built before with a different schema fingerprint —
    the ledger diffs the incoming fingerprint against the previous one
    and names exactly what changed (column added/removed, dtype,
    trailing shape, mask presence);
``bucket-change``
    same identity + fingerprint, different padding bucket (row growth
    crossed a bucket boundary — utils/padding.py — or a streaming
    chunk-budget downshift re-chunked the pass);
``donation-mismatch``
    same identity + fingerprint + bucket, but the donated-argument
    signature changed (a donated buffer shape/sharding no longer aliases
    — the sweep's packed grid block);
``cache-eviction``
    an unchanged program was rebuilt — its key was evicted from a
    bounded LRU (``TG_PLAN_CACHE_MAX`` / ``TG_FUSED_CACHE_MAX``; the
    caches report evictions via :func:`record_eviction`) or the cache
    was cleared;
``aot-miss``
    the AOT program store was active but could not serve this build —
    no entry for the key, a jaxlib/device-kind mismatch, a corrupt
    blob, or a deserialization failure (the store notes the key via
    :func:`note_aot_miss` with the miss reason right before the caller
    falls back to the trace path — transmogrifai_tpu/programstore/,
    docs/serving.md "AOT cold start & the program store"). Near-miss
    causes with real forensics (``schema-change``/``bucket-change``)
    still win when the identity was built before: the AOT note only
    explains builds that would otherwise read ``cold``.

Exports: ``tg_compile_total{cause,subsystem}`` +
``tg_compile_seconds{subsystem}`` through the gated metrics helpers
(zero writes when observability is off), and a ``compile`` flight-
recorder event stamped with the ambient correlation id
(observability/blackbox.py) — so ``cli doctor`` timelines show which
request or run paid a retrace.

Cost model mirrors the flight recorder: ``TG_LEDGER=0`` turns every
touch point into one flag check; enabled, a record is one lock-guarded
append of a small ``__slots__`` object into a ring bounded by
``TG_LEDGER_MAX`` (default 1024, drops counted). State is process-global
(:func:`ledger`); :func:`reset` gives tests a clean slate
(tests/conftest.py ``_no_ledger_leak``).
"""
from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from . import blackbox as _blackbox
from . import metrics as _obs_metrics

#: env switch: "0"/falsy disables the ledger (on by default — like the
#: flight recorder, a compile ledger must be recording when the retrace
#: storm happens)
LEDGER_ENV = "TG_LEDGER"
#: ring bound (records); drops are counted in CompileLedger.dropped
LEDGER_MAX_ENV = "TG_LEDGER_MAX"
DEFAULT_MAX_RECORDS = 1024

#: the closed cause taxonomy (docs/observability.md "Compile & memory
#: ledger"); classification can return nothing else
CAUSES = ("cold", "schema-change", "bucket-change", "donation-mismatch",
          "cache-eviction", "aot-miss")

#: the dispatch subsystems that report builds (docs/observability.md)
SUBSYSTEMS = ("plan", "sweep", "serve", "stream")

_FALSY = ("0", "false", "False", "no", "off")

_enabled_override: Optional[bool] = None


def ledger_enabled() -> bool:
    """True when the compile ledger is recording (default on;
    ``TG_LEDGER=0`` disables, :func:`enable_ledger` overrides)."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(LEDGER_ENV, "1") not in _FALSY


def enable_ledger(on: Optional[bool]) -> None:
    """Force the ledger on/off from code (benches, tests); ``None`` hands
    control back to the ``TG_LEDGER`` environment switch."""
    global _enabled_override
    _enabled_override = None if on is None else bool(on)


# -- subsystem attribution ---------------------------------------------------

#: the plan compiler is shared by train/score/serve/stream paths; the
#: owning subsystem scopes itself so its builds are attributed to it
#: (serving wraps warm + dispatch, streaming wraps its passes)
_SUBSYSTEM: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "tg_ledger_subsystem", default=None)


def current_subsystem(default: str = "plan") -> str:
    """The ambient dispatch subsystem, or ``default`` outside any scope."""
    return _SUBSYSTEM.get() or default


@contextlib.contextmanager
def subsystem_scope(name: str):
    """Attribute every build recorded inside the block (same thread /
    context) to ``name`` — e.g. a plan compile during serve warmup lands
    as ``subsystem="serve"``, not ``"plan"``."""
    token = _SUBSYSTEM.set(name)
    try:
        yield name
    finally:
        _SUBSYSTEM.reset(token)


# -- fingerprint diffing -----------------------------------------------------

def _fp_columns(fp: Any) -> Optional[Dict[str, Tuple]]:
    """Plan-style fingerprints — ``[[name, dtype, trailing, maskless]]``
    — as a name-keyed dict; None for any other shape."""
    if not isinstance(fp, (list, tuple)):
        return None
    out: Dict[str, Tuple] = {}
    for item in fp:
        if not isinstance(item, (list, tuple)) or len(item) != 4:
            return None
        nm, dt, shape, maskless = item
        out[str(nm)] = (str(dt), tuple(shape), bool(maskless))
    return out


def fingerprint_diff(old: Any, new: Any) -> List[str]:
    """Name exactly what changed between two schema fingerprints —
    the near-miss forensics a bare cache miss can never give. Handles
    the plan-cache column fingerprint (per-column dtype / trailing shape
    / mask presence), flat dict fingerprints (the sweep's config shape),
    and falls back to a repr comparison for anything else."""
    a, b = _fp_columns(old), _fp_columns(new)
    if a is not None and b is not None:
        diffs: List[str] = []
        for nm in sorted(set(a) | set(b)):
            if nm not in a:
                diffs.append(f"column added: '{nm}'")
            elif nm not in b:
                diffs.append(f"column removed: '{nm}'")
            else:
                (dt0, sh0, m0), (dt1, sh1, m1) = a[nm], b[nm]
                if dt0 != dt1:
                    diffs.append(f"column '{nm}': dtype {dt0} -> {dt1}")
                if sh0 != sh1:
                    diffs.append(f"column '{nm}': trailing shape "
                                 f"{list(sh0)} -> {list(sh1)}")
                if m0 != m1:
                    diffs.append(f"column '{nm}': mask "
                                 f"{'absent' if m0 else 'present'} -> "
                                 f"{'absent' if m1 else 'present'}")
        return diffs or ["fingerprints differ (no field-level delta found)"]
    if isinstance(old, dict) and isinstance(new, dict):
        diffs = []
        for k in sorted(set(old) | set(new)):
            if old.get(k) != new.get(k):
                diffs.append(f"{k}: {old.get(k)!r} -> {new.get(k)!r}")
        return diffs or ["fingerprints differ (no field-level delta found)"]
    return [f"fingerprint changed: {str(old)[:80]!r} -> {str(new)[:80]!r}"]


# -- records + ledger --------------------------------------------------------

class CompileRecord:
    """One program build. ``identity`` is the stable program identity the
    cause classification compares against (stage-uid sequence, sweep
    family, stream pass); ``key`` the exact cache key (hashed); ``diff``
    the named fields that changed when the cause is a near-miss."""

    __slots__ = ("seq", "subsystem", "identity", "key", "fingerprint",
                 "bucket", "donation", "cause", "diff", "seconds", "corr",
                 "ts_unix", "attrs")

    def __init__(self, seq: int, subsystem: str, identity: str, key: str,
                 fingerprint: Any, bucket: Optional[int],
                 donation: Optional[Tuple], cause: str, diff: List[str],
                 seconds: float, corr: Optional[str],
                 attrs: Dict[str, Any]):
        self.seq = seq
        self.subsystem = subsystem
        self.identity = identity
        self.key = key
        self.fingerprint = fingerprint
        self.bucket = bucket
        self.donation = donation
        self.cause = cause
        self.diff = diff
        self.seconds = seconds
        self.corr = corr
        self.ts_unix = time.time()
        self.attrs = attrs

    def to_json(self) -> Dict[str, Any]:
        return {"seq": self.seq, "subsystem": self.subsystem,
                "identity": self.identity, "key": self.key,
                "fingerprint": self.fingerprint, "bucket": self.bucket,
                "cause": self.cause, "diff": list(self.diff),
                "seconds": round(self.seconds, 6), "corr": self.corr,
                "unixTime": self.ts_unix, "attrs": dict(self.attrs)}


class CompileLedger:
    """The bounded build ring + per-identity classification memory. One
    module-level singleton records the process (:func:`ledger`); tests
    build their own instances."""

    #: how many evicted keys the eviction memory holds (older evictions
    #: age out — by then the rebuild they explain has long happened)
    EVICTED_MAX = 256

    def __init__(self, max_records: Optional[int] = None):
        if max_records is None:
            try:
                max_records = int(os.environ.get(LEDGER_MAX_ENV, "")
                                  or DEFAULT_MAX_RECORDS)
            except ValueError:
                max_records = DEFAULT_MAX_RECORDS
        self.max_records = max(1, int(max_records))
        self._records: deque = deque(maxlen=self.max_records)
        self.dropped = 0
        self._seq = 0
        self._lock = threading.Lock()
        #: identity → last build (the classification baseline); NOT ring-
        #: bounded — one entry per distinct program identity, the same
        #: O(#programs) footprint the compile caches already pay
        self._last: Dict[str, CompileRecord] = {}
        #: keys reported evicted by the bounded caches, awaiting rebuild
        self._evicted: "OrderedDict[str, bool]" = OrderedDict()
        #: keys the AOT program store failed to serve, awaiting the
        #: trace-path build they explain (key -> miss reason; bounded
        #: like the eviction memory)
        self._aot_misses: "OrderedDict[str, str]" = OrderedDict()
        #: (subsystem, cause) → builds (survives ring wrap)
        self._counts: Dict[Tuple[str, str], int] = {}
        self.seconds_total = 0.0

    # -- cache cooperation ---------------------------------------------------
    def record_eviction(self, key: str) -> None:
        """A bounded cache dropped ``key``: the next rebuild of that exact
        key is a ``cache-eviction``, not a mystery ``cold``."""
        if not ledger_enabled():
            return
        with self._lock:
            self._evicted[key] = True
            while len(self._evicted) > self.EVICTED_MAX:
                self._evicted.popitem(last=False)

    def note_aot_miss(self, key: str, reason: str) -> None:
        """The AOT program store could not serve ``key``: the trace-path
        build the caller is about to pay classifies ``aot-miss`` with
        ``reason`` as its diff (programstore/store.py fallback ladder)."""
        if not ledger_enabled():
            return
        with self._lock:
            self._aot_misses[key] = reason
            while len(self._aot_misses) > self.EVICTED_MAX:
                self._aot_misses.popitem(last=False)

    # -- classification ------------------------------------------------------
    def _classify(self, identity: str, key: str, fingerprint: Any,
                  bucket: Optional[int], donation: Optional[Tuple]
                  ) -> Tuple[str, List[str]]:
        """Lock held. Compare against the identity's previous build."""
        prev = self._last.get(identity)
        evicted = self._evicted.pop(key, False)
        aot_reason = self._aot_misses.pop(key, None)
        if prev is None:
            # a would-be-cold build the AOT store should have served:
            # name the miss. Builds with an in-process baseline keep
            # their richer near-miss causes (schema/bucket diffs) below.
            if aot_reason is not None:
                return "aot-miss", [aot_reason]
            return "cold", []
        if prev.fingerprint != fingerprint:
            diff = fingerprint_diff(prev.fingerprint, fingerprint)
            if bucket is not None and prev.bucket != bucket:
                diff.append(f"bucket {prev.bucket} -> {bucket}")
            return "schema-change", diff
        if bucket is not None and prev.bucket != bucket:
            return "bucket-change", [f"bucket {prev.bucket} -> {bucket}"]
        if donation != prev.donation:
            return "donation-mismatch", [
                f"donated args {prev.donation!r} -> {donation!r}"]
        # unchanged program rebuilt: the cached executable was lost
        diff = (["key evicted from a bounded cache"] if evicted
                else ["program rebuilt with unchanged key (cache cleared)"])
        return "cache-eviction", diff

    # -- recording (the instrumented-site entry point) -----------------------
    def record_build(self, subsystem: str, identity: str, key: str,
                     fingerprint: Any = None, seconds: float = 0.0,
                     bucket: Optional[int] = None,
                     donation: Optional[Tuple] = None,
                     corr: Optional[str] = None,
                     **attrs: Any) -> Optional[CompileRecord]:
        """Record one program build; returns the classified record (None
        when the ledger is disabled — zero writes, zero state)."""
        if not ledger_enabled():
            return None
        if corr is None:
            corr = _blackbox.current_correlation()
        with self._lock:
            cause, diff = self._classify(identity, key, fingerprint,
                                         bucket, donation)
            self._seq += 1
            rec = CompileRecord(self._seq, subsystem, identity, key,
                                fingerprint, bucket, donation, cause, diff,
                                float(seconds), corr, attrs)
            if len(self._records) == self._records.maxlen:
                self.dropped += 1
            self._records.append(rec)
            self._last[identity] = rec
            ck = (subsystem, cause)
            self._counts[ck] = self._counts.get(ck, 0) + 1
            self.seconds_total += float(seconds)
        _obs_metrics.inc_counter(
            "tg_compile_total", 1.0, cause=cause, subsystem=subsystem,
            help="program builds by classified cause and dispatch "
            "subsystem (docs/observability.md)")
        _obs_metrics.observe(
            "tg_compile_seconds", float(seconds), subsystem=subsystem,
            help="wall seconds per program build (trace + first-dispatch "
            "compile)")
        _blackbox.record("compile", corr=corr, subsystem=subsystem,
                         identity=identity, cause=cause,
                         seconds=round(float(seconds), 4),
                         diff=diff[0] if diff else None)
        return rec

    # -- queries -------------------------------------------------------------
    @property
    def total(self) -> int:
        with self._lock:
            return self._seq

    def mark(self) -> int:
        """A watermark for :meth:`since` — e.g. taken right after a warm
        ``registry.load`` so the zero-retrace gate can assert no build
        happened past it."""
        return self.total

    def since(self, mark: int) -> List[CompileRecord]:
        """Every ring record with ``seq > mark`` (oldest first). Records
        past the ring bound are gone from the ring but still counted —
        compare :attr:`total` against the mark for the exact count."""
        with self._lock:
            return [r for r in self._records if r.seq > mark]

    def entries(self) -> List[CompileRecord]:
        with self._lock:
            return list(self._records)

    def tail(self, n: int) -> List[CompileRecord]:
        with self._lock:
            if n >= len(self._records):
                return list(self._records)
            return list(self._records)[-n:]

    def counts(self) -> Dict[str, Dict[str, int]]:
        """``{subsystem: {cause: builds}}`` over the full process history
        (not just the ring)."""
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for (sub, cause), n in sorted(self._counts.items()):
                out.setdefault(sub, {})[cause] = n
            return out

    def counts_by_cause(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for (_sub, cause), n in self._counts.items():
                out[cause] = out.get(cause, 0) + n
            return dict(sorted(out.items()))

    def snapshot(self) -> Dict[str, Any]:
        """Ring + counter accounting for ``summary()`` / bundles."""
        with self._lock:
            by_sub: Dict[str, Dict[str, int]] = {}
            for (sub, cause), n in sorted(self._counts.items()):
                by_sub.setdefault(sub, {})[cause] = n
            return {"builds": self._seq,
                    "secondsTotal": round(self.seconds_total, 4),
                    "bySubsystem": by_sub,
                    "records": len(self._records),
                    "maxRecords": self.max_records,
                    "dropped": self.dropped}

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._last.clear()
            self._evicted.clear()
            self._aot_misses.clear()
            self._counts.clear()
            self._seq = 0
            self.dropped = 0
            self.seconds_total = 0.0


_LEDGER = CompileLedger()


def ledger() -> CompileLedger:
    return _LEDGER


def set_ledger(l: CompileLedger) -> CompileLedger:
    global _LEDGER
    _LEDGER = l
    return l


def reset() -> None:
    """Fresh ledger + env-driven enablement (test isolation)."""
    global _LEDGER, _enabled_override
    _LEDGER = CompileLedger()
    _enabled_override = None


# -- the instrumentation entry point (one enabled check, zero writes off) ----

def record_build(subsystem: Optional[str] = None, *, identity: str,
                 key: str, fingerprint: Any = None, seconds: float = 0.0,
                 bucket: Optional[int] = None,
                 donation: Optional[Tuple] = None,
                 corr: Optional[str] = None,
                 **attrs: Any) -> Optional[CompileRecord]:
    """Record one build on the process ledger; ``subsystem=None`` picks up
    the ambient :func:`subsystem_scope` (default ``"plan"``). This is the
    call compiled into every dispatch site — inert when ``TG_LEDGER=0``."""
    if not ledger_enabled():
        return None
    return _LEDGER.record_build(
        subsystem or current_subsystem(), identity, key,
        fingerprint=fingerprint, seconds=seconds, bucket=bucket,
        donation=donation, corr=corr, **attrs)


def record_eviction(key: str) -> None:
    if ledger_enabled():
        _LEDGER.record_eviction(key)


def note_aot_miss(key: str, reason: str) -> None:
    if ledger_enabled():
        _LEDGER.note_aot_miss(key, reason)


def cache_key_hash(key: Any) -> str:
    """A stable short hash of an arbitrary cache-key tuple (plan / fused
    caches key on nested tuples containing live objects)."""
    import hashlib
    return hashlib.sha256(repr(key).encode("utf-8")).hexdigest()[:16]
