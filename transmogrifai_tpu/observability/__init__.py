"""Observability: structured tracing + metrics + exporters (docs/observability.md).

The TPU build's replacement for the reference's Spark-listener telemetry
(reference utils/.../spark/OpSparkListener.scala → AppMetrics): spans for
every train/score unit of work (``trace``), a registry of counters, gauges
and streaming-quantile latency histograms (``metrics``), and pure-stdlib
exporters — Chrome trace-event JSON for ``chrome://tracing``/Perfetto,
Prometheus text exposition, JSONL (``export``).

Enable with ``TG_TRACE=1`` (spans + metrics) or ``TG_METRICS=1`` (metrics
only); disabled, every instrumentation point is a single flag check.
``OpWorkflowModel.summary()["observability"]`` returns :func:`summarize` —
the aggregated per-stage / per-family timings, fault counters and scoring
latency quantiles of the current process.

Independently of both switches, the **flight recorder** (``blackbox``)
runs always-on (``TG_BLACKBOX=0`` opts out): a bounded ring of compact
request-correlated events that ``postmortem`` snapshots into atomic
incident bundles on trigger events (breaker open, watchdog stall, OOM
downshift, drift degradation, unclean exit, campaign violations) —
docs/observability.md "Flight recorder & post-mortems".
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from . import blackbox, devicemem, export, ledger, metrics  # noqa: F401
from . import postmortem, slo, timeseries, trace  # noqa: F401
from .blackbox import (  # noqa: F401
    FlightRecorder, blackbox_enabled, correlated, current_correlation,
    enable_blackbox, new_correlation_id, recorder,
)
from .ledger import (  # noqa: F401
    CompileLedger, enable_ledger, fingerprint_diff, ledger_enabled,
    subsystem_scope,
)
from .ledger import ledger as compile_ledger  # noqa: F401
from .metrics import (  # noqa: F401
    MetricsRegistry, enable_metrics, inc_counter, metrics_enabled, observe,
    registry, set_gauge,
)
from .slo import SLOSpec, SLOTracker, scale_hint  # noqa: F401
from .timeseries import (  # noqa: F401
    MetricsSampler, enable_sampler, sampler_enabled, sketch_delta,
)
from .trace import (  # noqa: F401
    Span, Tracer, add_event, enable_tracing, span, tracer, tracing_enabled,
)


def reset() -> None:
    """Fresh tracer + registry + flight recorder + env-driven enablement —
    the per-test isolation hook (tests/conftest.py); production never
    needs it."""
    trace.reset()
    metrics.reset()
    blackbox.reset()
    postmortem.reset()
    ledger.reset()
    devicemem.reset()
    timeseries.reset()
    slo.reset()


def summarize(tr: Optional[trace.Tracer] = None,
              reg: Optional[metrics.MetricsRegistry] = None
              ) -> Dict[str, Any]:
    """Aggregate the span buffer + registry into the
    ``summary()["observability"]`` section: per-stage and per-model-family
    wall-clock (from spans), fault/retry/quarantine counters, scoring
    latency quantiles, and the process compile-cache hit/miss counts."""
    t = tr or trace.tracer()
    r = reg or metrics.registry()
    spans = t.finished()

    by_name: Dict[str, Dict[str, Any]] = {}
    stages: Dict[str, Dict[str, Any]] = {}
    families: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        if s.dur_ns is None:
            continue
        secs = s.dur_ns / 1e9
        agg = by_name.setdefault(s.name, {"seconds": 0.0, "count": 0})
        agg["seconds"] += secs
        agg["count"] += 1
        if s.name in ("stage.fit", "stage.transform"):
            key = str(s.attrs.get("stage", "?"))
            st = stages.setdefault(key, {"seconds": 0.0, "count": 0,
                                         "fitSeconds": 0.0,
                                         "transformSeconds": 0.0})
            st["seconds"] += secs
            st["count"] += 1
            op = ("fitSeconds" if s.name == "stage.fit"
                  else "transformSeconds")
            st[op] += secs
        elif s.name == "sweep.family":
            key = str(s.attrs.get("family", "?"))
            fam = families.setdefault(key, {"seconds": 0.0, "count": 0,
                                            "configs": 0})
            fam["seconds"] += secs
            fam["count"] += 1
            fam["configs"] += int(s.attrs.get("configs", 0) or 0)

    snap = r.snapshot()
    # serving series (tg_serve_* + the breaker gauge + the drift gauges,
    # labelled per model) get their own section — mirrored there from each
    # runtime's serve-local registry when metrics are enabled
    # (docs/serving.md); tg_drift_verdict mirrors each model's drift
    # verdict (0=ok, 1=drifting, 2=degraded)
    serving = {name: series for name, series in snap.items()
               if name.startswith(("tg_serve_", "tg_drift_"))
               or name == "tg_breaker_state"}
    counters = {name: series for name, series in snap.items()
                if not name.startswith("tg_score_") and name not in serving}
    scoring: Dict[str, Any] = {}
    for name, key in (("tg_score_request_seconds", "request"),
                      ("tg_score_microbatch_seconds", "microBatch")):
        series = snap.get(name)
        if series:
            # unlabelled single series — take it directly
            scoring[key] = next(iter(series.values()))
    for name, key in (("tg_score_rows_total", "rowsScored"),
                      ("tg_score_quarantined_total", "rowsQuarantined")):
        series = snap.get(name)
        if series:
            scoring[key] = sum(series.values())

    from ..plan import cache_stats as plan_cache_stats
    from ..utils.jax_cache import cache_stats
    return {
        "enabled": {"tracing": trace.tracing_enabled(),
                    "metrics": metrics.metrics_enabled()},
        "spanCount": len(spans),
        "droppedSpans": t.dropped,
        "byName": dict(sorted(by_name.items(),
                              key=lambda kv: -kv[1]["seconds"])),
        "stages": dict(sorted(stages.items(),
                              key=lambda kv: -kv[1]["seconds"])),
        "families": dict(sorted(families.items(),
                                key=lambda kv: -kv[1]["seconds"])),
        "counters": counters,
        "scoring": scoring,
        "serving": serving,
        "compileCache": cache_stats(),
        "planCache": plan_cache_stats(),
        # cause-classified program builds + predicted/measured device
        # bytes (docs/observability.md "Compile & memory ledger")
        "compileLedger": ledger.ledger().snapshot(),
        "deviceMemory": devicemem.observatory().snapshot(),
        # windowed-sampler + SLO-budget state: registered specs, attached
        # sampler accounting, per-model verdicts + scale hints
        # (docs/observability.md "SLOs, budgets & burn rates")
        "slo": slo.summarize(),
    }
