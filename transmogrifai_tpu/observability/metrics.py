"""Metrics registry: counters, gauges, and streaming-quantile histograms.

The reference aggregates its listener's task metrics into ``AppMetrics``
(reference utils/.../spark/AppMetrics.scala) — a one-shot summary at app
end. A serving system needs *live* aggregates, so this registry keeps
O(1)-memory instruments updated in place: counters/gauges are plain floats,
and latency histograms reuse the SPDT streaming sketch
(``utils/streaming_histogram.py`` — the same algorithm the reference ships
as ``StreamingHistogram.java``) so p50/p95/p99 on the scoring path cost a
fixed ~64 bins per series no matter how many requests flow through.

Instruments are keyed by ``(name, sorted(labels))`` — the Prometheus data
model — and export through ``observability/export.py`` (text exposition
format) or :meth:`MetricsRegistry.snapshot` (plain dicts for
``summary()``).

Switches: ``TG_METRICS=1`` enables recording; unset, it follows
``TG_TRACE`` (a traced run wants its counters too). The instrumentation
helpers (:func:`inc_counter` / :func:`set_gauge` / :func:`observe`) are the
hot-path entry points: one enabled check, zero writes when off — the
overhead guard in tests/test_observability.py holds the registry to exactly
zero writes with observability disabled.

Label cardinality is bounded: a metric name may hold at most
``TG_METRICS_MAX_LABELS`` distinct label sets (default 64). The first
series past the bound collapses into one ``__other__`` overflow series per
name (same label keys, every value ``__other__``) instead of growing the
registry without bound — the guard the per-feature ``tg_drift_*{feature}``
gauges need, and a safety net for any future labelled series (an
unbounded user-supplied label value would otherwise leak one instrument
per distinct value for the life of the process).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..utils.streaming_histogram import StreamingHistogram

#: env switch; unset defers to TG_TRACE (tracing implies metrics)
METRICS_ENV = "TG_METRICS"
#: per-name label-set cardinality bound (docstring above)
MAX_LABELS_ENV = "TG_METRICS_MAX_LABELS"
DEFAULT_MAX_LABELS = 64
#: the label value every over-bound series collapses to
OVERFLOW_LABEL = "__other__"

_FALSY = ("", "0", "false", "False", "no")


def _max_labels() -> int:
    try:
        return max(1, int(os.environ.get(MAX_LABELS_ENV, "")
                          or DEFAULT_MAX_LABELS))
    except ValueError:
        return DEFAULT_MAX_LABELS

_enabled_override: Optional[bool] = None


def metrics_enabled() -> bool:
    if _enabled_override is not None:
        return _enabled_override
    env = os.environ.get(METRICS_ENV)
    if env is not None:
        return env not in _FALSY
    from .trace import tracing_enabled
    return tracing_enabled()


def enable_metrics(on: Optional[bool]) -> None:
    """Force metrics on/off from code; ``None`` hands control back to the
    ``TG_METRICS`` (or ``TG_TRACE``) environment switches."""
    global _enabled_override
    _enabled_override = None if on is None else bool(on)


LabelKey = Tuple[Tuple[str, str], ...]


class Counter:
    """Monotonic accumulator (Prometheus counter; name by convention ends
    in ``_total`` or a unit suffix)."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        self.value += n


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n


#: quantiles exported for every histogram (Prometheus summary convention)
QUANTILES = (0.5, 0.95, 0.99)

#: how many slowest-observation exemplars a histogram keeps
EXEMPLARS_ENV = "TG_EXEMPLARS_K"
DEFAULT_EXEMPLARS_K = 5


def _exemplars_k() -> int:
    try:
        return max(0, int(os.environ.get(EXEMPLARS_ENV, "")
                          or DEFAULT_EXEMPLARS_K))
    except ValueError:
        return DEFAULT_EXEMPLARS_K


class Histogram:
    """Streaming-quantile distribution: fixed-size SPDT sketch + exact
    count/sum. ``observe`` is O(1); quantiles are approximations whose
    error shrinks with bin count (64 bins ≈ sub-percent on unimodal
    latency distributions — validated against numpy in the tests).

    **Exemplars**: observations may carry an exemplar tag (the serving
    runtime passes the request's flight-recorder correlation id —
    observability/blackbox.py); the histogram keeps the tags of its K
    largest observations (``TG_EXEMPLARS_K``, default 5), so a p99
    latency outlier links directly to the recorder timeline of the
    request that caused it."""

    __slots__ = ("name", "labels", "count", "sum", "_sketch", "_exemplars")

    def __init__(self, name: str, labels: Dict[str, str], max_bins: int = 64):
        self.name = name
        self.labels = labels
        self.count = 0
        self.sum = 0.0
        self._sketch = StreamingHistogram(max_bins=max_bins)
        #: (value, exemplar) of the K largest tagged observations, desc
        self._exemplars: List[Tuple[float, Any]] = []

    def observe(self, v: float, exemplar: Any = None) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        self._sketch.update([v])
        if exemplar is not None:
            xs = self._exemplars
            k = _exemplars_k()
            if k and (len(xs) < k or v > xs[-1][0]):
                xs.append((v, exemplar))
                xs.sort(key=lambda t: -t[0])
                del xs[k:]

    def quantile(self, q: float) -> float:
        if self.count == 0:
            return float("nan")
        return float(self._sketch.quantile(q))

    def sketch_state(self) -> Dict[str, Any]:
        """Checkpointable sketch state (plain arrays) — the windowed
        time-series sampler (observability/timeseries.py) snapshots this
        each tick so window-start-vs-now sketch subtraction can compute
        windowed quantiles."""
        return self._sketch.to_state()

    def exemplars(self) -> List[Dict[str, Any]]:
        """The slowest-K tagged observations, largest first:
        ``[{"value": seconds, "exemplar": corr-id}]``."""
        return [{"value": v, "exemplar": e} for v, e in self._exemplars]

    def cumulative_buckets(self) -> List[Tuple[float, float]]:
        """``[(le, cumulative count)]`` derived from the streaming
        sketch's bin centroids — monotone non-decreasing and capped at
        ``count``, ready for Prometheus ``_bucket`` exposition (the
        exporter appends the ``+Inf`` bucket; observability/export.py)."""
        if not self.count:
            return []
        out: List[Tuple[float, float]] = []
        prev = 0.0
        for center, _mass in self._sketch.bins():
            cum = min(float(self._sketch.sum(center)), float(self.count))
            cum = max(cum, prev)
            prev = cum
            out.append((float(center), cum))
        return out

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"count": self.count, "sum": self.sum}
        if self.count:
            out["min"] = float(self._sketch.min)
            out["max"] = float(self._sketch.max)
            for q in QUANTILES:
                out[f"p{int(q * 100)}"] = self.quantile(q)
        if self._exemplars:
            out["exemplars"] = self.exemplars()
        return out


class MetricsRegistry:
    """Get-or-create instrument store. A name is permanently bound to one
    instrument kind; re-requesting with another kind raises (the same
    collision Prometheus clients reject)."""

    def __init__(self, max_labels: Optional[int] = None):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, LabelKey], Any] = {}
        self._kinds: Dict[str, str] = {}
        self._help: Dict[str, str] = {}
        self._max_labels = (max(1, int(max_labels))
                            if max_labels is not None else _max_labels())
        self._series_count: Dict[str, int] = {}
        #: label sets collapsed into the __other__ series, per name
        self.overflowed: Dict[str, int] = {}

    # -- get-or-create -------------------------------------------------------
    def _get(self, cls, kind: str, name: str, help: str,
             labels: Dict[str, str], **kw):
        lk: LabelKey = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            prev = self._kinds.get(name)
            if prev is not None and prev != kind:
                raise ValueError(
                    f"metric '{name}' already registered as {prev}, "
                    f"requested as {kind}")
            self._kinds[name] = kind
            if help:
                self._help.setdefault(name, help)
            m = self._metrics.get((name, lk))
            if m is None:
                # cardinality bound: a NEW labelled series past the bound
                # collapses into the name's single __other__ series instead
                # of registering (last-write-wins for gauges there — an
                # overflow series is a "something beyond the bound exists"
                # signal, not a faithful per-label value)
                if lk and self._series_count.get(name, 0) >= self._max_labels:
                    self.overflowed[name] = self.overflowed.get(name, 0) + 1
                    lk = tuple((k, OVERFLOW_LABEL) for k, _ in lk)
                    m = self._metrics.get((name, lk))
                    if m is not None:
                        return m
                m = self._metrics[(name, lk)] = cls(
                    name, dict(lk), **kw)
                self._series_count[name] = self._series_count.get(name, 0) + 1
            return m

    def counter(self, name: str, help: str = "", **labels: str) -> Counter:
        return self._get(Counter, "counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels: str) -> Gauge:
        return self._get(Gauge, "gauge", name, help, labels)

    def histogram(self, name: str, help: str = "", max_bins: int = 64,
                  **labels: str) -> Histogram:
        return self._get(Histogram, "histogram", name, help, labels,
                         max_bins=max_bins)

    # -- introspection -------------------------------------------------------
    def collect(self) -> List[Tuple[str, str, str, List[Any]]]:
        """→ [(name, kind, help, [instruments])], names sorted, instruments
        in stable label order — the exporter's iteration order."""
        with self._lock:
            by_name: Dict[str, List[Any]] = {}
            for (name, lk), m in sorted(self._metrics.items()):
                by_name.setdefault(name, []).append(m)
            return [(name, self._kinds[name], self._help.get(name, ""), ms)
                    for name, ms in by_name.items()]

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Plain-dict view for ``summary()``: {name: {label-string: value
        or histogram snapshot}} (label-string "" for unlabelled series)."""
        out: Dict[str, Dict[str, Any]] = {}
        for name, kind, _help, ms in self.collect():
            series: Dict[str, Any] = {}
            for m in ms:
                key = ",".join(f"{k}={v}" for k, v in sorted(m.labels.items()))
                series[key] = (m.snapshot() if isinstance(m, Histogram)
                               else m.value)
            out[name] = series
        return out

    def to_prometheus(self, compat: Optional[bool] = None) -> str:
        """Text exposition format: counters/gauges as-is, histograms as
        real cumulative ``_bucket``/``_sum``/``_count`` series derived
        from the streaming sketch (observability/export.py owns the
        grammar). ``compat=True`` — or ``TG_PROM_SUMMARY_COMPAT=1`` —
        restores the pre-round-11 summary exposition (p50/p95/p99
        quantile series) for scrapers built against it."""
        from .export import prometheus_text
        return prometheus_text(self, compat=compat)


def _labels(labels: Dict[str, str], quantile: Optional[float] = None,
            le: Optional[str] = None) -> str:
    items = sorted(labels.items())
    if quantile is not None:
        items.append(("quantile", f"{quantile:g}"))
    if le is not None:
        # the bucket boundary label goes LAST (Prometheus convention)
        items.append(("le", str(le)))
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in items)
    return "{" + body + "}"


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _num(v: float) -> str:
    return repr(float(v))


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def set_registry(r: MetricsRegistry) -> MetricsRegistry:
    global _REGISTRY
    _REGISTRY = r
    return r


def reset() -> None:
    """Fresh registry + env-driven enablement (test isolation)."""
    global _REGISTRY, _enabled_override
    _REGISTRY = MetricsRegistry()
    _enabled_override = None


# -- hot-path instrumentation helpers (one enabled check, zero writes off) --
def inc_counter(name: str, n: float = 1.0, help: str = "",
                **labels: str) -> None:
    if metrics_enabled():
        _REGISTRY.counter(name, help, **labels).inc(n)


def set_gauge(name: str, v: float, help: str = "", **labels: str) -> None:
    if metrics_enabled():
        _REGISTRY.gauge(name, help, **labels).set(v)


def observe(name: str, v: float, help: str = "", **labels: str) -> None:
    if metrics_enabled():
        _REGISTRY.histogram(name, help, **labels).observe(v)
