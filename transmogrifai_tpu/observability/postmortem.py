"""Automatic post-mortem bundles: the flight recorder's crash dump.

When a recovery event fires in production — a breaker opens, the watchdog
catches a stalled thread, a resource-exhaustion downshift, a drift
verdict degrades, a resume finds a previous owner's dying breath, a
campaign schedule violates an oracle — the black box
(``observability/blackbox.py``) holds the last few thousand events of
context, but only until the ring wraps. :func:`trigger` freezes that
context the moment it matters: one atomic, self-contained JSON bundle
(``manifest.atomic_write_bytes`` — a kill mid-dump leaves debris, never a
torn bundle) written to ``TG_POSTMORTEM_DIR`` and rate-limited to
``TG_POSTMORTEM_MAX`` dumps per process (suppressed dumps are counted and
land in the ring as ``postmortem.suppressed`` events — a storm of
triggers cannot turn the incident into a disk-filling incident).

Bundle schema (``schemaVersion`` 3; validated by :func:`validate_bundle`
— which still accepts version-1 bundles from pre-ledger processes and
version-2 bundles from pre-SLO processes — and rendered by ``cli.py
doctor``)::

    {
      "schemaVersion": 3,
      "trigger":     {"kind", "tsNs", "unixTime", "corr", "detail"},
      "pid":         <int>,
      "recorder":    {"events": [...], "dropped", "maxEvents",
                      "epochUnix"},              // recent ring slice
      "correlated":  [...],   // the trigger correlation id's timeline
      "metrics":     {...},   // caller registry snapshot (serve-local)
      "globalMetrics": {...}, // process registry snapshot (TG_METRICS)
      "faults":      {...},   // FaultLog.to_json() when a log was given
      "state":       {...},   // trigger-site state (breaker, drift, ...)
      "ledger":      {"counts", "tail"},  // compile-ledger tail (v2;
                                          // observability/ledger.py)
      "deviceMemory": {...},  // devicemem observatory snapshot (v2)
      "slo":         {...},   // per-model SLO tracker snapshots (v3;
                              // observability/slo.py)
      "samples":     [...],   // recent windowed-sampler samples (v3;
                              // observability/timeseries.py)
      "aot":         {...},   // AOT program-store snapshot: sessions,
                              // hit/miss/export accounting (v4;
                              // transmogrifai_tpu/programstore/)
      "placement":   {...},   // per-fleet placer snapshots: residency,
                              // page-in/eviction accounting, refusals
                              // (v5; serving/placement.py)
      "environment": {"jax", "jaxlib", "backend", "devices", "python"}
    }

Trigger kinds (docs/observability.md "Flight recorder & post-mortems"
carries the full table): ``breaker_open``, ``thread_stalled``,
``oom_downshift``, ``drift_degraded``, ``unclean_exit``,
``campaign_violation``, ``campaign_escape``, ``slo_budget_exhausted``.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from . import blackbox as _blackbox

#: current bundle schema. v2 (PR 12) added the compile-ledger tail and
#: the device-memory snapshot; v3 (PR 13) added the SLO tracker
#: snapshots and the recent windowed-sampler samples; v4 (PR 15) added
#: the AOT program-store snapshot; v5 adds the fleet placement
#: snapshots (serving/placement.py); older bundles (no such sections)
#: must stay readable — validate_bundle accepts every
#: SUPPORTED_SCHEMA_VERSIONS
SCHEMA_VERSION = 5
SUPPORTED_SCHEMA_VERSIONS = (1, 2, 3, 4, 5)
#: how many ledger records a bundle carries (most recent builds)
LEDGER_TAIL = 32

#: where bundles land; default is a per-process tempdir subdirectory so
#: concurrent processes (and test sessions) never interleave bundles
POSTMORTEM_DIR_ENV = "TG_POSTMORTEM_DIR"
#: process-wide dump budget; past it triggers are counted, not dumped
POSTMORTEM_MAX_ENV = "TG_POSTMORTEM_MAX"
DEFAULT_MAX_DUMPS = 16
#: how much of the ring a bundle carries (most recent events)
POSTMORTEM_EVENTS_ENV = "TG_POSTMORTEM_EVENTS"
DEFAULT_BUNDLE_EVENTS = 512

BUNDLE_PREFIX = "postmortem_"

#: the registered trigger classes (docs/observability.md trigger table);
#: validate_bundle flags unknown kinds so the inventory cannot silently rot
TRIGGER_KINDS = (
    "breaker_open",        # circuit breaker transitioned to open
    "thread_stalled",      # watchdog stall / join-timeout thread leak
    "oom_downshift",       # ResourceExhaustedError adaptive downshift
    "drift_degraded",      # drift verdict crossed into degraded
    "unclean_exit",        # resume found a different-pid run sentinel
    "campaign_violation",  # a chaos schedule violated an invariant oracle
    "campaign_escape",     # a typed error escaped a campaign scenario
    "slo_budget_exhausted",  # an SLO error budget fully burned (slo.py)
    "replica_lost",        # a fleet replica died mid-flight (frontdoor.py)
)

_LOCK = threading.Lock()
_SEQ = itertools.count(1)
_DUMPED = 0
_SUPPRESSED = 0
_ENV_CACHE: Optional[Dict[str, Any]] = None


def default_dir() -> str:
    """The env-less bundle directory (per-process, under the tempdir)."""
    return os.path.join(tempfile.gettempdir(),
                        f"tg_postmortems_{os.getpid()}")


def postmortem_dir() -> str:
    return os.environ.get(POSTMORTEM_DIR_ENV) or default_dir()


def max_dumps() -> int:
    try:
        return max(0, int(os.environ.get(POSTMORTEM_MAX_ENV, "")
                          or DEFAULT_MAX_DUMPS))
    except ValueError:
        return DEFAULT_MAX_DUMPS


def bundle_events() -> int:
    try:
        return max(1, int(os.environ.get(POSTMORTEM_EVENTS_ENV, "")
                          or DEFAULT_BUNDLE_EVENTS))
    except ValueError:
        return DEFAULT_BUNDLE_EVENTS


def dump_counts() -> Dict[str, int]:
    """Process accounting: bundles written vs triggers suppressed by the
    rate limit."""
    with _LOCK:
        return {"dumped": _DUMPED, "suppressed": _SUPPRESSED}


def reset() -> None:
    """Reset the rate-limit counters (test isolation; bundles already on
    disk are the test's to clean — see conftest ``_no_blackbox_leak``)."""
    global _DUMPED, _SUPPRESSED
    with _LOCK:
        _DUMPED = 0
        _SUPPRESSED = 0


def _environment() -> Dict[str, Any]:
    """jax / device / interpreter provenance, computed once per process —
    the part of an incident report you can never reconstruct later."""
    global _ENV_CACHE
    if _ENV_CACHE is not None:
        return dict(_ENV_CACHE)
    env: Dict[str, Any] = {
        "python": sys.version.split()[0],
        "platform": sys.platform,
    }
    try:
        import jax
        env["jax"] = getattr(jax, "__version__", None)
        try:
            import jaxlib
            env["jaxlib"] = getattr(jaxlib, "__version__", None)
        except Exception:
            env["jaxlib"] = None
        devs = jax.devices()
        env["backend"] = devs[0].platform if devs else None
        env["devices"] = [{"id": d.id, "kind": getattr(d, "device_kind", "")}
                          for d in devs]
    except Exception as e:  # pragma: no cover - jax must never fail a dump
        env["jaxError"] = f"{type(e).__name__}: {e}"[:200]
    _ENV_CACHE = env
    return dict(env)


def trigger(kind: str, corr: Optional[str] = None,
            detail: Optional[Dict[str, Any]] = None,
            fault_log: Optional[Any] = None,
            metrics: Optional[Any] = None,
            state: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Dump one post-mortem bundle for a trigger event; returns the bundle
    path, or None (recorder disabled / rate limit hit / write failed — a
    post-mortem must NEVER take down the path it is documenting).

    ``corr`` filters a correlated timeline into the bundle; ``fault_log``
    / ``metrics`` / ``state`` are the trigger site's context (its
    FaultLog, its serve-local MetricsRegistry, and any extra state dict —
    a breaker snapshot, a drift report)."""
    global _DUMPED, _SUPPRESSED
    if not _blackbox.blackbox_enabled():
        return None
    if corr is None:
        corr = _blackbox.current_correlation()
    with _LOCK:
        if _DUMPED >= max_dumps():
            _SUPPRESSED += 1
            suppressed = _SUPPRESSED
            seq = None
        else:
            _DUMPED += 1
            seq = next(_SEQ)
    rec = _blackbox.recorder()
    if seq is None:
        rec.record("postmortem.suppressed", corr=corr, trigger=kind,
                   suppressed=suppressed)
        return None
    now_ns = time.perf_counter_ns() - rec.epoch_ns
    doc: Dict[str, Any] = {
        "schemaVersion": SCHEMA_VERSION,
        "trigger": {"kind": kind, "tsNs": now_ns, "unixTime": time.time(),
                    "corr": corr, "detail": dict(detail or {})},
        "pid": os.getpid(),
        "recorder": {**rec.snapshot(),
                     "events": [e.to_json()
                                for e in rec.tail(bundle_events())]},
        "correlated": ([e.to_json() for e in rec.slice_for(corr)]
                       if corr else []),
        "environment": _environment(),
    }
    try:
        if metrics is not None:
            doc["metrics"] = metrics.snapshot()
        from . import metrics as _obs_metrics
        doc["globalMetrics"] = _obs_metrics.registry().snapshot()
        if fault_log is not None:
            doc["faults"] = fault_log.to_json()
        if state:
            doc["state"] = dict(state)
        # compiles & memory (schema v2): the recent build tail with
        # classified causes, and the predicted/measured byte peaks — the
        # "was a retrace storm / allocation spike part of this incident?"
        # context (observability/ledger.py, observability/devicemem.py)
        from . import devicemem as _devicemem
        from . import ledger as _ledger
        led = _ledger.ledger()
        doc["ledger"] = {
            "counts": led.counts(),
            "builds": led.total,
            "tail": [r.to_json() for r in led.tail(LEDGER_TAIL)],
        }
        doc["deviceMemory"] = _devicemem.observatory().snapshot()
        # SLO & sampler context (schema v3): per-model budget/alert
        # snapshots and the recent windowed samples — the "was the SLO
        # already burning before this incident?" context. The serving
        # module is only consulted when already loaded (a train-side
        # trigger must not drag the serving stack in).
        import sys as _sys
        slo_doc: Dict[str, Any] = {}
        rt_mod = _sys.modules.get("transmogrifai_tpu.serving.runtime")
        if rt_mod is not None:
            for rt in rt_mod.live_runtimes():
                snap = rt.slo_snapshot()
                if snap is not None:
                    slo_doc[rt.name] = snap
        doc["slo"] = slo_doc
        from . import timeseries as _timeseries
        doc["samples"] = [{"source": s.name, **s.snapshot(),
                           "recent": s.recent(8)}
                          for s in _timeseries.attached()]
        # AOT program-store context (schema v4): was the incident's
        # process serving deserialized programs, and had the store been
        # missing/falling back? (transmogrifai_tpu/programstore/)
        from ..programstore import store as _pstore
        doc["aot"] = _pstore.snapshot()
        # placement context (schema v5): which models were resident
        # where, what paged in/evicted, and what the budget refused —
        # the "did the incident's replica hold the only warm copy?"
        # context. Consulted only when the placement module is already
        # loaded (train-side triggers must not drag serving in).
        place_doc: Dict[str, Any] = {}
        pl_mod = _sys.modules.get("transmogrifai_tpu.serving.placement")
        if pl_mod is not None:
            for p in pl_mod.live_placers():
                place_doc[p.name] = p.snapshot()
        doc["placement"] = place_doc
    except Exception as e:  # context gathering must not kill the dump
        doc["contextError"] = f"{type(e).__name__}: {e}"[:300]
    path = os.path.join(postmortem_dir(),
                        f"{BUNDLE_PREFIX}{seq:04d}_{kind}.json")
    try:
        from ..manifest import atomic_write_bytes
        os.makedirs(postmortem_dir(), exist_ok=True)
        atomic_write_bytes(path, json.dumps(
            doc, default=str, separators=(",", ":")).encode("utf-8"))
    except OSError:
        return None
    rec.record("postmortem", corr=corr, trigger=kind, path=path)
    return path


# -- reading + validation (cli.py doctor, tests, the campaign engine) --------

def list_bundles(dirpath: Optional[str] = None) -> List[str]:
    """Bundle paths in ``dirpath`` (default the active TG_POSTMORTEM_DIR),
    oldest first."""
    d = dirpath or postmortem_dir()
    if not os.path.isdir(d):
        return []
    return [os.path.join(d, f) for f in sorted(os.listdir(d))
            if f.startswith(BUNDLE_PREFIX) and f.endswith(".json")]


def read_bundle(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        return json.load(fh)


def validate_bundle(doc: Dict[str, Any]) -> List[str]:
    """Schema check → list of problems (empty = valid). The acceptance
    gate every trigger-class test and the serve bench run bundles
    through."""
    problems: List[str] = []
    version = doc.get("schemaVersion")
    if version not in SUPPORTED_SCHEMA_VERSIONS:
        problems.append(
            f"schemaVersion {version!r} not in {SUPPORTED_SCHEMA_VERSIONS}")
    trig = doc.get("trigger")
    if not isinstance(trig, dict):
        problems.append("missing trigger section")
    else:
        if trig.get("kind") not in TRIGGER_KINDS:
            problems.append(f"unknown trigger kind {trig.get('kind')!r}")
        for k in ("tsNs", "unixTime", "detail"):
            if k not in trig:
                problems.append(f"trigger missing {k!r}")
    recd = doc.get("recorder")
    if not isinstance(recd, dict) or not isinstance(
            recd.get("events"), list):
        problems.append("missing recorder.events ring slice")
    else:
        for e in recd["events"][:8]:
            if not {"kind", "tsNs", "attrs"} <= set(e):
                problems.append(f"malformed ring event: {e!r}")
                break
        # the triggering event must be visible in the ring slice: the
        # trigger sites record their event (fault choke point / breaker /
        # verdict) BEFORE dumping
        if not recd["events"]:
            problems.append("empty ring slice — the trigger left no events")
    if not isinstance(doc.get("correlated"), list):
        problems.append("missing correlated timeline list")
    if not isinstance(doc.get("environment"), dict):
        problems.append("missing environment section")
    if not isinstance(doc.get("pid"), int):
        problems.append("missing pid")
    if isinstance(version, int) and version >= 2:
        # v2+ sections; v1 bundles predate the ledger and stay valid
        led = doc.get("ledger")
        if not isinstance(led, dict) or not isinstance(
                led.get("tail"), list):
            problems.append("missing ledger section (schema v2)")
        if not isinstance(doc.get("deviceMemory"), dict):
            problems.append("missing deviceMemory section (schema v2)")
    if isinstance(version, int) and version >= 3:
        # v3 sections; v2 bundles predate the SLO engine and stay valid
        if not isinstance(doc.get("slo"), dict):
            problems.append("missing slo section (schema v3)")
        if not isinstance(doc.get("samples"), list):
            problems.append("missing samples section (schema v3)")
    if isinstance(version, int) and version >= 4:
        # v4 section; v3 bundles predate the AOT store and stay valid
        if not isinstance(doc.get("aot"), dict):
            problems.append("missing aot section (schema v4)")
    if isinstance(version, int) and version >= 5:
        # v5 section; v4 bundles predate the placement layer
        if not isinstance(doc.get("placement"), dict):
            problems.append("missing placement section (schema v5)")
    return problems
