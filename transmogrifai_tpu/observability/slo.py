"""SLO engine: declarative per-model/per-tenant objectives, error
budgets, and multi-window multi-burn-rate alerts
(docs/observability.md "SLOs, budgets & burn rates").

The methodology is the Google SRE Workbook's: an :class:`SLOSpec`
declares targets (availability, latency, freshness), the error budget
is the allowed bad fraction over a 30-day-style window
(``TG_SLO_WINDOW_S`` scales it — tests run the whole machinery in
milliseconds on an injectable clock), and alerts fire on **burn rate**
— how many times faster than "exactly exhausting the budget at the
window's end" the service is currently burning — measured over *two*
windows per rule so a short spike cannot page (the long window filters
it) and a real incident pages fast (the short window catches it):

    ========  ==========================  =========  ===========
    severity  long window                 short       burn ≥
    ========  ==========================  =========  ===========
    page      1h   (1/720 of the window)  5m  (1/12)  14.4
    ticket    6h   (1/120 of the window)  30m (1/12)  6.0
    ========  ==========================  =========  ===========

An active alert clears only when both windows drop below
``HYSTERESIS × threshold`` — boundary traffic cannot flap it.

Objectives per :class:`SLOSpec`:

* **availability** — SLI ``1 − (sheds + quarantined) / submitted`` from
  the serve counters, windowed through the sampler
  (``observability/timeseries.py``); budget ``1 − availability_target``.
* **latency** — bad events are requests over ``latency_p99_ms``
  (estimated from windowed sketch subtraction:
  ``window_count − cdf_increase(target)``); budget: 1% of requests may
  exceed a p99 target (``1 − 0.99``), so the same burn-rate algebra
  applies unchanged.
* **freshness** — binary: the model's drift verdict
  (serving/drift.py) must not be ``degraded``; reported as a verdict
  (no burn — drift heals by refit, not by budget).

Emissions on every evaluation (sampler tick cadence): the
``tg_slo_burn_rate{model,slo}`` / ``tg_slo_budget_remaining{model,slo}``
/ ``tg_slo_alert{model,slo,severity}`` series (serve-local, mirrored to
the global registry when TG_METRICS), ``slo.alert`` flight-recorder
events on every alert transition, and — when an objective's budget is
fully exhausted — ONE ``slo_budget_exhausted`` post-mortem bundle per
exhaustion episode (observability/postmortem.py, bundle schema v3).

:func:`scale_hint` is the autoscaling artifact ROADMAP item 2 consumes:
``up`` / ``hold`` / ``down`` derived from five signal families — queue
depth, windowed shed rate, breaker state, burn rate/alerts, and the
drift verdict — with machine-readable reasons (a breaker-open model
holds: replicas of a failing device path don't help; a drift-degraded
model holds: the *data* is wrong, not the capacity).
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import blackbox as _blackbox
from . import metrics as _obs_metrics
from . import timeseries as _timeseries

#: budget window (seconds); the canonical 30 days, env-scalable so tests
#: and the CLI can run the full alert ladder in milliseconds/seconds
SLO_WINDOW_ENV = "TG_SLO_WINDOW_S"
DEFAULT_WINDOW_S = 30 * 86400.0
#: default availability target for models without a registered spec
SLO_AVAILABILITY_ENV = "TG_SLO_AVAILABILITY"
DEFAULT_AVAILABILITY = 0.999
#: default latency target (ms) for default specs; unset disables the
#: latency objective unless a spec declares one
SLO_P99_ENV = "TG_SLO_P99_MS"

#: multi-window multi-burn-rate rules: (severity, long-window fraction
#: of the SLO window, short-window fraction, burn-rate threshold) — the
#: SRE Workbook's 1h/5m page + 6h/30m ticket pair
ALERT_RULES: Tuple[Tuple[str, float, float, float], ...] = (
    ("page", 1.0 / 720.0, 1.0 / 8640.0, 14.4),
    ("ticket", 1.0 / 120.0, 1.0 / 1440.0, 6.0),
)
#: an active alert clears only below HYSTERESIS × threshold (no flap)
HYSTERESIS = 0.8

#: alert severities, most severe first
SEVERITIES = ("page", "ticket")


def slo_window_s() -> float:
    try:
        v = float(os.environ.get(SLO_WINDOW_ENV, "") or DEFAULT_WINDOW_S)
        return v if v > 0 else DEFAULT_WINDOW_S
    except ValueError:
        return DEFAULT_WINDOW_S


def _default_availability() -> float:
    try:
        v = float(os.environ.get(SLO_AVAILABILITY_ENV, "")
                  or DEFAULT_AVAILABILITY)
        return v if 0.0 < v < 1.0 else DEFAULT_AVAILABILITY
    except ValueError:
        return DEFAULT_AVAILABILITY


def _default_p99_ms() -> Optional[float]:
    raw = os.environ.get(SLO_P99_ENV)
    if not raw:
        return None
    try:
        v = float(raw)
        return v if v > 0 else None
    except ValueError:
        return None


@dataclass
class SLOSpec:
    """One model's (or one tenant-within-a-model's) objectives."""
    model: str
    #: availability target (fraction of submitted requests that must be
    #: neither shed nor quarantined)
    availability: float = field(default_factory=_default_availability)
    #: p99 latency target in ms; None disables the latency objective
    latency_p99_ms: Optional[float] = field(default_factory=_default_p99_ms)
    #: include the freshness (drift-verdict) objective
    freshness: bool = True
    #: budget window; None defers to TG_SLO_WINDOW_S at evaluation time
    window_s: Optional[float] = None
    #: per-tenant budget: SLIs read the tenant-labelled serve series
    tenant: Optional[str] = None

    @property
    def key(self) -> str:
        return self.model if self.tenant is None else (
            f"{self.model}/{self.tenant}")

    def to_json(self) -> Dict[str, Any]:
        return {"model": self.model, "tenant": self.tenant,
                "availability": self.availability,
                "latencyP99Ms": self.latency_p99_ms,
                "freshness": self.freshness, "windowS": self.window_s}


# -- spec registry (declarative; conftest asserts no leak) -------------------

_SPEC_LOCK = threading.Lock()
_SPECS: List[SLOSpec] = []


def register(spec: SLOSpec) -> SLOSpec:
    """Register a spec; runtimes started afterwards pick it up (one
    tracker per spec matching the model's name)."""
    with _SPEC_LOCK:
        _SPECS[:] = [s for s in _SPECS if s.key != spec.key]
        _SPECS.append(spec)
    return spec


def unregister(key: str) -> None:
    with _SPEC_LOCK:
        _SPECS[:] = [s for s in _SPECS if s.key != key]


def registered_specs() -> List[SLOSpec]:
    with _SPEC_LOCK:
        return list(_SPECS)


def specs_for(model: str) -> List[SLOSpec]:
    """The specs a runtime named ``model`` tracks: every registered spec
    for that model, else one default (env-driven) model-level spec."""
    with _SPEC_LOCK:
        mine = [s for s in _SPECS if s.model == model]
    return mine if mine else [SLOSpec(model=model)]


def reset() -> None:
    """Drop every registered spec (test isolation)."""
    with _SPEC_LOCK:
        _SPECS.clear()


# -- the tracker -------------------------------------------------------------

class SLOTracker:
    """Evaluates ONE spec against a model's windowed serve telemetry.

    ``runtime`` is duck-typed (needs ``breaker.state``, ``drift_monitor``,
    ``fault_log``) and optional — unit tests drive a tracker from a bare
    registry + sampler. Evaluation normally runs on the sampler's tick
    hook; ``evaluate`` is also safe to call on demand (``health()``,
    ``cli slo``)."""

    def __init__(self, spec: SLOSpec, sampler: _timeseries.MetricsSampler,
                 metrics: _obs_metrics.MetricsRegistry,
                 runtime: Any = None,
                 clock: Optional[Callable[[], float]] = None):
        self.spec = spec
        self.sampler = sampler
        self.metrics = metrics
        self.runtime = runtime
        self.clock = clock or sampler.clock
        self._lock = threading.Lock()
        #: (objective, severity) → alert currently active
        self._active: Dict[Tuple[str, str], bool] = {}
        #: cumulative alert activations by severity (asserted by the
        #: bench chaos line — a fired-then-cleared page still counts)
        self.fired: Dict[str, int] = {s: 0 for s in SEVERITIES}
        #: objectives currently inside a budget-exhaustion episode (one
        #: post-mortem per episode, re-armed when the budget recovers)
        self._exhausted: Dict[str, bool] = {}
        self._snapshot: Dict[str, Any] = {"enabled": True,
                                          "spec": spec.to_json(),
                                          "objectives": {},
                                          "fired": dict(self.fired)}

    @property
    def key(self) -> str:
        return self.spec.key

    # -- SLI plumbing --------------------------------------------------------
    def _serve_labels(self) -> Dict[str, str]:
        lbls = {"model": self.spec.model}
        if self.spec.tenant is not None:
            lbls["tenant"] = self.spec.tenant
        return lbls

    def _series(self, base: str) -> str:
        """Tenant specs read the tenant-labelled twin series the runtime
        counts next to the model-level ones (serving/runtime.py)."""
        if self.spec.tenant is None:
            return base
        return base.replace("tg_serve_", "tg_serve_tenant_", 1)

    def _availability_bad_fraction(self, window_s: float, now: float
                                   ) -> Tuple[float, float]:
        """→ (bad fraction, submitted) over the window."""
        lbls = self._serve_labels()
        shed = self.sampler.increase(
            self._series("tg_serve_shed_total"), window_s, now=now, **lbls)
        quar = self.sampler.increase(
            self._series("tg_serve_quarantined_total"), window_s, now=now,
            **lbls)
        rows = self.sampler.increase(
            self._series("tg_serve_rows_total"), window_s, now=now, **lbls)
        submitted = rows + shed
        if submitted <= 0:
            return 0.0, 0.0
        return min(1.0, (shed + quar) / submitted), submitted

    def _latency_bad_fraction(self, window_s: float, now: float
                              ) -> Tuple[float, float]:
        lbls = self._serve_labels()
        name = self._series("tg_serve_request_seconds")
        target_s = (self.spec.latency_p99_ms or 0.0) / 1000.0
        cnt = self.sampler.window_count(name, window_s, now=now, **lbls)
        if cnt <= 0:
            return 0.0, 0.0
        below = self.sampler.cdf_increase(name, target_s, window_s,
                                          now=now, **lbls)
        over = max(0.0, cnt - below)
        return min(1.0, over / cnt), cnt

    # -- evaluation ----------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One full evaluation pass: SLIs → burn rates → alert state
        machines → budget accounting → gauges/events/triggers. Returns
        (and caches) the snapshot dict."""
        now = self.clock() if now is None else now
        window = self.spec.window_s or slo_window_s()
        objectives: Dict[str, Any] = {}
        objectives["availability"] = self._burn_objective(
            "availability", 1.0 - self.spec.availability,
            self._availability_bad_fraction, window, now)
        if self.spec.latency_p99_ms:
            objectives["latency"] = self._burn_objective(
                "latency", 1.0 - 0.99, self._latency_bad_fraction,
                window, now)
        if self.spec.freshness:
            objectives["freshness"] = self._freshness_objective()
        snap = {"enabled": True, "spec": self.spec.to_json(),
                "evaluatedAt": now, "windowS": window,
                "objectives": objectives, "fired": dict(self.fired),
                "worst": _worst_verdict(objectives)}
        with self._lock:
            self._snapshot = snap
        return snap

    def _burn_objective(self, obj: str, allowed: float,
                        bad_fraction, window: float, now: float
                        ) -> Dict[str, Any]:
        allowed = max(allowed, 1e-12)
        burns: Dict[str, Dict[str, float]] = {}
        alerts: Dict[str, bool] = {}
        for sev, long_f, short_f, thr in ALERT_RULES:
            b_long = bad_fraction(long_f * window, now)[0] / allowed
            b_short = bad_fraction(short_f * window, now)[0] / allowed
            burns[sev] = {"long": b_long, "short": b_short,
                          "threshold": thr}
            alerts[sev] = self._alert_state(obj, sev, b_long, b_short, thr)
        bad_w, submitted_w = bad_fraction(window, now)
        allowed_bad = allowed * submitted_w
        spent = (bad_w * submitted_w) / allowed_bad if allowed_bad else 0.0
        remaining = 1.0 - spent
        exhausted = bool(submitted_w and remaining <= 0.0)
        self._budget_episode(obj, exhausted, remaining, burns)
        verdict = ("exhausted" if exhausted
                   else "breach" if any(alerts.values()) else "ok")
        self._emit_gauges(obj, burns, remaining, alerts)
        return {"sli": 1.0 - bad_w, "badFraction": bad_w,
                "submitted": submitted_w, "allowedBadFraction": allowed,
                "burn": burns, "budgetRemaining": remaining,
                "alerts": alerts, "verdict": verdict}

    def _freshness_objective(self) -> Dict[str, Any]:
        verdict = "ok"
        drift = None
        mon = getattr(self.runtime, "drift_monitor", None)
        if mon is not None:
            try:
                drift = mon.verdict()
            except Exception:
                drift = None
            if drift == "degraded":
                verdict = "breach"
        self._gauge("tg_slo_burn_rate", 1.0 if verdict == "breach" else 0.0,
                    slo="freshness")
        return {"drift": drift, "verdict": verdict}

    # -- alert + budget state machines ---------------------------------------
    def _alert_state(self, obj: str, sev: str, b_long: float,
                     b_short: float, thr: float) -> bool:
        key = (obj, sev)
        with self._lock:
            active = self._active.get(key, False)
        if not active:
            fire = b_long >= thr and b_short >= thr
            if fire:
                with self._lock:
                    self._active[key] = True
                    self.fired[sev] = self.fired.get(sev, 0) + 1
                _blackbox.record("slo.alert", model=self.spec.model,
                                 tenant=self.spec.tenant, slo=obj,
                                 severity=sev, state="firing",
                                 burnLong=round(b_long, 3),
                                 burnShort=round(b_short, 3),
                                 threshold=thr)
            return fire
        # hysteresis: stay active until BOTH windows cool below 0.8×thr
        clear = b_long < thr * HYSTERESIS and b_short < thr * HYSTERESIS
        if clear:
            with self._lock:
                self._active[key] = False
            _blackbox.record("slo.alert", model=self.spec.model,
                             tenant=self.spec.tenant, slo=obj,
                             severity=sev, state="resolved",
                             burnLong=round(b_long, 3),
                             burnShort=round(b_short, 3))
            return False
        return True

    def _budget_episode(self, obj: str, exhausted: bool, remaining: float,
                        burns: Dict[str, Dict[str, float]]) -> None:
        with self._lock:
            was = self._exhausted.get(obj, False)
            self._exhausted[obj] = exhausted
        if exhausted and not was:
            # one post-mortem per exhaustion episode: the budget is gone —
            # every further bad event is un-budgeted SLO damage
            from . import postmortem as _postmortem
            _postmortem.trigger(
                "slo_budget_exhausted",
                fault_log=getattr(self.runtime, "fault_log", None),
                metrics=self.metrics,
                detail={"model": self.spec.model,
                        "tenant": self.spec.tenant, "objective": obj,
                        "budgetRemaining": round(remaining, 6),
                        "burn": {s: round(b["long"], 3)
                                 for s, b in burns.items()}},
                state={"slo": self.snapshot()})

    # -- emission ------------------------------------------------------------
    def _gauge(self, name: str, v: float, **labels: str) -> None:
        lbls = {"model": self.spec.model, **labels}
        if self.spec.tenant is not None:
            lbls["tenant"] = self.spec.tenant
        self.metrics.gauge(name, "", **lbls).set(v)
        _obs_metrics.set_gauge(name, v, "", **lbls)

    def _emit_gauges(self, obj: str, burns: Dict[str, Dict[str, float]],
                     remaining: float, alerts: Dict[str, bool]) -> None:
        self._gauge("tg_slo_burn_rate", burns["page"]["long"], slo=obj)
        self._gauge("tg_slo_budget_remaining", remaining, slo=obj)
        for sev, active in alerts.items():
            self._gauge("tg_slo_alert", 1.0 if active else 0.0,
                        slo=obj, severity=sev)

    # -- introspection -------------------------------------------------------
    def active_alerts(self) -> List[Dict[str, str]]:
        with self._lock:
            return [{"objective": obj, "severity": sev}
                    for (obj, sev), on in sorted(self._active.items()) if on]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            snap = dict(self._snapshot)
        snap["fired"] = dict(self.fired)
        snap["activeAlerts"] = self.active_alerts()
        return snap


def _worst_verdict(objectives: Dict[str, Any]) -> str:
    order = {"ok": 0, "breach": 1, "exhausted": 2}
    worst = "ok"
    for o in objectives.values():
        v = o.get("verdict", "ok")
        if order.get(v, 0) > order.get(worst, 0):
            worst = v
    return worst


# -- autoscaling signal ------------------------------------------------------

#: queue occupancy past this fraction of max_queue reads as overload
QUEUE_UP_FRACTION = 0.5
#: the shed-rate / request-rate lookback (seconds, scaled off the page
#: long window so TG_SLO_WINDOW_S shrinks it for tests)
def _hint_window_s() -> float:
    return max(ALERT_RULES[0][1] * slo_window_s(), 1e-6)


def scale_hint(runtime: Any,
               slo_snapshot: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
    """``{"hint": "up"|"hold"|"down", "reasons": [...]}`` — the
    machine-readable autoscaling artifact (ROADMAP item 2), derived from
    five signal families: breaker state, queue depth, windowed shed
    rate, SLO burn/alerts, and the drift verdict.

    Ladder (first match wins):

    1. breaker open/half-open → **hold** — more replicas of a failing
       device path fail identically; heal first.
    2. overload — queue past ``QUEUE_UP_FRACTION`` of ``max_queue``, a
       nonzero windowed shed rate, or an active page alert → **up**.
    3. drift verdict degraded → **hold** — the data is wrong, not the
       capacity; a refit is (or should be) healing it.
    4. idle — empty queue and ~zero windowed request rate with no
       active alerts → **down**.
    5. otherwise → **hold** (steady state).
    """
    reasons: List[str] = []
    breaker = getattr(getattr(runtime, "breaker", None), "state", "closed")
    if breaker != "closed":
        return {"hint": "hold",
                "reasons": [f"breaker-{breaker}: device path unhealthy — "
                            "scaling adds replicas of a failing path"]}
    depth = float(runtime.queue_depth())
    max_queue = float(getattr(runtime.config, "max_queue", 0) or 1)
    queue_frac = depth / max_queue
    w = _hint_window_s()
    sampler = getattr(runtime, "sampler", None)
    shed_rate = req_rate = 0.0
    if sampler is not None:
        shed_rate = sampler.rate("tg_serve_shed_total", w,
                                 model=runtime.name)
        req_rate = (sampler.rate("tg_serve_rows_total", w,
                                 model=runtime.name) + shed_rate)
    page_active = False
    if slo_snapshot:
        for snap in slo_snapshot.values():
            for a in snap.get("activeAlerts", []):
                if a.get("severity") == "page":
                    page_active = True
    if queue_frac >= QUEUE_UP_FRACTION:
        reasons.append(f"queue-depth {depth:.0f}/{max_queue:.0f}")
    if shed_rate > 0:
        reasons.append(f"shed-rate {shed_rate:.2f}/s over {w:.3g}s")
    if page_active:
        reasons.append("page-severity burn-rate alert active")
    if reasons:
        return {"hint": "up", "reasons": reasons}
    drift = None
    mon = getattr(runtime, "drift_monitor", None)
    if mon is not None:
        try:
            drift = mon.verdict()
        except Exception:
            drift = None
    if drift == "degraded":
        return {"hint": "hold",
                "reasons": ["drift-degraded: data drifted, not capacity — "
                            "refit heals this, replicas do not"]}
    if depth == 0 and req_rate <= 0.0:
        return {"hint": "down", "reasons": ["idle: empty queue, ~zero "
                                            f"request rate over {w:.3g}s"]}
    return {"hint": "hold", "reasons": ["steady: within SLO at current "
                                        "capacity"]}


def summarize() -> Dict[str, Any]:
    """The ``summary()["observability"]["slo"]`` section: registered
    specs, attached sampler accounting, and — when the serving runtime
    module is loaded — per-model tracker snapshots + scale hints."""
    import sys
    out: Dict[str, Any] = {
        "enabled": _timeseries.sampler_enabled(),
        "specs": [s.to_json() for s in registered_specs()],
        "samplers": [s.snapshot() for s in _timeseries.attached()],
    }
    rt_mod = sys.modules.get("transmogrifai_tpu.serving.runtime")
    if rt_mod is not None:
        models: Dict[str, Any] = {}
        for rt in rt_mod.live_runtimes():
            try:
                models[rt.name] = {"slo": rt.slo_snapshot(),
                                   "scaleHint": scale_hint(
                                       rt, rt.slo_snapshot())}
            except Exception:  # pragma: no cover - defensive
                pass
        out["models"] = models
    return out
