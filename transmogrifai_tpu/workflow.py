"""OpWorkflow / OpWorkflowModel — the user-facing engine.

Mirrors the reference workflow layer (reference:
core/src/main/scala/com/salesforce/op/OpWorkflow.scala,
OpWorkflowCore.scala, OpWorkflowModel.scala): the workflow reconstructs the
stage DAG from result-feature lineage, materializes the raw FeatureTable
through a reader, fits the DAG layer-by-layer, and returns a fitted model that
scores (batched, on device) and reports summaries.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .dag import (
    apply_transformations_dag, compute_dag, fit_and_transform_dag, validate_dag,
)
from .features import Feature
from .readers.readers import DataFrameReader, Reader, dataframe_to_table
from .stages.base import Estimator, FeatureGeneratorStage
from .table import Column, FeatureTable


def _open_run_sentinel(ckpt_dir: Optional[str], resume: bool):
    """Cross-process kill detection (docs/robustness.md): open this run's
    pid+phase sentinel in the checkpoint dir. On ``resume=True``, a stale
    sentinel left by a *different* process is the previous owner's dying
    breath — recorded as a FaultLog ``unclean_exit`` (``oomKillSuspected``
    when its last phase was device work) before this run takes over.
    Returns the started sentinel (cleared by the caller on clean exit),
    or None without a checkpoint dir."""
    if ckpt_dir is None:
        return None
    from .manifest import RunSentinel
    from .robustness.policy import FaultLog, FaultReport
    sentinel = RunSentinel(ckpt_dir)
    if resume:
        stale = sentinel.read_stale()
        if stale is not None:
            detail = {"pid": stale.get("pid"),
                      "phase": stale.get("phase"),
                      "dir": ckpt_dir,
                      "oomKillSuspected":
                          RunSentinel.suspects_oom_kill(stale)}
            FaultLog.record(FaultReport(
                site="manifest.sentinel", kind="unclean_exit",
                detail=dict(detail)))
            # trigger event: the previous owner of this checkpoint dir
            # died mid-run — dump what this process knows (the sentinel's
            # last phase is the dying breath; the resume that follows is
            # the recovery) before training over the evidence
            # (observability/postmortem.py)
            from .observability import postmortem as _postmortem
            _postmortem.trigger("unclean_exit", detail=detail)
    sentinel.start("dag_fit")
    return sentinel


class _WorkflowCore:
    """Shared state between workflow and model (reference OpWorkflowCore.scala:60-84)."""

    def __init__(self):
        self.reader: Optional[Reader] = None
        self.result_features: Tuple[Feature, ...] = ()
        self.raw_features: Tuple[Feature, ...] = ()
        self.blacklisted_features: Tuple[Feature, ...] = ()
        self.parameters: Dict[str, Any] = {}
        self._input_table: Optional[FeatureTable] = None

    # -- input wiring (reference OpWorkflowCore.setInputDataset:146-170) -----
    def set_reader(self, reader: Reader):
        self.reader = reader
        return self

    def set_input_dataset(self, df, key_field: Optional[str] = None):
        self.reader = DataFrameReader(df, key_field=key_field)
        return self

    def set_input_table(self, table: FeatureTable):
        self._input_table = table
        return self

    def set_parameters(self, params: Dict[str, Any]):
        """Workflow-level param injection by stage class name or uid
        (reference OpWorkflow.setStageParameters:166-188)."""
        self.parameters = dict(params)
        return self

    #: OpWorkflow (training) demands response columns too; the fitted model
    #: scores without them (reference: scoring never reads the label)
    _require_response_columns = True

    def _generate_raw_table(self) -> FeatureTable:
        if self._input_table is not None:
            self._validate_input_table(self._input_table)
            return self._input_table
        if self.reader is None:
            raise ValueError(
                "no data source: call set_reader / set_input_dataset / set_input_table")
        return self.reader.generate_table(self.raw_features)

    def _validate_input_table(self, table: FeatureTable) -> None:
        """A user-supplied table bypasses reader-side feature extraction, so
        check it up front: every raw feature needs a column of the matching
        type kind — otherwise a stage fails deep in the DAG with an opaque
        shape/dtype error."""
        required = [f for f in self.raw_features
                    if self._require_response_columns or not f.is_response]
        missing = [f.name for f in required
                   if f.name not in table.column_names]
        if missing:
            raise ValueError(
                f"input table is missing raw feature column(s) {missing}; "
                f"table has {sorted(table.column_names)}")
        mismatched = []
        for f in required:
            col = table[f.name]
            want = f.feature_type.column_kind
            got = col.feature_type.column_kind
            if want != got:
                mismatched.append(f"{f.name}: feature is {f.type_name} "
                                  f"({want}) but column holds "
                                  f"{col.feature_type.__name__} ({got})")
        if mismatched:
            raise ValueError("input table column kind mismatch — "
                             + "; ".join(mismatched))

    def _inject_stage_params(self, stages: Sequence[Any]) -> None:
        per_stage = self.parameters.get("stageParams", {})
        if not per_stage:
            return
        for stage in stages:
            for key in (stage.uid, type(stage).__name__):
                if key in per_stage:
                    stage.set_params(**per_stage[key])


class OpWorkflow(_WorkflowCore):
    """Defines the DAG from result features and trains it
    (reference OpWorkflow.scala:85-444)."""

    def __init__(self):
        super().__init__()
        self._layers = None
        self._raw_feature_filter = None
        self.profiler = None
        self._workflow_cv = False

    def with_workflow_cv(self) -> "OpWorkflow":
        """Leakage-free workflow-level cross-validation: label-dependent prep
        stages (SanityChecker, supervised bucketizers) refit inside every CV
        fold instead of once before the sweep (reference
        OpWorkflow.withWorkflowCV + FitStagesUtil.cutDAG:305-358)."""
        self._workflow_cv = True
        return self

    def with_profiler(self, profiler=None) -> "OpWorkflow":
        """Collect per-stage wall-clock metrics during train (the reference's
        OpSparkListener/logStageMetrics knob, OpParams.scala:66-72)."""
        from .utils.profiler import StageProfiler
        self.profiler = profiler or StageProfiler()
        return self

    def with_checkpoint_dir(self, path: str) -> "OpWorkflow":
        """Crash-resumable training: every fitted estimator persists to
        ``path`` as it completes, and a re-run skips stages already
        checkpointed there (matched by uid). Writes are atomic (tmp +
        fsync + rename) and committed through a per-directory integrity
        manifest (format version + per-file sha256 + completion records);
        every ModelSelector additionally persists per-candidate sweep
        results as they are evaluated. A re-run (see ``train(resume=True)``)
        restores *verified* stage checkpoints, replays the persisted sweep
        state, and refits only the remainder; corrupt or torn files are
        detected by checksum, reported in ``summary()["faults"]``, and
        never silently used. The TPU build's analog of the reference's
        persist-every-K-stages resilience (OpWorkflowModel.scala:449-455,
        FitStagesUtil.scala:125-131) — deterministic re-execution from
        saved state instead of Spark lineage recomputation."""
        self._checkpoint_dir = path
        return self

    def with_fault_policy(self, policy=None) -> "OpWorkflow":
        """Fault-isolated training: per-stage retries for TRANSIENT errors
        under ``policy`` (a ``robustness.RetryPolicy``; default policy when
        None), on top of the always-on guards (candidate quarantine,
        guarded transfers, checkpoint skip-and-log). Every recovery is
        recorded and surfaced in ``model.summary()["faults"]`` — the TPU
        build's analog of the reference riding ``spark.task.maxFailures`` +
        lineage recomputation (docs/robustness.md)."""
        from .robustness.policy import RetryPolicy
        self._fault_policy = policy or RetryPolicy()
        return self

    def with_mesh(self, mesh) -> "OpWorkflow":
        """Distribute training over a ('data', 'model') device mesh: every
        stage exposing ``set_mesh`` (ModelSelector — rows over 'data',
        configs over 'model') picks it up at train time. The reference's
        cluster topology (Spark driver+executors) becomes a jax mesh; under
        ``jax.distributed`` (parallel.distributed.initialize) the same code
        spans hosts with ICI inside a slice and DCN across slices."""
        self._mesh = mesh
        return self

    def set_result_features(self, *features: Feature) -> "OpWorkflow":
        """Reconstruct the stage DAG from lineage (reference
        OpWorkflow.setResultFeatures:85-105)."""
        if not features:
            raise ValueError("result features cannot be empty")
        self.result_features = tuple(features)
        validate_dag(self.result_features)
        raw: Dict[str, Feature] = {}
        for f in features:
            for r in f.raw_features():
                raw[r.uid] = r
        self.raw_features = tuple(sorted(raw.values(), key=lambda f: f.name))
        self._layers = compute_dag(self.result_features)
        return self

    def with_raw_feature_filter(self, rff) -> "OpWorkflow":
        """Attach a RawFeatureFilter applied before fitting (reference
        OpWorkflow.withRawFeatureFilter:524-563)."""
        self._raw_feature_filter = rff
        return self

    def with_model_stages(self, model: "OpWorkflowModel") -> "OpWorkflow":
        """Partial retrain: swap in already-fitted stages by uid so only new
        estimators refit (reference OpWorkflow.withModelStages:457-461)."""
        if not self.result_features:
            raise ValueError("call set_result_features before with_model_stages")
        fitted = {s.uid: s for s in model.stages}
        self.result_features = tuple(
            f.copy_with_new_stages(fitted) for f in self.result_features)
        self._layers = compute_dag(self.result_features)
        return self

    @property
    def stages(self) -> List[Any]:
        return [s for layer in (self._layers or []) for s, _ in layer]

    def train(self, resume: bool = False, stream=None) -> "OpWorkflowModel":
        """Materialize raw data, fit the DAG, return the fitted model
        (reference OpWorkflow.train:332-357). The whole fit runs under an
        activated FaultLog: retries, quarantines, skipped checkpoints and
        checkpoint restorations recorded anywhere in the stack surface in
        ``summary()["faults"]``.

        ``resume=True`` — preemption recovery: requires
        ``with_checkpoint_dir``; fitted upstream stages restore from
        *verified* checkpoints (manifest + sha256), persisted sweep state
        replays so only unevaluated candidates run, and the returned
        model's ``summary()["resume"]`` records exactly what was restored
        vs refit. Checkpoints failing verification are reported and the
        stage refits — a resume never crashes on (or silently uses) state
        it can deterministically rebuild.

        ``stream=<ChunkSource>`` — out-of-core training
        (docs/streaming.md): the raw table is never materialized; every
        estimator fits as chunked monoid folds over a double-buffered
        host→device feed, per-chunk-checkpointed when a checkpoint dir is
        set, so ``train(resume=True, stream=...)`` after a kill at any
        ``stream.*`` site resumes to a bit-identical model. The fitted
        model is a plain OpWorkflowModel (scoring, serving, persistence
        all unchanged); ``summary()["streaming"]`` carries the feed
        accounting (chunks, uploaded bytes, peak device residency,
        overlap)."""
        from .observability import blackbox as _blackbox
        from .observability.trace import span as _obs_span
        from .robustness.policy import FaultLog
        fault_log = FaultLog()
        # one flight-recorder correlation id per run: every black-box
        # event recorded inside this train (stream passes, sweep
        # dispatches, fault recoveries) is stamped with it, so a
        # recorder slice replays this run's full timeline
        # (observability/blackbox.py)
        corr = (_blackbox.new_correlation_id("run")
                if _blackbox.blackbox_enabled() else None)
        with fault_log.activate(), _blackbox.correlated(corr), \
                _obs_span("workflow.train", cat="train", resume=resume,
                          stream=stream is not None):
            _blackbox.record("workflow.train", resume=resume,
                             stream=stream is not None)
            if stream is not None:
                model = self._train_streaming(stream, resume=resume)
            else:
                model = self._train_logged(resume=resume)
            _blackbox.record("workflow.train_done")
        model._fault_log = fault_log
        model._correlation = corr
        return model

    def _train_streaming(self, source, resume: bool = False) -> "OpWorkflowModel":
        """Streamed dual of ``_train_logged``: same checkpoint/resume
        machinery, but the DAG fits via ``streaming.fit_dag_streaming``
        (layer-wise chunk folds) instead of one in-memory table. A few
        in-core-only workflow modes are rejected up front with the reason
        rather than silently materializing the dataset."""
        if not self.result_features:
            raise ValueError("call set_result_features before train")
        if self._raw_feature_filter is not None:
            raise ValueError(
                "RawFeatureFilter is not supported with train(stream=...): "
                "its fill-rate/histogram stats are available as streaming "
                "folds (streaming.folds.HistogramFold) but score-vs-train "
                "comparison needs a second stream — train in-core or drop "
                "the filter (ROADMAP item 5)")
        if self._workflow_cv:
            raise ValueError(
                "with_workflow_cv() is not supported with train(stream=...):"
                " per-fold DAG refits need fold-sliced tables")
        if getattr(self, "_mesh", None) is not None:
            raise ValueError(
                "with_mesh() is not supported with train(stream=...) yet: "
                "chunk folds are host monoids (ROADMAP item 3 will shard "
                "chunks over hosts)")
        from .streaming.checkpoint import StreamCheckpoint
        from .streaming.trainer import fit_dag_streaming
        layers = self._layers
        source.bind(self.raw_features)
        self._inject_stage_params([s for layer in layers for s, _ in layer])
        ckpt_dir = getattr(self, "_checkpoint_dir", None)
        if resume and ckpt_dir is None:
            raise ValueError(
                "train(resume=True) requires with_checkpoint_dir(...): "
                "there is no checkpoint state to resume from")
        checkpoint = None
        preloaded = None
        stream_ckpt = None
        if ckpt_dir is not None:
            from .persistence import (load_stage_checkpoints,
                                      open_checkpoint_manifest,
                                      save_stage_checkpoint)
            preloaded = load_stage_checkpoints(ckpt_dir)
            manifest = open_checkpoint_manifest(ckpt_dir)
            checkpoint = lambda model: save_stage_checkpoint(
                model, ckpt_dir, manifest)
            stream_ckpt = StreamCheckpoint(ckpt_dir, manifest,
                                           source.fingerprint())
        # transformed-chunk cache: one handle for the whole train, shared
        # by every stage and pass so repeat sweeps replay prepped chunks
        # (host LRU under TG_STREAM_CACHE_BYTES; sha256-verified disk
        # tier under TG_STREAM_CACHE_DIR — point it at
        # <checkpoint dir>/stream_cache so cached prep survives a kill
        # next to the fold states it matches)
        from .streaming.cache import ChunkCache
        stream_cache = ChunkCache.from_env()
        from .manifest import active_sentinel
        sentinel = _open_run_sentinel(ckpt_dir, resume)
        with active_sentinel(sentinel):
            fitted, transformers, stats = fit_dag_streaming(
                source, layers,
                checkpoint=checkpoint, stream_checkpoint=stream_ckpt,
                preloaded=preloaded,
                retry_policy=getattr(self, "_fault_policy", None),
                cache=stream_cache)
        if sentinel is not None:
            sentinel.clear()
        new_results = tuple(
            f.copy_with_new_stages(fitted) for f in self.result_features)
        model = OpWorkflowModel()
        model.reader = self.reader
        model.parameters = self.parameters
        model.result_features = new_results
        model.raw_features = self.raw_features
        model.blacklisted_features = ()
        model.rff_results = None
        # a small transformed head-of-stream probe stands in for the full
        # train table: it carries the fitted schema (vector widths,
        # metadata) that model persistence / serve warm-start fingerprint
        # read — O(probe rows), never the dataset
        probe = next(iter(source.chunks(0))).table
        if probe.num_rows > 256:
            probe = probe.take(np.arange(256))
        for m in transformers:
            probe = m.transform(probe)
        model.train_table = probe
        model._stream_stats = stats
        model._stream_cache_stats = (stream_cache.stats
                                     if stream_cache is not None else None)
        model._fitted_stage_uids = sorted(fitted)
        model._resume_requested = resume
        model._layers = compute_dag(new_results)
        return model

    def _train_logged(self, resume: bool = False) -> "OpWorkflowModel":
        if not self.result_features:
            raise ValueError("call set_result_features before train")
        table = self._generate_raw_table()
        layers = self._layers
        result_features = self.result_features
        blacklisted: Tuple[Feature, ...] = ()
        rff_results = None
        if self._raw_feature_filter is not None:
            if (getattr(self, "_mesh", None) is not None
                    and hasattr(self._raw_feature_filter, "set_mesh")):
                # RFF is the first full pass over raw data — shard it too
                self._raw_feature_filter.set_mesh(self._mesh)
            table, blacklist, rff_results = self._raw_feature_filter.filter_raw(
                table, self.raw_features)
            if blacklist:
                result_features, layers = self._apply_blacklist(blacklist)
                blacklisted = tuple(blacklist)
        self._inject_stage_params([s for layer in layers for s, _ in layer])
        mesh = getattr(self, "_mesh", None)
        if mesh is not None:
            for layer in layers:
                for s, _ in layer:
                    if hasattr(s, "set_mesh"):
                        s.set_mesh(mesh)
        ckpt_dir = getattr(self, "_checkpoint_dir", None)
        if resume and ckpt_dir is None:
            raise ValueError(
                "train(resume=True) requires with_checkpoint_dir(...): "
                "there is no checkpoint state to resume from")
        checkpoint = None
        preloaded = None
        if ckpt_dir is not None:
            from .impl.tuning.sweep_checkpoint import SweepCheckpoint
            from .persistence import (load_stage_checkpoints,
                                      open_checkpoint_manifest,
                                      save_stage_checkpoint)
            # restored stages are manifest-verified (sha256); failures are
            # reported as checkpoint_skipped and the stage refits
            preloaded = load_stage_checkpoints(ckpt_dir)
            # ONE manifest object shared by stage checkpoints and sweep
            # state, so sequential commits never clobber each other
            manifest = open_checkpoint_manifest(ckpt_dir)
            checkpoint = lambda model: save_stage_checkpoint(
                model, ckpt_dir, manifest)
            for layer in layers:
                for s, _ in layer:
                    if hasattr(s, "set_sweep_checkpoint"):
                        s.set_sweep_checkpoint(
                            SweepCheckpoint(ckpt_dir, s.uid, manifest))
        retry_policy = getattr(self, "_fault_policy", None)
        from .manifest import active_sentinel
        sentinel = _open_run_sentinel(ckpt_dir, resume)
        with active_sentinel(sentinel):
            if self._workflow_cv:
                table, fitted = self._fit_with_workflow_cv(table, layers)
            else:
                table, fitted = fit_and_transform_dag(
                    table, layers, profiler=self.profiler,
                    checkpoint=checkpoint, preloaded=preloaded,
                    retry_policy=retry_policy)
        if sentinel is not None:
            # clean-exit commit: a kill anywhere above leaves the sentinel
            # for the next resume to report
            sentinel.clear()
        new_results = tuple(
            f.copy_with_new_stages(fitted) for f in result_features)
        model = OpWorkflowModel()
        model.reader = self.reader
        model.parameters = self.parameters
        model.result_features = new_results
        model.raw_features = self.raw_features
        model.blacklisted_features = blacklisted
        model.rff_results = rff_results
        model.train_table = table
        #: resume accounting: which estimator uids this train fitted (or
        #: restored) — summary()["resume"] splits them via the fault log
        model._fitted_stage_uids = sorted(fitted)
        model._resume_requested = resume
        if self.profiler is not None:
            # score timings get their own collector — mixing them into the
            # train AppMetrics would conflate fit and serve costs
            from .utils.profiler import StageProfiler
            model.profiler = StageProfiler()
        model._layers = compute_dag(new_results)
        return model

    def drift_refit_hook(self, save_dir: str, resume: Optional[bool] = None):
        """A serving-registry refit hook bound to this workflow
        (``ModelRegistry(refit_hook=...)`` / ``set_refit_hook``;
        docs/serving.md "Drift monitoring & self-healing"): when a served
        model's drift verdict degrades, the registry calls the hook on a
        background thread; it retrains this workflow on whatever its
        reader/input currently yields (point the reader at fresh data —
        that is the whole point of a drift refit), saves the result under
        ``save_dir`` (``refit_000001``, ``refit_000002``, ... so the
        in-service model directory is never written over while being
        read), and returns the saved path for the registry's
        manifest-verified load + warm hot swap.

        ``resume`` defaults to whether a checkpoint dir is attached —
        ``with_checkpoint_dir`` makes the refit itself preemption-safe
        (``train(resume=True)`` restores verified stages and replays
        sweep state instead of starting over after a kill)."""
        import os as _os
        counter = {"n": 0}
        if resume is None:
            resume = getattr(self, "_checkpoint_dir", None) is not None

        def hook(name: str, runtime, report) -> str:
            counter["n"] += 1
            model = self.train(resume=resume)
            path = _os.path.join(save_dir, f"refit_{counter['n']:06d}")
            model.save(path)
            return path

        return hook

    def _fit_with_workflow_cv(self, table: FeatureTable, layers):
        """The cutDAG path (reference FitStagesUtil.cutDAG:305-358 +
        OpWorkflow.fitStages:397-442): fit label-independent stages once,
        run ModelSelector.find_best_estimator with per-fold copies of the
        label-dependent ("during") DAG, then fit everything remaining —
        including the during stages on the full data and the selector, which
        now skips its own sweep and refits the recorded winner."""
        from .impl.selector.model_selector import ModelSelector
        from .stages.base import AllowLabelAsInput

        all_stages = [(s, d) for layer in layers for s, d in layer]
        selectors = [s for s, _ in all_stages if isinstance(s, ModelSelector)]
        if len(selectors) != 1:
            raise ValueError(
                f"workflow-level CV requires exactly one ModelSelector, "
                f"found {len(selectors)} (reference FitStagesUtil.cutDAG:313)")
        sel = selectors[0]
        _, vec_f = sel.input_features

        # taint propagation over the FULL result ancestry: a feature is
        # label-dependent if its origin stage consumes the label while
        # producing a predictor (AllowLabelAsInput estimators), is the
        # selector itself, or has any tainted parent. Tainted stages — and
        # everything downstream of them, selector outputs included — defer to
        # the rest phase so their inputs exist when they fit.
        tainted: Dict[str, bool] = {}
        ordered: List[Feature] = []
        seen: set = set()
        for rf in self.result_features:
            for feat in rf.all_features():      # post-order: parents first
                if feat.uid in seen:
                    continue
                seen.add(feat.uid)
                ordered.append(feat)
                st = feat.origin_stage
                own = ((isinstance(st, Estimator)
                        and isinstance(st, AllowLabelAsInput))
                       or st is sel)
                tainted[feat.uid] = own or any(tainted.get(p.uid, False)
                                               for p in feat.parents)
        tainted_stage_uids = {f_.origin_stage.uid for f_ in ordered
                              if tainted[f_.uid] and not f_.is_raw}

        retry_policy = getattr(self, "_fault_policy", None)
        before_layers = [[(s, d) for s, d in layer
                          if s.uid not in tainted_stage_uids]
                         for layer in layers]
        table1, fitted_before = fit_and_transform_dag(
            table, before_layers, profiler=self.profiler,
            retry_policy=retry_policy)

        # the in-CV DAG refit per fold: tainted estimator stages on the
        # selector-input ancestry (not the selector, not its downstream)
        vec_anc = {f_.origin_stage.uid for f_ in vec_f.all_features()
                   if not f_.is_raw}
        during_layers = [[(s, d) for s, d in layer
                          if s.uid in tainted_stage_uids and s.uid in vec_anc
                          and s is not sel]
                         for layer in layers]
        during_layers = [l for l in during_layers if l]

        rest_layers = [[(s, d) for s, d in layer
                        if s.uid in tainted_stage_uids]
                       for layer in layers]
        rest_layers = [l for l in rest_layers if l]
        try:
            sel.find_best_estimator(table1, during_layers)
            table2, fitted_rest = fit_and_transform_dag(
                table1, rest_layers, profiler=self.profiler,
                retry_policy=retry_policy)
        except Exception:
            # don't leave a recorded winner behind: a later plain train()
            # on the same stage objects must validate from scratch, not
            # silently reuse a selection made on this failed run's data
            sel._preset_best = None
            raise
        return table2, {**fitted_before, **fitted_rest}

    def _apply_blacklist(self, blacklist: Sequence[Feature]):
        """DAG surgery removing blacklisted raw features (reference
        OpWorkflow.setBlacklist:112-154). Stages whose inputs are all
        blacklisted are dropped; vectorizers drop the blacklisted inputs."""
        gone = {f.uid for f in blacklist}

        def rebuild(f: Feature, cache: Dict[str, Optional[Feature]]) -> Optional[Feature]:
            if f.uid in cache:
                return cache[f.uid]
            if f.is_raw:
                out = None if f.uid in gone else f
                cache[f.uid] = out
                return out
            kept_parents = []
            for p in f.parents:
                np_ = rebuild(p, cache)
                if np_ is not None:
                    kept_parents.append(np_)
            if not kept_parents:
                cache[f.uid] = None
                return None
            stage = f.origin_stage
            if len(kept_parents) != len(f.parents):
                import copy as _copy
                stage = _copy.copy(stage)
                stage.input_features = tuple(kept_parents)
                stage._output_feature = None
                out = stage.get_output()
                # keep original identity so downstream wiring still matches
                out.name = f.name
                out.uid = f.uid
                stage._output_feature = out
            else:
                stage.input_features = tuple(kept_parents)
                out = Feature(f.name, f.feature_type, f.is_response, stage,
                              kept_parents, uid=f.uid)
                stage._output_feature = out
            cache[f.uid] = out
            return out

        cache: Dict[str, Optional[Feature]] = {}
        new_results = []
        for f in self.result_features:
            nf = rebuild(f, cache)
            if nf is None:
                raise ValueError(
                    f"result feature '{f.name}' lost all inputs to the raw feature filter")
            new_results.append(nf)
        return tuple(new_results), compute_dag(new_results)


class OpWorkflowModel(_WorkflowCore):
    """Fitted workflow (reference OpWorkflowModel.scala)."""

    #: serve-time tables may omit the label column — scoring never reads it
    _require_response_columns = False

    def __init__(self):
        super().__init__()
        self._layers = None
        self.train_table: Optional[FeatureTable] = None
        self.rff_results = None
        self.profiler = None
        #: train-scoped fault accounting (robustness.FaultLog); None for
        #: models loaded from disk — wiring state, never serialized
        self._fault_log = None

    @property
    def stages(self) -> List[Any]:
        return [s for layer in (self._layers or []) for s, _ in layer]

    def get_stage(self, uid: str) -> Any:
        for s in self.stages:
            if s.uid == uid:
                return s
        raise KeyError(uid)

    # -- scoring (reference OpWorkflowModel.score:254-324) -------------------
    def score(self, table: Optional[FeatureTable] = None, df=None,
              keep_raw_features: bool = True,
              keep_intermediate_features: bool = True) -> FeatureTable:
        """Batch scoring over the fitted transformer DAG. The pass runs on
        the fused substrate: the transform-plan compiler (``plan.py``)
        traces each device-fusable segment into one XLA program (eager
        per-stage dispatch under a profiler, ``TG_PLAN=0``, or active
        chaos — results are bit-identical either way, docs/plan.md)."""
        if df is not None:
            table = dataframe_to_table(df, self.raw_features)
        if table is None:
            table = self._generate_raw_table()
        from .observability.trace import span as _obs_span
        with _obs_span("workflow.score", cat="score", rows=table.num_rows):
            scored = apply_transformations_dag(table, self._layers,
                                               profiler=self.profiler)
        if keep_raw_features and keep_intermediate_features:
            return scored
        keep = [f.name for f in self.result_features if f.name in scored.column_names]
        if keep_raw_features:
            keep = [f.name for f in self.raw_features] + keep
        return scored.select(keep)

    def score_and_evaluate(self, evaluator, table: Optional[FeatureTable] = None,
                           df=None) -> Tuple[FeatureTable, Dict[str, float]]:
        scored = self.score(table=table, df=df)
        return scored, evaluator.evaluate_all(scored)

    def evaluate(self, evaluator, table: Optional[FeatureTable] = None) -> Dict[str, float]:
        return self.score_and_evaluate(evaluator, table=table)[1]

    # -- persistence (reference OpWorkflowModel.save) ------------------------
    def save(self, path: str) -> None:
        from .persistence import save_model
        save_model(self, path)

    @staticmethod
    def load(path: str, workflow: Optional["OpWorkflow"] = None) -> "OpWorkflowModel":
        from .persistence import load_model
        return load_model(path, workflow=workflow)

    # -- local scoring (reference local/OpWorkflowModelLocal.scala) ----------
    def score_function(self):
        from .local import score_function
        return score_function(self)

    # -- summaries (reference OpWorkflowModel.summary:183-211) ---------------
    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for stage in self.stages:
            md = getattr(stage, "summary_metadata", None)
            if md:
                out[stage.uid] = md
        # fault accounting for THIS train run: quarantined candidates,
        # successful retries, skipped checkpoints, restorations
        # (docs/robustness.md; empty sections for models loaded from disk —
        # the log is train-scoped)
        from .robustness.policy import FaultLog
        log = getattr(self, "_fault_log", None)
        out["faults"] = (log or FaultLog()).to_json()
        # resume accounting: what this train restored from verified
        # checkpoints vs actually (re)fit (docs/robustness.md "Resume
        # semantics"). Empty/false for models loaded from disk.
        restored_stages = sorted(
            r.detail.get("uid") for r in (log.reports if log else [])
            if r.kind == "restored" and r.site == "dag.stage_fit")
        out["resume"] = {
            "requested": bool(getattr(self, "_resume_requested", False)),
            "restoredStages": restored_stages,
            "refitStages": [
                uid for uid in getattr(self, "_fitted_stage_uids", [])
                if uid not in set(restored_stages)],
            "restoredSweepCandidates": [
                dict(r.detail) for r in (log.reports if log else [])
                if r.kind == "restored" and r.site == "sweep.candidate"],
        }
        # live telemetry aggregates (docs/observability.md): per-stage /
        # per-family span timings, fault counters, scoring latency
        # quantiles, compile-cache hit/miss. Process-scoped (the tracer and
        # registry outlive any one train — exactly like serving counters
        # should); {"enabled": {... false}} sections when observability is
        # off.
        from .observability import summarize
        out["observability"] = summarize()
        # out-of-core feed accounting for streamed trains (chunks, uploaded
        # bytes, peak device residency, overlap — docs/streaming.md);
        # absent for in-core/loaded models
        stream_stats = getattr(self, "_stream_stats", None)
        if stream_stats is not None:
            out["streaming"] = stream_stats.to_json()
            cache_stats = getattr(self, "_stream_cache_stats", None)
            if cache_stats is not None:
                out["streaming"]["cache"] = cache_stats.to_json()
        return out

    def summary_json(self) -> str:
        return json.dumps(self.summary(), indent=2, default=_json_default)

    def summary_pretty(self) -> str:
        lines: List[str] = ["Workflow summary:"]
        for stage in self.stages:
            pretty = getattr(stage, "summary_pretty", None)
            if callable(pretty):
                lines.append(pretty())
            elif getattr(stage, "summary_metadata", None):
                lines.append(f"-- {type(stage).__name__} ({stage.uid})")
        return "\n".join(lines)

    def model_insights(self, feature=None):
        """Full model report extracted from the fitted stages (reference
        OpWorkflowModel.modelInsights:163-176). ``feature`` is accepted for
        API parity; insights always cover the model's result features."""
        from .insights import ModelInsights
        return ModelInsights.extract(self)


def _json_default(o):
    if isinstance(o, (np.floating, np.integer)):
        return o.item()
    if isinstance(o, np.ndarray):
        return o.tolist()
    if hasattr(o, "to_json"):
        return o.to_json()
    if hasattr(o, "__dict__"):
        return {k: v for k, v in vars(o).items() if not k.startswith("_")}
    return str(o)
