"""Data-prep examples: event aggregation, joins, and conditional readers.

Mirrors the reference helloworld dataprep apps (reference:
helloworld/src/main/scala/com/salesforce/hw/dataprep/JoinsAndAggregates.scala
and ConditionalAggregation.scala) on the reference's own Email/WebVisits CSV
datasets:

* ``joins_and_aggregates`` — two event tables ("email sends" and "email
  clicks") are each monoid-aggregated by user around a cutoff date
  (predictors before, responses after), joined on the key, and a derived
  CTR feature is computed with the arithmetic DSL.
* ``conditional_aggregation`` — web-visit events are aggregated per user
  relative to the time each user first hits a target landing page
  (conditional-probability prep); users who never hit it are dropped.
"""
from __future__ import annotations

import datetime as _dt

import numpy as np

from ..aggregators import CutOffTime, Sum
from ..features import FeatureBuilder
from ..readers.aggregates import (
    AggregateDataReader, AggregateParams, ConditionalDataReader,
    ConditionalParams, JoinedDataReader,
)
from ..readers.readers import CSVReader

_RES = "/root/reference/helloworld/src/main/resources"
CLICKS_PATH = f"{_RES}/EmailDataset/Clicks.csv"
SENDS_PATH = f"{_RES}/EmailDataset/Sends.csv"
WEB_VISITS_PATH = f"{_RES}/WebVisitsDataset/WebVisits.csv"

DAY_MS = 24 * 3600 * 1000


def _parse_ts(value: str) -> int:
    """'2017-09-02::09:30:00' → epoch millis (reference joda pattern
    yyyy-MM-dd::HH:mm:ss)."""
    dt = _dt.datetime.strptime(value, "%Y-%m-%d::%H:%M:%S")
    return int(dt.replace(tzinfo=_dt.timezone.utc).timestamp() * 1000)


def joins_and_aggregates(clicks_path: str = CLICKS_PATH,
                         sends_path: str = SENDS_PATH):
    """reference JoinsAndAggregates: aggregate clicks/sends per user around
    the 2017-09-04 cutoff, join, and derive CTR. Returns the joined
    FeatureTable and the feature handles."""
    num_clicks_yday = (FeatureBuilder.Real("numClicksYday")
                       .extract(lambda r: 1.0).aggregate(Sum())
                       .window(DAY_MS).as_predictor())
    num_clicks_tomorrow = (FeatureBuilder.Real("numClicksTomorrow")
                           .extract(lambda r: 1.0).aggregate(Sum())
                           .window(DAY_MS).as_response())
    num_sends_last_week = (FeatureBuilder.Real("numSendsLastWeek")
                           .extract(lambda r: 1.0).aggregate(Sum())
                           .window(7 * DAY_MS).as_predictor())

    cutoff = CutOffTime.unix_epoch(_parse_ts("2017-09-04::00:00:00"))
    clicks_reader = AggregateDataReader(
        CSVReader(clicks_path, header=False,
                  schema=["clickId", "userId", "emailId", "timeStamp"]),
        AggregateParams(cutoff=cutoff,
                        timestamp_fn=lambda r: _parse_ts(r["timeStamp"])),
        key_field="userId")
    sends_reader = AggregateDataReader(
        CSVReader(sends_path, header=False,
                  schema=["sendId", "userId", "emailId", "timeStamp"]),
        AggregateParams(cutoff=cutoff,
                        timestamp_fn=lambda r: _parse_ts(r["timeStamp"])),
        key_field="userId")

    reader = JoinedDataReader(
        clicks_reader, sends_reader, join_type="outer",
        feature_sides={"numClicksYday": "left",
                       "numClicksTomorrow": "left",
                       "numSendsLastWeek": "right"})
    features = [num_clicks_yday, num_clicks_tomorrow, num_sends_last_week]
    table = reader.generate_table(features)

    clicks = np.nan_to_num(np.asarray(table["numClicksYday"].values,
                                      dtype=np.float64))
    sends = np.nan_to_num(np.asarray(table["numSendsLastWeek"].values,
                                     dtype=np.float64))
    ctr = clicks / (sends + 1.0)
    return table, ctr


def conditional_aggregation(path: str = WEB_VISITS_PATH):
    """reference ConditionalAggregation: per user, the first visit to the
    SaveBig landing page sets the cutoff; predictors aggregate the prior
    week, responses the next day; users never meeting the condition drop."""
    num_visits_week_prior = (FeatureBuilder.RealNN("numVisitsWeekPrior")
                             .extract(lambda r: 1.0).aggregate(Sum())
                             .window(7 * DAY_MS).as_predictor())
    def _bought(r):
        v = r.get("productId")
        return 1.0 if v is not None and v == v and v != "" else 0.0  # NaN-safe

    num_purchases_next_day = (FeatureBuilder.RealNN("numPurchasesNextDay")
                              .extract(_bought)
                              .aggregate(Sum()).window(DAY_MS).as_response())

    reader = ConditionalDataReader(
        CSVReader(path, header=False,
                  schema=["userId", "url", "productId", "price",
                          "timestamp"]),
        ConditionalParams(
            target_condition=lambda r: r["url"]
            == "http://www.amazon.com/SaveBig",
            timestamp_fn=lambda r: _parse_ts(r["timestamp"]),
            response_window=DAY_MS,
            drop_if_target_condition_not_met=True),
        key_field="userId")
    return reader.generate_table([num_visits_week_prior,
                                  num_purchases_next_day])


def main():
    table, ctr = joins_and_aggregates()
    print("JoinsAndAggregates:")
    for i, k in enumerate(table.key):
        print(f"  user {k}: clicksYday="
              f"{np.asarray(table['numClicksYday'].values)[i]:.1f} "
              f"sendsLastWeek="
              f"{np.asarray(table['numSendsLastWeek'].values)[i]:.1f} "
              f"ctr={ctr[i]:.3f} clicksTomorrow="
              f"{np.asarray(table['numClicksTomorrow'].values)[i]:.1f}")
    cond = conditional_aggregation()
    print("ConditionalAggregation:")
    for i, k in enumerate(cond.key):
        print(f"  user {k}: visitsWeekPrior="
              f"{np.asarray(cond['numVisitsWeekPrior'].values)[i]:.1f} "
              f"purchasesNextDay="
              f"{np.asarray(cond['numPurchasesNextDay'].values)[i]:.1f}")
    return table, cond


if __name__ == "__main__":
    main()
