"""Iris multiclass — helloworld parity example.

Mirrors the reference helloworld app (reference:
helloworld/src/main/scala/com/salesforce/hw/iris/OpIris.scala): sepal/petal
numerics → transmogrify → MultiClassificationModelSelector (with DataCutter)
→ train/score.
"""
from __future__ import annotations

from typing import Tuple

from ..features import Feature, FeatureBuilder
from ..impl.feature import transmogrify
from ..impl.selector import MultiClassificationModelSelector
from ..workflow import OpWorkflow

IRIS_SCHEMA = ["sepalLength", "sepalWidth", "petalLength", "petalWidth",
               "irisClass"]
DEFAULT_PATH = ("/root/reference/helloworld/src/main/resources/"
                "IrisDataset/iris.data")
_CLASSES = ("Iris-setosa", "Iris-versicolor", "Iris-virginica")


def iris_features() -> Tuple[Feature, Feature]:
    """(label, featureVector) (reference OpIris.scala feature definitions —
    the label is the indexed irisClass)."""
    label = FeatureBuilder.RealNN("irisClass").extract(
        lambda r: float(_CLASSES.index(r.get("irisClass")))
        if r.get("irisClass") in _CLASSES else None).as_response()
    nums = [FeatureBuilder.RealNN(c).extract_field().as_predictor()
            for c in IRIS_SCHEMA[:4]]
    return label, transmogrify(nums)


def build_workflow(path: str = DEFAULT_PATH, seed: int = 42):
    import pandas as pd
    df = pd.read_csv(path, header=None, names=IRIS_SCHEMA).dropna()
    label, vec = iris_features()
    pred = (MultiClassificationModelSelector
            .with_cross_validation(seed=seed)
            .set_input(label, vec).get_output())
    wf = OpWorkflow().set_input_dataset(df).set_result_features(pred)
    return wf, label, pred


def main(path: str = DEFAULT_PATH):
    wf, label, pred = build_workflow(path)
    model = wf.train()
    print(model.summary_pretty())
    return model


if __name__ == "__main__":
    main()
