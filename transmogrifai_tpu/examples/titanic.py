"""Titanic survival — the canonical end-to-end flow.

Mirrors the reference helloworld app (reference:
helloworld/src/main/scala/com/salesforce/hw/OpTitanicSimple.scala): typed
FeatureBuilders → derived features → ``transmogrify`` → SanityChecker →
BinaryClassificationModelSelector → OpWorkflow.train → summary/score.
"""
from __future__ import annotations

from typing import Optional, Tuple

from ..features import Feature, FeatureBuilder
from ..impl.feature import transmogrify
from ..impl.preparators import SanityChecker
from ..impl.selector import BinaryClassificationModelSelector
from ..readers import DataReaders
from ..workflow import OpWorkflow, OpWorkflowModel

TITANIC_SCHEMA = ["PassengerId", "Survived", "Pclass", "Name", "Sex", "Age",
                  "SibSp", "Parch", "Ticket", "Fare", "Cabin", "Embarked"]
DEFAULT_PATH = ("/root/reference/helloworld/src/main/resources/"
                "TitanicDataset/TitanicPassengersTrainData.csv")


def titanic_features() -> Tuple[Feature, Feature]:
    """(survived, featureVector) — the reference's feature definitions
    (OpTitanicSimple.scala: pClass/name/sex/age/sibSp/parCh/ticket/cabin/
    embarked + derived familySize/estimatedCostOfTickets/pivotedSex/ageGroup)."""
    survived = FeatureBuilder.RealNN("Survived").extract_field().as_response()
    p_class = FeatureBuilder.PickList("Pclass").extract(
        lambda r: None if r.get("Pclass") is None else str(r.get("Pclass"))
    ).as_predictor()
    name = FeatureBuilder.Text("Name").extract_field().as_predictor()
    sex = FeatureBuilder.PickList("Sex").extract_field().as_predictor()
    age = FeatureBuilder.Real("Age").extract_field().as_predictor()
    sib_sp = FeatureBuilder.Integral("SibSp").extract_field().as_predictor()
    par_ch = FeatureBuilder.Integral("Parch").extract_field().as_predictor()
    ticket = FeatureBuilder.PickList("Ticket").extract_field().as_predictor()
    fare = FeatureBuilder.Real("Fare").extract_field().as_predictor()
    cabin = FeatureBuilder.PickList("Cabin").extract_field().as_predictor()
    embarked = FeatureBuilder.PickList("Embarked").extract_field().as_predictor()

    # derived features (reference OpTitanicSimple.scala familySize etc.)
    from ..stages.base import BinaryTransformer
    from ..types import Real
    family_size = sib_sp.transform_with(
        BinaryTransformer("familySize",
                          lambda s, p: (s or 0) + (p or 0) + 1, Real), par_ch)
    estimated_cost = family_size.transform_with(
        BinaryTransformer("estCost",
                          lambda f, fare_v: (f or 0) * (fare_v or 0.0), Real), fare)

    feature_vector = transmogrify([
        p_class, name, sex, age, sib_sp, par_ch, ticket, fare, cabin, embarked,
        family_size, estimated_cost])
    return survived, feature_vector


def build_workflow(csv_path: str = DEFAULT_PATH,
                   seed: int = 42) -> Tuple[OpWorkflow, Feature, Feature]:
    survived, feature_vector = titanic_features()
    checked = survived.transform_with(SanityChecker(seed=seed), feature_vector)
    prediction = survived.transform_with(
        BinaryClassificationModelSelector.with_cross_validation(seed=seed), checked)
    reader = DataReaders.Simple.csv(csv_path, schema=TITANIC_SCHEMA, header=False,
                                    key_field="PassengerId")
    wf = (OpWorkflow()
          .set_reader(reader)
          .set_result_features(prediction, checked))
    return wf, survived, prediction


def run(csv_path: str = DEFAULT_PATH, seed: int = 42) -> OpWorkflowModel:
    wf, survived, prediction = build_workflow(csv_path, seed)
    model = wf.train()
    return model


if __name__ == "__main__":
    model = run()
    print(model.summary_pretty())
