"""Boston housing regression — helloworld parity example.

Mirrors the reference helloworld app (reference:
helloworld/src/main/scala/com/salesforce/hw/boston/OpBoston.scala): housing
numerics → transmogrify → RegressionModelSelector → train/score.
"""
from __future__ import annotations

from typing import Tuple

from ..features import Feature, FeatureBuilder
from ..impl.feature import transmogrify
from ..impl.selector import RegressionModelSelector
from ..workflow import OpWorkflow

BOSTON_SCHEMA = ["crim", "zn", "indus", "chas", "nox", "rm", "age", "dis",
                 "rad", "tax", "ptratio", "b", "lstat", "medv"]
DEFAULT_PATH = ("/root/reference/helloworld/src/main/resources/"
                "BostonDataset/housing.data")


def boston_features() -> Tuple[Feature, Feature]:
    """(medv label, featureVector) (reference OpBoston.scala definitions)."""
    label = FeatureBuilder.RealNN("medv").extract_field().as_response()
    preds = []
    for c in BOSTON_SCHEMA[:-1]:
        if c == "chas":
            preds.append(FeatureBuilder.Binary(c).extract(
                lambda r: bool(r.get("chas"))).as_predictor())
        else:
            preds.append(FeatureBuilder.Real(c).extract_field().as_predictor())
    return label, transmogrify(preds)


def build_workflow(path: str = DEFAULT_PATH, seed: int = 42):
    import pandas as pd
    df = pd.read_csv(path, header=None, names=BOSTON_SCHEMA, sep=r"\s+")
    label, vec = boston_features()
    pred = (RegressionModelSelector
            .with_train_validation_split(seed=seed)
            .set_input(label, vec).get_output())
    wf = OpWorkflow().set_input_dataset(df).set_result_features(pred)
    return wf, label, pred


def main(path: str = DEFAULT_PATH):
    wf, label, pred = build_workflow(path)
    model = wf.train()
    print(model.summary_pretty())
    return model


if __name__ == "__main__":
    main()
