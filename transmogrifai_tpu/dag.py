"""DAG scheduler: stage layering, layer-wise fit and transform.

Mirrors the reference scheduler (reference:
core/src/main/scala/com/salesforce/op/utils/stages/FitStagesUtil.scala):
``compute_dag`` groups stages into layers by max distance-to-result
(computeDAG:173-198); ``fit_and_transform_dag`` folds over layers fitting
estimators then applying transformers (fitAndTransformDAG:213-240).

Execution differences, by design: where the reference fuses all row lambdas of
a layer into a single RDD map (applyOpTransformations:96-119) and persists
every K Spark stages to sidestep Catalyst (applySparkTransformations:134-165),
here each transformer produces whole columns via jitted kernels — and the
transform-plan compiler (``plan.py``) goes one step further, tracing each
layer run's device-fusable stages into ONE jitted program so XLA fuses
*across* stage boundaries instead of dispatching N separate executables.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from .features import Feature
from .observability.trace import span as _obs_span
from .stages.base import Estimator, FeatureGeneratorStage, Transformer
from .table import FeatureTable

#: a DAG is a list of layers; each layer is a list of (stage, distance)
StageLayer = List[Tuple[Any, int]]


def compute_dag(result_features: Sequence[Feature]) -> List[StageLayer]:
    """Group all non-generator ancestor stages into layers by max distance to
    any result feature, farthest first (reference FitStagesUtil.computeDAG)."""
    dist: Dict[str, int] = {}
    stages: Dict[str, Any] = {}
    for f in result_features:
        for stage, d in f.parent_stages().items():
            if isinstance(stage, FeatureGeneratorStage):
                continue
            if stage.uid not in dist or d > dist[stage.uid]:
                dist[stage.uid] = d
                stages[stage.uid] = stage
    by_layer: Dict[int, StageLayer] = {}
    for uid, d in dist.items():
        by_layer.setdefault(d, []).append((stages[uid], d))
    return [sorted(by_layer[d], key=lambda sd: sd[0].uid)
            for d in sorted(by_layer, reverse=True)]


def validate_dag(result_features: Sequence[Feature]) -> None:
    """DAG sanity checks (reference OpWorkflow.validateStages:316): distinct
    stage uids, every feature produced by exactly one stage."""
    seen_stage: Dict[str, Any] = {}
    for f in result_features:
        for feat in f.all_features():
            st = feat.origin_stage
            if st is None:
                raise ValueError(f"feature '{feat.name}' has no origin stage")
            prev = seen_stage.get(st.uid)
            if prev is not None and prev is not st:
                raise ValueError(
                    f"duplicate stage uid '{st.uid}' for distinct stage instances")
            seen_stage[st.uid] = st


class _NullProfiler:
    def track(self, stage, op, layer=-1):
        import contextlib
        return contextlib.nullcontext()


_NULL_PROFILER = _NullProfiler()


def fit_and_transform_dag(table: FeatureTable, layers: List[StageLayer],
                          profiler: Optional[Any] = None,
                          checkpoint: Optional[Any] = None,
                          preloaded: Optional[Dict[str, Any]] = None,
                          retry_policy: Optional[Any] = None,
                          ) -> Tuple[FeatureTable, Dict[str, Any]]:
    """Fit estimators layer-by-layer, transforming as we go (reference
    FitStagesUtil.fitAndTransformDAG / fitAndTransformLayer).

    ``checkpoint(model)`` is invoked after each estimator fit and
    ``preloaded`` {uid → fitted model} skips refitting — together they give
    crash-resumable training (the analog of the reference's persist-every-K
    resilience, OpWorkflowModel.scala:449-455).

    ``retry_policy`` (a ``robustness.RetryPolicy``, wired by
    ``OpWorkflow.with_fault_policy``) re-runs a stage fit that fails with a
    TRANSIENT error — device-transfer hiccups on tunneled backends — the
    analog of the reference's ``spark.task.maxFailures``. Fatal errors
    (shape/trace bugs) are never retried: the fit is deterministic, so
    re-running the same program on the same inputs cannot change them.

    Returns (transformed table, {estimator uid → fitted model}).
    """
    from .robustness import faults
    from .robustness.policy import FaultLog, FaultReport
    prof = profiler or _NULL_PROFILER
    pre = preloaded or {}
    fitted: Dict[str, Any] = {}
    for li, layer in enumerate(layers):
        models: List[Transformer] = []
        for stage, _ in layer:
            if isinstance(stage, Estimator):
                if stage.uid in pre:
                    model = pre[stage.uid]
                    # re-wire onto this DAG's features (uids match)
                    model.input_features = stage.input_features
                    model._output_feature = stage.get_output()
                    # resume accounting: this stage's fit was skipped in
                    # favor of verified checkpoint state —
                    # summary()["resume"] reports restored vs refit
                    FaultLog.record(FaultReport(
                        site="dag.stage_fit", kind="restored",
                        detail={"uid": stage.uid,
                                "stage": type(stage).__name__}))
                else:
                    def _fit(stage=stage, li=li):
                        # deterministic preemption point: the process dies
                        # mid-DAG with earlier stages already checkpointed
                        faults.inject("preempt.stage_fit", key=stage.uid)
                        faults.inject("dag.stage_fit", key=stage.uid)
                        with _obs_span("stage.fit", cat="train",
                                       uid=stage.uid,
                                       stage=type(stage).__name__,
                                       layer=li), \
                                prof.track(stage, "fit", li):
                            return stage.fit(table)
                    if retry_policy is not None:
                        model = retry_policy.execute(
                            _fit, site=f"dag.stage_fit[{stage.uid}]")
                    else:
                        model = _fit()
                    if checkpoint is not None:
                        checkpoint(model)
                fitted[stage.uid] = model
                models.append(model)
            elif isinstance(stage, Transformer):
                models.append(stage)
            else:
                raise TypeError(f"unexpected stage kind {type(stage).__name__}")
        table = _transform_stages(table, models, cat="train", layer=li,
                                  profiler=profiler,
                                  retry_policy=retry_policy)
    return table, fitted


def _transform_stages(table: FeatureTable, models: Sequence[Any], *,
                      cat: str, layer: int = -1,
                      profiler: Optional[Any] = None,
                      retry_policy: Optional[Any] = None) -> FeatureTable:
    """Run a topologically-ordered transformer sequence: as a compiled plan
    (one XLA program per device-fusable segment, ``plan.apply_planned``)
    when eligible, else eagerly stage by stage.

    Eager runs whenever per-stage semantics matter: a profiler wants
    per-stage wall-clock, a retry policy wants per-stage fault isolation
    (PR 1), or chaos is active (``plan.planning_applicable``). A planned
    run that raises falls back to eager for the run — recorded, never
    silent — so results are identical either way."""
    from . import plan as _plan
    if profiler is None and retry_policy is None and len(models) > 1:
        # ≥2 fusable stages: a lone-stage run gains nothing over eager
        # dispatch but would still pay the plan's probe/compile cost
        out = _plan.apply_planned(models, table, keep_intermediates=True,
                                  cat=cat, min_device_stages=2)
        if out is not None:
            return out
    prof = profiler or _NULL_PROFILER
    for model in models:
        _plan.count_eager_dispatch(model)
        with _obs_span("stage.transform", cat=cat,
                       uid=getattr(model, "uid", "?"),
                       stage=type(model).__name__, layer=layer), \
                prof.track(model, "transform", layer):
            table = model.transform(table)
    return table


def apply_transformations_dag(table: FeatureTable, layers: List[StageLayer],
                              profiler: Optional[Any] = None,
                              ) -> FeatureTable:
    """Score-time pass: all stages must already be transformers (reference
    OpWorkflowCore.applyTransformationsDAG:321-345). The flattened
    farthest-first layer order is topological, so the whole pass plans as
    one sequence — bigger fusable segments than the per-layer train runs."""
    for layer in layers:
        for stage, _ in layer:
            if isinstance(stage, Estimator):
                raise ValueError(
                    f"stage {stage.uid} is an unfitted estimator; "
                    "score requires a fitted workflow model")
    flat = [stage for layer in layers for stage, _ in layer]
    return _transform_stages(table, flat, cat="score", profiler=profiler)
