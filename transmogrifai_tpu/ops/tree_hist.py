"""Compatibility shim: the histogram contraction kernels moved to the
histogram-engine subsystem (``transmogrifai_tpu.histeng.kernels``) when the
engine unified the in-core, streaming, and mesh histogram paths (ISSUE 18,
docs/trees.md). Import from ``transmogrifai_tpu.histeng`` in new code; this
module re-exports the full kernel surface so existing importers
(ops/forest.py helpers, tests, docs/experiments measurement records) keep
working unchanged."""
from ..histeng.kernels import (  # noqa: F401
    _BLK_B, _BLK_S, _HIST_PALLAS_MAX_B, _hist_pallas, _hist_shards,
    _hist_xla, _hist_xla_pinned, _interpret, _make, _node_hist_xla, _pad_to,
    _t_pad128, _tile_lanes, _tree_combine, _use_pallas, hist_matmul,
    node_hist_matmul,
)

__all__ = ["hist_matmul", "node_hist_matmul"]
