"""Jitted statistics kernels over masked columnar data.

The TPU replacements for Spark MLlib's distributed statistics
(reference: mllib.stat.Statistics.colStats/corr used by SanityChecker.scala:574-638,
utils/.../stats/OpStatistics.scala): one pass of fused XLA reductions instead of
``treeAggregate`` over RDD partitions. All kernels take an explicit validity
mask so null semantics match the reference's Option-valued columns, and all are
``shard_map``-friendly: they reduce over the row axis only, so under a mesh the
row-sharded version just wraps them in psum (see transmogrifai_tpu.parallel).
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.jax_cache import ensure_compilation_cache

ensure_compilation_cache()
import numpy as np


class ColStats(NamedTuple):
    """Per-column moments (analog of mllib MultivariateStatisticalSummary)."""
    count: jnp.ndarray      # valid count per column
    mean: jnp.ndarray
    variance: jnp.ndarray   # unbiased (n-1), matching Spark colStats
    min: jnp.ndarray
    max: jnp.ndarray
    num_nonzeros: jnp.ndarray


@jax.jit
def col_stats(x: jnp.ndarray, mask: Optional[jnp.ndarray] = None) -> ColStats:
    """Masked per-column stats of an (n, d) matrix in one fused pass."""
    n, d = x.shape
    if mask is None:
        mask = jnp.ones((n,), dtype=bool)
    m = mask[:, None].astype(x.dtype) if mask.ndim == 1 else mask.astype(x.dtype)
    cnt = m.sum(axis=0)
    safe_cnt = jnp.maximum(cnt, 1.0)
    xm = x * m
    mean = xm.sum(axis=0) / safe_cnt
    sq = (x - mean[None, :]) ** 2 * m
    var = sq.sum(axis=0) / jnp.maximum(cnt - 1.0, 1.0)
    big = jnp.asarray(jnp.finfo(x.dtype).max, x.dtype)
    mn = jnp.where(m > 0, x, big).min(axis=0)
    mx = jnp.where(m > 0, x, -big).max(axis=0)
    nz = ((xm != 0) & (m > 0)).sum(axis=0)
    return ColStats(cnt, mean, var,
                    jnp.where(cnt > 0, mn, 0.0), jnp.where(cnt > 0, mx, 0.0),
                    nz)


@jax.jit
def pearson_correlation(x: jnp.ndarray, y: jnp.ndarray,
                        mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Masked Pearson correlation of each column of (n, d) x against y (n,).

    Analog of ``Statistics.corr(labelAndSample)`` label-column mode used by
    SanityChecker.scala:634-638. NaN where a column is constant (matching
    Spark's NaN correlation for zero variance).
    """
    n, d = x.shape
    if mask is None:
        mask = jnp.ones((n,), dtype=bool)
    m = mask.astype(x.dtype)
    cnt = jnp.maximum(m.sum(), 1.0)
    ym = y * m
    y_mean = ym.sum() / cnt
    yc = (y - y_mean) * m
    x_mean = (x * m[:, None]).sum(axis=0) / cnt
    xc = (x - x_mean[None, :]) * m[:, None]
    cov = (xc * yc[:, None]).sum(axis=0)
    xvar = (xc ** 2).sum(axis=0)
    yvar = (yc ** 2).sum()
    denom = jnp.sqrt(xvar * yvar)
    return jnp.where(denom > 0, cov / jnp.maximum(denom, 1e-30), jnp.nan)


@jax.jit
def pearson_correlation_matrix(x: jnp.ndarray,
                               mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Full (d, d) correlation matrix (SanityChecker correlationType full mode)."""
    n, d = x.shape
    if mask is None:
        mask = jnp.ones((n,), dtype=bool)
    m = mask.astype(x.dtype)
    cnt = jnp.maximum(m.sum(), 1.0)
    mean = (x * m[:, None]).sum(axis=0) / cnt
    xc = (x - mean[None, :]) * m[:, None]
    cov = xc.T @ xc                      # MXU matmul
    std = jnp.sqrt(jnp.diag(cov))
    denom = std[:, None] * std[None, :]
    return jnp.where(denom > 0, cov / jnp.maximum(denom, 1e-30), jnp.nan)


def _rank(v: jnp.ndarray) -> jnp.ndarray:
    """Average-tie ranks, jit-friendly (for Spearman)."""
    n = v.shape[0]
    order = jnp.argsort(v)
    sorted_v = v[order]
    ranks_ord = jnp.arange(1, n + 1, dtype=v.dtype)
    # average ranks over ties: segment by value
    is_new = jnp.concatenate([jnp.array([True]), sorted_v[1:] != sorted_v[:-1]])
    seg = jnp.cumsum(is_new) - 1
    seg_sum = jax.ops.segment_sum(ranks_ord, seg, num_segments=n)
    seg_cnt = jax.ops.segment_sum(jnp.ones_like(ranks_ord), seg, num_segments=n)
    avg = seg_sum / jnp.maximum(seg_cnt, 1.0)
    ranks_sorted = avg[seg]
    return jnp.zeros_like(ranks_sorted).at[order].set(ranks_sorted)


@jax.jit
def spearman_correlation(x: jnp.ndarray, y: jnp.ndarray,
                         mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Masked Spearman correlation per column: Pearson over ranks.

    Invalid rows are ranked but excluded from the correlation via the mask
    (rank distortion from masked rows is bounded and matches sampling noise;
    exact masked ranking would need per-column sorts of varying length, which
    breaks static shapes)."""
    ranks_x = jax.vmap(_rank, in_axes=1, out_axes=1)(x)
    rank_y = _rank(y)
    return pearson_correlation(ranks_x, rank_y, mask)


class ContingencyStats(NamedTuple):
    """Per-categorical-group association stats (reference
    OpStatistics.contingencyStats:300 — chi², Cramér's V, PMI, mutual info,
    max rule confidence/support)."""
    chi2: jnp.ndarray
    cramers_v: jnp.ndarray
    mutual_info: jnp.ndarray
    pointwise_mutual_info: jnp.ndarray   # (k, L) PMI per cell
    max_rule_confidence: jnp.ndarray     # max over labels of P(label|feature value)
    support: jnp.ndarray                 # P(feature value)


@partial(jax.jit, static_argnames=("num_labels",))
def contingency_table(indicators: jnp.ndarray, label_idx: jnp.ndarray,
                      num_labels: int, mask: Optional[jnp.ndarray] = None
                      ) -> jnp.ndarray:
    """(k, L) contingency counts from (n, k) 0/1 indicator columns and integer
    labels — the SanityChecker ``reduceByKey`` replacement
    (SanityChecker.scala:433-440): one one-hot matmul on the MXU."""
    n, k = indicators.shape
    if mask is None:
        mask = jnp.ones((n,), dtype=bool)
    label_onehot = jax.nn.one_hot(label_idx, num_labels, dtype=indicators.dtype)
    label_onehot = label_onehot * mask[:, None].astype(indicators.dtype)
    return indicators.T @ label_onehot


@partial(jax.jit, static_argnames=("total_is_rows",))
def contingency_stats(table: jnp.ndarray, total_is_rows: bool = True
                      ) -> ContingencyStats:
    """Association statistics from a (k, L) contingency table (reference
    OpStatistics.contingencyStats:300)."""
    t = table.astype(jnp.float64) if jax.config.jax_enable_x64 else table.astype(jnp.float32)
    n = jnp.maximum(t.sum(), 1.0)
    row = t.sum(axis=1)            # per feature-value counts
    col = t.sum(axis=0)            # per label counts
    expected = row[:, None] * col[None, :] / n
    chi2 = jnp.where(expected > 0, (t - expected) ** 2 / jnp.maximum(expected, 1e-30), 0.0).sum()
    k = (row > 0).sum()
    l = (col > 0).sum()
    min_dim = jnp.maximum(jnp.minimum(k, l) - 1, 1)
    cramers_v = jnp.sqrt(chi2 / (n * min_dim))
    p = t / n
    p_row = row / n
    p_col = col / n
    denom = p_row[:, None] * p_col[None, :]
    pmi = jnp.where((p > 0) & (denom > 0),
                    jnp.log2(jnp.maximum(p, 1e-30) / jnp.maximum(denom, 1e-30)), 0.0)
    mi = (p * pmi).sum()
    conf = jnp.where(row[:, None] > 0, t / jnp.maximum(row[:, None], 1e-30), 0.0)
    max_conf = conf.max(axis=1)
    support = row / n
    return ContingencyStats(chi2, cramers_v, mi, pmi, max_conf, support)
