"""Pallas TPU kernel: fused multi-level forest descent.

The full-data passes of tree fitting and scoring (models/trees.py) both do

    node[s, t]  =  leaf reached by row s in tree t          (descent)
    then either Σ_s aug[s, k]·1[node==l]                    (exact leaf stats)
    or          Σ_t leaf[t, node[s,t], k]                   (prediction)

Done per level in XLA this materializes (n, T·m) decision matrices and
(n, T·L) leaf one-hots in HBM — at 1M rows × 50 trees that is gigabytes per
config and was ~97% of the RandomForest sweep's wall clock (356 ms per
config; the whole default RF grid 12.8 s). This kernel performs the whole
descent for a row block in VMEM:

- per level, the split feature's bin code is *gathered by matmul*: a (d, T·m)
  one-hot of the level's split features against the row block's codes —
  gathers are scatters' evil twin on TPU, but a gather whose index set is
  shared by every row IS a matmul, and matmuls are what the MXU is for;
- the go-right bit is one f32 compare against the level's bin thresholds
  (sentinel bin = n_bins ⇒ always left, which also makes padded trees and
  stopped nodes route to leaf 0 with zero extra logic);
- the per-row node is selected from the (T·m) candidate bits by an equality
  mask against a lane iota and a tiny (T·m, T) group-sum matmul;
- the leaf one-hot for the final reduction never leaves VMEM: leaf sums are
  accumulated into a (k, T·L) f32 block across the row grid; predictions are
  a (R, T·L)×(T·L, k) matmul against the leaf-value table.

HBM traffic per config drops to: read codes once (n·d int32), write either
(T, L, k) sums or (n, k) predictions. No (n, T·m) intermediate exists.

Replaces the reference's per-executor SparkML `Node.predictImpl` recursion
and the XGBoost JNI predictor (reference: SURVEY §2.9) with a TPU-native
kernel. Layout notes: lanes are j-major — lane = j·T_pad + t — because
`_tile_lanes` (Mosaic RepeatOp on TPU) tiles whole vectors along lanes, so
repeating the (R, T_pad)
node vector m times lines tree t up with every candidate j at lane j·T_pad+t.

Fallback: non-TPU backends (CPU test mesh, dry runs) and shapes outside the
VMEM envelope (depth > 7 or > 128 trees) run the same math as XLA einsums.
Dispatch reads the backend at trace time (see tree_hist.py note).
"""
from __future__ import annotations

import math
from functools import lru_cache, partial

import jax
import jax.numpy as jnp

from .tree_hist import _interpret, _pad_to, _tile_lanes, _use_pallas

import os as _os

_BLK_R = int(_os.environ.get("TG_FOREST_BLK_R", "128"))  # rows per VMEM block
_MAX_DEPTH_PALLAS = 7  # beyond this the (R, T·m) block outgrows VMEM
_MAX_TREES_PALLAS = 128


def _t_pad(T: int, depth: int) -> int:
    """Tree-axis padding: a multiple of 64 keeps every RAGGED level's lane
    width (T_pad × even node count) a 128-multiple AND an exact multiple of
    T_pad, so `_tile_lanes(node, m_eff)` lands each tree at lane
    j·T_pad + t without any in-kernel pad."""
    return max(64, _pad_to(T, 64))


def _m_eff(level: int) -> int:
    """Per-level node-lane count: the natural 2^level, floored at 2 so the
    lane width stays a 128-multiple (T_pad is a multiple of 64)."""
    return max(2, 2 ** level)


def _level_tables(feat_heap: jnp.ndarray, bin_heap: jnp.ndarray, depth: int,
                  n_bins: int, T_pad: int):
    """j-major RAGGED per-level split tables, concatenated flat.

    Level ``l`` occupies ``T_pad·_m_eff(l)`` lanes (lane = j·T_pad + t) —
    ~3x fewer total lanes than padding every level to the deepest width.
    Sentinel bins fill every padded slot (tree, level-width, stopped node).
    Returns ((1, Σw) f_flat, (1, Σw) b_flat)."""
    T = feat_heap.shape[0]
    f_rows, b_rows = [], []
    for level in range(depth):
        base, m = 2 ** level - 1, 2 ** level
        m_eff = _m_eff(level)
        f = jnp.pad(feat_heap[:, base:base + m],
                    ((0, T_pad - T), (0, m_eff - m)))
        b = jnp.pad(bin_heap[:, base:base + m],
                    ((0, T_pad - T), (0, m_eff - m)),
                    constant_values=n_bins)
        # (T_pad, m_eff) -> j-major flat: lane j*T_pad + t
        f_rows.append(f.T.reshape(-1))
        b_rows.append(b.T.reshape(-1))
    return jnp.concatenate(f_rows)[None, :].astype(jnp.int32), \
        jnp.concatenate(b_rows)[None, :].astype(jnp.int32)


def _descend(codes_f, f_flat_ref, b_flat_ref, *, depth, T_pad, d_pad):
    """In-kernel: (R, d_pad) f32 codes → (R, T_pad) int32 leaf ids.

    Ragged levels: level l reads its own T_pad·_m_eff(l)-lane slice of the
    flat split tables, so early levels do 1/m_max-th the deepest level's
    VPU/MXU work instead of padding up to it."""
    R = codes_f.shape[0]
    codes_bf = codes_f.astype(jnp.bfloat16)
    node = jnp.zeros((R, T_pad), jnp.int32)
    off = 0
    for level in range(depth):
        m_eff = _m_eff(level)
        w = T_pad * m_eff
        f_row = f_flat_ref[0:1, off:off + w]                  # (1, w)
        b_row = b_flat_ref[0:1, off:off + w]
        off += w
        d_iota = jax.lax.broadcasted_iota(jnp.int32, (d_pad, w), 0)
        sel = (d_iota == f_row).astype(jnp.bfloat16)          # (d_pad, w)
        code_sel = jnp.dot(codes_bf, sel,
                           preferred_element_type=jnp.float32)  # (R, w)
        go_lane = (code_sel > b_row.astype(jnp.float32)
                   ).astype(jnp.bfloat16)
        node_rep = _tile_lanes(node, m_eff)                   # (R, w)
        lane = jax.lax.broadcasted_iota(jnp.int32, (R, w), 1)
        oh = (node_rep == lane // T_pad).astype(jnp.bfloat16)
        gl = jax.lax.broadcasted_iota(jnp.int32, (w, T_pad), 0) % T_pad
        gt = jax.lax.broadcasted_iota(jnp.int32, (w, T_pad), 1)
        G = (gl == gt).astype(jnp.bfloat16)                   # (w, T_pad)
        go = jnp.dot(go_lane * oh, G,
                     preferred_element_type=jnp.float32)      # (R, T_pad)
        node = 2 * node + (go > 0.5).astype(jnp.int32)
    return node


def _leaf_onehot(node, *, depth, T_pad):
    """(R, T_pad) leaf ids → (R, T_pad·L) bf16 one-hot, lane = leaf·T_pad+t."""
    R = node.shape[0]
    L = 2 ** depth
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, T_pad * L), 1)
    node_rep = _tile_lanes(node, L)
    return (node_rep == lane // T_pad).astype(jnp.bfloat16)


def _leaf_sums_pallas(codes, f_lvls, b_lvls, aug, *, depth, n_bins, T_pad):
    from jax.experimental import pallas as pl

    n, d = codes.shape
    k = aug.shape[1]
    d_pad = _pad_to(d, 128)
    k_pad = _pad_to(k, 8)
    L = 2 ** depth
    blk_r = _BLK_R
    n_pad = _pad_to(n, blk_r)
    codes_p = jnp.pad(codes.astype(jnp.int32),
                      ((0, n_pad - n), (0, d_pad - d)))
    aug_p = jnp.pad(aug.astype(jnp.float32),
                    ((0, n_pad - n), (0, k_pad - k)))  # zero rows: no-op

    def kernel(codes_ref, f_ref, b_ref, aug_ref, out_ref):
        r = pl.program_id(0)
        node = _descend(codes_ref[:].astype(jnp.float32), f_ref, b_ref,
                        depth=depth, T_pad=T_pad, d_pad=d_pad)
        l_oh = _leaf_onehot(node, depth=depth, T_pad=T_pad)
        # (k, T_pad·L): lanes wide, accumulator small. precision=HIGHEST:
        # default matmul precision truncates f32 operands to bf16 — exact for
        # the 0/1 one-hot, NOT for the stat values (leaf stats serve
        # predictions and must not round)
        part = jax.lax.dot_general(
            aug_ref[:], l_oh.astype(jnp.float32),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

        @pl.when(r == 0)
        def _():
            out_ref[:] = part

        @pl.when(r > 0)
        def _():
            out_ref[:] += part

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((k_pad, T_pad * L), jnp.float32),
        grid=(n_pad // blk_r,),
        in_specs=[
            pl.BlockSpec((blk_r, d_pad), lambda r: (r, 0)),
            pl.BlockSpec(f_lvls.shape, lambda r: (0, 0)),
            pl.BlockSpec(b_lvls.shape, lambda r: (0, 0)),
            pl.BlockSpec((blk_r, k_pad), lambda r: (r, 0)),
        ],
        out_specs=pl.BlockSpec((k_pad, T_pad * L), lambda r: (0, 0)),
        interpret=_interpret(),
    )(codes_p, f_lvls, b_lvls, aug_p)
    # (k, leaf·T_pad+t) -> (T_pad, L, k)
    return out.reshape(k_pad, L, T_pad).transpose(2, 1, 0)[:, :, :k]


def _predict_pallas(codes, f_lvls, b_lvls, leaf_flat, *, depth, n_bins,
                    T_pad):
    from jax.experimental import pallas as pl

    n, d = codes.shape
    k = leaf_flat.shape[1]
    d_pad = _pad_to(d, 128)
    k_pad = _pad_to(k, 128)
    L = 2 ** depth
    blk_r = _BLK_R
    n_pad = _pad_to(n, blk_r)
    codes_p = jnp.pad(codes.astype(jnp.int32),
                      ((0, n_pad - n), (0, d_pad - d)))
    leaf_p = jnp.pad(leaf_flat.astype(jnp.float32),
                     ((0, 0), (0, k_pad - k)))

    def kernel(codes_ref, f_ref, b_ref, leaf_ref, out_ref):
        node = _descend(codes_ref[:].astype(jnp.float32), f_ref, b_ref,
                        depth=depth, T_pad=T_pad, d_pad=d_pad)
        l_oh = _leaf_onehot(node, depth=depth, T_pad=T_pad)
        out_ref[:] = jnp.dot(l_oh.astype(jnp.float32), leaf_ref[:],
                             preferred_element_type=jnp.float32,
                             precision=jax.lax.Precision.HIGHEST)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, k_pad), jnp.float32),
        grid=(n_pad // blk_r,),
        in_specs=[
            pl.BlockSpec((blk_r, d_pad), lambda r: (r, 0)),
            pl.BlockSpec(f_lvls.shape, lambda r: (0, 0)),
            pl.BlockSpec(b_lvls.shape, lambda r: (0, 0)),
            pl.BlockSpec(leaf_flat.shape[:1] + (k_pad,), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((blk_r, k_pad), lambda r: (r, 0)),
        interpret=_interpret(),
    )(codes_p, f_lvls, b_lvls, leaf_p)
    return out[:n, :k]


# ---------------------------------------------------------------------------
# XLA fallback: identical math, per-level feature-select matmuls
# ---------------------------------------------------------------------------

def route_codes_xla(codes: jnp.ndarray, feat_heap: jnp.ndarray,
                    bin_heap: jnp.ndarray, depth: int,
                    n_bins: int) -> jnp.ndarray:
    """(n, T) leaf assignments via per-level feature-select matmuls.

    The gather codes[s, feat] is a matmul against the (d, T·m) split-feature
    one-hot — even in XLA this replaces the old (d·n_bins)-wide comparison
    contraction (route_matmul) at 1/n_bins-th the FLOPs."""
    n, d = codes.shape
    T = feat_heap.shape[0]
    codes_f = codes.astype(jnp.bfloat16)
    node = jnp.zeros((n, T), jnp.int32)
    for level in range(depth):
        base, m = 2 ** level - 1, 2 ** level
        f_lvl = feat_heap[:, base:base + m]                  # (T, m)
        b_lvl = bin_heap[:, base:base + m]
        sel = (f_lvl.reshape(-1)[None, :]
               == jnp.arange(d, dtype=jnp.int32)[:, None]
               ).astype(jnp.bfloat16)                        # (d, T·m)
        code_sel = (codes_f @ sel).reshape(n, T, m)
        go_all = code_sel > b_lvl[None].astype(jnp.bfloat16)
        n_oh = node[:, :, None] == jnp.arange(m, dtype=jnp.int32)
        go = jnp.any(go_all & n_oh, axis=2)
        node = 2 * node + go.astype(jnp.int32)
    return node


def _leaf_sums_xla(codes, feat_heap, bin_heap, aug, *, depth, n_bins):
    n = codes.shape[0]
    T = feat_heap.shape[0]
    L = 2 ** depth
    node = route_codes_xla(codes, feat_heap, bin_heap, depth, n_bins)
    comb = node + (jnp.arange(T, dtype=jnp.int32) * L)[None, :]
    l_oh = (comb[:, :, None]
            == jnp.arange(T * L, dtype=jnp.int32).reshape(1, T, L)
            ).astype(jnp.float32).reshape(n, T * L)
    out = jnp.einsum("na,nk->ak", l_oh, aug.astype(jnp.float32),
                     preferred_element_type=jnp.float32,
                     precision=jax.lax.Precision.HIGHEST)
    return out.reshape(T, L, -1)


def _predict_xla(codes, feat_heap, bin_heap, leaf, *, depth, n_bins):
    n = codes.shape[0]
    T, L, k = leaf.shape
    node = route_codes_xla(codes, feat_heap, bin_heap, depth, n_bins)
    comb = node + (jnp.arange(T, dtype=jnp.int32) * L)[None, :]
    l_oh = (comb[:, :, None]
            == jnp.arange(T * L, dtype=jnp.int32).reshape(1, T, L)
            ).astype(jnp.float32).reshape(n, T * L)
    return jnp.einsum("na,ak->nk", l_oh, leaf.reshape(T * L, k),
                      preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST)


# ---------------------------------------------------------------------------
# Public entry points
# ---------------------------------------------------------------------------

def _pallas_ok(depth: int, T: int) -> bool:
    return (_use_pallas() and depth <= _MAX_DEPTH_PALLAS
            and T <= _MAX_TREES_PALLAS)


def _check_bins(n_bins: int) -> None:
    """Descent casts int32 bin codes to bf16, which represents integers
    exactly only up to 256 — larger bin codes would silently misroute."""
    if n_bins > 256:
        raise ValueError(
            f"n_bins={n_bins} > 256: bin codes are routed in bfloat16, "
            f"which is exact only for codes <= 256")


def forest_leaf_sums(codes: jnp.ndarray, feat_heap: jnp.ndarray,
                     bin_heap: jnp.ndarray, aug: jnp.ndarray, *,
                     depth: int, n_bins: int) -> jnp.ndarray:
    """Exact leaf statistics for a forest in one fused pass.

    codes: (n, d) int32 bin codes; feat_heap/bin_heap: (T, 2^depth−1)
    complete-heap splits (sentinel bin ≥ n_bins ⇒ route left);
    aug: (n, k) f32 per-row stats (pad rows with zeros — they add nothing).
    Returns (T, L, k) f32 with L = 2^depth: sums of aug over rows landing in
    each (tree, leaf).
    """
    _check_bins(n_bins)
    T = feat_heap.shape[0]
    if not _pallas_ok(depth, T):
        return _leaf_sums_xla(codes, feat_heap, bin_heap, aug,
                              depth=depth, n_bins=n_bins)
    T_pad = _t_pad(T, depth)
    fh = jnp.pad(feat_heap, ((0, T_pad - T), (0, 0)))
    bh = jnp.pad(bin_heap, ((0, T_pad - T), (0, 0)),
                 constant_values=n_bins)
    f_lvls, b_lvls = _level_tables(fh, bh, depth, n_bins, T_pad)
    out = _leaf_sums_pallas(codes, f_lvls, b_lvls, aug,
                            depth=depth, n_bins=n_bins, T_pad=T_pad)
    return out[:T]


# ---------------------------------------------------------------------------
# Slot-chain ("leaf budget") trees: arbitrary depth at bounded width
#
# A complete heap doubles its level width every level (2^l nodes), which caps
# the practical depth at ~7: the descent's per-level lane width T_pad·2^l and
# the final (R, T_pad·2^depth) leaf one-hot outgrow VMEM, and the grower's
# histograms outgrow HBM. The reference's default grids include maxDepth 12
# (DefaultSelectorParams.scala:37), so deep trees get a second representation:
# per-level SLOT tables of static width W (the leaf budget — every split adds
# exactly one net slot, so a W-slot chain holds any tree with ≤ W leaves,
# grown level-wise with the best-gain splits kept, the XGBoost 'lossguide' /
# LightGBM num_leaves design point). Routing is
#
#     slot' = base[slot] + go,   go = codes[:, feat[slot]] > bin[slot]
#
# where a split slot's base points at its child pair, a finished leaf's base
# carries it forward unchanged (sentinel bin ⇒ go 0), and the slot after the
# last level IS the leaf id in [0, W). Every per-level operand is ≤ T_pad·W
# lanes regardless of depth, so depth 12 runs in the same VMEM envelope as a
# depth-5 heap. Shallow complete heaps embed exactly (base = 2·slot), letting
# mixed-depth grids share one predict program.
# ---------------------------------------------------------------------------

_BLK_R_CHAIN = 64     # rows per VMEM block (deep levels are lane-wide)
_T_CHAIN = 32         # trees per chain kernel call (lane budget)
_MAX_SLOTS = 256      # bin codes AND slot ids ride bf16 lanes: exact ≤ 256


def _chain_widths(depth: int, W: int):
    """Ragged per-level slot widths: level l holds ≤ min(2^l, W) live slots
    (a level can at most double the previous one's count, capped at W)."""
    return [min(2 ** level, W) for level in range(depth)]


def _chain_w_eff(Wl: int) -> int:
    """Kernel lane width per level: floored at 4 so T_pad·W_eff stays a
    128-multiple (T_pad is a multiple of 32)."""
    return max(4, Wl)


def _check_slots(W: int) -> None:
    if W > _MAX_SLOTS:
        raise ValueError(
            f"n_slots={W} > {_MAX_SLOTS}: slot ids are accumulated in "
            f"bfloat16 lanes, exact only up to 256")


def _chain_tables(feat_lv, bin_lv, base_lv, depth, W, n_bins, T_pad):
    """j-major ragged per-level tables, concatenated flat: level l occupies
    T_pad·_chain_w_eff(W_l) lanes (lane = slot·T_pad + t). Sentinel bins fill
    padded slots/trees; padded bases are 0 (no rows ever sit there)."""
    T = feat_lv.shape[0]
    f_rows, b_rows, a_rows = [], [], []
    for level, Wl in enumerate(_chain_widths(depth, W)):
        We = _chain_w_eff(Wl)
        f = jnp.pad(feat_lv[:, level, :Wl],
                    ((0, T_pad - T), (0, We - Wl)))
        b = jnp.pad(bin_lv[:, level, :Wl],
                    ((0, T_pad - T), (0, We - Wl)), constant_values=n_bins)
        a = jnp.pad(base_lv[:, level, :Wl],
                    ((0, T_pad - T), (0, We - Wl)))
        f_rows.append(f.T.reshape(-1))
        b_rows.append(b.T.reshape(-1))
        a_rows.append(a.T.reshape(-1))
    return (jnp.concatenate(f_rows)[None, :].astype(jnp.int32),
            jnp.concatenate(b_rows)[None, :].astype(jnp.int32),
            jnp.concatenate(a_rows)[None, :].astype(jnp.int32))


def _descend_chain(codes_f, f_ref, b_ref, a_ref, *, depth, W, T_pad, d_pad):
    """In-kernel: (R, d_pad) f32 codes → (R, T_pad) int32 leaf slots.

    Same matmul skeleton as `_descend`, plus the base-pointer gather: the
    next slot is Σ_j oh[j]·(base[j] + go[j]) — one fused group-sum matmul
    (base values < 256 are exact in the bf16 operand, accumulated f32)."""
    R = codes_f.shape[0]
    codes_bf = codes_f.astype(jnp.bfloat16)
    slot = jnp.zeros((R, T_pad), jnp.int32)
    off = 0
    for level, Wl in enumerate(_chain_widths(depth, W)):
        We = _chain_w_eff(Wl)
        w = T_pad * We
        f_row = f_ref[0:1, off:off + w]                       # (1, w)
        b_row = b_ref[0:1, off:off + w]
        a_row = a_ref[0:1, off:off + w]
        off += w
        d_iota = jax.lax.broadcasted_iota(jnp.int32, (d_pad, w), 0)
        sel = (d_iota == f_row).astype(jnp.bfloat16)          # (d_pad, w)
        code_sel = jnp.dot(codes_bf, sel,
                           preferred_element_type=jnp.float32)  # (R, w)
        go_lane = (code_sel > b_row.astype(jnp.float32)
                   ).astype(jnp.bfloat16)
        slot_rep = _tile_lanes(slot, We)                      # (R, w)
        lane = jax.lax.broadcasted_iota(jnp.int32, (R, w), 1)
        oh = (slot_rep == lane // T_pad).astype(jnp.bfloat16)
        val = (go_lane + a_row.astype(jnp.bfloat16)) * oh     # (R, w)
        gl = jax.lax.broadcasted_iota(jnp.int32, (w, T_pad), 0) % T_pad
        gt = jax.lax.broadcasted_iota(jnp.int32, (w, T_pad), 1)
        G = (gl == gt).astype(jnp.bfloat16)                   # (w, T_pad)
        nxt = jnp.dot(val, G, preferred_element_type=jnp.float32)
        slot = nxt.astype(jnp.int32)
    return slot


def _leaf_onehot_chain(slot, *, W_out, T_pad):
    R = slot.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, T_pad * W_out), 1)
    slot_rep = _tile_lanes(slot, W_out)
    return (slot_rep == lane // T_pad).astype(jnp.bfloat16)


def _leaf_sums_chain_pallas(codes, f_lvls, b_lvls, a_lvls, aug, *, depth, W,
                            W_out, n_bins, T_pad):
    from jax.experimental import pallas as pl

    n, d = codes.shape
    k = aug.shape[1]
    d_pad = _pad_to(d, 128)
    k_pad = _pad_to(k, 8)
    blk_r = _BLK_R_CHAIN
    n_pad = _pad_to(n, blk_r)
    codes_p = jnp.pad(codes.astype(jnp.int32),
                      ((0, n_pad - n), (0, d_pad - d)))
    aug_p = jnp.pad(aug.astype(jnp.float32),
                    ((0, n_pad - n), (0, k_pad - k)))

    def kernel(codes_ref, f_ref, b_ref, a_ref, aug_ref, out_ref):
        r = pl.program_id(0)
        slot = _descend_chain(codes_ref[:].astype(jnp.float32), f_ref, b_ref,
                              a_ref, depth=depth, W=W, T_pad=T_pad,
                              d_pad=d_pad)
        l_oh = _leaf_onehot_chain(slot, W_out=W_out, T_pad=T_pad)
        part = jax.lax.dot_general(
            aug_ref[:], l_oh.astype(jnp.float32),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

        @pl.when(r == 0)
        def _():
            out_ref[:] = part

        @pl.when(r > 0)
        def _():
            out_ref[:] += part

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((k_pad, T_pad * W_out), jnp.float32),
        grid=(n_pad // blk_r,),
        in_specs=[
            pl.BlockSpec((blk_r, d_pad), lambda r: (r, 0)),
            pl.BlockSpec(f_lvls.shape, lambda r: (0, 0)),
            pl.BlockSpec(b_lvls.shape, lambda r: (0, 0)),
            pl.BlockSpec(a_lvls.shape, lambda r: (0, 0)),
            pl.BlockSpec((blk_r, k_pad), lambda r: (r, 0)),
        ],
        out_specs=pl.BlockSpec((k_pad, T_pad * W_out), lambda r: (0, 0)),
        interpret=_interpret(),
    )(codes_p, f_lvls, b_lvls, a_lvls, aug_p)
    # (k, slot·T_pad + t) -> (T_pad, W_out, k)
    return out.reshape(k_pad, W_out, T_pad).transpose(2, 1, 0)[:, :, :k]


def _predict_chain_pallas(codes, f_lvls, b_lvls, a_lvls, leaf_flat, *,
                          depth, W, W_out, n_bins, T_pad):
    from jax.experimental import pallas as pl

    n, d = codes.shape
    k = leaf_flat.shape[1]
    d_pad = _pad_to(d, 128)
    k_pad = _pad_to(k, 128)
    blk_r = _BLK_R_CHAIN
    n_pad = _pad_to(n, blk_r)
    codes_p = jnp.pad(codes.astype(jnp.int32),
                      ((0, n_pad - n), (0, d_pad - d)))
    leaf_p = jnp.pad(leaf_flat.astype(jnp.float32),
                     ((0, 0), (0, k_pad - k)))

    def kernel(codes_ref, f_ref, b_ref, a_ref, leaf_ref, out_ref):
        slot = _descend_chain(codes_ref[:].astype(jnp.float32), f_ref, b_ref,
                              a_ref, depth=depth, W=W, T_pad=T_pad,
                              d_pad=d_pad)
        l_oh = _leaf_onehot_chain(slot, W_out=W_out, T_pad=T_pad)
        out_ref[:] = jnp.dot(l_oh.astype(jnp.float32), leaf_ref[:],
                             preferred_element_type=jnp.float32,
                             precision=jax.lax.Precision.HIGHEST)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, k_pad), jnp.float32),
        grid=(n_pad // blk_r,),
        in_specs=[
            pl.BlockSpec((blk_r, d_pad), lambda r: (r, 0)),
            pl.BlockSpec(f_lvls.shape, lambda r: (0, 0)),
            pl.BlockSpec(b_lvls.shape, lambda r: (0, 0)),
            pl.BlockSpec(a_lvls.shape, lambda r: (0, 0)),
            pl.BlockSpec(leaf_flat.shape[:1] + (k_pad,), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((blk_r, k_pad), lambda r: (r, 0)),
        interpret=_interpret(),
    )(codes_p, f_lvls, b_lvls, a_lvls, leaf_p)
    return out[:n, :k]


def route_codes_chain_xla(codes: jnp.ndarray, feat_lv: jnp.ndarray,
                          bin_lv: jnp.ndarray, base_lv: jnp.ndarray,
                          n_bins: int) -> jnp.ndarray:
    """(n, T) leaf-slot assignments for slot-chain trees, plain XLA."""
    n, d = codes.shape
    T, depth, W = feat_lv.shape
    codes_bf = codes.astype(jnp.bfloat16)
    slot = jnp.zeros((n, T), jnp.int32)
    for level, Wl in enumerate(_chain_widths(depth, W)):
        f_l = feat_lv[:, level, :Wl]                         # (T, Wl)
        b_l = bin_lv[:, level, :Wl]
        a_l = base_lv[:, level, :Wl]
        sel = (f_l.reshape(-1)[None, :]
               == jnp.arange(d, dtype=jnp.int32)[:, None]
               ).astype(jnp.bfloat16)                        # (d, T·Wl)
        code_sel = (codes_bf @ sel).reshape(n, T, Wl)
        go_all = code_sel > b_l[None].astype(jnp.bfloat16)
        s_oh = slot[:, :, None] == jnp.arange(Wl, dtype=jnp.int32)
        go = jnp.any(go_all & s_oh, axis=2)
        base = jnp.sum(jnp.where(s_oh, a_l[None], 0), axis=2)
        slot = base + go.astype(jnp.int32)
    return slot


def _chain_xla_rowblocks(codes, fn, blk: int = 16384):
    """Run ``fn(codes_block)`` over row blocks via lax.map — the XLA chain
    fallback's per-level (n, T·W) transients would otherwise be O(n) HBM."""
    n = codes.shape[0]
    if n <= blk:
        return fn(codes), n
    n_pad = -(-n // blk) * blk
    codes_p = jnp.pad(codes, ((0, n_pad - n), (0, 0)),
                      constant_values=-1)    # code -1: routes left everywhere
    blocks = codes_p.reshape(n_pad // blk, blk, -1)
    return jax.lax.map(fn, blocks), n


def _chain_leaf_onehot_xla(c, feat_lv, bin_lv, base_lv, W_out, n_bins):
    """Route a row block down the chain tables and expand the (rows, T·W_out)
    leaf-slot one-hot — the shared front half of the XLA leaf-sums/predict
    fallbacks."""
    T = feat_lv.shape[0]
    node = route_codes_chain_xla(c, feat_lv, bin_lv, base_lv, n_bins)
    comb = node + (jnp.arange(T, dtype=jnp.int32) * W_out)[None, :]
    return (comb[:, :, None]
            == jnp.arange(T * W_out, dtype=jnp.int32).reshape(1, T, W_out)
            ).astype(jnp.float32).reshape(c.shape[0], T * W_out)


def _leaf_sums_chain_xla(codes, feat_lv, bin_lv, base_lv, aug, *, n_bins):
    n = codes.shape[0]
    T, depth, W = feat_lv.shape
    W_out = min(2 ** depth, W)
    aug_f = aug.astype(jnp.float32)
    blk = 16384

    def one(args):
        c, a = args
        l_oh = _chain_leaf_onehot_xla(c, feat_lv, bin_lv, base_lv, W_out,
                                      n_bins)
        return jnp.einsum("na,nk->ak", l_oh, a,
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)

    if n <= blk:
        return one((codes, aug_f)).reshape(T, W_out, -1)
    n_pad = -(-n // blk) * blk
    codes_p = jnp.pad(codes, ((0, n_pad - n), (0, 0)))
    aug_p = jnp.pad(aug_f, ((0, n_pad - n), (0, 0)))  # zero rows: no-op
    parts = jax.lax.map(one, (codes_p.reshape(-1, blk, codes.shape[1]),
                              aug_p.reshape(-1, blk, aug.shape[1])))
    return parts.sum(0).reshape(T, W_out, -1)


def _predict_chain_xla(codes, feat_lv, bin_lv, base_lv, leaf, *, n_bins):
    T, depth, W = feat_lv.shape
    W_out, k = leaf.shape[1], leaf.shape[2]
    leaf_2d = leaf.reshape(T * W_out, k).astype(jnp.float32)

    def one(c):
        l_oh = _chain_leaf_onehot_xla(c, feat_lv, bin_lv, base_lv, W_out,
                                      n_bins)
        return jnp.einsum("na,ak->nk", l_oh, leaf_2d,
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)

    out, n = _chain_xla_rowblocks(codes, one)
    if out.ndim == 3:
        out = out.reshape(-1, out.shape[-1])[:n]
    return out


def forest_leaf_sums_chain(codes: jnp.ndarray, feat_lv: jnp.ndarray,
                           bin_lv: jnp.ndarray, base_lv: jnp.ndarray,
                           aug: jnp.ndarray, *, n_bins: int) -> jnp.ndarray:
    """Exact leaf statistics for slot-chain trees in one fused pass.

    feat_lv/bin_lv/base_lv: (T, depth, W) per-level slot tables (level l uses
    the first min(2^l, W) slots); aug: (n, k) f32 per-row stats. Returns
    (T, W_out, k) with W_out = min(2^depth, W).
    """
    _check_bins(n_bins)
    T, depth, W = feat_lv.shape
    _check_slots(W)
    W_out = min(2 ** depth, W)
    if not _use_pallas():
        return _leaf_sums_chain_xla(codes, feat_lv, bin_lv, base_lv, aug,
                                    n_bins=n_bins)
    parts = []
    for lo in range(0, T, _T_CHAIN):
        hi = min(lo + _T_CHAIN, T)
        T_pad = _T_CHAIN
        f_lvls, b_lvls, a_lvls = _chain_tables(
            feat_lv[lo:hi], bin_lv[lo:hi], base_lv[lo:hi], depth, W, n_bins,
            T_pad)
        out = _leaf_sums_chain_pallas(
            codes, f_lvls, b_lvls, a_lvls, aug, depth=depth, W=W,
            W_out=W_out, n_bins=n_bins, T_pad=T_pad)
        parts.append(out[:hi - lo])
    return jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]


def forest_predict_chain(codes: jnp.ndarray, feat_lv: jnp.ndarray,
                         bin_lv: jnp.ndarray, base_lv: jnp.ndarray,
                         leaf: jnp.ndarray, *, n_bins: int) -> jnp.ndarray:
    """Σ_t leaf[t, slot(row, t), :] for slot-chain trees in one fused pass.

    leaf: (T, W_out, k) f32 leaf values. Returns (n, k) f32.
    """
    _check_bins(n_bins)
    T, depth, W = feat_lv.shape
    _check_slots(W)
    W_out, k = leaf.shape[1], leaf.shape[2]
    if not _use_pallas():
        return _predict_chain_xla(codes, feat_lv, bin_lv, base_lv, leaf,
                                  n_bins=n_bins)
    out = None
    for lo in range(0, T, _T_CHAIN):
        hi = min(lo + _T_CHAIN, T)
        T_pad = _T_CHAIN
        f_lvls, b_lvls, a_lvls = _chain_tables(
            feat_lv[lo:hi], bin_lv[lo:hi], base_lv[lo:hi], depth, W, n_bins,
            T_pad)
        leaf_flat = (jnp.pad(leaf[lo:hi].astype(jnp.float32),
                             ((0, T_pad - (hi - lo)), (0, 0), (0, 0)))
                     .transpose(1, 0, 2).reshape(T_pad * W_out, k))
        part = _predict_chain_pallas(
            codes, f_lvls, b_lvls, a_lvls, leaf_flat, depth=depth, W=W,
            W_out=W_out, n_bins=n_bins, T_pad=T_pad)
        out = part if out is None else out + part
    return out


def forest_predict(codes: jnp.ndarray, feat_heap: jnp.ndarray,
                   bin_heap: jnp.ndarray, leaf: jnp.ndarray, *,
                   depth: int, n_bins: int) -> jnp.ndarray:
    """Σ_t leaf[t, node(row, t), :] for every row, in one fused pass.

    leaf: (T, L, k) f32 leaf values (any per-tree weighting baked into the
    values; zero a tree's leaves to drop it). Returns (n, k) f32.
    """
    _check_bins(n_bins)
    T, L, k = leaf.shape
    if not _pallas_ok(depth, T):
        return _predict_xla(codes, feat_heap, bin_heap, leaf,
                            depth=depth, n_bins=n_bins)
    T_pad = _t_pad(T, depth)
    fh = jnp.pad(feat_heap, ((0, T_pad - T), (0, 0)))
    bh = jnp.pad(bin_heap, ((0, T_pad - T), (0, 0)),
                 constant_values=n_bins)
    f_lvls, b_lvls = _level_tables(fh, bh, depth, n_bins, T_pad)
    # (T, L, k) -> j-major rows: lane leaf·T_pad + t
    leaf_flat = (jnp.pad(leaf.astype(jnp.float32),
                         ((0, T_pad - T), (0, 0), (0, 0)))
                 .transpose(1, 0, 2).reshape(T_pad * L, k))
    return _predict_pallas(codes, f_lvls, b_lvls, leaf_flat,
                           depth=depth, n_bins=n_bins, T_pad=T_pad)
