"""Jitted evaluation-metric kernels.

TPU replacements for Spark MLlib's BinaryClassificationMetrics /
MulticlassMetrics / RegressionMetrics used by the reference evaluators
(reference: core/.../evaluators/OpBinaryClassificationEvaluator.scala:68,
OpMultiClassificationEvaluator.scala, OpRegressionEvaluator.scala): sort-based
scans on device instead of RDD aggregations.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from .stats import _rank

# -- binned threshold curves (large-n path) ----------------------------------
# Above this row count, AuROC/AuPR switch from exact sort-based scans to
# binned threshold curves — the same downsampling Spark's
# BinaryClassificationMetrics applies (numBins=1000 there; 4096 here), but
# computed sort- and scatter-free: bin indices split into a (64, 64)
# high/low pair and the histogram becomes chunked one-hot outer-product
# matmuls that tile onto the MXU.
_BINNED_MIN_N = 100_000
_NUM_BINS = 4096
_HI = 64
_LO = _NUM_BINS // _HI
_HIST_CHUNK = 32768


def _binned_hists(scores: jnp.ndarray, labels: jnp.ndarray,
                  mask: jnp.ndarray):
    """(pos_hist, total_hist), each (_NUM_BINS,), over the masked subset;
    bins span the masked score range (descending-threshold curves read the
    histograms reversed)."""
    n = scores.shape[0]
    inf = jnp.asarray(jnp.inf, scores.dtype)
    smin = jnp.min(jnp.where(mask, scores, inf))
    smax = jnp.max(jnp.where(mask, scores, -inf))
    width = jnp.maximum(smax - smin, 1e-12)
    idx = jnp.clip(((scores - smin) / width * _NUM_BINS).astype(jnp.int32),
                   0, _NUM_BINS - 1)
    w = mask.astype(scores.dtype)
    pos = w * (labels > 0.5)
    pad = (-n) % _HIST_CHUNK
    if pad:
        idx = jnp.pad(idx, (0, pad))      # padded rows carry zero weight
        w = jnp.pad(w, (0, pad))
        pos = jnp.pad(pos, (0, pad))
    hi = idx // _LO
    lo = idx % _LO
    iot_hi = jnp.arange(_HI, dtype=jnp.int32)
    iot_lo = jnp.arange(_LO, dtype=jnp.int32)

    def step(carry, k):
        hp, ha = carry
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, k * _HIST_CHUNK,
                                                    _HIST_CHUNK)
        h, l = sl(hi), sl(lo)
        # 0/1 weights: int8 operands with int32 accumulation are exact and
        # run the MXU at twice the bf16 rate on v5e
        wp = sl(pos).astype(jnp.int8)
        wa = sl(w).astype(jnp.int8)
        oh_hi = (h[:, None] == iot_hi).astype(jnp.int8)
        oh_lo = (l[:, None] == iot_lo).astype(jnp.int8)
        hp = hp + jnp.einsum("nh,nl->hl", oh_hi * wp[:, None], oh_lo,
                             preferred_element_type=jnp.int32)
        ha = ha + jnp.einsum("nh,nl->hl", oh_hi * wa[:, None], oh_lo,
                             preferred_element_type=jnp.int32)
        return (hp, ha), None

    z = jnp.zeros((_HI, _LO), jnp.int32)
    (hp, ha), _ = jax.lax.scan(step, (z, z),
                               jnp.arange((n + pad) // _HIST_CHUNK))
    return (hp.reshape(-1).astype(jnp.float32),
            ha.reshape(-1).astype(jnp.float32))


def _auroc_from_hists(hp: jnp.ndarray, ha: jnp.ndarray) -> jnp.ndarray:
    """Trapezoid over the binned ROC curve: each bin is one tie group, so this
    is the grouped tie-corrected Mann-Whitney statistic."""
    hp, ha = hp[::-1], ha[::-1]
    hn = ha - hp
    ctp, cfp = jnp.cumsum(hp), jnp.cumsum(hn)
    n_pos, n_neg = ctp[-1], cfp[-1]
    tpr = ctp / jnp.maximum(n_pos, 1.0)
    fpr = cfp / jnp.maximum(n_neg, 1.0)
    tp = jnp.concatenate([jnp.zeros(1, tpr.dtype), tpr[:-1]])
    fp = jnp.concatenate([jnp.zeros(1, fpr.dtype), fpr[:-1]])
    area = ((fpr - fp) * (tpr + tp) / 2).sum()
    return jnp.where((n_pos > 0) & (n_neg > 0), area, 0.0)


def _aupr_from_hists(hp: jnp.ndarray, ha: jnp.ndarray) -> jnp.ndarray:
    """Binned precision-recall curve, first point at (recall 0, precision 1)
    matching the exact path's convention."""
    hp, ha = hp[::-1], ha[::-1]
    hn = ha - hp
    ctp, cfp = jnp.cumsum(hp), jnp.cumsum(hn)
    n_pos = jnp.maximum(ctp[-1], 1.0)
    rec = ctp / n_pos
    prec = ctp / jnp.maximum(ctp + cfp, 1.0)
    rp = jnp.concatenate([jnp.zeros(1, rec.dtype), rec[:-1]])
    pp = jnp.concatenate([jnp.ones(1, prec.dtype), prec[:-1]])
    return ((rec - rp) * (prec + pp) / 2).sum()


@jax.jit
def binary_confusion(scores: jnp.ndarray, labels: jnp.ndarray,
                     threshold: float = 0.5):
    """(tp, tn, fp, fn) at a score threshold."""
    pred = (scores >= threshold).astype(jnp.float32)
    pos = (labels > 0.5).astype(jnp.float32)
    tp = (pred * pos).sum()
    fp = (pred * (1 - pos)).sum()
    fn = ((1 - pred) * pos).sum()
    tn = ((1 - pred) * (1 - pos)).sum()
    return tp, tn, fp, fn


@jax.jit
def auroc(scores: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """AuROC: exact Mann-Whitney rank formula (tie-correct); above
    _BINNED_MIN_N rows, binned threshold curves (Spark-style downsampling)."""
    if scores.shape[0] >= _BINNED_MIN_N:
        return _auroc_from_hists(
            *_binned_hists(scores, labels, jnp.ones_like(scores, jnp.bool_)))
    pos = (labels > 0.5).astype(scores.dtype)
    n_pos = pos.sum()
    n_neg = pos.shape[0] - n_pos
    ranks = _rank(scores)
    pos_rank_sum = (ranks * pos).sum()
    u = pos_rank_sum - n_pos * (n_pos + 1) / 2.0
    return jnp.where((n_pos > 0) & (n_neg > 0), u / jnp.maximum(n_pos * n_neg, 1.0), 0.0)


@jax.jit
def aupr(scores: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Area under the precision-recall curve, linear interpolation over
    distinct-threshold boundary points (matches Spark's areaUnderPR up to its
    first-point convention); binned above _BINNED_MIN_N rows."""
    if scores.shape[0] >= _BINNED_MIN_N:
        return _aupr_from_hists(
            *_binned_hists(scores, labels, jnp.ones_like(scores, jnp.bool_)))
    n = scores.shape[0]
    order = jnp.argsort(-scores)
    s = scores[order]
    y = (labels[order] > 0.5).astype(scores.dtype)
    cum_tp = jnp.cumsum(y)
    cum_fp = jnp.cumsum(1.0 - y)
    n_pos = jnp.maximum(cum_tp[-1], 1.0)
    # points valid only at tie-group boundaries (last index of equal scores)
    boundary = jnp.concatenate([s[1:] != s[:-1], jnp.array([True])])
    recall = cum_tp / n_pos
    precision = cum_tp / jnp.maximum(cum_tp + cum_fp, 1.0)
    # previous boundary's (recall, precision) for each boundary point
    idx = jnp.arange(n)
    b_idx = jnp.where(boundary, idx, -1)
    prev_b = jnp.concatenate([jnp.array([-1]), jax.lax.cummax(b_idx)[:-1]])
    r_prev = jnp.where(prev_b >= 0, recall[jnp.maximum(prev_b, 0)], 0.0)
    p_prev = jnp.where(prev_b >= 0, precision[jnp.maximum(prev_b, 0)], 1.0)
    seg = (recall - r_prev) * (precision + p_prev) / 2.0
    return jnp.where(boundary, seg, 0.0).sum()


@partial(jax.jit, static_argnames=("binned",))
def auroc_masked(scores: jnp.ndarray, labels: jnp.ndarray,
                 mask: jnp.ndarray, binned: Optional[bool] = None
                 ) -> jnp.ndarray:
    """AuROC over the masked subset. Masked rows get +inf scores (ranking above
    all valid rows, so valid ranks 1..n_valid are unchanged) and are excluded
    from the positive/negative counts — used inside vmapped CV where every fold
    shares one static shape. Binned above _BINNED_MIN_N rows; pass ``binned``
    to pin the algorithm regardless of shape (the fold-sliced CV path pins it
    to the pre-slice row count so results match full-row scoring)."""
    use_binned = (binned if binned is not None
                  else scores.shape[0] >= _BINNED_MIN_N)
    if use_binned:
        return _auroc_from_hists(*_binned_hists(scores, labels, mask))
    s = jnp.where(mask, scores, jnp.inf)
    pos = (labels > 0.5) & mask
    n_pos = pos.sum().astype(scores.dtype)
    n_neg = mask.sum().astype(scores.dtype) - n_pos
    ranks = _rank(s)
    pos_rank_sum = (ranks * pos.astype(scores.dtype)).sum()
    u = pos_rank_sum - n_pos * (n_pos + 1) / 2.0
    return jnp.where((n_pos > 0) & (n_neg > 0), u / jnp.maximum(n_pos * n_neg, 1.0), 0.0)


@partial(jax.jit, static_argnames=("binned",))
def aupr_masked(scores: jnp.ndarray, labels: jnp.ndarray,
                mask: jnp.ndarray, binned: Optional[bool] = None
                ) -> jnp.ndarray:
    """AuPR over the masked subset (masked rows sink to -inf and contribute
    nothing to cumulative TP/FP, so curve deltas in their range are zero).
    Binned above _BINNED_MIN_N rows; ``binned`` pins the algorithm (see
    auroc_masked)."""
    use_binned = (binned if binned is not None
                  else scores.shape[0] >= _BINNED_MIN_N)
    if use_binned:
        return _aupr_from_hists(*_binned_hists(scores, labels, mask))
    n = scores.shape[0]
    s_in = jnp.where(mask, scores, -jnp.inf)
    order = jnp.argsort(-s_in)
    s = s_in[order]
    valid = mask[order].astype(scores.dtype)
    y = (labels[order] > 0.5).astype(scores.dtype) * valid
    cum_tp = jnp.cumsum(y)
    cum_fp = jnp.cumsum(valid - y)
    n_pos = jnp.maximum(cum_tp[-1], 1.0)
    boundary = jnp.concatenate([s[1:] != s[:-1], jnp.array([True])])
    recall = cum_tp / n_pos
    precision = cum_tp / jnp.maximum(cum_tp + cum_fp, 1.0)
    idx = jnp.arange(n)
    b_idx = jnp.where(boundary, idx, -1)
    prev_b = jnp.concatenate([jnp.array([-1]), jax.lax.cummax(b_idx)[:-1]])
    r_prev = jnp.where(prev_b >= 0, recall[jnp.maximum(prev_b, 0)], 0.0)
    p_prev = jnp.where(prev_b >= 0, precision[jnp.maximum(prev_b, 0)], 1.0)
    seg = (recall - r_prev) * (precision + p_prev) / 2.0
    return jnp.where(boundary, seg, 0.0).sum()


@jax.jit
def binary_threshold_metrics_masked(scores: jnp.ndarray, labels: jnp.ndarray,
                                    mask: jnp.ndarray, threshold: float = 0.5):
    """Precision/Recall/F1/Error at a probability threshold over the masked
    subset (vmapped-CV fast path; assumes probability-like scores)."""
    w = mask.astype(scores.dtype)
    pred = (scores >= threshold).astype(scores.dtype) * w
    pos = (labels > 0.5).astype(scores.dtype) * w
    tp = (pred * pos).sum()
    fp = (pred * (w - pos)).sum()
    fn = ((w - pred) * pos).sum()
    cnt = jnp.maximum(w.sum(), 1.0)
    prec = tp / jnp.maximum(tp + fp, 1.0)
    rec = tp / jnp.maximum(pos.sum(), 1.0)
    f1 = jnp.where(prec + rec > 0,
                   2 * prec * rec / jnp.maximum(prec + rec, 1e-30), 0.0)
    err = (fp + fn) / cnt
    return {"Precision": prec, "Recall": rec, "F1": f1, "Error": err}


@partial(jax.jit, static_argnames=("num_classes",))
def multiclass_metrics_masked(pred_idx: jnp.ndarray, label_idx: jnp.ndarray,
                              mask: jnp.ndarray, num_classes: int):
    """Weighted Precision/Recall/F1 + Error over the masked subset."""
    w = mask.astype(jnp.float32)
    p = jax.nn.one_hot(pred_idx, num_classes, dtype=jnp.float32) * w[:, None]
    l = jax.nn.one_hot(label_idx, num_classes, dtype=jnp.float32) * w[:, None]
    cm = l.T @ p
    n = jnp.maximum(cm.sum(), 1.0)
    support = cm.sum(axis=1)
    pred_cnt = cm.sum(axis=0)
    tp = jnp.diag(cm)
    prec_c = tp / jnp.maximum(pred_cnt, 1.0)
    rec_c = tp / jnp.maximum(support, 1.0)
    f1_c = jnp.where(prec_c + rec_c > 0,
                     2 * prec_c * rec_c / jnp.maximum(prec_c + rec_c, 1e-30), 0.0)
    wgt = support / n
    return {"Error": 1.0 - jnp.trace(cm) / n,
            "Precision": (prec_c * wgt).sum(),
            "Recall": (rec_c * wgt).sum(),
            "F1": (f1_c * wgt).sum()}


@jax.jit
def regression_metrics_masked(pred: jnp.ndarray, label: jnp.ndarray,
                              mask: jnp.ndarray):
    w = mask.astype(pred.dtype)
    cnt = jnp.maximum(w.sum(), 1.0)
    err = (pred - label) * w
    mse = (err ** 2).sum() / cnt
    label_mean = (label * w).sum() / cnt
    ss_tot = (((label - label_mean) * w) ** 2).sum()
    r2 = jnp.where(ss_tot > 0, 1.0 - (err ** 2).sum() / jnp.maximum(ss_tot, 1e-30), 0.0)
    return {"RootMeanSquaredError": jnp.sqrt(mse), "MeanSquaredError": mse,
            "MeanAbsoluteError": jnp.abs(err).sum() / cnt, "R2": r2}


def log_loss_masked(scores: jnp.ndarray, labels: jnp.ndarray,
                    mask: jnp.ndarray) -> jnp.ndarray:
    """Binary log loss over the masked subset (validation-sweep variant of
    ``log_loss``)."""
    p = jnp.clip(scores, 1e-15, 1 - 1e-15)
    y = (labels > 0.5).astype(scores.dtype)
    w = mask.astype(scores.dtype)
    ll = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p)) * w
    return ll.sum() / jnp.maximum(w.sum(), 1.0)


@partial(jax.jit, static_argnames=("num_bins",))
def threshold_metrics(scores: jnp.ndarray, labels: jnp.ndarray,
                      num_bins: int = 100):
    """Precision/recall/F1 over evenly spaced thresholds (reference
    threshold curves in BinaryClassificationMetrics)."""
    thresholds = jnp.linspace(0.0, 1.0, num_bins)
    pos = (labels > 0.5).astype(scores.dtype)
    n_pos = jnp.maximum(pos.sum(), 1.0)

    def at(t):
        pred = (scores >= t).astype(scores.dtype)
        tp = (pred * pos).sum()
        fp = (pred * (1 - pos)).sum()
        prec = tp / jnp.maximum(tp + fp, 1.0)
        rec = tp / n_pos
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / jnp.maximum(prec + rec, 1e-30), 0.0)
        return prec, rec, f1

    prec, rec, f1 = jax.vmap(at)(thresholds)
    return thresholds, prec, rec, f1


@partial(jax.jit, static_argnames=("num_classes",))
def multiclass_confusion(pred_idx: jnp.ndarray, label_idx: jnp.ndarray,
                         num_classes: int) -> jnp.ndarray:
    """(C, C) confusion matrix rows=label, cols=pred — one-hot matmul."""
    p = jax.nn.one_hot(pred_idx, num_classes, dtype=jnp.float32)
    l = jax.nn.one_hot(label_idx, num_classes, dtype=jnp.float32)
    return l.T @ p


@partial(jax.jit, static_argnames=("num_classes",))
def multiclass_metrics(pred_idx: jnp.ndarray, label_idx: jnp.ndarray,
                       num_classes: int):
    """error, weighted precision/recall/F1 (reference
    OpMultiClassificationEvaluator default metrics)."""
    cm = multiclass_confusion(pred_idx, label_idx, num_classes)
    n = jnp.maximum(cm.sum(), 1.0)
    correct = jnp.trace(cm)
    support = cm.sum(axis=1)                   # per true class
    pred_cnt = cm.sum(axis=0)
    tp = jnp.diag(cm)
    prec_c = tp / jnp.maximum(pred_cnt, 1.0)
    rec_c = tp / jnp.maximum(support, 1.0)
    f1_c = jnp.where(prec_c + rec_c > 0,
                     2 * prec_c * rec_c / jnp.maximum(prec_c + rec_c, 1e-30), 0.0)
    w = support / n
    return {
        "Error": 1.0 - correct / n,
        "Precision": (prec_c * w).sum(),
        "Recall": (rec_c * w).sum(),
        "F1": (f1_c * w).sum(),
    }


@jax.jit
def regression_metrics(pred: jnp.ndarray, label: jnp.ndarray):
    """RMSE/MSE/MAE/R² (reference OpRegressionEvaluator.scala)."""
    err = pred - label
    mse = (err ** 2).mean()
    mae = jnp.abs(err).mean()
    ss_res = (err ** 2).sum()
    ss_tot = ((label - label.mean()) ** 2).sum()
    r2 = jnp.where(ss_tot > 0, 1.0 - ss_res / jnp.maximum(ss_tot, 1e-30), 0.0)
    return {"RootMeanSquaredError": jnp.sqrt(mse), "MeanSquaredError": mse,
            "MeanAbsoluteError": mae, "R2": r2}


@jax.jit
def log_loss(prob_pos: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Binary log loss (reference impl/evaluator/OPLogLoss.scala)."""
    p = jnp.clip(prob_pos, 1e-15, 1 - 1e-15)
    y = (labels > 0.5).astype(prob_pos.dtype)
    return -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p)).mean()


@jax.jit
def multiclass_log_loss(probs: jnp.ndarray, label_idx: jnp.ndarray) -> jnp.ndarray:
    p = jnp.clip(probs, 1e-15, 1.0)
    picked = jnp.take_along_axis(p, label_idx[:, None].astype(jnp.int32), axis=1)[:, 0]
    return -jnp.log(picked).mean()
