"""Rich feature syntax — the DSL layer.

Mirrors the reference implicit-class DSL (reference: core/.../dsl/ —
RichNumericFeature.scala, RichTextFeature.scala, RichMapFeature.scala,
RichDateFeature.scala, RichListFeature.scala, RichFeaturesCollection.scala):
``f1 + f2``, ``f / 2``, ``f.tokenize()``, ``f.pivot()``, ``f.bucketize(...)``,
``f.sanity_check(label)``, ``transmogrify([...])``. In Python the "implicit
enrichment" is direct methods on :class:`Feature`, attached on import of this
module (imported by the package ``__init__``), so every feature carries the
syntax with zero wrapping.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from .features import Feature
from .impl.feature.bucketizers import (
    DecisionTreeNumericBucketizer, NumericBucketizer, PercentileCalibrator,
)
from .impl.feature.dates import (
    DEFAULT_CIRCULAR_PERIODS, DateListVectorizer, DateToUnitCircleTransformer,
    TimePeriodTransformer,
)
from .impl.feature.math import (
    AbsoluteValue, AliasTransformer, BinaryMathOp, Ceil, Exp, FilterMap, Floor,
    JaccardSimilarity, Log, NGramSimilarity, Power, RoundTransformer, ScalarOp,
    Sqrt, SubstringTransformer, TextLenTransformer, ToOccurTransformer,
)
from .impl.feature.scalers import (
    DescalerTransformer, FillMissingWithMean, OpScalarStandardScaler,
    ScalerTransformer,
)
from .impl.feature.transmogrifier import transmogrify
from .impl.feature.vectorizers import (
    OneHotVectorizer, SmartTextVectorizer, TextTokenizer,
)
# NOTE: SanityChecker is imported inside sanity_check() — it pulls in jax,
# which must stay lazy until the user has set platform flags (see __init__)


def _num_binop(op: str):
    def method(self: Feature, other):
        if isinstance(other, Feature):
            return BinaryMathOp(op).set_input(self, other).get_output()
        return ScalarOp(op, float(other)).set_input(self).get_output()
    return method


def _num_rbinop(op: str):
    # scalar on the left: scalar + f == f + scalar; scalar - f == (f * -1) + s
    def method(self: Feature, other):
        if op in ("+", "*"):
            return _num_binop(op)(self, other)
        if op == "-":
            neg = ScalarOp("*", -1.0).set_input(self).get_output()
            return ScalarOp("+", float(other)).set_input(neg).get_output()
        raise TypeError(f"unsupported reflected op {op} on Feature")
    return method


# -- RichNumericFeature (reference RichNumericFeature.scala) -----------------

def _attach():
    F = Feature
    F.__add__ = _num_binop("+")
    F.__sub__ = _num_binop("-")
    F.__mul__ = _num_binop("*")
    F.__truediv__ = _num_binop("/")
    F.__radd__ = _num_rbinop("+")
    F.__rmul__ = _num_rbinop("*")
    F.__rsub__ = _num_rbinop("-")

    def alias(self: Feature, name: str) -> Feature:
        return AliasTransformer(name).set_input(self).get_output()

    def abs_(self: Feature) -> Feature:
        return AbsoluteValue().set_input(self).get_output()

    def log(self: Feature, base: float = 2.718281828459045) -> Feature:
        return Log(base).set_input(self).get_output()

    def exp(self: Feature) -> Feature:
        return Exp().set_input(self).get_output()

    def sqrt(self: Feature) -> Feature:
        return Sqrt().set_input(self).get_output()

    def power(self: Feature, p: float) -> Feature:
        return Power(p).set_input(self).get_output()

    def round_(self: Feature) -> Feature:
        return RoundTransformer().set_input(self).get_output()

    def ceil(self: Feature) -> Feature:
        return Ceil().set_input(self).get_output()

    def floor(self: Feature) -> Feature:
        return Floor().set_input(self).get_output()

    def bucketize(self: Feature, splits: Sequence[float],
                  bucket_labels: Optional[Sequence[str]] = None,
                  track_nulls: bool = True, track_invalid: bool = False
                  ) -> Feature:
        return NumericBucketizer(
            splits, bucket_labels=bucket_labels, track_nulls=track_nulls,
            track_invalid=track_invalid).set_input(self).get_output()

    def auto_bucketize(self: Feature, label: Feature, max_depth: int = 2,
                       min_info_gain: float = 0.01) -> Feature:
        return DecisionTreeNumericBucketizer(
            max_depth=max_depth, min_info_gain=min_info_gain
        ).set_input(label, self).get_output()

    def fill_missing_with_mean(self: Feature, default: float = 0.0) -> Feature:
        return FillMissingWithMean(default).set_input(self).get_output()

    def zscore(self: Feature) -> Feature:
        return OpScalarStandardScaler().set_input(self).get_output()

    def scale(self: Feature, scaling_type: str = "linear", slope: float = 1.0,
              intercept: float = 0.0) -> Feature:
        return ScalerTransformer(scaling_type, slope, intercept
                                 ).set_input(self).get_output()

    def descale(self: Feature, scaled: Feature) -> Feature:
        return DescalerTransformer().set_input(self, scaled).get_output()

    def to_occur(self: Feature) -> Feature:
        return ToOccurTransformer().set_input(self).get_output()

    def percentile_calibrate(self: Feature, buckets: int = 100) -> Feature:
        return PercentileCalibrator(buckets).set_input(self).get_output()

    # -- RichTextFeature ------------------------------------------------------
    def tokenize(self: Feature, min_token_length: int = 1) -> Feature:
        return TextTokenizer(min_token_length).set_input(self).get_output()

    # -- domain-text accessors (reference RichTextFeature email/url/phone
    # syntax backed by the parser stages in impl/feature/text.py) ----------
    def is_valid_email(self: Feature) -> Feature:
        from .impl.feature.text import ValidEmailTransformer
        return ValidEmailTransformer().set_input(self).get_output()

    def to_email_domain(self: Feature) -> Feature:
        """Email → PickList of the domain (reference RichTextFeature
        toEmailDomain). Pivot the result with ``.pivot(top_k=...)`` — the
        reference's domain pivoting is likewise a separate vectorize step."""
        from .impl.feature.text import EmailToPickList
        return EmailToPickList().set_input(self).get_output()

    def to_url_domain(self: Feature) -> Feature:
        from .impl.feature.text import UrlToDomain
        return UrlToDomain().set_input(self).get_output()

    def is_valid_url(self: Feature) -> Feature:
        from .impl.feature.text import IsValidUrl
        return IsValidUrl().set_input(self).get_output()

    def is_valid_phone(self: Feature, region: str = "US") -> Feature:
        from .impl.feature.text import IsValidPhoneDefaultCountry
        return (IsValidPhoneDefaultCountry(default_region=region)
                .set_input(self).get_output())

    def detect_languages(self: Feature) -> Feature:
        from .impl.feature.text import LangDetector
        return LangDetector().set_input(self).get_output()

    def detect_mime_types(self: Feature) -> Feature:
        from .impl.feature.text import MimeTypeDetector
        return MimeTypeDetector().set_input(self).get_output()

    def recognize_entities(self: Feature) -> Feature:
        from .impl.feature.text import NameEntityRecognizer
        return NameEntityRecognizer().set_input(self).get_output()

    def pivot(self: Feature, top_k: int = 20, min_support: int = 10,
              track_nulls: bool = True) -> Feature:
        return OneHotVectorizer(top_k=top_k, min_support=min_support,
                                track_nulls=track_nulls
                                ).set_input(self).get_output()

    def smart_vectorize(self: Feature, **kw) -> Feature:
        return SmartTextVectorizer(**kw).set_input(self).get_output()

    def text_len(self: Feature) -> Feature:
        return TextLenTransformer().set_input(self).get_output()

    def contains(self: Feature, other: Feature) -> Feature:
        return SubstringTransformer().set_input(self, other).get_output()

    def jaccard_similarity(self: Feature, other: Feature) -> Feature:
        return JaccardSimilarity().set_input(self, other).get_output()

    def ngram_similarity(self: Feature, other: Feature, n: int = 3) -> Feature:
        return NGramSimilarity(n).set_input(self, other).get_output()

    # -- RichFeature generic lifts (reference RichFeature.scala map/exists/
    # filter/replaceWith/occurs — user-lambda row transforms) ---------------
    def map_values(self: Feature, fn: Callable[[Any], Any],
                   output_type=None) -> Feature:
        """Row-wise value map (reference RichFeature.map); None stays None."""
        from .stages.base import UnaryTransformer
        out_t = output_type or self.feature_type
        return UnaryTransformer(
            "map", transform_fn=lambda v: None if v is None else fn(v),
            output_type=out_t).set_input(self).get_output()

    def exists(self: Feature, predicate: Callable[[Any], bool]) -> Feature:
        """Binary: value present AND predicate holds (RichFeature.exists)."""
        from .stages.base import UnaryTransformer
        from .types import Binary
        return UnaryTransformer(
            "exists",
            transform_fn=lambda v: v is not None and bool(predicate(v)),
            output_type=Binary).set_input(self).get_output()

    def filter_values(self: Feature, predicate: Callable[[Any], bool],
                      keep: bool = True) -> Feature:
        """Keep the value only when predicate holds (RichFeature.filter /
        filterNot with keep=False); otherwise missing."""
        from .stages.base import UnaryTransformer
        return UnaryTransformer(
            "filter",
            transform_fn=lambda v: v if (v is not None
                                         and bool(predicate(v)) == keep)
            else None,
            output_type=self.feature_type).set_input(self).get_output()

    def replace_with(self: Feature, old_val: Any, new_val: Any) -> Feature:
        """Substitute one value for another (RichFeature.replaceWith)."""
        from .stages.base import UnaryTransformer
        return UnaryTransformer(
            "replaced",
            transform_fn=lambda v: new_val if v == old_val else v,
            output_type=self.feature_type).set_input(self).get_output()

    def occurs(self: Feature,
               matches: Optional[Callable[[Any], bool]] = None) -> Feature:
        return ToOccurTransformer(matches).set_input(self).get_output()

    # -- RichTextFeature extras ----------------------------------------------
    def to_multi_pick_list(self: Feature) -> Feature:
        from .impl.feature.text import TextToMultiPickList
        return TextToMultiPickList().set_input(self).get_output()

    def indexed(self: Feature, handle_invalid: str = "keep") -> Feature:
        """Text → frequency-ranked label index (RichTextFeature.indexed)."""
        from .impl.feature.text import OpStringIndexer
        return (OpStringIndexer(handle_invalid=handle_invalid)
                .set_input(self).get_output())

    def deindexed(self: Feature, labels: Sequence[str]) -> Feature:
        """Index → label string (RichFeature.deindexed)."""
        from .impl.feature.text import OpIndexToString
        return OpIndexToString(labels).set_input(self).get_output()

    def tokenize_regex(self: Feature, pattern: str = r"\w+",
                       to_lowercase: bool = True,
                       min_token_length: int = 1) -> Feature:
        from .impl.feature.text import RegexTokenizer
        return RegexTokenizer(pattern, to_lowercase, min_token_length
                              ).set_input(self).get_output()

    def to_email_prefix(self: Feature) -> Feature:
        from .impl.feature.text import EmailToPrefix
        return EmailToPrefix().set_input(self).get_output()

    def to_url_protocol(self: Feature) -> Feature:
        from .impl.feature.text import UrlToProtocol
        return UrlToProtocol().set_input(self).get_output()

    def parse_phone(self: Feature, region: str = "US") -> Feature:
        from .impl.feature.text import PhoneNumberParser
        return (PhoneNumberParser(default_region=region)
                .set_input(self).get_output())

    # -- RichListFeature (TextList) ------------------------------------------
    def tf(self: Feature, num_hashes: int = 512) -> Feature:
        """Term-frequency hashing vector (RichListFeature.tf)."""
        from .impl.feature.vectorizers import HashingVectorizer
        return HashingVectorizer(num_hashes=num_hashes
                                 ).set_input(self).get_output()

    def tfidf(self: Feature, num_hashes: int = 512,
              min_doc_freq: int = 0) -> Feature:
        """tf-idf weights (RichListFeature.tfidf = HashingTF → IDF)."""
        from .impl.feature.text import OpIDF
        tf_f = self.tf(num_hashes=num_hashes)
        return OpIDF(min_doc_freq=min_doc_freq).set_input(tf_f).get_output()

    def idf(self: Feature, min_doc_freq: int = 0) -> Feature:
        """IDF weighting of an existing term-count vector."""
        from .impl.feature.text import OpIDF
        return OpIDF(min_doc_freq=min_doc_freq).set_input(self).get_output()

    def word2vec(self: Feature, vector_size: int = 32, **kw) -> Feature:
        from .impl.feature.text import OpWord2Vec
        return (OpWord2Vec(vector_size=vector_size, **kw)
                .set_input(self).get_output())

    def count_vec(self: Feature, vocab_size: int = 512, min_df: int = 1,
                  binary: bool = False) -> Feature:
        from .impl.feature.text import OpCountVectorizer
        return (OpCountVectorizer(vocab_size, min_df, binary)
                .set_input(self).get_output())

    def ngram(self: Feature, n: int = 2) -> Feature:
        from .impl.feature.text import OpNGram
        return OpNGram(n).set_input(self).get_output()

    def remove_stop_words(self: Feature,
                          stop_words: Optional[Sequence[str]] = None,
                          case_sensitive: bool = False) -> Feature:
        from .impl.feature.text import OpStopWordsRemover
        return (OpStopWordsRemover(stop_words, case_sensitive)
                .set_input(self).get_output())

    def lda(self: Feature, k: int = 10, **kw) -> Feature:
        """Topic mixture of a term-count vector (RichVectorFeature.lda)."""
        from .impl.feature.text import OpLDA
        return OpLDA(k=k, **kw).set_input(self).get_output()

    # -- RichDateFeature ------------------------------------------------------
    def to_unit_circle(self: Feature,
                       periods: Sequence[str] = DEFAULT_CIRCULAR_PERIODS
                       ) -> Feature:
        from .types import DateMap
        if issubclass(self.feature_type, DateMap):
            from .impl.feature.dates import DateMapToUnitCircleVectorizer
            from .impl.feature.vectorizers import VectorsCombiner
            # one vectorizer per requested period, combined — the map stage
            # encodes a single period (reference DateMapToUnitCircleVectorizer)
            outs = [DateMapToUnitCircleVectorizer(period=p)
                    .set_input(self).get_output() for p in periods]
            if len(outs) == 1:
                return outs[0]
            return VectorsCombiner().set_input(*outs).get_output()
        return DateToUnitCircleTransformer(periods=periods
                                           ).set_input(self).get_output()

    def time_period(self: Feature, period: str = "DayOfWeek") -> Feature:
        """Date/DateList/DateMap → extracted time period (reference
        TimePeriod{,List,Map}Transformer dispatch by input kind)."""
        from .types import DateList as DL, DateMap as DM
        from .impl.feature.dates import (
            TimePeriodListTransformer, TimePeriodMapTransformer)
        if issubclass(self.feature_type, DL):
            return (TimePeriodListTransformer(period)
                    .set_input(self).get_output())
        if issubclass(self.feature_type, DM):
            return (TimePeriodMapTransformer(period)
                    .set_input(self).get_output())
        return TimePeriodTransformer(period).set_input(self).get_output()

    def since_last(self: Feature, reference_date_ms: Optional[int] = None
                   ) -> Feature:
        return DateListVectorizer(
            "SinceLast", reference_date_ms=reference_date_ms
        ).set_input(self).get_output()

    def to_date_list(self: Feature) -> Feature:
        """Date → one-element DateList (RichDateFeature.toDateList)."""
        from .stages.base import UnaryTransformer
        from .types import DateList as DL
        return UnaryTransformer(
            "toDateList",
            transform_fn=lambda v: None if v is None else [int(v)],
            output_type=DL).set_input(self).get_output()

    # -- RichMapFeature -------------------------------------------------------
    def filter_keys(self: Feature, white_list: Sequence[str] = (),
                    black_list: Sequence[str] = ()) -> Feature:
        return FilterMap(white_list, black_list).set_input(self).get_output()

    def vectorize_map(self: Feature, white_list_keys: Sequence[str] = (),
                      black_list_keys: Sequence[str] = (), **kw) -> Feature:
        """Per-key map vectorization with key white/black lists (reference
        RichMapFeature.vectorize overloads)."""
        from .impl.feature.maps import MapVectorizer
        return MapVectorizer(white_list_keys=white_list_keys,
                             black_list_keys=black_list_keys, **kw
                             ).set_input(self).get_output()

    def smart_vectorize_map(self: Feature, **kw) -> Feature:
        """Per-key cardinality-adaptive text-map vectorization (reference
        RichMapFeature.smartVectorize)."""
        from .impl.feature.maps import SmartTextMapVectorizer
        return SmartTextMapVectorizer(**kw).set_input(self).get_output()

    def pivot_map(self: Feature, top_k: int = 20,
                  min_support: int = 10) -> Feature:
        """Per-key top-K pivot of a TextMap (reference RichMapFeature
        TextMap vectorize)."""
        from .impl.feature.maps import TextMapPivotVectorizer
        return (TextMapPivotVectorizer(top_k=top_k, min_support=min_support)
                .set_input(self).get_output())

    def auto_bucketize_map(self: Feature, label: Feature, max_depth: int = 2,
                           min_info_gain: float = 0.01) -> Feature:
        """Label-aware per-key bucketization of a numeric map (reference
        RichMapFeature.autoBucketize)."""
        from .impl.feature.bucketizers import DecisionTreeNumericMapBucketizer
        return DecisionTreeNumericMapBucketizer(
            max_depth=max_depth, min_info_gain=min_info_gain
        ).set_input(label, self).get_output()

    def is_valid_phone_map(self: Feature, region: str = "US") -> Feature:
        from .impl.feature.text import IsValidPhoneMap
        return (IsValidPhoneMap(default_region=region)
                .set_input(self).get_output())

    # -- RichVectorFeature ----------------------------------------------------
    def combine(self: Feature, *others: Feature) -> Feature:
        """Concatenate vectors (RichVectorFeature.combine)."""
        from .impl.feature.vectorizers import VectorsCombiner
        return VectorsCombiner().set_input(self, *others).get_output()

    def drop_indices_by(self: Feature,
                        predicate: Callable[[Any], bool]) -> Feature:
        from .impl.feature.math import DropIndicesByTransformer
        return (DropIndicesByTransformer(predicate)
                .set_input(self).get_output())

    def to_isotonic_calibrated(self: Feature, label: Feature,
                               isotonic: bool = True) -> Feature:
        """Calibrate a score against the label (RichNumericFeature
        .toIsotonicCalibrated)."""
        from .impl.regression.isotonic import IsotonicRegressionCalibrator
        return (IsotonicRegressionCalibrator(isotonic=isotonic)
                .set_input(label, self).get_output())

    # -- vectorize / sanity check ---------------------------------------------
    def vectorize(self: Feature) -> Feature:
        """Per-feature default vectorization (reference Rich*Feature.vectorize)."""
        return transmogrify([self])

    def sanity_check(self: Feature, label: Feature, **kw) -> Feature:
        """self must be an OPVector; label a RealNN (reference
        RichNumericFeature.sanityCheck:469)."""
        from .impl.preparators.sanity_checker import SanityChecker
        return SanityChecker(**kw).set_input(label, self).get_output()

    methods = [
        ("alias", alias), ("abs", abs_), ("log", log), ("exp", exp),
        ("sqrt", sqrt), ("power", power), ("round", round_), ("ceil", ceil),
        ("floor", floor), ("bucketize", bucketize),
        ("auto_bucketize", auto_bucketize),
        ("fill_missing_with_mean", fill_missing_with_mean),
        ("zscore", zscore), ("scale", scale), ("descale", descale),
        ("to_occur", to_occur), ("percentile_calibrate", percentile_calibrate),
        ("tokenize", tokenize), ("pivot", pivot),
        ("smart_vectorize", smart_vectorize), ("text_len", text_len),
        ("contains", contains), ("jaccard_similarity", jaccard_similarity),
        ("ngram_similarity", ngram_similarity),
        ("to_unit_circle", to_unit_circle), ("time_period", time_period),
        ("since_last", since_last), ("filter_keys", filter_keys),
        ("vectorize", vectorize), ("sanity_check", sanity_check),
        ("is_valid_email", is_valid_email),
        ("to_email_domain", to_email_domain),
        ("to_url_domain", to_url_domain), ("is_valid_url", is_valid_url),
        ("is_valid_phone", is_valid_phone),
        ("detect_languages", detect_languages),
        ("detect_mime_types", detect_mime_types),
        ("recognize_entities", recognize_entities),
        # generic lifts
        ("map_values", map_values), ("exists", exists),
        ("filter_values", filter_values), ("replace_with", replace_with),
        ("occurs", occurs),
        # text extras
        ("to_multi_pick_list", to_multi_pick_list), ("indexed", indexed),
        ("deindexed", deindexed), ("tokenize_regex", tokenize_regex),
        ("to_email_prefix", to_email_prefix),
        ("to_url_protocol", to_url_protocol), ("parse_phone", parse_phone),
        # list / NLP
        ("tf", tf), ("tfidf", tfidf), ("idf", idf), ("word2vec", word2vec),
        ("count_vec", count_vec), ("ngram", ngram),
        ("remove_stop_words", remove_stop_words), ("lda", lda),
        # dates
        ("to_date_list", to_date_list),
        # maps
        ("vectorize_map", vectorize_map),
        ("smart_vectorize_map", smart_vectorize_map),
        ("pivot_map", pivot_map),
        ("auto_bucketize_map", auto_bucketize_map),
        ("is_valid_phone_map", is_valid_phone_map),
        # vectors
        ("combine", combine), ("drop_indices_by", drop_indices_by),
        ("to_isotonic_calibrated", to_isotonic_calibrated),
    ]
    for name, fn in methods:
        setattr(F, name, fn)
    return tuple(name for name, _ in methods)


#: every DSL method attached to Feature — tests assert each one runs
#: end-to-end (the round-1 to_email_domain crash must never recur)
DSL_METHODS = _attach()
