"""Monoid aggregators for event-level data.

Mirrors the reference aggregation layer (reference:
features/src/main/scala/com/salesforce/op/aggregators/ —
MonoidAggregatorDefaults.scala, Numerics.scala, Maps.scala,
TimeBasedAggregator.scala:37-72, CutOffTime.scala:72,
FeatureAggregator.scala:138): every feature type has a default monoid
(prepare → plus → present) used by the aggregating readers to fold a key's
event records into one training row; predictors aggregate events before the
cutoff time and responses after (reference DataReader.scala:206-279).

The monoid structure is what makes multi-host ingestion parallel: partial
aggregates from different shards merge associatively, exactly like the
reference's map-side combine.
"""
from __future__ import annotations

import time as _time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from .types import (
    Binary, Date, DateList, DateTime, FeatureType, Geolocation, Integral,
    MultiPickList, OPList, OPMap, OPNumeric, OPSet, PickList, Real, RealNN,
    Text, TextList,
)

_DAY_MS = 86_400_000


class MonoidAggregator:
    """prepare/plus/present monoid (reference algebird MonoidAggregator)."""

    def prepare(self, v: Any) -> Any:
        return v

    def plus(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def present(self, a: Optional[Any]) -> Any:
        return a

    def aggregate(self, values: Sequence[Any]) -> Any:
        acc: Optional[Any] = None
        for v in values:
            if v is None:
                continue
            p = self.prepare(v)
            if p is None:
                continue
            acc = p if acc is None else self.plus(acc, p)
        return self.present(acc)


class Sum(MonoidAggregator):
    def plus(self, a, b):
        return a + b


class MaxAgg(MonoidAggregator):
    def plus(self, a, b):
        return max(a, b)


class MinAgg(MonoidAggregator):
    def plus(self, a, b):
        return min(a, b)


class MeanAgg(MonoidAggregator):
    def prepare(self, v):
        return (float(v), 1)

    def plus(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def present(self, a):
        return None if a is None or a[1] == 0 else a[0] / a[1]


class LogicalOr(MonoidAggregator):
    def plus(self, a, b):
        return bool(a) or bool(b)


class ConcatText(MonoidAggregator):
    """Concatenate text with a separator (reference ConcatTextWithSeparator)."""

    def __init__(self, separator: str = " "):
        self.separator = separator

    def prepare(self, v):
        return str(v)

    def plus(self, a, b):
        return a + self.separator + b


class ModeAgg(MonoidAggregator):
    """Most frequent value, ties → smallest (reference mode semantics)."""

    def prepare(self, v):
        return {v: 1}

    def plus(self, a, b):
        out = dict(a)
        for k, c in b.items():
            out[k] = out.get(k, 0) + c
        return out

    def present(self, a):
        if not a:
            return None
        return sorted(a.items(), key=lambda kv: (-kv[1], str(kv[0])))[0][0]


class ConcatList(MonoidAggregator):
    def prepare(self, v):
        return list(v)

    def plus(self, a, b):
        return a + b


class UnionSet(MonoidAggregator):
    def prepare(self, v):
        return set(v)

    def plus(self, a, b):
        return a | b

    def present(self, a):
        return None if a is None else sorted(a)


class UnionMap(MonoidAggregator):
    """Merge maps, combining shared keys with an element aggregator
    (reference aggregators/Maps.scala)."""

    def __init__(self, element: Optional[MonoidAggregator] = None):
        self.element = element or LastValue()

    def prepare(self, v):
        return {k: self.element.prepare(x) for k, x in dict(v).items()
                if x is not None}

    def plus(self, a, b):
        out = dict(a)
        for k, x in b.items():
            out[k] = self.element.plus(out[k], x) if k in out else x
        return out

    def present(self, a):
        if a is None:
            return None
        return {k: self.element.present(x) for k, x in a.items()}


class LastValue(MonoidAggregator):
    """Keep the rightmost value (events are time-ordered by the reader;
    reference LastAggregator, TimeBasedAggregator.scala)."""

    def plus(self, a, b):
        return b


class FirstValue(MonoidAggregator):
    def plus(self, a, b):
        return a


class GeoMidpoint(MonoidAggregator):
    """Geographic midpoint of (lat, lon, acc) triples (reference
    Geolocation union semantics)."""

    def prepare(self, v):
        lat, lon = np.radians(float(v[0])), np.radians(float(v[1]))
        acc = float(v[2]) if len(v) > 2 else 0.0
        return (np.cos(lat) * np.cos(lon), np.cos(lat) * np.sin(lon),
                np.sin(lat), acc, 1)

    def plus(self, a, b):
        return tuple(x + y for x, y in zip(a, b))

    def present(self, a):
        if a is None or a[4] == 0:
            return None
        x, y, z, acc, n = a
        x, y, z = x / n, y / n, z / n
        hyp = np.hypot(x, y)
        return [float(np.degrees(np.arctan2(z, hyp))),
                float(np.degrees(np.arctan2(y, x))), acc / n]


def default_aggregator(ft: Type[FeatureType]) -> MonoidAggregator:
    """Per-type defaults (reference MonoidAggregatorDefaults.scala)."""
    if issubclass(ft, (Date, DateTime)):
        return MaxAgg()                       # latest event time
    if issubclass(ft, Binary):
        return LogicalOr()
    if issubclass(ft, (RealNN, Real, Integral)) or issubclass(ft, OPNumeric):
        return Sum()
    if issubclass(ft, Geolocation):
        return GeoMidpoint()
    if issubclass(ft, (MultiPickList,)) or issubclass(ft, OPSet):
        return UnionSet()
    if issubclass(ft, (TextList, DateList)) or issubclass(ft, OPList):
        return ConcatList()
    if issubclass(ft, OPMap):
        return UnionMap()
    if issubclass(ft, PickList):
        return ModeAgg()
    if issubclass(ft, Text):
        return ConcatText()
    return LastValue()


class CutOffTime:
    """Event-time cutoff separating predictor history from response window
    (reference aggregators/CutOffTime.scala)."""

    def __init__(self, kind: str, cutoff_ms: Optional[int] = None):
        self.kind = kind
        self.cutoff_ms = cutoff_ms

    @staticmethod
    def unix_epoch(ms: int) -> "CutOffTime":
        return CutOffTime("UnixEpoch", int(ms))

    @staticmethod
    def days_ago(days: int, now_ms: Optional[int] = None) -> "CutOffTime":
        now = int(_time.time() * 1000) if now_ms is None else int(now_ms)
        return CutOffTime("DaysAgo", now - days * _DAY_MS)

    @staticmethod
    def no_cutoff() -> "CutOffTime":
        return CutOffTime("NoCutoff", None)
