"""Histogram engine: one tree-growth primitive for in-core, streaming, mesh.

``build_node_hist`` produces (node, feature, bin) sufficient statistics for
histogram tree growth behind one contract with three backends:

=============  ==========================  ==================================
backend        selected when               implementation
=============  ==========================  ==================================
``xla``        device arrays (default      K-blocked one-hot einsum with
               off-TPU, or pallas          pinned combine order
               disabled)                   (`kernels._hist_xla_pinned`)
``pallas``     device arrays on TPU with   VMEM one-hot expansion kernel
               TG_TREE_PALLAS unset/1      (`kernels._hist_pallas`)
``host``       numpy inputs or             flat-index ``np.bincount``,
               ``backend="host"``          bit-equal to StreamingGBT's
                                           legacy inline block (`host`)
=============  ==========================  ==================================

Determinism: the xla backend's K row blocks (K = TG_HIST_SHARDS, default 8)
and explicit pairwise combine make the contraction's floating-point result a
pinned expression — the same bits single-device and with rows sharded over a
mesh 'data' axis. The fused sweep path activates `engine_mesh` around its
program traces so the blocks carry 'data'-axis sharding constraints; tree
sweeps are then bit-identical across topologies the way linear families
already were (docs/trees.md).

Env knobs: TG_HIST_SHARDS (pinned block count, default 8; 0/1 → plain
einsum), TG_HIST_BACKEND (force ``xla``/``pallas``; overrides
TG_TREE_PALLAS). Both are read at trace time.

Chaos: ``chaos_gate(family)`` is the host-side ``hist.build`` fault site —
the fused sweep dispatcher calls it once per tree-family program dispatch,
and a raise there quarantines that family exactly like
``validator.family_fit`` (typed error, NaN placeholder, other families keep
racing). Divergence from the fault-free baseline is allowed
(``bit_equal=False``): the quarantined family's metrics are gone, so the
winner may legitimately differ.
"""
from __future__ import annotations

from contextlib import contextmanager, nullcontext
from typing import Optional, Sequence

import numpy as np

from .host import bin_codes_host, build_node_hist_host, node_stat_sums
from .kernels import (_ENGINE_MESH, _hist_shards, _make, current_engine_mesh,
                      hist_matmul, node_hist_matmul, pinned_row_sum)

__all__ = [
    "build_hist", "build_node_hist", "bin_codes_host", "chaos_gate",
    "node_stat_sums",
    "clear_engine_caches", "current_engine_mesh", "engine_mesh",
    "engine_probe", "hist_matmul", "node_hist_matmul", "pinned_row_sum",
]


@contextmanager
def engine_mesh(mesh):
    """Activate ``mesh`` as the engine's sharding target for the duration of
    the block. Must wrap the *trace* (the first call of a jitted fit /
    fused program, and any re-trace such as AOT export) — the kernels read
    the context at trace time, like their env knobs."""
    token = _ENGINE_MESH.set(mesh)
    try:
        yield
    finally:
        _ENGINE_MESH.reset(token)


def build_hist(codes, A, n_bins: int, exact: bool = False):
    """Flat-stat histogram build: hist[a, f·nb + b] = Σ_s A[s,a]·1[codes=b].

    The engine entry point for callers that fold node structure into the
    stat columns themselves (`models/trees.py` `_grow_tree`, diagonal leaf
    sums). See `kernels.hist_matmul` for the full contract.
    """
    return hist_matmul(codes, A, n_bins, exact=exact)


def build_node_hist(codes, node, stats: Sequence, n_bins: int, *,
                    n_nodes: int = 1, stride: int = 1, mesh=None,
                    backend: Optional[str] = None):
    """(node, feature, bin) sufficient statistics — the one tree-growth
    primitive shared by in-core growers, StreamingGBT, and the mesh sweep.

    Device backends (jax inputs): ``codes`` (S, d) int32 row-major bin
    codes, ``node`` (S, T) int32 current slot per tree (values < 0 never
    match), ``stats``: k arrays (S, T) of per-tree row statistics,
    ``stride``: slot-id multiplier (2 = heap left-children). Returns
    (k, n_nodes, T, d, n_bins) f32 on device.

    Host backend (numpy inputs or ``backend="host"``): ``codes`` (d, n)
    int64 feature-major from `bin_codes_host` (feature-major on purpose —
    the bincount traversal order, and so the f64 sums bit for bit, depend
    on it), ``node`` (n,) int64, ``stats``: k entries each ``None``
    (unweighted count) or (n,) f64 weights; ``stride`` must be 1. Returns
    (k, n_nodes, d, n_bins) f64 — no tree axis, streamed growth is
    single-tree per pass.

    ``mesh``: shard the build's row blocks over that mesh's 'data' axis
    (equivalent to tracing under `engine_mesh`; the fused sweep path uses
    the context form).
    """
    if backend not in (None, "host", "xla", "pallas"):
        raise ValueError(f"unknown histogram backend {backend!r}")
    if backend == "host" or (backend is None and isinstance(codes, np.ndarray)
                             and codes.dtype.kind in "iu"
                             and isinstance(node, np.ndarray)):
        if stride != 1:
            raise ValueError("host histogram backend is stride-1 only")
        return build_node_hist_host(codes, node, stats, n_bins, n_nodes)
    import jax.numpy as jnp
    ctx = engine_mesh(mesh) if mesh is not None else nullcontext()
    with ctx:
        flat = node_hist_matmul(codes, node, list(stats), n_nodes, n_bins,
                                stride=stride)
    k = len(stats)
    T = node.shape[1]
    d = codes.shape[1]
    return flat.reshape(k, n_nodes, T, d, n_bins)


def chaos_gate(family_name: str) -> None:
    """Fault site ``hist.build`` — fires before a tree family's histogram
    programs dispatch in the fused sweep; a raise quarantines the family
    (robustness/faults.py three-way table, docs/robustness.md)."""
    from ..robustness import faults
    faults.inject("hist.build", key=family_name)


def clear_engine_caches() -> None:
    """Drop the engine's own caches (the lru factory of custom_vmap
    contractions). Traced jit programs are unaffected — this exists so the
    per-test no-leak fixture can bound cross-test state."""
    _make.cache_clear()


def engine_probe() -> dict:
    """Invariant probe for the `oracles` no-leak check: the mesh context
    must be None between dispatches (a leaked context would silently shard
    the next single-device trace) and the factory cache stays bounded."""
    return {
        "mesh_ctx": current_engine_mesh(),
        "factory_cache": _make.cache_info().currsize,
        "shards": _hist_shards(),
    }
