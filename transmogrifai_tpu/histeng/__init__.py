"""Unified sharded histogram engine — one tree-growth primitive for
in-core growers (models/trees.py), StreamingGBT (streaming/model.py), and
the fused mesh sweep (impl/tuning/validators.py). See docs/trees.md."""
from .engine import (bin_codes_host, build_hist, build_node_hist, chaos_gate,
                     clear_engine_caches, current_engine_mesh, engine_mesh,
                     engine_probe, hist_matmul, node_hist_matmul,
                     node_stat_sums, pinned_row_sum)

__all__ = [
    "bin_codes_host", "build_hist", "build_node_hist", "chaos_gate",
    "clear_engine_caches", "current_engine_mesh", "engine_mesh",
    "engine_probe", "hist_matmul", "node_hist_matmul", "node_stat_sums",
    "pinned_row_sum",
]
