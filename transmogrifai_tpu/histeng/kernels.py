"""Histogram-engine kernels: fused one-hot histogram matmul for tree growth.

The inner loop of histogram tree building (models/trees.py `_grow_tree`) is

    hist[a, f*nb + b] = sum_s A[s, a] * 1[codes[s, f] == b]

i.e. a matmul of per-row statistics A (S, B) against the bin one-hot matrix
(S, d*nb). XLA has to *materialize* that one-hot in HBM — 256 MB at the
65k-row split-search sample with d=64, nb=32 — and stream it back in for
every tree level of every config in the sweep. This kernel instead reads only
the int32 bin codes (S, d) — 64x less HBM traffic — and expands the one-hot
tile-by-tile in VMEM, feeding the MXU directly (the "fuse elementwise into
matmul" pattern the XLA fusion engine cannot do across a dot operand).

Replaces the JNI/native histogram plumbing of the reference's XGBoost
dependency (reference: SURVEY §2.9, ml.dmlc:xgboost4j C++ core) with a
TPU-native kernel.

Layout notes
- In-kernel the one-hot is built *bin-major* — `oh[s, b*D + f]` — because
  Mosaic can `pltpu.repeat` along lanes but not reshape (S, d, nb) → (S,
  d*nb); the cheap bin-major → feature-major permute happens outside on the
  (B, d*nb) result.
- Grid is (B blocks, D blocks, S blocks), S innermost: each (b, d) output
  block accumulates over the whole row axis before moving on.
- vmap (RF trees, GBT classes, selector configs) flattens the batch into
  extra A columns via a custom_vmap rule — one wide kernel call per tree
  level for the entire sweep, which is exactly the MXU-friendly shape.

Pinned reduction (mesh determinism)
- The XLA contraction runs as a *K-blocked* batched einsum over row blocks
  followed by an explicit fixed-order pairwise tree-combine in f32
  (`_tree_combine`). K = TG_HIST_SHARDS (default 8) is the same whether the
  program runs on one device or with rows sharded over a mesh 'data' axis —
  per-block partials are shape-identical local work either way, and the
  cross-block combine is a pinned expression rather than an
  order-unspecified `psum`, so mesh tree sweeps are bit-identical to
  single-device ones (docs/trees.md, "Determinism").
- When an engine mesh context is active (``engine.engine_mesh``), the
  blocked operands and partials carry ``with_sharding_constraint`` over the
  'data' axis so the per-block GEMMs stay shard-local.

Fallback: on non-TPU backends (CPU test mesh, virtual-device dry runs) the
same contraction runs as the blocked XLA one-hot einsum.

NOTE: `_use_pallas()` / `_interpret()` read TG_TREE_PALLAS / TG_HIST_BACKEND
and the backend at *trace time* inside jitted tree fits — once a shape is
traced, flipping the env var has no effect for that shape until the jit
caches are cleared (`jax.clear_caches()`), which tests that toggle the flag
must do. The pallas path (TPU single-device) does not use the K-blocked
contraction; force TG_TREE_PALLAS=0 when bit-equality across topologies is
required (see docs/trees.md).
"""
from __future__ import annotations

import math
import os
from functools import lru_cache

import jax
import jax.numpy as jnp

_BLK_S = 1024   # rows per tile

#: beyond this many stat columns the one-hot re-expansion per column block
#: outweighs the saved HBM traffic — fall back to the XLA contraction
#: (empirically: RF's 1600-wide flattened tree batch regressed 11%)
_HIST_PALLAS_MAX_B = 1024
_BLK_B = 128    # stat columns per tile


def _use_pallas() -> bool:
    forced = os.environ.get("TG_HIST_BACKEND", "")
    if forced == "xla":
        return False
    if forced == "pallas":
        return True
    env = os.environ.get("TG_TREE_PALLAS", "")
    if env in ("0", "false"):
        return False
    if env in ("1", "true"):
        return True
    return jax.default_backend() in ("tpu",)


def _interpret() -> bool:
    """Run the kernels in pallas interpret mode off-TPU (CI coverage of the
    kernel logic itself; forced via TG_TREE_PALLAS=1 on CPU)."""
    return jax.default_backend() != "tpu"


def _hist_shards() -> int:
    """K, the pinned row-block count of the XLA contraction (TG_HIST_SHARDS,
    default 8). 0/1 disables blocking — plain single-einsum contraction,
    the pre-engine numerics."""
    try:
        k = int(os.environ.get("TG_HIST_SHARDS", "8"))
    except ValueError:
        k = 8
    return max(1, k)


def _tile_lanes(x, repeats: int):
    """``[x, x, …]`` concatenated ``repeats`` times along lanes (axis 1).

    Mosaic's RepeatOp — what ``pltpu.repeat`` lowers to ON TPU — tiles the
    whole vector, and every kernel lane layout here is built on that. But
    jax 0.4.36+ registers a generic lowering for the same primitive that is
    ELEMENT-WISE ``jnp.repeat`` — so in interpret mode (CPU CI) the lanes
    came back permuted and every kernel test silently compared bin-major
    against feature-major garbage. Keep the hardware op on TPU; emulate the
    tile semantics with an explicit concatenate everywhere else."""
    if _interpret():
        return jnp.concatenate([x] * repeats, axis=1)
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.repeat(x, repeats, axis=1)


def _pad_to(x: int, m: int) -> int:
    return -(-x // m) * m


# --------------------------------------------------------------------------
# Engine mesh context: set by the fused sweep path (validators) around the
# trace of a mesh program so the blocked contraction can pin its row blocks
# to the 'data' axis. Read at TRACE time, like _use_pallas().
# --------------------------------------------------------------------------

import contextvars as _contextvars

_ENGINE_MESH = _contextvars.ContextVar("tg_histeng_mesh", default=None)


def current_engine_mesh():
    """The mesh the histogram engine should shard row blocks over, or None."""
    return _ENGINE_MESH.get()


def _data_spec(mesh, ndim: int):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec("data", *([None] * (ndim - 1))))


def _tree_combine(parts: jnp.ndarray) -> jnp.ndarray:
    """Fixed-order pairwise tree reduction over axis 0, exact f32 adds.

    The combine is an explicit expression — (p0+p1)+(p2+p3) … — so its
    floating-point result is pinned by construction: the same bits on one
    device and on a mesh, unlike `psum`/plain `.sum(0)` whose grouping the
    compiler may re-associate across topologies."""
    while parts.shape[0] > 1:
        h = parts.shape[0] // 2
        s = parts[0:2 * h:2] + parts[1:2 * h:2]
        if parts.shape[0] % 2:
            s = jnp.concatenate([s, parts[2 * h:]], axis=0)
        parts = s
    return parts[0]


def _hist_xla(codes: jnp.ndarray, A: jnp.ndarray, n_bins: int,
              exact: bool = False) -> jnp.ndarray:
    """Reference contraction, feature-major (B, d*nb) f32 — single einsum,
    no row blocking (used when TG_HIST_SHARDS<=1 or S<K)."""
    S, d = codes.shape
    dt = jnp.float32 if exact else jnp.bfloat16
    oh = (codes[:, :, None] == jnp.arange(n_bins, dtype=jnp.int32)
          ).astype(dt).reshape(S, d * n_bins)
    # materialize the one-hot: left fusible, XLA lowers the contraction as a
    # pred-kernel convolution in some surrounding graphs (~6x slower than
    # the plain einsum on v5e — seen in the tree grower's level loop)
    oh = jax.lax.optimization_barrier(oh)
    kw = ({"precision": jax.lax.Precision.HIGHEST} if exact else {})
    return jnp.einsum("sa,sf->af", A.astype(dt), oh,
                      preferred_element_type=jnp.float32, **kw)


def _hist_xla_pinned(codes: jnp.ndarray, A: jnp.ndarray, n_bins: int,
                     exact: bool = False) -> jnp.ndarray:
    """K-blocked contraction with pinned combine order (see module notes).

    Rows are sentinel-padded to a multiple of K (code == n_bins matches no
    one-hot lane; the padded stat rows are zero), reshaped to (K, S/K, ·),
    contracted as one batched einsum into per-block f32 partials, and
    combined by `_tree_combine`. Under an active engine mesh context the
    blocked axes carry sharding constraints over 'data'."""
    K = _hist_shards()
    S, d = codes.shape
    if K <= 1 or S < K:
        return _hist_xla(codes, A, n_bins, exact)
    B = A.shape[1]
    Sp = _pad_to(S, K)
    codes_p = jnp.pad(codes.astype(jnp.int32), ((0, Sp - S), (0, 0)),
                      constant_values=n_bins)
    A_p = jnp.pad(A, ((0, Sp - S), (0, 0)))
    cb = codes_p.reshape(K, Sp // K, d)
    ab = A_p.reshape(K, Sp // K, B)
    mesh = current_engine_mesh()
    if mesh is not None:
        cb = jax.lax.with_sharding_constraint(cb, _data_spec(mesh, 3))
        ab = jax.lax.with_sharding_constraint(ab, _data_spec(mesh, 3))
    dt = jnp.float32 if exact else jnp.bfloat16
    oh = (cb[:, :, :, None] == jnp.arange(n_bins, dtype=jnp.int32)
          ).astype(dt).reshape(K, Sp // K, d * n_bins)
    oh = jax.lax.optimization_barrier(oh)
    kw = ({"precision": jax.lax.Precision.HIGHEST} if exact else {})
    parts = jnp.einsum("ksa,ksf->kaf", ab.astype(dt), oh,
                       preferred_element_type=jnp.float32, **kw)
    if mesh is not None:
        parts = jax.lax.with_sharding_constraint(parts, _data_spec(mesh, 3))
    return _tree_combine(parts)


def pinned_row_sum(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Fixed-order K-blocked sum over ``axis`` (rows), bit-identical across
    mesh topologies — the non-histogram companion to `_hist_xla_pinned` for
    the few direct row reductions in tree fits (GBT's base-score f0)."""
    K = _hist_shards()
    x = jnp.moveaxis(x, axis, 0)
    S = x.shape[0]
    if K <= 1 or S < K:
        return x.sum(0)
    Sp = _pad_to(S, K)
    xp = jnp.pad(x, ((0, Sp - S),) + ((0, 0),) * (x.ndim - 1))
    xb = xp.reshape(K, Sp // K, *x.shape[1:])
    mesh = current_engine_mesh()
    if mesh is not None:
        xb = jax.lax.with_sharding_constraint(xb, _data_spec(mesh, xb.ndim))
    parts = xb.sum(1)
    if mesh is not None:
        xb_spec = _data_spec(mesh, parts.ndim)
        parts = jax.lax.with_sharding_constraint(parts, xb_spec)
    return _tree_combine(parts)


def _hist_pallas(codes: jnp.ndarray, A: jnp.ndarray,
                 n_bins: int, exact: bool = False) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, d = codes.shape
    B = A.shape[1]
    # feature blocking: either one full-width block (any lane count whose
    # nb*d_pad is a multiple of 128) or 128-wide feature tiles — Mosaic
    # requires block dims be 128-divisible or span the whole array axis
    d_mult = 128 // math.gcd(n_bins, 128)
    d_pad = _pad_to(d, d_mult)
    if d_pad > 128:
        d_pad = _pad_to(d_pad, 128)
        blk_d = 128
    else:
        blk_d = d_pad
    lanes = n_bins * blk_d
    # keep the VMEM one-hot tile (blk_s × lanes bf16) around ≤4 MB
    blk_s = _BLK_S
    while blk_s > 256 and blk_s * lanes * 2 > (4 << 20):
        blk_s //= 2
    s_pad = _pad_to(S, blk_s)
    b_pad = _pad_to(B, 8)
    blk_b = min(_BLK_B, b_pad)
    if b_pad > _BLK_B:
        b_pad = _pad_to(b_pad, _BLK_B)

    # sentinel bin n_bins never matches a one-hot lane → padded rows/features
    # contribute exact zeros
    codes_p = jnp.pad(codes.astype(jnp.int32),
                      ((0, s_pad - S), (0, d_pad - d)),
                      constant_values=n_bins)
    A_p = jnp.pad(A.astype(jnp.float32), ((0, s_pad - S), (0, b_pad - B)))

    def kernel(codes_ref, a_ref, out_ref):
        s = pl.program_id(2)
        rep = _tile_lanes(codes_ref[:], n_bins)             # (blk_s, nb*blk_d)
        b_iota = (jax.lax.broadcasted_iota(jnp.int32, (blk_s, lanes), 1)
                  // blk_d)
        if exact:
            # f32 stat operands, HIGHEST precision: leaf-value reductions
            # (served predictions) must not round to bf16
            oh = (rep == b_iota).astype(jnp.float32)
            part = jnp.dot(a_ref[:].T, oh,
                           preferred_element_type=jnp.float32,
                           precision=jax.lax.Precision.HIGHEST)
        else:
            oh = (rep == b_iota).astype(jnp.bfloat16)
            part = jnp.dot(a_ref[:].T.astype(jnp.bfloat16), oh,
                           preferred_element_type=jnp.float32)

        @pl.when(s == 0)
        def _():
            out_ref[:] = part

        @pl.when(s > 0)
        def _():
            out_ref[:] += part

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b_pad, d_pad * n_bins), jnp.float32),
        grid=(b_pad // blk_b, d_pad // blk_d, s_pad // blk_s),
        in_specs=[
            pl.BlockSpec((blk_s, blk_d), lambda b, f, s: (s, f)),
            pl.BlockSpec((blk_s, blk_b), lambda b, f, s: (s, b)),
        ],
        out_specs=pl.BlockSpec((blk_b, lanes), lambda b, f, s: (b, f)),
        interpret=_interpret(),
    )(codes_p, A_p)

    # bin-major blocks → feature-major flat, then strip padding
    nbd = d_pad // blk_d
    out = (out.reshape(b_pad, nbd, n_bins, blk_d)
           .transpose(0, 1, 3, 2)
           .reshape(b_pad, d_pad * n_bins))
    return out[:B, :d * n_bins]


@lru_cache(maxsize=None)
def _make(n_bins: int, exact: bool = False):
    from jax.custom_batching import custom_vmap

    @custom_vmap
    def hist(codes, A):
        if _use_pallas() and A.shape[1] <= _HIST_PALLAS_MAX_B:
            return _hist_pallas(codes, A, n_bins, exact)
        return _hist_xla_pinned(codes, A, n_bins, exact)

    @hist.def_vmap
    def _rule(axis_size, in_batched, codes, A):
        codes_b, A_b = in_batched
        if codes_b:
            # not a shape this framework produces (codes are shared across
            # the sweep); keep semantics anyway
            out = jax.lax.map(lambda ca: hist(ca[0], ca[1]), (codes, A))
            return out, True
        S, B = A.shape[1], A.shape[2]
        flat = A.transpose(1, 0, 2).reshape(S, axis_size * B)
        out = hist(codes, flat)                     # (V*B, d*nb)
        return out.reshape(axis_size, B, -1), True

    return hist


def hist_matmul(codes: jnp.ndarray, A: jnp.ndarray,
                n_bins: int, exact: bool = False) -> jnp.ndarray:
    """hist[a, f*n_bins + b] = Σ_s A[s, a]·1[codes[s, f] == b], f32.

    codes: (S, d) int bin indices in [0, n_bins); values == n_bins are
    allowed and contribute nothing (sentinel). A: (S, B) per-row statistics.
    Returns (B, d*n_bins) feature-major. Batches over leading axes of A
    (vmap) by widening B — the whole sweep becomes one kernel call.
    ``exact``: keep the stat operands f32 at HIGHEST precision (leaf-value
    reductions — served predictions must not round to bf16); growth
    histograms use the default bf16 operands by design.
    """
    return _make(n_bins, exact)(codes, A)


# ---------------------------------------------------------------------------
# Fused node-histogram: hist over (stat, slot, tree) lanes WITHOUT ever
# materializing the (S, k·Wl·T) masked-stat operand in HBM
# ---------------------------------------------------------------------------



def _t_pad128(T: int) -> int:
    """Tree-lane padding the node-hist kernel accepts: 32, 64, or a multiple
    of 128 (so a 128-lane output block covers whole trees × whole slots)."""
    if T <= 32:
        return 32
    if T <= 64:
        return 64
    return _pad_to(T, 128)


def _node_hist_xla(codes, node, sws, Wl_eff, n_bins, stride, k, exact=False):
    """Reference semantics: materialize the masked-stat operand and reuse the
    blocked hist contraction. node: (S, T_pad) int32 (pad -1); sws:
    (k, S, T_pad) stat-stacked. Returns (k·Wl_eff·T_pad, d·nb)."""
    S, T_pad = node.shape
    j = stride * jnp.arange(Wl_eff, dtype=jnp.int32)[None, :, None]
    n_oh = (node[:, None, :] == j).astype(sws.dtype)      # (S, Wl_eff, T_pad)
    A = jnp.concatenate(
        [n_oh * sws[ki][:, None, :] for ki in range(k)],
        axis=1).reshape(S, k * Wl_eff * T_pad)
    return _hist_xla_pinned(codes, A, n_bins, exact)



def node_hist_matmul(codes: jnp.ndarray, node: jnp.ndarray,
                     sw_list, Wl: int, n_bins: int,
                     stride: int = 1) -> jnp.ndarray:
    """hist[(k, j, t), f·nb + b] = Σ_s sw_k[s,t] · 1[node[s,t] == stride·j]
    · 1[codes[s,f] == b] — the tree-growth histogram as one XLA contraction
    over the masked-stat operand (the (S, k·Wl·T) A_cat is materialized;
    a pallas kernel that expanded it tile-by-tile in VMEM measured SLOWER
    at every production shape, sweep and refit alike — retired with its
    measurement table to docs/experiments/node_hist_pallas.py).

    codes: (S, d) int32 bin codes; node: (S, T) int32 current slot per tree
    (values < 0 never match); sw_list: k arrays (S, T) of per-tree stats;
    ``stride``: slot-id multiplier (2 = heap left-children, 1 = chain slots).
    Returns (k·Wl·T, d·n_bins) f32, lane = (k·Wl + j)·T + t — identical
    layout to ``hist_matmul(codes, A_cat, n_bins)`` with A_cat built k-major
    then j-major.
    """
    S, d = codes.shape
    T = node.shape[1]
    k = len(sw_list)
    # lane padding to 32/64/128-multiple tree lanes is KEPT on purpose: it
    # predates the retired pallas kernel's constraints but MEASURES faster
    # on v5e — removing it dropped the default-grid sweep from ~108 to
    # ~88 fits/sec (the A_cat expansion + contraction tile better on
    # 128-aligned minor dims than on T=54-ragged ones, logical-FLOP
    # savings notwithstanding)
    T_pad = _t_pad128(T)
    rep = max(1, 128 // T_pad)
    Wl_eff = max(Wl, rep)
    if Wl_eff * T_pad % 128:
        Wl_eff = -(-Wl_eff // rep) * rep
    node_p = (jnp.pad(node, ((0, 0), (0, T_pad - T)), constant_values=-1)
              if T_pad != T else node)
    sws = jnp.stack(
        [jnp.pad(sw.astype(jnp.float32), ((0, 0), (0, T_pad - T)))
         if T_pad != T else sw.astype(jnp.float32) for sw in sw_list])
    out = _node_hist_xla(codes, node_p, sws, Wl_eff, n_bins, stride, k)
    if Wl_eff != Wl or T_pad != T:
        out = (out.reshape(k, Wl_eff, T_pad, d * n_bins)[:, :Wl, :T]
               .reshape(k * Wl * T, d * n_bins))
    return out


# Routing no longer lives here: the per-level decision-bit contraction
# (route_matmul) was replaced by the feature-select matmul inside
# models/trees.py _grow_tree (1/n_bins-th the FLOPs) and by the fused
# multi-level descent kernel in ops/forest.py for full-data passes.
