"""Host (numpy) histogram backend — StreamingGBT's per-level stat pass.

This is the exact flat-bincount arithmetic that used to live inline in
``streaming/model.py``: one flat (node, feature, bin) index per cell, then
one ``np.bincount`` per statistic. Bit-equality with the legacy block is a
contract, not an accident — the flat index array is built feature-major
(d, n) and ravelled in the same order, so every weighted bincount
accumulates its f64 partial sums in the identical sequence
(tests/test_histeng.py pins this against a frozen copy of the old code).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def bin_codes_host(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Feature-major (d, n) int64 bin codes for host histogram builds.

    ``edges``: (d, nb-1) split thresholds (np.inf pads unused slots).
    Rows are compared in f64 — bit-consistent with the f64 thresholds the
    streamed descent routes by. Codes lie in [0, nb-1]; the matrix is kept
    feature-major because the host build's bincount traversal order (and
    therefore its f64 sums, bit for bit) depends on it.
    """
    d = edges.shape[0]
    Xt = np.ascontiguousarray(X.T, dtype=np.float64)
    codes = np.empty((d, Xt.shape[1]), dtype=np.int64)
    for j in range(d):
        codes[j] = np.searchsorted(edges[j], Xt[j], side="left")
    return codes


def build_node_hist_host(codes: np.ndarray, node: np.ndarray,
                         stats: Sequence[Optional[np.ndarray]],
                         n_bins: int, n_nodes: int) -> np.ndarray:
    """(k, n_nodes, d, n_bins) f64 sufficient statistics on host.

    ``codes``: (d, n) int64 from `bin_codes_host`; ``node``: (n,) int64
    current node per row; ``stats``: k entries, each ``None`` (unweighted
    count) or an (n,) f64 weight vector (residuals, squared residuals, …).
    One flat index for every (node, feature, bin) cell, then k bincounts
    total — the column-strided per-feature variant costs ~2× (cache-hostile
    reads and k·d small bincounts).
    """
    d, n = codes.shape
    flat = np.empty((d, n), dtype=np.int64)
    base = node * (d * n_bins)
    for j in range(d):
        np.add(base, j * n_bins + codes[j], out=flat[j])
    size = n_nodes * d * n_bins
    fl = flat.ravel()
    shape = (n_nodes, d, n_bins)
    out = np.empty((len(stats),) + shape, dtype=np.float64)
    for i, w in enumerate(stats):
        if w is None:
            out[i] = (np.bincount(fl, minlength=size)
                      .astype(np.float64).reshape(shape))
        else:
            out[i] = np.bincount(fl, weights=np.tile(w, d),
                                 minlength=size).reshape(shape)
    return out


def node_stat_sums(node: np.ndarray,
                   stats: Sequence[Optional[np.ndarray]],
                   n_nodes: int) -> list:
    """Per-node f64 sums without the feature/bin axes — the leaf-value
    pass (n_bins=1, d=1 degenerate histogram). Same ``stats`` convention
    as `build_node_hist_host`: ``None`` → unweighted count."""
    return [np.bincount(node, minlength=n_nodes).astype(np.float64)
            if w is None
            else np.bincount(node, weights=w, minlength=n_nodes)
            for w in stats]
