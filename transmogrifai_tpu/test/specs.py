"""Published contract specs for stage tests.

Mirrors the reference's shared test bases that ship in the main source set so
USERS can spec their own stages (reference:
features/src/main/scala/com/salesforce/op/test/OpTransformerSpec.scala,
OpEstimatorSpec.scala:55-142, OpPipelineStageSpec): subclass, provide the
wired stage + input table (+ optionally the expected output values), and the
base class asserts the stage contract — naming, typing, columnar/row-dual
parity, and persistence round-trip.

Usage::

    class TestMyStage(OpTransformerSpec):
        @classmethod
        def build(cls):
            f = FeatureBuilder.Real("x").extract_field().as_predictor()
            stage = MyStage().set_input(f)
            table = FeatureTable.from_columns({"x": (Real, [1.0, None])})
            expected = [2.0, None]          # or None to skip value check
            return stage, table, expected
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np
import pytest

from ..stages.base import Estimator, OpPipelineStage, Transformer
from ..table import Column, FeatureTable


def _cell(col: Column, i: int) -> Any:
    valid = col.mask is None or bool(np.asarray(col.mask)[i])
    if not valid:
        return None
    v = np.asarray(col.values)[i]
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v.item() if isinstance(v, np.generic) else v


def _norm_empty(v: Any) -> Any:
    """The FeatureTable's missing semantics conflate empty collections with
    null (table._is_missing) — row duals may surface []/{} where the columnar
    path surfaces None; both mean "empty" (reference SomeValue). Applied ONLY
    to the row/columnar parity comparison — explicit expected values stay
    strict."""
    if isinstance(v, (list, set, dict, tuple)) and len(v) == 0:
        return None
    return v


def _approx_equal(a: Any, b: Any) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(_approx_equal(x, y) for x, y in zip(a, b))
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_approx_equal(a[k], b[k]) for k in a)
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return bool(np.isclose(float(a), float(b), rtol=1e-5, atol=1e-6))
    return a == b


class _SpecBase:
    """Shared plumbing; subclasses implement build()."""

    @classmethod
    def build(cls) -> Tuple[OpPipelineStage, FeatureTable, Optional[Sequence[Any]]]:
        raise NotImplementedError("spec subclasses must implement build()")

    @pytest.fixture(scope="class")
    def spec(self):
        return type(self).build()

    # -- stage contract (reference OpPipelineStageSpec) ----------------------
    def test_stage_naming(self, spec):
        stage, table, _ = spec
        assert stage.uid.startswith(type(stage).__name__ + "_")
        out = stage.get_output()
        assert out.origin_stage is stage
        assert out.feature_type is stage.output_type

    def test_input_wiring(self, spec):
        stage, table, _ = spec
        assert stage.input_features, "spec stage must have wired inputs"
        for f in stage.input_features:
            assert f.name in table.column_names, (
                f"input feature '{f.name}' missing from the spec table")


class OpTransformerSpec(_SpecBase):
    """Contract for transformers (reference OpTransformerSpec): columnar
    transform matches expected values, and the row dual agrees with the
    columnar path on every row."""

    #: set False for stages whose row dual legitimately differs (e.g. needs
    #: batch-level metadata)
    check_row_parity: bool = True

    def _transformer(self, spec) -> Tuple[Transformer, FeatureTable]:
        stage, table, _ = spec
        assert isinstance(stage, Transformer), "use OpEstimatorSpec for estimators"
        return stage, table

    def test_transform(self, spec):
        stage, table = self._transformer(spec)
        _, _, expected = spec
        out = stage.transform_column(table)
        assert len(out) == len(table)
        if expected is not None:
            got = [_cell(out, i) for i in range(len(out))]
            for i, (g, e) in enumerate(zip(got, expected)):
                assert _approx_equal(g, e), f"row {i}: got {g!r}, want {e!r}"

    def test_row_columnar_parity(self, spec):
        stage, table = self._transformer(spec)
        if not self.check_row_parity:
            pytest.skip("row parity disabled for this stage")
        out = stage.transform_column(table)
        for i in range(len(table)):
            row_val = _norm_empty(stage.transform_row(table.row(i)))
            col_val = _norm_empty(_cell(out, i))
            assert _approx_equal(row_val, col_val), (
                f"row {i}: transform_row={row_val!r} vs columnar={col_val!r}")

    def test_serialization_round_trip(self, spec):
        stage, table = self._transformer(spec)
        from ..persistence import _Arrays, stage_from_json, stage_to_json
        arrays = _Arrays()
        desc = stage_to_json(stage, arrays)
        loaded = stage_from_json(desc, arrays.store)
        unresolved = [k for k, v in vars(loaded).items()
                      if type(v).__name__ in ("Unresolved", "_StageRef")]
        if unresolved:
            pytest.skip(f"stage holds unserializable state {unresolved} "
                        f"(resolved from the workflow at load time)")
        loaded.input_features = stage.input_features
        loaded._output_feature = stage._output_feature
        out1 = stage.transform_column(table)
        out2 = loaded.transform_column(table)
        for i in range(len(table)):
            a, b = _cell(out1, i), _cell(out2, i)
            assert _approx_equal(a, b), (
                f"row {i} after round-trip: {a!r} != {b!r}")


class OpEstimatorSpec(_SpecBase):
    """Contract for estimators (reference OpEstimatorSpec:55-142): fit yields
    a Transformer that reuses the estimator's uid/output feature, and the
    fitted model passes the transformer contract."""

    check_row_parity: bool = True

    @pytest.fixture(scope="class")
    def fitted(self, spec):
        stage, table, _ = spec
        assert isinstance(stage, Estimator), "use OpTransformerSpec for transformers"
        return stage.fit(table)

    def test_fit_returns_transformer(self, spec, fitted):
        stage, table, _ = spec
        assert isinstance(fitted, Transformer)
        assert fitted.uid == stage.uid, "model must reuse the estimator uid"
        assert fitted.get_output() is stage.get_output()

    def test_model_transform(self, spec, fitted):
        stage, table, expected = spec
        out = fitted.transform_column(table)
        assert len(out) == len(table)
        if expected is not None:
            got = [_cell(out, i) for i in range(len(out))]
            for i, (g, e) in enumerate(zip(got, expected)):
                assert _approx_equal(g, e), f"row {i}: got {g!r}, want {e!r}"

    def test_model_row_parity(self, spec, fitted):
        if not self.check_row_parity:
            pytest.skip("row parity disabled for this stage")
        stage, table, _ = spec
        out = fitted.transform_column(table)
        for i in range(len(table)):
            row_val = _norm_empty(fitted.transform_row(table.row(i)))
            col_val = _norm_empty(_cell(out, i))
            assert _approx_equal(row_val, col_val), (
                f"row {i}: transform_row={row_val!r} vs columnar={col_val!r}")
