from .specs import OpEstimatorSpec, OpTransformerSpec  # noqa: F401
