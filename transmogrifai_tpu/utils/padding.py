"""Row-count bucketing for compile reuse.

Under jit every distinct row count is a distinct XLA program; AutoML
pipelines naturally produce many (raw n, balanced n, per-fold n, holdout n),
which would recompile every fit/predict/metric kernel per size. Padding the
row axis up to a coarse geometric grid of bucket sizes makes shapes repeat,
so each program compiles once and is reused across stages, datasets and
runs (with the persistent compilation cache). Padding rows carry zero weight
/ False masks everywhere, so results are bit-identical to unpadded runs.

The grid: multiples of 256 on a ~1.19× geometric ladder (4 buckets per
octave) — at most ~19% wasted FLOPs, ~26 distinct shapes across 1k → 1B
rows.
"""
from __future__ import annotations

import math

_STEPS_PER_OCTAVE = 4
_MIN_BUCKET = 256


def row_bucket(n: int) -> int:
    """Smallest bucket ≥ n on the geometric grid."""
    if n <= _MIN_BUCKET:
        return _MIN_BUCKET
    k = math.ceil(_STEPS_PER_OCTAVE * math.log2(n / _MIN_BUCKET))
    b = _MIN_BUCKET * 2 ** (k / _STEPS_PER_OCTAVE)
    b = int(math.ceil(b / _MIN_BUCKET) * _MIN_BUCKET)
    while b < n:  # guard rounding
        b += _MIN_BUCKET
    return b


def bucket_for(n: int, multiple_of: int = 1) -> int:
    """Bucket ≥ n that is also a multiple of ``multiple_of`` (mesh shards)."""
    b = row_bucket(n)
    if multiple_of > 1:
        b = int(math.ceil(b / multiple_of) * multiple_of)
    return b


def pad_rows(values, n_pad: int):
    """Pad a column's row axis (axis 0) to ``n_pad`` with neutral filler:
    zeros for numeric dtypes, ``None`` for object columns. The shared
    padding primitive for every row-align site (mesh equal-sharding,
    bucket padding) — pad rows must always pair with a False validity mask
    (see :func:`padded_valid_mask`), never carry weight."""
    import numpy as np
    v = np.asarray(values)
    pad = n_pad - v.shape[0]
    if pad <= 0:
        return v
    if v.dtype == object:
        filler = np.full((pad,) + v.shape[1:], None, dtype=object)
    else:
        filler = np.zeros((pad,) + v.shape[1:], v.dtype)
    return np.concatenate([v, filler])


def padded_bytes(n_pad: int, trailing=(), itemsize: int = 4,
                 with_mask: bool = True) -> int:
    """Device bytes one padded column stages: ``n_pad`` rows of
    ``trailing``-shaped ``itemsize`` cells, plus the 1-byte-per-row bool
    validity mask the traced programs always materialize. The shared
    prediction primitive of the device-memory observatory
    (observability/devicemem.py) — prediction must use the exact same
    bucket arithmetic the dispatch sites pad with, or the predicted
    bytes drift from what XLA actually allocates."""
    cells = 1
    for x in trailing:
        cells *= int(x)
    total = int(n_pad) * cells * int(itemsize)
    if with_mask:
        total += int(n_pad)
    return total


def padded_valid_mask(mask, n: int, n_pad: int):
    """(n_pad,) bool validity mask: the original mask (or all-valid when
    ``mask`` is None) over the first ``n`` rows, False over the pad."""
    import numpy as np
    m = np.zeros(n_pad, bool)
    m[:n] = True if mask is None else np.asarray(mask)
    return m
