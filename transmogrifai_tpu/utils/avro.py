"""Pure-python Avro Object Container File reader/writer.

The reference treats Avro as its first-class data format (reference:
readers/.../AvroReaders.scala:134, utils/.../io/avro/AvroInOut.scala:186,
and OpWorkflowModel.saveScores writing scores as avro,
OpWorkflowModel.scala:376-421). This environment ships no avro library, so
the container format (spec 1.11: header, deflate/null codecs, zigzag-varint
primitives) is implemented here directly — records in/out are plain dicts.

Supported schema types: null, boolean, int, long, float, double, bytes,
string, record, enum, array, map, fixed, and unions thereof (the subset the
reference's datasets and score files use). Logical types pass through as
their underlying primitives.
"""
from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterator, List, Optional, Sequence

MAGIC = b"Obj\x01"
_SYNC_SIZE = 16


# ---------------------------------------------------------------------------
# Primitive codecs (Avro spec: zigzag varints, little-endian IEEE floats)
# ---------------------------------------------------------------------------

def _read_long(buf: BinaryIO) -> int:
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _write_long(out: io.BytesIO, value: int) -> None:
    value = (value << 1) ^ (value >> 63)
    while True:
        if value & ~0x7F:
            out.write(bytes([(value & 0x7F) | 0x80]))
            value >>= 7
        else:
            out.write(bytes([value]))
            return


def _read_bytes(buf: BinaryIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated bytes")
    return data


def _write_bytes(out: io.BytesIO, data: bytes) -> None:
    _write_long(out, len(data))
    out.write(data)


# ---------------------------------------------------------------------------
# Schema-driven datum codec
# ---------------------------------------------------------------------------

def _named(schema: Any) -> Any:
    """Normalize a schema node to a dict with a 'type' key or a string."""
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return schema
    return schema


def _read_datum(buf: BinaryIO, schema: Any, names: Dict[str, Any]) -> Any:
    schema = _named(schema)
    if isinstance(schema, list):                       # union
        idx = _read_long(buf)
        return _read_datum(buf, schema[idx], names)
    if isinstance(schema, dict):
        t = schema["type"]
    else:
        t = schema
    if t in names and not isinstance(schema, dict):
        return _read_datum(buf, names[t], names)
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return _read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return _read_bytes(buf)
    if t == "string":
        return _read_bytes(buf).decode("utf-8")
    if t == "record":
        out = {}
        for f in schema["fields"]:
            out[f["name"]] = _read_datum(buf, f["type"], names)
        return out
    if t == "enum":
        return schema["symbols"][_read_long(buf)]
    if t == "fixed":
        return buf.read(schema["size"])
    if t == "array":
        items: List[Any] = []
        while True:
            n = _read_long(buf)
            if n == 0:
                break
            if n < 0:
                _read_long(buf)  # block byte size, unused
                n = -n
            for _ in range(n):
                items.append(_read_datum(buf, schema["items"], names))
        return items
    if t == "map":
        out = {}
        while True:
            n = _read_long(buf)
            if n == 0:
                break
            if n < 0:
                _read_long(buf)
                n = -n
            for _ in range(n):
                k = _read_bytes(buf).decode("utf-8")
                out[k] = _read_datum(buf, schema["values"], names)
        return out
    raise ValueError(f"unsupported avro type {t!r}")


def _union_index(schema: List[Any], value: Any) -> int:
    def kind(s):
        return s if isinstance(s, str) else s.get("type")

    if value is None:
        for i, s in enumerate(schema):
            if kind(s) == "null":
                return i
    prefer = {bool: ("boolean",), int: ("long", "int", "double", "float"),
              float: ("double", "float"), str: ("string", "enum"),
              bytes: ("bytes", "fixed"), dict: ("record", "map"),
              list: ("array",)}
    for want in prefer.get(type(value), ()):
        for i, s in enumerate(schema):
            if kind(s) == want:
                return i
    for i, s in enumerate(schema):
        if kind(s) != "null":
            return i
    raise ValueError(f"no union branch for {value!r} in {schema}")


def _write_datum(out: io.BytesIO, schema: Any, value: Any,
                 names: Dict[str, Any]) -> None:
    schema = _named(schema)
    if isinstance(schema, list):
        idx = _union_index(schema, value)
        _write_long(out, idx)
        _write_datum(out, schema[idx], value, names)
        return
    t = schema["type"] if isinstance(schema, dict) else schema
    if t in names and not isinstance(schema, dict):
        _write_datum(out, names[t], value, names)
        return
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        _write_long(out, int(value))
    elif t == "float":
        out.write(struct.pack("<f", float(value)))
    elif t == "double":
        out.write(struct.pack("<d", float(value)))
    elif t == "bytes":
        _write_bytes(out, bytes(value))
    elif t == "string":
        _write_bytes(out, str(value).encode("utf-8"))
    elif t == "record":
        for f in schema["fields"]:
            _write_datum(out, f["type"], value.get(f["name"]), names)
    elif t == "enum":
        _write_long(out, schema["symbols"].index(value))
    elif t == "fixed":
        out.write(bytes(value))
    elif t == "array":
        if value:
            _write_long(out, len(value))
            for v in value:
                _write_datum(out, schema["items"], v, names)
        _write_long(out, 0)
    elif t == "map":
        if value:
            _write_long(out, len(value))
            for k, v in value.items():
                _write_bytes(out, str(k).encode("utf-8"))
                _write_datum(out, schema["values"], v, names)
        _write_long(out, 0)
    else:
        raise ValueError(f"unsupported avro type {t!r}")


def _collect_names(schema: Any, names: Dict[str, Any]) -> None:
    if isinstance(schema, list):
        for s in schema:
            _collect_names(s, names)
    elif isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed") and "name" in schema:
            names[schema["name"]] = schema
            ns = schema.get("namespace")
            if ns:
                names[f"{ns}.{schema['name']}"] = schema
        if t == "record":
            for f in schema.get("fields", []):
                _collect_names(f["type"], names)
        elif t == "array":
            _collect_names(schema.get("items"), names)
        elif t == "map":
            _collect_names(schema.get("values"), names)


# ---------------------------------------------------------------------------
# Container files
# ---------------------------------------------------------------------------

def read_avro(path: str) -> Iterator[Dict[str, Any]]:
    """Iterate records of an Avro Object Container File."""
    with open(path, "rb") as fh:
        if fh.read(4) != MAGIC:
            raise ValueError(f"{path}: not an avro container file")
        meta_schema = {"type": "map", "values": "bytes"}
        meta = _read_datum(fh, meta_schema, {})
        schema = json.loads(meta["avro.schema"].decode("utf-8"))
        codec = meta.get("avro.codec", b"null").decode("utf-8")
        if codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {codec!r}")
        names: Dict[str, Any] = {}
        _collect_names(schema, names)
        fh.read(_SYNC_SIZE)
        while True:
            head = fh.read(1)
            if not head:
                return
            fh.seek(-1, os.SEEK_CUR)
            try:
                count = _read_long(fh)
            except EOFError:
                return
            block = _read_bytes(fh)
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            buf = io.BytesIO(block)
            for _ in range(count):
                yield _read_datum(buf, schema, names)
            fh.read(_SYNC_SIZE)


def schema_of_records(records: Sequence[Dict[str, Any]],
                      name: str = "Row") -> Dict[str, Any]:
    """Infer a nullable-union record schema from dict records."""
    fields: Dict[str, set] = {}
    for r in records:
        for k, v in r.items():
            kinds = fields.setdefault(k, set())
            if v is None:
                kinds.add("null")
            elif isinstance(v, bool):
                kinds.add("boolean")
            elif isinstance(v, int):
                kinds.add("long")
            elif isinstance(v, float):
                kinds.add("double")
            else:
                kinds.add("string")
    out_fields = []
    for k, kinds in fields.items():
        kinds.discard("null")
        if kinds == {"long"}:
            t: Any = "long"
        elif kinds <= {"long", "double"} and kinds:
            t = "double"
        elif kinds == {"boolean"}:
            t = "boolean"
        else:
            t = "string"
        out_fields.append({"name": k, "type": ["null", t]})
    return {"type": "record", "name": name, "fields": out_fields}


def write_avro(path: str, records: Sequence[Dict[str, Any]],
               schema: Optional[Dict[str, Any]] = None,
               codec: str = "deflate", sync_interval: int = 4000) -> None:
    """Write records to an Avro Object Container File."""
    if schema is None:
        schema = schema_of_records(records)
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    names: Dict[str, Any] = {}
    _collect_names(schema, names)
    sync = os.urandom(_SYNC_SIZE)
    with open(path, "wb") as fh:
        fh.write(MAGIC)
        head = io.BytesIO()
        meta = {"avro.schema": json.dumps(schema).encode("utf-8"),
                "avro.codec": codec.encode("utf-8")}
        _write_datum(head, {"type": "map", "values": "bytes"}, meta, {})
        fh.write(head.getvalue())
        fh.write(sync)
        i = 0
        while i < len(records):
            chunk = records[i:i + sync_interval]
            i += sync_interval
            block = io.BytesIO()
            for r in chunk:
                _write_datum(block, schema, r, names)
            payload = block.getvalue()
            if codec == "deflate":
                co = zlib.compressobj(9, zlib.DEFLATED, -15)
                payload = co.compress(payload) + co.flush()
            frame = io.BytesIO()
            _write_long(frame, len(chunk))
            _write_bytes(frame, payload)
            fh.write(frame.getvalue())
            fh.write(sync)
        if not records:
            frame = io.BytesIO()
            _write_long(frame, 0)
            _write_bytes(frame, b"")
            fh.write(frame.getvalue())
            fh.write(sync)
