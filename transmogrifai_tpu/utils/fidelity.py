"""Sweep-fidelity switch.

Round 5's throughput defaults stack three approximations on the CV sweep
(32k-row metric estimates, an 8k-row split-search sample, 16-tree RF /
12-round GBT ranking ensembles). Each is fidelity-gated individually, but
their COMBINED delta vs the round-4 defaults is what a caller comparing
selections across versions actually experiences (docs/benchmarks.md "Sweep
fidelity"). ``TG_SWEEP_FIDELITY=round4`` restores the round-4 defaults in
one switch: ``max_eval_rows=65536``, split-search sample 16384, no
ensemble caps. The env is read at call time so tests (and long-lived
processes) can flip it without re-importing.
"""
from __future__ import annotations

import os

ENV = "TG_SWEEP_FIDELITY"

#: round-4 default values restored by the switch
ROUND4_MAX_EVAL_ROWS = 65536
ROUND4_SWEEP_HIST_SAMPLE = 16384


def round4_defaults() -> bool:
    """True when the process opted into round-4 fidelity defaults."""
    return os.environ.get(ENV, "").lower() in ("round4", "r4", "high")
