"""ctypes binding for the native streaming histogram (see
native/streaming_histogram.cpp — the TPU build's equivalent of the reference's
Java StreamingHistogram, utils/.../stats/StreamingHistogram.java, plus its
Scala enrichment RichStreamingHistogram.scala).

The shared library compiles on first use with g++ into
``transmogrifai_tpu/native/_build/`` and is cached by source mtime. If no
toolchain is available the pure-numpy fallback implements the same algorithm
(slower, same results) so the framework never hard-depends on the compiler.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_HERE, "native", "streaming_histogram.cpp")
_BUILD_DIR = os.path.join(_HERE, "native", "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libstreaminghist.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            os.makedirs(_BUILD_DIR, exist_ok=True)
            if (not os.path.exists(_LIB_PATH)
                    or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     _SRC, "-o", _LIB_PATH],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_LIB_PATH)
            lib.sh_create.restype = ctypes.c_void_p
            lib.sh_create.argtypes = [ctypes.c_int]
            lib.sh_free.argtypes = [ctypes.c_void_p]
            lib.sh_update.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
            lib.sh_update_weighted.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
            lib.sh_merge.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.sh_num_bins.restype = ctypes.c_int64
            lib.sh_num_bins.argtypes = [ctypes.c_void_p]
            lib.sh_total.restype = ctypes.c_double
            lib.sh_total.argtypes = [ctypes.c_void_p]
            lib.sh_min.restype = ctypes.c_double
            lib.sh_min.argtypes = [ctypes.c_void_p]
            lib.sh_max.restype = ctypes.c_double
            lib.sh_max.argtypes = [ctypes.c_void_p]
            lib.sh_get_bins.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double)]
            lib.sh_sum.restype = ctypes.c_double
            lib.sh_sum.argtypes = [ctypes.c_void_p, ctypes.c_double]
            lib.sh_uniform.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_double)]
            lib.sh_load.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
                ctypes.c_double, ctypes.c_double, ctypes.c_double]
            _lib = lib
        except Exception:
            _lib_failed = True
        return _lib


def native_available() -> bool:
    return _build_lib() is not None


def _compress_bins(bins: List[Tuple[float, float]], max_bins: int,
                   ) -> List[Tuple[float, float]]:
    """SPDT compaction on a sorted (centroid, mass) list: repeatedly merge
    the leftmost smallest-gap adjacent pair until <= max_bins remain —
    the exact loop the native compress() runs, kept in python so merges
    involving a python-fallback sketch stay bit-identical to native."""
    if len(bins) <= max_bins:
        return list(bins)
    centers = np.asarray([p for p, _ in bins], dtype=np.float64)
    masses = np.asarray([m for _, m in bins], dtype=np.float64)
    centers = centers.tolist()
    masses = masses.tolist()
    while len(centers) > max_bins:
        gaps = np.diff(np.asarray(centers))
        j = int(np.argmin(gaps))            # leftmost minimum, like C++
        m = masses[j] + masses[j + 1]
        centers[j] = (centers[j] * masses[j] + centers[j + 1] * masses[j + 1]) / m
        masses[j] = m
        del centers[j + 1], masses[j + 1]
    return list(zip(centers, masses))


class StreamingHistogram:
    """Fixed-size mergeable histogram sketch (SPDT algorithm)."""

    def __init__(self, max_bins: int = 100):
        self.max_bins = max(2, int(max_bins))
        self._lib = _build_lib()
        if self._lib is not None:
            self._h = ctypes.c_void_p(self._lib.sh_create(self.max_bins))
        else:
            self._bins: List[Tuple[float, float]] = []  # (centroid, mass)
            self._total = 0.0
            self._min = np.inf
            self._max = -np.inf

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.sh_free(h)
            self._h = None

    # -- updates -------------------------------------------------------------
    def update(self, values: Sequence[float]) -> "StreamingHistogram":
        xs = np.ascontiguousarray(np.asarray(values, dtype=np.float64).ravel())
        if self._lib is not None:
            self._lib.sh_update(
                self._h, xs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                xs.shape[0])
        else:
            for x in xs:
                if not np.isnan(x):
                    self._py_insert(float(x), 1.0)
        return self

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """SPDT Merge: union of bins, one compaction pass (the paper's Merge
        procedure — NOT per-point insertion, whose repeated compactions give
        a different, impl-dependent sketch). All four native/python impl
        pairings run the identical algorithm, and the result always honors
        the bin-count + mass invariants (``_check_invariants``): this is
        what makes chunk folds reproducible across hosts with and without a
        C++ toolchain."""
        if not isinstance(other, StreamingHistogram):
            raise TypeError(f"cannot merge {type(other).__name__} into a "
                            "StreamingHistogram")
        total = self.total + other.total
        lo = min(self.min, other.min)
        hi = max(self.max, other.max)
        if self._lib is not None and other._lib is not None:
            self._lib.sh_merge(self._h, other._h)
        else:
            # dst-first stable union by centroid — byte-identical to the
            # native std::merge + coalesce + compress sequence
            merged = sorted(self.bins() + other.bins(), key=lambda b: b[0])
            out: List[Tuple[float, float]] = []
            for p, m in merged:
                if out and out[-1][0] == p:
                    out[-1] = (p, out[-1][1] + m)
                else:
                    out.append((p, m))
            bins = _compress_bins(out, self.max_bins)
            self._load_state(bins, total, lo, hi)
        self._check_invariants(total)
        return self

    def _check_invariants(self, expected_total: Optional[float] = None) -> None:
        """Merge/restore postconditions: bounded bins, conserved mass,
        min/max bracket every centroid. A violated invariant means fold
        order could change quantile outputs — fail loudly instead."""
        nb = len(self.bins())
        if nb > self.max_bins:
            raise AssertionError(
                f"histogram holds {nb} bins > max_bins={self.max_bins}")
        if expected_total is not None and self.total != expected_total:
            raise AssertionError(
                f"merge lost mass: total={self.total!r} != "
                f"expected {expected_total!r}")
        if nb and (self.bins()[0][0] < self.min
                   or self.bins()[-1][0] > self.max):
            raise AssertionError("centroids escaped the [min, max] range")

    def _load_state(self, bins: List[Tuple[float, float]], total: float,
                    lo: float, hi: float) -> None:
        """Replace this sketch's entire state (sorted bins expected)."""
        if self._lib is not None:
            centers = np.ascontiguousarray([p for p, _ in bins], dtype=np.float64)
            masses = np.ascontiguousarray([m for _, m in bins], dtype=np.float64)
            self._lib.sh_load(
                self._h,
                centers.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                masses.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                centers.shape[0], float(total), float(lo), float(hi))
        else:
            self._bins = list(bins)
            self._total = total
            self._min = lo
            self._max = hi

    # -- serialization + canonical multiset merge (streaming folds) ----------
    def to_state(self) -> dict:
        """Checkpointable state: plain arrays, impl-independent. Restoring
        via :meth:`from_state` is bit-exact on either backend."""
        bins = self.bins()
        return {
            "max_bins": np.int64(self.max_bins),
            "centers": np.asarray([p for p, _ in bins], dtype=np.float64),
            "masses": np.asarray([m for _, m in bins], dtype=np.float64),
            "total": np.float64(self.total),
            "min": np.float64(self.min),
            "max": np.float64(self.max),
        }

    @classmethod
    def from_state(cls, state: dict) -> "StreamingHistogram":
        h = cls(int(state["max_bins"]))
        bins = list(zip(np.asarray(state["centers"], dtype=np.float64).tolist(),
                        np.asarray(state["masses"], dtype=np.float64).tolist()))
        h._load_state(bins, float(state["total"]),
                      float(state["min"]), float(state["max"]))
        h._check_invariants(float(state["total"]))
        return h

    @classmethod
    def merged(cls, hists: Sequence["StreamingHistogram"],
               max_bins: Optional[int] = None) -> "StreamingHistogram":
        """Canonical N-way merge: a pure function of the *multiset* of input
        bins, so any permutation of ``hists`` produces a bit-identical
        sketch (the associativity/commutativity contract chunk folds need —
        pairwise :meth:`merge` compacts intermediates, so its result
        depends on grouping). Bins sort by (centroid, mass), equal
        centroids coalesce in that canonical order, and ONE compaction pass
        runs at the end. Computed host-side in pure python for
        impl-independence; the result loads into whichever backend is
        available."""
        hists = list(hists)
        mb = max_bins if max_bins is not None else max(
            [h.max_bins for h in hists], default=2)
        centers: List[float] = []
        masses: List[float] = []
        for h in hists:
            for p, m in h.bins():
                centers.append(p)
                masses.append(m)
        ca = np.asarray(centers, dtype=np.float64)
        ma = np.asarray(masses, dtype=np.float64)
        order = np.lexsort((ma, ca))
        out: List[Tuple[float, float]] = []
        for i in order.tolist():
            p, m = float(ca[i]), float(ma[i])
            if out and out[-1][0] == p:
                out[-1] = (p, out[-1][1] + m)
            else:
                out.append((p, m))
        total = float(ma[order].sum()) if ma.size else 0.0
        lo = min([h.min for h in hists], default=np.inf)
        hi = max([h.max for h in hists], default=-np.inf)
        result = cls(mb)
        result._load_state(_compress_bins(out, mb), total, lo, hi)
        result._check_invariants(total)
        return result

    # -- queries -------------------------------------------------------------
    def bins(self) -> List[Tuple[float, float]]:
        if self._lib is not None:
            n = self._lib.sh_num_bins(self._h)
            centers = np.zeros(n, dtype=np.float64)
            masses = np.zeros(n, dtype=np.float64)
            if n:
                self._lib.sh_get_bins(
                    self._h,
                    centers.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    masses.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            return list(zip(centers.tolist(), masses.tolist()))
        return list(self._bins)

    @property
    def total(self) -> float:
        if self._lib is not None:
            return self._lib.sh_total(self._h)
        return self._total

    @property
    def min(self) -> float:
        if self._lib is not None:
            return self._lib.sh_min(self._h)
        return self._min

    @property
    def max(self) -> float:
        if self._lib is not None:
            return self._lib.sh_max(self._h)
        return self._max

    def sum(self, b: float) -> float:
        """Estimated count of points <= b (paper's Sum procedure)."""
        if self._lib is not None:
            return self._lib.sh_sum(self._h, float(b))
        return self._py_sum(float(b))

    def quantile(self, q: float) -> float:
        """Approximate q-quantile via binary search over sum()."""
        if self.total == 0:
            return float("nan")
        target = q * self.total
        lo, hi = self.min, self.max
        for _ in range(60):
            mid = (lo + hi) / 2.0
            if self.sum(mid) < target:
                lo = mid
            else:
                hi = mid
        return (lo + hi) / 2.0

    def uniform(self, num_bins: int) -> np.ndarray:
        """num_bins-1 interior boundaries of equal-mass bins (Uniform)."""
        if num_bins < 2 or self.total == 0:
            return np.zeros(0, dtype=np.float64)
        if self._lib is not None:
            out = np.zeros(num_bins - 1, dtype=np.float64)
            self._lib.sh_uniform(
                self._h, num_bins,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            return out
        return np.array([self.quantile(k / num_bins)
                         for k in range(1, num_bins)])

    def density(self, boundaries: np.ndarray) -> np.ndarray:
        """Mass per interval given sorted boundary edges (len B+1) → (B,)."""
        sums = np.array([self.sum(b) for b in boundaries])
        return np.diff(sums)

    # -- pure-python fallback (same algorithm) -------------------------------
    def _py_insert(self, x: float, w: float) -> None:
        import bisect
        ps = [p for p, _ in self._bins]
        i = bisect.bisect_left(ps, x)
        if i < len(self._bins) and self._bins[i][0] == x:
            self._bins[i] = (x, self._bins[i][1] + w)
        else:
            self._bins.insert(i, (x, w))
        self._total += w
        self._min = min(self._min, x)
        self._max = max(self._max, x)
        while len(self._bins) > self.max_bins:
            gaps = [self._bins[j + 1][0] - self._bins[j][0]
                    for j in range(len(self._bins) - 1)]
            j = int(np.argmin(gaps))
            (p1, m1), (p2, m2) = self._bins[j], self._bins[j + 1]
            m = m1 + m2
            self._bins[j:j + 2] = [((p1 * m1 + p2 * m2) / m, m)]

    def _py_sum(self, b: float) -> float:
        bins = self._bins
        if not bins:
            return 0.0
        if b >= bins[-1][0]:
            if self._max > bins[-1][0] and b < self._max:
                frac = (b - bins[-1][0]) / (self._max - bins[-1][0])
                return self._total - bins[-1][1] / 2.0 + bins[-1][1] / 2.0 * frac
            return self._total
        if b < bins[0][0]:
            if self._min < bins[0][0] and b >= self._min:
                frac = (b - self._min) / (bins[0][0] - self._min)
                return bins[0][1] / 2.0 * frac
            return 0.0
        i = 0
        while i + 1 < len(bins) and bins[i + 1][0] <= b:
            i += 1
        s = sum(m for _, m in bins[:i]) + bins[i][1] / 2.0
        if i + 1 < len(bins) and bins[i + 1][0] > bins[i][0]:
            pi, mi = bins[i]
            pj, mj = bins[i + 1]
            frac = (b - pi) / (pj - pi)
            mb = mi + (mj - mi) * frac
            s += (mi + mb) / 2.0 * frac
        return s
