"""ctypes binding for the native streaming histogram (see
native/streaming_histogram.cpp — the TPU build's equivalent of the reference's
Java StreamingHistogram, utils/.../stats/StreamingHistogram.java, plus its
Scala enrichment RichStreamingHistogram.scala).

The shared library compiles on first use with g++ into
``transmogrifai_tpu/native/_build/`` and is cached by source mtime. If no
toolchain is available the pure-numpy fallback implements the same algorithm
(slower, same results) so the framework never hard-depends on the compiler.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_HERE, "native", "streaming_histogram.cpp")
_BUILD_DIR = os.path.join(_HERE, "native", "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libstreaminghist.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            os.makedirs(_BUILD_DIR, exist_ok=True)
            if (not os.path.exists(_LIB_PATH)
                    or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     _SRC, "-o", _LIB_PATH],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_LIB_PATH)
            lib.sh_create.restype = ctypes.c_void_p
            lib.sh_create.argtypes = [ctypes.c_int]
            lib.sh_free.argtypes = [ctypes.c_void_p]
            lib.sh_update.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
            lib.sh_update_weighted.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
            lib.sh_merge.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.sh_num_bins.restype = ctypes.c_int64
            lib.sh_num_bins.argtypes = [ctypes.c_void_p]
            lib.sh_total.restype = ctypes.c_double
            lib.sh_total.argtypes = [ctypes.c_void_p]
            lib.sh_min.restype = ctypes.c_double
            lib.sh_min.argtypes = [ctypes.c_void_p]
            lib.sh_max.restype = ctypes.c_double
            lib.sh_max.argtypes = [ctypes.c_void_p]
            lib.sh_get_bins.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double)]
            lib.sh_sum.restype = ctypes.c_double
            lib.sh_sum.argtypes = [ctypes.c_void_p, ctypes.c_double]
            lib.sh_uniform.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_double)]
            _lib = lib
        except Exception:
            _lib_failed = True
        return _lib


def native_available() -> bool:
    return _build_lib() is not None


class StreamingHistogram:
    """Fixed-size mergeable histogram sketch (SPDT algorithm)."""

    def __init__(self, max_bins: int = 100):
        self.max_bins = max(2, int(max_bins))
        self._lib = _build_lib()
        if self._lib is not None:
            self._h = ctypes.c_void_p(self._lib.sh_create(self.max_bins))
        else:
            self._bins: List[Tuple[float, float]] = []  # (centroid, mass)
            self._total = 0.0
            self._min = np.inf
            self._max = -np.inf

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.sh_free(h)
            self._h = None

    # -- updates -------------------------------------------------------------
    def update(self, values: Sequence[float]) -> "StreamingHistogram":
        xs = np.ascontiguousarray(np.asarray(values, dtype=np.float64).ravel())
        if self._lib is not None:
            self._lib.sh_update(
                self._h, xs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                xs.shape[0])
        else:
            for x in xs:
                if not np.isnan(x):
                    self._py_insert(float(x), 1.0)
        return self

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        if self._lib is not None and other._lib is not None:
            self._lib.sh_merge(self._h, other._h)
        else:
            for p, m in other.bins():
                self._py_insert(p, m)
            self._min = min(self._min, other.min)
            self._max = max(self._max, other.max)
        return self

    # -- queries -------------------------------------------------------------
    def bins(self) -> List[Tuple[float, float]]:
        if self._lib is not None:
            n = self._lib.sh_num_bins(self._h)
            centers = np.zeros(n, dtype=np.float64)
            masses = np.zeros(n, dtype=np.float64)
            if n:
                self._lib.sh_get_bins(
                    self._h,
                    centers.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    masses.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            return list(zip(centers.tolist(), masses.tolist()))
        return list(self._bins)

    @property
    def total(self) -> float:
        if self._lib is not None:
            return self._lib.sh_total(self._h)
        return self._total

    @property
    def min(self) -> float:
        if self._lib is not None:
            return self._lib.sh_min(self._h)
        return self._min

    @property
    def max(self) -> float:
        if self._lib is not None:
            return self._lib.sh_max(self._h)
        return self._max

    def sum(self, b: float) -> float:
        """Estimated count of points <= b (paper's Sum procedure)."""
        if self._lib is not None:
            return self._lib.sh_sum(self._h, float(b))
        return self._py_sum(float(b))

    def quantile(self, q: float) -> float:
        """Approximate q-quantile via binary search over sum()."""
        if self.total == 0:
            return float("nan")
        target = q * self.total
        lo, hi = self.min, self.max
        for _ in range(60):
            mid = (lo + hi) / 2.0
            if self.sum(mid) < target:
                lo = mid
            else:
                hi = mid
        return (lo + hi) / 2.0

    def uniform(self, num_bins: int) -> np.ndarray:
        """num_bins-1 interior boundaries of equal-mass bins (Uniform)."""
        if num_bins < 2 or self.total == 0:
            return np.zeros(0, dtype=np.float64)
        if self._lib is not None:
            out = np.zeros(num_bins - 1, dtype=np.float64)
            self._lib.sh_uniform(
                self._h, num_bins,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            return out
        return np.array([self.quantile(k / num_bins)
                         for k in range(1, num_bins)])

    def density(self, boundaries: np.ndarray) -> np.ndarray:
        """Mass per interval given sorted boundary edges (len B+1) → (B,)."""
        sums = np.array([self.sum(b) for b in boundaries])
        return np.diff(sums)

    # -- pure-python fallback (same algorithm) -------------------------------
    def _py_insert(self, x: float, w: float) -> None:
        import bisect
        ps = [p for p, _ in self._bins]
        i = bisect.bisect_left(ps, x)
        if i < len(self._bins) and self._bins[i][0] == x:
            self._bins[i] = (x, self._bins[i][1] + w)
        else:
            self._bins.insert(i, (x, w))
        self._total += w
        self._min = min(self._min, x)
        self._max = max(self._max, x)
        while len(self._bins) > self.max_bins:
            gaps = [self._bins[j + 1][0] - self._bins[j][0]
                    for j in range(len(self._bins) - 1)]
            j = int(np.argmin(gaps))
            (p1, m1), (p2, m2) = self._bins[j], self._bins[j + 1]
            m = m1 + m2
            self._bins[j:j + 2] = [((p1 * m1 + p2 * m2) / m, m)]

    def _py_sum(self, b: float) -> float:
        bins = self._bins
        if not bins:
            return 0.0
        if b >= bins[-1][0]:
            if self._max > bins[-1][0] and b < self._max:
                frac = (b - bins[-1][0]) / (self._max - bins[-1][0])
                return self._total - bins[-1][1] / 2.0 + bins[-1][1] / 2.0 * frac
            return self._total
        if b < bins[0][0]:
            if self._min < bins[0][0] and b >= self._min:
                frac = (b - self._min) / (bins[0][0] - self._min)
                return bins[0][1] / 2.0 * frac
            return 0.0
        i = 0
        while i + 1 < len(bins) and bins[i + 1][0] <= b:
            i += 1
        s = sum(m for _, m in bins[:i]) + bins[i][1] / 2.0
        if i + 1 < len(bins) and bins[i + 1][0] > bins[i][0]:
            pi, mi = bins[i]
            pj, mj = bins[i + 1]
            frac = (b - pi) / (pj - pi)
            mb = mi + (mj - mi) * frac
            s += (mi + mb) / 2.0 * frac
        return s
