"""Build/version stamping for saved models (reference
utils/src/main/scala/com/salesforce/op/utils/version/VersionInfo.scala — git
sha + build time into model metadata)."""
from __future__ import annotations

import subprocess
import time
from functools import lru_cache
from typing import Dict

FRAMEWORK_VERSION = "0.1.0"


@lru_cache(maxsize=1)
def git_sha() -> str:
    try:
        import os
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def version_info() -> Dict[str, str]:
    return {
        "version": FRAMEWORK_VERSION,
        "gitSha": git_sha(),
        "savedAt": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
