"""ASCII table renderer (reference utils/.../table/Table.scala:156 — used by
summaryPretty/ModelInsights pretty printing)."""
from __future__ import annotations

from typing import Any, List, Optional, Sequence


def format_table(columns: Sequence[str], rows: Sequence[Sequence[Any]],
                 title: Optional[str] = None,
                 max_cell_width: int = 40) -> str:
    """Render rows as a boxed ASCII table.

    >>> print(format_table(["a", "b"], [[1, "x"]]))
    +---+---+
    | a | b |
    +---+---+
    | 1 | x |
    +---+---+
    """
    def cell(v: Any) -> str:
        s = "" if v is None else (f"{v:.6g}" if isinstance(v, float) else str(v))
        return s if len(s) <= max_cell_width else s[:max_cell_width - 1] + "…"

    head = [cell(c) for c in columns]
    body = [[cell(v) for v in row] for row in rows]
    ncol = max([len(head)] + [len(r) for r in body]) if (head or body) else 0
    head += [""] * (ncol - len(head))
    body = [r + [""] * (ncol - len(r)) for r in body]
    widths = [max([len(head[i])] + [len(r[i]) for r in body] + [1])
              for i in range(ncol)]
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"

    def line(cells: List[str], right_align: bool = False) -> str:
        parts = []
        for v, w in zip(cells, widths):
            parts.append(f" {v:>{w}} " if right_align and _num(v)
                         else f" {v:<{w}} ")
        return "|" + "|".join(parts) + "|"

    def _num(s: str) -> bool:
        try:
            float(s)
            return True
        except ValueError:
            return False

    out = []
    if title:
        width = len(sep)
        out.append(title.center(width).rstrip())
    out += [sep, line(head), sep]
    out += [line(r, right_align=True) for r in body]
    out.append(sep)
    return "\n".join(out)
