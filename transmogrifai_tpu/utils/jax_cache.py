"""Persistent XLA compilation cache setup.

First compilation of each jitted program costs seconds (tens of seconds on
remote-compile backends); the reference has no analog cost because Spark
plans interpret immediately. Enabling jax's persistent compilation cache
makes every run after the first skip straight to execution for unchanged
program shapes. Applied once, lazily, from the modules that first touch jax;
a user-set ``jax_compilation_cache_dir`` (or ``JAX_COMPILATION_CACHE_DIR``)
always wins.
"""
from __future__ import annotations

import os

_done = False


def ensure_compilation_cache() -> None:
    global _done
    if _done:
        return
    _done = True
    try:
        import jax
        if jax.config.jax_compilation_cache_dir:
            return  # user already configured one
        d = os.environ.get(
            "TRANSMOGRIFAI_TPU_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "transmogrifai_tpu", "jax"))
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cacheless operation is only slower, never wrong
