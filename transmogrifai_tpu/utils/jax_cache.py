"""Persistent XLA compilation cache setup.

First compilation of each jitted program costs seconds (tens of seconds on
remote-compile backends); the reference has no analog cost because Spark
plans interpret immediately. Enabling jax's persistent compilation cache
makes every run after the first skip straight to execution for unchanged
program shapes. Applied once, lazily, from the modules that first touch jax;
a user-set ``jax_compilation_cache_dir`` (or ``JAX_COMPILATION_CACHE_DIR``)
always wins.
"""
from __future__ import annotations

import os
import threading
from typing import Dict

_done = False

# -- compile-cache hit/miss accounting ---------------------------------------
# jax announces persistent-cache outcomes through its internal monitoring
# events ('/jax/compilation_cache/cache_hits' / 'cache_misses'); a
# best-effort listener folds them into plain process counters that
# StageProfiler.app_metrics() and observability.summarize() report, and that
# sweep spans diff to tag each family branch hit/miss. The monitoring module
# is private API — if it moves, the counters simply stay at zero.
_CACHE_EVENTS: Dict[str, int] = {"hits": 0, "misses": 0}
_listener_lock = threading.Lock()
_listener_done = False


def record_cache_event(hit: bool) -> None:
    """Count one compile-cache outcome (the listener's target; also the
    deterministic entry point for tests)."""
    _CACHE_EVENTS["hits" if hit else "misses"] += 1


def _install_listener() -> None:
    global _listener_done
    with _listener_lock:
        if _listener_done:
            return
        _listener_done = True
    try:
        from jax._src import monitoring

        def _on_event(event: str, **kw) -> None:
            if event == "/jax/compilation_cache/cache_hits":
                record_cache_event(True)
            elif event == "/jax/compilation_cache/cache_misses":
                record_cache_event(False)

        monitoring.register_event_listener(_on_event)
    except Exception:
        pass  # counters stay zero; never break compilation for telemetry


def cache_stats() -> Dict[str, int]:
    """Process-wide persistent compile-cache ``{"hits": n, "misses": n}``."""
    _install_listener()
    return dict(_CACHE_EVENTS)


def ensure_compilation_cache() -> None:
    global _done
    if _done:
        return
    _done = True
    _install_listener()
    try:
        import jax
        # partition-invariant counter-based threefry: the RF/GBT bootstrap
        # streams (models/trees.py jax.random calls inside sharded fit
        # programs) must generate the SAME bits whether the sweep runs on
        # one device or row-sharded over the mesh 'data' axis — the legacy
        # stream is not partition-stable and forces XLA to serialize the
        # generator. jax flipped this default back and forth across 0.4.x;
        # pin it (an explicit user/env setting still wins).
        if "JAX_THREEFRY_PARTITIONABLE" not in os.environ:
            jax.config.update("jax_threefry_partitionable", True)
        if jax.config.jax_compilation_cache_dir:
            return  # user already configured one
        d = os.environ.get(
            "TRANSMOGRIFAI_TPU_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "transmogrifai_tpu", "jax"))
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cacheless operation is only slower, never wrong
