"""ctypes binding for the native text kernels (native/text_ops.cpp).

Host-side replacement for the reference's executor-parallel JVM text path
(Lucene tokenization + Spark HashingTF — reference TextTokenizer.scala:196,
OPCollectionHashingVectorizer.scala:398). Token hashing is bit-identical to
the Python fallback (both are zlib crc32 over UTF-8 bytes); the fused
tokenize+hash path handles pure-ASCII documents natively and returns the
non-ASCII rows to the caller for the Unicode-aware Python tokenizer.

Compiled on first use with ``g++ -O2 -shared -lz`` into
``native/_build/libtextops.so`` (same lifecycle as the streaming histogram
library); without a toolchain every entry point degrades to pure Python.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_HERE, "native", "text_ops.cpp")
_BUILD_DIR = os.path.join(_HERE, "native", "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libtextops.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False

_I64P = ctypes.POINTER(ctypes.c_int64)
_F32P = ctypes.POINTER(ctypes.c_float)
_U8P = ctypes.POINTER(ctypes.c_uint8)


def _build_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            os.makedirs(_BUILD_DIR, exist_ok=True)
            if (not os.path.exists(_LIB_PATH)
                    or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     _SRC, "-o", _LIB_PATH, "-lz"],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_LIB_PATH)
            lib.tg_hash_tokens.argtypes = [
                ctypes.c_char_p, _I64P, ctypes.c_int64, _I64P,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, _F32P]
            lib.tg_tokenize_hash_count.argtypes = [
                ctypes.c_char_p, _I64P, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, _F32P, _U8P]
            _lib = lib
        except Exception:
            _lib_failed = True
        return _lib


def native_available() -> bool:
    return _build_lib() is not None


def hash_token_lists_native(
        token_lists: Sequence[Optional[Sequence[str]]], num_hashes: int,
        binary: bool = False) -> Optional[np.ndarray]:
    """(n, num_hashes) float32 token-count rows, or None when the native
    library is unavailable. Exact crc32 parity with the Python path."""
    lib = _build_lib()
    if lib is None:
        return None
    n = len(token_lists)
    enc: List[bytes] = []
    doc_starts = np.zeros(n + 1, dtype=np.int64)
    for i, toks in enumerate(token_lists):
        if toks:
            enc.extend(t.encode("utf-8") for t in toks)
        doc_starts[i + 1] = len(enc)
    tok_offs = np.zeros(len(enc) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in enc], out=tok_offs[1:])
    buf = b"".join(enc)
    out = np.zeros((n, num_hashes), dtype=np.float32)
    lib.tg_hash_tokens(
        buf, tok_offs.ctypes.data_as(_I64P), len(enc),
        doc_starts.ctypes.data_as(_I64P), n,
        np.int32(num_hashes), np.int32(1 if binary else 0),
        out.ctypes.data_as(_F32P))
    return out


def tokenize_hash_native(
        docs: Sequence[Optional[str]], num_hashes: int,
        min_token_length: int = 1, binary: bool = False):
    """Fused tokenize+hash for a document batch.

    Returns (counts (n, num_hashes) float32, needs_py bool (n,)) — rows
    flagged in needs_py are untouched zeros (non-ASCII or degenerate docs)
    and must be filled by the Python tokenizer path. Returns None when the
    native library is unavailable.
    """
    lib = _build_lib()
    if lib is None:
        return None
    n = len(docs)
    enc = [(d.encode("utf-8") if isinstance(d, str) else b"") for d in docs]
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(e) for e in enc], out=offs[1:])
    buf = b"".join(enc)
    out = np.zeros((n, num_hashes), dtype=np.float32)
    needs_py = np.zeros(n, dtype=np.uint8)
    lib.tg_tokenize_hash_count(
        buf, offs.ctypes.data_as(_I64P), n, np.int32(num_hashes),
        np.int32(min_token_length), np.int32(1 if binary else 0),
        out.ctypes.data_as(_F32P), needs_py.ctypes.data_as(_U8P))
    return out, needs_py.astype(bool)
