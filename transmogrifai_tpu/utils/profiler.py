"""Per-stage wall-clock profiling.

The analog of the reference's Spark-listener metrics collection (reference:
utils/.../spark/OpSparkListener.scala:55-110 — per-stage run time aggregated
into AppMetrics at app end, wired by OpWorkflowRunner.scala:139-154). Here the
scheduler itself times every fit/transform; ``jax.profiler`` traces can be
layered on top for device-level detail (start_trace/stop_trace around train).
"""
from __future__ import annotations

import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional


class StageProfiler:
    """Collects per-stage timings during fit/score (AppMetrics analog).

    Aggregates run forever in O(#stage classes) memory; raw per-op records are
    kept in a bounded ring so long-running streaming scorers don't grow
    without bound."""

    def __init__(self, max_records: int = 10_000):
        self.records: deque = deque(maxlen=max_records)
        self.app_start = time.time()
        #: monotonic epoch for span-compatible record timestamps
        self._epoch = time.perf_counter()
        self._total = 0.0
        self._count = 0
        self._by_stage: Dict[str, float] = {}
        self._by_layer: Dict[str, float] = {}
        self._by_op: Dict[str, float] = {}

    @contextmanager
    def track(self, stage: Any, op: str, layer: int = -1):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            secs = time.perf_counter() - t0
            name = type(stage).__name__
            self.records.append({
                "stage": name,
                "uid": getattr(stage, "uid", "?"),
                "op": op,
                "layer": layer,
                "seconds": secs,
                # microseconds since profiler construction — the span/chrome
                # timestamp of this op (see spans())
                "ts": (t0 - self._epoch) * 1e6,
            })
            self._total += secs
            self._count += 1
            self._by_stage[name] = self._by_stage.get(name, 0.0) + secs
            self._by_op[op] = self._by_op.get(op, 0.0) + secs
            lk = f"layer_{layer}" if layer >= 0 else "unlayered"
            self._by_layer[lk] = self._by_layer.get(lk, 0.0) + secs

    def spans(self) -> List[Dict[str, Any]]:
        """The records ring as Chrome-trace complete events (``ph: "X"``,
        microsecond ``ts``/``dur``) — droppable straight into a trace-event
        document alongside the observability tracer's output. Bounded by the
        ring: only the newest ``maxlen`` ops survive a long run."""
        import os
        pid = os.getpid()
        return [{
            "name": f"{r['stage']}.{r['op']}",
            "ph": "X",
            "ts": r.get("ts", 0.0),
            "dur": r["seconds"] * 1e6,
            "pid": pid,
            "tid": 0,
            "args": {"uid": r["uid"], "op": r["op"], "layer": r["layer"]},
        } for r in self.records]

    # -- aggregation (reference AppMetrics, OpSparkListener.scala:55-110) ----
    def app_metrics(self) -> Dict[str, Any]:
        # accumulated in track() (NOT derived from the bounded records ring,
        # which would undercount runs past its maxlen)
        by_layer = self._by_layer
        from ..observability import devicemem as _devicemem
        from ..observability import ledger as _ledger
        from .jax_cache import cache_stats
        led = _ledger.ledger()
        out = {
            "appDurationSecs": time.time() - self.app_start,
            "stageSecondsTotal": self._total,
            "byStage": dict(sorted(self._by_stage.items(), key=lambda kv: -kv[1])),
            "byOp": dict(self._by_op),
            "byLayer": dict(sorted(by_layer.items())),
            "numRecords": self._count,
            # span-compatible view of the (bounded) record ring + the
            # process compile accounting — the two blind spots of the
            # original wall-clock-sums-only report. Program-build counts
            # come from the compile ledger (backend-independent: the
            # dispatch sites report their own builds); the persistent-
            # cache listener's hits/misses ride along as a cross-check
            # where its monitoring events fire (TPU/GPU — they read 0 on
            # CPU, the pre-ledger gap; observability/ledger.py)
            "spans": self.spans(),
            "compileCache": {
                **cache_stats(),
                "builds": led.total,
                "byCause": led.counts_by_cause(),
                "bySubsystem": led.counts(),
            },
        }
        # device-side memory: measured live-buffer stats where the
        # backend reports them, plus the observatory's shape-predicted
        # per-subsystem peaks (works on every backend, CPU included)
        stats = _devicemem.memory_stats()
        if stats:
            out["deviceMemory"] = stats
        out["deviceMemoryPredicted"] = _devicemem.observatory().snapshot()
        return out

    def pretty(self, top_k: int = 15) -> str:
        m = self.app_metrics()
        lines = [f"Stage timings ({m['numRecords']} ops, "
                 f"{m['stageSecondsTotal']:.2f}s total):"]
        for name, secs in list(m["byStage"].items())[:top_k]:
            lines.append(f"  {secs:8.3f}s  {name}")
        return "\n".join(lines)
