"""Feature DAG nodes and builders.

Mirrors the reference feature algebra (reference:
features/src/main/scala/com/salesforce/op/features/FeatureLike.scala,
Feature.scala, FeatureBuilder.scala, FeatureUID): a ``Feature`` is a typed,
lazily-evaluated node in a DAG whose origin stage produced it and whose parents
are the stage's inputs. Nothing computes at definition time — the workflow
reconstructs the full stage DAG from result-feature lineage
(``raw_features`` / ``parent_stages`` walks with cycle checking, reference
FeatureLike.scala:309-380).
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple, Type

from .types import FeatureType, feature_type_by_name, FEATURE_TYPES

_uid_counter = itertools.count(1)


def make_uid(cls_name: str) -> str:
    """Stage/feature uid: ``ClassName_000000000001`` (reference UID.scala)."""
    return f"{cls_name}_{next(_uid_counter):012x}"


def reset_uids() -> None:
    """Reset the uid counter (tests only — keeps goldens deterministic)."""
    global _uid_counter
    _uid_counter = itertools.count(1)


class Feature:
    """A typed node in the feature DAG (reference FeatureLike.scala:48-103).

    origin_stage: the stage that produces this feature (a FeatureGeneratorStage
    for raw features); parents: the input features of that stage.
    """

    def __init__(self, name: str, feature_type: Type[FeatureType], is_response: bool,
                 origin_stage: Any, parents: Sequence["Feature"], uid: Optional[str] = None,
                 distributions: Sequence[Any] = ()):
        self.name = name
        self.feature_type = feature_type
        self.is_response = is_response
        self.origin_stage = origin_stage
        self.parents = tuple(parents)
        self.uid = uid or make_uid(feature_type.__name__)
        self.distributions = tuple(distributions)

    # -- identity ------------------------------------------------------------
    @property
    def type_name(self) -> str:
        return self.feature_type.__name__

    @property
    def is_raw(self) -> bool:
        return len(self.parents) == 0

    def __repr__(self) -> str:
        return (f"Feature[{self.type_name}](name={self.name!r}, uid={self.uid!r}, "
                f"isResponse={self.is_response})")

    def __eq__(self, other):
        return isinstance(other, Feature) and self.uid == other.uid

    def __hash__(self):
        return hash(self.uid)

    # -- graph construction --------------------------------------------------
    def transform_with(self, stage: Any, *others: "Feature") -> "Feature":
        """Apply a stage to this feature (+ optional others) and return its
        output feature (reference FeatureLike.transformWith:210-229)."""
        stage.set_input(self, *others)
        return stage.get_output()

    # -- lineage walks (reference FeatureLike.scala:309-380) -----------------
    def traverse(self, visit: Callable[["Feature"], None]) -> None:
        """DFS over ancestry with cycle detection."""
        in_path: Set[str] = set()
        done: Set[str] = set()

        def rec(f: "Feature"):
            if f.uid in done:
                return
            if f.uid in in_path:
                raise ValueError(f"Feature DAG contains a cycle at {f.name} ({f.uid})")
            in_path.add(f.uid)
            for p in f.parents:
                rec(p)
            in_path.discard(f.uid)
            done.add(f.uid)
            visit(f)

        rec(self)

    def all_features(self) -> List["Feature"]:
        out: List[Feature] = []
        self.traverse(out.append)
        return out

    def raw_features(self) -> List["Feature"]:
        """All raw (origin) ancestors, de-duplicated, stable order
        (reference FeatureLike.rawFeatures:338)."""
        return [f for f in self.all_features() if f.is_raw]

    def parent_stages(self) -> Dict[Any, int]:
        """All ancestor stages mapped to their distance from this feature
        (reference FeatureLike.parentStages:363). Distance = max over paths.

        Linear-time: one cycle-checked traversal for the node list, then
        longest-path relaxation in reverse post-order (diamond-heavy graphs —
        every transmogrify DAG — would blow up an unmemoized walk)."""
        ordered = self.all_features()  # post-order: ancestors before descendants
        dist: Dict[str, int] = {self.uid: 0}
        by_uid = {f.uid: f for f in ordered}
        for f in reversed(ordered):  # root first, toward raw features
            d = dist.get(f.uid, 0)
            for p in f.parents:
                dist[p.uid] = max(dist.get(p.uid, 0), d + 1)
        out: Dict[Any, int] = {}
        for uid, d in dist.items():
            st = by_uid[uid].origin_stage
            if st is not None:
                out[st] = max(out.get(st, 0), d)
        return out

    def copy_with_new_stages(self, stage_map: Dict[str, Any]) -> "Feature":
        """Rebuild this feature's ancestry substituting fitted stages by uid
        (reference FeatureLike.copyWithNewStages:456)."""
        cache: Dict[str, Feature] = {}

        def rec(f: "Feature") -> "Feature":
            if f.uid in cache:
                return cache[f.uid]
            new_parents = [rec(p) for p in f.parents]
            replaced = f.origin_stage is not None and f.origin_stage.uid in stage_map
            stage = stage_map[f.origin_stage.uid] if replaced else f.origin_stage
            nf = Feature(f.name, f.feature_type, f.is_response, stage, new_parents,
                         uid=f.uid, distributions=f.distributions)
            # only stages swapped in (fitted models) get rewired to the clone;
            # stages of the original graph must keep their own output feature
            if replaced:
                stage._output_feature = nf
            cache[f.uid] = nf
            return nf

        return rec(self)

    def pretty_parent_stages(self) -> str:
        lines: List[str] = []
        for stage, d in sorted(self.parent_stages().items(), key=lambda kv: -kv[1]):
            lines.append("  " * 0 + f"[{d}] {type(stage).__name__} -> {stage.uid}")
        return "\n".join(lines)

    def history(self) -> Dict[str, Any]:
        return {
            "originFeatures": [f.name for f in self.raw_features()],
            "stages": [s.uid for s in self.parent_stages()],
        }

    def as_raw(self, extract_fn: Optional[Callable[[Any], Any]] = None) -> "Feature":
        """Detach: a raw feature with the same name/type (reference FeatureLike.asRaw)."""
        builder = FeatureBuilder(self.name, self.feature_type).extract(
            extract_fn or _field_extractor(self.name, self.feature_type))
        return builder.as_response() if self.is_response else builder.as_predictor()


class FieldExtractor:
    """Default extract function: pull the record field with the feature's name.

    A class (not a closure) so model persistence can round-trip it — the analog
    of the reference serializing extract lambdas by class name
    (FeatureGeneratorStage + OpPipelineStageReader ctor reflection)."""

    def __init__(self, name: str):
        self.name = name
        self.__name__ = f"extract_{name}"

    def __call__(self, record: Any) -> Any:
        if isinstance(record, dict):
            return record.get(self.name)
        return getattr(record, self.name, None)


def _field_extractor(name: str, ft: Type[FeatureType]) -> Callable[[Any], Any]:
    return FieldExtractor(name)


class FeatureBuilder:
    """Typed factory for raw features (reference FeatureBuilder.scala:48-177).

    Usage::

        age = FeatureBuilder.Real("age").extract(lambda r: r.get("age")).as_predictor()
        survived = FeatureBuilder.RealNN("survived").extract(...).as_response()
    """

    def __init__(self, name: str, feature_type: Type[FeatureType]):
        self.name = name
        self.feature_type = feature_type
        self._extract_fn: Optional[Callable[[Any], Any]] = None
        self._aggregator: Optional[Any] = None
        self._aggregate_window: Optional[int] = None

    def extract(self, fn: Callable[[Any], Any]) -> "FeatureBuilder":
        self._extract_fn = fn
        return self

    def extract_field(self) -> "FeatureBuilder":
        """Extract the record field with the feature's name (dict or attr)."""
        return self.extract(_field_extractor(self.name, self.feature_type))

    def aggregate(self, aggregator: Any) -> "FeatureBuilder":
        """Set the monoid aggregator used by event-aggregating readers
        (reference FeatureBuilder aggregate + MonoidAggregatorDefaults)."""
        self._aggregator = aggregator
        return self

    def window(self, millis: int) -> "FeatureBuilder":
        self._aggregate_window = millis
        return self

    def _build(self, is_response: bool) -> Feature:
        from .stages.base import FeatureGeneratorStage
        extract = self._extract_fn or _field_extractor(self.name, self.feature_type)
        stage = FeatureGeneratorStage(
            extract_fn=extract, output_name=self.name,
            output_type=self.feature_type, is_response=is_response,
            aggregator=self._aggregator, aggregate_window=self._aggregate_window)
        return stage.get_output()

    def as_predictor(self) -> Feature:
        return self._build(is_response=False)

    def as_response(self) -> Feature:
        return self._build(is_response=True)

    # -- typed factories: FeatureBuilder.Real("x"), .Text("y"), … ------------
    @classmethod
    def _typed(cls, type_name: str):
        ft = feature_type_by_name(type_name)

        def factory(name: str) -> "FeatureBuilder":
            return cls(name, ft)

        return factory

    # -- schema inference ----------------------------------------------------
    @staticmethod
    def from_dataframe(df, response: str,
                       response_type: Optional[Type[FeatureType]] = None,
                       ) -> Tuple[Feature, List[Feature]]:
        """Infer raw features from a pandas DataFrame schema (reference
        FeatureBuilder.fromDataFrame:190-218). Returns (response, predictors)."""
        from .types import (Real, RealNN, Integral, Binary, Text, Date, DateTime)
        import numpy as np
        import pandas as pd

        if response not in df.columns:
            raise ValueError(
                f"response feature '{response}' is not present in the dataframe")
        feats: List[Feature] = []
        resp: Optional[Feature] = None
        for col in df.columns:
            dtype = df[col].dtype
            if col == response:
                rt = response_type or RealNN
                resp = FeatureBuilder(col, rt).extract_field().as_response()
                continue
            if pd.api.types.is_bool_dtype(dtype):
                ft = Binary
            elif pd.api.types.is_integer_dtype(dtype):
                ft = Integral
            elif pd.api.types.is_float_dtype(dtype):
                ft = Real
            elif pd.api.types.is_datetime64_any_dtype(dtype):
                ft = DateTime
            else:
                ft = Text
            feats.append(FeatureBuilder(col, ft).extract_field().as_predictor())
        assert resp is not None
        return resp, feats

    @staticmethod
    def from_row(row: Dict[str, Any], response: str,
                 response_type: Optional[Type[FeatureType]] = None,
                 ) -> Tuple[Feature, List[Feature]]:
        """Infer raw features from one sample record (reference
        FeatureBuilder.fromRow:231-241). Returns (response, predictors)."""
        import pandas as pd
        return FeatureBuilder.from_dataframe(pd.DataFrame([row]), response,
                                             response_type=response_type)


# Attach one typed factory per concrete feature type:
#   FeatureBuilder.Real, FeatureBuilder.PickList, FeatureBuilder.RealMap, …
for _name in FEATURE_TYPES:
    setattr(FeatureBuilder, _name, staticmethod(FeatureBuilder._typed(_name)))
