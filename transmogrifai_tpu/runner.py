"""Run configuration, workflow runner, and CLI app.

Mirrors the reference run layer (reference:
features/src/main/scala/com/salesforce/op/OpParams.scala:40-160 — JSON run
config with per-stage param injection, reader paths, model/metrics locations;
core/src/main/scala/com/salesforce/op/OpWorkflowRunner.scala:70-459 — run
types Train/Score/StreamingScore/Features/Evaluate wiring readers + workflow,
saving model and metrics; OpApp.scala:213 — CLI entry).
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from .table import FeatureTable
from .workflow import OpWorkflow, OpWorkflowModel


class OpParams:
    """JSON-serializable run config (reference OpParams.scala:81-160)."""

    def __init__(self,
                 stage_params: Optional[Dict[str, Dict[str, Any]]] = None,
                 reader_params: Optional[Dict[str, Any]] = None,
                 model_location: Optional[str] = None,
                 write_location: Optional[str] = None,
                 metrics_location: Optional[str] = None,
                 log_stage_metrics: bool = False,
                 custom_params: Optional[Dict[str, Any]] = None):
        self.stage_params = dict(stage_params or {})
        self.reader_params = dict(reader_params or {})
        self.model_location = model_location
        self.write_location = write_location
        self.metrics_location = metrics_location
        self.log_stage_metrics = log_stage_metrics
        self.custom_params = dict(custom_params or {})

    def to_json(self) -> Dict[str, Any]:
        return {
            "stageParams": self.stage_params,
            "readerParams": self.reader_params,
            "modelLocation": self.model_location,
            "writeLocation": self.write_location,
            "metricsLocation": self.metrics_location,
            "logStageMetrics": self.log_stage_metrics,
            "customParams": self.custom_params,
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "OpParams":
        return OpParams(
            stage_params=d.get("stageParams"),
            reader_params=d.get("readerParams"),
            model_location=d.get("modelLocation"),
            write_location=d.get("writeLocation"),
            metrics_location=d.get("metricsLocation"),
            log_stage_metrics=bool(d.get("logStageMetrics", False)),
            custom_params=d.get("customParams"),
        )

    @staticmethod
    def from_file(path: str) -> "OpParams":
        with open(path) as fh:
            return OpParams.from_json(json.load(fh))



def _write_scores(df, path: str) -> None:
    """Write scored output by extension: .avro (the reference's saveScores
    format, via utils/avro.py), .csv, or parquet (default)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if path.endswith(".avro"):
        from .utils.avro import write_avro
        write_avro(path, df.to_dict("records"))
    elif path.endswith(".csv"):
        df.to_csv(path, index=False)
    else:
        df.to_parquet(path)


class OpWorkflowRunnerResult:
    """(reference OpWorkflowRunner result types)."""

    def __init__(self, run_type: str):
        self.run_type = run_type
        self.model: Optional[OpWorkflowModel] = None
        self.metrics: Dict[str, Any] = {}
        self.scores: Optional[FeatureTable] = None
        self.score_batches: int = 0


class RunType:
    TRAIN = "train"
    SCORE = "score"
    STREAMING_SCORE = "streamingScore"
    FEATURES = "features"
    EVALUATE = "evaluate"
    ALL = (TRAIN, SCORE, STREAMING_SCORE, FEATURES, EVALUATE)


def table_to_dataframe(table: FeatureTable):
    """FeatureTable → pandas DataFrame (score writing path; the analog of
    OpWorkflowModel.saveScores' avro write, reference :376-421)."""
    import pandas as pd
    data: Dict[str, Any] = {}
    if table.key is not None:
        data[FeatureTable.KEY] = list(table.key)
    for name in table.column_names:
        col = table[name]
        vals = np.asarray(col.values)
        valid = col.valid_mask()
        if vals.ndim > 1:
            keys = col.metadata.get("keys")
            if keys:  # prediction column → one flat dict per row
                data[name] = [dict(zip(keys, row)) for row in vals.tolist()]
            else:
                data[name] = [list(map(float, row)) for row in vals.tolist()]
        elif col.kind in ("real", "binary", "integral", "date"):
            out = vals.astype(object)
            out[~valid] = None
            data[name] = out
        else:
            data[name] = [v if ok else None for v, ok in zip(vals, valid)]
    return pd.DataFrame(data)


class OpWorkflowRunner:
    """Wires readers + workflow + evaluator per run type (reference
    OpWorkflowRunner.scala: train :163-181, score :204-222,
    streamingScore :232-263)."""

    def __init__(self, workflow: OpWorkflow,
                 train_reader=None, score_reader=None,
                 streaming_score_reader=None,
                 evaluator=None,
                 label_feature=None, prediction_feature=None):
        self.workflow = workflow
        self.train_reader = train_reader
        self.score_reader = score_reader
        self.streaming_score_reader = streaming_score_reader
        self.evaluator = evaluator
        self.label_feature = label_feature
        self.prediction_feature = prediction_feature

    def _eval(self):
        ev = self.evaluator
        if ev is None:
            return None
        if self.label_feature is not None:
            ev.set_label_col(self.label_feature)
        if self.prediction_feature is not None:
            ev.set_prediction_col(self.prediction_feature)
        return ev

    def run(self, run_type: str, params: Optional[OpParams] = None
            ) -> OpWorkflowRunnerResult:
        params = params or OpParams()
        if params.stage_params:
            self.workflow.set_parameters({"stageParams": params.stage_params})
        if params.log_stage_metrics and self.workflow.profiler is None:
            self.workflow.with_profiler()
        result = OpWorkflowRunnerResult(run_type)
        handler = {
            RunType.TRAIN: self._train,
            RunType.SCORE: self._score,
            RunType.STREAMING_SCORE: self._streaming_score,
            RunType.FEATURES: self._features,
            RunType.EVALUATE: self._evaluate,
        }.get(run_type)
        if handler is None:
            raise ValueError(f"unknown run type {run_type!r}; one of {RunType.ALL}")
        handler(result, params)
        if params.metrics_location and result.metrics:
            os.makedirs(os.path.dirname(params.metrics_location) or ".",
                        exist_ok=True)
            with open(params.metrics_location, "w") as fh:
                json.dump(result.metrics, fh, indent=2, default=str)
        return result

    def _train(self, result: OpWorkflowRunnerResult, params: OpParams) -> None:
        if self.train_reader is not None:
            self.workflow.set_reader(self.train_reader)
        model = self.workflow.train()
        result.model = model
        if params.model_location:
            model.save(params.model_location)
        ev = self._eval()
        if ev is not None and model.train_table is not None:
            result.metrics["trainEvaluation"] = {
                k: v for k, v in ev.evaluate_all(model.train_table).items()
                if isinstance(v, (int, float))}
        # always record the per-stage summaries (selector sweep results,
        # sanity-checker drops) so --metrics-location has content even
        # without an explicit evaluator (reference writes train metrics
        # unconditionally, OpWorkflowRunner.scala:169-178)
        result.metrics["summary"] = model.summary()
        if self.workflow.profiler is not None:
            result.metrics["appMetrics"] = self.workflow.profiler.app_metrics()

    def _load_model(self, params: OpParams) -> OpWorkflowModel:
        if params.model_location:
            return OpWorkflowModel.load(params.model_location,
                                        workflow=self.workflow)
        # no saved model: train in place (keeps small pipelines one-shot)
        if self.train_reader is not None:
            self.workflow.set_reader(self.train_reader)
        return self.workflow.train()

    def _score(self, result: OpWorkflowRunnerResult, params: OpParams) -> None:
        model = self._load_model(params)
        reader = self.score_reader or self.train_reader
        if reader is not None:
            model.set_reader(reader)
        scored = model.score()
        result.model = model
        result.scores = scored
        ev = self._eval()
        if ev is not None:
            result.metrics["scoreEvaluation"] = {
                k: v for k, v in ev.evaluate_all(scored).items()
                if isinstance(v, (int, float))}
        if params.write_location:
            _write_scores(table_to_dataframe(scored), params.write_location)

    def _streaming_score(self, result: OpWorkflowRunnerResult,
                         params: OpParams) -> None:
        model = self._load_model(params)
        reader = self.streaming_score_reader
        if reader is None:
            raise ValueError("streamingScore needs a streaming_score_reader")
        n = 0
        frames = []
        for batch in reader.stream_tables(model.raw_features):
            scored = model.score(table=batch)
            frames.append(table_to_dataframe(scored))
            n += 1
        result.model = model
        result.score_batches = n
        if params.write_location and frames:
            import pandas as pd
            _write_scores(pd.concat(frames), params.write_location)

    def _features(self, result: OpWorkflowRunnerResult, params: OpParams) -> None:
        reader = self.train_reader or self.workflow.reader
        if reader is None:
            raise ValueError("features run needs a reader")
        if not self.workflow.raw_features:
            raise ValueError("call set_result_features before a features run")
        table = reader.generate_table(self.workflow.raw_features)
        result.scores = table
        if params.write_location:
            table_to_dataframe(table).to_parquet(params.write_location)

    def _evaluate(self, result: OpWorkflowRunnerResult, params: OpParams) -> None:
        if self.evaluator is None:
            raise ValueError("evaluate run needs an evaluator")
        self._score(result, params)
        result.metrics["evaluation"] = result.metrics.pop("scoreEvaluation", {})


class OpApp:
    """CLI entry (reference OpApp.scala / OpAppWithRunner): subclass, provide
    the runner, call ``main()``."""

    def __init__(self, runner: OpWorkflowRunner):
        self.runner = runner

    def parse_args(self, argv: Optional[List[str]] = None) -> argparse.Namespace:
        p = argparse.ArgumentParser(description="transmogrifai_tpu app")
        p.add_argument("--run-type", required=True, choices=RunType.ALL)
        p.add_argument("--param-location", default=None,
                       help="path to an OpParams JSON file")
        p.add_argument("--model-location", default=None)
        p.add_argument("--write-location", default=None)
        p.add_argument("--metrics-location", default=None)
        return p.parse_args(argv)

    def main(self, argv: Optional[List[str]] = None) -> OpWorkflowRunnerResult:
        a = self.parse_args(argv)
        params = (OpParams.from_file(a.param_location)
                  if a.param_location else OpParams())
        for attr, val in (("model_location", a.model_location),
                          ("write_location", a.write_location),
                          ("metrics_location", a.metrics_location)):
            if val:
                setattr(params, attr, val)
        return self.runner.run(a.run_type, params)
