"""Chunk sources — the out-of-core replacement for "materialize the table".

The reference streams Spark partitions through monoid aggregators
(reference: readers/StreamingReaders.scala, aggregators.py §L3 of the
SURVEY); the TPU build's analog is a :class:`ChunkSource`: a re-iterable,
deterministic producer of fixed-row-budget :class:`~..table.FeatureTable`
chunks. Determinism is the load-bearing property — a resumed train replays
the exact same chunk sequence from the last committed chunk, so every fold
is bit-identical to the uninterrupted run (docs/streaming.md "Chunk
protocol"):

* chunk ``index`` is the position in the schedule, ``chunk_id`` is
  ``<source fingerprint>:<index>`` — stable across processes;
* ``chunks(start=k)`` restarts mid-schedule without replaying chunks < k;
* ``fingerprint()`` commits to the dataset identity + chunk schedule, and
  is embedded in every stream checkpoint so a resume against different
  data (or a different ``chunk_rows``) is *detected*, never silently
  folded in.
"""
from __future__ import annotations

import abc
import hashlib
import os
from dataclasses import dataclass
from typing import Iterator, Optional, Sequence

import numpy as np

from ..table import FeatureTable

#: default fixed row budget per chunk (TG_STREAM_CHUNK_ROWS)
CHUNK_ROWS_ENV = "TG_STREAM_CHUNK_ROWS"
DEFAULT_CHUNK_ROWS = 65_536


def env_chunk_rows(chunk_rows: Optional[int] = None) -> int:
    if chunk_rows is not None:
        return max(1, int(chunk_rows))
    try:
        return max(1, int(os.environ.get(CHUNK_ROWS_ENV, "")
                          or DEFAULT_CHUNK_ROWS))
    except ValueError:
        return DEFAULT_CHUNK_ROWS


@dataclass
class Chunk:
    """One fixed-budget slice of the logical dataset."""
    index: int
    chunk_id: str
    table: FeatureTable

    @property
    def rows(self) -> int:
        return self.table.num_rows


class ChunkSource(abc.ABC):
    """Deterministic, re-iterable producer of FeatureTable chunks."""

    chunk_rows: int = DEFAULT_CHUNK_ROWS

    #: True when ``read_chunk(i)`` is O(chunk) for ANY i — the input
    #: engine then lets its producer workers read claimed indices in
    #: parallel (streaming/feed.py). Sequential-only sources (Avro's
    #: record stream) keep False: reads serialize under the claim lock,
    #: transforms still parallelize.
    random_access: bool = False

    @abc.abstractmethod
    def fingerprint(self) -> str:
        """Stable hex digest of (dataset identity, chunk schedule)."""

    @property
    @abc.abstractmethod
    def num_chunks(self) -> int:
        """Chunks in one full pass (the schedule length)."""

    @abc.abstractmethod
    def chunks(self, start: int = 0) -> Iterator[Chunk]:
        """Yield chunks ``start..num_chunks-1`` of the fixed schedule."""

    def bind(self, raw_features: Sequence) -> None:
        """Called by the streaming trainer before the first pass; sources
        that build tables from records (Avro) need the raw feature set."""

    def with_chunk_rows(self, chunk_rows: int) -> "ChunkSource":
        """The same logical dataset re-chunked at ``chunk_rows`` rows per
        chunk — what the trainer's memory-pressure downshift halves to
        (robustness/resources.py; docs/robustness.md "Resource exhaustion
        & watchdog"). Sources that cannot re-chunk deterministically leave
        this unimplemented; exhaustion then propagates instead of
        downshifting."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support re-chunking")

    def chunk_id(self, index: int) -> str:
        return f"{self.fingerprint()[:16]}:{index:06d}"

    def read_chunk(self, index: int) -> Chunk:
        """Chunk ``index`` of the fixed schedule, in isolation. The
        default derives it from ``chunks(start=index)`` — correct for
        every source but O(prefix) for sequential ones; sources that set
        ``random_access = True`` make this O(chunk)."""
        chunk = next(iter(self.chunks(start=index)), None)
        if chunk is None or chunk.index != index:
            raise IndexError(f"chunk {index} is past the schedule "
                             f"({self.num_chunks} chunks)")
        return chunk


class TableChunkSource(ChunkSource):
    """Chunks over an in-memory FeatureTable (slices are views/cheap takes).

    The bridge between the in-core and out-of-core paths: a streamed fold
    over ``TableChunkSource(t, chunk_rows=len(t))`` IS the in-core fit, so
    equivalence tests compare the two paths on identical arithmetic.
    """

    random_access = True  # chunk i is one O(chunk) take() slice

    def __init__(self, table: FeatureTable, chunk_rows: Optional[int] = None):
        self.table = table
        self.chunk_rows = env_chunk_rows(chunk_rows)
        self._fp: Optional[str] = None

    def with_chunk_rows(self, chunk_rows: int) -> "TableChunkSource":
        return TableChunkSource(self.table, chunk_rows)

    def fingerprint(self) -> str:
        if self._fp is None:
            h = hashlib.sha256()
            h.update(f"table:{self.table.num_rows}:{self.chunk_rows}".encode())
            for name in sorted(self.table.column_names):
                col = self.table[name]
                h.update(f"{name}:{col.kind}:{col.width}".encode())
                vals = np.asarray(col.values)
                if vals.dtype != object and vals.size:
                    # strided content sample — cheap, catches "same shape,
                    # different data" resumes
                    flat = np.ascontiguousarray(vals).reshape(-1)
                    h.update(flat[::max(1, flat.size // 256)].tobytes())
            self._fp = h.hexdigest()
        return self._fp

    @property
    def num_chunks(self) -> int:
        return max(1, -(-self.table.num_rows // self.chunk_rows))

    def chunks(self, start: int = 0) -> Iterator[Chunk]:
        n = self.table.num_rows
        for i in range(start, self.num_chunks):
            lo = i * self.chunk_rows
            hi = min(n, lo + self.chunk_rows)
            yield Chunk(i, self.chunk_id(i),
                        self.table.take(np.arange(lo, hi)))


class AvroChunkSource(ChunkSource):
    """Chunks decoded incrementally from an Avro container file
    (utils/avro.read_avro is already a record iterator — the file never
    materializes whole). Nested records flatten dotted like AvroReader."""

    def __init__(self, path: str, chunk_rows: Optional[int] = None,
                 raw_features: Optional[Sequence] = None):
        self.path = path
        self.chunk_rows = env_chunk_rows(chunk_rows)
        self.raw_features = tuple(raw_features) if raw_features else None
        self._num_chunks: Optional[int] = None

    def bind(self, raw_features: Sequence) -> None:
        if self.raw_features is None:
            self.raw_features = tuple(raw_features)

    def with_chunk_rows(self, chunk_rows: int) -> "AvroChunkSource":
        return AvroChunkSource(self.path, chunk_rows, self.raw_features)

    def fingerprint(self) -> str:
        st = os.stat(self.path)
        ident = f"avro:{os.path.abspath(self.path)}:{st.st_size}:{self.chunk_rows}"
        return hashlib.sha256(ident.encode()).hexdigest()

    @property
    def num_chunks(self) -> int:
        if self._num_chunks is None:
            from ..utils.avro import read_avro
            n = sum(1 for _ in read_avro(self.path))
            self._num_chunks = max(1, -(-n // self.chunk_rows))
        return self._num_chunks

    def chunks(self, start: int = 0) -> Iterator[Chunk]:
        import pandas as pd

        from ..readers.readers import AvroReader
        from ..utils.avro import read_avro
        if self.raw_features is None:
            raise ValueError("AvroChunkSource needs raw_features: pass them "
                             "to the constructor or let the trainer bind()")
        buf = []
        index = 0
        for rec in read_avro(self.path):
            buf.append(AvroReader._flatten(rec))
            if len(buf) == self.chunk_rows:
                if index >= start:
                    yield self._emit(pd.DataFrame(buf), index)
                buf = []
                index += 1
        if buf or index == 0:
            if index >= start:
                yield self._emit(pd.DataFrame(buf), index)
            index += 1
        self._num_chunks = index

    def _emit(self, df, index: int) -> Chunk:
        from ..readers.readers import dataframe_to_table
        table = dataframe_to_table(df, self.raw_features)
        return Chunk(index, self.chunk_id(index), table)


class SyntheticChunkSource(ChunkSource):
    """Deterministic synthetic generator: chunk ``i`` is a pure function of
    ``(seed, i)``, so any chunk regenerates independently — resume never
    replays the prefix, and no pass ever materializes the dataset (the
    10M×64 bench source, BENCH_MODE=stream).

    Emits ``x0..x{d-1}`` Real predictor columns (a deterministic ~3% of
    slots masked invalid) and a RealNN ``y`` response from a fixed hidden
    linear model — binary 0/1 by default, continuous for
    ``problem='regression'``.
    """

    random_access = True  # chunk i is a pure function of (seed, i)

    def __init__(self, num_rows: int, num_features: int,
                 chunk_rows: Optional[int] = None, seed: int = 0,
                 problem: str = "binary", missing_rate: float = 0.03):
        self.num_rows = int(num_rows)
        self.num_features = int(num_features)
        self.chunk_rows = env_chunk_rows(chunk_rows)
        self.seed = int(seed)
        self.problem = problem
        self.missing_rate = float(missing_rate)
        self._w = np.random.RandomState(seed).randn(num_features).astype(
            np.float64)

    # NOTE: no ``with_chunk_rows`` — chunk ``i``'s rows are a pure function
    # of ``(seed, i, chunk_rows)``, so re-chunking would change the DATA,
    # not just the schedule; the memory-pressure downshift must propagate
    # instead of silently folding a different dataset.

    def fingerprint(self) -> str:
        ident = (f"synthetic:{self.num_rows}:{self.num_features}:"
                 f"{self.chunk_rows}:{self.seed}:{self.problem}:"
                 f"{self.missing_rate}")
        return hashlib.sha256(ident.encode()).hexdigest()

    @property
    def num_chunks(self) -> int:
        return max(1, -(-self.num_rows // self.chunk_rows))

    def chunks(self, start: int = 0) -> Iterator[Chunk]:
        from ..table import Column
        from ..types import Real, RealNN
        for i in range(start, self.num_chunks):
            lo = i * self.chunk_rows
            n = min(self.num_rows, lo + self.chunk_rows) - lo
            rng = np.random.RandomState(
                (self.seed * 1_000_003 + i) % (2 ** 31 - 1))
            X = rng.randn(n, self.num_features).astype(np.float32)
            mask = rng.rand(n, self.num_features) >= self.missing_rate
            z = (np.where(mask, X, 0.0).astype(np.float64) @ self._w)
            if self.problem == "regression":
                y = (z + rng.randn(n)).astype(np.float32)
            else:
                y = (z > 0).astype(np.float32)
            cols = {f"x{j}": Column(Real, X[:, j], mask[:, j])
                    for j in range(self.num_features)}
            cols["y"] = Column(RealNN, y, None)
            yield Chunk(i, self.chunk_id(i), FeatureTable(cols, n))
