"""Out-of-core streaming training (docs/streaming.md).

Datasets larger than device (and host) memory train as chunked monoid
folds: a :class:`ChunkSource` yields fixed-row-budget FeatureTable chunks,
a double-buffered :class:`DeviceFeed` packs + uploads chunk N+1 while
chunk N folds, estimator fits run as accumulate/merge/finalize monoids
(:mod:`.folds`), and per-chunk checkpoints through the PR 2 manifest make
a kill at any ``stream.*`` chaos site resume bit-exactly. Entry point:
``OpWorkflow.train(stream=source)``.
"""
from .checkpoint import StreamCheckpoint  # noqa: F401
from .feed import DeviceFeed, FeedStats, device_bytes, live_feeds  # noqa: F401
from .folds import (  # noqa: F401
    ArraySumFold, ColStatsFold, CompositeFold, ContingencyFold,
    CorrelationFold, HistogramFold, MonoidFold,
)
from .model import StreamingGBT, StreamingGBTModel  # noqa: F401
from .source import (  # noqa: F401
    AvroChunkSource, Chunk, ChunkSource, SyntheticChunkSource,
    TableChunkSource, env_chunk_rows,
)
from .trainer import (  # noqa: F401
    StreamingNotSupportedError, StreamRun, fit_dag_streaming,
)
