"""Out-of-core streaming training (docs/streaming.md).

Datasets larger than device (and host) memory train as chunked monoid
folds: a :class:`ChunkSource` yields fixed-row-budget FeatureTable chunks,
the :class:`DeviceFeed` input engine prepares them behind the consumer —
a ``TG_STREAM_WORKERS`` pool runs read+transform per claimed index while
one ordered committer packs + uploads in schedule order, and a bounded
:class:`ChunkCache` (host LRU + sha256-verified disk tier) replays
transformed chunks on repeat passes — estimator fits run as
accumulate/merge/finalize monoids (:mod:`.folds`), and per-chunk
checkpoints through the PR 2 manifest make a kill at any ``stream.*``
chaos site resume bit-exactly. Entry point:
``OpWorkflow.train(stream=source)``.
"""
from .cache import (  # noqa: F401
    ChunkCache, PackedChunk, chunk_cache_key, pack_table,
    transform_identity,
)
from .checkpoint import StreamCheckpoint  # noqa: F401
from .feed import (  # noqa: F401
    DeviceFeed, FeedStats, device_bytes, env_workers, live_feeds,
)
from .folds import (  # noqa: F401
    ArraySumFold, ColStatsFold, CompositeFold, ContingencyFold,
    CorrelationFold, HistogramFold, MonoidFold,
)
from .model import StreamingGBT, StreamingGBTModel  # noqa: F401
from .source import (  # noqa: F401
    AvroChunkSource, Chunk, ChunkSource, SyntheticChunkSource,
    TableChunkSource, env_chunk_rows,
)
from .trainer import (  # noqa: F401
    StreamingNotSupportedError, StreamRun, fit_dag_streaming,
)
