"""Transformed-chunk cache — skip read+transform+pack on repeat passes.

A transformed chunk is a pure function of four identities::

    (source fingerprint) x (chunk index) x (fitted-transform identity) x
    (chunk row budget)

so once the first pass over a :class:`~.source.ChunkSource` has paid
read + upstream transform + pack for chunk ``i``, every later pass with
the same upstream models can replay the exact bytes instead of redoing
the work. The streaming GBT makes ``1 + trees x (depth + 1)`` passes over
the identical transformed stream — this cache is what turns that
amplification from "re-prepare everything" into "re-read host blocks"
(docs/benchmarks.md round 20; the bench A/B's third arm).

Two bounded tiers:

* **host tier** — packed per-dtype blocks (the same layout
  ``FeatureTable.to_device`` transfers, so accounting and byte-equality
  checks are exact), LRU under ``TG_STREAM_CACHE_BYTES`` (default 256
  MiB; ``0`` disables);
* **disk tier** (optional) — one npz per chunk under
  ``TG_STREAM_CACHE_DIR``, written atomically and sha256-verified on
  every read exactly like manifest files (manifest.atomic_write_bytes),
  so entries survive a kill and a ``resume=True`` train skips the prep
  its predecessor already paid for.

Safety contract: the cache can only ever be *slower*, never *wrong*. A
miss, an evicted entry, a sha mismatch, a header/key mismatch, or the
``stream.cache`` chaos site firing all take the same typed fallback —
recompute the chunk from source (bit-equal by the determinism contract)
and record ``stream_cache_fallback`` in the fault log. Unpacked columns
are numpy views into the packed blocks, so byte-equality of cached vs
recomputed chunks is assertable (and asserted — tests/test_stream_engine
.py, plus spot-checks in the chaos campaign's ``stream`` scenario).
"""
from __future__ import annotations

import hashlib
import importlib
import io
import json
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

from ..observability import metrics as _obs_metrics
from ..robustness import faults
from ..robustness.policy import FaultLog, FaultReport
from ..table import Column, FeatureTable

CACHE_BYTES_ENV = "TG_STREAM_CACHE_BYTES"
CACHE_DIR_ENV = "TG_STREAM_CACHE_DIR"
DEFAULT_CACHE_BYTES = 256 * 1024 * 1024


def env_cache_bytes(max_bytes: Optional[int] = None) -> int:
    if max_bytes is not None:
        return max(0, int(max_bytes))
    try:
        raw = os.environ.get(CACHE_BYTES_ENV, "")
        return max(0, int(raw)) if raw else DEFAULT_CACHE_BYTES
    except ValueError:
        return DEFAULT_CACHE_BYTES


def env_cache_dir() -> Optional[str]:
    return os.environ.get(CACHE_DIR_ENV) or None


def transform_identity(models: Sequence[Any]) -> str:
    """Stable digest of the *fitted* upstream transform stack: the same
    serialized form model persistence commits (class + uid + full fitted
    state, arrays hashed by content). Anything that refuses to serialize
    hashes as process-unique — degrading to a guaranteed miss, never to a
    wrong hit."""
    from ..persistence import _Arrays, stage_to_json
    h = hashlib.sha256()
    for m in models:
        arrays = _Arrays()
        try:
            d = stage_to_json(m, arrays)
            h.update(json.dumps(d, sort_keys=True, default=repr).encode())
            for k in sorted(arrays.store):
                a = arrays.store[k]
                h.update(f"{k}:{a.dtype}:{a.shape}".encode())
                h.update(np.ascontiguousarray(a).tobytes())
        except Exception:
            h.update(f"opaque:{type(m).__name__}:{id(m)}".encode())
    return h.hexdigest()[:16]


def chunk_cache_key(source_fingerprint: str, index: int, ident: str,
                    chunk_rows: int) -> str:
    return f"{source_fingerprint[:16]}:{ident}:{chunk_rows}:{index:06d}"


@dataclass
class PackedChunk:
    """One transformed chunk in packed per-dtype form.

    ``header`` is JSON-able (it IS the disk header): row count, dtype
    block order, and a column directory (name, feature-type path, dtype,
    shape, mask offset flag, JSON-able metadata). ``blocks`` hold the
    concatenated flattened values per dtype; ``mask_block`` concatenates
    every present mask. ``extra_meta`` carries non-JSON-able column
    metadata (e.g. ``vector_meta`` objects) by reference — host tier
    only; a disk-restored chunk keeps the JSON-able subset (fold
    consumers read values; schema metadata comes from the probe table).
    """
    header: Dict[str, Any]
    blocks: Dict[str, np.ndarray]
    mask_block: Optional[np.ndarray]
    key_values: Optional[np.ndarray] = None
    extra_meta: Dict[str, Mapping[str, Any]] = field(default_factory=dict)

    @property
    def rows(self) -> int:
        return int(self.header["rows"])

    @property
    def nbytes(self) -> int:
        total = sum(int(b.nbytes) for b in self.blocks.values())
        if self.mask_block is not None:
            total += int(self.mask_block.nbytes)
        if self.key_values is not None:
            total += int(self.key_values.nbytes)
        return total

    def content_sha(self) -> str:
        """Digest of the packed payload bytes — the byte-equality probe
        tests and the bench A/B compare cached vs recomputed chunks on."""
        h = hashlib.sha256()
        h.update(json.dumps(self.header, sort_keys=True).encode())
        for dt in self.header["dtypes"]:
            h.update(np.ascontiguousarray(self.blocks[dt]).tobytes())
        if self.mask_block is not None:
            h.update(np.ascontiguousarray(self.mask_block).tobytes())
        return h.hexdigest()

    def unpack(self) -> FeatureTable:
        """Rebuild the FeatureTable; column values/masks are views into
        the packed blocks (the base buffers stay alive under the views,
        so a later LRU eviction cannot invalidate a delivered chunk)."""
        offs = {dt: 0 for dt in self.blocks}
        moff = 0
        cols: Dict[str, Column] = {}
        for d in self.header["cols"]:
            dt = d["dtype"]
            shape = tuple(d["shape"])
            size = int(np.prod(shape)) if shape else 1
            vals = self.blocks[dt][offs[dt]:offs[dt] + size].reshape(shape)
            offs[dt] += size
            mask = None
            if d["masked"]:
                n = int(d["mask_size"])
                mask = self.mask_block[moff:moff + n].reshape(
                    tuple(d["mask_shape"]))
                moff += n
            meta = dict(d.get("meta") or {})
            meta.update(self.extra_meta.get(d["name"], {}))
            mod, _, qual = d["type"].rpartition(":")
            ftype = getattr(importlib.import_module(mod), qual)
            cols[d["name"]] = Column(ftype, vals, mask, meta)
        return FeatureTable(cols, self.rows, self.key_values)


def pack_table(table: FeatureTable) -> Optional[PackedChunk]:
    """Pack a (host-side, transformed) chunk table; ``None`` when the
    chunk is not cacheable — any object-dtype column (un-vectorized
    text/map payloads) or non-numpy storage makes the whole chunk
    uncacheable rather than partially cached."""
    key_values = table.key
    if key_values is not None:
        key_values = np.asarray(key_values)
        if key_values.dtype == object:
            return None
    directory: List[Dict[str, Any]] = []
    by_dtype: "OrderedDict[str, List[np.ndarray]]" = OrderedDict()
    masks: List[np.ndarray] = []
    extra_meta: Dict[str, Mapping[str, Any]] = {}
    for name in table.column_names:
        col = table[name]
        vals = col.values
        if not isinstance(vals, np.ndarray) or vals.dtype == object:
            return None
        mask = None if col.mask is None else np.asarray(col.mask)
        jsonable: Dict[str, Any] = {}
        opaque: Dict[str, Any] = {}
        for k, v in dict(col.metadata).items():
            try:
                json.dumps({k: v})
                jsonable[k] = v
            except (TypeError, ValueError):
                opaque[k] = v
        if opaque:
            extra_meta[name] = opaque
        directory.append({
            "name": name,
            "type": f"{col.feature_type.__module__}:"
                    f"{col.feature_type.__qualname__}",
            "dtype": str(vals.dtype), "shape": list(vals.shape),
            "masked": mask is not None,
            "mask_size": 0 if mask is None else int(mask.size),
            "mask_shape": [] if mask is None else list(mask.shape),
            "meta": jsonable,
        })
        by_dtype.setdefault(str(vals.dtype), []).append(
            np.ascontiguousarray(vals).reshape(-1))
        if mask is not None:
            masks.append(np.ascontiguousarray(mask).reshape(-1))
    blocks = {dt: (np.concatenate(parts) if len(parts) > 1 else parts[0])
              for dt, parts in by_dtype.items()}
    mask_block = (np.concatenate(masks) if len(masks) > 1
                  else masks[0] if masks else None)
    header = {"rows": table.num_rows, "dtypes": list(blocks),
              "cols": directory}
    return PackedChunk(header, blocks, mask_block, key_values, extra_meta)


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    stores: int = 0
    evictions: int = 0
    skipped: int = 0          # uncacheable chunks (object columns)
    fallbacks: int = 0        # corrupt/chaos entries recomputed from source
    disk_hits: int = 0
    hit_bytes: int = 0
    host_bytes: int = 0       # current host-tier residency

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def to_json(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "stores": self.stores, "evictions": self.evictions,
                "skipped": self.skipped, "fallbacks": self.fallbacks,
                "diskHits": self.disk_hits, "hitBytes": self.hit_bytes,
                "hostBytes": self.host_bytes,
                "hitRate": round(self.hit_rate(), 4)}


class CorruptCacheEntry(RuntimeError):
    """A disk-tier entry failed sha256/header verification. Internal —
    ``ChunkCache.get`` converts it into the typed recompute fallback."""


class ChunkCache:
    """Bounded two-tier transformed-chunk cache (host LRU + sha-verified
    disk). Thread-safe: producer workers get/put concurrently."""

    def __init__(self, max_bytes: Optional[int] = None,
                 disk_dir: Optional[str] = None):
        self.max_bytes = env_cache_bytes(max_bytes)
        self.disk_dir = disk_dir
        self.stats = CacheStats()
        self._lock = threading.Lock()
        self._host: "OrderedDict[str, PackedChunk]" = OrderedDict()
        # fallback reports happen on PRODUCER threads, which never see the
        # consumer's ambient FaultLog (contextvars are per-thread) — the
        # feed binds the owning run's log here at construction
        self._log: Optional[FaultLog] = None

    def bind_log(self, log: Optional[FaultLog]) -> None:
        """Bind the owning run's FaultLog so worker-thread fallbacks land
        in its accounting (DeviceFeed calls this on the consumer thread)."""
        if log is not None:
            self._log = log

    @classmethod
    def from_env(cls, disk_dir: Optional[str] = None,
                 ) -> Optional["ChunkCache"]:
        """The workflow's constructor: host budget from
        TG_STREAM_CACHE_BYTES, disk tier from TG_STREAM_CACHE_DIR (the
        conventional spot is ``<checkpoint dir>/stream_cache`` so cached
        prep survives a kill next to the fold states it matches).
        Returns ``None`` when both tiers are disabled."""
        max_bytes = env_cache_bytes()
        disk = env_cache_dir() or disk_dir
        if max_bytes <= 0 and not disk:
            return None
        return cls(max_bytes=max_bytes, disk_dir=disk)

    # -- lookup ---------------------------------------------------------------
    def get(self, key: str) -> Optional[PackedChunk]:
        """Packed chunk for ``key`` or ``None`` (miss → caller recomputes
        from source). Every failure mode inside — the ``stream.cache``
        chaos site, a sha256/header mismatch on the disk tier — degrades
        to the same typed recompute fallback; preemption (a
        BaseException) propagates like any other kill."""
        try:
            faults.inject("stream.cache")
            with self._lock:
                entry = self._host.get(key)
                if entry is not None:
                    self._host.move_to_end(key)
            if entry is None and self.disk_dir:
                entry = self._disk_read(key)
                if entry is not None:
                    self.stats.disk_hits += 1
                    self._host_insert(key, entry)
        except CorruptCacheEntry as e:
            self._fallback(key, str(e))
            entry = None
        except Exception as e:  # chaos raise — recompute, never wrong data
            self._fallback(key, f"{type(e).__name__}: {e}")
            entry = None
        if entry is None:
            self.stats.misses += 1
            _obs_metrics.inc_counter(
                "tg_stream_cache_misses_total", 1.0,
                help="transformed-chunk cache misses (chunk recomputed)")
            return None
        self.stats.hits += 1
        self.stats.hit_bytes += entry.nbytes
        _obs_metrics.inc_counter(
            "tg_stream_cache_hits_total", 1.0,
            help="transformed-chunk cache hits (read+transform skipped)")
        return entry

    def _fallback(self, key: str, reason: str) -> None:
        self.stats.fallbacks += 1
        report = FaultReport(
            site="stream.cache", kind="stream_cache_fallback",
            detail={"key": key, "reason": reason[:200]})
        if self._log is not None:
            self._log.add(report)
        else:
            FaultLog.record(report)

    # -- store ----------------------------------------------------------------
    def put(self, key: str, packed: Optional[PackedChunk]) -> None:
        if packed is None:
            self.stats.skipped += 1
            return
        self.stats.stores += 1
        self._host_insert(key, packed)
        if self.disk_dir:
            try:
                self._disk_write(key, packed)
            except OSError as e:
                self._fallback(key, f"disk store failed: {e}")

    def _host_insert(self, key: str, packed: PackedChunk) -> None:
        if self.max_bytes <= 0 or packed.nbytes > self.max_bytes:
            return
        with self._lock:
            prev = self._host.pop(key, None)
            if prev is not None:
                self.stats.host_bytes -= prev.nbytes
            while (self._host
                   and self.stats.host_bytes + packed.nbytes
                   > self.max_bytes):
                _, evicted = self._host.popitem(last=False)
                self.stats.host_bytes -= evicted.nbytes
                self.stats.evictions += 1
            self._host[key] = packed
            self.stats.host_bytes += packed.nbytes

    # -- disk tier ------------------------------------------------------------
    def _paths(self, key: str) -> "tuple[str, str]":
        fname = f"chunk_{hashlib.sha256(key.encode()).hexdigest()[:24]}.npz"
        path = os.path.join(self.disk_dir, fname)
        return path, path + ".sha256"

    def _disk_write(self, key: str, packed: PackedChunk) -> None:
        from ..manifest import atomic_write_bytes
        os.makedirs(self.disk_dir, exist_ok=True)
        path, shapath = self._paths(key)
        if os.path.exists(path):
            return
        header = dict(packed.header)
        header["key"] = key
        arrays = {"__header__": np.frombuffer(
            json.dumps(header, sort_keys=True).encode(), dtype=np.uint8)}
        for i, dt in enumerate(packed.header["dtypes"]):
            arrays[f"block_{i}"] = packed.blocks[dt]
        if packed.mask_block is not None:
            arrays["mask"] = packed.mask_block
        if packed.key_values is not None:
            arrays["key_values"] = packed.key_values
        buf = io.BytesIO()
        np.savez_compressed(buf, **arrays)
        data = buf.getvalue()
        sha = atomic_write_bytes(path, data)
        atomic_write_bytes(shapath, sha.encode())

    def _disk_read(self, key: str) -> Optional[PackedChunk]:
        path, shapath = self._paths(key)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                data = f.read()
            with open(shapath, "rb") as f:
                want = f.read().decode().strip()
        except OSError as e:
            raise CorruptCacheEntry(f"unreadable entry: {e}")
        got = hashlib.sha256(data).hexdigest()
        if got != want:
            self._evict_disk(path, shapath)
            raise CorruptCacheEntry(
                f"sha256 mismatch ({got[:12]} != {want[:12]})")
        try:
            with np.load(io.BytesIO(data), allow_pickle=False) as z:
                header = json.loads(bytes(z["__header__"]).decode())
                if header.pop("key", None) != key:
                    raise CorruptCacheEntry("entry key mismatch")
                blocks = {dt: z[f"block_{i}"]
                          for i, dt in enumerate(header["dtypes"])}
                mask = z["mask"] if "mask" in z.files else None
                kv = z["key_values"] if "key_values" in z.files else None
        except (ValueError, KeyError, OSError) as e:
            self._evict_disk(path, shapath)
            raise CorruptCacheEntry(f"undecodable entry: {e}")
        return PackedChunk(header, blocks, mask, kv)

    @staticmethod
    def _evict_disk(path: str, shapath: str) -> None:
        for p in (path, shapath):
            try:
                os.remove(p)
            except OSError:
                pass
