"""Double-buffered host→device chunk feed.

While the consumer folds chunk N, a single producer thread prepares chunk
N+1: pulls it from the :class:`~.source.ChunkSource` (chaos site
``stream.read``), applies the already-fitted upstream transformers
host-side, and uploads the packed per-dtype blocks via
``FeatureTable.to_device()`` (chaos site ``stream.upload``; the PR 4
packed path, counted in ``tg_transfer_bytes_total{direction="h2d"}``).
A bounded queue of depth ``prefetch`` (TG_STREAM_PREFETCH, default 1)
keeps host+device residency at O(prefetch + 1 chunks) — never O(dataset).

Accounting (:class:`FeedStats`) is what the stream bench line reports:
uploaded bytes, peak concurrently-resident device bytes (the O(chunk)
claim, asserted in tests), and the overlap fraction — the share of
consumer wall-clock NOT stalled waiting on the feed.

Error contract: any exception in the producer — ``SimulatedPreemption``
(a BaseException, modeling a kill mid-read/mid-upload) included — is
forwarded through the queue and re-raised in the consumer thread, so a
streamed ``train()`` dies exactly like an in-core one would, with the
last committed chunk checkpoint intact. Resource exhaustion
(``oom.stream`` chaos site, or a real ``RESOURCE_EXHAUSTED`` from the
packed upload) forwards the same way; the trainer catches it and halves
the chunk row budget (robustness/resources.py).

Hang contract: the producer beats a watchdog heart
(robustness/watchdog.py, ``TG_WATCHDOG_S``) every loop iteration. A
producer wedged inside a dead reader or a hung upload stops beating; the
stall is recorded (``thread_stalled`` + ``tg_watchdog_stalls_total``)
and the feed ABORTS — the consumer's next ``__next__`` raises a typed
``WatchdogStallError`` instead of waiting on the wedge forever.
``close()`` likewise records (never silently discards) a producer that
outlives its join timeout.
"""
from __future__ import annotations

import os
import queue
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from ..manifest import sentinel_phase as _sentinel_phase
from ..observability import blackbox as _blackbox
from ..observability import devicemem as _devicemem
from ..observability import metrics as _obs_metrics
from ..robustness import faults
from ..robustness import watchdog as _watchdog
from ..robustness.watchdog import WatchdogStallError
from ..table import DEVICE_KINDS, FeatureTable
from .source import Chunk

PREFETCH_ENV = "TG_STREAM_PREFETCH"
DEFAULT_PREFETCH = 1

#: live feeds (weak) — the conftest no-leak fixture asserts none survive
_LIVE: "weakref.WeakSet[DeviceFeed]" = weakref.WeakSet()


def live_feeds() -> List["DeviceFeed"]:
    return [f for f in list(_LIVE) if not f.closed]


def env_prefetch(prefetch: Optional[int] = None) -> int:
    if prefetch is not None:
        return max(1, int(prefetch))
    try:
        return max(1, int(os.environ.get(PREFETCH_ENV, "")
                          or DEFAULT_PREFETCH))
    except ValueError:
        return DEFAULT_PREFETCH


def device_bytes(table: FeatureTable) -> int:
    """Bytes of device-kind column storage a chunk pins while resident."""
    total = 0
    for name in table.column_names:
        col = table[name]
        if col.kind not in DEVICE_KINDS:
            continue
        vals = col.values
        total += int(np.dtype(getattr(vals, "dtype", np.float32)).itemsize
                     * int(np.prod(np.shape(vals))))
        if col.mask is not None:
            total += int(np.shape(col.mask)[0])
    return total


@dataclass
class FeedStats:
    chunks: int = 0
    rows: int = 0
    upload_bytes: int = 0
    max_chunk_bytes: int = 0
    peak_device_bytes: int = 0
    peak_resident_chunks: int = 0
    upload_seconds: float = 0.0
    wait_seconds: float = 0.0
    wall_seconds: float = 0.0

    def overlap_fraction(self) -> float:
        """Share of consumer wall-clock NOT stalled on the feed: 1.0 means
        read+transform+upload hid entirely behind fold compute."""
        if self.wall_seconds <= 0:
            return 0.0
        return max(0.0, 1.0 - self.wait_seconds / self.wall_seconds)

    def merge(self, other: "FeedStats") -> "FeedStats":
        self.chunks += other.chunks
        self.rows += other.rows
        self.upload_bytes += other.upload_bytes
        self.max_chunk_bytes = max(self.max_chunk_bytes,
                                   other.max_chunk_bytes)
        self.peak_device_bytes = max(self.peak_device_bytes,
                                     other.peak_device_bytes)
        self.peak_resident_chunks = max(self.peak_resident_chunks,
                                        other.peak_resident_chunks)
        self.upload_seconds += other.upload_seconds
        self.wait_seconds += other.wait_seconds
        self.wall_seconds += other.wall_seconds
        return self

    def to_json(self) -> dict:
        return {
            "chunks": self.chunks, "rows": self.rows,
            "uploadBytes": self.upload_bytes,
            "maxChunkBytes": self.max_chunk_bytes,
            "peakDeviceBytes": self.peak_device_bytes,
            "peakResidentChunks": self.peak_resident_chunks,
            "uploadSeconds": round(self.upload_seconds, 4),
            "waitSeconds": round(self.wait_seconds, 4),
            "overlapFraction": round(self.overlap_fraction(), 4),
        }


class DeviceFeed:
    """Iterate device-resident chunks with one prefetching producer thread.

    Usage (always close — ``with`` or the trainer's finally)::

        with DeviceFeed(source.chunks(), transforms=models) as feed:
            for chunk in feed:
                ...fold chunk.table...
    """

    _SENTINEL = object()

    def __init__(self, chunks: Iterable[Chunk],
                 transforms: Sequence[Any] = (),
                 prefetch: Optional[int] = None,
                 to_device: bool = True):
        self._chunks = iter(chunks)
        self._transforms = list(transforms)
        self.prefetch = env_prefetch(prefetch)
        self._to_device = to_device
        self.stats = FeedStats()
        self._q: "queue.Queue" = queue.Queue(maxsize=self.prefetch + 1)
        #: production gate: the producer may hold at most ``prefetch``
        #: chunks beyond the one being consumed — acquired BEFORE a chunk
        #: is read/transformed/uploaded, released when the consumer takes
        #: the next chunk. This is what makes residency O(prefetch + 1),
        #: not O(prefetch + 2): without the gate the producer would prepare
        #: chunk N+2 while N+1 sits queued and N is being consumed.
        self._slots = threading.Semaphore(self.prefetch)
        self._stop = threading.Event()
        self._resident = 0           # device bytes of yielded-but-live chunks
        self._resident_chunks = 0
        self._lock = threading.Lock()
        self._prev_bytes = 0
        self.closed = False
        self._stall_error: Optional[BaseException] = None
        self._t0 = time.perf_counter()
        # flight-recorder correlation: captured HERE on the constructing
        # (consumer/train) thread — contextvars do not cross into the
        # producer thread, so the producer stamps its upload events with
        # the owning run's id explicitly (observability/blackbox.py)
        self._corr = _blackbox.current_correlation()
        # hang watchdog: the producer beats this heart per loop iteration;
        # a wedge (dead reader, hung upload) stops the beats → the feed
        # aborts with a typed error instead of hanging the consumer
        self._heart = _watchdog.register(
            "tg-stream-feed", kind="stream.producer",
            on_stall=self._on_watchdog_stall)
        self._thread = threading.Thread(
            target=self._produce, name="tg-stream-feed", daemon=True)
        _LIVE.add(self)
        self._thread.start()

    def _on_watchdog_stall(self, heart, waited: float) -> None:
        """Watchdog stall response (scanner thread): abort the feed. The
        wedged producer cannot be killed, but the consumer must not wait
        on it forever — it sees a typed error on its next take."""
        err = WatchdogStallError(
            f"stream feed producer stalled {waited:.1f}s "
            f"(> TG_WATCHDOG_S); aborting the feed")
        self._stall_error = err
        self._stop.set()
        try:  # wake a consumer blocked on an empty queue
            self._q.put_nowait((self._SENTINEL, err))
        except queue.Full:
            pass

    # -- producer -------------------------------------------------------------
    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                self._heart.beat()
                if not self._slots.acquire(timeout=0.1):
                    continue
                faults.inject("stream.read")
                try:
                    chunk = next(self._chunks)
                except StopIteration:
                    self._put((self._SENTINEL, None))
                    return
                table = chunk.table
                for model in self._transforms:
                    table = model.transform(table)
                t0 = time.perf_counter()
                # crash evidence: an OOM-killed process dies right here —
                # the run sentinel's phase names the packed upload
                # (module-global ambient, so this producer thread sees the
                # trainer's sentinel)
                _sentinel_phase("device_upload")
                faults.inject("stream.upload")
                # chaos: a RESOURCE_EXHAUSTED here models the packed chunk
                # upload not fitting on the device — it forwards through
                # the queue and the trainer halves the chunk row budget
                faults.inject("oom.stream")
                if self._to_device:
                    table = table.to_device()
                nbytes = device_bytes(table)
                self.stats.upload_seconds += time.perf_counter() - t0
                self.stats.upload_bytes += nbytes
                with self._lock:
                    self._resident += nbytes
                    self._resident_chunks += 1
                    self.stats.max_chunk_bytes = max(
                        self.stats.max_chunk_bytes, nbytes)
                    self.stats.peak_device_bytes = max(
                        self.stats.peak_device_bytes, self._resident)
                    self.stats.peak_resident_chunks = max(
                        self.stats.peak_resident_chunks,
                        self._resident_chunks)
                _blackbox.record("stream.upload", corr=self._corr,
                                 chunk=chunk.index, bytes=nbytes)
                # device-memory observatory: the packed upload's shape-
                # derived bytes (the chunk-residency prediction) +
                # measured live-buffer peak where the backend reports it
                _devicemem.record_dispatch("stream", nbytes,
                                           rows=chunk.rows)
                _devicemem.sample_measured("stream")
                self._put((Chunk(chunk.index, chunk.chunk_id, table), nbytes))
        except BaseException as e:  # noqa: BLE001 — preemption must forward
            self._put((self._SENTINEL, e))
        finally:
            # a finished producer has nothing left to stall on; keeping
            # the heart open would flag a slow CONSUMER as a feed stall
            self._heart.close()

    def _put(self, item) -> None:
        while not self._stop.is_set():
            self._heart.beat()
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    # -- consumer -------------------------------------------------------------
    def __iter__(self) -> Iterator[Chunk]:
        return self

    def __next__(self) -> Chunk:
        self._release_prev()
        t0 = time.perf_counter()
        while True:
            try:
                item, extra = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                if self._stall_error is not None:
                    # watchdog abort: the producer is wedged — fail the
                    # consumer with the typed error instead of waiting
                    err = self._stall_error
                    self.close()
                    raise err
                if not self._thread.is_alive() and self._q.empty():
                    raise RuntimeError(
                        "stream feed producer died without a sentinel")
        self.stats.wait_seconds += time.perf_counter() - t0
        self._slots.release()
        if item is self._SENTINEL:
            self.stats.wall_seconds = time.perf_counter() - self._t0
            if extra is not None:
                self.close()
                raise extra
            self.close()
            raise StopIteration
        self._prev_bytes = extra
        self.stats.chunks += 1
        self.stats.rows += item.rows
        return item

    def _release_prev(self) -> None:
        if self._prev_bytes:
            with self._lock:
                self._resident -= self._prev_bytes
                self._resident_chunks -= 1
            self._prev_bytes = 0

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._stop.set()
        # drain so a blocked producer put() unblocks and exits
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # never discard a still-alive producer silently: record the
            # stall (thread_stalled FaultLog + tg_watchdog_stalls_total)
            # so it surfaces in summary()["faults"]["threadStalls"]
            _watchdog.report_thread_stalled(
                site="stream.close", thread_name=self._thread.name,
                waited_s=5.0)
        self._heart.close()
        if self.stats.wall_seconds == 0.0:
            self.stats.wall_seconds = time.perf_counter() - self._t0
        if _obs_metrics.metrics_enabled():
            _obs_metrics.inc_counter(
                "tg_stream_chunks_total", float(self.stats.chunks),
                help="chunks consumed through the streaming device feed")
            _obs_metrics.inc_counter(
                "tg_stream_rows_total", float(self.stats.rows),
                help="rows consumed through the streaming device feed")
            _obs_metrics.observe(
                "tg_stream_wait_seconds", self.stats.wait_seconds,
                help="consumer seconds stalled waiting on the chunk feed")

    def __enter__(self) -> "DeviceFeed":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
