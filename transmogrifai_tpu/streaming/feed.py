"""Streaming input engine: parallel chunk preparation + cached replay.

While the consumer folds chunk N, a pool of ``TG_STREAM_WORKERS``
producer threads (default min(4, cores); ``1`` reproduces the round-7
serial feed thread-for-thread and is the bench A/B baseline) prepares
the chunks behind it. Each worker *claims* the next schedule index —
gated on the same slot semaphore as always, so device residency stays
O(prefetch + 1 chunks), never O(dataset) — then runs read (chaos site
``stream.read``) + upstream host-side transform for its claim, while a
single ordered **committer** thread performs the packed host→device
uploads (``FeatureTable.to_device()``; chaos sites ``stream.upload`` /
``oom.stream``) and queue puts strictly in schedule order. Claims are
serialized under one lock, so fault-injection counters, chunk delivery
order, monoid fold results, and checkpoint/resume semantics are all
bit-identical to the serial feed at ANY worker count.

A :class:`~.cache.ChunkCache` (``TG_STREAM_CACHE_BYTES`` host LRU +
optional sha256-verified ``TG_STREAM_CACHE_DIR`` disk tier) short-cuts
the whole prep: a transformed chunk is a pure function of (source
fingerprint × chunk index × fitted-transform identity × chunk rows), so
repeat passes replay packed host blocks instead of re-reading and
re-transforming — and skip the upload entirely (every in-tree fold
consumes host numpy views, so a cache hit is byte-equal input with zero
h2d traffic; chaos site ``stream.cache`` = corrupt/evicted entry, which
falls back to a typed bit-equal recompute).

Accounting (:class:`FeedStats`) is what the stream bench line reports:
uploaded bytes, per-stage seconds (read / transform / upload — also
observed as ``tg_stream_stage_seconds{stage=...}``), cache hits/misses,
peak concurrently-resident device bytes (the O(chunk) claim, asserted
in tests), and the overlap fraction — the share of consumer wall-clock
NOT stalled waiting on the feed.

Error contract: any exception in a worker or the committer —
``SimulatedPreemption`` (a BaseException, modeling a kill mid-read/
mid-upload) included — is forwarded through the queue in schedule order
(chunks claimed before the failing one still deliver; the FIRST error
in schedule order wins) and re-raises in the consumer thread, so a
streamed ``train()`` dies exactly like an in-core one would, with the
last committed chunk checkpoint intact. Resource exhaustion
(``oom.stream``, or a real ``RESOURCE_EXHAUSTED`` from the packed
upload) forwards the same way; the trainer catches it, drains this pool
(``close()``), and re-chunks at half the row budget
(robustness/resources.py).

Hang contract: every worker beats its own watchdog heart
(robustness/watchdog.py, ``TG_WATCHDOG_S``), as does the committer. A
thread wedged inside a dead reader, a hung transform, or a stuck upload
stops beating; the stall is recorded (``thread_stalled`` +
``tg_watchdog_stalls_total``) and the feed ABORTS — the queue is
drained and the typed error put in its place, so a consumer blocked on
an empty OR full queue wakes deterministically instead of spinning.
``close()`` likewise records (never silently discards) any thread that
outlives its join timeout.
"""
from __future__ import annotations

import os
import queue
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Any, Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np

from ..manifest import sentinel_phase as _sentinel_phase
from ..observability import blackbox as _blackbox
from ..observability import devicemem as _devicemem
from ..observability import metrics as _obs_metrics
from ..robustness import faults
from ..robustness import watchdog as _watchdog
from ..robustness.watchdog import WatchdogStallError
from ..table import DEVICE_KINDS, FeatureTable
from .cache import ChunkCache, chunk_cache_key, pack_table
from .source import Chunk, ChunkSource

PREFETCH_ENV = "TG_STREAM_PREFETCH"
DEFAULT_PREFETCH = 1
WORKERS_ENV = "TG_STREAM_WORKERS"

#: live feeds (weak) — the conftest no-leak fixture asserts none survive
_LIVE: "weakref.WeakSet[DeviceFeed]" = weakref.WeakSet()


def live_feeds() -> List["DeviceFeed"]:
    return [f for f in list(_LIVE) if not f.closed]


def env_prefetch(prefetch: Optional[int] = None) -> int:
    if prefetch is not None:
        return max(1, int(prefetch))
    try:
        return max(1, int(os.environ.get(PREFETCH_ENV, "")
                          or DEFAULT_PREFETCH))
    except ValueError:
        return DEFAULT_PREFETCH


def env_workers(workers: Optional[int] = None) -> int:
    """Producer pool size: TG_STREAM_WORKERS, default min(4, cores).
    Note that concurrency is additionally gated by the slot semaphore —
    at most ``prefetch`` chunks are ever in flight, so real parallel
    prep needs ``TG_STREAM_PREFETCH >= workers`` (docs/streaming.md
    "Input engine")."""
    if workers is not None:
        return max(1, int(workers))
    try:
        raw = os.environ.get(WORKERS_ENV, "")
        if raw:
            return max(1, int(raw))
    except ValueError:
        pass
    return max(1, min(4, os.cpu_count() or 1))


def device_bytes(table: FeatureTable) -> int:
    """Bytes of device-kind column storage a chunk pins while resident.
    Masks charge their FULL element count × itemsize — a (n, d) validity
    mask is n·d bytes resident, not n."""
    total = 0
    for name in table.column_names:
        col = table[name]
        if col.kind not in DEVICE_KINDS:
            continue
        vals = col.values
        total += int(np.dtype(getattr(vals, "dtype", np.float32)).itemsize
                     * int(np.prod(np.shape(vals))))
        if col.mask is not None:
            m = col.mask
            total += int(np.dtype(getattr(m, "dtype", np.bool_)).itemsize
                         * int(np.prod(np.shape(m))))
    return total


@dataclass
class FeedStats:
    chunks: int = 0
    rows: int = 0
    upload_bytes: int = 0
    max_chunk_bytes: int = 0
    peak_device_bytes: int = 0
    peak_resident_chunks: int = 0
    read_seconds: float = 0.0
    transform_seconds: float = 0.0
    upload_seconds: float = 0.0
    wait_seconds: float = 0.0
    wall_seconds: float = 0.0
    cache_hits: int = 0
    cache_misses: int = 0

    def overlap_fraction(self) -> float:
        """Share of consumer wall-clock NOT stalled on the feed: 1.0 means
        read+transform+upload hid entirely behind fold compute."""
        if self.wall_seconds <= 0:
            return 0.0
        return max(0.0, 1.0 - self.wait_seconds / self.wall_seconds)

    def merge(self, other: "FeedStats") -> "FeedStats":
        self.chunks += other.chunks
        self.rows += other.rows
        self.upload_bytes += other.upload_bytes
        self.max_chunk_bytes = max(self.max_chunk_bytes,
                                   other.max_chunk_bytes)
        self.peak_device_bytes = max(self.peak_device_bytes,
                                     other.peak_device_bytes)
        self.peak_resident_chunks = max(self.peak_resident_chunks,
                                        other.peak_resident_chunks)
        self.read_seconds += other.read_seconds
        self.transform_seconds += other.transform_seconds
        self.upload_seconds += other.upload_seconds
        self.wait_seconds += other.wait_seconds
        self.wall_seconds += other.wall_seconds
        self.cache_hits += other.cache_hits
        self.cache_misses += other.cache_misses
        return self

    def to_json(self) -> dict:
        return {
            "chunks": self.chunks, "rows": self.rows,
            "uploadBytes": self.upload_bytes,
            "maxChunkBytes": self.max_chunk_bytes,
            "peakDeviceBytes": self.peak_device_bytes,
            "peakResidentChunks": self.peak_resident_chunks,
            "readSeconds": round(self.read_seconds, 4),
            "transformSeconds": round(self.transform_seconds, 4),
            "uploadSeconds": round(self.upload_seconds, 4),
            "waitSeconds": round(self.wait_seconds, 4),
            "overlapFraction": round(self.overlap_fraction(), 4),
            "cacheHits": self.cache_hits,
            "cacheMisses": self.cache_misses,
        }


class DeviceFeed:
    """Iterate device-resident chunks prepared by the input engine.

    ``chunks`` is either a :class:`~.source.ChunkSource` (engine mode —
    enables the transformed-chunk cache and, for random-access sources,
    parallel reads) or any iterable of :class:`Chunk` (legacy mode:
    reads stay sequential under the claim lock, transforms still
    parallelize). Usage (always close — ``with`` or the trainer's
    finally)::

        with DeviceFeed(source, transforms=models, start=k) as feed:
            for chunk in feed:
                ...fold chunk.table...
    """

    _SENTINEL = object()

    def __init__(self, chunks: Union[ChunkSource, Iterable[Chunk]],
                 transforms: Sequence[Any] = (),
                 prefetch: Optional[int] = None,
                 to_device: bool = True,
                 workers: Optional[int] = None,
                 cache: Optional[ChunkCache] = None,
                 cache_ident: str = "",
                 start: int = 0):
        if isinstance(chunks, ChunkSource):
            self._source: Optional[ChunkSource] = chunks
            self._start = int(start)
            self._it: Optional[Iterator[Chunk]] = None
            self._it_pos = self._start
        else:
            self._source = None
            self._start = 0
            self._it = iter(chunks)
            self._it_pos = 0
        self._transforms = list(transforms)
        self.workers = env_workers(workers)
        self.prefetch = env_prefetch(prefetch)
        self._to_device = to_device
        # the cache needs index-addressed claims — source mode only
        self._cache = cache if self._source is not None else None
        self._cache_ident = cache_ident
        if self._cache is not None:
            # bind the owning run's fault log now, on the consumer thread
            # — cache fallbacks recorded from producer threads would
            # otherwise miss the ambient (per-thread) log
            from ..robustness.policy import FaultLog
            self._cache.bind_log(FaultLog.current())
        self._random_access = bool(getattr(self._source, "random_access",
                                           False))
        self.stats = FeedStats()
        self._q: "queue.Queue" = queue.Queue(maxsize=self.prefetch + 1)
        #: production gate: the pool may hold at most ``prefetch`` chunks
        #: beyond the one being consumed — a worker acquires a slot BEFORE
        #: claiming an index (so before any read/transform/cache fetch),
        #: the consumer releases one per take. This is what keeps
        #: residency O(prefetch + 1) regardless of the worker count: with
        #: W workers but P slots, at most min(W, P) preps run concurrently.
        self._slots = threading.Semaphore(self.prefetch)
        self._stop = threading.Event()
        self._resident = 0           # device bytes of yielded-but-live chunks
        self._resident_chunks = 0
        self._lock = threading.Lock()
        self._prev_bytes = 0
        self.closed = False
        self._stall_error: Optional[BaseException] = None
        self._t0 = time.perf_counter()
        # claim/commit plane: workers claim monotonically increasing
        # sequence numbers under _claim_lock (seq s ↔ schedule index
        # start+s in source mode) and deposit results keyed by seq;
        # the committer consumes them strictly in seq order.
        self._claim_lock = threading.Lock()
        self._next_seq = 0
        self._ready = threading.Condition()
        self._results: dict = {}
        self._halt_seq: Optional[int] = None   # first end/error seq
        # flight-recorder correlation: captured HERE on the constructing
        # (consumer/train) thread — contextvars do not cross into the
        # producer threads, so they stamp their events with the owning
        # run's id explicitly (observability/blackbox.py)
        self._corr = _blackbox.current_correlation()
        # hang watchdog: every pool thread beats its own heart; a wedge
        # (dead reader, hung transform, stuck upload) stops that thread's
        # beats → the feed aborts with a typed error instead of hanging
        # the consumer
        self._heart = _watchdog.register(
            "tg-stream-feed", kind="stream.producer",
            on_stall=self._on_watchdog_stall)
        self._worker_hearts = [
            _watchdog.register(f"tg-stream-w{i}", kind="stream.producer",
                               on_stall=self._on_watchdog_stall)
            for i in range(self.workers)]
        self._thread = threading.Thread(
            target=self._commit_loop, name="tg-stream-feed", daemon=True)
        self._workers = [
            threading.Thread(target=self._work, args=(i,),
                             name=f"tg-stream-w{i}", daemon=True)
            for i in range(self.workers)]
        _LIVE.add(self)
        self._thread.start()
        for t in self._workers:
            t.start()

    def _on_watchdog_stall(self, heart, waited: float) -> None:
        """Watchdog stall response (scanner thread): abort the feed. The
        wedged thread cannot be killed, but the consumer must not wait on
        it forever — drain the queue and put the typed error in its
        place, so a consumer blocked on EITHER an empty or a full queue
        wakes deterministically (a bare ``put_nowait`` could drop on a
        full queue, leaving the consumer to spin until it polled
        ``_stall_error``)."""
        err = WatchdogStallError(
            f"stream feed producer stalled {waited:.1f}s "
            f"(> TG_WATCHDOG_S); aborting the feed")
        self._stall_error = err
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        try:  # wake a consumer blocked on the (now drained) queue
            self._q.put_nowait((self._SENTINEL, err))
        except queue.Full:
            pass

    # -- claim plane (workers) ------------------------------------------------
    def _key(self, index: int) -> str:
        return chunk_cache_key(self._source.fingerprint(), index,
                               self._cache_ident, self._source.chunk_rows)

    def _read_locked(self, index: int) -> Chunk:
        """Sequential read at ``index`` (claim lock held). After cache
        hits skipped ahead, the shared iterator reopens at the miss."""
        if self._it is None or self._it_pos != index:
            self._it = iter(self._source.chunks(index))
            self._it_pos = index
        chunk = next(self._it)
        self._it_pos = index + 1
        return chunk

    def _claim(self):
        """Claim the next schedule index. Returns ``(seq, index, chunk,
        packed)`` — ``packed`` set on a cache hit, ``chunk`` set when the
        read had to happen under the lock (sequential sources), both
        ``None`` for a random-access read the worker performs outside the
        lock — or ``None`` when there is nothing left to claim."""
        with self._claim_lock:
            if self._stop.is_set():
                return None
            with self._ready:
                if (self._halt_seq is not None
                        and self._next_seq >= self._halt_seq):
                    return None
            seq = self._next_seq
            self._next_seq += 1
            index = self._start + seq if self._source is not None else seq
            try:
                # ordered by claim → fault counters are schedule-
                # deterministic at any worker count
                faults.inject("stream.read")
                if self._cache is not None:
                    t0 = time.perf_counter()
                    packed = self._cache.get(self._key(index))
                    if packed is not None:
                        self._add_stage("read", time.perf_counter() - t0)
                        if not self._random_access:
                            self._it = None  # iterator is now behind
                        return seq, index, None, packed
                if self._random_access:
                    if index >= self._source.num_chunks:
                        self._finish(seq, ("end", None, False))
                        return None
                    return seq, index, None, None
                t0 = time.perf_counter()
                chunk = self._read_locked(index)
                self._add_stage("read", time.perf_counter() - t0)
                return seq, index, chunk, None
            except StopIteration:
                self._finish(seq, ("end", None, False))
                return None
            except BaseException as e:  # noqa: BLE001 — preemption forwards
                self._finish(seq, ("err", e, False))
                return None

    def _finish(self, seq: int, result) -> None:
        with self._ready:
            self._results[seq] = result
            if result[0] != "ok" and (self._halt_seq is None
                                      or seq < self._halt_seq):
                # first end/error in SCHEDULE order wins: chunks claimed
                # before it still deliver, later claims never start
                self._halt_seq = seq
            self._ready.notify_all()

    def _add_stage(self, stage: str, dt: float) -> None:
        with self._lock:
            if stage == "read":
                self.stats.read_seconds += dt
            elif stage == "transform":
                self.stats.transform_seconds += dt
            else:
                self.stats.upload_seconds += dt
        if _obs_metrics.metrics_enabled():
            _obs_metrics.observe(
                "tg_stream_stage_seconds", dt, stage=stage,
                help="seconds per chunk per input-engine stage")

    def _work(self, wid: int) -> None:
        heart = self._worker_hearts[wid]
        try:
            while not self._stop.is_set():
                heart.beat()
                if not self._slots.acquire(timeout=0.1):
                    continue
                claim = self._claim()
                if claim is None:
                    self._slots.release()
                    return
                seq, index, chunk, packed = claim
                try:
                    if packed is not None:
                        table = packed.unpack()
                        self._finish(seq, ("ok", Chunk(
                            index, self._source.chunk_id(index), table),
                            True))
                        continue
                    if chunk is None:  # random-access read, outside the lock
                        t0 = time.perf_counter()
                        chunk = self._source.read_chunk(index)
                        self._add_stage("read", time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    table = chunk.table
                    for model in self._transforms:
                        table = model.transform(table)
                    if self._cache is not None:
                        self._cache.put(self._key(chunk.index),
                                        pack_table(table))
                    self._add_stage("transform", time.perf_counter() - t0)
                    self._finish(seq, ("ok", Chunk(
                        chunk.index, chunk.chunk_id, table), False))
                except BaseException as e:  # noqa: BLE001
                    self._finish(seq, ("err", e, False))
                    return
        finally:
            heart.close()

    # -- commit plane (single ordered committer) ------------------------------
    def _commit_loop(self) -> None:
        expected = 0
        try:
            while not self._stop.is_set():
                with self._ready:
                    while (expected not in self._results
                           and not self._stop.is_set()):
                        self._heart.beat()
                        self._ready.wait(timeout=0.1)
                    if self._stop.is_set():
                        return
                    kind, payload, from_cache = self._results.pop(expected)
                expected += 1
                self._heart.beat()
                if kind == "end":
                    self._put((self._SENTINEL, None))
                    return
                if kind == "err":
                    self._put((self._SENTINEL, payload))
                    return
                chunk = payload
                t0 = time.perf_counter()
                # crash evidence: an OOM-killed process dies right here —
                # the run sentinel's phase names the packed upload
                # (module-global ambient, so this committer thread sees
                # the trainer's sentinel)
                _sentinel_phase("device_upload")
                faults.inject("stream.upload")
                # chaos: a RESOURCE_EXHAUSTED here models the packed chunk
                # upload not fitting on the device — it forwards through
                # the queue and the trainer halves the chunk row budget
                faults.inject("oom.stream")
                table = chunk.table
                if self._to_device and not from_cache:
                    table = table.to_device()
                nbytes = device_bytes(table)
                self._add_stage("upload", time.perf_counter() - t0)
                with self._lock:
                    if from_cache:
                        # a hit is delivered as host views of the cached
                        # packed blocks — nothing crossed the h2d link
                        self.stats.cache_hits += 1
                    else:
                        self.stats.upload_bytes += nbytes
                        if self._cache is not None:
                            self.stats.cache_misses += 1
                    self._resident += nbytes
                    self._resident_chunks += 1
                    self.stats.max_chunk_bytes = max(
                        self.stats.max_chunk_bytes, nbytes)
                    self.stats.peak_device_bytes = max(
                        self.stats.peak_device_bytes, self._resident)
                    self.stats.peak_resident_chunks = max(
                        self.stats.peak_resident_chunks,
                        self._resident_chunks)
                _blackbox.record("stream.upload", corr=self._corr,
                                 chunk=chunk.index, bytes=nbytes,
                                 fromCache=from_cache)
                if not from_cache:
                    # device-memory observatory: the packed upload's
                    # shape-derived bytes (the chunk-residency
                    # prediction) + measured live-buffer peak where the
                    # backend reports it
                    _devicemem.record_dispatch("stream", nbytes,
                                               rows=chunk.rows)
                    _devicemem.sample_measured("stream")
                self._put((Chunk(chunk.index, chunk.chunk_id, table),
                           nbytes))
        except BaseException as e:  # noqa: BLE001 — preemption must forward
            self._put((self._SENTINEL, e))
        finally:
            # a finished committer has nothing left to stall on; keeping
            # the heart open would flag a slow CONSUMER as a feed stall
            self._heart.close()

    def _put(self, item) -> None:
        while not self._stop.is_set():
            self._heart.beat()
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    # -- consumer -------------------------------------------------------------
    def __iter__(self) -> Iterator[Chunk]:
        return self

    def __next__(self) -> Chunk:
        self._release_prev()
        t0 = time.perf_counter()
        while True:
            try:
                item, extra = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                if self._stall_error is not None:
                    # watchdog abort: the pool is wedged — fail the
                    # consumer with the typed error instead of waiting
                    err = self._stall_error
                    self.close()
                    raise err
                if not self._thread.is_alive() and self._q.empty():
                    raise RuntimeError(
                        "stream feed producer died without a sentinel")
        self.stats.wait_seconds += time.perf_counter() - t0
        self._slots.release()
        if item is self._SENTINEL:
            self.stats.wall_seconds = time.perf_counter() - self._t0
            if extra is not None:
                self.close()
                raise extra
            self.close()
            raise StopIteration
        self._prev_bytes = extra
        self.stats.chunks += 1
        self.stats.rows += item.rows
        return item

    def _release_prev(self) -> None:
        if self._prev_bytes:
            with self._lock:
                self._resident -= self._prev_bytes
                self._resident_chunks -= 1
            self._prev_bytes = 0

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._stop.set()
        with self._ready:
            self._ready.notify_all()
        # drain so a blocked committer put() unblocks and exits
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        for t in self._workers:
            t.join(timeout=2.0)
        for t in [self._thread] + self._workers:
            if t.is_alive():
                # never discard a still-alive pool thread silently: record
                # the stall (thread_stalled FaultLog +
                # tg_watchdog_stalls_total) so it surfaces in
                # summary()["faults"]["threadStalls"]
                _watchdog.report_thread_stalled(
                    site="stream.close", thread_name=t.name,
                    waited_s=5.0 if t is self._thread else 2.0)
        self._heart.close()
        for h in self._worker_hearts:
            h.close()
        if self.stats.wall_seconds == 0.0:
            self.stats.wall_seconds = time.perf_counter() - self._t0
        if _obs_metrics.metrics_enabled():
            _obs_metrics.inc_counter(
                "tg_stream_chunks_total", float(self.stats.chunks),
                help="chunks consumed through the streaming device feed")
            _obs_metrics.inc_counter(
                "tg_stream_rows_total", float(self.stats.rows),
                help="rows consumed through the streaming device feed")
            _obs_metrics.observe(
                "tg_stream_wait_seconds", self.stats.wait_seconds,
                help="consumer seconds stalled waiting on the chunk feed")

    def __enter__(self) -> "DeviceFeed":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
