"""Monoid folds: accumulate / merge / finalize over chunked data.

The reference fits its prep stages with ``treeAggregate`` over RDD
partitions — associative, commutative combiners (reference:
SanityChecker.scala:574-638 colStats/corr, OpStatistics contingency,
aggregators.py monoids). This module is that contract rebuilt for the
chunked path: every fold exposes

* ``zero()``            — the identity state,
* ``accumulate(s, x)``  — fold one chunk's arrays into the state,
* ``merge(a, b)``       — combine two states (pure addition everywhere),
* ``finalize(s)``       — state → the statistic the in-core kernel returns,
* ``state_to_arrays`` / ``state_from_arrays`` — checkpointable plain-numpy
  state, so a kill mid-pass resumes bit-exactly from the last committed
  chunk (streaming/checkpoint.py).

Accumulators run in float64 on host: partial sums merge exactly enough
that the float32-finalized outputs are bit-identical across chunk
schedules (the f64 grouping error is ~2^-53 relative against a 2^-24
float32 ulp — six orders of headroom, asserted by the associativity tests
in tests/test_streaming.py). Counts (col counts, contingency cells,
nonzeros) are exact integers, so those are bit-equal unconditionally.
"""
from __future__ import annotations

import abc
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..utils.streaming_histogram import StreamingHistogram


class MonoidFold(abc.ABC):
    """The accumulate/merge/finalize contract (one fold = one pass)."""

    @abc.abstractmethod
    def zero(self) -> Any:
        ...

    @abc.abstractmethod
    def accumulate(self, state: Any, *chunk_args) -> Any:
        ...

    @abc.abstractmethod
    def merge(self, a: Any, b: Any) -> Any:
        ...

    @abc.abstractmethod
    def finalize(self, state: Any) -> Any:
        ...

    # -- checkpointing: state <-> flat dict of numpy arrays ------------------
    def state_to_arrays(self, state: Any) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in state.items()}

    def state_from_arrays(self, arrays: Dict[str, np.ndarray]) -> Any:
        return dict(arrays)


class StreamedColStats(NamedTuple):
    """Finalized per-column moments, matching ``ops.stats.ColStats``."""
    count: np.ndarray
    mean: np.ndarray
    variance: np.ndarray
    min: np.ndarray
    max: np.ndarray
    num_nonzeros: np.ndarray


class ColStatsFold(MonoidFold):
    """Masked per-column count/mean/var/min/max/nnz over (n, d) chunks —
    the streaming dual of ``ops.stats.col_stats`` (backs SanityChecker and
    the mean-fill vectorizers)."""

    def __init__(self, d: int):
        self.d = int(d)

    def zero(self) -> Dict[str, np.ndarray]:
        d = self.d
        return {
            "n": np.zeros(d, np.int64),
            "s1": np.zeros(d, np.float64),
            "s2": np.zeros(d, np.float64),
            "min": np.full(d, np.inf),
            "max": np.full(d, -np.inf),
            "nnz": np.zeros(d, np.int64),
        }

    def accumulate(self, state, X: np.ndarray,
                   mask: Optional[np.ndarray] = None):
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X[:, None]
        if mask is None:
            m = np.ones(X.shape, dtype=bool)
        else:
            m = np.asarray(mask, dtype=bool)
            if m.ndim == 1:
                m = m[:, None] & np.ones(X.shape, dtype=bool)
        Xv = np.where(m, X, 0.0)
        state["n"] = state["n"] + m.sum(axis=0)
        state["s1"] = state["s1"] + Xv.sum(axis=0)
        state["s2"] = state["s2"] + (Xv * Xv).sum(axis=0)
        state["min"] = np.minimum(state["min"],
                                  np.where(m, X, np.inf).min(axis=0))
        state["max"] = np.maximum(state["max"],
                                  np.where(m, X, -np.inf).max(axis=0))
        state["nnz"] = state["nnz"] + ((Xv != 0) & m).sum(axis=0)
        return state

    def merge(self, a, b):
        return {
            "n": a["n"] + b["n"], "s1": a["s1"] + b["s1"],
            "s2": a["s2"] + b["s2"],
            "min": np.minimum(a["min"], b["min"]),
            "max": np.maximum(a["max"], b["max"]),
            "nnz": a["nnz"] + b["nnz"],
        }

    def finalize(self, state) -> StreamedColStats:
        n = state["n"].astype(np.float64)
        safe = np.maximum(n, 1.0)
        mean = state["s1"] / safe
        # unbiased (n-1), matching Spark colStats / ops.stats.col_stats
        var = np.maximum(state["s2"] - n * mean * mean, 0.0) \
            / np.maximum(n - 1.0, 1.0)
        return StreamedColStats(
            count=n, mean=mean, variance=var,
            min=np.where(n > 0, state["min"], 0.0),
            max=np.where(n > 0, state["max"], 0.0),
            num_nonzeros=state["nnz"].astype(np.float64))


class CorrelationFold(MonoidFold):
    """Masked Pearson correlation of each column of X against y via exact
    co-moment sums (the streaming dual of ``ops.stats.pearson_correlation``;
    ``full=True`` also accumulates the (d, d) feature co-moment block for
    the full correlation matrix)."""

    def __init__(self, d: int, full: bool = False):
        self.d = int(d)
        self.full = bool(full)

    def zero(self):
        d = self.d
        st = {
            "n": np.zeros((), np.int64),
            "sx": np.zeros(d, np.float64), "sy": np.zeros((), np.float64),
            "sxx": np.zeros(d, np.float64), "syy": np.zeros((), np.float64),
            "sxy": np.zeros(d, np.float64),
        }
        if self.full:
            st["xtx"] = np.zeros((d, d), np.float64)
        return st

    def accumulate(self, state, X: np.ndarray, y: np.ndarray,
                   mask: Optional[np.ndarray] = None):
        X = np.asarray(X, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64).reshape(-1)
        if mask is not None:
            m = np.asarray(mask, dtype=bool)
            X = np.where(m[:, None], X, 0.0)
            y = np.where(m, y, 0.0)
            state["n"] = state["n"] + m.sum()
        else:
            state["n"] = state["n"] + X.shape[0]
        state["sx"] = state["sx"] + X.sum(axis=0)
        state["sy"] = state["sy"] + y.sum()
        state["sxx"] = state["sxx"] + (X * X).sum(axis=0)
        state["syy"] = state["syy"] + (y * y).sum()
        state["sxy"] = state["sxy"] + (X * y[:, None]).sum(axis=0)
        if self.full:
            state["xtx"] = state["xtx"] + X.T @ X
        return state

    def merge(self, a, b):
        return {k: a[k] + b[k] for k in a}

    def finalize(self, state) -> np.ndarray:
        n = max(float(state["n"]), 1.0)
        cov = state["sxy"] - state["sx"] * state["sy"] / n
        xvar = state["sxx"] - state["sx"] ** 2 / n
        yvar = state["syy"] - state["sy"] ** 2 / n
        denom = np.sqrt(np.maximum(xvar, 0.0) * max(yvar, 0.0))
        with np.errstate(invalid="ignore"):
            return np.where(denom > 0, cov / np.maximum(denom, 1e-30), np.nan)

    def finalize_matrix(self, state) -> np.ndarray:
        """(d, d) feature-feature correlations (``full=True`` states)."""
        n = max(float(state["n"]), 1.0)
        cov = state["xtx"] - np.outer(state["sx"], state["sx"]) / n
        std = np.sqrt(np.maximum(np.diag(cov), 0.0))
        denom = np.outer(std, std)
        with np.errstate(invalid="ignore"):
            return np.where(denom > 0, cov / np.maximum(denom, 1e-30), np.nan)


class ContingencyFold(MonoidFold):
    """(k, L) contingency counts of 0/1 indicator columns against an
    integer-ish label — exact int64 sums, so the fold is bit-equal to
    ``ops.stats.contingency_table`` under any chunk schedule. Labels are
    discovered as they stream; a label set that grows past ``max_labels``
    (or goes non-integer) flips the state invalid, matching the in-core
    checker's "not binary-like → skip contingency" branch."""

    def __init__(self, k: int, max_labels: int = 20):
        self.k = int(k)
        self.max_labels = int(max_labels)

    def zero(self):
        return {"labels": np.zeros(0, np.int64),
                "counts": np.zeros((0, self.k), np.int64),
                "invalid": np.zeros((), np.int64)}

    def accumulate(self, state, indicators: np.ndarray, y: np.ndarray,
                   mask: Optional[np.ndarray] = None):
        if int(state["invalid"]):
            return state
        ind = np.asarray(indicators, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64).reshape(-1)
        valid = np.isfinite(y)
        if mask is not None:
            valid &= np.asarray(mask, dtype=bool)
        yv = y[valid]
        if yv.size and not np.allclose(yv, np.round(yv)):
            state["invalid"] = np.ones((), np.int64)
            return state
        labels = state["labels"]
        counts = state["counts"]
        for lab in np.unique(yv).astype(np.int64):
            rows = valid & (y == lab)
            row_counts = np.round(ind[rows].sum(axis=0)).astype(np.int64)
            at = np.searchsorted(labels, lab)
            if at == labels.size or labels[at] != lab:
                labels = np.insert(labels, at, lab)
                counts = np.insert(counts, at, 0, axis=0)
            counts[at] += row_counts
        if labels.size > self.max_labels:
            state["invalid"] = np.ones((), np.int64)
            return state
        state["labels"], state["counts"] = labels, counts
        return state

    def merge(self, a, b):
        if int(a["invalid"]) or int(b["invalid"]):
            return {"labels": np.zeros(0, np.int64),
                    "counts": np.zeros((0, self.k), np.int64),
                    "invalid": np.ones((), np.int64)}
        labels = np.union1d(a["labels"], b["labels"])
        counts = np.zeros((labels.size, self.k), np.int64)
        for src in (a, b):
            idx = np.searchsorted(labels, src["labels"])
            counts[idx] += src["counts"]
        if labels.size > self.max_labels:
            return {"labels": np.zeros(0, np.int64),
                    "counts": np.zeros((0, self.k), np.int64),
                    "invalid": np.ones((), np.int64)}
        return {"labels": labels, "counts": counts,
                "invalid": np.zeros((), np.int64)}

    def finalize(self, state) -> Optional[np.ndarray]:
        """(k, L) table with L = max label + 1 (dense, like the in-core
        one-hot matmul); None when labels were not binary-like."""
        if int(state["invalid"]) or state["labels"].size == 0:
            return None
        labels = state["labels"]
        if labels.min() < 0:
            return None
        L = int(labels.max()) + 1
        if L > self.max_labels:
            return None
        out = np.zeros((self.k, L), np.int64)
        for i, lab in enumerate(labels.tolist()):
            out[:, lab] = state["counts"][i]
        return out


class HistogramFold(MonoidFold):
    """Per-column SPDT sketches (the Ben-Haim & Tom-Tov monoid,
    utils/streaming_histogram.py). State keeps the raw multiset of per-chunk
    bins and only compacts through the canonical ``StreamingHistogram.
    merged`` normalization — at a bounded spill cap and at finalize — so
    results cannot depend on merge grouping (the RFF sketch + streaming
    tree quantile-edge backing store). Rows beyond ``sample_stride`` are
    skipped deterministically (global-index stride), which keeps the sketch
    cost sublinear for edge-finding passes."""

    #: spill cap: compact the multiset when it exceeds this many bins/col
    SPILL_FACTOR = 32

    def __init__(self, d: int, max_bins: int = 64, sample_stride: int = 1):
        self.d = int(d)
        self.max_bins = int(max_bins)
        self.sample_stride = max(1, int(sample_stride))

    def zero(self):
        st = {"nulls": np.zeros(self.d, np.int64),
              "rows": np.zeros((), np.int64)}
        for j in range(self.d):
            st[f"c{j}"] = np.zeros(0, np.float64)
            st[f"m{j}"] = np.zeros(0, np.float64)
            st[f"r{j}"] = np.array([np.inf, -np.inf])
        return st

    def accumulate(self, state, X: np.ndarray,
                   mask: Optional[np.ndarray] = None,
                   row_offset: int = 0):
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X[:, None]
        n = X.shape[0]
        if mask is None:
            m = np.ones(X.shape, dtype=bool)
        else:
            m = np.asarray(mask, dtype=bool)
            if m.ndim == 1:
                m = m[:, None] & np.ones(X.shape, dtype=bool)
        state["rows"] = state["rows"] + n
        state["nulls"] = state["nulls"] + (~m).sum(axis=0)
        take = (np.arange(row_offset, row_offset + n)
                % self.sample_stride) == 0
        for j in range(self.d):
            vals = X[take & m[:, j], j] if self.sample_stride > 1 \
                else X[m[:, j], j]
            if not vals.size:
                continue
            h = StreamingHistogram(self.max_bins).update(vals)
            st = h.to_state()
            state[f"c{j}"] = np.concatenate([state[f"c{j}"], st["centers"]])
            state[f"m{j}"] = np.concatenate([state[f"m{j}"], st["masses"]])
            state[f"r{j}"] = np.array([min(state[f"r{j}"][0], h.min),
                                       max(state[f"r{j}"][1], h.max)])
            if state[f"c{j}"].size > self.max_bins * self.SPILL_FACTOR:
                self._compact(state, j)
        return state

    def _hist_of(self, state, j) -> StreamingHistogram:
        """The raw bin-multiset carrier for column ``j``. INTERNAL: the
        state concatenates one sorted run per accumulated chunk, so the
        bins are NOT globally sorted — only ``StreamingHistogram.merged``
        (which lexsorts + coalesces) may consume this; ``sum``/``density``
        on it would silently interpolate garbage."""
        return StreamingHistogram.from_state({
            "max_bins": max(self.max_bins, state[f"c{j}"].size),
            "centers": state[f"c{j}"], "masses": state[f"m{j}"],
            "total": state[f"m{j}"].sum(),
            "min": state[f"r{j}"][0], "max": state[f"r{j}"][1]})

    def column_histogram(self, state, j: int) -> StreamingHistogram:
        """Column ``j``'s canonical sketch (≤ max_bins bins, queryable) —
        the public single-column accessor. RawFeatureFilter distributions
        and the serving DriftMonitor both build their
        ``FeatureDistribution`` views through it
        (filters/distribution.py ``fold_distribution``)."""
        return StreamingHistogram.merged([self._hist_of(state, j)],
                                         max_bins=self.max_bins)

    def _compact(self, state, j) -> None:
        h = StreamingHistogram.merged([self._hist_of(state, j)],
                                      max_bins=self.max_bins)
        st = h.to_state()
        state[f"c{j}"], state[f"m{j}"] = st["centers"], st["masses"]

    def merge(self, a, b):
        out = {"nulls": a["nulls"] + b["nulls"], "rows": a["rows"] + b["rows"]}
        for j in range(self.d):
            out[f"c{j}"] = np.concatenate([a[f"c{j}"], b[f"c{j}"]])
            out[f"m{j}"] = np.concatenate([a[f"m{j}"], b[f"m{j}"]])
            out[f"r{j}"] = np.array([min(a[f"r{j}"][0], b[f"r{j}"][0]),
                                     max(a[f"r{j}"][1], b[f"r{j}"][1])])
        return out

    def finalize(self, state) -> List[StreamingHistogram]:
        """One canonical sketch per column (≤ max_bins bins each)."""
        return [self.column_histogram(state, j) for j in range(self.d)]

    def fill_rates(self, state) -> np.ndarray:
        """Per-column fill fraction — the RawFeatureFilter backing stat."""
        rows = max(float(state["rows"]), 1.0)
        return 1.0 - state["nulls"].astype(np.float64) / rows


class CompositeFold(MonoidFold):
    """Several folds over the same pass, one shared chunk extraction.
    ``accumulate`` takes ``{name: chunk_args_tuple}``."""

    def __init__(self, folds: Dict[str, MonoidFold]):
        self.folds = dict(folds)

    def zero(self):
        return {k: f.zero() for k, f in self.folds.items()}

    def accumulate(self, state, parts: Dict[str, Tuple]):
        for k, f in self.folds.items():
            if k in parts:
                state[k] = f.accumulate(state[k], *parts[k])
        return state

    def merge(self, a, b):
        return {k: f.merge(a[k], b[k]) for k, f in self.folds.items()}

    def finalize(self, state):
        return {k: f.finalize(state[k]) for k, f in self.folds.items()}

    def state_to_arrays(self, state):
        out: Dict[str, np.ndarray] = {}
        for k, f in self.folds.items():
            for kk, v in f.state_to_arrays(state[k]).items():
                out[f"{k}.{kk}"] = v
        return out

    def state_from_arrays(self, arrays):
        split: Dict[str, Dict[str, np.ndarray]] = {k: {} for k in self.folds}
        for kk, v in arrays.items():
            name, sub = kk.split(".", 1)
            split[name][sub] = v
        return {k: f.state_from_arrays(split[k])
                for k, f in self.folds.items()}


class ArraySumFold(MonoidFold):
    """Plain float64 array addition under fixed keys — the workhorse for
    streaming tree level stats (per node×feature×bin count/sum/sumsq)."""

    def __init__(self, shapes: Dict[str, Tuple[int, ...]]):
        self.shapes = dict(shapes)

    def zero(self):
        return {k: np.zeros(s, np.float64) for k, s in self.shapes.items()}

    def accumulate(self, state, parts: Dict[str, np.ndarray]):
        for k, v in parts.items():
            state[k] = state[k] + v
        return state

    def merge(self, a, b):
        return {k: a[k] + b[k] for k in self.shapes}

    def finalize(self, state):
        return state
