"""Per-chunk fold-state checkpointing through the integrity manifest.

Every ``TG_STREAM_CKPT_EVERY`` chunks (default 1) the in-flight fold state
serializes to an npz written atomically (tmp + fsync + rename,
manifest.atomic_write_bytes) and commits through the checkpoint
directory's ``MANIFEST.json`` ``streams`` section — the same
write-then-commit protocol stage checkpoints use (PR 2), so a kill at ANY
instruction leaves either the previous committed chunk or the new one
authoritative, never a torn state:

* the state file for chunk ``k`` gets a fresh name (``...:<k>.npz``); the
  previous chunk's file is deleted only AFTER the manifest commit, so a
  kill between payload write and commit leaves the old record intact;
* every record embeds the source fingerprint + pass id + stage uid;
  restore verifies all three plus the sha256 before trusting a state, and
  refolds the pass from scratch (deterministically identical) on any
  mismatch — corruption is detected and reported, never silently used.
"""
from __future__ import annotations

import io
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..manifest import CheckpointManifest, atomic_write_bytes
from ..robustness.policy import FaultLog, FaultReport

CKPT_EVERY_ENV = "TG_STREAM_CKPT_EVERY"

#: chunk marker recorded when a pass's fold is complete
PASS_COMPLETE = -1


def env_ckpt_every() -> int:
    try:
        return max(1, int(os.environ.get(CKPT_EVERY_ENV, "") or 1))
    except ValueError:
        return 1


def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


class StreamCheckpoint:
    """Fold-state persistence for one checkpoint directory + one source."""

    def __init__(self, dirpath: str, manifest: CheckpointManifest,
                 source_fingerprint: str):
        self.dirpath = dirpath
        self.manifest = manifest
        self.fingerprint = source_fingerprint
        self.every = env_ckpt_every()

    def _fname(self, key: str, chunk: int) -> str:
        safe = key.replace("/", "_").replace(":", "_")
        return f"stream_{safe}_{max(chunk, 0)}.npz"

    # -- commit ---------------------------------------------------------------
    def commit(self, key: str, arrays: Dict[str, np.ndarray],
               next_chunk: int, fingerprint: Optional[str] = None,
               chunk_rows: Optional[int] = None) -> None:
        """Persist ``arrays`` as the fold state with chunks < ``next_chunk``
        folded in (``PASS_COMPLETE`` = the pass finished).

        ``fingerprint``/``chunk_rows`` override the source identity the
        record commits to — the memory-pressure downshift re-chunks the
        source mid-pass (streaming/trainer.py), and the record must carry
        the *active* chunking so a killed downshifted train resumes
        against the same schedule, bit-exactly."""
        os.makedirs(self.dirpath, exist_ok=True)
        rec = self.manifest.streams.get(key)
        prev_file = rec.get("file") if rec else None
        fname = self._fname(key, next_chunk)
        data = _npz_bytes(arrays)
        sha = atomic_write_bytes(os.path.join(self.dirpath, fname), data)
        self.manifest.record_file(fname, sha, len(data))
        extra = {"fingerprint": fingerprint or self.fingerprint,
                 "chunk": int(next_chunk)}
        if chunk_rows is not None:
            extra["chunkRows"] = int(chunk_rows)
        self.manifest.complete_stream(key, fname, extra)
        if prev_file and prev_file != fname:
            self.manifest.files.pop(prev_file, None)
        self.manifest.save()          # ← the commit point
        if prev_file and prev_file != fname:
            try:
                os.remove(os.path.join(self.dirpath, prev_file))
            except OSError:
                pass

    # -- restore --------------------------------------------------------------
    def restore(self, key: str, fingerprint: Optional[str] = None,
                ) -> Tuple[Optional[Dict[str, np.ndarray]], int]:
        """(state arrays, next chunk to fold) — ``(None, 0)`` when nothing
        committed/verifiable for this key+fingerprint. A verified complete
        pass returns ``(state, PASS_COMPLETE)``. ``fingerprint`` overrides
        the expected source identity (the trainer passes a downshifted
        source's fingerprint when the record carries its ``chunkRows``)."""
        rec = self.manifest.streams.get(key)
        if rec is None:
            return None, 0
        reason = None
        if rec.get("fingerprint") != (fingerprint or self.fingerprint):
            reason = ("source fingerprint mismatch — resumed against "
                      "different data or chunking")
        else:
            reason = self.manifest.verify_file(rec["file"])
        if reason is None:
            try:
                with np.load(os.path.join(self.dirpath, rec["file"]),
                             allow_pickle=False) as z:
                    arrays = {k: z[k] for k in z.files}
                return arrays, int(rec.get("chunk", 0))
            except (OSError, ValueError) as e:
                reason = f"unreadable state: {type(e).__name__}: {e}"
        FaultLog.record(FaultReport(
            site="stream.checkpoint", kind="checkpoint_skipped",
            detail={"key": key, "file": rec.get("file"), "reason": reason}))
        return None, 0
