"""Streaming gradient-boosted trees on merged SPDT histograms.

This is the Ben-Haim & Tom-Tov streaming decision tree (JMLR 2010) used
exactly as the paper prescribes, extended to squared-loss gradient
boosting: split candidates come from merged streaming-histogram quantile
edges (one sketch pass), and each tree level is grown from per
(node, feature, bin) residual statistics accumulated as an exact-f64
monoid fold over chunks — one pass per level plus one leaf pass, one pass
per boosting round for residual recomputation (the ensemble re-predicts
each chunk on the fly; nothing is ever materialized). Every pass
checkpoints per-chunk (streaming/checkpoint.py), so a kill anywhere
resumes to a bit-identical model.

Parity note (docs/streaming.md "Trees"): the in-core tree families
(models/trees.py) bin features by exact sample quantiles on device; this
trainer bins by SPDT sketch quantiles on host. Same split-finder math,
approximate edges — streamed-vs-in-core tree parity is therefore
*tolerance*, not bit-equality (the in-core ``fit`` here IS the one-chunk
streamed fold, so the two paths share every line of arithmetic).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..histeng import bin_codes_host, build_node_hist, node_stat_sums
from ..stages.base import AllowLabelAsInput, Estimator, Transformer
from ..table import Column, FeatureTable
from ..types import OPVector, Prediction, RealNN
from .folds import ArraySumFold, ColStatsFold, CompositeFold, HistogramFold

#: total rows (per feature) sampled into the edge-finding sketch pass
HIST_SAMPLE_ROWS = 65_536


def _descend(X: np.ndarray, feat_lv: List[np.ndarray],
             thr_lv: List[np.ndarray], upto: Optional[int] = None
             ) -> np.ndarray:
    """Vectorized node assignment after ``upto`` split levels (stopped
    nodes — feat < 0 — deterministically route left)."""
    n, d = X.shape
    node = np.zeros(n, dtype=np.int64)
    levels = len(feat_lv) if upto is None else upto
    rows = np.arange(n)
    for lv in range(levels):
        f = feat_lv[lv][node]
        t = thr_lv[lv][node]
        xf = X[rows, np.clip(f, 0, d - 1)]
        right = (f >= 0) & (xf > t)
        node = node * 2 + right
    return node


def _tree_values(X: np.ndarray, tree: Dict[str, Any]) -> np.ndarray:
    leaf_idx = _descend(X, tree["feat_lv"], tree["thr_lv"])
    return tree["leaf"][leaf_idx]


def _ensemble_raw(X: np.ndarray, f0: float, lr: float,
                  trees: List[Dict[str, Any]]) -> np.ndarray:
    F = np.full(X.shape[0], f0, dtype=np.float64)
    for tree in trees:
        F += lr * _tree_values(X, tree)
    return F


class StreamingGBT(AllowLabelAsInput, Estimator):
    """Estimator[(RealNN label, OPVector features)] → Prediction, fit as
    streaming folds — the model stage ``OpWorkflow.train(stream=...)``
    pipelines end in. ``problem='regression'`` boosts squared loss on y;
    ``'binary'`` boosts squared loss on y ∈ {0,1} (LS-Boost) and emits
    clipped probabilities. ``fit`` on an in-memory table runs the identical
    fold over a single chunk."""

    input_types = (RealNN, OPVector)
    output_type = Prediction

    def __init__(self, problem: str = "binary", num_trees: int = 3,
                 max_depth: int = 4, n_bins: int = 32,
                 learning_rate: float = 0.3,
                 min_instances_per_node: int = 16,
                 min_info_gain: float = 1e-9,
                 uid: Optional[str] = None):
        super().__init__("streamingGBT", uid)
        if problem not in ("binary", "regression"):
            raise ValueError(
                f"StreamingGBT supports binary|regression, got {problem!r}")
        self.problem = problem
        self.num_trees = int(num_trees)
        self.max_depth = int(max_depth)
        self.n_bins = int(n_bins)
        self.learning_rate = float(learning_rate)
        self.min_instances_per_node = int(min_instances_per_node)
        self.min_info_gain = float(min_info_gain)

    # -- in-core fit == one-chunk streamed fold ------------------------------
    def fit(self, table: FeatureTable) -> Transformer:
        from .source import TableChunkSource
        from .trainer import StreamRun
        run = StreamRun(TableChunkSource(table, max(1, table.num_rows)),
                        upstream=[], stage_uid=self.uid)
        return self.fit_streaming(run)

    # -- streaming fit -------------------------------------------------------
    def _xy(self, table: FeatureTable) -> Tuple[np.ndarray, np.ndarray]:
        label_f, vec_f = self.input_features
        X = np.asarray(table[vec_f.name].values, dtype=np.float32)
        y = np.asarray(table[label_f.name].values,
                       dtype=np.float32).reshape(-1)
        return X, y

    def fit_streaming(self, run) -> Transformer:
        # NOTE (round 20): pass ids ("edges", "t{t}.l{lv}", "t{t}.leaf")
        # are a persistence contract — stream checkpoint keys embed them,
        # so renaming one orphans committed fold states on resume. The
        # input-engine cache keys by (source × upstream identity × chunk
        # rows), NOT by pass id: all 1 + trees×(depth+1) passes here share
        # one upstream stack, which is exactly why passes ≥ 2 replay
        # cached transformed chunks instead of re-preparing them.
        probe = self.get_probe_width(run)
        d = probe
        nb = max(2, self.n_bins)
        depth = max(1, self.max_depth)

        # pass 0 — quantile edges from merged SPDT sketches + the label
        # moments for the base score (one combined pass)
        total_rows = run.num_chunks * run.chunk_rows
        stride = max(1, total_rows // HIST_SAMPLE_ROWS)
        sketch = CompositeFold({
            "hist": HistogramFold(d, max_bins=4 * nb, sample_stride=stride),
            "y": ColStatsFold(1),
        })

        def extract_sketch(table: FeatureTable):
            X, y = self._xy(table)
            return ({"hist": (X,), "y": (y[:, None],)},)

        st = run.fold("edges", sketch, extract_sketch)
        hists = sketch.folds["hist"].finalize(st["hist"])
        ystats = sketch.folds["y"].finalize(st["y"])
        f0 = float(ystats.mean[0])
        edges = np.full((d, nb - 1), np.inf, dtype=np.float64)
        for j, h in enumerate(hists):
            b = h.uniform(nb)
            edges[j, :b.shape[0]] = b

        # boosting rounds: depth level passes + one leaf pass each
        trees: List[Dict[str, Any]] = []
        lr = self.learning_rate
        for t in range(self.num_trees):
            feat_lv: List[np.ndarray] = []
            thr_lv: List[np.ndarray] = []
            for lv in range(depth):
                n_nodes = 2 ** lv
                fold = ArraySumFold({"cnt": (n_nodes, d, nb),
                                     "sum": (n_nodes, d, nb),
                                     "sumsq": (n_nodes, d, nb)})

                def extract_level(table: FeatureTable, feat_lv=feat_lv,
                                  thr_lv=thr_lv, n_nodes=n_nodes):
                    X, y = self._xy(table)
                    r = (y.astype(np.float64)
                         - _ensemble_raw(X, f0, lr, trees))
                    node = _descend(X, feat_lv, thr_lv)
                    # histogram-engine host backend: the same flat-bincount
                    # arithmetic this trainer used to carry inline, bit for
                    # bit (tests/test_histeng.py pins the equality)
                    codes = bin_codes_host(X, edges)
                    cnt, s, sq = build_node_hist(
                        codes, node, [None, r, r * r], nb, n_nodes=n_nodes)
                    return ({"cnt": cnt, "sum": s, "sumsq": sq},)

                st = run.fold(f"t{t}.l{lv}", fold, extract_level)
                feat, thr = self._best_splits(st, edges)
                feat_lv.append(feat)
                thr_lv.append(thr)

            leaf_nodes = 2 ** depth
            leaf_fold = ArraySumFold({"cnt": (leaf_nodes,),
                                      "sum": (leaf_nodes,)})

            def extract_leaf(table: FeatureTable, feat_lv=feat_lv,
                             thr_lv=thr_lv, leaf_nodes=leaf_nodes):
                X, y = self._xy(table)
                r = (y.astype(np.float64)
                     - _ensemble_raw(X, f0, lr, trees))
                node = _descend(X, feat_lv, thr_lv)
                cnt, s = node_stat_sums(node, [None, r], leaf_nodes)
                return ({"cnt": cnt, "sum": s},)

            st = run.fold(f"t{t}.leaf", leaf_fold, extract_leaf)
            leaf = np.where(st["cnt"] > 0, st["sum"]
                            / np.maximum(st["cnt"], 1.0), 0.0)
            trees.append({"feat_lv": feat_lv, "thr_lv": thr_lv,
                          "leaf": leaf})

        model = StreamingGBTModel(
            problem=self.problem, f0=f0, learning_rate=lr, trees=trees,
            num_features=d)
        model.summary_metadata = {
            "problem": self.problem, "numTrees": len(trees),
            "maxDepth": depth, "nBins": nb, "f0": f0,
            "learningRate": lr,
            "streaming": run.stats.to_json(),
        }
        return self._finalize_model(model)

    def get_probe_width(self, run) -> int:
        _, vec_f = self.input_features
        probe = run.probe_table()
        return probe[vec_f.name].width

    def _best_splits(self, stats: Dict[str, np.ndarray], edges: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """Variance-gain split per node from (node, feature, bin) stats —
        the SPDT split finder, vectorized over every candidate at once."""
        cnt, s, q = stats["cnt"], stats["sum"], stats["sumsq"]
        n_nodes, d, nb = cnt.shape
        CL = np.cumsum(cnt, axis=2)[:, :, :-1]
        SL = np.cumsum(s, axis=2)[:, :, :-1]
        QL = np.cumsum(q, axis=2)[:, :, :-1]
        # per-node totals are feature-independent (vector rows carry no
        # mask); feature 0's bins are the canonical accumulator
        CT = cnt[:, 0, :].sum(axis=1)[:, None, None]
        ST = s[:, 0, :].sum(axis=1)[:, None, None]
        QT = q[:, 0, :].sum(axis=1)[:, None, None]
        CR, SR, QR = CT - CL, ST - SL, QT - QL

        def sse(c, sv, qv):
            return np.where(c > 0, qv - sv * sv / np.maximum(c, 1.0), 0.0)

        gain = sse(CT, ST, QT) - sse(CL, SL, QL) - sse(CR, SR, QR)
        feasible = ((CL >= self.min_instances_per_node)
                    & (CR >= self.min_instances_per_node)
                    & np.isfinite(edges[None, :, :]))
        gain = np.where(feasible, gain, -np.inf)
        flat = gain.reshape(n_nodes, d * (nb - 1))
        best = flat.argmax(axis=1)          # ties → lowest feature/bin
        best_gain = flat[np.arange(n_nodes), best]
        bf = (best // (nb - 1)).astype(np.int64)
        bb = best % (nb - 1)
        ok = best_gain > self.min_info_gain
        feat = np.where(ok, bf, -1)
        thr = np.where(ok, edges[bf, bb], np.nan)
        return feat, thr


class StreamingGBTModel(Transformer):
    """Fitted streaming ensemble: Prediction emission via vectorized
    descent (host numpy — the model is small; serving batches route
    through the same arrays)."""

    output_type = Prediction

    def __init__(self, problem: str, f0: float, learning_rate: float,
                 trees: List[Dict[str, Any]], num_features: int, uid=None):
        super().__init__("streamingGBT", uid)
        self.problem = problem
        self.f0 = f0
        self.learning_rate = learning_rate
        self.trees = trees
        self.num_features = num_features

    def _parts(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        F = _ensemble_raw(X, self.f0, self.learning_rate, self.trees)
        if self.problem == "binary":
            p = np.clip(F, 1e-6, 1.0 - 1e-6)
            return {"prediction": (F > 0.5).astype(np.float64),
                    "probability": np.stack([1.0 - p, p], axis=1)}
        return {"prediction": F}

    def transform_column(self, table: FeatureTable) -> Column:
        from ..impl.selector.model_selector import prediction_column
        _, vec_f = self.input_features
        X = np.asarray(table[vec_f.name].values, dtype=np.float32)
        return prediction_column(self._parts(X))

    def transform_row(self, row: Dict[str, Any]) -> Any:
        _, vec_f = self.input_features
        v = np.asarray(row.get(vec_f.name) or [], dtype=np.float32)[None, :]
        parts = self._parts(v)
        out = {"prediction": float(parts["prediction"][0])}
        if "probability" in parts:
            for i, x in enumerate(parts["probability"][0]):
                out[f"probability_{i}"] = float(x)
        return out
