"""Streaming DAG trainer: layer-wise estimator fits as chunk folds.

The streaming dual of ``dag.fit_and_transform_dag``: estimators fit
layer-by-layer, but instead of one in-memory table each fit makes one or
more *passes* over the :class:`~.source.ChunkSource` through the
double-buffered :class:`~.feed.DeviceFeed`, with every chunk transformed
through the already-fitted upstream stages inside the producer thread (so
transform + upload overlap the fold compute). An estimator opts in by
implementing::

    def fit_streaming(self, run: StreamRun) -> Transformer

and drives its passes through ``run.fold(pass_id, fold, extract)`` — which
is where chunk checkpointing (streaming/checkpoint.py), the ``stream.fold``
chaos site, observability spans, and the O(chunk) memory bound all live.
Estimators without the hook fail the train with a descriptive error
(docs/streaming.md "What can stream") — a streamed fit must never silently
materialize the dataset.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..observability.trace import span as _obs_span
from ..robustness import faults
from ..robustness.policy import FaultLog, FaultReport
from ..stages.base import Estimator, Transformer
from ..table import FeatureTable
from .checkpoint import PASS_COMPLETE, StreamCheckpoint
from .feed import DeviceFeed, FeedStats
from .folds import MonoidFold
from .source import ChunkSource


class StreamingNotSupportedError(TypeError):
    """A DAG stage cannot fit as a streaming fold. Names the stage and the
    hook it would need — the streamed train fails up front instead of
    materializing the dataset behind the caller's back."""


class StreamRun:
    """One estimator's view of the stream: fold passes + a schema probe."""

    def __init__(self, source: ChunkSource, upstream: List[Transformer],
                 stage_uid: str, checkpoint: Optional[StreamCheckpoint] = None,
                 prefetch: Optional[int] = None,
                 stats: Optional[FeedStats] = None):
        self.source = source
        self.upstream = list(upstream)
        self.stage_uid = stage_uid
        self.checkpoint = checkpoint
        self.prefetch = prefetch
        self.stats = stats if stats is not None else FeedStats()
        self._probe: Optional[FeatureTable] = None

    @property
    def num_chunks(self) -> int:
        return self.source.num_chunks

    @property
    def chunk_rows(self) -> int:
        return self.source.chunk_rows

    def probe_table(self, rows: int = 256) -> FeatureTable:
        """A small transformed head-of-stream table for schema/metadata
        (vector widths, vector_meta groups) — never the data itself."""
        if self._probe is None:
            chunk = next(iter(self.source.chunks(0)))
            table = chunk.table
            if table.num_rows > rows:
                table = table.take(np.arange(rows))
            for model in self.upstream:
                table = model.transform(table)
            self._probe = table
        return self._probe

    def fold(self, pass_id: str, fold: MonoidFold,
             extract: Callable[[FeatureTable], Tuple]) -> Any:
        """Run one full pass: ``state = fold(extract(chunk) for chunks)``.

        Restores a committed state for this (stage, pass) and continues
        from the next un-folded chunk; commits after every
        TG_STREAM_CKPT_EVERY chunks and marks the pass complete at the
        end — so a resumed train re-executes no completed pass and no
        committed chunk, bit-exactly."""
        key = f"{self.stage_uid}/{pass_id}"
        state, start = None, 0
        if self.checkpoint is not None:
            arrays, start = self.checkpoint.restore(key)
            if arrays is not None:
                state = fold.state_from_arrays(arrays)
                if start == PASS_COMPLETE:
                    FaultLog.record(FaultReport(
                        site="stream.fold", kind="restored",
                        detail={"key": key, "pass": pass_id}))
                    return state
                FaultLog.record(FaultReport(
                    site="stream.fold", kind="restored",
                    detail={"key": key, "pass": pass_id,
                            "fromChunk": start}))
        if state is None:
            state, start = fold.zero(), 0
        every = self.checkpoint.every if self.checkpoint is not None else 0
        with _obs_span("stream.pass", cat="train", uid=self.stage_uid,
                       passId=pass_id, fromChunk=start), \
                DeviceFeed(self.source.chunks(start),
                           transforms=self.upstream,
                           prefetch=self.prefetch) as feed:
            for chunk in feed:
                faults.inject("stream.fold", key=pass_id)
                state = fold.accumulate(state, *extract(chunk.table))
                done = chunk.index + 1
                if (self.checkpoint is not None
                        and done < self.num_chunks
                        and (done - start) % every == 0):
                    self.checkpoint.commit(
                        key, fold.state_to_arrays(state), done)
            self.stats.merge(feed.stats)
        if self.checkpoint is not None:
            self.checkpoint.commit(key, fold.state_to_arrays(state),
                                   PASS_COMPLETE)
        return state


def fit_dag_streaming(source: ChunkSource, layers, *,
                      checkpoint: Optional[Callable] = None,
                      stream_checkpoint: Optional[StreamCheckpoint] = None,
                      preloaded: Optional[Dict[str, Any]] = None,
                      retry_policy: Optional[Any] = None,
                      prefetch: Optional[int] = None,
                      ) -> Tuple[Dict[str, Any], List[Transformer], FeedStats]:
    """Fit every estimator in the layered DAG as streaming folds.

    Returns ``(fitted {uid → model}, topological transformer order,
    aggregate feed stats)``. Mirrors ``dag.fit_and_transform_dag``'s
    checkpoint/preload/retry contract (docs/robustness.md) — ``preloaded``
    stages restore instead of refitting, ``checkpoint(model)`` commits each
    fitted stage, transient errors retry under ``retry_policy``.
    """
    pre = preloaded or {}
    fitted: Dict[str, Any] = {}
    upstream: List[Transformer] = []
    stats = FeedStats()
    for li, layer in enumerate(layers):
        models: List[Transformer] = []
        for stage, _ in layer:
            if isinstance(stage, Estimator):
                if stage.uid in pre:
                    model = pre[stage.uid]
                    model.input_features = stage.input_features
                    model._output_feature = stage.get_output()
                    FaultLog.record(FaultReport(
                        site="dag.stage_fit", kind="restored",
                        detail={"uid": stage.uid,
                                "stage": type(stage).__name__}))
                elif hasattr(stage, "fit_streaming"):
                    def _fit(stage=stage, li=li):
                        faults.inject("preempt.stage_fit", key=stage.uid)
                        run = StreamRun(source, upstream, stage.uid,
                                        checkpoint=stream_checkpoint,
                                        prefetch=prefetch, stats=stats)
                        with _obs_span("stream.fit", cat="train",
                                       uid=stage.uid,
                                       stage=type(stage).__name__,
                                       layer=li,
                                       chunks=source.num_chunks):
                            return stage.fit_streaming(run)
                    if retry_policy is not None:
                        model = retry_policy.execute(
                            _fit, site=f"stream.stage_fit[{stage.uid}]")
                    else:
                        model = _fit()
                    if checkpoint is not None:
                        checkpoint(model)
                        if stream_checkpoint is not None:
                            # per-pass fold states are now redundant
                            stream_checkpoint.manifest.drop_streams(stage.uid)
                            stream_checkpoint.manifest.save()
                else:
                    raise StreamingNotSupportedError(
                        f"stage {type(stage).__name__} ({stage.uid}) does "
                        f"not implement fit_streaming(run) — it cannot fit "
                        f"on a chunk stream. Streaming-capable stages: "
                        f"RealVectorizer, SanityChecker, StreamingGBT "
                        f"(docs/streaming.md)")
                fitted[stage.uid] = model
                models.append(model)
            elif isinstance(stage, Transformer):
                models.append(stage)
            else:
                raise TypeError(
                    f"unexpected stage kind {type(stage).__name__}")
        upstream.extend(models)
    return fitted, upstream, stats
