"""Streaming DAG trainer: layer-wise estimator fits as chunk folds.

The streaming dual of ``dag.fit_and_transform_dag``: estimators fit
layer-by-layer, but instead of one in-memory table each fit makes one or
more *passes* over the :class:`~.source.ChunkSource` through the
double-buffered :class:`~.feed.DeviceFeed`, with every chunk transformed
through the already-fitted upstream stages inside the producer thread (so
transform + upload overlap the fold compute). An estimator opts in by
implementing::

    def fit_streaming(self, run: StreamRun) -> Transformer

and drives its passes through ``run.fold(pass_id, fold, extract)`` — which
is where chunk checkpointing (streaming/checkpoint.py), the ``stream.fold``
chaos site, observability spans, and the O(chunk) memory bound all live.
Estimators without the hook fail the train with a descriptive error
(docs/streaming.md "What can stream") — a streamed fit must never silently
materialize the dataset.

Two round-20 input-engine hooks ride on the same contract:

* every ``StreamRun`` carries the pass-aware transformed-chunk cache
  handle (streaming/cache.py) plus its fitted-upstream identity digest,
  so repeat passes of the SAME stage (the GBT's ``1 + trees×(depth+1)``
  passes) replay cached prep instead of redoing read+transform+upload;
* estimators whose whole streaming fit is ONE fold pass may additionally
  expose ``fit_streaming_prep(run) -> (pass_id, fold, extract, finish)``
  (or ``None`` when no pass is needed); when a DAG layer holds two or
  more such stages with no data dependency between them, the trainer
  FUSES their passes into a single chunk sweep via the existing
  ``CompositeFold`` — one read of the stream fits them all. Fused fold
  states checkpoint under the joined uid, so kill/resume stays
  bit-exact; ``TG_STREAM_FUSE=0`` disables fusion for A/B.
"""
from __future__ import annotations

import logging
import os

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..observability import blackbox as _blackbox
from ..observability import ledger as _obs_ledger
from ..observability.trace import span as _obs_span
from ..robustness import faults, resources
from ..robustness.policy import FaultLog, FaultReport
from ..stages.base import Estimator, Transformer
from ..table import FeatureTable
from .cache import ChunkCache, transform_identity
from .checkpoint import PASS_COMPLETE, StreamCheckpoint
from .feed import DeviceFeed, FeedStats
from .folds import MonoidFold
from .source import ChunkSource

FUSE_ENV = "TG_STREAM_FUSE"


def env_fuse() -> bool:
    return os.environ.get(FUSE_ENV, "1").lower() not in ("0", "false", "no")

logger = logging.getLogger(__name__)


class StreamingNotSupportedError(TypeError):
    """A DAG stage cannot fit as a streaming fold. Names the stage and the
    hook it would need — the streamed train fails up front instead of
    materializing the dataset behind the caller's back."""


class StreamRun:
    """One estimator's view of the stream: fold passes + a schema probe."""

    def __init__(self, source: ChunkSource, upstream: List[Transformer],
                 stage_uid: str, checkpoint: Optional[StreamCheckpoint] = None,
                 prefetch: Optional[int] = None,
                 stats: Optional[FeedStats] = None,
                 cache: Optional[ChunkCache] = None,
                 workers: Optional[int] = None):
        self.source = source
        self.upstream = list(upstream)
        self.stage_uid = stage_uid
        self.checkpoint = checkpoint
        self.prefetch = prefetch
        self.stats = stats if stats is not None else FeedStats()
        self.cache = cache
        self.workers = workers
        self._probe: Optional[FeatureTable] = None
        self._cache_ident: Optional[str] = None

    @property
    def cache_ident(self) -> str:
        """Fitted-transform identity of this run's upstream stack — the
        third axis of the transformed-chunk cache key (a chunk prepped
        under different upstream models must never be replayed here)."""
        if self._cache_ident is None:
            self._cache_ident = transform_identity(self.upstream)
        return self._cache_ident

    @property
    def num_chunks(self) -> int:
        return self.source.num_chunks

    @property
    def chunk_rows(self) -> int:
        return self.source.chunk_rows

    def probe_table(self, rows: int = 256) -> FeatureTable:
        """A small transformed head-of-stream table for schema/metadata
        (vector widths, vector_meta groups) — never the data itself."""
        if self._probe is None:
            chunk = next(iter(self.source.chunks(0)))
            table = chunk.table
            if table.num_rows > rows:
                table = table.take(np.arange(rows))
            for model in self.upstream:
                table = model.transform(table)
            self._probe = table
        return self._probe

    def fold(self, pass_id: str, fold: MonoidFold,
             extract: Callable[[FeatureTable], Tuple]) -> Any:
        """Run one full pass: ``state = fold(extract(chunk) for chunks)``.

        Restores a committed state for this (stage, pass) and continues
        from the next un-folded chunk; commits after every
        TG_STREAM_CKPT_EVERY chunks and marks the pass complete at the
        end — so a resumed train re-executes no completed pass and no
        committed chunk, bit-exactly.

        Resource exhaustion (a chunk the device cannot hold — forwarded
        from the feed producer, or raised by the fold itself) downshifts
        instead of dying: the chunk row budget HALVES and the pass
        continues from the committed-row prefix — the already-folded rows
        align exactly with the new chunk grid (old budget = 2 × new), so
        no row refolds and no row is skipped. Commits after a downshift
        carry the re-chunked source's fingerprint + ``chunkRows``, and
        restore recognizes them (``with_chunk_rows``), so a kill mid-
        downshifted-pass resumes against the identical schedule,
        bit-exactly. The downshift is pass-local: later passes start back
        at the configured budget. Floor: ``TG_OOM_MIN_CHUNK_ROWS``
        (docs/robustness.md "Resource exhaustion & watchdog")."""
        key = f"{self.stage_uid}/{pass_id}"
        src = self.source
        state, start = None, 0
        if self.checkpoint is not None:
            src, state, start = self._restore(key, pass_id, fold, src)
            if start == PASS_COMPLETE:
                return state
        if state is None:
            state, start = fold.zero(), 0
        every = self.checkpoint.every if self.checkpoint is not None else 0
        while True:
            folded = start
            # flight-recorder: pass boundaries carry the run's ambient
            # correlation id (workflow.train), so a post-mortem slice for
            # one run shows which pass/chunk it died in
            _blackbox.record("stream.pass", uid=self.stage_uid,
                             passId=pass_id, fromChunk=start,
                             chunkRows=src.chunk_rows)
            # compile ledger: each fold pass is one streaming program
            # over the chunk grid — first attempt is cold; an OOM
            # downshift re-enters at a halved row budget and the ledger
            # classifies the rebuild as bucket-change (the stream analog
            # of a padding-bucket crossing; docs/observability.md)
            _obs_ledger.record_build(
                "stream", identity=f"stream/{key}",
                key=f"{key}@{src.chunk_rows}",
                bucket=src.chunk_rows, fromChunk=start,
                chunks=src.num_chunks)
            try:
                with _obs_span("stream.pass", cat="train",
                               uid=self.stage_uid, passId=pass_id,
                               fromChunk=start,
                               chunkRows=src.chunk_rows), \
                        DeviceFeed(src, start=start,
                                   transforms=self.upstream,
                                   prefetch=self.prefetch,
                                   workers=self.workers,
                                   cache=self.cache,
                                   cache_ident=self.cache_ident) as feed:
                    try:
                        for chunk in feed:
                            faults.inject("stream.fold", key=pass_id)
                            state = fold.accumulate(state,
                                                    *extract(chunk.table))
                            folded = chunk.index + 1
                            if (self.checkpoint is not None
                                    and folded < src.num_chunks
                                    and (folded - start) % every == 0):
                                self.checkpoint.commit(
                                    key, fold.state_to_arrays(state),
                                    folded,
                                    fingerprint=src.fingerprint(),
                                    chunk_rows=src.chunk_rows)
                    finally:
                        self.stats.merge(feed.stats)
                break
            except Exception as e:
                src, start = self._downshift(e, src, folded, key, fold,
                                             state)
        if self.checkpoint is not None:
            self.checkpoint.commit(key, fold.state_to_arrays(state),
                                   PASS_COMPLETE,
                                   fingerprint=src.fingerprint(),
                                   chunk_rows=src.chunk_rows)
        _blackbox.record("stream.pass_done", uid=self.stage_uid,
                         passId=pass_id, chunks=folded)
        return state

    def _restore(self, key: str, pass_id: str, fold: MonoidFold, src):
        """Committed-row-prefix-aware restore: a record committed by a
        downshifted run carries its ``chunkRows``; when re-chunking the
        run's source at that budget reproduces the record's fingerprint,
        the pass resumes on the downshifted grid — the committed rows are
        a prefix of both schedules."""
        rec = self.checkpoint.manifest.streams.get(key)
        if rec is not None and rec.get("fingerprint") != src.fingerprint():
            cr = rec.get("chunkRows")
            if cr and int(cr) != src.chunk_rows:
                try:
                    cand = src.with_chunk_rows(int(cr))
                except NotImplementedError:
                    cand = None
                if (cand is not None
                        and cand.fingerprint() == rec.get("fingerprint")):
                    src = cand
        arrays, start = self.checkpoint.restore(
            key, fingerprint=src.fingerprint())
        if arrays is None:
            return src, None, 0
        state = fold.state_from_arrays(arrays)
        detail = {"key": key, "pass": pass_id}
        if start != PASS_COMPLETE:
            detail["fromChunk"] = start
        if src is not self.source:
            detail["chunkRows"] = src.chunk_rows  # downshifted record
        FaultLog.record(FaultReport(site="stream.fold", kind="restored",
                                    detail=detail))
        return src, state, start

    def _downshift(self, exc: Exception, src, folded: int, key: str,
                   fold: MonoidFold, state):
        """Halve the chunk row budget after resource exhaustion, or
        re-raise anything that is not exhaustion / cannot halve. Returns
        ``(re-chunked source, next chunk index on the new grid)`` —
        ``folded`` full chunks at the old budget are exactly ``2·folded``
        chunks at the new one, so the committed-row prefix is preserved
        row-for-row."""
        if resources.classify_exhaustion(exc) is None:
            raise exc
        new_rows = src.chunk_rows // 2
        if src.chunk_rows % 2 or new_rows < resources.min_chunk_rows():
            raise exc  # at (or below) the floor: exhaustion is fatal
        try:
            new_src = src.with_chunk_rows(new_rows)
        except NotImplementedError:
            raise exc  # source cannot re-chunk deterministically
        start = folded * 2
        resources.record_downshift(
            "oom.stream", stage=self.stage_uid,
            chunkRows=new_rows, fromChunk=start,
            error=f"{type(exc).__name__}: {exc}"[:200])
        logger.warning(
            "stream pass for %s exhausted memory at chunk_rows=%d; "
            "halving to %d and resuming at chunk %d",
            self.stage_uid, src.chunk_rows, new_rows, start)
        if self.checkpoint is not None:
            # commit the prefix under the NEW chunking so a kill right
            # after the downshift resumes on the same grid
            self.checkpoint.commit(key, fold.state_to_arrays(state), start,
                                   fingerprint=new_src.fingerprint(),
                                   chunk_rows=new_rows)
        return new_src, start


def _fit_layer_fused(candidates, source, upstream, *, stream_checkpoint,
                     prefetch, workers, cache, stats, retry_policy,
                     layer_index) -> Dict[str, Transformer]:
    """Fuse the independent one-pass prep fits of one DAG layer into a
    single chunk sweep (they share the same upstream, so they have no
    data dependency on each other). Each stage's fold becomes one arm of
    a ``CompositeFold`` keyed by its uid; the fused state checkpoints
    under the joined uid, so a mid-pass kill resumes the joint fold
    bit-exactly. Returns ``{uid → fitted model}`` for the stages whose
    prep participated (a stage whose ``fit_streaming_prep`` returns
    ``None`` needs no pass and falls back to its solo fit)."""
    from .folds import CompositeFold
    runs = {s.uid: StreamRun(source, upstream, s.uid, checkpoint=None,
                             prefetch=prefetch, stats=stats,
                             cache=cache, workers=workers)
            for s in candidates}
    specs = {}
    for s in candidates:
        spec = s.fit_streaming_prep(runs[s.uid])
        if spec is not None:
            specs[s.uid] = spec
    if len(specs) < 2:
        return {}
    stages = [s for s in candidates if s.uid in specs]
    fused_uid = "+".join(s.uid for s in stages)
    pass_id = "+".join(specs[s.uid][0] for s in stages)

    def _fit() -> Dict[str, Transformer]:
        for s in stages:
            faults.inject("preempt.stage_fit", key=s.uid)
        composite = CompositeFold({uid: spec[1]
                                   for uid, spec in specs.items()})
        extractors = {uid: spec[2] for uid, spec in specs.items()}

        def extract_all(table: FeatureTable) -> Tuple:
            return ({uid: ex(table) for uid, ex in extractors.items()},)

        fused_run = StreamRun(source, upstream, fused_uid,
                              checkpoint=stream_checkpoint,
                              prefetch=prefetch, stats=stats,
                              cache=cache, workers=workers)
        with _obs_span("stream.fit_fused", cat="train", uid=fused_uid,
                       layer=layer_index, fusedPasses=len(stages),
                       chunks=source.num_chunks):
            state = fused_run.fold(pass_id, composite, extract_all)
        return {uid: specs[uid][3](state[uid]) for uid in specs}

    if retry_policy is not None:
        return retry_policy.execute(
            _fit, site=f"stream.stage_fit[{fused_uid}]")
    return _fit()


def fit_dag_streaming(source: ChunkSource, layers, *,
                      checkpoint: Optional[Callable] = None,
                      stream_checkpoint: Optional[StreamCheckpoint] = None,
                      preloaded: Optional[Dict[str, Any]] = None,
                      retry_policy: Optional[Any] = None,
                      prefetch: Optional[int] = None,
                      cache: Optional[ChunkCache] = None,
                      workers: Optional[int] = None,
                      ) -> Tuple[Dict[str, Any], List[Transformer], FeedStats]:
    """Fit every estimator in the layered DAG as streaming folds.

    Returns ``(fitted {uid → model}, topological transformer order,
    aggregate feed stats)``. Mirrors ``dag.fit_and_transform_dag``'s
    checkpoint/preload/retry contract (docs/robustness.md) — ``preloaded``
    stages restore instead of refitting, ``checkpoint(model)`` commits each
    fitted stage, transient errors retry under ``retry_policy``. ``cache``
    is the run-wide transformed-chunk cache handle (shared across every
    pass and stage so repeat sweeps replay prepped chunks); ``workers``
    sizes the input-engine producer pool (None → TG_STREAM_WORKERS).
    """
    pre = preloaded or {}
    fitted: Dict[str, Any] = {}
    upstream: List[Transformer] = []
    stats = FeedStats()
    for li, layer in enumerate(layers):
        models: List[Transformer] = []
        fused: Dict[str, Transformer] = {}
        fusable = [stage for stage, _ in layer
                   if isinstance(stage, Estimator) and stage.uid not in pre
                   and hasattr(stage, "fit_streaming_prep")]
        if env_fuse() and len(fusable) >= 2:
            fused = _fit_layer_fused(
                fusable, source, upstream,
                stream_checkpoint=stream_checkpoint, prefetch=prefetch,
                workers=workers, cache=cache, stats=stats,
                retry_policy=retry_policy, layer_index=li)
        for stage, _ in layer:
            if isinstance(stage, Estimator):
                if stage.uid in pre:
                    model = pre[stage.uid]
                    model.input_features = stage.input_features
                    model._output_feature = stage.get_output()
                    FaultLog.record(FaultReport(
                        site="dag.stage_fit", kind="restored",
                        detail={"uid": stage.uid,
                                "stage": type(stage).__name__}))
                elif stage.uid in fused:
                    model = fused[stage.uid]
                    if checkpoint is not None:
                        checkpoint(model)
                elif hasattr(stage, "fit_streaming"):
                    def _fit(stage=stage, li=li):
                        faults.inject("preempt.stage_fit", key=stage.uid)
                        run = StreamRun(source, upstream, stage.uid,
                                        checkpoint=stream_checkpoint,
                                        prefetch=prefetch, stats=stats,
                                        cache=cache, workers=workers)
                        with _obs_span("stream.fit", cat="train",
                                       uid=stage.uid,
                                       stage=type(stage).__name__,
                                       layer=li,
                                       chunks=source.num_chunks):
                            return stage.fit_streaming(run)
                    if retry_policy is not None:
                        model = retry_policy.execute(
                            _fit, site=f"stream.stage_fit[{stage.uid}]")
                    else:
                        model = _fit()
                    if checkpoint is not None:
                        checkpoint(model)
                        if stream_checkpoint is not None:
                            # per-pass fold states are now redundant
                            stream_checkpoint.manifest.drop_streams(stage.uid)
                            stream_checkpoint.manifest.save()
                else:
                    raise StreamingNotSupportedError(
                        f"stage {type(stage).__name__} ({stage.uid}) does "
                        f"not implement fit_streaming(run) — it cannot fit "
                        f"on a chunk stream. Streaming-capable stages: "
                        f"RealVectorizer, SanityChecker, StreamingGBT "
                        f"(docs/streaming.md)")
                fitted[stage.uid] = model
                models.append(model)
            elif isinstance(stage, Transformer):
                models.append(stage)
            else:
                raise TypeError(
                    f"unexpected stage kind {type(stage).__name__}")
        if (fused and checkpoint is not None
                and stream_checkpoint is not None):
            # every fused stage's full checkpoint committed above — the
            # joint fold state under the joined uid is now redundant
            stream_checkpoint.manifest.drop_streams("+".join(fused))
            stream_checkpoint.manifest.save()
        upstream.extend(models)
    return fitted, upstream, stats
