"""Seeded random feature-data generators.

Mirrors the reference testkit (reference:
testkit/src/main/scala/com/salesforce/op/testkit/ — RandomData.scala:43-75,
RandomReal.scala:45-110, RandomText.scala, RandomMap.scala, RandomList.scala,
RandomVector.scala, RandomIntegral.scala, RandomBinary.scala): infinite,
deterministic streams of typed feature values with configurable
``probability_of_empty`` null injection — the data source for stage contract
tests and synthetic benchmark tables.
"""
from __future__ import annotations

import string
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np


class RandomData:
    """Infinite seeded stream (reference RandomData trait). Iterate or
    ``take(n)``; ``with_probability_of_empty(p)`` injects Nones."""

    def __init__(self, seed: int = 42):
        self._seed = int(seed)
        self._rng = np.random.RandomState(seed)
        self.probability_of_empty = 0.0

    def with_probability_of_empty(self, p: float) -> "RandomData":
        self.probability_of_empty = float(p)
        return self

    def reset(self, seed: int) -> "RandomData":
        self._seed = int(seed)
        self._rng = np.random.RandomState(seed)
        return self

    def _one(self) -> Any:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self.probability_of_empty and self._rng.rand() < self.probability_of_empty:
            return None
        return self._one()

    def take(self, n: int) -> List[Any]:
        return [next(self) for _ in range(n)]

    # fluent alias matching the reference's `limit`
    limit = take


class RandomReal(RandomData):
    """reference RandomReal: uniform/normal/poisson/exponential/gamma/
    lognormal distributions."""

    def __init__(self, dist: str = "normal", seed: int = 42, **kw):
        super().__init__(seed)
        self.dist = dist
        self.kw = kw

    @staticmethod
    def uniform(lo: float = 0.0, hi: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal("uniform", seed, low=lo, high=hi)

    @staticmethod
    def normal(mean: float = 0.0, sigma: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal("normal", seed, loc=mean, scale=sigma)

    @staticmethod
    def poisson(lam: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal("poisson", seed, lam=lam)

    @staticmethod
    def exponential(scale: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal("exponential", seed, scale=scale)

    @staticmethod
    def gamma(shape: float = 2.0, scale: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal("gamma", seed, shape=shape, scale=scale)

    @staticmethod
    def lognormal(mean: float = 0.0, sigma: float = 1.0, seed: int = 42) -> "RandomReal":
        return RandomReal("lognormal", seed, mean=mean, sigma=sigma)

    def _one(self) -> float:
        return float(getattr(self._rng, self.dist)(**self.kw))


class RandomIntegral(RandomData):
    """reference RandomIntegral: uniform ints or poisson counts; also epoch
    dates via ``dates``."""

    def __init__(self, lo: int = 0, hi: int = 100, seed: int = 42):
        super().__init__(seed)
        self.lo, self.hi = lo, hi

    @staticmethod
    def integers(lo: int = 0, hi: int = 100, seed: int = 42) -> "RandomIntegral":
        return RandomIntegral(lo, hi, seed)

    @staticmethod
    def dates(start_ms: int = 1_500_000_000_000, span_days: int = 365,
              seed: int = 42) -> "RandomIntegral":
        return RandomIntegral(start_ms, start_ms + span_days * 86_400_000, seed)

    def _one(self) -> int:
        return int(self._rng.randint(self.lo, self.hi))


class RandomBinary(RandomData):
    """reference RandomBinary(probabilityOfSuccess)."""

    def __init__(self, probability_of_true: float = 0.5, seed: int = 42):
        super().__init__(seed)
        self.p = probability_of_true

    def _one(self) -> bool:
        return bool(self._rng.rand() < self.p)


_FIRST_NAMES = ("james mary robert patricia john jennifer michael linda david "
                "elizabeth william barbara richard susan joseph jessica thomas "
                "sarah charles karen").split()
_LAST_NAMES = ("smith johnson williams brown jones garcia miller davis "
               "rodriguez martinez hernandez lopez gonzalez wilson anderson "
               "thomas taylor moore jackson martin").split()
_COUNTRIES = ("United States,Canada,Mexico,Brazil,France,Germany,Spain,Italy,"
              "Japan,China,India,Australia,Kenya,Egypt,Norway").split(",")
_DOMAINS = "example.com test.org mail.net company.io sample.co".split()
_WORDS = ("alpha beta gamma delta epsilon omega sigma lambda theta kappa "
          "zeta quick brown fox lazy dog lorem ipsum dolor amet").split()


class RandomText(RandomData):
    """reference RandomText: strings/names/emails/urls/countries/phones/
    picklists/ids/base64."""

    def __init__(self, kind: str = "strings", seed: int = 42,
                 domain: Optional[Sequence[str]] = None, words: int = 5):
        super().__init__(seed)
        self.kind = kind
        self.domain = list(domain) if domain is not None else None
        self.words = words

    @staticmethod
    def strings(words: int = 5, seed: int = 42) -> "RandomText":
        return RandomText("strings", seed, words=words)

    @staticmethod
    def names(seed: int = 42) -> "RandomText":
        return RandomText("names", seed)

    @staticmethod
    def emails(domain: str = "example.com", seed: int = 42) -> "RandomText":
        return RandomText("emails", seed, domain=[domain])

    @staticmethod
    def urls(seed: int = 42) -> "RandomText":
        return RandomText("urls", seed)

    @staticmethod
    def countries(seed: int = 42) -> "RandomText":
        return RandomText("countries", seed)

    @staticmethod
    def phones(seed: int = 42) -> "RandomText":
        return RandomText("phones", seed)

    @staticmethod
    def pick_lists(domain: Sequence[str], seed: int = 42) -> "RandomText":
        return RandomText("picklists", seed, domain=domain)

    @staticmethod
    def ids(seed: int = 42) -> "RandomText":
        return RandomText("ids", seed)

    @staticmethod
    def base64(seed: int = 42) -> "RandomText":
        return RandomText("base64", seed)

    def _one(self) -> str:
        r = self._rng
        if self.kind == "strings":
            k = r.randint(1, self.words + 1)
            return " ".join(r.choice(_WORDS) for _ in range(k))
        if self.kind == "names":
            return f"{r.choice(_FIRST_NAMES).title()} {r.choice(_LAST_NAMES).title()}"
        if self.kind == "emails":
            dom = r.choice(self.domain) if self.domain else r.choice(_DOMAINS)
            return f"{r.choice(_FIRST_NAMES)}.{r.choice(_LAST_NAMES)}@{dom}"
        if self.kind == "urls":
            return f"https://{r.choice(_DOMAINS)}/{r.choice(_WORDS)}"
        if self.kind == "countries":
            return str(r.choice(_COUNTRIES))
        if self.kind == "phones":
            return "+1" + "".join(str(r.randint(0, 10)) for _ in range(10))
        if self.kind == "picklists":
            return str(r.choice(self.domain))
        if self.kind == "ids":
            alphabet = np.array(list(string.ascii_uppercase + string.digits))
            return "".join(r.choice(alphabet) for _ in range(12))
        if self.kind == "base64":
            import base64
            return base64.b64encode(r.bytes(24)).decode()
        raise ValueError(self.kind)


class RandomList(RandomData):
    """reference RandomList: lists drawn from an element generator."""

    def __init__(self, element: RandomData, min_len: int = 0, max_len: int = 5,
                 seed: int = 42):
        super().__init__(seed)
        self.element = element
        self.min_len, self.max_len = min_len, max_len

    def _one(self) -> List[Any]:
        k = int(self._rng.randint(self.min_len, self.max_len + 1))
        return [v for v in self.element.take(k) if v is not None]


class RandomMultiPickList(RandomList):
    def __init__(self, domain: Sequence[str], min_len: int = 0,
                 max_len: int = 3, seed: int = 42):
        super().__init__(RandomText.pick_lists(domain, seed=seed + 1),
                         min_len, max_len, seed)

    def _one(self) -> List[str]:
        return sorted(set(super()._one()))


class RandomMap(RandomData):
    """reference RandomMap: maps of an element generator under generated keys."""

    def __init__(self, element: RandomData, keys: Sequence[str],
                 min_keys: int = 1, seed: int = 42):
        super().__init__(seed)
        self.element = element
        self.keys = list(keys)
        self.min_keys = min_keys

    def _one(self) -> Dict[str, Any]:
        k = int(self._rng.randint(self.min_keys, len(self.keys) + 1))
        chosen = list(self._rng.choice(self.keys, size=k, replace=False))
        out = {}
        for key in chosen:
            v = next(self.element)
            if v is not None:
                out[key] = v
        return out


class RandomVector(RandomData):
    """reference RandomVector: dense vectors from a real generator."""

    def __init__(self, dim: int, element: Optional[RandomReal] = None,
                 seed: int = 42):
        super().__init__(seed)
        self.dim = dim
        self.element = element or RandomReal.normal(seed=seed + 1)

    def _one(self) -> List[float]:
        return [v if v is not None else 0.0 for v in self.element.take(self.dim)]


class InfiniteStream:
    """Infinite transformed stream (reference InfiniteStream.scala:63):
    wrap an index function (``of``) or any iterator, then ``map``/``take``.

    Streams built with ``of`` are PURE VALUES like the reference's: ``map``
    returns an independent stream and the source keeps its own position.
    Streams wrapping a raw one-shot iterator cannot be re-created, so there
    ``map`` consumes the source (documented deviation)."""

    def __init__(self, it: Optional[Iterator[Any]] = None,
                 factory: Optional[Callable[[], Iterator[Any]]] = None):
        self._factory = factory
        self._it = it if it is not None else factory()

    @staticmethod
    def of(fn: Callable[[int], Any]) -> "InfiniteStream":
        import itertools
        return InfiniteStream(
            factory=lambda: (fn(i) for i in itertools.count()))

    def map(self, fn: Callable[[Any], Any]) -> "InfiniteStream":
        if self._factory is not None:  # pure value: fresh source each time
            fac = self._factory
            return InfiniteStream(factory=lambda: (fn(v) for v in fac()))
        return InfiniteStream(fn(v) for v in self._it)

    def __iter__(self) -> Iterator[Any]:
        return self._it

    def __next__(self) -> Any:
        return next(self._it)

    def take(self, n: int) -> List[Any]:
        return [next(self._it) for _ in range(n)]

    limit = take


class RandomStream(RandomData):
    """Seeded stream from an arbitrary draw function (reference
    RandomStream.scala:303 — the building block behind every Random* type):
    ``RandomStream(lambda rng: ...)``. Composes via ``map`` / ``zip``."""

    def __init__(self, draw: Callable[[np.random.RandomState], Any],
                 seed: int = 42):
        super().__init__(seed)
        self._draw = draw

    @staticmethod
    def of(draw: Callable[[np.random.RandomState], Any],
           seed: int = 42) -> "RandomStream":
        return RandomStream(draw, seed)

    @staticmethod
    def random_between(lo: float, hi: float, seed: int = 42) -> "RandomStream":
        return RandomStream(lambda r: float(r.uniform(lo, hi)), seed)

    @staticmethod
    def random_longs(lo: int, hi: int, seed: int = 42) -> "RandomStream":
        return RandomStream(lambda r: int(r.randint(lo, hi)), seed)

    def map(self, fn: Callable[[Any], Any]) -> "RandomStream":
        # child seed derives from the parent SEED, never from the parent's
        # live RNG — deriving a stream must not perturb the parent's
        # deterministic sequence
        draw = self._draw
        return RandomStream(lambda r: fn(draw(r)),
                            (self._seed * 1000003 + 1) % (2**31))

    def zip(self, other: "RandomData") -> "RandomStream":
        draw = self._draw
        return RandomStream(lambda r: (draw(r), next(other)),
                            (self._seed * 1000003 + 2) % (2**31))

    def _one(self) -> Any:
        return self._draw(self._rng)


_STREETS = ("Main St,Oak Ave,Maple Dr,Cedar Ln,Pine Rd,Elm St,2nd Ave,"
            "Park Blvd,Lake View Dr,Hill Crest Rd").split(",")
_CITIES = ("Springfield,Riverton,Fairview,Georgetown,Arlington,Ashland,"
           "Dover,Clinton,Salem,Madison").split(",")
_STATES = "CA NY TX WA OR IL MA CO GA FL".split()


class RandomGeolocation(RandomData):
    """reference RandomList.ofGeolocations: (lat, lon, accuracy) triples."""

    def __init__(self, seed: int = 42):
        super().__init__(seed)

    def _one(self) -> List[float]:
        r = self._rng
        return [float(r.uniform(-90, 90)), float(r.uniform(-180, 180)),
                float(r.randint(1, 11))]


class RandomCurrency(RandomReal):
    """reference RandomReal.currency-style positive amounts (2 decimals)."""

    def __init__(self, lo: float = 0.0, hi: float = 1000.0, seed: int = 42):
        super().__init__("uniform", seed, low=lo, high=hi)

    def _one(self) -> float:
        return round(super()._one(), 2)


class RandomDateList(RandomList):
    """reference RandomList.ofDates: sorted epoch-millis event lists."""

    def __init__(self, start_ms: int = 1_500_000_000_000,
                 span_days: int = 365, min_len: int = 0, max_len: int = 5,
                 seed: int = 42):
        super().__init__(RandomIntegral.dates(start_ms, span_days,
                                              seed=seed + 1),
                         min_len, max_len, seed)

    def _one(self) -> List[int]:
        return sorted(super()._one())


# ---------------------------------------------------------------------------
# Default generator per feature type — the testkit can produce EVERY type
# ---------------------------------------------------------------------------

def generator_of(feature_type: Any, seed: int = 42) -> RandomData:
    """A sensible default generator for any of the 52 feature types
    (reference testkit package object defaults). Text-ish types draw from
    their domain tables; maps wrap the scalar generator under keys k0..k3."""
    from ..types import FEATURE_TYPES
    name = (feature_type if isinstance(feature_type, str)
            else feature_type.__name__)
    if name not in FEATURE_TYPES:
        raise ValueError(f"unknown feature type {name!r}")
    if name.endswith("Map") and name not in ("PickListMap",):
        inner = generator_of(name[:-3], seed + 1)
        return RandomMap(inner, keys=["k0", "k1", "k2", "k3"], seed=seed)

    scalar: Dict[str, Callable[[], RandomData]] = {
        "Real": lambda: RandomReal.normal(seed=seed),
        "RealNN": lambda: RandomReal.normal(seed=seed),
        "Currency": lambda: RandomCurrency(seed=seed),
        "Percent": lambda: RandomReal.uniform(0.0, 1.0, seed=seed),
        "Integral": lambda: RandomIntegral.integers(seed=seed),
        "Date": lambda: RandomIntegral.dates(seed=seed),
        "DateTime": lambda: RandomIntegral.dates(seed=seed),
        "Binary": lambda: RandomBinary(seed=seed),
        "Text": lambda: RandomText.strings(seed=seed),
        "TextArea": lambda: RandomText.strings(words=30, seed=seed),
        "Email": lambda: RandomText.emails(seed=seed),
        "URL": lambda: RandomText.urls(seed=seed),
        "Phone": lambda: RandomText.phones(seed=seed),
        "ID": lambda: RandomText.ids(seed=seed),
        "Base64": lambda: RandomText.base64(seed=seed),
        "PickList": lambda: RandomText.pick_lists(
            ["red", "green", "blue", "yellow"], seed=seed),
        "PickListMap": lambda: RandomMap(
            RandomText.pick_lists(["red", "green", "blue"], seed=seed + 1),
            keys=["k0", "k1", "k2", "k3"], seed=seed),
        "ComboBox": lambda: RandomText.pick_lists(
            ["small", "medium", "large"], seed=seed),
        "Country": lambda: RandomText.countries(seed=seed),
        "State": lambda: RandomStream(lambda r: str(r.choice(_STATES)), seed),
        "City": lambda: RandomStream(lambda r: str(r.choice(_CITIES)), seed),
        "Street": lambda: RandomStream(
            lambda r: f"{r.randint(1, 9999)} {r.choice(_STREETS)}", seed),
        "PostalCode": lambda: RandomStream(
            lambda r: f"{r.randint(10000, 99999)}", seed),
        "TextList": lambda: RandomList(RandomText.strings(words=1,
                                                          seed=seed + 1),
                                       1, 5, seed),
        "DateList": lambda: RandomDateList(seed=seed),
        "DateTimeList": lambda: RandomDateList(seed=seed),
        "MultiPickList": lambda: RandomMultiPickList(
            ["a", "b", "c", "d"], seed=seed),
        "Geolocation": lambda: RandomGeolocation(seed=seed),
        "OPVector": lambda: RandomVector(8, seed=seed),
        "Prediction": lambda: RandomStream(
            lambda r: {"prediction": float(r.randint(0, 2))}, seed),
    }
    if name in scalar:
        return scalar[name]()
    raise ValueError(f"no default generator for feature type {name!r}")


# ---------------------------------------------------------------------------
# Benchmark-scale table builder
# ---------------------------------------------------------------------------

def random_table(spec: Dict[str, Any], n: int, seed: int = 42):
    """Build a FeatureTable from {column: FeatureType | (FeatureType, gen)}.

    Numeric scalar types draw VECTORIZED (one numpy call for all n rows), so
    benchmark-scale tables (millions of rows) build in milliseconds; host
    types fall back to the per-row generator streams."""
    from ..table import Column, FeatureTable
    from ..types import FEATURE_TYPES
    rng = np.random.RandomState(seed)
    cols: Dict[str, Any] = {}
    for i, (name, entry) in enumerate(spec.items()):
        if isinstance(entry, tuple):
            ftype, gen = entry
        else:
            ftype, gen = entry, None
        if isinstance(ftype, str):
            ftype = FEATURE_TYPES[ftype]
        kind = ftype.column_kind
        if gen is None and kind in ("real", "binary", "integral", "date"):
            # vectorized fast path end to end: build the Column directly
            # from the numpy draw (of_values' per-element loops would undo
            # the vectorization at benchmark scale)
            if kind == "real":
                vals = rng.randn(n).astype(np.float32)
            elif kind == "binary":
                vals = (rng.rand(n) < 0.5).astype(np.float32)
            elif kind == "date":
                vals = rng.randint(1_500_000_000_000,
                                   1_530_000_000_000, size=n,
                                   dtype=np.int64)
            else:
                vals = rng.randint(0, 100, size=n).astype(np.int64)
            cols[name] = Column(ftype, vals, None)
        elif gen is None and kind == "vector":
            cols[name] = Column(ftype, rng.randn(n, 8).astype(np.float32),
                                None)
        else:
            g = gen or generator_of(ftype, seed=seed + i)
            cols[name] = Column.of_values(ftype, g.take(n))
    return FeatureTable(cols, n)
