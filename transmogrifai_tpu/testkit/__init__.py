from .random_data import (  # noqa: F401
    RandomBinary, RandomData, RandomIntegral, RandomList, RandomMap,
    RandomMultiPickList, RandomReal, RandomText, RandomVector,
)
