from .random_data import (  # noqa: F401
    InfiniteStream, RandomBinary, RandomCurrency, RandomData, RandomDateList,
    RandomGeolocation, RandomIntegral, RandomList, RandomMap,
    RandomMultiPickList, RandomReal, RandomStream, RandomText, RandomVector,
    generator_of, random_table,
)
