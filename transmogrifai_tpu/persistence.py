"""Workflow-model persistence: plan JSON + array store.

The reference saves a fitted workflow as one JSON document (stages serialized
by ctor-arg reflection, features as a JSON graph) plus Spark-native stage dirs
(reference: core/src/main/scala/com/salesforce/op/OpWorkflowModelWriter.scala:52-180,
OpWorkflowModelReader.scala, stages/OpPipelineStageWriter.scala,
features/FeatureJsonHelper.scala). The TPU build keeps that shape but swaps the
substrate: a ``plan.json`` carries the feature graph + per-stage state
descriptors, and an ``arrays.npz`` carries every fitted device array (model
coefficients, vocabularies' hash tables, scaler stats) as host numpy.

Stage state is encoded generically from ``__dict__``: arrays → npz entries,
JSON-able scalars inline, nested objects (summaries, vector metadata,
FittedParams pytrees) → recursive ``__obj__`` descriptors rebuilt via
``cls.__new__``. Stages are resolved by class name through ``STAGE_REGISTRY``
— the analog of the reference's reflection loader. Callables serialize by
module/qualname when importable; otherwise loading requires the original
workflow (``load_model(path, workflow=...)``), exactly the reference's
"resolve against original workflow" path (OpWorkflowModelReader.scala).
"""
from __future__ import annotations

import dataclasses
import importlib
import io
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .features import Feature
from .manifest import (
    MANIFEST_FILE, SENTINEL_FILE, CheckpointManifest, atomic_write_bytes,
    clean_tmp_debris,
)
from .stages.base import STAGE_REGISTRY, FeatureGeneratorStage, OpPipelineStage
from .types import feature_type_by_name

PLAN_FILE = "plan.json"
ARRAYS_FILE = "arrays.npz"
FORMAT_VERSION = 1


class CorruptModelError(RuntimeError):
    """A saved model/checkpoint file failed integrity verification or could
    not be decoded. Carries the failing file and the reason, so "the model
    dir was truncated by a preempted copy" reads as exactly that instead of
    a raw npz/json decode traceback."""

    def __init__(self, path: str, reason: str):
        self.path = path
        self.reason = reason
        super().__init__(f"corrupt model artifact {path!r}: {reason}")


def _npz_bytes(store: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez_compressed(buf, **store)
    return buf.getvalue()

#: stage attributes that carry DAG wiring, rebuilt from the feature graph
#: attributes that are workflow wiring / runtime placement, not model state:
#: re-established by the loading context, never serialized ("mesh" holds a
#: jax.sharding.Mesh of live Device objects — unpicklable and meaningless in
#: another process)
_WIRING_ATTRS = ("input_features", "_output_feature", "mesh")


class _Arrays:
    """Accumulates arrays for the npz store, keyed by stage uid + path."""

    def __init__(self):
        self.store: Dict[str, np.ndarray] = {}
        self._n = 0

    def add(self, arr: np.ndarray) -> str:
        key = f"a{self._n}"
        self._n += 1
        self.store[key] = np.asarray(arr)
        return key


def _is_jsonable_scalar(v: Any) -> bool:
    return v is None or isinstance(v, (bool, int, float, str))


def _encode(v: Any, arrays: _Arrays) -> Any:
    """Value → JSON-able descriptor, externalizing arrays."""
    if _is_jsonable_scalar(v):
        if isinstance(v, float) and not np.isfinite(v):
            return {"__float__": repr(v)}
        return v
    if isinstance(v, (np.floating, np.integer, np.bool_)):
        return _encode(v.item(), arrays)
    if isinstance(v, np.ndarray):
        return {"__array__": arrays.add(v)}
    # jax arrays
    tname = type(v).__module__
    if tname.startswith("jax") or type(v).__name__ == "ArrayImpl":
        return {"__array__": arrays.add(np.asarray(v))}
    if isinstance(v, tuple):
        return {"__tuple__": [_encode(x, arrays) for x in v]}
    if isinstance(v, list):
        return [_encode(x, arrays) for x in v]
    if isinstance(v, (set, frozenset)):
        return {"__set__": [_encode(x, arrays) for x in sorted(v, key=repr)]}
    if isinstance(v, dict):
        if all(isinstance(k, str) for k in v):
            return {"__dict__": {k: _encode(x, arrays) for k, x in v.items()}}
        return {"__kvdict__": [[_encode(k, arrays), _encode(x, arrays)]
                               for k, x in v.items()]}
    if isinstance(v, type):
        from .types import FeatureType
        if issubclass(v, FeatureType):
            return {"__feature_type__": v.__name__}
        return {"__class__": f"{v.__module__}:{v.__qualname__}"}
    # model families live in the registry — persist by name
    from .models.api import ModelFamily
    if isinstance(v, ModelFamily):
        return {"__family__": v.name}
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {"__obj__": _clsname(v),
                "state": {f.name: _encode(getattr(v, f.name), arrays)
                          for f in dataclasses.fields(v)}}
    import types as _t
    if isinstance(v, (_t.FunctionType, _t.MethodType, _t.BuiltinFunctionType)):
        qn = getattr(v, "__qualname__", "")
        if "<locals>" in qn or "<lambda>" in qn or isinstance(v, _t.MethodType):
            return {"__unresolved__": repr(v)}  # resolve from original workflow
        return {"__fn__": f"{v.__module__}:{qn}"}
    # a stage held BY another stage (RecordInsightsLOCO.model_stage) is a
    # reference into the workflow, not owned state: encode by uid —
    # load_model re-links it against the plan's own stages (the stage graph
    # is cyclic — features point back at their origin stages — so recursing
    # would never end)
    if isinstance(v, OpPipelineStage):
        return {"__stage_ref__": v.uid}
    if hasattr(v, "__dict__"):  # plain objects + callable objects (FieldExtractor)
        return {"__obj__": _clsname(v),
                "state": {k: _encode(x, arrays) for k, x in vars(v).items()}}
    return {"__unresolved__": repr(v)}


def _clsname(v: Any) -> str:
    cls = type(v)
    return f"{cls.__module__}:{cls.__qualname__}"


def _resolve_class(spec: str) -> type:
    mod, qual = spec.split(":")
    obj: Any = importlib.import_module(mod)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


class _StageRef:
    """Placeholder for a stage-valued attribute; load_model re-links it to
    the loaded stage of the same uid."""

    def __init__(self, uid: str):
        self.uid = uid

    def __repr__(self):
        return f"_StageRef({self.uid!r})"


class Unresolved:
    """Placeholder for state that could not be serialized; must be resolved
    from the original workflow at load time."""

    def __init__(self, desc: str):
        self.desc = desc

    def __repr__(self):
        return f"Unresolved({self.desc!r})"


def _decode(d: Any, arrays: Dict[str, np.ndarray]) -> Any:
    if _is_jsonable_scalar(d):
        return d
    if isinstance(d, list):
        return [_decode(x, arrays) for x in d]
    assert isinstance(d, dict), d
    if "__float__" in d:
        return float(d["__float__"])
    if "__array__" in d:
        return arrays[d["__array__"]]
    if "__tuple__" in d:
        return tuple(_decode(x, arrays) for x in d["__tuple__"])
    if "__set__" in d:
        return set(_decode(x, arrays) for x in d["__set__"])
    if "__dict__" in d:
        return {k: _decode(x, arrays) for k, x in d["__dict__"].items()}
    if "__kvdict__" in d:
        return {_decode(k, arrays): _decode(x, arrays) for k, x in d["__kvdict__"]}
    if "__feature_type__" in d:
        return feature_type_by_name(d["__feature_type__"])
    if "__class__" in d:
        return _resolve_class(d["__class__"])
    if "__family__" in d:
        from .models.api import MODEL_REGISTRY
        return MODEL_REGISTRY[d["__family__"]]
    if "__fn__" in d:
        return _resolve_class(d["__fn__"])
    if "__obj__" in d:
        cls = _resolve_class(d["__obj__"])
        obj = cls.__new__(cls)
        for k, v in d["state"].items():
            # frozen dataclasses (VectorMetadata, Column specs) refuse
            # setattr — restore their fields the way dataclass internals do
            object.__setattr__(obj, k, _decode(v, arrays))
        return obj
    if "__stage_ref__" in d:
        return _StageRef(d["__stage_ref__"])
    if "__unresolved__" in d:
        return Unresolved(d["__unresolved__"])
    raise ValueError(f"cannot decode {d!r}")


# ---------------------------------------------------------------------------
# Stage (de)serialization
# ---------------------------------------------------------------------------

def stage_to_json(stage: OpPipelineStage, arrays: _Arrays) -> Dict[str, Any]:
    state = {k: v for k, v in vars(stage).items() if k not in _WIRING_ATTRS}
    return {
        "className": type(stage).__name__,
        "module": type(stage).__module__,
        "uid": stage.uid,
        "state": {k: _encode(v, arrays) for k, v in state.items()},
    }


def stage_from_json(d: Dict[str, Any], arrays: Dict[str, np.ndarray]) -> OpPipelineStage:
    cls = STAGE_REGISTRY.get(d["className"])
    if cls is None and d.get("module"):
        # fresh process: the defining module may not be imported yet — stage
        # classes self-register on import (__init_subclass__)
        try:
            importlib.import_module(d["module"])
        except ImportError:
            pass
        cls = STAGE_REGISTRY.get(d["className"])
    if cls is None:
        raise ValueError(
            f"unknown stage class {d['className']!r}; import the module defining "
            f"it before loading (stage registry has {len(STAGE_REGISTRY)} classes)")
    stage = cls.__new__(cls)
    stage.input_features = ()
    stage._output_feature = None
    for k, v in d["state"].items():
        setattr(stage, k, _decode(v, arrays))
    return stage


# ---------------------------------------------------------------------------
# Feature graph (reference FeatureJsonHelper.scala)
# ---------------------------------------------------------------------------

def features_to_json(result_features, extra_features=()) -> List[Dict[str, Any]]:
    seen: Dict[str, Feature] = {}
    order: List[Feature] = []
    for f in result_features:
        for a in f.all_features():
            if a.uid not in seen:
                seen[a.uid] = a
                order.append(a)
    # raw/blacklisted features outside the (post-surgery) result ancestry —
    # they must still round-trip (model.raw_features keeps pre-RFF features)
    for f in extra_features:
        for a in f.all_features():
            if a.uid not in seen:
                seen[a.uid] = a
                order.append(a)
    return [{
        "uid": f.uid,
        "name": f.name,
        "typeName": f.type_name,
        "isResponse": f.is_response,
        "originStageUid": f.origin_stage.uid if f.origin_stage else None,
        "parents": [p.uid for p in f.parents],
    } for f in order]


def features_from_json(descs: List[Dict[str, Any]],
                       stages: Dict[str, OpPipelineStage]) -> Dict[str, Feature]:
    feats: Dict[str, Feature] = {}
    for d in descs:  # descs are in dependency order (post-order per result)
        parents = [feats[p] for p in d["parents"]]
        stage = stages.get(d["originStageUid"])
        f = Feature(d["name"], feature_type_by_name(d["typeName"]),
                    d["isResponse"], stage, parents, uid=d["uid"])
        feats[d["uid"]] = f
        if stage is not None:
            stage.input_features = tuple(parents)
            stage._output_feature = f
    return feats


# ---------------------------------------------------------------------------
# Model save / load
# ---------------------------------------------------------------------------

def save_model(model, path: str) -> None:
    """Write the fitted workflow model to ``path`` (a directory):
    plan.json + arrays.npz + MANIFEST.json with per-file sha256 checksums
    (reference OpWorkflowModelWriter.scala:52-80). Every file is written
    atomically (tmp + fsync + rename), so a kill mid-save leaves either the
    previous complete model or ``*.tmp`` debris — never a torn file that
    :func:`load_model` would decode garbage from."""
    from .utils.version import version_info
    os.makedirs(path, exist_ok=True)
    arrays = _Arrays()
    stage_descs = [stage_to_json(s, arrays) for s in model.stages]
    extra_by_uid = {f.uid: f for f in
                    tuple(model.raw_features) + tuple(model.blacklisted_features)}
    extra = tuple(extra_by_uid.values())
    raw_stage_descs = [stage_to_json(f.origin_stage, arrays) for f in extra]
    plan = {
        "formatVersion": FORMAT_VERSION,
        "versionInfo": version_info(),
        "features": features_to_json(model.result_features, extra),
        "resultFeatures": [f.uid for f in model.result_features],
        "rawFeatures": [f.uid for f in model.raw_features],
        "blacklistedFeatures": [f.uid for f in model.blacklisted_features],
        "stages": stage_descs,
        "rawFeatureGenerators": raw_stage_descs,
        "parameters": _encode(model.parameters, arrays),
        "rffResults": _encode(getattr(model, "rff_results", None), arrays),
    }
    # fail at save, not load: a __stage_ref__ pointing outside the saved
    # plan can never be re-linked and would only surface later through the
    # unresolved-state path with a vaguer error
    saved_uids = ({s.uid for s in model.stages}
                  | {f.origin_stage.uid for f in extra})
    dangling = sorted(_collect_stage_ref_uids(
        [stage_descs, raw_stage_descs,
         plan["parameters"], plan["rffResults"]]) - saved_uids)
    if dangling:
        import warnings
        warnings.warn(
            f"save_model: stage attribute(s) reference uid(s) {dangling} "
            f"that are not among the stages being saved — they will load "
            f"as permanent placeholders. Include those stages in the "
            f"workflow or drop the references before saving.",
            stacklevel=2)
    plan_bytes = json.dumps(plan, indent=2).encode("utf-8")
    npz_bytes = _npz_bytes(arrays.store)
    plan_sha = atomic_write_bytes(os.path.join(path, PLAN_FILE), plan_bytes)
    npz_sha = atomic_write_bytes(os.path.join(path, ARRAYS_FILE), npz_bytes)
    manifest = CheckpointManifest(path, FORMAT_VERSION)
    manifest.record_file(PLAN_FILE, plan_sha, len(plan_bytes))
    manifest.record_file(ARRAYS_FILE, npz_sha, len(npz_bytes))
    # warm-start hint: the serve-path plan schema fingerprint, pre-traced
    # by the serving registry at load so a fresh process serves its first
    # request without retracing (serving/warmup.py; docs/serving.md). A
    # model whose raw extracts cannot take the synthetic probe simply
    # ships no hint — the hint must never fail a save.
    try:
        from .serving.warmup import manifest_serving_entry
        manifest.serving = manifest_serving_entry(model)
    except Exception:
        pass
    # drift baseline: per-feature training-distribution sketches + fill
    # rates (serving/drift.py) — the serving registry hands them to a
    # DriftMonitor at load so scoring traffic is compared online against
    # what the model trained on. Same contract as the warm-start hint: a
    # model without a usable train table simply ships no baseline — the
    # entry must never fail a save.
    try:
        from .serving.drift import manifest_drift_entry
        manifest.drift = manifest_drift_entry(model)
    except Exception:
        pass
    # dispatch cost table: the training process's measured (segment
    # fingerprint × padding bucket) → {bytes, compileSeconds,
    # executeSeconds} rows (observability/devicemem.py) — what pre-flight
    # admission control and the AOT store read at load. Advisory like the
    # two entries above: never fails a save.
    try:
        from .observability import devicemem as _devicemem
        costs = _devicemem.costs_manifest_entry()
        if costs.get("table"):
            manifest.costs = costs
    except Exception:
        pass
    manifest.save()
    # AOT program store: drive the serve scorer once under a capture
    # scope so the model ships with its serialized compiled programs
    # (programstore/ — entries land in the manifest `programs` section,
    # blobs under `programs/`), and a fresh process's registry.load
    # deserializes instead of tracing. Same contract as the three
    # advisory entries above: population must never fail a save
    # (TG_AOT_SAVE=0 defers it to the first warm load).
    try:
        from .programstore import populate_for_save
        populate_for_save(model, path)
    except Exception:
        pass


def _collect_stage_ref_uids(v: Any) -> set:
    """All __stage_ref__ uids inside an encoded (JSON-ready) plan fragment."""
    out: set = set()
    if isinstance(v, dict):
        uid = v.get("__stage_ref__")
        if isinstance(uid, str):
            out.add(uid)
        for x in v.values():
            out |= _collect_stage_ref_uids(x)
    elif isinstance(v, list):
        for x in v:
            out |= _collect_stage_ref_uids(x)
    return out


def _has_unresolved(v: Any, depth: int = 0) -> bool:
    if isinstance(v, (Unresolved, _StageRef)):
        return True
    if depth > 8:
        return False
    if isinstance(v, (list, tuple, set)):
        return any(_has_unresolved(x, depth + 1) for x in v)
    if isinstance(v, dict):
        return any(_has_unresolved(x, depth + 1) for x in v.values())
    if hasattr(v, "__dict__") and not isinstance(v, type):
        return any(_has_unresolved(x, depth + 1) for x in vars(v).values())
    return False


def _relink_stage_refs(v: Any, stages: Dict[str, OpPipelineStage],
                       depth: int = 0) -> Any:
    """Replace _StageRef placeholders anywhere inside ``v`` (nested lists/
    dicts/objects, mirroring what _encode recursed into) with the loaded
    stage of the same uid; unknown uids stay _StageRef and are counted
    unresolved by _has_unresolved."""
    if isinstance(v, _StageRef):
        return stages.get(v.uid, v)
    if depth > 8:
        return v
    if isinstance(v, list):
        return [_relink_stage_refs(x, stages, depth + 1) for x in v]
    if isinstance(v, tuple):
        return tuple(_relink_stage_refs(x, stages, depth + 1) for x in v)
    if isinstance(v, dict):
        return {k: _relink_stage_refs(x, stages, depth + 1)
                for k, x in v.items()}
    if (hasattr(v, "__dict__") and not isinstance(v, type)
            and not isinstance(v, OpPipelineStage)):
        for k, x in list(vars(v).items()):
            nx = _relink_stage_refs(x, stages, depth + 1)
            if nx is not x:
                object.__setattr__(v, k, nx)
    return v


def _collect_unresolved(stage: OpPipelineStage) -> List[str]:
    """Attributes with an Unresolved placeholder anywhere inside (nested
    lambdas in lists/dicts/objects included) — the whole attribute is patched
    from the original workflow's stage."""
    return [k for k, v in vars(stage).items() if _has_unresolved(v)]


def load_model(path: str, workflow=None):
    """Load a fitted model saved by :func:`save_model`.

    If ``workflow`` (the original OpWorkflow) is given, stages with
    unserializable state (user lambdas) are patched from the workflow's stage
    of the same uid — the reference's OpWorkflowModelReader "resolve against
    workflow" path.

    Integrity: when the directory carries a MANIFEST.json (every model saved
    by the current :func:`save_model` does), each file's size + sha256 is
    verified before decoding; a mismatch raises :class:`CorruptModelError`
    naming the failing file. Decode failures (truncated legacy files) are
    wrapped in the same error instead of surfacing a raw traceback."""
    from .workflow import OpWorkflowModel

    plan_path = os.path.join(path, PLAN_FILE)
    npz_path = os.path.join(path, ARRAYS_FILE)
    manifest, merr = CheckpointManifest.load(path, FORMAT_VERSION)
    if merr not in (None, "missing"):  # pre-manifest dirs load unverified
        raise CorruptModelError(manifest.path, merr)
    if merr is None and os.path.isdir(path) and manifest.files:
        for fname in (PLAN_FILE, ARRAYS_FILE):
            reason = manifest.verify_file(fname)
            if reason is not None:
                raise CorruptModelError(os.path.join(path, fname), reason)
    try:
        with open(plan_path) as fh:
            plan = json.load(fh)
    except ValueError as e:
        raise CorruptModelError(plan_path,
                                f"undecodable JSON: {e}") from e
    if plan.get("formatVersion") != FORMAT_VERSION:
        raise ValueError(f"unsupported model format {plan.get('formatVersion')}")
    try:
        with np.load(npz_path, allow_pickle=False) as npz:
            arrays = {k: npz[k] for k in npz.files}
    except (ValueError, OSError, KeyError) as e:
        if not os.path.isfile(npz_path):
            raise
        raise CorruptModelError(npz_path,
                                f"undecodable npz: {type(e).__name__}: {e}"
                                ) from e

    stages: Dict[str, OpPipelineStage] = {}
    for d in plan["stages"] + plan["rawFeatureGenerators"]:
        if d["uid"] not in stages:
            stages[d["uid"]] = stage_from_json(d, arrays)

    # re-link stage-valued attributes (top-level OR nested in containers)
    # to the loaded stages of the same uid (e.g. RecordInsightsLOCO
    # .model_stage -> the loaded SelectedModel); refs to stages outside the
    # plan stay placeholders and fall through to the workflow-patch path
    for stage in stages.values():
        for k, v in list(vars(stage).items()):
            nv = _relink_stage_refs(v, stages)
            if nv is not v:
                setattr(stage, k, nv)

    # patch unresolved state from the original workflow (by stage uid)
    wf_stages: Dict[str, OpPipelineStage] = {}
    if workflow is not None:
        for s in workflow.stages:
            wf_stages[s.uid] = s
        for f in workflow.raw_features:
            wf_stages[f.origin_stage.uid] = f.origin_stage
    for uid, stage in stages.items():
        missing = _collect_unresolved(stage)
        if not missing:
            continue
        src = wf_stages.get(uid)
        if src is None:
            raise ValueError(
                f"stage {uid} has unserializable state {missing}; pass the "
                f"original workflow to load_model to resolve it")
        for k in missing:
            setattr(stage, k, getattr(src, k))

    feats = features_from_json(plan["features"], stages)
    model = OpWorkflowModel()
    model.result_features = tuple(feats[u] for u in plan["resultFeatures"])
    model.raw_features = tuple(feats[u] for u in plan["rawFeatures"])
    model.blacklisted_features = tuple(
        feats[u] for u in plan.get("blacklistedFeatures", []))
    model.parameters = _decode(plan.get("parameters", {}), arrays) or {}
    model.rff_results = _decode(plan.get("rffResults"), arrays)
    from .dag import compute_dag
    model._layers = compute_dag(model.result_features)
    return model


# ---------------------------------------------------------------------------
# Per-stage training checkpoints (the TPU build's resilience analog of the
# reference's persist-every-K-stages, OpWorkflowModel.scala:449-455 /
# FitStagesUtil.scala:125-131: deterministic re-execution from saved fitted
# stage state instead of Spark lineage recomputation)
# ---------------------------------------------------------------------------

def open_checkpoint_manifest(ckpt_dir: str) -> CheckpointManifest:
    """The directory's manifest, or a fresh one when absent/unreadable
    (an unreadable manifest means nothing in the dir is trustworthy — it is
    reported at load time; the new manifest recommits from scratch)."""
    manifest, _err = CheckpointManifest.load(ckpt_dir, FORMAT_VERSION)
    return manifest


def save_stage_checkpoint(stage: OpPipelineStage, ckpt_dir: str,
                          manifest: Optional[CheckpointManifest] = None,
                          ) -> None:
    """Persist one fitted stage as <uid>.json + <uid>.npz, atomically, and
    commit it to the directory manifest.

    Write protocol (kill-safe at every step): each payload file goes
    through tmp + fsync + rename; the stage only becomes *loadable* when
    the manifest — rewritten atomically last — records its completion and
    checksums. A preemption anywhere mid-protocol leaves files the loader
    classifies as debris (reported, refit) rather than state it trusts."""
    from .manifest import sentinel_phase
    from .robustness import faults
    os.makedirs(ckpt_dir, exist_ok=True)
    # crash evidence: a kill in here died writing a checkpoint, not inside
    # a device dispatch (run sentinel, docs/robustness.md)
    sentinel_phase("checkpoint_write")
    if manifest is None:
        manifest = open_checkpoint_manifest(ckpt_dir)
    arrays = _Arrays()
    desc = stage_to_json(stage, arrays)
    npz_name, json_name = f"{stage.uid}.npz", f"{stage.uid}.json"
    npz_bytes = _npz_bytes(arrays.store)
    npz_sha = atomic_write_bytes(os.path.join(ckpt_dir, npz_name), npz_bytes)
    # deterministic kill point BETWEEN the payload files and the manifest
    # commit: the .npz exists but nothing records it — resume must treat it
    # as debris, not as a checkpoint
    faults.inject("preempt.checkpoint_write", key=stage.uid)
    json_bytes = json.dumps(desc).encode("utf-8")
    json_sha = atomic_write_bytes(os.path.join(ckpt_dir, json_name),
                                  json_bytes)
    manifest.record_file(npz_name, npz_sha, len(npz_bytes))
    manifest.record_file(json_name, json_sha, len(json_bytes))
    manifest.complete_stage(stage.uid, [json_name, npz_name])
    manifest.save()        # the commit point


def _report_skipped(uid: str, ckpt_dir: str, file: str, reason: str) -> None:
    import logging
    from .robustness.policy import FaultLog, FaultReport
    logging.getLogger(__name__).warning(
        "skipping stage checkpoint %s in %s (%s: %s); the stage will refit",
        uid, ckpt_dir, file, reason)
    FaultLog.record(FaultReport(
        site="persistence.checkpoint", kind="checkpoint_skipped",
        detail={"uid": uid, "dir": ckpt_dir, "file": file,
                "reason": reason, "error": reason}))


def load_stage_checkpoints(ckpt_dir: str,
                           manifest: Optional[CheckpointManifest] = None,
                           ) -> Dict[str, OpPipelineStage]:
    """Load every *verified* stage checkpoint in ``ckpt_dir``, keyed by uid.

    With a manifest present, only stages with a completion record load, and
    each file's size + sha256 must match the manifest — corruption
    (truncated file, bit flip, kill between a stage's two files) is
    *detected* and reported as a ``checkpoint_skipped`` FaultReport carrying
    the file path and the verification failure; the stage refits from data.
    Payload files with no completion record (debris of an interrupted
    write) are reported the same way. Pre-manifest directories fall back to
    decode-or-skip with the same reporting."""
    import logging

    logger = logging.getLogger(__name__)
    out: Dict[str, OpPipelineStage] = {}
    if not os.path.isdir(ckpt_dir):
        return out
    removed = clean_tmp_debris(ckpt_dir)
    if removed:
        logger.info("removed %d partial-write tmp file(s) from %s",
                    len(removed), ckpt_dir)
    if manifest is None:
        manifest, merr = CheckpointManifest.load(ckpt_dir, FORMAT_VERSION)
        if merr not in (None, "missing"):
            _report_skipped("*", ckpt_dir, manifest.path,
                            f"manifest unusable ({merr}); no checkpoint in "
                            f"the directory can be verified")
            return out
        if merr == "missing" and any(
                f.endswith(".json")
                and f not in (MANIFEST_FILE, SENTINEL_FILE)
                for f in os.listdir(ckpt_dir)):
            return _load_legacy_checkpoints(ckpt_dir)
    for fname in manifest.unrecorded_files():
        uid = fname.rsplit(".", 1)[0]
        _report_skipped(uid, ckpt_dir, os.path.join(ckpt_dir, fname),
                        "file has no manifest completion record "
                        "(interrupted write)")
    for uid, rec in sorted(manifest.stages.items()):
        fnames = rec.get("files", [])
        bad = [(f, manifest.verify_file(f)) for f in fnames]
        bad = [(f, r) for f, r in bad if r is not None]
        if bad:
            f0, r0 = bad[0]
            _report_skipped(uid, ckpt_dir, os.path.join(ckpt_dir, f0), r0)
            continue
        try:
            with open(os.path.join(ckpt_dir, f"{uid}.json")) as fh:
                desc = json.load(fh)
            with np.load(os.path.join(ckpt_dir, f"{uid}.npz"),
                         allow_pickle=False) as npz:
                arrays = dict(npz)
            out[uid] = stage_from_json(desc, arrays)
        except Exception as e:
            # checksums matched but decode failed: a format bug or a stage
            # class that moved — still refit rather than crash the resume
            _report_skipped(uid, ckpt_dir, os.path.join(ckpt_dir,
                                                        f"{uid}.json"),
                            f"verified but undecodable: "
                            f"{type(e).__name__}: {e}")
    return out


def _load_legacy_checkpoints(ckpt_dir: str) -> Dict[str, OpPipelineStage]:
    """Pre-manifest directories: best-effort decode-or-skip (the PR-1
    behavior), with skips reported through the same FaultLog path."""
    out: Dict[str, OpPipelineStage] = {}
    for fname in sorted(os.listdir(ckpt_dir)):
        if (not fname.endswith(".json") or fname.startswith("sweep_")
                or fname in (MANIFEST_FILE, SENTINEL_FILE)):
            continue
        uid = fname[:-5]
        try:
            with open(os.path.join(ckpt_dir, fname)) as fh:
                desc = json.load(fh)
            with np.load(os.path.join(ckpt_dir, f"{uid}.npz"),
                         allow_pickle=False) as npz:
                arrays = dict(npz)
            out[uid] = stage_from_json(desc, arrays)
        except Exception as e:
            _report_skipped(uid, ckpt_dir, os.path.join(ckpt_dir, fname),
                            f"{type(e).__name__}: {e} (unverified legacy "
                            f"checkpoint — no manifest)")
    return out
