"""Per-column provenance of feature vectors.

Mirrors the reference's OpVectorMetadata / OpVectorColumnMetadata
(reference: utils/src/main/scala/com/salesforce/op/utils/spark/OpVectorMetadata.scala,
OpVectorColumnMetadata.scala): every slot of an ``OPVector`` column records which
raw feature produced it, its type, an optional grouping (e.g. the pivot group or
map key), an optional indicator value (the one-hot category), and whether it is
a null-tracking indicator. SanityChecker uses this to propagate removals across
a feature's indicator group; ModelInsights uses it to attribute contributions
back to raw features.
"""
from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Any, Dict, List, Optional, Sequence

NULL_INDICATOR = "NullIndicatorValue"
OTHER_INDICATOR = "OTHER"


@dataclass(frozen=True)
class VectorColumnMetadata:
    """Provenance of a single vector slot (reference OpVectorColumnMetadata.scala)."""
    parent_feature_name: str
    parent_feature_type: str
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    descriptor_value: Optional[str] = None
    index: int = 0

    @property
    def is_null_indicator(self) -> bool:
        return self.indicator_value == NULL_INDICATOR

    @property
    def is_other_indicator(self) -> bool:
        return self.indicator_value == OTHER_INDICATOR

    def column_name(self) -> str:
        parts = [self.parent_feature_name]
        if self.grouping and self.grouping != self.parent_feature_name:
            parts.append(self.grouping)
        if self.indicator_value is not None:
            parts.append(self.indicator_value)
        elif self.descriptor_value is not None:
            parts.append(self.descriptor_value)
        return "_".join(parts) + f"_{self.index}"

    def feature_group(self) -> str:
        """Key used to group sibling indicator columns of one raw feature/map-key
        (reference OpVectorColumnMetadata.featureGroup)."""
        return f"{self.parent_feature_name}::{self.grouping or ''}"

    def to_json(self) -> Dict[str, Any]:
        return asdict(self)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "VectorColumnMetadata":
        return VectorColumnMetadata(**d)


@dataclass(frozen=True)
class VectorMetadata:
    """Provenance of a whole vector column (reference OpVectorMetadata.scala)."""
    name: str
    columns: tuple  # Tuple[VectorColumnMetadata, ...] with indices 0..n-1

    @property
    def size(self) -> int:
        return len(self.columns)

    def column_names(self) -> List[str]:
        return [c.column_name() for c in self.columns]

    def index_of_group(self) -> Dict[str, List[int]]:
        groups: Dict[str, List[int]] = {}
        for c in self.columns:
            groups.setdefault(c.feature_group(), []).append(c.index)
        return groups

    def select(self, indices: Sequence[int]) -> "VectorMetadata":
        return VectorMetadata.of(self.name, [self.columns[i] for i in indices])

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "columns": [c.to_json() for c in self.columns]}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "VectorMetadata":
        return VectorMetadata(
            d["name"], tuple(VectorColumnMetadata.from_json(c) for c in d["columns"]))

    @staticmethod
    def of(name: str, cols: Sequence[VectorColumnMetadata]) -> "VectorMetadata":
        from dataclasses import replace
        return VectorMetadata(
            name, tuple(replace(c, index=i) for i, c in enumerate(cols)))

    @staticmethod
    def flatten(name: str, metas: Sequence["VectorMetadata"]) -> "VectorMetadata":
        cols: List[VectorColumnMetadata] = []
        for m in metas:
            cols.extend(m.columns)
        return VectorMetadata.of(name, cols)


@dataclass(frozen=True)
class VectorColumnHistory:
    """Full provenance of one vector slot: the column's immediate parent
    feature plus the RAW features and STAGE chain that produced that parent
    (reference features/.../spark/OpVectorColumnHistory.scala:56 +
    OpVectorMetadata.getColumnHistory :120)."""
    column_name: str
    parent_feature_name: str
    parent_feature_origins: List[str]
    parent_feature_stages: List[str]
    parent_feature_type: str
    grouping: Optional[str]
    indicator_value: Optional[str]
    descriptor_value: Optional[str]
    index: int

    def to_json(self) -> Dict[str, Any]:
        return asdict(self)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "VectorColumnHistory":
        return VectorColumnHistory(**d)


def column_history(vm: VectorMetadata,
                   parent_features: Sequence[Any]) -> List[VectorColumnHistory]:
    """Per-column stage-history provenance: join each column's parent
    feature name against the feature DAG — origin raw features from the
    lineage walk, stage chain from parent_stages ordered by distance
    (reference OpVectorMetadata.getColumnHistory :120)."""
    by_name = {f.name: f for f in parent_features}
    out: List[VectorColumnHistory] = []
    for c in vm.columns:
        f = by_name.get(c.parent_feature_name)
        if f is not None:
            origins = sorted({r.name for r in f.raw_features()})
            stages = [s.operation_name for s, _dist in
                      sorted(f.parent_stages().items(),
                             key=lambda t: -t[1])]
        else:
            origins, stages = [c.parent_feature_name], []
        out.append(VectorColumnHistory(
            column_name=c.column_name(),
            parent_feature_name=c.parent_feature_name,
            parent_feature_origins=origins,
            parent_feature_stages=stages,
            parent_feature_type=c.parent_feature_type,
            grouping=c.grouping,
            indicator_value=c.indicator_value,
            descriptor_value=c.descriptor_value,
            index=c.index))
    return out
